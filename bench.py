"""Benchmark harness — run on real TPU hardware by the driver.

Measures the headline metric from BASELINE.json — cell-updates/sec
(turns x H x W / s) on the reference's 512x512 board — plus the other
single-chip BASELINE configs:

  config 2: 128x128  — pallas VMEM bitboard kernel
  config 3: 512x512  — pallas VMEM bitboard kernel (HEADLINE) + the
            engine-driven number (Engine.run with the packed BitPlane,
            pipelined chunk dispatches — what a real session achieves)
  config 4: 4096x4096 — grid-tiled pallas bitboard (the packed board
            exceeds the whole-board VMEM gate, ops/pallas_stencil.fits_vmem,
            so BitPlane routes to ops/pallas_tiled.py)
  config 5: BOTH the 16384^2 waypoint AND the true BASELINE scale,
            65536^2 sparse R-pentomino — the board exists only as a
            packed bitboard on device (512 MiB at 65536^2), evolved by
            the grid-tiled pallas kernel; timed calls sync via a
            device-side popcount, never a state transfer
  config 8: sessions — 1k x 128^2 concurrent universes in one
            device-resident batch (engine/sessions.py over the batched
            kernel family) vs 1k sequential runs; gates bit-identical
            per-universe parity and >= 10x sessions/sec
  config 12: fused vs serial launch chains — the 128^2 floor case stepped
            one-launch-per-turn vs K=8 turns per launch (ops/fused.py);
            gates bit-identical boards, >= 5x per-turn on TPU, and the
            roofline flip off launch-bound; embeds dispatches_per_turn
            (deterministic — bench_diff gates it with no noise band)

Parity gates: exact alive counts against check/alive/512x512.csv at turns
1000 and 10000 plus the period-2 steady state; 128^2 against a numpy
oracle at 1000 turns; 4096^2 bitboard against the independent roll-stencil
implementation at 100 turns (on-device array equality); 16384^2 and
65536^2 R-pentomino against the oracle-validated 1000-turn population
(156, verified on a 1536^2 window with envelope check —
tests/test_bigboard.py).

Methodology: the remote-TPU tunnel adds a fixed ~0.1 s dispatch overhead
per call with occasional ~50 ms spikes, so throughput is the MARGINAL
cost between an n_lo- and an n_hi-turn run (overhead cancels). Each
endpoint is min over REPS=5 timed runs; a fit whose marginal work does
not dominate the min-estimator's spread by NOISE_MARGIN, or is
non-positive, raises instead of publishing (the round-2 c5 entry was a
negative throughput born of exactly that).

Prints exactly ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "extra": {...}}

Baseline: the reference publishes no numbers (BASELINE.md). We use an
explicit, documented estimate for its 8-worker distributed deployment:
50 turns/s on 512x512 — generous for a path that gob-serialises the full
board to every worker every turn (broker/broker.go:135-224) — giving
50 * 512 * 512 = 13.1e6 cell-updates/s.
"""

import json
import statistics
import sys
import time

from gol_distributed_final_tpu.obs import tracing as _tracing

BASELINE_CELL_UPDATES_PER_SEC = 50 * 512 * 512  # documented estimate, see above

GOLDEN_512 = {1000: 6444, 10000: 5565}  # check/alive/512x512.csv
STEADY_512 = {0: 5565, 1: 5567}  # period-2 steady state beyond turn 10000
REPS = 5
NOISE_MARGIN = 5  # marginal work must exceed endpoint spread by this factor


def oracle_step_n(board, n):
    """Independent numpy reference (tests/oracle.py's vector_step, inlined
    so bench.py stays standalone)."""
    import numpy as np

    b = (board != 0).astype(np.int32)
    for _ in range(n):
        counts = sum(
            np.roll(np.roll(b, dy, 0), dx, 1)
            for dy in (-1, 0, 1)
            for dx in (-1, 0, 1)
            if (dy, dx) != (0, 0)
        )
        b = ((counts == 3) | ((b == 1) & (counts == 2))).astype(np.int32)
    return (b * 255).astype(np.uint8)


class InvalidMeasurement(RuntimeError):
    """A fit that must not be published (non-positive or noise-dominated)."""


def provenance() -> dict:
    """Environment stamp for the JSON line: jax version, device fleet, and
    git SHA. ``scripts/bench_diff`` (obs/regress.py) refuses to compare
    rounds whose jax version or device kind/count differ — a number from
    a different chip is not a regression. Each field degrades to None
    rather than failing the bench that exists to publish numbers."""
    out = {"jax_version": None, "platform": None, "device_kind": None,
           "device_count": None, "git_sha": None}
    try:
        import jax

        out["jax_version"] = jax.__version__
        devs = jax.devices()
        out["platform"] = devs[0].platform if devs else None
        out["device_kind"] = getattr(devs[0], "device_kind", None) if devs else None
        out["device_count"] = len(devs)
    except Exception:
        pass
    try:
        import subprocess

        out["git_sha"] = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=10,
        ).stdout.strip() or None
    except Exception:
        pass
    return out


def marginal(time_fn, n_lo, n_hi, label="?"):
    """Per-run-unit marginal cost between n_lo and n_hi, with variance.

    Returns (per_turn_seconds, details): endpoints are min over REPS; the
    details dict records min/median/spread per endpoint and the fixed
    overhead implied by the linear fit.

    Raises InvalidMeasurement — the round-2 c5 entry published a NEGATIVE
    throughput because the 1000-turn marginal work (~3 ms) was buried
    under ~2 s of per-call transfer overhead with +-1 s spread — if the
    fit is non-positive, or if the marginal work does not dominate the
    endpoint noise by at least NOISE_MARGIN. Callers must widen the
    endpoints (or cut per-call transfers) rather than publish garbage."""

    def sample(n):
        times = []
        for _ in range(REPS):
            t0 = time.perf_counter()
            time_fn(n)
            times.append(time.perf_counter() - t0)
        return times

    lo, hi = sample(n_lo), sample(n_hi)
    per_turn = (min(hi) - min(lo)) / (n_hi - n_lo)
    # stability of the min-estimator itself: the gap between the two best
    # runs per endpoint (medians inflate under the tunnel's occasional
    # one-sided latency spikes, which min() is already robust to)
    spread = max(
        sorted(lo)[1] - min(lo), sorted(hi)[1] - min(hi)
    )
    details = {
        "n_lo": n_lo,
        "n_hi": n_hi,
        "reps": REPS,
        "t_lo_min_s": round(min(lo), 4),
        "t_lo_median_s": round(statistics.median(lo), 4),
        "t_hi_min_s": round(min(hi), 4),
        "t_hi_median_s": round(statistics.median(hi), 4),
        "fixed_overhead_s": round(min(lo) - n_lo * per_turn, 4),
        "per_turn_us": round(per_turn * 1e6, 5),
        "per_turn_us_median_fit": round(
            (statistics.median(hi) - statistics.median(lo)) / (n_hi - n_lo) * 1e6,
            5,
        ),
        # the gate's inputs, so borderline fits are auditable after the fact
        "spread_s": round(spread, 4),
        "noise_margin": round((min(hi) - min(lo)) / spread, 1)
        if spread > 0
        else None,
    }
    marginal_work = min(hi) - min(lo)
    if per_turn <= 0:
        raise InvalidMeasurement(
            f"{label}: non-positive fit {per_turn * 1e6:.2f} us/turn — {details}"
        )
    if marginal_work < NOISE_MARGIN * spread:
        raise InvalidMeasurement(
            f"{label}: marginal work {marginal_work:.4f}s does not dominate "
            f"endpoint spread {spread:.4f}s (need {NOISE_MARGIN}x) — {details}"
        )
    return per_turn, details


def gated(time_fn, n_lo, n_hi, label, attempts=3):
    """``marginal`` with a bounded retry: the tunnel's occasional one-sided
    latency spikes can push a single sampling below the noise margin
    (observed once in three r5 full runs, on the untouched c2 config) —
    a fresh sampling recovers, a REAL noise problem still fails after
    ``attempts``. Never weakens the gate itself.

    Each config's sampling runs inside a ``bench.stage`` span, so a
    ``--trace`` bench leaves a per-stage timeline (out/trace_bench.json)
    beside the published numbers."""
    last = None
    with _tracing.span(_tracing.SPAN_BENCH_STAGE, stage=label):
        for i in range(attempts):
            try:
                return marginal(time_fn, n_lo, n_hi, label)
            except InvalidMeasurement as exc:
                last = exc
                if i + 1 < attempts:
                    print(
                        f"{label}: resampling after noise gate "
                        f"({i + 1}/{attempts})",
                        file=sys.stderr,
                    )
    raise last


def _bench_wire_modes(extra: dict) -> int:
    """The workers-backend data plane on a loopback 4-worker cluster
    (in-process RpcServers — real sockets, real frames): ``-wire full``
    vs ``haloed`` vs ``resident`` at K ∈ {1, 8}. Beside the wall-clock
    fit, each case embeds ``wire_bytes_per_turn`` measured from
    ``gol_wire_bytes_total`` over a fixed run — so ``scripts/bench_diff``
    gates the COMMS trajectory, not just wall-clock. The resident-vs-
    haloed byte ratio is a hard gate here (≥ 10×): byte accounting is
    deterministic, unlike loopback timing."""
    import shutil
    import tempfile
    import threading

    import numpy as np

    from gol_distributed_final_tpu.obs import fleet as obs_fleet
    from gol_distributed_final_tpu.obs import journal as obs_journal
    from gol_distributed_final_tpu.obs import metrics as obs_metrics
    from gol_distributed_final_tpu.obs import perf as obs_perf
    from gol_distributed_final_tpu.obs import profiler as obs_profiler
    from gol_distributed_final_tpu.obs import timeline as obs_timeline
    from gol_distributed_final_tpu.rpc import integrity as _integrity
    from gol_distributed_final_tpu.rpc import worker as rpc_worker
    from gol_distributed_final_tpu.rpc.broker import WorkersBackend
    from gol_distributed_final_tpu.rpc.protocol import Request

    def wire_bytes() -> float:
        for fam in obs_metrics.registry().snapshot()["families"]:
            if fam["name"] == "gol_wire_bytes_total":
                return sum(s["value"] for s in fam["series"])
        return 0.0

    size = 256
    servers = [rpc_worker.serve(port=0) for _ in range(4)]
    addrs = [f"127.0.0.1:{s.port}" for s, _ in servers]
    rng = np.random.default_rng(1)
    board = np.where(rng.random((size, size)) < 0.3, 255, 0).astype(np.uint8)
    want100 = None  # cross-mode parity reference (100 turns)
    jdir = tempfile.mkdtemp(prefix="gol_bench_journal_")
    try:
        for wire, k, key, n_lo, n_hi, check, timeline, attribution, journal, profile, fleet in (
            ("full", 1, "c7_wire_full", 30, 230, True, False, True, False, False, False),
            ("haloed", 1, "c7_wire_haloed", 30, 230, True, False, True, False, False, False),
            # resident turns are much cheaper per RPC: wider endpoints so
            # the marginal work still dominates loopback timing noise
            ("resident", 1, "c7_wire_resident_k1", 100, 1100, True, False, True, False, False, False),
            ("resident", 8, "c7_wire_resident_k8", 100, 1100, True, False, True, False, False, False),
            # the same case UNDEFENDED (-integrity off, both sides): the
            # checked case above pays the in-header frame crcs + adler32
            # attestations, so the pair prices the integrity layer — the
            # overhead gate below holds it under 3% of resident turn cost
            ("resident", 8, "c7_wire_resident_k8_nock", 100, 1100, False, False, True, False, False, False),
            # the same case with the -timeline sampler ON (1 s cadence,
            # the serving default): prices the always-on history + SLO
            # evaluation; the overhead gate below holds it under 2%
            ("resident", 8, "c7_wire_resident_k8_timeline", 100, 1100, True, True, True, False, False, False),
            # the same case with the dispatch-wall decomposition + the
            # critical-path attribution OFF (obs/perf.set_attribution):
            # the on-vs-off pair prices the WHERE-TIME-GOES layer; the
            # overhead gate below holds it under 2%
            ("resident", 8, "c7_wire_resident_k8_noattr", 100, 1100, True, False, False, False, False, False),
            # the same case with the durable lifecycle journal ON
            # (obs/journal.py: hot-path record() calls + the buffered
            # segment writer, flushing to a throwaway dir): prices the
            # "-journal in production" story; the overhead gate below
            # holds it under 2% of resident turn cost
            ("resident", 8, "c7_wire_resident_k8_journal", 100, 1100, True, False, True, True, False, False),
            # the same case with the continuous sampling profiler ON
            # (obs/profiler.py: 10 ms wall-clock stack sampling + GC
            # pause metering, adaptive backoff armed): prices the
            # "-profile in production" story; the overhead gate below
            # holds it under 2% of resident turn cost, and the case
            # embeds the sampled hot-frame table for regress's
            # cross-round top-mover gate
            ("resident", 8, "c7_wire_resident_k8_profile", 100, 1100, True, False, True, False, True, False),
            # the same case SCRAPED: a FleetCollector sweeping all 4
            # workers' Status endpoints at a 1 s cadence (5x the 5 s
            # production default) from a background thread (obs/fleet.py
            # — parallel fan-out, exact registry merge, fleet gauges)
            # while the data plane runs. The on-vs-off pair prices "a
            # collector is watching" for the serving story; the overhead
            # gate below holds the scrape tax under 2% of resident turn
            # cost, and the case embeds fleet_scrape_p99_us (p99 of
            # gol_fleet_scrape_seconds over the run) for regress's
            # cross-round gate
            ("resident", 8, "c7_wire_resident_k8_fleet", 100, 1100, True, False, True, False, False, True),
        ):
            _integrity.set_enabled(check)
            obs_perf.set_attribution(attribution)
            if timeline:
                obs_timeline.enable(period=1.0)
            if journal:
                obs_journal.enable(out_dir=jdir, role="bench")
            if profile:
                obs_profiler.enable(period_ms=10.0, out_dir=jdir, tag="bench")
            collector = scrape_stop = scrape_thread = None
            if fleet:
                # the collector scrapes the four workers directly (no
                # broker in this loopback rig). 1 s cadence: aggressive
                # (5x the production default) but honest — every scrape
                # serve + the whole-registry merge runs IN this process,
                # so a saturating cadence would price GIL contention the
                # deployment never sees, not the collector
                collector = obs_fleet.FleetCollector(
                    [], extra_workers=addrs, interval=1.0, timeout=5.0
                )
                scrape_stop = threading.Event()

                def _scrape_loop(c=collector, stop=scrape_stop):
                    while not stop.is_set():
                        c.sweep()
                        stop.wait(1.0)

                scrape_thread = threading.Thread(
                    target=_scrape_loop, name="bench-fleet-scrape",
                    daemon=True,
                )
                scrape_thread.start()
            backend = WorkersBackend(addrs, wire=wire, halo_depth=k)
            try:
                def evolve(n, backend=backend):
                    return backend.run(
                        Request(
                            world=board, turns=n, threads=4,
                            image_width=size, image_height=size,
                        )
                    )

                got = np.asarray(evolve(100).world)
                if want100 is None:
                    want100 = got
                elif not np.array_equal(got, want100):
                    print(f"PARITY FAILURE wire={wire} k={k}", file=sys.stderr)
                    return 1
                n_bytes = 400 if wire == "resident" else 200
                b0 = wire_bytes()
                evolve(n_bytes)
                per_turn_bytes = (wire_bytes() - b0) / n_bytes
                pt, det = gated(evolve, n_lo, n_hi, key)
                extra[key] = dict(
                    det,
                    cell_updates_per_s=round(size * size / pt),
                    wire=wire,
                    halo_depth=k,
                    wire_bytes_per_turn=round(per_turn_bytes, 1),
                )
                if profile:
                    # embed the sampled top busy frames BEFORE disable
                    # (disable drops the trie): regress's cross-round
                    # top-mover gate reads this table out of BENCH_r*.json
                    ps = obs_profiler.summary() or {}
                    frames = [
                        r for r in ps.get("frames") or []
                        if not obs_profiler.is_idle_frame(
                            r.get("func", ""), r.get("file", "")
                        )
                    ]
                    busy_total = sum(r.get("self") or 0 for r in frames)
                    extra[key]["profile_hot"] = [
                        {
                            "frame": obs_profiler.frame_name(
                                r["func"], r["file"], r["line"]
                            ),
                            "self_share": round(
                                (r.get("self") or 0) / busy_total, 3
                            ),
                        }
                        for r in frames[:5]
                    ] if busy_total else []
                    extra[key]["profile_samples"] = ps.get("stacks", 0)
                if fleet:
                    # embed the sweep-latency p99 (µs) from the
                    # gol_fleet_scrape_seconds histogram — the scrape
                    # plane's own cost, priced beside the data-plane tax
                    for fam in obs_metrics.registry().snapshot()["families"]:
                        if fam["name"] != "gol_fleet_scrape_seconds":
                            continue
                        for s in fam["series"]:
                            p99 = obs_timeline.quantile_from_buckets(
                                tuple(fam["le"]), s["buckets"], 0.99
                            )
                            if p99 is not None:
                                extra[key]["fleet_scrape_p99_us"] = round(
                                    p99 * 1e6, 1
                                )
                    extra[key]["fleet_sweeps"] = collector.sweeps
            finally:
                if scrape_stop is not None:
                    scrape_stop.set()
                    scrape_thread.join(timeout=10.0)
                backend.close()
                if timeline:
                    obs_timeline.disable()
                if journal:
                    obs_journal.disable()
                if profile:
                    obs_profiler.disable()
        print("parity wire modes ok (100 turns, cross-mode)", file=sys.stderr)
        hal = extra["c7_wire_haloed"]["wire_bytes_per_turn"]
        res8 = extra["c7_wire_resident_k8"]["wire_bytes_per_turn"]
        if res8 * 10 > hal:
            print(
                f"WIRE GATE FAILURE: resident k8 moves {res8:.0f} B/turn vs "
                f"haloed {hal:.0f} — less than the 10x contract",
                file=sys.stderr,
            )
            return 1
        extra["c7_wire_resident_k8"]["bytes_ratio_vs_haloed"] = round(
            hal / res8, 1
        )
        print(
            f"wire gate ok: resident k8 {res8:.0f} B/turn, haloed "
            f"{hal:.0f} B/turn ({hal / res8:.0f}x fewer)",
            file=sys.stderr,
        )
        # integrity overhead gate: checked vs unchecked resident K=8. Byte
        # accounting is deterministic; wall-clock is not, so the 3% bound
        # gets each fit's own noise band on top (the obs/regress posture) —
        # a loopback scheduling hiccup must not fail the bench, a real
        # hashing-cost regression must. The embedded overhead_pct rides
        # into BENCH_r*.json so bench_diff gates the trajectory too.
        ck, nock = extra["c7_wire_resident_k8"], extra["c7_wire_resident_k8_nock"]
        pt_ck = ck["per_turn_us"]
        pt_no = nock["per_turn_us"]
        noise_us = sum(
            c["spread_s"] / (c["n_hi"] - c["n_lo"]) * 1e6 for c in (ck, nock)
        )
        overhead_pct = (pt_ck - pt_no) / pt_no * 100.0
        ck["integrity_overhead_pct"] = round(overhead_pct, 2)
        if pt_ck - pt_no > 0.03 * pt_no + 2 * noise_us:
            print(
                f"INTEGRITY OVERHEAD GATE FAILURE: checked resident k8 "
                f"{pt_ck:.2f} us/turn vs unchecked {pt_no:.2f} "
                f"({overhead_pct:+.1f}%) exceeds 3% beyond the "
                f"{noise_us:.2f} us noise band",
                file=sys.stderr,
            )
            return 1
        print(
            f"integrity overhead ok: checked {pt_ck:.2f} us/turn vs "
            f"unchecked {pt_no:.2f} ({overhead_pct:+.1f}%, band "
            f"{2 * noise_us:.2f} us)",
            file=sys.stderr,
        )
        # timeline overhead gate: sampler-on vs sampler-off resident K=8,
        # the same noise-band posture as the integrity pair — always-on
        # history must stay under 2% of resident turn cost or the
        # "-timeline in production" story dies here, not in a deployment
        tl = extra["c7_wire_resident_k8_timeline"]
        pt_tl = tl["per_turn_us"]
        tl_noise_us = sum(
            c["spread_s"] / (c["n_hi"] - c["n_lo"]) * 1e6 for c in (ck, tl)
        )
        tl_overhead_pct = (pt_tl - pt_ck) / pt_ck * 100.0
        tl["timeline_overhead_pct"] = round(tl_overhead_pct, 2)
        if pt_tl - pt_ck > 0.02 * pt_ck + 2 * tl_noise_us:
            print(
                f"TIMELINE OVERHEAD GATE FAILURE: sampler-on resident k8 "
                f"{pt_tl:.2f} us/turn vs off {pt_ck:.2f} "
                f"({tl_overhead_pct:+.1f}%) exceeds 2% beyond the "
                f"{tl_noise_us:.2f} us noise band",
                file=sys.stderr,
            )
            return 1
        print(
            f"timeline overhead ok: sampler on {pt_tl:.2f} us/turn vs "
            f"off {pt_ck:.2f} ({tl_overhead_pct:+.1f}%, band "
            f"{2 * tl_noise_us:.2f} us)",
            file=sys.stderr,
        )
        # decomposition overhead gate: attribution-on (the plain checked
        # k8 case — segments, per-call walls, the critical-path tracker)
        # vs attribution-off, same noise-band posture — the WHERE-TIME-
        # GOES layer must stay under 2% of resident turn cost or the
        # "attribution always on in production" story dies here
        na = extra["c7_wire_resident_k8_noattr"]
        pt_na = na["per_turn_us"]
        na_noise_us = sum(
            c["spread_s"] / (c["n_hi"] - c["n_lo"]) * 1e6 for c in (ck, na)
        )
        decomp_overhead_pct = (pt_ck - pt_na) / pt_na * 100.0
        ck["decomposition_overhead_pct"] = round(decomp_overhead_pct, 2)
        if pt_ck - pt_na > 0.02 * pt_na + 2 * na_noise_us:
            print(
                f"DECOMPOSITION OVERHEAD GATE FAILURE: attribution-on "
                f"resident k8 {pt_ck:.2f} us/turn vs off {pt_na:.2f} "
                f"({decomp_overhead_pct:+.1f}%) exceeds 2% beyond the "
                f"{na_noise_us:.2f} us noise band",
                file=sys.stderr,
            )
            return 1
        print(
            f"decomposition overhead ok: attribution on {pt_ck:.2f} "
            f"us/turn vs off {pt_na:.2f} ({decomp_overhead_pct:+.1f}%, "
            f"band {2 * na_noise_us:.2f} us)",
            file=sys.stderr,
        )
        # journal overhead gate: journal-on vs journal-off resident K=8,
        # the same noise-band posture — the durable lifecycle journal
        # (one record per chunk commit plus the buffered segment writer)
        # must stay under 2% of resident turn cost or the "persistent
        # universes run -journal always" story dies here
        jn = extra["c7_wire_resident_k8_journal"]
        pt_jn = jn["per_turn_us"]
        jn_noise_us = sum(
            c["spread_s"] / (c["n_hi"] - c["n_lo"]) * 1e6 for c in (ck, jn)
        )
        journal_overhead_pct = (pt_jn - pt_ck) / pt_ck * 100.0
        jn["journal_overhead_pct"] = round(journal_overhead_pct, 2)
        if pt_jn - pt_ck > 0.02 * pt_ck + 2 * jn_noise_us:
            print(
                f"JOURNAL OVERHEAD GATE FAILURE: journal-on resident k8 "
                f"{pt_jn:.2f} us/turn vs off {pt_ck:.2f} "
                f"({journal_overhead_pct:+.1f}%) exceeds 2% beyond the "
                f"{jn_noise_us:.2f} us noise band",
                file=sys.stderr,
            )
            return 1
        print(
            f"journal overhead ok: journal on {pt_jn:.2f} us/turn vs "
            f"off {pt_ck:.2f} ({journal_overhead_pct:+.1f}%, band "
            f"{2 * jn_noise_us:.2f} us)",
            file=sys.stderr,
        )
        # profiler overhead gate: profiler-on vs profiler-off resident
        # K=8, the same noise-band posture — 10 ms wall-clock stack
        # sampling (plus GC pause metering) must stay under 2% of
        # resident turn cost or the "continuous profiling in
        # production" story dies here; the adaptive backoff exists
        # precisely to make this gate holdable on slow hosts
        pr = extra["c7_wire_resident_k8_profile"]
        pt_pr = pr["per_turn_us"]
        pr_noise_us = sum(
            c["spread_s"] / (c["n_hi"] - c["n_lo"]) * 1e6 for c in (ck, pr)
        )
        profile_overhead_pct = (pt_pr - pt_ck) / pt_ck * 100.0
        pr["profile_overhead_pct"] = round(profile_overhead_pct, 2)
        if pt_pr - pt_ck > 0.02 * pt_ck + 2 * pr_noise_us:
            print(
                f"PROFILER OVERHEAD GATE FAILURE: profiler-on resident k8 "
                f"{pt_pr:.2f} us/turn vs off {pt_ck:.2f} "
                f"({profile_overhead_pct:+.1f}%) exceeds 2% beyond the "
                f"{pr_noise_us:.2f} us noise band",
                file=sys.stderr,
            )
            return 1
        print(
            f"profiler overhead ok: profiler on {pt_pr:.2f} us/turn vs "
            f"off {pt_ck:.2f} ({profile_overhead_pct:+.1f}%, band "
            f"{2 * pr_noise_us:.2f} us; {pr.get('profile_samples', 0)} "
            f"stacks sampled)",
            file=sys.stderr,
        )
        # fleet scrape-tax gate: collector-on vs collector-off resident
        # K=8, the same noise-band posture — a FleetCollector hammering
        # the workers' Status endpoints at a 10 ms cadence (parallel
        # fan-out + exact registry merge per sweep) must cost the DATA
        # PLANE under 2% of resident turn cost, or the "point a
        # collector at production and leave it" story dies here. The
        # embedded fleet_overhead_pct and fleet_scrape_p99_us ride into
        # BENCH_r*.json so obs/regress.py gates the trajectory too.
        fl = extra["c7_wire_resident_k8_fleet"]
        pt_fl = fl["per_turn_us"]
        fl_noise_us = sum(
            c["spread_s"] / (c["n_hi"] - c["n_lo"]) * 1e6 for c in (ck, fl)
        )
        fleet_overhead_pct = (pt_fl - pt_ck) / pt_ck * 100.0
        fl["fleet_overhead_pct"] = round(fleet_overhead_pct, 2)
        if pt_fl - pt_ck > 0.02 * pt_ck + 2 * fl_noise_us:
            print(
                f"FLEET OVERHEAD GATE FAILURE: collector-on resident k8 "
                f"{pt_fl:.2f} us/turn vs off {pt_ck:.2f} "
                f"({fleet_overhead_pct:+.1f}%) exceeds 2% beyond the "
                f"{fl_noise_us:.2f} us noise band",
                file=sys.stderr,
            )
            return 1
        print(
            f"fleet overhead ok: collector on {pt_fl:.2f} us/turn vs "
            f"off {pt_ck:.2f} ({fleet_overhead_pct:+.1f}%, band "
            f"{2 * fl_noise_us:.2f} us; {fl.get('fleet_sweeps', 0)} "
            f"sweeps, scrape p99 {fl.get('fleet_scrape_p99_us', 0)} us)",
            file=sys.stderr,
        )
    finally:
        _integrity.set_enabled(True)
        obs_perf.set_attribution(True)
        obs_timeline.disable()
        obs_journal.disable()
        obs_profiler.disable()
        shutil.rmtree(jdir, ignore_errors=True)
        for server, _service in servers:
            server.stop()
    return 0


def _bench_tile_grid(extra: dict) -> int:
    """2-D checkerboard tiles vs 1-D strips (config 13): the SAME
    4-worker loopback resident cluster, K=8, on a square 256² board —
    ``-grid 2x2`` (each worker owns a 128² tile, per-worker wire cost
    O(K·(tile_h+tile_w)) in packed bits) against ``-grid 1x4`` (full
    256-wide strips, O(K·W) raw rows). Each case embeds
    ``halo_bytes_per_turn`` measured from ``gol_halo_bytes_total`` so
    ``obs/regress.py`` gates the halo trajectory across rounds; the
    pair itself is a HARD deterministic gate here — the two boards must
    be bit-identical and the 2x2 halo bytes must come in at ≤ 0.6x of
    the strip plane's (byte accounting is exact, unlike loopback
    timing; the square board is the strip plane's BEST case, so the
    margin is all bit-packing and corner geometry)."""
    import numpy as np

    from gol_distributed_final_tpu.obs import metrics as obs_metrics
    from gol_distributed_final_tpu.rpc import worker as rpc_worker
    from gol_distributed_final_tpu.rpc.broker import WorkersBackend
    from gol_distributed_final_tpu.rpc.protocol import Request

    def halo_bytes() -> float:
        for fam in obs_metrics.registry().snapshot()["families"]:
            if fam["name"] == "gol_halo_bytes_total":
                return sum(s["value"] for s in fam["series"])
        return 0.0

    size = 256
    servers = [rpc_worker.serve(port=0) for _ in range(4)]
    addrs = [f"127.0.0.1:{s.port}" for s, _ in servers]
    rng = np.random.default_rng(3)
    board = np.where(rng.random((size, size)) < 0.3, 255, 0).astype(np.uint8)
    want100 = None  # cross-grid parity reference (100 turns)
    try:
        for grid, key, n_lo, n_hi in (
            ("2x2", "c13_tile_2x2_k8", 100, 1100),
            # the SAME roster forced into the legacy strip plane (an
            # explicit one-column grid routes the strip loop, byte-
            # identical to a plain resident run) — the baseline the
            # tile gate below is measured against
            ("1x4", "c13_tile_1x4_k8", 100, 1100),
        ):
            backend = WorkersBackend(
                addrs, wire="resident", halo_depth=8, grid=grid
            )
            try:
                def evolve(n, backend=backend):
                    return backend.run(
                        Request(
                            world=board, turns=n, threads=4,
                            image_width=size, image_height=size,
                        )
                    )

                got = np.asarray(evolve(100).world)
                if want100 is None:
                    want100 = got
                elif not np.array_equal(got, want100):
                    print(
                        f"TILE PARITY FAILURE: grid={grid} diverges from "
                        f"2x2 at 100 turns", file=sys.stderr,
                    )
                    return 1
                n_bytes = 400
                b0 = halo_bytes()
                evolve(n_bytes)
                per_turn_halo = (halo_bytes() - b0) / n_bytes
                pt, det = gated(evolve, n_lo, n_hi, key)
                extra[key] = dict(
                    det,
                    cell_updates_per_s=round(size * size / pt),
                    wire="resident",
                    halo_depth=8,
                    grid=grid,
                    halo_bytes_per_turn=round(per_turn_halo, 1),
                )
            finally:
                backend.close()
        print("parity tile grids ok (100 turns, 2x2 vs 1x4)", file=sys.stderr)
        tile = extra["c13_tile_2x2_k8"]["halo_bytes_per_turn"]
        strip = extra["c13_tile_1x4_k8"]["halo_bytes_per_turn"]
        if not tile or not strip or tile > 0.6 * strip:
            print(
                f"TILE HALO GATE FAILURE: 2x2 moves {tile:.0f} halo B/turn "
                f"vs 1x4 strips {strip:.0f} — more than the 0.6x contract",
                file=sys.stderr,
            )
            return 1
        extra["c13_tile_2x2_k8"]["halo_ratio_vs_strips"] = round(
            tile / strip, 3
        )
        print(
            f"tile halo gate ok: 2x2 {tile:.0f} halo B/turn vs 1x4 strips "
            f"{strip:.0f} ({tile / strip:.2f}x, contract <= 0.6x)",
            file=sys.stderr,
        )
    finally:
        for server, _service in servers:
            server.stop()
    return 0


def _bench_sparse_wire(extra: dict) -> int:
    """Dirty-tile delta syncs (config 11): a <1%-active 16384² R-pentomino
    on a loopback 4-worker RESIDENT cluster, measured at the run-end
    StripFetch sync. The sparse side fetches deltas against the broker's
    seed-time copy (ops/sparse.py wire tiles, reconstruction digest-
    verified against the committed strip chain); the control side forces
    full frames (``-sparse-sync off``). Byte accounting is deterministic,
    so the ≥10× contract is a HARD gate (the PR 5 wire-byte posture), and
    ``sparse_frame_bytes_per_sync`` rides into BENCH_r*.json so
    ``obs/regress.py`` gates the trajectory alongside wire bytes."""
    import numpy as np

    from gol_distributed_final_tpu.obs import metrics as obs_metrics
    from gol_distributed_final_tpu.rpc import worker as rpc_worker
    from gol_distributed_final_tpu.rpc.broker import WorkersBackend
    from gol_distributed_final_tpu.rpc.protocol import Methods, Request

    def fetch_received() -> float:
        total = 0.0
        for fam in obs_metrics.registry().snapshot()["families"]:
            if fam["name"] == "gol_wire_bytes_total":
                for s in fam["series"]:
                    if s.get("labels") == [Methods.STRIP_FETCH, "received"]:
                        total += s["value"]
        return total

    size, turns = 16384, 1
    board = np.zeros((size, size), np.uint8)
    cx = cy = size // 2
    for dx, dy in ((1, 0), (2, 0), (0, 1), (1, 1), (1, 2)):
        board[cy + dy, cx + dx] = 255
    sync_bytes = {}
    for sparse in (True, False):
        servers = [rpc_worker.serve(port=0) for _ in range(4)]
        addrs = [f"127.0.0.1:{s.port}" for s, _ in servers]
        backend = WorkersBackend(
            addrs, wire="resident", halo_depth=1, sync_interval=0,
            sparse_sync=sparse,
        )
        try:
            b0 = fetch_received()
            res = backend.run(Request(
                world=board, turns=turns, threads=4,
                image_width=size, image_height=size,
            ))
            sync_bytes[sparse] = fetch_received() - b0
            if int(np.count_nonzero(res.world)) != int(
                np.count_nonzero(oracle_step_n(
                    board[cy - 8:cy + 8, cx - 8:cx + 8], turns
                ))
            ):
                print("SPARSE WIRE PARITY FAILURE", file=sys.stderr)
                return 1
        finally:
            backend.close()
            for server, _service in servers:
                server.stop()
    delta_b, full_b = sync_bytes[True], sync_bytes[False]
    if delta_b * 10 > full_b:
        print(
            f"SPARSE WIRE GATE FAILURE: delta sync ships {delta_b:.0f} B "
            f"vs full gather {full_b:.0f} — less than the 10x contract",
            file=sys.stderr,
        )
        return 1
    print(
        f"sparse wire gate ok: delta sync {delta_b:.0f} B vs full gather "
        f"{full_b:.0f} B ({full_b / delta_b:.0f}x fewer)", file=sys.stderr,
    )
    extra["c11_sparse_wire_16384"] = {
        # no wall-clock fit here — the contract is BYTES (deterministic);
        # per_turn_us=0 keeps the case visible to bench_diff, which
        # reports it incomparable on wall-clock and gates the bytes
        "per_turn_us": 0.0,
        "sparse_frame_bytes_per_sync": round(delta_b, 1),
        "full_gather_bytes_per_sync": round(full_b, 1),
        "bytes_ratio_vs_full": round(full_b / delta_b, 1),
        "workers": 4,
        "turns": turns,
    }
    return 0


def _bench_fused(extra: dict) -> int:
    """Fused vs serial launch chains (config 12): the 128² floor case —
    BENCH_r04's launch-bound site — stepped two ways on the same device:

    * ``c12_128_serial_per_turn`` — ONE kernel launch per turn (the
      per-turn dispatch chain every pre-fused caller pays): the floor
      this PR exists to kill, embedded with ``dispatches_per_turn=1.0``.
    * ``c12_128_fused_k8`` — the fused ladder (ops/fused.py): K=8 turns
      per launch, all launches inside one jitted program;
      ``dispatches_per_turn=1/K`` (exact ladder arithmetic — launch
      accounting is deterministic, so obs/regress.py gates it with no
      noise band, the wire-bytes posture).

    Hard gates: bit-identical boards (odd 137-turn horizon, so the pow2
    remainder ladder is in the parity path), fused ≥ 5× serial per turn
    on TPU (the ISSUE 15 acceptance bar; ≥ 2× elsewhere — a CPU serial
    chain pays a smaller dispatch floor, measured ~12× here), and the
    PR 12 roofline flip: where the serial chain classifies launch-bound,
    the fused case must NOT (asserted on TPU, reported elsewhere —
    fitted CPU ceilings are too coarse to pin a hard class)."""
    import numpy as np

    import jax

    from gol_distributed_final_tpu.io.pgm import read_pgm
    from gol_distributed_final_tpu.obs import perf as obs_perf
    from gol_distributed_final_tpu.ops import bitpack
    from gol_distributed_final_tpu.ops.fused import _ladder, fused_bit_step_n
    from gol_distributed_final_tpu.ops.pallas_stencil import _bit_compiled

    on_tpu = jax.devices()[0].platform == "tpu"
    size, fused_k = 128, 8
    board = read_pgm("images/128x128.pgm")
    packed = jax.device_put(bitpack.pack(board, 0))
    step1 = _bit_compiled(1, 0, not on_tpu)

    def evolve_serial(n):
        # the per-turn dispatch chain: n launches, serially dependent
        state = packed
        for _ in range(n):
            state = step1(state)
        return np.asarray(state)  # full sync (the c3 posture)

    def evolve_fused(n):
        return np.asarray(
            fused_bit_step_n(packed, n, k=fused_k, interpret=not on_tpu)
        )

    if not np.array_equal(evolve_serial(137), evolve_fused(137)):
        print(
            "FUSED PARITY FAILURE: fused-K 128^2 diverges from the serial "
            "per-turn chain at 137 turns", file=sys.stderr,
        )
        return 1
    print("parity fused ok (137 turns, fused == serial bit-identical)",
          file=sys.stderr)

    ns_lo, ns_hi = 2_000, 22_000
    evolve_serial(ns_lo), evolve_serial(ns_hi)  # warm both shapes
    pt_serial, det_serial = gated(
        evolve_serial, ns_lo, ns_hi, "c12_128_serial_per_turn"
    )
    nf_lo, nf_hi = 20_000, 520_000
    evolve_fused(nf_lo), evolve_fused(nf_hi)
    pt_fused, det_fused = gated(evolve_fused, nf_lo, nf_hi, "c12_128_fused_k8")

    full, rem_ks = _ladder(nf_hi, fused_k)
    fused_dpt = (full + len(rem_ks)) / nf_hi
    speedup = pt_serial / pt_fused
    floor_gate = 5.0 if on_tpu else 2.0
    if speedup < floor_gate:
        print(
            f"FUSED GATE FAILURE: fused K={fused_k} is only {speedup:.1f}x "
            f"the serial per-turn chain ({pt_fused * 1e6:.3f} vs "
            f"{pt_serial * 1e6:.3f} us/turn) — less than the "
            f"{floor_gate:.0f}x contract", file=sys.stderr,
        )
        return 1
    print(
        f"fused gate ok: {pt_fused * 1e6:.3f} us/turn fused vs "
        f"{pt_serial * 1e6:.3f} serial ({speedup:.1f}x, gate "
        f"{floor_gate:.0f}x)", file=sys.stderr,
    )

    # roofline flip (obs/perf.py): the serial chain's wall is the launch
    # floor; the fused case must leave the launch-bound class behind
    ceilings = obs_perf.calibrate()
    cls_serial = obs_perf.classify_case(size, size, pt_serial, ceilings)
    cls_fused = obs_perf.classify_case(size, size, pt_fused, ceilings)
    print(
        f"roofline fused pair: serial {cls_serial['bound_class']} -> "
        f"fused {cls_fused['bound_class']} (vs {ceilings.device_kind} "
        "ceilings)", file=sys.stderr,
    )
    if (
        on_tpu
        and cls_serial["bound_class"] == "launch-bound"
        and cls_fused["bound_class"] == "launch-bound"
    ):
        print(
            "FUSED ROOFLINE GATE FAILURE: the fused 128^2 case still "
            "classifies launch-bound — K turns per launch did not move "
            "the site off the dispatch floor", file=sys.stderr,
        )
        return 1

    extra["c12_128_serial_per_turn"] = dict(
        det_serial,
        cell_updates_per_s=round(size * size / pt_serial),
        dispatches_per_turn=1.0,
        **cls_serial,
    )
    extra["c12_128_fused_k8"] = dict(
        det_fused,
        cell_updates_per_s=round(size * size / pt_fused),
        dispatches_per_turn=round(fused_dpt, 5),
        fused_k=fused_k,
        speedup_vs_serial=round(speedup, 1),
        **cls_fused,
    )
    return 0


def _bench_sessions(extra: dict) -> int:
    """Multi-universe serving (config 8): 1k × 128² concurrent universes
    in ONE device-resident session batch (engine/sessions.SessionTable
    over the batched kernel family) vs the SAME 1k universes served as
    sequential single-board runs on the same device. 128² is the measured
    dispatch-latency-bound case (BENCH_r04 c2: ~0.10 us/turn, unroll
    sweep flat — the serial launch chain is the floor), so the batch axis
    is the only lever: N universes per launch amortise the floor N ways.

    Gates (hard): every universe's batched result bit-identical to its
    sequential run, and batched serving ≥ 10× sessions/sec over
    sequential. The per-turn fit (``gated`` marginal over batch turns)
    rides into BENCH_r*.json with its noise band so ``scripts/bench_diff``
    gates the serving trajectory like every other case."""
    import numpy as np

    from gol_distributed_final_tpu.engine.sessions import SessionTable
    from gol_distributed_final_tpu.models import CONWAY
    from gol_distributed_final_tpu.ops.auto import auto_batch_plane, auto_plane

    B, size, turns = 1000, 128, 100
    rng = np.random.default_rng(7)
    boards = np.where(
        rng.random((B, size, size)) < 0.3, 255, 0
    ).astype(np.uint8)
    boards[0] = 0  # an all-dead universe rides the batch...
    boards[1] = 0  # ...and a lone glider: mixed liveness in one tensor
    for y, x in ((1, 2), (2, 3), (3, 1), (3, 2), (3, 3)):
        boards[1, y, x] = 255

    # sequential baseline: the same auto-selected single-board plane per
    # universe — 1000 independent dispatch chains, each paying the launch
    # floor (and its own host round-trip) alone. This pass doubles as the
    # parity reference.
    plane1 = auto_plane(CONWAY, (size, size))
    # untimed warm pass: the sequential side must be measured at steady
    # state exactly like the batched side (run_batch below is warmed and
    # min-of-3'd) — a cold t_seq would carry the one-time jit/pallas
    # compile wall and inflate the speedup the 10x gate enforces
    plane1.decode(plane1.step_n(plane1.encode(boards[0]), turns))
    t0 = time.perf_counter()
    seq = []
    for i in range(B):
        state = plane1.encode(boards[i])
        seq.append(plane1.decode(plane1.step_n(state, turns)))
    t_seq = time.perf_counter() - t0

    def run_batch():
        table = SessionTable(CONWAY, (size, size), capacity=B)
        sessions = [table.admit(boards[i], turns) for i in range(B)]
        while table.advance():
            pass
        return sessions

    sessions = run_batch()  # warm + compile; also the parity subject
    for i in range(B):
        if not np.array_equal(sessions[i].result, seq[i]):
            print(
                f"SESSIONS PARITY FAILURE: universe {i} diverges from its "
                f"sequential run",
                file=sys.stderr,
            )
            return 1
    print(f"parity sessions ok ({B} x {size}^2, {turns} turns, "
          "batched == sequential per universe)", file=sys.stderr)

    t_batch = None
    for _ in range(3):  # min over reps: the marginal-endpoint posture
        t0 = time.perf_counter()
        run_batch()
        dt = time.perf_counter() - t0
        t_batch = dt if t_batch is None else min(t_batch, dt)

    sessions_per_s = B / t_batch
    seq_sessions_per_s = B / t_seq
    speedup = t_seq / t_batch
    # the 10x contract is a DEVICE claim (the dispatch-latency floor being
    # amortised is the TPU launch chain + tunnel round-trip); on CPU the
    # sequential baseline pays no launch floor, so the hard gate there is
    # only "batching must win at all" — the TPU run the driver publishes
    # still enforces the full contract
    import jax

    floor_gate = 10.0 if jax.devices()[0].platform == "tpu" else 1.0
    if speedup < floor_gate:
        print(
            f"SESSIONS GATE FAILURE: batched serving is only {speedup:.1f}x "
            f"sequential ({sessions_per_s:.0f} vs {seq_sessions_per_s:.0f} "
            f"sessions/s) — less than the {floor_gate:.0f}x contract",
            file=sys.stderr,
        )
        return 1
    print(
        f"sessions gate ok: {sessions_per_s:,.0f} sessions/s batched vs "
        f"{seq_sessions_per_s:,.0f} sequential ({speedup:.1f}x, gate "
        f"{floor_gate:.0f}x)",
        file=sys.stderr,
    )

    # the bench_diff-gated fit: marginal per-BATCH-turn cost of the raw
    # batched kernel (1000 universes per turn), noise-banded like every
    # other case; sessions_per_s etc. ride along as extras
    plane_b = auto_batch_plane(CONWAY, (size, size))
    state_b = plane_b.encode(boards)

    def evolve_batch(n, plane_b=plane_b, state_b=state_b):
        # alive_counts syncs through the one batched reduction — B int32s
        # cross the device boundary, never the batch tensor
        return plane_b.alive_counts(plane_b.step_n(state_b, n))

    # endpoints sized from a probe of the actual batch-turn rate: the
    # marginal work must dominate the tunnel's ~50 ms noise spikes by
    # NOISE_MARGIN on TPU without inflating a CPU sanity run to hours
    evolve_batch(1_000)  # warm/compile at a probe shape
    t0 = time.perf_counter()
    evolve_batch(1_000)
    per_batch_turn = (time.perf_counter() - t0) / 1_000
    n_lo = 200
    n_hi = n_lo + max(2_000, int(0.5 / max(per_batch_turn, 1e-9)))
    n_hi = min(n_hi, 500_000)
    evolve_batch(n_lo), evolve_batch(n_hi)
    pt, det = gated(evolve_batch, n_lo, n_hi, "c8_sessions_batched")
    extra["c8_sessions_batched"] = dict(
        det,
        batch_universes=B,
        cell_updates_per_s=round(B * size * size / pt),
        sessions_per_s=round(sessions_per_s, 1),
        sequential_sessions_per_s=round(seq_sessions_per_s, 1),
        speedup_vs_sequential=round(speedup, 1),
        # the BENCH_r04 floor story: c2 measured 128^2 latency-bound at
        # ~0.10 us/turn (serial launch chain, unroll sweep flat); the
        # batch amortises that launch over B universes, so the effective
        # per-universe per-turn cost is pt / B
        per_universe_turn_us=round(pt * 1e6 / B, 5),
        floor_note="BENCH_r04 c2 floor ~0.10 us/turn is per-LAUNCH; "
        "batching N universes per launch divides it by N "
        "(ops/pallas_stencil._bit_compiled_batch)",
    )
    return 0


def _bench_loadgen(extra: dict) -> int:
    """Open-loop serving (config 9): the obs/loadgen.py generator against
    a loopback broker — the FULL client path (RPC frames, admission,
    batched session driver, tagged retrieves), not a kernel call.

    Two numbers ride into BENCH_r*.json:

    * the bench_diff-gated fit: marginal per-SESSION cost over a
      SERIAL schedule (max_inflight=1 — one session at a time, so the
      batch shapes the driver compiles stay fixed at B=1 and the fit is
      shape-stable run to run). This is the serving-path latency floor
      per session: RPC round-trip + admission + a batch-of-one's chunk
      chain — the overhead the batch axis amortises.
    * the serving story as extras: a concurrent burst (min over reps →
      ``sessions_per_s``) and an open-loop Poisson run at ~50%% of that
      measured capacity, whose client-side
      ``p99_admit_to_first_turn_us`` is the ROADMAP front-door
      objective measured for the first time — the number every
      admission-control stage will be gated against.
    """
    from gol_distributed_final_tpu.obs import accounting as obs_accounting
    from gol_distributed_final_tpu.obs import metrics as obs_metrics
    from gol_distributed_final_tpu.obs.loadgen import LoadConfig, LoadGenerator
    from gol_distributed_final_tpu.obs.status import scalar_value
    from gol_distributed_final_tpu.rpc.broker import serve

    def session_turns_metric() -> float:
        return scalar_value(
            obs_metrics.registry().snapshot(), "gol_session_turns_total"
        ) or 0.0

    obs_metrics.enable()  # idempotent; the ledger + meters must record
    obs_accounting.ledger().reset()
    # delta baseline: config 8 already moved the session counter; the
    # freshly-reset ledger must match THIS config's increment only
    turns_before = session_turns_metric()
    server, service = serve(port=0, session_capacity=1024)
    addr = f"127.0.0.1:{server.port}"
    size, turns = 16, 16
    try:
        def run_serial(n):
            summary = LoadGenerator(addr, LoadConfig(
                rate=1e6, sessions=n, arrival="burst", burst=1,
                tenants=4, size=size, turns=turns, seed=11,
                max_inflight=1,
            )).run()
            if summary["completed"] != n:
                raise InvalidMeasurement(
                    f"loadgen serial floor: only {summary['completed']}/{n} "
                    f"sessions completed ({summary['rejected_total']} "
                    f"rejected, {summary['errors']} errors)"
                )

        n_lo, n_hi = 20, 120
        run_serial(n_lo), run_serial(n_hi)  # warm the B=1 chunk shapes
        per_session, det = gated(run_serial, n_lo, n_hi, "c9_loadgen_open_loop")

        # concurrent burst: the serving capacity number (min over reps —
        # untimed-gated extras, like c8's sessions_per_s)
        burst_n, t_burst = 200, None
        for _ in range(3):
            t0 = time.perf_counter()
            summary = LoadGenerator(addr, LoadConfig(
                rate=1e6, sessions=burst_n, arrival="burst", burst=burst_n,
                tenants=8, size=size, turns=turns, seed=12,
            )).run()
            if summary["completed"] != burst_n:
                print(
                    f"LOADGEN BURST FAILURE: {summary['completed']}/"
                    f"{burst_n} completed", file=sys.stderr,
                )
                return 1
            dt = time.perf_counter() - t0
            t_burst = dt if t_burst is None else min(t_burst, dt)
        sessions_per_s = burst_n / t_burst

        # open-loop Poisson at ~50% of measured capacity: queueing is
        # real but bounded, so the p99 is a serving number, not a
        # saturation artifact
        rate = max(20.0, min(2000.0, 0.5 * sessions_per_s))
        poisson = LoadGenerator(addr, LoadConfig(
            rate=rate, sessions=300, arrival="poisson", tenants=8,
            tenant_dist="zipf", size=size, turns=turns, seed=13,
        )).run()
        if poisson["errors"]:
            print(
                f"LOADGEN POISSON FAILURE: {poisson['errors']} error(s)",
                file=sys.stderr,
            )
            return 1
        att = poisson["admit_to_first_turn"]
        e2e = poisson["session_e2e"]
        extra["c9_loadgen_open_loop"] = dict(
            det,
            unit_note="per_turn_us is per SESSION (serial floor): the "
            "full-RPC-path serving cost one session pays alone",
            sessions_per_s=round(sessions_per_s, 1),
            serial_sessions_per_s=round(1.0 / per_session, 1),
            concurrency_speedup=round(per_session * sessions_per_s, 1),
            open_loop_rate_per_s=round(rate, 1),
            p99_admit_to_first_turn_us=att.get("p99_us"),
            p50_admit_to_first_turn_us=att.get("p50_us"),
            p99_session_us=e2e.get("p99_us"),
            rejected=poisson["rejected_total"],
            tenants=8,
        )
        print(
            f"loadgen ok: serial floor {per_session * 1e3:.2f} ms/session, "
            f"{sessions_per_s:,.0f} sessions/s burst, open-loop p99 "
            f"admit-to-first-turn {att.get('p99_us', 0) / 1e3:.1f} ms "
            f"at {rate:.0f}/s", file=sys.stderr,
        )
        # reconciliation ride-along: the accounting ledger must agree
        # with the session meters after thousands of sessions (the
        # loadgen selfcheck contract, asserted here on TPU too)
        turns_delta = session_turns_metric() - turns_before
        ledger_turns = obs_accounting.ledger().totals().get("turns")
        if not ledger_turns or ledger_turns != int(turns_delta):
            print(
                f"LOADGEN LEDGER FAILURE: ledger turns {ledger_turns} != "
                f"gol_session_turns_total delta {int(turns_delta)}",
                file=sys.stderr,
            )
            return 1
        print(
            f"ledger reconciles: {ledger_turns} universe-turns attributed",
            file=sys.stderr,
        )
    finally:
        service._shutdown()
    return 0


def main(argv=None) -> int:
    import argparse
    import contextlib

    parser = argparse.ArgumentParser(description="GoL TPU benchmark")
    parser.add_argument(
        "--trace", action="store_true", default=False,
        help="record bench.stage / halo.dispatch spans and write a "
             "Perfetto-loadable out/trace_bench.json beside the JSON line",
    )
    parser.add_argument(
        "--trace-device", dest="trace_device", nargs="?",
        const="out/trace_device", default=None, metavar="DIR",
        help="wrap the whole bench in a jax.profiler device trace written "
             "to DIR (default out/trace_device), span names annotated",
    )
    args = parser.parse_args(argv)
    if args.trace:
        _tracing.enable()
        _tracing.set_process_name("bench")
    device_ctx = (
        _tracing.device_trace(args.trace_device)
        if args.trace_device else contextlib.nullcontext()
    )
    with device_ctx:
        rc = _bench_body()
    if args.trace:
        path = _tracing.write_chrome_trace(
            "out/trace_bench.json", _tracing.tracer().snapshot()
        )
        print(f"chrome trace written to {path}", file=sys.stderr)
    return rc


def _bench_body() -> int:
    import numpy as np

    import jax
    import jax.numpy as jnp

    from gol_distributed_final_tpu.io.pgm import read_pgm
    from gol_distributed_final_tpu.models import CONWAY
    from gol_distributed_final_tpu.ops import bitpack
    from gol_distributed_final_tpu.ops.pallas_stencil import _bit_compiled, fits_vmem
    from gol_distributed_final_tpu.ops.plane import BitPlane

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    print(f"bench device: {dev}", file=sys.stderr)
    extra = {}

    # per-stage attribution rides along with every published number: the
    # metrics registry (obs/) accumulates engine/dispatch/compile-cache
    # timings across all configs and lands in extra["stage_timings"], so
    # future rounds see WHERE the wall clock went, not just the totals
    from gol_distributed_final_tpu.obs import metrics as obs_metrics

    obs_metrics.enable()

    # ---- config 3 (headline): 512^2, pallas VMEM bitboard ----------------
    board = read_pgm("images/512x512.pgm")
    word_axis = 0  # rows packed: [H/32, W], lanes stay W wide
    packed = jax.device_put(bitpack.pack(board, word_axis))
    assert fits_vmem(packed.shape, itemsize=4)

    def evolve(n):
        # np.asarray forces a full device sync (block_until_ready does not
        # reliably wait under the remote tunnel)
        return np.asarray(_bit_compiled(n, word_axis, not on_tpu)(packed))

    for n, want in GOLDEN_512.items():
        alive = int(np.count_nonzero(bitpack.unpack(evolve(n), word_axis)))
        if alive != want:
            print(f"PARITY FAILURE 512^2 turn {n}: {alive} != {want}", file=sys.stderr)
            return 1
    print("parity 512^2 ok (turns 1000, 10000)", file=sys.stderr)

    n_lo, n_hi = 100_000, 2_100_000
    for n in (n_lo, n_hi):  # warm/compile + steady-state gate
        alive = int(np.count_nonzero(bitpack.unpack(evolve(n), word_axis)))
        if alive != STEADY_512[n % 2]:
            print(f"STEADY-STATE FAILURE at {n}: {alive}", file=sys.stderr)
            return 1
    per_turn, det = gated(evolve, n_lo, n_hi, "c3_512_pallas_bitboard")
    headline = 512 * 512 / per_turn
    extra["c3_512_pallas_bitboard"] = dict(det, cell_updates_per_s=round(headline))

    # ---- config 3, engine-driven: what Engine.run actually achieves ------
    from gol_distributed_final_tpu.engine.engine import Engine, EngineConfig
    from gol_distributed_final_tpu.params import Params

    cfg = EngineConfig(min_chunk=1 << 20, max_chunk=1 << 20, target_dispatch_seconds=8.0)

    def engine_run(n):
        r = Engine(cfg).run(
            Params(turns=n, image_width=512, image_height=512), board
        )
        return r

    alive = len(engine_run(10_000).alive)
    if alive != GOLDEN_512[10_000]:
        print(f"ENGINE PARITY FAILURE: {alive}", file=sys.stderr)
        return 1
    engine_run(n_lo), engine_run(n_hi)  # warm both endpoint shapes
    eng_per_turn, eng_det = gated(engine_run, n_lo, n_hi, "c3_512_engine_driven")
    extra["c3_512_engine_driven"] = dict(
        eng_det,
        cell_updates_per_s=round(512 * 512 / eng_per_turn),
        ratio_vs_raw_kernel=round(eng_per_turn / per_turn, 2),
    )

    # ---- config 2: 128^2 -------------------------------------------------
    b128 = read_pgm("images/128x128.pgm")
    want128 = int(np.count_nonzero(oracle_step_n(b128, 1000)))
    p128 = jax.device_put(bitpack.pack(b128, word_axis))

    def evolve128(n):
        return np.asarray(_bit_compiled(n, word_axis, not on_tpu)(p128))

    alive = int(np.count_nonzero(bitpack.unpack(evolve128(1000), word_axis)))
    if alive != want128:
        print(f"PARITY FAILURE 128^2: {alive} != {want128}", file=sys.stderr)
        return 1
    print("parity 128^2 ok (1000 turns vs numpy oracle)", file=sys.stderr)
    evolve128(n_lo), evolve128(n_hi)
    pt128, det128 = gated(evolve128, n_lo, n_hi, "c2_128_pallas_bitboard")
    extra["c2_128_pallas_bitboard"] = dict(
        det128,
        cell_updates_per_s=round(128 * 128 / pt128),
        # the small-board floor is the serial latency of one turn's ~39-op
        # bit-plane dependency chain, NOT loop overhead: an unroll sweep
        # (u=1..32, r4) measured u>=2 flat at ~100 ns/turn while 512^2
        # with 16x the cells costs only ~1.5x — full account in
        # ops/pallas_stencil.py::_bit_kernel
        floor_note="latency-bound: serial per-turn op chain; unroll sweep "
        "u>=2 flat (see ops/pallas_stencil._bit_kernel)",
    )

    # ---- config 4: 4096^2 (grid-tiled pallas beyond the whole-board gate) -
    rng = np.random.default_rng(0)
    b4k = np.where(rng.random((4096, 4096)) < 0.3, 255, 0).astype(np.uint8)
    plane = BitPlane(CONWAY, word_axis)
    state = plane.encode(b4k)
    assert not fits_vmem(state.shape, itemsize=4), "4096^2 must be past the whole-board VMEM gate"
    # cross-implementation parity: independent roll stencil, 100 turns
    want4k = CONWAY.step_n(jnp.asarray(b4k), 100)
    got4k = plane.decode(plane.step_n(state, 100))
    if not np.array_equal(got4k, np.asarray(want4k)):
        print("PARITY FAILURE 4096^2 vs roll stencil", file=sys.stderr)
        return 1
    print("parity 4096^2 ok (100 turns vs roll stencil)", file=sys.stderr)

    def evolve4k(n):
        # popcount sync: timed calls never transfer the packed state
        return bitpack.alive_count_packed(plane.step_n(state, n))

    # 60k marginal turns (~0.4s of work at ~7us/turn): the tunnel's ~35ms
    # round-trip noise spikes must be dominated 5x for the fit to publish
    n4_lo, n4_hi = 2_000, 62_000
    evolve4k(n4_lo), evolve4k(n4_hi)
    pt4k, det4k = gated(evolve4k, n4_lo, n4_hi, "c4_4096_tiled_bitboard")
    extra["c4_4096_tiled_bitboard"] = dict(
        det4k, cell_updates_per_s=round(4096 * 4096 / pt4k)
    )

    # ---- config 6: the mesh tax on one chip (VERDICT r4 item 7) ----------
    # The SAME packed evolution through the multi-chip code path — a
    # degenerate (1, 1) mesh: shard_map wrapper, local-wrap halo concats,
    # (at 4096^2) the tile-aligned ext padding of the pallas local route.
    # The ratio vs the raw single-chip kernel is the single-chip cost of
    # keeping the multi-chip path on — the reference's single-worker
    # fallback story (broker/broker.go:75-107).
    from gol_distributed_final_tpu.parallel import make_mesh
    from gol_distributed_final_tpu.parallel.bit_halo import ShardedBitPlane

    # depth 8 is the SECOND role of wide halos (r5 finding): the
    # tile-aligned ext is built once per 8 turns, amortising its HBM
    # materialisation 8-fold even where collective latency is free — on
    # chip, depth 8 at 512^2 measured ~2x over depth 1
    mesh11 = make_mesh((1, 1), devices=[dev])
    want_cache = {}  # per-size 96-turn reference: both depths share it
    for size, src, raw_pt, depth, key in (
        (512, board, per_turn, 1, "c6_512_mesh_tax"),
        (4096, b4k, pt4k, 1, "c6_4096_mesh_tax"),
        (512, board, per_turn, 8, "c6_512_mesh_tax_wide8"),
        (4096, b4k, pt4k, 8, "c6_4096_mesh_tax_wide8"),
    ):
        mplane = ShardedBitPlane(mesh11, CONWAY, word_axis, halo_depth=depth)
        mstate = mplane.encode(src)
        # parity vs the single-chip plane, on-device array equality
        # (96 = 12 wide iterations at depth 8, no remainder)
        if size not in want_cache:
            want_cache[size] = plane.step_n(plane.encode(src), 96)
        want_m = want_cache[size]
        got_m = mplane.step_n(mstate, 96)
        if not np.array_equal(np.asarray(got_m), np.asarray(want_m)):
            print(f"PARITY FAILURE {size}^2 mesh d{depth}", file=sys.stderr)
            return 1
        print(f"parity {size}^2 mesh(1,1) d{depth} ok (96 turns)", file=sys.stderr)

        def evolve_mesh(n, mplane=mplane, mstate=mstate):
            return bitpack.alive_count_packed(mplane.step_n(mstate, n))

        # endpoints sized for the mesh path's expected rate so marginal
        # work dominates tunnel noise 5x even if the tax is large
        n6_lo, n6_hi = (20_000, 420_000) if size == 512 else (2_000, 62_000)
        evolve_mesh(n6_lo), evolve_mesh(n6_hi)
        pt_mesh, det_mesh = gated(evolve_mesh, n6_lo, n6_hi, key)
        extra[key] = dict(
            det_mesh,
            cell_updates_per_s=round(size * size / pt_mesh),
            ratio_vs_raw_kernel=round(pt_mesh / raw_pt, 2),
        )
        del evolve_mesh, mstate, mplane

    # ---- config 5: 65536^2 sparse (THE BASELINE scale), 16384^2 waypoint --
    # The board exists only as a packed bitboard on device (512 MiB at
    # 65536^2), evolved by the grid-tiled pallas kernel. Timed calls sync
    # through a device-side popcount — a handful of KiB across the tunnel —
    # NOT a full-state transfer (the round-2 mistake: 32 MiB per call put
    # ~2 s +-1 s of noise around ~3 ms of marginal work and published a
    # negative throughput).
    from gol_distributed_final_tpu.bigboard import r_pentomino, seed_packed

    from gol_distributed_final_tpu.ops.sparse import (
        SparseBitPlane,
        active_fraction_of,
    )

    for size, key in ((16384, "c5_16384_sparse_bigboard"), (65536, "c5_65536_sparse_bigboard")):
        state_big = seed_packed(size, r_pentomino(size))
        plane_big = BitPlane(CONWAY, word_axis)
        state_1000 = plane_big.step_n(state_big, 1000)
        alive = bitpack.alive_count_packed(state_1000)
        if alive != 156:  # oracle-validated (tests/test_bigboard.py methodology)
            print(f"PARITY FAILURE {size}^2: {alive} != 156", file=sys.stderr)
            return 1
        print(f"parity {size}^2 ok (R-pentomino, 1000 turns)", file=sys.stderr)
        # the sparsity the dense path ignores: active-tile fraction of
        # the evolved board (ops/sparse.py tile geometry) — near zero
        # here, which is exactly why the c10 sparse pair below wins
        af_big = active_fraction_of(state_1000)
        del state_1000

        def evolve_big(n, state_big=state_big, plane_big=plane_big):
            return bitpack.alive_count_packed(plane_big.step_n(state_big, n))

        n5_lo, n5_hi = (2_000, 22_000) if size == 16384 else (500, 3_500)
        evolve_big(n5_lo), evolve_big(n5_hi)
        pt_big, det_big = gated(evolve_big, n5_lo, n5_hi, key)
        extra[key] = dict(
            det_big,
            cell_updates_per_s=round(size * size / pt_big),
            # per-ACTIVE-cell accounting (ISSUE 14 satellite): the dense
            # path updates the whole board to serve this tiny active
            # fraction, so its active throughput is cell_updates x af —
            # the figure obs/regress.py now gates alongside wall-clock
            active_fraction=round(af_big, 6),
            cell_updates_per_s_active=round(size * size * af_big / pt_big),
        )

        # ---- config 10: the sparse-vs-dense pair (16384^2 R-pentomino) ---
        # The activity-sparse plane (ops/sparse.SparseBitPlane) against
        # the dense fit just measured, SAME seed: the acceptance gate is
        # >= 5x wall-clock over 1000 turns with bit-identical boards.
        if size == 16384:
            sp = SparseBitPlane(CONWAY)
            sp_seed = sp.from_packed(state_big)
            want_pk = plane_big.step_n(state_big, 1000)
            got = sp.step_n(sp_seed, 1000)
            if not bool(jnp.all(got.packed == want_pk)):
                print(
                    "SPARSE PARITY FAILURE: 16384^2 R-pentomino sparse "
                    "!= dense at 1000 turns", file=sys.stderr,
                )
                return 1
            print(
                "parity 16384^2 sparse ok (1000 turns, bit-identical to "
                "dense)", file=sys.stderr,
            )
            del want_pk

            def evolve_sp(n, sp=sp, sp_seed=sp_seed):
                return bitpack.alive_count_packed(
                    sp.step_n(sp_seed, n).packed
                )

            n10_lo, n10_hi = 500, 2_500
            evolve_sp(n10_lo), evolve_sp(n10_hi)
            pt_sp, det_sp = gated(
                evolve_sp, n10_lo, n10_hi, "c10_16384_rpent_sparse"
            )
            wall_sparse = None
            for _ in range(3):
                t0 = time.perf_counter()
                evolve_sp(1000)
                dt = time.perf_counter() - t0
                wall_sparse = dt if wall_sparse is None else min(wall_sparse, dt)
            wall_dense = pt_big * 1000
            speedup = wall_dense / wall_sparse
            af_sp = sp.active_fraction(got)
            if speedup < 5.0:
                print(
                    f"SPARSE GATE FAILURE: 16384^2 R-pentomino sparse is "
                    f"only {speedup:.1f}x dense over 1000 turns "
                    f"({wall_sparse:.3f}s vs {wall_dense:.3f}s) — less "
                    "than the 5x contract", file=sys.stderr,
                )
                return 1
            print(
                f"sparse gate ok: 1000 turns in {wall_sparse:.3f}s vs "
                f"dense {wall_dense:.3f}s ({speedup:.1f}x)",
                file=sys.stderr,
            )
            extra["c10_16384_rpent_sparse"] = dict(
                det_sp,
                cell_updates_per_s=round(size * size / pt_sp),
                active_fraction=round(af_sp, 6),
                cell_updates_per_s_active=round(
                    size * size * af_sp / pt_sp
                ),
                wall_1000_turns_s=round(wall_sparse, 4),
                dense_wall_1000_turns_s=round(wall_dense, 4),
                speedup_vs_dense=round(speedup, 1),
            )
            del evolve_sp, sp_seed, got, sp
        # drop BOTH references (the closure's default-arg binding keeps the
        # device buffer alive otherwise) so the 512 MiB frees between sizes
        del evolve_big, state_big

    # ---- config 7: the RPC data plane — wire modes, loopback 4 workers ----
    rc = _bench_wire_modes(extra)
    if rc:
        return rc

    # ---- config 13: 2-D tile grid vs strips — the halo-byte gate ---------
    rc = _bench_tile_grid(extra)
    if rc:
        return rc

    # ---- config 11: dirty-tile delta syncs — sparse resident wire --------
    rc = _bench_sparse_wire(extra)
    if rc:
        return rc

    # ---- config 12: fused vs serial launch chains — the 128^2 floor ------
    rc = _bench_fused(extra)
    if rc:
        return rc

    # ---- config 8: multi-universe serving — 1k x 128^2 batched sessions --
    rc = _bench_sessions(extra)
    if rc:
        return rc

    # ---- config 9: open-loop serving — loadgen vs a loopback broker ------
    rc = _bench_loadgen(extra)
    if rc:
        return rc

    # roofline fields per kernel case (obs/perf.py): achieved FLOP/s and
    # bytes/s from the analytic stencil cost model over each case's own
    # per-turn fit, classified against this device's calibrated ceilings
    # — so every published number carries its bound class, bench_diff
    # gates achieved-throughput regressions per site, and the "128^2 is
    # latency-bound" claim is a field, not a prose note
    from gol_distributed_final_tpu.obs import perf as obs_perf

    ceilings = obs_perf.calibrate()
    for key, size in (
        ("c2_128_pallas_bitboard", 128),
        ("c3_512_pallas_bitboard", 512),
        ("c3_512_engine_driven", 512),
        ("c4_4096_tiled_bitboard", 4096),
        ("c5_16384_sparse_bigboard", 16384),
        ("c5_65536_sparse_bigboard", 65536),
        ("c6_512_mesh_tax", 512),
        ("c6_4096_mesh_tax", 4096),
        ("c6_512_mesh_tax_wide8", 512),
        ("c6_4096_mesh_tax_wide8", 4096),
    ):
        case = extra.get(key)
        if case and (case.get("per_turn_us") or 0) > 0:
            case.update(obs_perf.classify_case(
                size, size, case["per_turn_us"] * 1e-6, ceilings
            ))
            print(
                f"roofline {key}: {case['bound_class']} "
                f"({100 * case['flops_utilization']:.1f}% flop, "
                f"{100 * case['memory_utilization']:.1f}% mem of "
                f"{ceilings.device_kind} ceilings)",
                file=sys.stderr,
            )

    # the RunReport's compact breakdown (obs/report.stage_timings): every
    # nonzero histogram series as {count, sum_s, mean_s} + nonzero counters
    from gol_distributed_final_tpu.obs.report import stage_timings

    extra["stage_timings"] = stage_timings()

    print(
        json.dumps(
            {
                "metric": "cell-updates/sec (512x512 Conway, marginal over 2M turns, single chip)",
                "value": headline,
                "unit": "cell-updates/s",
                "vs_baseline": headline / BASELINE_CELL_UPDATES_PER_SEC,
                # environment stamp: bench_diff refuses cross-environment
                # comparisons (obs/regress.py)
                "provenance": provenance(),
                "extra": extra,
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
