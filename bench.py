"""Benchmark harness — run on real TPU hardware by the driver.

Measures the headline metric from BASELINE.json: cell-updates/sec
(turns x H x W / s) evolving the reference's 512x512 board for 1000 turns
(the coursework's suggested benchLength, content/ReporGuidanceCollated.md:57),
with a bit-exactness gate against the committed alive-count goldens
(check/alive/512x512.csv).

Prints exactly ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Baseline: the reference publishes no numbers (BASELINE.md). We use an
explicit, documented estimate for its 8-worker distributed deployment:
50 turns/s on 512x512 — generous for a path that gob-serialises the full
board to every worker every turn (broker/broker.go:135-224) — giving
50 * 512 * 512 = 13.1e6 cell-updates/s.
"""

import json
import sys
import time

BASELINE_CELL_UPDATES_PER_SEC = 50 * 512 * 512  # documented estimate, see above

BOARD = 512
TURNS = 1000
GOLDEN_ALIVE_AT_1000 = 6444  # check/alive/512x512.csv line 1001


def main() -> int:
    import numpy as np

    import jax
    import jax.numpy as jnp

    from gol_distributed_final_tpu.io.pgm import read_pgm
    from gol_distributed_final_tpu.models import CONWAY

    dev = jax.devices()[0]
    print(f"bench device: {dev}", file=sys.stderr)

    board = jnp.asarray(read_pgm(f"images/{BOARD}x{BOARD}.pgm"))

    # correctness gate: 1000 turns must hit the golden alive count exactly
    out = CONWAY.step_n(board, TURNS)
    alive = int(jnp.sum(out != 0, dtype=jnp.int32))
    if alive != GOLDEN_ALIVE_AT_1000:
        print(
            f"PARITY FAILURE: alive at turn {TURNS} = {alive}, "
            f"golden = {GOLDEN_ALIVE_AT_1000}",
            file=sys.stderr,
        )
        return 1

    # timed runs: single-dispatch fori_loop over all 1000 turns (compile
    # already cached by the parity run)
    reps = 3
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        CONWAY.step_n(board, TURNS).block_until_ready()
        best = min(best, time.perf_counter() - t0)

    value = TURNS * BOARD * BOARD / best
    print(
        json.dumps(
            {
                "metric": "cell-updates/sec (512x512, 1000 turns, single chip)",
                "value": value,
                "unit": "cell-updates/s",
                "vs_baseline": value / BASELINE_CELL_UPDATES_PER_SEC,
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
