"""Benchmark harness — run on real TPU hardware by the driver.

Measures the headline metric from BASELINE.json: cell-updates/sec
(turns x H x W / s) evolving the reference's 512x512 board, with
bit-exactness gates against the committed alive-count goldens
(check/alive/512x512.csv) at turn 1000 and turn 10000.

The timed path is the framework's fastest single-device data plane: the
pallas VMEM bitboard kernel (ops/pallas_stencil.pallas_bit_step_n_fn —
32 cells/int32 word, the whole evolution in one kernel launch). The
remote-TPU tunnel adds a fixed ~0.1 s dispatch+transfer overhead per
call, so throughput is computed from the MARGINAL cost between a 100k-turn
and a 1.1M-turn run (overhead cancels; both runs are verified to return
the period-2 steady state).

Prints exactly ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Baseline: the reference publishes no numbers (BASELINE.md). We use an
explicit, documented estimate for its 8-worker distributed deployment:
50 turns/s on 512x512 — generous for a path that gob-serialises the full
board to every worker every turn (broker/broker.go:135-224) — giving
50 * 512 * 512 = 13.1e6 cell-updates/s.
"""

import json
import sys
import time

BASELINE_CELL_UPDATES_PER_SEC = 50 * 512 * 512  # documented estimate, see above

BOARD = 512
GOLDEN = {1000: 6444, 10000: 5565}  # check/alive/512x512.csv
STEADY = {0: 5565, 1: 5567}  # period-2 steady state beyond turn 10000
N_LO, N_HI = 100_000, 1_100_000
REPS = 3


def main() -> int:
    import numpy as np

    import jax

    from gol_distributed_final_tpu.io.pgm import read_pgm
    from gol_distributed_final_tpu.ops import bitpack
    from gol_distributed_final_tpu.ops.pallas_stencil import _bit_compiled

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    print(f"bench device: {dev}", file=sys.stderr)

    board = read_pgm(f"images/{BOARD}x{BOARD}.pgm")
    word_axis = 0  # rows packed: [H/32, W], lanes stay W wide
    packed = jax.device_put(bitpack.pack(board, word_axis))

    def evolve(n):
        return np.asarray(_bit_compiled(n, word_axis, not on_tpu)(packed))

    # correctness gates: exact alive counts at the golden checkpoints
    for n, want in GOLDEN.items():
        alive = int(np.count_nonzero(bitpack.unpack(evolve(n), word_axis)))
        if alive != want:
            print(f"PARITY FAILURE at turn {n}: {alive} != {want}", file=sys.stderr)
            return 1
    print("parity gates passed (turns 1000, 10000)", file=sys.stderr)

    def best_time(n):
        evolve(n)  # warm/compile
        best = float("inf")
        for _ in range(REPS):
            t0 = time.perf_counter()
            out = evolve(n)  # np.asarray forces full device sync
            best = min(best, time.perf_counter() - t0)
        alive = int(np.count_nonzero(bitpack.unpack(out, word_axis)))
        if alive != STEADY[n % 2]:
            raise AssertionError(f"steady-state violation at {n}: {alive}")
        return best

    t_lo, t_hi = best_time(N_LO), best_time(N_HI)
    per_turn = (t_hi - t_lo) / (N_HI - N_LO)
    value = BOARD * BOARD / per_turn
    print(
        f"fixed overhead ~{t_lo - N_LO * per_turn:.3f}s, "
        f"{per_turn * 1e6:.3f} us/turn marginal",
        file=sys.stderr,
    )
    print(
        json.dumps(
            {
                "metric": "cell-updates/sec (512x512 Conway, marginal over 1M turns, single chip)",
                "value": value,
                "unit": "cell-updates/s",
                "vs_baseline": value / BASELINE_CELL_UPDATES_PER_SEC,
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
