"""Sharded halo-exchange parity tests on the virtual 8-device CPU mesh.

The contract: a board evolved under shard_map + ppermute halos is
bit-identical to the single-device stencil, for 1-D and 2-D meshes,
including cells whose neighbourhoods span shard boundaries and corners.
"""

import numpy as np
import pytest

import jax

from gol_distributed_final_tpu.models import CONWAY, HIGHLIFE
from gol_distributed_final_tpu.ops import step_n
from gol_distributed_final_tpu.parallel import (
    best_mesh_shape,
    board_sharding,
    make_engine_step,
    make_mesh,
    sharded_step_fn,
    sharded_step_n_fn,
)

from oracle import vector_step


def random_board(h, w, seed=0, density=0.3):
    rng = np.random.default_rng(seed)
    return np.where(rng.random((h, w)) < density, 255, 0).astype(np.uint8)


requires_8 = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices"
)

MESH_SHAPES = [(8, 1), (1, 8), (4, 2), (2, 4)]


@requires_8
@pytest.mark.parametrize("shape", MESH_SHAPES)
def test_sharded_step_matches_single_device(shape):
    mesh = make_mesh(shape)
    step = sharded_step_fn(mesh)
    board = random_board(64, 64, seed=11)
    got = board
    want = board
    for _ in range(3):
        got = step(got)
        # block per dispatch: on a 1-core host, queueing many async
        # multi-device programs can starve XLA's collective rendezvous
        got.block_until_ready()
        want = vector_step(np.asarray(want))
    np.testing.assert_array_equal(np.asarray(got), want)


@requires_8
@pytest.mark.parametrize("shape", MESH_SHAPES)
def test_glider_crosses_shard_boundaries(shape):
    """A glider translating across every internal boundary (and the torus
    edge) must behave identically to the dense single-device stencil."""
    mesh = make_mesh(shape)
    step = sharded_step_fn(mesh)
    board = np.zeros((32, 32), np.uint8)
    for x, y in [(1, 0), (2, 1), (0, 2), (1, 2), (2, 2)]:
        board[y, x] = 255
    got = board
    for _ in range(4 * 32):  # full wrap back to start
        got = step(got)
        got.block_until_ready()  # see rendezvous note above
    np.testing.assert_array_equal(np.asarray(got), board)


@requires_8
def test_sharded_step_n_single_dispatch():
    mesh = make_mesh((4, 2))
    stepn = sharded_step_n_fn(mesh)
    board = random_board(32, 64, seed=5)
    got = np.asarray(stepn(board, 23))
    want = np.asarray(step_n(jax.numpy.asarray(board), 23))
    np.testing.assert_array_equal(got, want)


@requires_8
def test_sharded_non_conway_rule():
    mesh = make_mesh((2, 4))
    step = sharded_step_fn(mesh, HIGHLIFE)
    board = random_board(16, 16, seed=8)
    got = np.asarray(step(board))
    want = vector_step(board, birth=(3, 6), survive=(2, 3))
    np.testing.assert_array_equal(got, want)


@requires_8
def test_output_keeps_sharding():
    mesh = make_mesh((4, 2))
    step = sharded_step_fn(mesh)
    out = step(random_board(32, 32, seed=2))
    assert out.sharding == board_sharding(mesh)


@requires_8
def test_engine_runs_sharded(tmp_path):
    """Full engine run with the mesh data plane: golden parity end-to-end."""
    import queue

    from gol_distributed_final_tpu import FinalTurnComplete, Params, run
    from gol_distributed_final_tpu.engine.engine import EngineConfig
    from gol_distributed_final_tpu.engine.controller import CLOSED

    from helpers import REPO_ROOT, read_alive_cells, assert_equal_board

    mesh = make_mesh((4, 2))
    cfg = EngineConfig(step_n_fn=make_engine_step(mesh))
    p = Params(turns=100, image_width=64, image_height=64)
    events = queue.Queue()
    run(
        p,
        events,
        engine_config=cfg,
        images_dir=REPO_ROOT / "images",
        out_dir=tmp_path / "out",
        tick_seconds=3600,
    )
    final = None
    while True:
        ev = events.get_nowait()
        if ev is CLOSED:
            break
        if isinstance(ev, FinalTurnComplete):
            final = ev
    expected = read_alive_cells(REPO_ROOT / "check" / "images" / "64x64x100.pgm")
    assert_equal_board(final.alive, expected, 64, 64)


def test_best_mesh_shape():
    assert best_mesh_shape(8, 512, 512) in {(4, 2), (2, 4)}
    assert best_mesh_shape(4, 512, 512) == (2, 2)
    assert best_mesh_shape(8, 8, 8) in {(4, 2), (2, 4)}  # square-ish wins
    with pytest.raises(ValueError, match="factorisation"):
        best_mesh_shape(8, 9, 9)


@requires_8
def test_indivisible_board_rejected():
    mesh = make_mesh((8, 1))
    step = sharded_step_fn(mesh)
    with pytest.raises(ValueError):
        step(random_board(17, 8, seed=1))  # 17 rows not divisible by 8
