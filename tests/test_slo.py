"""Serving-SLO layer tests (obs/timeline.py, obs/slo.py, obs/doctor.py):
timeline ring wraparound + counter-reset detection + quantile math vs a
numpy oracle, multi-window burn-rate rule firing on synthetic series,
skew-safe Status round-trips of incremental timeline windows, the watch
ALERTS panel pure render, doctor correlation on a canned multi-process
fixture, and one live broker+worker poll with ``-timeline`` on.
"""

import json

import numpy as np
import pytest

from gol_distributed_final_tpu.obs import metrics as obs_metrics
from gol_distributed_final_tpu.obs import slo
from gol_distributed_final_tpu.obs import timeline as obs_timeline
from gol_distributed_final_tpu.obs.metrics import DEFAULT_BUCKETS, Registry
from gol_distributed_final_tpu.obs.timeline import (
    TimelineSampler,
    counter_delta,
    quantile_from_buckets,
)

from helpers import REPO_ROOT
from test_rpc import _spawn, _wait_listening


@pytest.fixture
def live_metrics():
    """Enable the process-global registry for one test, zeroed before and
    disabled+zeroed after (the test_obs.py posture)."""
    reg = obs_metrics.registry()
    reg.reset()
    obs_metrics.enable()
    yield reg
    obs_metrics.enable(False)
    reg.reset()


def _ticking_sampler(capacity=64):
    """A sampler over a private registry with deterministic clocks:
    returns (registry, sampler, tick) where tick() advances one second."""
    reg = Registry()
    tl = TimelineSampler(registry=reg, period=1.0, capacity=capacity)
    state = {"t": 1000.0, "w": 5000.0}

    def tick(n=1):
        for _ in range(n):
            state["t"] += 1.0
            state["w"] += 1.0
            tl.sample_once(now=state["t"], wall=state["w"])

    return reg, tl, tick


# -- timeline rings ----------------------------------------------------------


def test_ring_wraparound_bounds_memory():
    """The per-series ring holds exactly ``capacity`` samples no matter
    how long the process runs; seqs keep increasing across the wrap."""
    reg, tl, tick = _ticking_sampler(capacity=8)
    c = reg.counter("x_total")
    for _ in range(30):
        c.inc()
        tick()
    ring = tl._rings("x_total")[0]
    assert len(ring.samples) == 8
    seqs = [s[0] for s in ring.samples]
    assert seqs == sorted(seqs) and seqs[-1] == 30
    # the window only reaches what the ring holds — and still answers
    assert tl.increase("x_total", 1000.0) == 7


def test_counter_reset_detection_no_negative_rates():
    """A registry reset (process restart's in-process twin) folds the
    previous total into a base: increase/rate stay >= 0, never the
    negative garbage a raw subtraction would produce."""
    reg, tl, tick = _ticking_sampler()
    c = reg.counter("x_total")
    c.inc(10)
    tick()
    c.inc(10)
    tick()
    reg.reset()  # counter back to 0
    c.inc(3)
    tick()
    assert tl.reset_count("x_total") == 1
    inc = tl.increase("x_total", 10.0)
    assert inc is not None and inc >= 0
    assert inc == 13  # 10 after the first sample + 3 after the reset
    rate = tl.rate("x_total", 10.0)
    assert rate is not None and rate >= 0


def test_histogram_reset_detection():
    """Histogram count/sum/buckets fold across resets element-wise, so
    windowed quantiles never see negative bucket deltas."""
    reg, tl, tick = _ticking_sampler()
    h = reg.histogram("lat_seconds")
    h.observe(0.01)
    tick()
    reg.reset()
    for _ in range(5):
        h.observe(0.04)
    tick()
    assert tl.reset_count("lat_seconds") == 1
    # the pre-reset observation was already committed in the first
    # sample; the window increase is the 5 post-reset observations
    assert tl.increase("lat_seconds", 10.0) == 5
    q = tl.quantile("lat_seconds", 0.5, 10.0)
    assert q is not None and 0.025 < q <= 0.05


def test_counter_delta_client_side():
    """The shared reset logic obs/watch.py rides: monotone polls
    subtract, a backwards poll (restart) yields the new total."""
    assert counter_delta(100, 150) == 50
    assert counter_delta(100, 100) == 0
    assert counter_delta(100, 7) == 7  # restarted server, never -93


def test_quantile_math_vs_numpy_oracle():
    """Windowed bucket-interpolated quantiles agree with numpy's within
    one bucket's resolution (the best any fixed-edge histogram can do)."""
    rng = np.random.default_rng(42)
    values = rng.lognormal(mean=-6.0, sigma=1.2, size=4000)
    reg, tl, tick = _ticking_sampler()
    h = reg.histogram("lat_seconds")
    tick()  # a pre-observation sample so the window has a baseline
    for v in values:
        h.observe(float(v))
    tick()
    edges = (0.0,) + DEFAULT_BUCKETS
    for q in (0.5, 0.9, 0.99):
        est = tl.quantile("lat_seconds", q, 10.0)
        truth = float(np.quantile(values, q))
        # the estimate must land in the same bucket as the oracle
        i = int(np.searchsorted(DEFAULT_BUCKETS, truth))
        lo = edges[i]
        hi = DEFAULT_BUCKETS[i] if i < len(DEFAULT_BUCKETS) else edges[-1]
        assert lo <= est <= hi, (q, est, truth, lo, hi)


def test_quantile_edge_cases():
    assert quantile_from_buckets((0.1, 1.0), [0, 0, 0], 0.99) is None
    # everything in the overflow slot clamps to the last finite edge
    assert quantile_from_buckets((0.1, 1.0), [0, 0, 5], 0.5) == 1.0
    # single bucket interpolates within [lower edge, its edge]
    est = quantile_from_buckets((0.1, 1.0), [4, 0, 0], 0.5)
    assert 0.0 < est <= 0.1


def test_incremental_window_and_summary():
    """window(since=seq) ships only newer samples; the summary carries
    server-computed rates and p99s; the whole payload is plain JSON."""
    reg, tl, tick = _ticking_sampler()
    c = reg.counter("x_total")
    h = reg.histogram("lat_seconds")
    for _ in range(5):
        c.inc(7)
        h.observe(0.02)
        tick()
    w = tl.window(since=0)
    assert w["seq"] == 5 and len(w["series"]) == 2
    json.dumps(w)  # JSON-able end to end
    counter_series = next(
        s for s in w["series"] if s["name"] == "x_total"
    )
    assert len(counter_series["samples"]) == 5
    summary = w["summary"]
    assert summary["x_total"]["rate_per_s"] == pytest.approx(7.0)
    assert summary["lat_seconds"]["p99_s"] is not None
    assert summary["lat_seconds"]["rate_per_s"] == pytest.approx(1.0)
    # incremental: nothing new since seq -> empty series, same summary
    w2 = tl.window(since=w["seq"])
    assert w2["series"] == []
    c.inc()
    tick()
    # one new tick: EVERY series gains exactly one sample past the seq
    w3 = tl.window(since=w["seq"])
    assert [len(s["samples"]) for s in w3["series"]] == [1, 1]


def test_chrome_counter_samples():
    """Counters export as per-second rate tracks, gauges as values —
    and they fold into the Chrome trace as ph:"C" events on a dedicated
    track."""
    from gol_distributed_final_tpu.obs.tracing import to_chrome_trace

    reg, tl, tick = _ticking_sampler()
    c = reg.counter("x_total")
    g = reg.gauge("depth")
    for i in range(3):
        c.inc(5)
        g.set(i + 1)
        tick()
    samples = tl.chrome_counter_samples()
    names = {s["name"] for s in samples}
    assert "x_total /s" in names and "depth" in names
    rates = [s["value"] for s in samples if s["name"] == "x_total /s"]
    assert all(r == pytest.approx(5.0) for r in rates)
    trace = to_chrome_trace([], samples)
    cs = [e for e in trace["traceEvents"] if e["ph"] == "C"]
    assert len(cs) == len(samples)
    meta = [e for e in trace["traceEvents"] if e["ph"] == "M"]
    assert any(e["args"]["name"] == "metrics timeline" for e in meta)


# -- SLO rules ---------------------------------------------------------------


def test_burn_rate_rule_needs_both_windows():
    """The SRE two-window recipe: a fresh error burst trips the fast
    window immediately but the rule only fires once the SLOW window
    burns too; recovery clears it."""
    reg, tl, tick = _ticking_sampler()
    reqs = reg.counter("reqs_total")
    errs = reg.counter("errs_total")
    rule = slo.BurnRateRule(
        "r", "page", "errs_total", "reqs_total",
        objective=0.99, factor=10.0, fast_s=3.0, slow_s=12.0,
    )
    # 12 clean seconds: the slow window is full of 0-ratio history
    for _ in range(12):
        reqs.inc(10)
        tick()
    assert rule.evaluate(tl)[0] is False
    # errors start: the fast window burns at once, the slow one lags
    fired_at = None
    for i in range(12):
        reqs.inc(10)
        errs.inc(5)
        tick()
        firing, value, detail = rule.evaluate(tl)
        if firing and fired_at is None:
            fired_at = i
    assert fired_at is not None and fired_at >= 1, (
        "must not fire on the first bad tick (slow window still clean)"
    )
    # recovery: clean traffic ages the errors out of both windows
    for _ in range(14):
        reqs.inc(10)
        tick()
    assert rule.evaluate(tl)[0] is False


def test_increase_rule_fires_and_ages_out():
    reg, tl, tick = _ticking_sampler()
    lost = reg.counter("gol_worker_lost_total")
    rule = slo.IncreaseRule("worker-lost", "page",
                            "gol_worker_lost_total", window_s=5.0)
    tick(2)
    assert rule.evaluate(tl)[0] is False
    lost.inc()
    tick()  # the loss lands on the very next tick — within one window
    assert rule.evaluate(tl)[0] is True
    tick(8)  # ages out
    assert rule.evaluate(tl)[0] is False


def test_gauge_ratio_and_growth_rules():
    reg, tl, tick = _ticking_sampler()
    use = reg.gauge("hbm_use", labelnames=("device",))
    cap = reg.gauge("hbm_cap", labelnames=("device",))
    dl = reg.gauge("deadline_s")
    ratio = slo.GaugeRatioRule("hbm", "page", "hbm_use", "hbm_cap",
                               max_ratio=0.9)
    growth = slo.GrowthRule("dl", "warn", "deadline_s", factor=3.0,
                            window_s=10.0, floor=1.0)
    use.labels("0").set(50)
    cap.labels("0").set(100)
    dl.set(2.0)
    tick(2)
    assert ratio.evaluate(tl)[0] is False
    assert growth.evaluate(tl)[0] is False
    use.labels("0").set(95)
    dl.set(7.0)  # 3.5x the window-ago value
    tick()
    firing, value, _ = ratio.evaluate(tl)
    assert firing and value == pytest.approx(0.95)
    firing, g, _ = growth.evaluate(tl)
    assert firing and g == pytest.approx(3.5)


def test_rulebook_transitions_meter_and_flight(live_metrics):
    """A firing transition increments gol_slo_alerts_total{rule,severity}
    exactly once per fire, lands an slo.fire flight event, and the
    snapshot is JSON-able with firing rules first."""
    from gol_distributed_final_tpu.obs import flight as obs_flight

    reg, tl, tick = _ticking_sampler()
    lost = reg.counter("gol_worker_lost_total")
    rb = slo.RuleBook([
        slo.IncreaseRule("worker-lost", "page",
                         "gol_worker_lost_total", window_s=4.0),
        slo.IncreaseRule("never", "warn", "absent_total", window_s=4.0),
    ])
    obs_flight.recorder().reset()
    obs_flight.enable()
    try:
        tick(2)
        rb.evaluate(tl, now=1.0, wall=2.0)
        lost.inc()
        tick()
        transitions = rb.evaluate(tl, now=2.0, wall=3.0)
        assert transitions == [{"rule": "worker-lost", "event": "fire"}]
        # still firing: no second increment
        tick()
        rb.evaluate(tl, now=3.0, wall=4.0)
        snap = live_metrics.snapshot()
        fam = next(
            f for f in snap["families"]
            if f["name"] == "gol_slo_alerts_total"
        )
        # other suites may have registered rule children on the shared
        # family (reset() keeps registrations); only live series count
        live = [s for s in fam["series"] if s["value"]]
        assert live == [
            {"labels": ["worker-lost", "page"], "value": 1.0}
        ]
        events = obs_flight.recorder().snapshot()
        assert any(
            e["kind"] == "slo.fire" and e["name"] == "worker-lost"
            for e in events
        )
        states = rb.snapshot()
        json.dumps(states)
        assert states[0]["rule"] == "worker-lost"
        assert states[0]["state"] == "firing"
        assert [a["rule"] for a in rb.active()] == ["worker-lost"]
        # ages out -> clears, flight records the clear
        tick(8)
        transitions = rb.evaluate(tl, now=4.0, wall=5.0)
        assert transitions == [{"rule": "worker-lost", "event": "clear"}]
        assert rb.active() == []
    finally:
        obs_flight.enable(False)
        obs_flight.recorder().reset()


def test_blocking_verbs_excluded_from_dispatch_histogram(live_metrics):
    """Run/SessionRun park for the whole game by contract: their handler
    wall must never feed the dispatch-latency SLO histogram (a healthy
    hour-long run is not a latency violation), while quick verbs must."""
    from gol_distributed_final_tpu.rpc.broker import serve
    from gol_distributed_final_tpu.rpc.client import RpcClient
    from gol_distributed_final_tpu.rpc.protocol import Methods, Request

    server, _service = serve(port=0)
    client = RpcClient(f"127.0.0.1:{server.port}")
    try:
        board = np.zeros((8, 8), np.uint8)
        client.call(
            Methods.BROKER_RUN,
            Request(world=board, turns=2, image_width=8, image_height=8,
                    threads=1),
            timeout=60.0,
        )
        client.call(Methods.STATUS, Request())
        snap = live_metrics.snapshot()
        fam = next(
            f for f in snap["families"]
            if f["name"] == "gol_rpc_dispatch_seconds"
        )
        verbs = {s["labels"][0] for s in fam["series"]}
        assert Methods.STATUS in verbs
        assert Methods.BROKER_RUN not in verbs
        # the blocking verb stays covered by the full-dispatch histogram
        fam = next(
            f for f in snap["families"]
            if f["name"] == "gol_rpc_server_request_seconds"
        )
        assert Methods.BROKER_RUN in {s["labels"][0] for s in fam["series"]}
    finally:
        client.close()
        server.stop()


def test_enable_capacity_covers_rule_horizon():
    """enable() must size the rings to span the slow SLO windows at ANY
    cadence — a 0.2 s timeline with the default 360-sample ring would
    silently shrink the 120 s slow window to 72 s."""
    s = obs_timeline.enable(period=0.2, start_thread=False)
    try:
        assert s.capacity * 0.2 >= obs_timeline.RULE_HORIZON_S
    finally:
        obs_timeline.disable()
    s = obs_timeline.enable(period=1.0, start_thread=False)
    try:
        assert s.capacity == obs_timeline.DEFAULT_CAPACITY
    finally:
        obs_timeline.disable()
    obs_metrics.enable(False)  # enable() implied it; leave tests clean
    obs_metrics.registry().reset()


def test_default_rules_match_contract():
    rules = slo.default_rules()
    assert tuple(r.name for r in rules) == slo.DEFAULT_RULE_NAMES
    with pytest.raises(ValueError):
        slo.RuleBook([slo.IncreaseRule("a", "page", "x_total")] * 2)
    with pytest.raises(ValueError):
        slo.IncreaseRule("a", "sev-nope", "x_total")


# -- Status round-trip + skew -----------------------------------------------


def test_status_payload_timeline_roundtrip(live_metrics):
    """status_payload ships the incremental window + alert states while
    the global sampler is on, nothing when off — and the payload stays
    plain JSON (the restricted-unpickler contract)."""
    from gol_distributed_final_tpu.obs.report import status_payload

    assert "timeline" not in status_payload(role="t")
    tl = obs_timeline.enable(period=60.0, start_thread=False)
    try:
        obs_metrics.registry().counter("gol_engine_turns_total").inc(5)
        tl.sample_once()
        tl.sample_once()
        payload = status_payload(role="t", timeline_since=0)
        assert payload["timeline"]["seq"] == 2
        assert payload["timeline"]["series"]
        assert isinstance(payload["alerts"], list)
        json.dumps(payload["timeline"])
        json.dumps(payload["alerts"])
        # incremental: a poller that echoes seq gets only newer samples
        again = status_payload(role="t", timeline_since=2)
        assert again["timeline"]["series"] == []
    finally:
        obs_timeline.disable()
    assert "timeline" not in status_payload(role="t")


def test_old_client_status_request_gets_full_window(live_metrics):
    """A version-skewed client whose Request pickle predates
    ``timeline_since`` must get the full ring (the getattr default),
    never an AttributeError reply — and a hostile non-int value must
    degrade the same way."""
    from gol_distributed_final_tpu.rpc.broker import serve
    from gol_distributed_final_tpu.rpc.client import RpcClient
    from gol_distributed_final_tpu.rpc.protocol import Methods, Request

    tl = obs_timeline.enable(period=60.0, start_thread=False)
    server, _service = serve(port=0)
    client = RpcClient(f"127.0.0.1:{server.port}")
    try:
        obs_metrics.registry().counter("gol_engine_turns_total").inc()
        tl.sample_once()
        old = Request()
        del old.__dict__["timeline_since"]
        res = client.call(Methods.STATUS, old)
        assert res.status["timeline"]["seq"] == 1
        assert res.status["timeline"]["series"]
        bad = Request()
        bad.timeline_since = "not-a-seq"
        res = client.call(Methods.STATUS, bad)
        assert res.status["timeline"]["seq"] == 1  # treated as 0, not a crash
    finally:
        client.close()
        server.stop()
        obs_timeline.disable()


# -- watch ALERTS panel ------------------------------------------------------


def test_watch_alerts_panel_pure_render():
    from gol_distributed_final_tpu.obs.watch import render_status

    payload = {
        "role": "broker", "pid": 1, "metrics_enabled": True,
        "metrics": {"families": []},
        "alerts": [
            {"rule": "worker-lost", "severity": "page", "state": "firing",
             "since_unix": 1.0, "value": 1,
             "detail": "gol_worker_lost_total +1 over 60s (> 0)"},
            {"rule": "hbm-headroom", "severity": "page", "state": "ok",
             "since_unix": None, "value": None, "detail": ""},
        ],
    }
    out = render_status("broker :1", payload)
    assert "ALERTS — 1 FIRING" in out
    assert "PAGE worker-lost" in out.replace("** ", "")
    assert "gol_worker_lost_total +1" in out
    # all-ok rulebook renders the quiet line; no alerts field renders none
    payload["alerts"] = [dict(payload["alerts"][1])]
    out = render_status("broker :1", payload)
    assert "none firing" in out
    del payload["alerts"]
    assert "ALERTS" not in render_status("broker :1", payload)


def test_watch_timeline_panel_and_reset_safe_rate():
    """The TIMELINE panel renders server-computed rates; the client-side
    turns rate survives a counter reset (the satellite fix)."""
    from gol_distributed_final_tpu.obs.watch import Watcher, render_status

    payload = {
        "role": "broker", "pid": 1, "metrics_enabled": True,
        "metrics": {"families": []},
        "timeline": {
            "seq": 9, "period_s": 1.0, "summary_window_s": 60,
            "series": [],
            "summary": {
                "gol_engine_turns_total": {"rate_per_s": 1234.5,
                                           "increase": 100},
                "gol_session_turn_seconds": {
                    "rate_per_s": 10.0, "count": 10, "mean_s": 0.01,
                    "p50_s": 0.01, "p99_s": 0.02,
                },
            },
        },
    }
    out = render_status("broker :1", payload)
    assert "TIMELINE (server-side" in out
    assert "1,234.5/s" in out and "p99" in out

    watcher = Watcher(":1", [], timeout=1.0)

    def poll(turns):
        return watcher._turns_rate(":1", {
            "metrics": {"families": [{
                "name": "gol_engine_turns_total", "type": "counter",
                "labelnames": [],
                "series": [{"labels": [], "value": turns}],
            }]},
        })

    assert poll(100) is None  # first poll: no rate yet
    rate = poll(150)
    assert rate is not None and rate >= 0
    rate = poll(30)  # server restarted: 30 < 150
    assert rate is not None and rate >= 0  # never negative


# -- doctor ------------------------------------------------------------------


def _canned_statuses():
    """A multi-process fixture: a broker with a lost, thrice-flapped
    worker + firing alert + integrity failure, one healthy worker, one
    unreachable worker."""
    lost_events = [
        {"kind": "worker.lost", "name": "127.0.0.1:8041",
         "t_unix": 10.0, "t_mono": 1.0, "pid": 1, "tid": 1,
         "args": {"reason": "scatter failed"}, "seq": i}
        for i in range(3)
    ]
    broker = {
        "role": "broker", "pid": 11, "metrics_enabled": True,
        "workers": [
            {"address": "127.0.0.1:8040", "state": "connected"},
            {"address": "127.0.0.1:8041", "state": "lost",
             "retry_in_s": 12.5},
        ],
        "flight": lost_events + [
            {"kind": "integrity.fail", "name": "127.0.0.1:8041",
             "t_unix": 11.0, "t_mono": 2.0, "pid": 11, "tid": 1,
             "args": {"check": "attest"}, "seq": 9},
        ],
        "alerts": [
            {"rule": "worker-lost", "severity": "page", "state": "firing",
             "since_unix": 5.0, "value": 3.0,
             "detail": "gol_worker_lost_total +3 over 60s (> 0)"},
            {"rule": "hbm-headroom", "severity": "page", "state": "ok",
             "since_unix": None, "value": None, "detail": ""},
        ],
        "metrics": {"families": [
            {"name": "gol_worker_lost_total", "type": "counter",
             "labelnames": [],
             "series": [{"labels": [], "value": 3.0}]},
            {"name": "gol_worker_readmitted_total", "type": "counter",
             "labelnames": [],
             "series": [{"labels": [], "value": 2.0}]},
            {"name": "gol_strip_resync_total", "type": "counter",
             "labelnames": [],
             "series": [{"labels": [], "value": 7.0}]},
            {"name": "gol_integrity_failures_total", "type": "counter",
             "labelnames": ["kind"],
             "series": [{"labels": ["attest"], "value": 1.0}]},
            {"name": "gol_engine_turns_total", "type": "counter",
             "labelnames": [],
             "series": [{"labels": [], "value": 500.0}]},
            {"name": "gol_wire_bytes_total", "type": "counter",
             "labelnames": ["verb", "direction"],
             "series": [{
                 "labels": ["GameOfLifeOperations.StripStep", "sent"],
                 "value": 6_000_000.0,
             }]},
        ]},
    }
    healthy_worker = {
        "role": "worker", "pid": 12, "metrics_enabled": True,
        "metrics": {"families": []},
    }
    return {
        "broker 127.0.0.1:9000": broker,
        "worker 127.0.0.1:8040": healthy_worker,
        "worker 127.0.0.1:8041": {"error": "poll failed: refused"},
    }


def test_doctor_correlation_on_canned_fixture(tmp_path):
    from gol_distributed_final_tpu.obs import doctor

    statuses = _canned_statuses()
    findings = doctor.diagnose(statuses)
    assert findings and findings[0]["rank"] == 1
    # the top-ranked finding names the flapping worker as the suspect
    top = findings[0]
    assert top["severity"] == "page"
    assert "127.0.0.1:8041" in top["suspects"]
    assert "flapping" in top["title"] or "quarantined" in top["title"]
    titles = " | ".join(f["title"] for f in findings)
    assert "integrity" in titles  # the caught corruption is a finding
    assert "worker-lost" in titles  # the firing SLO rule is a finding
    # evidence correlates the machinery: flight count + resync + probe
    assert any("3 loss event" in e for e in top["evidence"])
    assert any("resync" in e for e in top["evidence"])
    # pure render + artifact
    text = doctor.render(findings, statuses)
    assert "127.0.0.1:8041" in text and "#1 [PAGE]" in text
    assert "UNREACHABLE" in text
    path = doctor.write_report(findings, statuses, tmp_path)
    report = json.loads(path.read_text())
    assert report["schema"] == "gol-doctor/1"
    assert report["findings"][0]["title"] == top["title"]
    assert report["targets"]["broker 127.0.0.1:9000"]["firing_alerts"] == [
        "worker-lost"
    ]


def test_doctor_healthy_cluster_still_renders(tmp_path):
    """A clean bill of health is itself a finding: the diagnosis is
    never empty (the scripts/check --doctor renderability contract)."""
    from gol_distributed_final_tpu.obs import doctor

    statuses = {
        "broker 127.0.0.1:9000": {
            "role": "broker", "pid": 1, "metrics_enabled": True,
            "metrics": {"families": []},
        },
    }
    findings = doctor.diagnose(statuses)
    assert len(findings) == 1 and findings[0]["severity"] == "info"
    assert "no anomalies" in findings[0]["title"]
    assert doctor.render(findings, statuses).strip()


def test_doctor_stall_heuristic():
    from gol_distributed_final_tpu.obs import doctor

    statuses = {"broker b": {
        "role": "broker", "pid": 1, "metrics_enabled": True,
        "metrics": {"families": [
            {"name": "gol_engine_turns_total", "type": "counter",
             "labelnames": [],
             "series": [{"labels": [], "value": 900.0}]},
        ]},
        "timeline": {"summary": {
            "gol_engine_turns_total": {"rate_per_s": 0.0, "increase": 0},
        }},
        "flight": [{"kind": "span.open", "name": "broker.turn",
                    "t_unix": 1.0, "t_mono": 1.0, "pid": 1, "tid": 1,
                    "args": {}, "seq": 1}],
    }}
    findings = doctor.diagnose(statuses)
    stall = next(f for f in findings if "stalled" in f["title"])
    assert any("broker.turn" in e for e in stall["evidence"])
    # the REAL wedged shape: the summary DROPS zero-increase counters,
    # so a fully stalled engine's entry is ABSENT — that must still
    # read as rate 0 and fire (a missing timeline entirely must not)
    statuses["broker b"]["timeline"] = {"summary": {}}
    findings = doctor.diagnose(statuses)
    assert any("stalled" in f["title"] for f in findings)
    del statuses["broker b"]["timeline"]
    findings = doctor.diagnose(statuses)
    assert not any("stalled" in f["title"] for f in findings)


# -- run report --------------------------------------------------------------


def test_run_report_embeds_timeline_and_alerts(tmp_path, live_metrics):
    from gol_distributed_final_tpu.obs.report import write_run_report
    from gol_distributed_final_tpu.params import Params

    tl = obs_timeline.enable(period=60.0, start_thread=False)
    try:
        tl.sample_once(now=1.0, wall=1.0)
        live_metrics.counter("gol_engine_turns_total").inc(50)
        live_metrics.counter("gol_worker_lost_total").inc()
        tl.sample_once(now=2.0, wall=2.0)
        params = Params(turns=3, threads=1, image_width=8, image_height=8)
        path = write_run_report(params, tmp_path)
        report = json.loads(path.read_text())
        assert report["timeline"]["gol_engine_turns_total"]["increase"] == 50
        assert report["alerts_fired"] == ["worker-lost"]
        states = {a["rule"]: a["state"] for a in report["alerts"]}
        assert states["worker-lost"] == "firing"
    finally:
        obs_timeline.disable()


# -- the lint ----------------------------------------------------------------


def test_slo_lints_pass_on_real_readme():
    from gol_distributed_final_tpu.obs import lint

    assert lint.undocumented_slo_metrics() == []
    assert lint.undocumented_slo_rules() == []
    assert lint.missing_readme_sections() == []


def test_slo_lint_catches_drift(tmp_path):
    bad = tmp_path / "README.md"
    bad.write_text(
        "## SLOs & alerting\n\ngol_slo_alerts_total only\n\n## Doctor\nx\n"
    )
    from gol_distributed_final_tpu.obs import lint

    missing = lint.undocumented_slo_metrics(bad)
    assert "gol_session_turn_seconds" in missing
    assert "gol_slo_alerts_total" not in missing
    rules = lint.undocumented_slo_rules(bad)
    assert "worker-lost" in rules


# -- live: one broker+worker poll with -timeline on --------------------------


def test_live_timeline_status_poll():
    """A -timeline broker + worker cluster: one Status poll returns
    server-computed rates and p99s for the serving histograms, the alert
    states ride along, a second poll's echoed seq gets an INCREMENTAL
    window, and the doctor diagnoses the live payloads."""
    import time as _time

    from gol_distributed_final_tpu.obs import doctor
    from gol_distributed_final_tpu.obs.status import fetch_status
    from gol_distributed_final_tpu.rpc.client import RemoteBroker

    worker = _spawn(
        "gol_distributed_final_tpu.rpc.worker",
        "-port", "0", "-timeline", "0.2",
    )
    worker_port = _wait_listening(worker)
    broker = _spawn(
        "gol_distributed_final_tpu.rpc.broker",
        "-port", "0", "-backend", "workers",
        "-workers", f"127.0.0.1:{worker_port}",
        "-timeline", "0.2",
    )
    broker_port = _wait_listening(broker)
    addr = f"127.0.0.1:{broker_port}"
    try:
        # let the samplers tick before traffic lands: the serving
        # histograms' series are born mid-window and diff against the
        # implicit zero seed (a just-started server's first period is
        # the one blind window, by design)
        _time.sleep(0.5)
        rb = RemoteBroker(addr)
        from gol_distributed_final_tpu.params import Params

        rng = np.random.default_rng(3)
        board = np.where(
            rng.random((32, 32)) < 0.3, 255, 0
        ).astype(np.uint8)
        rb.run(Params(turns=40, threads=2, image_width=32,
                      image_height=32), board)
        rb.close()
        _time.sleep(0.6)  # a few sampler ticks past the run
        payload = fetch_status(addr, timeout=10.0)
        tl = payload["timeline"]
        assert tl["series"], "timeline window must carry samples"
        assert isinstance(payload["alerts"], list)
        # server-computed rates + p99s for the serving histograms, no
        # client math (the run's handler latency rides the request
        # histogram; the blocking Run verb is EXCLUDED from the
        # dispatch-latency SLO feed by contract)
        run_req = tl["summary"].get(
            "gol_rpc_server_request_seconds{method=Operations.Run}"
        )
        assert run_req and run_req["p99_s"] is not None, tl["summary"].keys()
        assert (
            "gol_rpc_dispatch_seconds{method=Operations.Run}"
            not in tl["summary"]
        )
        # incremental second poll: echoing seq ships only newer ticks,
        # and the first poll's own (quick-verb) Status dispatch has
        # landed in the SLO histogram by now
        seq = tl["seq"]
        _time.sleep(0.5)
        payload2 = fetch_status(addr, timeout=10.0, timeline_since=seq)
        tl2 = payload2["timeline"]
        assert tl2["seq"] > seq
        dispatch = [
            k for k in tl2["summary"]
            if k.startswith("gol_rpc_dispatch_seconds")
        ]
        assert dispatch, tl2["summary"].keys()
        assert all(
            tl2["summary"][k]["p99_s"] is not None for k in dispatch
        )
        assert all(
            s2[0] > seq
            for series in tl2["series"]
            for s2 in series["samples"]
        )
        # the worker's twin verb serves its own timeline
        wpayload = fetch_status(
            f"127.0.0.1:{worker_port}", worker=True, timeout=10.0
        )
        assert wpayload["timeline"]["series"]
        # and the doctor can diagnose the live pair
        statuses = doctor.collect(
            addr, [f"127.0.0.1:{worker_port}"], timeout=10.0
        )
        findings = doctor.diagnose(statuses)
        assert findings and doctor.render(findings, statuses).strip()
    finally:
        for p in (broker, worker):
            if p.poll() is None:
                p.kill()
            p.wait()
