"""Native C++ PGM codec: build, bind, and agree byte-for-byte with the
pure-Python codec."""

import numpy as np
import pytest

from gol_distributed_final_tpu.io import native
from gol_distributed_final_tpu.io.pgm import PgmReader, read_pgm, write_pgm


pytestmark = pytest.mark.skipif(
    not native.available(), reason="native codec unavailable (no g++?)"
)


def board(h, w, seed=0):
    rng = np.random.default_rng(seed)
    return np.where(rng.random((h, w)) < 0.5, 255, 0).astype(np.uint8)


def test_native_header_and_rows_match_python(tmp_path):
    b = board(64, 48, seed=1)
    p = tmp_path / "b.pgm"
    write_pgm(p, b)
    hdr = native.read_header(p)
    assert hdr is not None
    w, h, maxval, offset = hdr
    assert (w, h, maxval) == (48, 64, 255)
    rows = native.read_rows(p, offset, w, 10, 30)
    np.testing.assert_array_equal(rows, b[10:30])


def test_native_write_matches_python_bytes(tmp_path):
    b = board(32, 32, seed=2)
    p_native = tmp_path / "n.pgm"
    p_python = tmp_path / "p.pgm"
    assert native.write_board(p_native, b)
    write_pgm(p_python, b)
    assert p_native.read_bytes() == p_python.read_bytes()


def test_large_board_roundtrip_uses_native(tmp_path):
    # above _NATIVE_THRESHOLD_BYTES: write + streamed read hit the C++ path
    b = board(1024, 1024, seed=3)
    p = tmp_path / "big.pgm"
    write_pgm(p, b)
    np.testing.assert_array_equal(read_pgm(p), b)
    with PgmReader(p) as r:
        np.testing.assert_array_equal(r.read_rows(100, 900), b[100:900])


def test_native_header_rejects_garbage(tmp_path):
    p = tmp_path / "g.pgm"
    p.write_bytes(b"not a pgm at all")
    assert native.read_header(p) is None
