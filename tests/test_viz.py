"""Visualiser tests: window semantics (sdl/window.go) and the event loop's
flip/render protocol (sdl/loop.go), including the TestSdl-style shadow
reconstruction through a full session."""

import queue
import threading

import numpy as np
import pytest

from gol_distributed_final_tpu import Params, run
from gol_distributed_final_tpu.viz import Window
from gol_distributed_final_tpu.viz.loop import run as viz_run

from helpers import REPO_ROOT, read_alive_counts


def test_window_flip_set_count_clear():
    w = Window(8, 4)
    w.flip_pixel(0, 0)
    w.flip_pixel(7, 3)
    assert w.count_pixels() == 2
    w.flip_pixel(0, 0)  # flip back off
    assert w.count_pixels() == 1
    w.set_pixel(2, 2)
    assert w.count_pixels() == 2
    w.clear_pixels()
    assert w.count_pixels() == 0


def test_window_bounds_panic():
    w = Window(8, 4)
    with pytest.raises(IndexError):
        w.flip_pixel(8, 0)
    with pytest.raises(IndexError):
        w.flip_pixel(0, -1)


def test_viz_loop_reconstructs_board(tmp_path):
    """TestSdl's contract through the real stack: a session with flips on,
    consumed by the visualiser loop; the window's pixel count at every
    TurnComplete must match the golden alive CSV (sdl_test.go:56-74)."""
    counts = read_alive_counts(REPO_ROOT / "check" / "alive" / "64x64.csv")
    p = Params(turns=20, image_width=64, image_height=64)
    events = queue.Queue()
    seen = []

    window = Window(64, 64)
    viz_thread = threading.Thread(
        target=viz_run,
        args=(p, events),
        kwargs={
            "window": window,
            "on_turn": lambda w, turn: seen.append((turn, w.count_pixels())),
        },
    )
    viz_thread.start()
    run(
        p,
        events,
        emit_flips=True,
        images_dir=REPO_ROOT / "images",
        out_dir=tmp_path / "out",
        tick_seconds=3600,
    )
    viz_thread.join(timeout=30)
    assert not viz_thread.is_alive()
    assert [t for t, _ in seen] == list(range(1, 21))
    for turn, count in seen:
        assert count == counts[turn], f"turn {turn}: {count} != {counts[turn]}"
    assert window.frames_rendered == 20


def test_make_window_falls_back_headless():
    from gol_distributed_final_tpu.viz.window import make_window

    w = make_window(4, 4)
    assert isinstance(w, Window)  # no libSDL2 in this image


def test_bigview_tracks_engine_session():
    """The config-5 visualiser: a BigView watching an engine-driven big
    board renders the oracle window through the reference SetPixel
    protocol — live while the session runs, exact after it ends."""
    from gol_distributed_final_tpu.bigboard import r_pentomino
    from gol_distributed_final_tpu.engine import Engine
    from gol_distributed_final_tpu.engine.engine import EngineConfig
    from gol_distributed_final_tpu.viz.bigview import BigView

    from helpers import oracle_window

    SIZE, TURNS, WIN = 2048, 60, 256
    W0 = SIZE // 2 - WIN // 2
    eng = Engine(EngineConfig(final_world=False, min_chunk=2, max_chunk=8))
    view = BigView(
        eng, W0, W0, WIN, WIN, window=Window(WIN, WIN), interval=0.05
    ).watch()
    # run via the engine directly so no PGM lands in the repo out/
    from gol_distributed_final_tpu.bigboard import seed_packed
    from gol_distributed_final_tpu.ops.plane import BitPlane
    from gol_distributed_final_tpu.params import Params

    state = seed_packed(SIZE, r_pentomino(SIZE))
    eng.run(
        Params(turns=TURNS, image_width=SIZE, image_height=SIZE),
        None, plane=BitPlane(), initial_state=state,
    )
    view.stop()  # re-raises if the watch thread died
    assert view.live_frames >= 1, "no frame rendered WHILE the run was live"
    assert view.refresh()  # one final frame from the settled state
    oracle = oracle_window(SIZE, TURNS, WIN)
    np.testing.assert_array_equal((view.window._pixels != 0), oracle != 0)
    assert view.last_turn == TURNS


def test_bigview_double_watch_raises():
    """A second watch() while one is live would orphan the first refresh
    thread and drop its pending _error (ADVICE.md round 3)."""
    from gol_distributed_final_tpu.engine import Engine
    from gol_distributed_final_tpu.viz.bigview import BigView

    view = BigView(Engine(), 0, 0, 8, 8, window=Window(8, 8), interval=0.05)
    view.watch()
    try:
        with pytest.raises(RuntimeError, match="already watching"):
            view.watch()
    finally:
        view.stop()
    # after stop(), watching again must actually loop (the _stop event is
    # re-armed, not left set from the previous stop — a set event would
    # make the restarted thread exit before its first refresh)
    import time

    view.watch()
    time.sleep(0.2)
    assert view._thread.is_alive(), "restarted watch thread exited immediately"
    view.stop()
    assert view._thread is None
