"""Visualiser tests: window semantics (sdl/window.go) and the event loop's
flip/render protocol (sdl/loop.go), including the TestSdl-style shadow
reconstruction through a full session."""

import queue
import threading

import numpy as np
import pytest

from gol_distributed_final_tpu import Params, run
from gol_distributed_final_tpu.viz import Window
from gol_distributed_final_tpu.viz.loop import run as viz_run

from helpers import REPO_ROOT, read_alive_counts


def test_window_flip_set_count_clear():
    w = Window(8, 4)
    w.flip_pixel(0, 0)
    w.flip_pixel(7, 3)
    assert w.count_pixels() == 2
    w.flip_pixel(0, 0)  # flip back off
    assert w.count_pixels() == 1
    w.set_pixel(2, 2)
    assert w.count_pixels() == 2
    w.clear_pixels()
    assert w.count_pixels() == 0


def test_window_bounds_panic():
    w = Window(8, 4)
    with pytest.raises(IndexError):
        w.flip_pixel(8, 0)
    with pytest.raises(IndexError):
        w.flip_pixel(0, -1)


def test_viz_loop_reconstructs_board(tmp_path):
    """TestSdl's contract through the real stack: a session with flips on,
    consumed by the visualiser loop; the window's pixel count at every
    TurnComplete must match the golden alive CSV (sdl_test.go:56-74)."""
    counts = read_alive_counts(REPO_ROOT / "check" / "alive" / "64x64.csv")
    p = Params(turns=20, image_width=64, image_height=64)
    events = queue.Queue()
    seen = []

    window = Window(64, 64)
    viz_thread = threading.Thread(
        target=viz_run,
        args=(p, events),
        kwargs={
            "window": window,
            "on_turn": lambda w, turn: seen.append((turn, w.count_pixels())),
        },
    )
    viz_thread.start()
    run(
        p,
        events,
        emit_flips=True,
        images_dir=REPO_ROOT / "images",
        out_dir=tmp_path / "out",
        tick_seconds=3600,
    )
    viz_thread.join(timeout=30)
    assert not viz_thread.is_alive()
    assert [t for t, _ in seen] == list(range(1, 21))
    for turn, count in seen:
        assert count == counts[turn], f"turn {turn}: {count} != {counts[turn]}"
    assert window.frames_rendered == 20


def test_make_window_falls_back_headless():
    from gol_distributed_final_tpu.viz.window import make_window

    w = make_window(4, 4)
    assert isinstance(w, Window)  # no libSDL2 in this image


def test_bigview_tracks_engine_session():
    """The config-5 visualiser: a BigView watching an engine-driven big
    board renders the oracle window through the reference SetPixel
    protocol — live while the session runs, exact after it ends."""
    from gol_distributed_final_tpu.bigboard import r_pentomino
    from gol_distributed_final_tpu.engine import Engine
    from gol_distributed_final_tpu.engine.engine import EngineConfig
    from gol_distributed_final_tpu.viz.bigview import BigView

    from helpers import oracle_window

    SIZE, TURNS, WIN = 2048, 60, 256
    W0 = SIZE // 2 - WIN // 2
    eng = Engine(EngineConfig(final_world=False, min_chunk=2, max_chunk=8))
    view = BigView(
        eng, W0, W0, WIN, WIN, window=Window(WIN, WIN), interval=0.05
    ).watch()
    # run via the engine directly so no PGM lands in the repo out/
    from gol_distributed_final_tpu.bigboard import seed_packed
    from gol_distributed_final_tpu.ops.plane import BitPlane
    from gol_distributed_final_tpu.params import Params

    state = seed_packed(SIZE, r_pentomino(SIZE))
    eng.run(
        Params(turns=TURNS, image_width=SIZE, image_height=SIZE),
        None, plane=BitPlane(), initial_state=state,
    )
    view.stop()  # re-raises if the watch thread died
    assert view.live_frames >= 1, "no frame rendered WHILE the run was live"
    assert view.refresh()  # one final frame from the settled state
    oracle = oracle_window(SIZE, TURNS, WIN)
    np.testing.assert_array_equal((view.window._pixels != 0), oracle != 0)
    assert view.last_turn == TURNS


def test_bigview_double_watch_raises():
    """A second watch() while one is live would orphan the first refresh
    thread and drop its pending _error (ADVICE.md round 3)."""
    from gol_distributed_final_tpu.engine import Engine
    from gol_distributed_final_tpu.viz.bigview import BigView

    view = BigView(Engine(), 0, 0, 8, 8, window=Window(8, 8), interval=0.05)
    view.watch()
    try:
        with pytest.raises(RuntimeError, match="already watching"):
            view.watch()
    finally:
        view.stop()
    # after stop(), watching again must actually loop (the _stop event is
    # re-armed, not left set from the previous stop — a set event would
    # make the restarted thread exit before its first refresh)
    import time

    view.watch()
    time.sleep(0.2)
    assert view._thread.is_alive(), "restarted watch thread exited immediately"
    view.stop()
    assert view._thread is None


def test_window_keypresses_drive_full_session(tmp_path):
    """The reference's sdl/loop.go:16-28 path: keys pressed IN THE WINDOW
    are forwarded through the visualiser loop into the controller's
    keypress queue and drive the session — 's' writes a snapshot PGM, 'p'
    pauses (StateChange Paused), a second 'p' resumes with the reference's
    turn-1 quirk (gol/distributor.go:118), and 'q' quits cleanly."""
    import time as time_mod

    from gol_distributed_final_tpu import (
        FinalTurnComplete,
        State,
        StateChange,
    )

    class ScriptedWindow(Window):
        """Headless window that 'presses' a scripted key sequence, one key
        per poll interval, mimicking a user typing in the SDL window."""

        def __init__(self, width, height, keys, interval=0.35):
            super().__init__(width, height)
            self._keys = list(keys)
            self._interval = interval
            self._next_at = time_mod.monotonic() + interval
            self.destroyed = False

        def poll_key(self):
            if self._keys and time_mod.monotonic() >= self._next_at:
                self._next_at = time_mod.monotonic() + self._interval
                return self._keys.pop(0)
            return None

        def destroy(self):
            self.destroyed = True

    p = Params(turns=100_000_000, image_width=64, image_height=64)
    events = queue.Queue()
    keypresses = queue.Queue()
    window = ScriptedWindow(64, 64, ["s", "p", "p", "q"])
    collected = []

    def consume_and_forward():
        # the visualiser loop IS the consumer; record what it prints by
        # teeing events through a wrapper queue
        viz_run(p, events, keypresses, window=window)

    # tee: collect events for assertions while the viz loop drains them —
    # wrap the queue's get so both see the stream. Installed BEFORE the
    # viz thread starts so not even the first event can bypass the tee.
    orig_get = events.get

    def tee_get(*a, **kw):
        ev = orig_get(*a, **kw)
        collected.append(ev)
        return ev

    events.get = tee_get

    viz_thread = threading.Thread(target=consume_and_forward)
    viz_thread.start()

    result = run(
        p,
        events,
        keypresses,
        images_dir=REPO_ROOT / "images",
        out_dir=tmp_path / "out",
        tick_seconds=0.1,
    )
    viz_thread.join(timeout=30)
    assert not viz_thread.is_alive()
    assert window.destroyed

    # 'q' ended the run early
    assert 0 < result.turns_completed < p.turns

    # 's' wrote a snapshot PGM named by the reference convention
    snap_path = tmp_path / "out" / f"{p.output_filename}.pgm"
    assert snap_path.exists(), "s-key snapshot PGM missing"

    # pause/resume StateChange pair. The paused event's turn is read
    # BEFORE the pause lands (reference does the same), so in-flight
    # chunks may commit in between: the resume event (frozen turn - 1,
    # gol/distributor.go:118) can only be bounded from below here; the
    # exact -1 arithmetic is pinned by
    # test_pause_resume_quirk_exact_arithmetic
    changes = [e for e in collected if isinstance(e, StateChange)]
    paused = [e for e in changes if e.new_state == State.PAUSED]
    executing = [e for e in changes if e.new_state == State.EXECUTING]
    assert len(paused) == 1 and len(executing) == 1
    assert executing[0].completed_turns >= paused[0].completed_turns - 1

    # clean quit: a Quitting StateChange from 'q' plus the closing sequence
    quits = [e for e in changes if e.new_state == State.QUITTING]
    assert len(quits) == 2
    assert any(isinstance(e, FinalTurnComplete) for e in collected)


def test_pause_resume_quirk_exact_arithmetic():
    """The reference reports exactly (turn - 1) on resume
    (gol/distributor.go:118). Deterministic check through the same
    _handle_key path the window keys drive, with a broker whose turn
    counter is frozen at a known value."""
    from gol_distributed_final_tpu import State, StateChange
    from gol_distributed_final_tpu.engine.controller import _Ticker
    from gol_distributed_final_tpu.engine.engine import Snapshot

    class FrozenBroker:
        def retrieve(self, include_world=True):
            return Snapshot(None, 7, 42)

        def pause(self):
            pass

    events, keys = queue.Queue(), queue.Queue()
    ticker = _Ticker(
        Params(turns=10, image_width=16, image_height=16),
        events, keys, FrozenBroker(), "out", 3600.0,
    )
    ticker._handle_key("p")
    ticker._handle_key("p")
    first, second = events.get_nowait(), events.get_nowait()
    assert first == StateChange(7, State.PAUSED)
    assert second == StateChange(6, State.EXECUTING)
