"""Child process for the multi-host end-to-end test (tests/test_multihost.py).

One of N processes in a real ``jax.distributed`` job over the CPU backend:
each process owns 4 virtual devices, the board is sharded over the GLOBAL
('rows', 'cols') mesh spanning all processes, and each process touches ONLY
its own row range of the on-disk PGM (parallel/multihost.host_row_range +
io/sharded.py) — the BASELINE config-5 IO pattern at test scale.

Usage: multihost_child.py <coordinator> <num_procs> <proc_id> <images_dir>
       <out_path> <turns>

Reference anchor: the reference scales to more machines by adding worker
addresses (broker/broker.go:288-300) and shipping the full board to each;
here a process joins the job and only ever holds its shard.
"""

import pathlib
import sys

import numpy as np


def main() -> int:
    coordinator, num_procs, proc_id, images_dir, out_path, turns = sys.argv[1:7]
    num_procs, proc_id, turns = int(num_procs), int(proc_id), int(turns)

    import jax

    from gol_distributed_final_tpu.parallel import multihost
    from gol_distributed_final_tpu.parallel import (
        make_bit_plane,
        make_mesh,
        sharded_step_n_fn,
    )
    from gol_distributed_final_tpu.parallel.halo import board_sharding
    from gol_distributed_final_tpu.io.sharded import (
        create_pgm,
        pgm_raster_offset,
        read_shard,
        write_rows_at,
    )

    assert multihost.initialize(coordinator, num_procs, proc_id)
    assert multihost.process_count() == num_procs
    devices = jax.devices()
    assert len(devices) == 4 * num_procs, f"global devices: {len(devices)}"

    size = 64
    # rows axis == processes (jax.devices() is process-major), cols local
    mesh = make_mesh((num_procs, 4), devices=devices)
    lo, hi = multihost.host_row_range(mesh, size)
    expected_rows = size // num_procs
    assert hi - lo == expected_rows and lo == proc_id * expected_rows

    # per-host streamed read: ONLY this host's rows leave the disk
    local = read_shard(pathlib.Path(images_dir) / f"{size}x{size}.pgm", lo, hi)
    sharding = board_sharding(mesh)
    board = jax.make_array_from_process_local_data(sharding, local, (size, size))

    # evolve on the global mesh: halo ppermutes cross the process boundary
    step_n = sharded_step_n_fn(mesh)
    out = step_n(board, turns)
    out.block_until_ready()

    # the fast plane, same topology: mesh-sharded bitboard parity
    plane = make_bit_plane(mesh, (size, size))
    assert plane is not None
    state = plane.step_n(plane.encode(board), turns)
    bit_out = plane.decode_global(state)  # a global sharded device array
    # the public count path: every rank must report the GLOBAL count even
    # though it only holds its own shards (allgathered row popcounts)
    global_count = plane.alive_count(state)
    want_count = int(jax.jit(lambda b: (b != 0).sum())(out))  # replicated
    assert global_count == want_count, (global_count, want_count)

    # gather each array's LOCAL rows and compare shard-wise
    def local_rows(arr):
        rows = np.full((hi - lo, size), 255, np.uint8)  # poison non-owned
        for shard in arr.addressable_shards:
            r0, c0 = (idx.start or 0 for idx in shard.index)
            data = np.asarray(shard.data)
            rows[r0 - lo : r0 - lo + data.shape[0], c0 : c0 + data.shape[1]] = data
        return rows

    mine = local_rows(out)
    np.testing.assert_array_equal(local_rows(bit_out), mine)

    # per-host streamed write, disjoint pwrites (io/sharded.py)
    out_path = pathlib.Path(out_path)
    if proc_id == 0:
        offset = create_pgm(out_path, size, size)
    else:
        offset = pgm_raster_offset(size, size)
    # cross-process barrier so rank!=0 never writes before the file is sized
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices("pgm_created")
    write_rows_at(out_path, offset, size, lo, mine)
    multihost_utils.sync_global_devices("pgm_written")
    print(f"rank {proc_id} rows [{lo}, {hi}) done", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
