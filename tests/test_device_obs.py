"""Device-telemetry layer tests (obs/device.py + its surfaces): compile
wall/cost-analysis capture on CPU-lowered kernels, the memory_stats
null-on-CPU guard, the watch dashboard (pure render + one live poll
against a broker subprocess), the noise-aware bench_diff verdicts, the
status CLI's timeout/empty-vs-missing split, and the device-metric lint.
"""

import json
import types

import numpy as np
import pytest

from gol_distributed_final_tpu.models import CONWAY
from gol_distributed_final_tpu.obs import device as obs_device
from gol_distributed_final_tpu.obs import metrics as obs_metrics

from helpers import REPO_ROOT
from test_rpc import _spawn, _wait_listening


@pytest.fixture
def live_metrics():
    """Enabled, zeroed registry + zeroed HBM peaks for one test; back to
    the no-op default (and fresh HBM discovery) after."""
    reg = obs_metrics.registry()
    reg.reset()
    obs_metrics.enable()
    obs_device.reset_hbm()
    yield reg
    obs_metrics.enable(False)
    reg.reset()
    obs_device.reset_hbm()


def _series(snapshot: dict, name: str) -> dict:
    for fam in snapshot["families"]:
        if fam["name"] == name:
            return {tuple(s["labels"]): s for s in fam["series"]}
    return {}


# -- compile telemetry (instrument_jit / compile_and_call) -------------------


def test_instrument_jit_records_compile_and_cost(live_metrics):
    """First call per signature goes through a timed lower/compile with
    XLA cost analysis captured; the second call reuses the executable
    (compile count stays 1) and computes the same thing."""
    import jax

    jitted = jax.jit(lambda x: x @ x + 1.0)
    wrapped = obs_device.instrument_jit("test.site", jitted)
    x = np.ones((32, 32), np.float32)
    first = np.asarray(wrapped(x))
    second = np.asarray(wrapped(x))
    np.testing.assert_array_equal(first, np.asarray(jitted(x)))
    np.testing.assert_array_equal(first, second)
    snap = live_metrics.snapshot()
    compile_series = _series(snap, "gol_compile_seconds")[("test.site",)]
    assert compile_series["count"] == 1  # second call hit the cache
    # a 32^3 matmul has real flops on the CPU cost model
    assert _series(snap, "gol_kernel_flops")[("test.site",)]["value"] > 0
    assert (
        _series(snap, "gol_kernel_bytes_accessed")[("test.site",)]["value"] > 0
    )


def test_instrument_jit_disabled_is_invisible():
    """With the registry off, the wrapper is a plain call: nothing
    recorded, and the signature is pinned to the jit path (no surprise
    AOT recompile if metrics come on later)."""
    import jax

    wrapped = obs_device.instrument_jit("test.off", jax.jit(lambda x: x + 1))
    x = np.zeros((4,), np.int32)
    np.testing.assert_array_equal(np.asarray(wrapped(x)), x + 1)
    obs_metrics.enable()
    try:
        np.testing.assert_array_equal(np.asarray(wrapped(x)), x + 1)
        snap = obs_metrics.registry().snapshot()
        assert ("test.off",) not in _series(snap, "gol_compile_seconds")
    finally:
        obs_metrics.enable(False)
        obs_metrics.registry().reset()


def test_instrument_jit_passes_through_duck_typed_fakes():
    """A plain callable without .lower comes back unwrapped — the halo
    tests' fake step functions must survive the instrumented factories."""
    fn = lambda x: x  # noqa: E731
    assert obs_device.instrument_jit("test.fake", fn) is fn


def test_kernel_paths_record_compile_site_and_stay_exact(live_metrics):
    """The real compile sites: a BitPlane step on CPU (interpret mode)
    records a pallas.vmem_bit compile, and the instrumented path's
    evolution stays bit-exact against the independent roll stencil.

    The factory cache is cleared first: an earlier suite test may have
    pulled this exact (n, masks) program while metrics were off, which
    pins that signature to the plain jit path (the no-surprise-recompile
    contract) — the telemetry assertion needs a genuinely fresh compile."""
    from gol_distributed_final_tpu.ops import pallas_stencil
    from gol_distributed_final_tpu.ops.plane import BitPlane

    pallas_stencil._bit_compiled.cache_clear()
    rng = np.random.default_rng(7)
    board = np.where(rng.random((64, 64)) < 0.3, 255, 0).astype(np.uint8)
    plane = BitPlane(CONWAY, 0)
    got = plane.decode(plane.step_n(plane.encode(board), 3))
    want = np.asarray(CONWAY.step_n(np.asarray(board), 3))
    np.testing.assert_array_equal(got, want)
    snap = live_metrics.snapshot()
    compiles = _series(snap, "gol_compile_seconds")
    assert compiles[("pallas.vmem_bit",)]["count"] >= 1


def test_mesh_halo_path_records_compile_site(live_metrics):
    """The byte halo plane's compile-cache miss now also records compile
    wall + cost analysis under the halo.byte site, and the mesh evolution
    stays exact."""
    import jax

    from gol_distributed_final_tpu.parallel import make_mesh
    from gol_distributed_final_tpu.parallel.halo import sharded_step_n_fn

    mesh = make_mesh((2, 2), devices=jax.devices()[:4])
    step = sharded_step_n_fn(mesh)
    rng = np.random.default_rng(11)
    board = np.where(rng.random((32, 32)) < 0.3, 255, 0).astype(np.uint8)
    out = np.asarray(step(board, 4))
    want = np.asarray(CONWAY.step_n(np.asarray(board), 4))
    np.testing.assert_array_equal(out, want)
    snap = live_metrics.snapshot()
    assert _series(snap, "gol_compile_seconds")[("halo.byte",)]["count"] >= 1
    # flops estimate for the compiled mesh program landed on the gauge
    assert _series(snap, "gol_kernel_flops")[("halo.byte",)]["value"] >= 0


# -- HBM sampling ------------------------------------------------------------


def test_sample_hbm_null_on_cpu(live_metrics):
    """CPU devices report memory_stats()=None: sampling returns empty,
    sets no gauges, never raises — and the discovery is cached so later
    samples are free."""
    assert obs_device.sample_hbm() == {}
    assert obs_device.sample_hbm() == {}  # cached unsupported path
    assert obs_device.hbm_peak_observed() == {}
    snap = live_metrics.snapshot()
    assert _series(snap, "gol_device_hbm_bytes_in_use") == {}


def test_sample_hbm_gauges_and_peak_high_water(live_metrics):
    """With a device that HAS memory stats (faked), the three gauges are
    set and the peak-observed high-water mark survives a later, lower
    sample — the mid-run-spike visibility the RunReport publishes."""

    class Fake:
        def __init__(self, in_use):
            self.id = 3
            self._in_use = in_use

        def memory_stats(self):
            return {
                "bytes_in_use": self._in_use,
                "peak_bytes_in_use": self._in_use,
                "bytes_limit": 1000,
            }

    assert obs_device.sample_hbm([Fake(800)])["3"]["bytes_in_use"] == 800
    obs_device.sample_hbm([Fake(100)])  # spike subsided
    snap = live_metrics.snapshot()
    assert _series(snap, "gol_device_hbm_bytes_in_use")[("3",)]["value"] == 100
    assert _series(snap, "gol_device_hbm_bytes_limit")[("3",)]["value"] == 1000
    assert obs_device.hbm_peak_observed() == {"3": 800}
    # a fake-device sample must not poison the real-backend discovery
    assert obs_device.sample_hbm() == {}


def test_sample_hbm_supported_latch_survives_transient_failure(live_metrics):
    """Once a backend has produced memory stats, one sweep where every
    device fails must not permanently disable sampling (the gauges would
    freeze mid-run) — the latch only goes False on the FIRST probe."""
    obs_device._HBM_SUPPORTED = True  # as if a TPU sweep had succeeded
    assert obs_device.sample_hbm() == {}  # CPU: transient-empty shape
    assert obs_device._HBM_SUPPORTED is True  # not flipped off


def test_engine_run_samples_hbm_without_breaking(live_metrics):
    """A metrics-on engine run drives the per-chunk sampling path on CPU
    (guarded null) and the run itself stays exact."""
    from gol_distributed_final_tpu.engine.engine import Engine
    from gol_distributed_final_tpu.params import Params

    rng = np.random.default_rng(3)
    board = np.where(rng.random((32, 32)) < 0.3, 255, 0).astype(np.uint8)
    p = Params(turns=8, image_width=32, image_height=32)
    result = Engine().run(p, board)
    assert result.turns_completed == 8
    want = np.asarray(CONWAY.step_n(np.asarray(board), 8))
    np.testing.assert_array_equal(result.world, want)
    assert obs_device.hbm_peak_observed() == {}  # CPU: sampled, null


def test_device_inventory_carries_observed_peak(live_metrics):
    """The RunReport's device inventory includes the high-water key for
    every device (null on CPU where nothing was ever sampled)."""
    from gol_distributed_final_tpu.obs.report import device_inventory

    inventory = device_inventory()
    for dev in inventory["local_devices"]:
        assert "hbm_peak_observed_bytes" in dev
        assert dev["hbm_peak_observed_bytes"] is None  # CPU backend


# -- status CLI: -timeout + empty-vs-missing ---------------------------------


def test_extract_status_distinguishes_old_from_empty():
    from gol_distributed_final_tpu.obs.status import (
        StatusUnavailable,
        extract_status,
    )

    with pytest.raises(StatusUnavailable, match="predates"):
        extract_status(types.SimpleNamespace())  # no field at all
    with pytest.raises(StatusUnavailable, match="predates"):
        extract_status(types.SimpleNamespace(status=None))
    with pytest.raises(StatusUnavailable, match="EMPTY"):
        extract_status(types.SimpleNamespace(status={}))
    assert extract_status(types.SimpleNamespace(status={"pid": 1})) == {
        "pid": 1
    }


def test_status_cli_timeout_flag_bounds_dead_server(capsys):
    """-timeout reaches the client: a dead port fails fast with rc 1."""
    from gol_distributed_final_tpu.obs.status import main as status_main

    assert status_main(["-timeout", "0.5", "127.0.0.1:1"]) == 1
    assert "status fetch failed" in capsys.readouterr().err


# -- watch dashboard ---------------------------------------------------------


def _synthetic_status_payload() -> dict:
    reg = obs_metrics.Registry()
    reg.counter("gol_engine_turns_total").inc(1000)
    reg.gauge("gol_engine_chunk_size").set(64)
    reg.histogram(
        "gol_rpc_server_request_seconds", labelnames=("method",)
    ).labels("Operations.Run").observe(0.25)
    reg.counter(
        "gol_rpc_server_requests_total", labelnames=("method",)
    ).labels("Operations.Run").inc()
    reg.counter(
        "gol_compile_cache_requests_total", labelnames=("site",)
    ).labels("halo.bit").inc(4)
    reg.counter(
        "gol_compile_cache_misses_total", labelnames=("site",)
    ).labels("halo.bit").inc(1)
    reg.gauge("gol_device_hbm_bytes_in_use", labelnames=("device",)).labels(
        "0"
    ).set(2 * 1024**3)
    reg.gauge("gol_device_hbm_bytes_limit", labelnames=("device",)).labels(
        "0"
    ).set(16 * 1024**3)
    return {
        "role": "broker",
        "pid": 42,
        "metrics_enabled": True,
        "metrics": reg.snapshot(),
        "flight": [{"kind": "rpc.dispatch", "name": "Operations.Run"}],
    }


def test_watch_render_is_pure_and_skew_safe():
    """render_status is a pure function of the payload: all panels from a
    synthetic snapshot, and an empty payload (maximal skew) still renders
    a header instead of crashing."""
    from gol_distributed_final_tpu.obs.watch import render_status

    frame = render_status("broker :8040", _synthetic_status_payload(), 123.4)
    assert "THROUGHPUT" in frame and "1,000" in frame and "123 turns/s" in frame
    assert "Operations.Run" in frame and "250.0ms" in frame
    assert "cache 3/4 hit (75%)" in frame
    assert "2.0GiB / 16.0GiB (12%)" in frame
    assert "FLIGHT" in frame and "rpc.dispatch" in frame
    bare = render_status("worker :1", {}, None)
    assert "worker :1" in bare  # skew-safe: renders, just sparse


def test_watch_one_poll_against_live_broker(capsys):
    """The acceptance shape: one -once poll against a live -metrics broker
    renders throughput and per-verb latency from the Status verb."""
    broker = _spawn(
        "gol_distributed_final_tpu.rpc.broker", "-port", "0", "-metrics"
    )
    try:
        port = _wait_listening(broker)
        from gol_distributed_final_tpu.io.pgm import read_board
        from gol_distributed_final_tpu.params import Params
        from gol_distributed_final_tpu.rpc.client import RemoteBroker

        p = Params(turns=20, threads=8, image_width=64, image_height=64)
        board = read_board(p, REPO_ROOT / "images")
        remote = RemoteBroker(f"127.0.0.1:{port}")
        try:
            assert remote.run(p, board).turns_completed == 20
        finally:
            remote.close()

        from gol_distributed_final_tpu.obs.watch import main as watch_main

        assert watch_main([f"127.0.0.1:{port}", "-once"]) == 0
        frame = capsys.readouterr().out
        assert "THROUGHPUT" in frame
        assert "turns 20" in frame
        assert "Operations.Run" in frame
        assert "HBM" in frame  # section renders (null on CPU)
    finally:
        if broker.poll() is None:
            broker.kill()
        broker.wait()


def test_watch_one_poll_dead_target_fails_cleanly(capsys):
    from gol_distributed_final_tpu.obs.watch import main as watch_main

    assert watch_main(["127.0.0.1:1", "-once", "-timeout", "0.5"]) == 1
    assert "poll failed" in capsys.readouterr().out


# -- bench_diff (obs/regress.py) ---------------------------------------------


def _bench_doc(cases: dict, provenance=None) -> dict:
    doc = {"metric": "cell-updates/sec", "value": 1.0, "extra": cases}
    if provenance is not None:
        doc["provenance"] = provenance
    return doc


def _case(per_turn_us, spread_s=0.001, n_lo=1000, n_hi=101_000):
    return {
        "per_turn_us": per_turn_us,
        "spread_s": spread_s,
        "n_lo": n_lo,
        "n_hi": n_hi,
    }


def _write(tmp_path, name, doc):
    path = tmp_path / name
    path.write_text(json.dumps(doc))
    return str(path)


def test_bench_diff_verdicts_and_exit_codes(tmp_path, capsys):
    """Synthetic improved / regressed / noisy pairs: the regression exits
    nonzero, the improvement and the within-noise wobble do not, and the
    table names each verdict. Noise per side is spread_s/(n_hi-n_lo) —
    0.01 µs/turn here, so the noisy case's +0.02 µs sits inside the
    2x(old+new) band while the regressed case's +1 µs clears it."""
    from gol_distributed_final_tpu.obs.regress import main as regress_main

    old = _bench_doc(
        {
            "c_improved": _case(2.0),
            "c_regressed": _case(1.0),
            "c_noisy": _case(1.0, spread_s=0.001),
            "c_removed": _case(5.0),
        }
    )
    new = _bench_doc(
        {
            "c_improved": _case(1.0),
            "c_regressed": _case(2.0),
            "c_noisy": _case(1.02, spread_s=0.001),
            "c_new": _case(3.0),
        }
    )
    rc = regress_main([_write(tmp_path, "old.json", old),
                       _write(tmp_path, "new.json", new)])
    out = capsys.readouterr().out
    assert rc == 1  # the regression gates
    assert "c_improved" in out and "improved" in out
    assert "c_regressed" in out and "REGRESSED" in out
    assert "jitter" in out
    assert "new" in out and "removed" in out

    # a round compared against itself is all jitter: gate passes
    rc = regress_main([_write(tmp_path, "same.json", new),
                       _write(tmp_path, "same2.json", new)])
    capsys.readouterr()
    assert rc == 0


def test_bench_diff_noise_band_suppresses_false_regression(tmp_path, capsys):
    """A 10% slowdown whose measurements carry +-20% per-turn noise is
    jitter, not a regression — the core noise-aware property."""
    from gol_distributed_final_tpu.obs.regress import main as regress_main

    noisy = dict(spread_s=0.01, n_lo=1000, n_hi=101_000)  # 0.1 µs/turn noise
    old = _bench_doc({"c": _case(1.0, **noisy)})
    new = _bench_doc({"c": _case(1.1, **noisy)})
    rc = regress_main([_write(tmp_path, "a.json", old),
                       _write(tmp_path, "b.json", new)])
    assert rc == 0
    assert "jitter" in capsys.readouterr().out


def test_bench_diff_zero_fit_is_incomparable_either_side(tmp_path, capsys):
    """A zero per_turn_us (broken fit on a salvaged fragment) is
    ``incomparable`` on EITHER side — never an infinite improvement that
    greenwashes the gate, never a phantom regression."""
    from gol_distributed_final_tpu.obs.regress import compare_case
    from gol_distributed_final_tpu.obs.regress import main as regress_main

    assert compare_case(_case(1.0), _case(0.0))["verdict"] == "incomparable"
    assert compare_case(_case(0.0), _case(1.0))["verdict"] == "incomparable"
    rc = regress_main(
        [_write(tmp_path, "za.json", _bench_doc({"c": _case(1.0)})),
         _write(tmp_path, "zb.json", _bench_doc({"c": _case(0.0)}))]
    )
    assert rc == 0
    assert "incomparable" in capsys.readouterr().out


def test_bench_diff_refuses_cross_environment(tmp_path, capsys):
    from gol_distributed_final_tpu.obs.regress import main as regress_main

    prov_a = {"jax_version": "0.4.37", "device_kind": "TPU v5e",
              "device_count": 1}
    prov_b = dict(prov_a, jax_version="0.5.0")
    old = _write(
        tmp_path, "pa.json", _bench_doc({"c": _case(1.0)}, prov_a)
    )
    new = _write(
        tmp_path, "pb.json", _bench_doc({"c": _case(1.0)}, prov_b)
    )
    assert regress_main([old, new]) == 2
    assert "REFUSING" in capsys.readouterr().err
    assert regress_main([old, new, "--force"]) == 0  # forced through
    # identical provenance: no refusal, no warning
    same = _write(
        tmp_path, "pc.json", _bench_doc({"c": _case(1.0)}, prov_a)
    )
    capsys.readouterr()
    assert regress_main([old, same]) == 0


def test_bench_diff_salvages_truncated_driver_tail(tmp_path):
    """The driver wrapper keeps only the tail of stdout: a head-truncated
    bench line still yields every complete case object."""
    from gol_distributed_final_tpu.obs.regress import load_bench

    line = json.dumps(
        _bench_doc({"c_lost": _case(9.0), "c_kept": _case(1.0),
                    "c_also": _case(2.0)})
    )
    cut = line.index('"c_kept"') - 10  # decapitate: c_lost's body is gone
    wrapper = {"n": 5, "cmd": "python bench.py", "rc": 0,
               "tail": line[cut:], "parsed": None}
    loaded = load_bench(_write(tmp_path, "BENCH_r09.json", wrapper))
    assert loaded["salvaged"] is True
    assert set(loaded["cases"]) == {"c_kept", "c_also"}


def test_bench_diff_latest_mode(tmp_path, capsys):
    """--latest picks the two newest rounds numerically (r9 < r10), and is
    a clean no-op when fewer than two rounds exist."""
    from gol_distributed_final_tpu.obs.regress import main as regress_main

    assert regress_main(["--latest", "--dir", str(tmp_path)]) == 0
    assert "fewer than two" in capsys.readouterr().err
    _write(tmp_path, "BENCH_r02.json", _bench_doc({"c": _case(5.0)}))
    _write(tmp_path, "BENCH_r09.json", _bench_doc({"c": _case(1.0)}))
    _write(tmp_path, "BENCH_r10.json", _bench_doc({"c": _case(3.0)}))
    rc = regress_main(["--latest", "--dir", str(tmp_path)])
    out = capsys.readouterr().out
    assert "BENCH_r09.json -> BENCH_r10.json" in out
    assert rc == 1  # 1.0 -> 3.0 is a real regression


def test_real_bench_rounds_are_loadable():
    """The repo's own BENCH_r*.json (driver wrappers with truncated
    tails) load — the acceptance path scripts/bench_diff runs on."""
    from gol_distributed_final_tpu.obs.regress import (
        latest_bench_files,
        load_bench,
    )

    rounds = latest_bench_files(REPO_ROOT)
    assert len(rounds) >= 2
    for path in rounds[-2:]:
        assert load_bench(path)["cases"], f"{path.name}: no cases loaded"


# -- provenance + lint -------------------------------------------------------


def test_bench_provenance_stamp():
    import bench

    stamp = bench.provenance()
    assert stamp["jax_version"]
    assert stamp["device_count"] >= 1
    assert stamp["platform"] == "cpu"


def test_device_metrics_documented_and_sections_present():
    from gol_distributed_final_tpu.obs.lint import (
        missing_readme_sections,
        undocumented_device_metrics,
    )

    assert undocumented_device_metrics() == []
    assert missing_readme_sections() == []


def test_device_metric_lint_is_section_scoped(tmp_path):
    """A device metric named only AFTER the Device telemetry section's
    end (the next ## heading) is still flagged — mention elsewhere in the
    file does not count as documented in the table."""
    from gol_distributed_final_tpu.obs.instruments import HBM_BYTES_IN_USE
    from gol_distributed_final_tpu.obs.lint import undocumented_device_metrics

    name = HBM_BYTES_IN_USE.name
    readme = tmp_path / "README.md"
    readme.write_text(
        "### Device telemetry\n(table without the name)\n"
        f"## Later section\n{name} mentioned here only\n"
    )
    assert name in undocumented_device_metrics(readme)
    readme.write_text(f"### Device telemetry\n| `{name}` | gauge | x |\n## Next\n")
    assert name not in undocumented_device_metrics(readme)
