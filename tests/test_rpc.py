"""Distributed control-plane tests: real broker + worker subprocesses over
TCP, driven through the public controller with a RemoteBroker — the full
three-process topology of the reference (controller / broker / workers),
golden-exact.
"""

import os
import queue
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from gol_distributed_final_tpu import FinalTurnComplete, Params, run
from gol_distributed_final_tpu.engine.controller import CLOSED, iter_events
from gol_distributed_final_tpu.rpc.client import RemoteBroker, RpcClient, RpcError
from gol_distributed_final_tpu.rpc.protocol import Methods, Request

from helpers import REPO_ROOT, assert_equal_board, read_alive_cells


def _spawn(module: str, *args: str, devices: int = 1) -> subprocess.Popen:
    env = dict(os.environ)
    env.update(
        {"JAX_PLATFORMS": "cpu",
         "XLA_FLAGS": f"--xla_force_host_platform_device_count={devices}"}
    )
    # env vars alone are NOT enough here: the ambient sitecustomize
    # registers the real-TPU plugin at interpreter start and the child
    # would land on it (1 device) regardless — the takeover must go
    # through jax.config before any device query, exactly like
    # tests/conftest.py and the dryrun child (utils/cpumesh.py). Found in
    # r5: every spawned broker/worker had been running single-real-TPU,
    # so multi-device broker paths were never actually exercised.
    code = (
        "import sys, runpy; "
        "from gol_distributed_final_tpu.utils.cpumesh import "
        "force_virtual_cpu_devices; "
        f"assert force_virtual_cpu_devices({devices}); "
        f"sys.argv[0] = {module!r}; "
        f"runpy.run_module({module!r}, run_name='__main__')"
    )
    proc = subprocess.Popen(
        [sys.executable, "-c", code, *args],
        cwd=REPO_ROOT,
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )
    return proc


def _wait_listening(proc: subprocess.Popen, timeout=60) -> int:
    """Parse 'listening on :<port>' from process stdout."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if "listening on :" in line:
            return int(line.rsplit(":", 1)[1].split()[0])
        if proc.poll() is not None:
            raise RuntimeError(f"process died: {proc.stdout.read()}")
    raise TimeoutError("server did not report listening")


@pytest.fixture(scope="module")
def worker_cluster():
    """Two workers + a workers-backend broker, torn down via SuperQuit."""
    workers = [_spawn("gol_distributed_final_tpu.rpc.worker", "-port", "0") for _ in range(2)]
    ports = [_wait_listening(w) for w in workers]
    addrs = ",".join(f"127.0.0.1:{p}" for p in ports)
    broker = _spawn(
        "gol_distributed_final_tpu.rpc.broker",
        "-port", "0", "-backend", "workers", "-workers", addrs,
    )
    broker_port = _wait_listening(broker)
    yield f"127.0.0.1:{broker_port}", workers, broker
    for p in (*workers, broker):
        if p.poll() is None:
            p.kill()
        p.wait()


@pytest.fixture(scope="module")
def tpu_broker():
    """A tpu-backend broker subprocess (single virtual CPU device)."""
    broker = _spawn("gol_distributed_final_tpu.rpc.broker", "-port", "0")
    port = _wait_listening(broker)
    yield f"127.0.0.1:{port}", broker
    if broker.poll() is None:
        broker.kill()
    broker.wait()


def _run_remote(address, size, turns, tmp_path, keys=None, tick=3600.0, threads=8):
    p = Params(turns=turns, threads=threads, image_width=size, image_height=size)
    events = queue.Queue()
    remote = RemoteBroker(address)
    try:
        result = run(
            p,
            events,
            keys,
            broker=remote,
            images_dir=REPO_ROOT / "images",
            out_dir=tmp_path / "out",
            tick_seconds=tick,
        )
    finally:
        remote.close()
    drained = []
    while True:
        ev = events.get_nowait()
        if ev is CLOSED:
            break
        drained.append(ev)
    return result, drained


@pytest.mark.parametrize("size,turns", [(16, 100), (64, 100)])
def test_workers_backend_golden(worker_cluster, size, turns, tmp_path):
    address, _, _ = worker_cluster
    result, events = _run_remote(address, size, turns, tmp_path)
    finals = [e for e in events if isinstance(e, FinalTurnComplete)]
    expected = read_alive_cells(
        REPO_ROOT / "check" / "images" / f"{size}x{size}x{turns}.pgm"
    )
    assert_equal_board(finals[0].alive, expected, size, size)


def test_tpu_backend_golden(tpu_broker, tmp_path):
    address, _ = tpu_broker
    result, events = _run_remote(address, 64, 100, tmp_path)
    expected = read_alive_cells(REPO_ROOT / "check" / "images" / "64x64x100.pgm")
    assert_equal_board(result.alive, expected, 64, 64)


def test_tpu_backend_wide_halo_golden(tmp_path):
    """The -halo-depth knob on the DEPLOYMENT surface (VERDICT r4 item 5):
    a broker started with 8 devices and -halo-depth 2 serves remote runs
    through its wide-halo mesh planes, golden-exact. The RPC verbs — not
    only the library API — can turn the DCN lever. Both plane routes are
    proven: 512^2 rides the PACKED wide plane (blocks (8, 128) words over
    the (2, 4) mesh), 64^2 falls back to the byte wide plane (its packed
    blocks would be (1, 16) words — too shallow for depth 2)."""
    broker = _spawn(
        "gol_distributed_final_tpu.rpc.broker",
        "-port", "0", "-halo-depth", "2",
        devices=8,
    )
    try:
        port = _wait_listening(broker)
        address = f"127.0.0.1:{port}"
        for size in (512, 64):
            result, _ = _run_remote(address, size, 100, tmp_path)
            expected = read_alive_cells(
                REPO_ROOT / "check" / "images" / f"{size}x{size}x100.pgm"
            )
            assert_equal_board(result.alive, expected, size, size)
    finally:
        if broker.poll() is None:
            broker.kill()
        broker.wait()


def test_request_halo_depth_rides_the_wire(tmp_path):
    """The per-request override (Request.halo_depth, 0 = server default):
    a depth-1 broker serves a -halo-depth 2 SESSION golden-exact — the
    controller CLI's knob reaches the remote mesh planes."""
    broker = _spawn(
        "gol_distributed_final_tpu.rpc.broker", "-port", "0", devices=8
    )
    try:
        port = _wait_listening(broker)
        p = Params(turns=100, threads=8, image_width=64, image_height=64)
        remote = RemoteBroker(f"127.0.0.1:{port}")
        try:
            result = run(
                p,
                queue.Queue(),
                broker=remote,
                images_dir=REPO_ROOT / "images",
                out_dir=tmp_path / "out",
                tick_seconds=3600.0,
                halo_depth=2,
            )
        finally:
            remote.close()
        expected = read_alive_cells(
            REPO_ROOT / "check" / "images" / "64x64x100.pgm"
        )
        assert_equal_board(result.alive, expected, 64, 64)
    finally:
        if broker.poll() is None:
            broker.kill()
        broker.wait()


def test_halo_depth_vacuous_on_single_device_but_refused_when_too_deep():
    """A cluster-wide -halo-depth flag must not fail runs landing on a
    one-chip node: with no mesh there are no halo exchanges, so the knob
    is vacuous, not dishonored. But when a mesh EXISTS and no plane can
    carry the depth (board smaller than the depth everywhere), the
    backend refuses loudly rather than silently running at depth 1."""
    from gol_distributed_final_tpu.io.pgm import read_board
    from gol_distributed_final_tpu.rpc.broker import TpuBackend

    board = read_board(
        Params(turns=4, image_width=16, image_height=16), REPO_ROOT / "images"
    )
    req = Request(world=board, turns=4, image_width=16, image_height=16)
    # single-device node (use_mesh=False models it): vacuous-accept
    single = TpuBackend(use_mesh=False, halo_depth=2)
    res = single.run(req)
    assert res.turns_completed == 4
    # an INDIVISIBLE board (no mesh shape divides 17) also runs on the
    # single-device engine — zero halo exchanges, equally vacuous
    odd = np.zeros((17, 17), np.uint8)
    res = TpuBackend(halo_depth=2).run(
        Request(world=odd, turns=2, image_width=17, image_height=17)
    )
    assert res.turns_completed == 2
    # 8-device mesh, depth deeper than any plane's blocks: loud refusal
    deep = TpuBackend(halo_depth=16)
    with pytest.raises(ValueError, match="cannot be honored"):
        deep.run(req)


def test_halo_depth_requires_mesh_broker(tmp_path):
    """run(halo_depth=N) without a remote broker is a clean ValueError
    (like a mismatched rule), not a TypeError mid-session — the knob
    belongs to mesh-backed brokers."""
    p = Params(turns=4, image_width=16, image_height=16)
    with pytest.raises(ValueError, match="halo_depth"):
        run(
            p,
            queue.Queue(),
            images_dir=REPO_ROOT / "images",
            out_dir=tmp_path / "out",
            tick_seconds=3600.0,
            halo_depth=2,
        )


def test_workers_backend_rejects_halo_depth(worker_cluster, tmp_path):
    """Wide halos are a mesh-plane knob: the reference-shaped workers
    backend refuses rather than silently running at depth 1."""
    address, _, _ = worker_cluster
    p = Params(turns=4, threads=2, image_width=16, image_height=16)
    remote = RemoteBroker(address)
    try:
        with pytest.raises(RpcError, match="halo_depth"):
            run(
                p,
                queue.Queue(),
                broker=remote,
                images_dir=REPO_ROOT / "images",
                out_dir=tmp_path / "out",
                tick_seconds=3600.0,
                halo_depth=2,
            )
    finally:
        remote.close()


def test_detach_reattach(tpu_broker, tmp_path):
    """'q' detaches the controller; the broker survives and a fresh
    controller session completes on the same broker (README.md:187)."""
    address, broker_proc = tpu_broker
    keys = queue.Queue()
    keys.put("q")  # quit immediately once the ticker sees it
    result, _ = _run_remote(address, 16, 100_000_000, tmp_path, keys=keys, tick=0.1)
    assert result.turns_completed < 100_000_000
    assert broker_proc.poll() is None  # broker still alive

    # reattach: a complete fresh run against the same broker
    result2, _ = _run_remote(address, 16, 100, tmp_path)
    expected = read_alive_cells(REPO_ROOT / "check" / "images" / "16x16x100.pgm")
    assert_equal_board(result2.alive, expected, 16, 16)


def test_remote_pause_retrieve(tpu_broker, tmp_path):
    """Pause over RPC freezes the turn counter; retrieve is live during Run."""
    address, _ = tpu_broker
    remote = RemoteBroker(address)
    p = Params(turns=100_000_000, threads=1, image_width=64, image_height=64)
    import gol_distributed_final_tpu.io.pgm as pgm

    board = pgm.read_board(p, REPO_ROOT / "images")
    t = threading.Thread(target=lambda: remote.run(p, board))
    t.start()
    try:
        time.sleep(1.0)
        remote.pause()
        a = remote.retrieve(include_world=False).turns_completed
        time.sleep(0.5)
        b = remote.retrieve(include_world=False).turns_completed
        assert a == b
        remote.pause()
        time.sleep(0.5)
        assert remote.retrieve(include_world=False).turns_completed >= b
    finally:
        remote.quit()
        t.join(timeout=30)
        remote.close()
    assert not t.is_alive()


def test_super_quit_shuts_down_cluster(worker_cluster, tmp_path):
    """'k': broker quits workers then itself (broker/broker.go:241-249)."""
    address, workers, broker = worker_cluster
    keys = queue.Queue()
    keys.put("k")
    _run_remote(address, 16, 100_000_000, tmp_path, keys=keys, tick=0.1)
    deadline = time.monotonic() + 20
    procs = [*workers, broker]
    while time.monotonic() < deadline and any(p.poll() is None for p in procs):
        time.sleep(0.2)
    assert all(p.poll() is not None for p in procs), "cluster did not shut down"


def test_unknown_method_and_bad_worker():
    """Protocol robustness: unknown method errors cleanly; a dead worker
    address is skipped at startup (isConnected, broker/broker.go:302-311)."""
    from gol_distributed_final_tpu.rpc.server import RpcServer

    server = RpcServer(port=0)
    server.serve_background()
    client = RpcClient(f"127.0.0.1:{server.port}")
    with pytest.raises(RpcError, match="unknown method"):
        client.call("Operations.Nope", Request())
    client.close()
    server.stop()


# -- elastic recovery (the extension the reference leaves unimplemented:
# its gather hangs on worker death, README.md:266-270) ----------------------


def _poll_turn(remote, minimum, timeout=60.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        snap = remote.retrieve(include_world=False)
        if snap.turns_completed >= minimum:
            return snap.turns_completed
        time.sleep(0.02)
    raise TimeoutError(f"run never reached turn {minimum}")


def test_worker_killed_mid_run_resplits_golden(tmp_path):
    """SIGKILL one of three workers mid-run: the broker drops it, re-splits
    its rows over the survivors, RECOMPUTES the interrupted turn from the
    pre-turn world, and the run completes with exact alive-count parity."""
    turns = 3000
    workers = [
        _spawn("gol_distributed_final_tpu.rpc.worker", "-port", "0")
        for _ in range(3)
    ]
    broker = None
    try:
        ports = [_wait_listening(w) for w in workers]
        addrs = ",".join(f"127.0.0.1:{p}" for p in ports)
        broker = _spawn(
            "gol_distributed_final_tpu.rpc.broker",
            "-port", "0", "-backend", "workers", "-workers", addrs,
        )
        address = f"127.0.0.1:{_wait_listening(broker)}"

        p = Params(turns=turns, threads=3, image_width=64, image_height=64)
        import gol_distributed_final_tpu.io.pgm as pgm

        board = pgm.read_board(p, REPO_ROOT / "images")
        remote = RemoteBroker(address, timeout=10.0)
        result = {}
        t = threading.Thread(
            target=lambda: result.update(r=remote.run(p, board))
        )
        t.start()
        try:
            reached = _poll_turn(remote, turns // 6)
            workers[1].kill()  # SIGKILL, mid-run
            workers[1].wait()
            t.join(timeout=120)
            assert not t.is_alive(), "run did not survive the worker loss"
        finally:
            if t.is_alive():
                remote.quit()
                t.join(timeout=30)
            remote.close()
        r = result["r"]
        assert r.turns_completed == turns
        assert reached < turns  # the kill really happened mid-run
        from helpers import read_alive_counts

        want = read_alive_counts(REPO_ROOT / "check" / "alive" / "64x64.csv")
        assert len(r.alive) == want[turns]
    finally:
        for proc in (*workers, *([broker] if broker else [])):
            if proc.poll() is None:
                proc.kill()
            proc.wait()


def test_all_workers_lost_errors_cleanly(tmp_path):
    """Losing EVERY worker mid-run surfaces a clean RpcError to the blocked
    Run call instead of hanging the gather like the reference."""
    worker = _spawn("gol_distributed_final_tpu.rpc.worker", "-port", "0")
    broker = None
    try:
        port = _wait_listening(worker)
        broker = _spawn(
            "gol_distributed_final_tpu.rpc.broker",
            "-port", "0", "-backend", "workers",
            "-workers", f"127.0.0.1:{port}",
        )
        address = f"127.0.0.1:{_wait_listening(broker)}"

        p = Params(turns=10**7, threads=1, image_width=64, image_height=64)
        import gol_distributed_final_tpu.io.pgm as pgm

        board = pgm.read_board(p, REPO_ROOT / "images")
        remote = RemoteBroker(address, timeout=10.0)
        try:
            errors = {}

            def runner():
                try:
                    remote.run(p, board)
                except Exception as e:  # any failure must reach the assert
                    errors["e"] = e

            t = threading.Thread(target=runner)
            t.start()
            _poll_turn(remote, 10)
            worker.kill()
            worker.wait()
            t.join(timeout=60)
            assert not t.is_alive(), "Run hung after losing all workers"
            assert isinstance(errors.get("e"), RpcError), errors
            assert "all workers lost" in str(errors["e"])
        finally:
            remote.close()
    finally:
        for proc in (worker, *([broker] if broker else [])):
            if proc.poll() is None:
                proc.kill()
            proc.wait()


# -- transport hardening (ADVICE.md round 1) --------------------------------


def test_restricted_unpickler_rejects_forbidden_globals():
    """The wire deserialiser must refuse anything outside the protocol
    vocabulary — a pickle that resolves os.system is an RCE attempt."""
    import pickle

    from gol_distributed_final_tpu.rpc.protocol import loads_restricted

    class Evil:
        def __reduce__(self):
            return (os.system, ("true",))

    proto = pickle.HIGHEST_PROTOCOL  # what send_frame uses on the wire
    payload = pickle.dumps({"id": 0, "method": "x", "request": Evil()}, protocol=proto)
    with pytest.raises(pickle.UnpicklingError, match="forbidden global"):
        loads_restricted(payload)

    # the legitimate vocabulary still round-trips, at every pickle protocol
    from gol_distributed_final_tpu.utils.cell import Cell

    req = Request(world=np.arange(16, dtype=np.uint8).reshape(4, 4), turns=3)
    for pr in (2, 4, proto):
        frame = {"id": 1, "request": req, "cells": [Cell(1, 2)], "n": np.int64(7)}
        out = loads_restricted(pickle.dumps(frame, protocol=pr))
        assert out["request"].turns == 3 and out["cells"] == [Cell(1, 2)]
        np.testing.assert_array_equal(out["request"].world, req.world)


def test_malformed_envelopes_get_defined_behavior(tmp_path):
    """Frames that deserialise through the allowlist but are not proper
    call envelopes get DEFINED behavior: an ERROR REPLY whenever an id is
    present (an identified client is blocking on it), a silent skip when
    none is recoverable — and never an uncaught thread exception (the
    broker process must emit no traceback)."""
    import socket

    from gol_distributed_final_tpu.rpc.protocol import recv_frame, send_frame

    broker = _spawn("gol_distributed_final_tpu.rpc.broker", "-port", "0")
    try:
        port = _wait_listening(broker)
        s = socket.create_connection(("127.0.0.1", port), timeout=30)
        try:
            send_frame(s, ["not", "an", "envelope"])  # no id: no reply owed
            send_frame(s, {"id": 5, "method": {}, "request": None})
            reply = recv_frame(s)
            assert reply["id"] == 5 and "unknown method" in reply["error"]
            send_frame(s, {"id": 6, "method": Methods.RETRIEVE})  # no request
            reply = recv_frame(s)
            assert reply["id"] == 6 and "error" in reply
            # the same connection still serves a real call
            send_frame(
                s,
                {"id": 7, "method": Methods.RETRIEVE,
                 "request": Request(include_world=False)},
            )
            reply = recv_frame(s)
            assert reply["id"] == 7 and ("result" in reply or "error" in reply)
        finally:
            s.close()
    finally:
        if broker.poll() is None:
            broker.kill()
        out, _ = broker.communicate(timeout=30)
    assert "Traceback" not in out, f"uncaught exception in broker:\n{out}"


def test_server_drops_connection_on_malicious_frame(tmp_path):
    """A forbidden frame kills only that connection; the server keeps
    serving honest peers, and the payload is never executed."""
    import pickle
    import socket
    import struct

    from gol_distributed_final_tpu.rpc.server import RpcServer

    canary = str(tmp_path / "pwned.txt")

    class Evil:
        def __reduce__(self):
            return (os.system, (f"touch {canary}",))

    server = RpcServer(port=0)
    server.register("Echo.Echo", lambda req: req)
    server.serve_background()
    try:
        evil = pickle.dumps({"id": 0, "method": "Echo.Echo", "request": Evil()})
        s = socket.create_connection(("127.0.0.1", server.port), timeout=5)
        s.sendall(struct.pack(">Q", len(evil)) + evil)
        # server must close on us without executing anything
        s.settimeout(5)
        assert s.recv(1) == b""  # EOF: connection dropped
        s.close()
        assert not os.path.exists(canary), "malicious payload executed!"

        # an honest client on a fresh connection still gets service
        client = RpcClient(f"127.0.0.1:{server.port}")
        res = client.call("Echo.Echo", Request(turns=7))
        assert res.turns == 7
        client.close()
    finally:
        server.stop()


def test_server_binds_loopback_by_default():
    from gol_distributed_final_tpu.rpc.server import RpcServer

    server = RpcServer(port=0)
    assert server._sock.getsockname()[0] == "127.0.0.1"
    server.stop()


# -- TpuBackend's multi-device routing (the branch real multi-chip hardware
# runs: broker/broker.go:288-311's fan-out, re-founded on the mesh) ---------


def test_tpu_backend_mesh_routing_in_process():
    """On the 8-device test mesh, _plane_for must select the sharded
    bit-packed plane, Run must hold golden parity through it, and the reply
    frame must not carry a Cell list (cells are derived client-side)."""
    import jax

    from gol_distributed_final_tpu.ops import alive_cells
    from gol_distributed_final_tpu.parallel.bit_halo import ShardedBitPlane
    from gol_distributed_final_tpu.rpc.broker import BrokerService, TpuBackend

    assert len(jax.devices()) == 8  # conftest's virtual CPU mesh
    backend = TpuBackend()
    service = BrokerService(None, backend)  # server only matters for SuperQuit
    import gol_distributed_final_tpu.io.pgm as pgm

    p = Params(turns=100, threads=8, image_width=64, image_height=64)
    board = pgm.read_board(p, REPO_ROOT / "images")
    res = service.run(
        Request(world=board, turns=100, image_width=64, image_height=64, threads=8)
    )
    from gol_distributed_final_tpu.models import CONWAY

    assert isinstance(backend._plane_for(64, 64, CONWAY, 1), ShardedBitPlane)
    assert res.alive == []  # Run's reply ships the world, never the cells
    expected = read_alive_cells(REPO_ROOT / "check" / "images" / "64x64x100.pgm")
    assert res.alive_count == len(expected)
    assert_equal_board(alive_cells(res.world), expected, 64, 64)


# -- worker-count sweep (the reference's threads 1..16 matrix,
# gol_test.go:14-31, against the remainder split rpc/broker.py:_split) -------


@pytest.fixture(scope="module")
def five_worker_cluster():
    """Five workers + a workers-backend broker; threads= selects how many
    strips the broker actually scatters."""
    workers = [
        _spawn("gol_distributed_final_tpu.rpc.worker", "-port", "0")
        for _ in range(5)
    ]
    broker = None
    try:
        ports = [_wait_listening(w) for w in workers]
        addrs = ",".join(f"127.0.0.1:{p}" for p in ports)
        broker = _spawn(
            "gol_distributed_final_tpu.rpc.broker",
            "-port", "0", "-backend", "workers", "-workers", addrs,
        )
        yield f"127.0.0.1:{_wait_listening(broker)}"
    finally:
        for p in (*workers, *([broker] if broker else [])):
            if p.poll() is None:
                p.kill()
            p.wait()


@pytest.mark.parametrize("threads", [1, 2, 3, 4, 5])
def test_worker_count_sweep_golden(five_worker_cluster, threads, tmp_path):
    """64 rows over 1..5 workers: even splits (1, 2, 4) and remainder splits
    (3 -> 22/21/21, 5 -> 13/13/13/13/12), all golden-exact."""
    result, _ = _run_remote(
        five_worker_cluster, 64, 100, tmp_path, threads=threads
    )
    expected = read_alive_cells(REPO_ROOT / "check" / "images" / "64x64x100.pgm")
    assert_equal_board(result.alive, expected, 64, 64)


def test_worker_count_sweep_16_golden(five_worker_cluster, tmp_path):
    """The 16-row board over 5 workers (16 = 5*3 + 1: remainder split with
    4/3/3/3/3 strips), golden-exact."""
    result, _ = _run_remote(five_worker_cluster, 16, 100, tmp_path, threads=5)
    expected = read_alive_cells(REPO_ROOT / "check" / "images" / "16x16x100.pgm")
    assert_equal_board(result.alive, expected, 16, 16)


def test_more_workers_than_rows(five_worker_cluster):
    """A 4-row board with 5 connected workers exercises plan()'s n = min(...,
    h) clamp (rpc/broker.py): only 4 single-row strips are scattered, and the
    result matches the independent numpy oracle."""
    from oracle import vector_step

    rng = np.random.default_rng(7)
    world = np.where(rng.random((4, 32)) < 0.4, 255, 0).astype(np.uint8)
    want = world
    for _ in range(10):
        want = vector_step(want)
    p = Params(turns=10, threads=5, image_width=32, image_height=4)
    remote = RemoteBroker(five_worker_cluster)
    try:
        result = remote.run(p, world)
    finally:
        remote.close()
    assert result.turns_completed == 10
    np.testing.assert_array_equal(result.world, want)


def test_workers_backend_pause_parks_before_return():
    """Pause must not return until the turn loop has parked — the same
    guarantee Engine.pause gives, so both backends mean the same thing by
    Operations.Pause: a retrieve immediately after pause() can never
    observe another turn (VERDICT round 3 weak #7)."""
    from gol_distributed_final_tpu.rpc.broker import WorkersBackend
    from gol_distributed_final_tpu.rpc.protocol import Response

    class SlowFakeWorker:
        def call(self, method, req, timeout=None, **kw):
            time.sleep(0.05)
            return Response(work_slice=req.world[1:-1])

    backend = WorkersBackend([])
    backend.clients = [SlowFakeWorker()]
    board = np.where(
        np.random.default_rng(3).random((16, 16)) < 0.3, 255, 0
    ).astype(np.uint8)
    req = Request(
        world=board, turns=10**9, threads=1, image_width=16, image_height=16
    )
    t = threading.Thread(target=lambda: backend.run(req))
    t.start()
    try:
        deadline = time.monotonic() + 10
        while (
            backend.retrieve(False).turns_completed < 2
            and time.monotonic() < deadline
        ):
            time.sleep(0.01)
        backend.pause()
        # no sleep: the guarantee is immediate — the loop is already parked
        a = backend.retrieve(False).turns_completed
        time.sleep(0.3)  # several turn-times at the fake worker's pace
        b = backend.retrieve(False).turns_completed
        assert a == b, "board advanced after pause() returned"
        backend.pause()  # resume
    finally:
        backend.quit()
        t.join(timeout=10)
    assert not t.is_alive()


def test_remote_resume_from_checkpoint(tpu_broker, tmp_path):
    """VERDICT round-3 item 3: checkpoint locally at turn 40, resume
    against the broker subprocess via -resume semantics, and land exactly
    on the turn-100 golden."""
    from oracle import vector_step

    from gol_distributed_final_tpu.engine.checkpoint import save_checkpoint
    from gol_distributed_final_tpu.io.pgm import read_pgm

    address, _ = tpu_broker
    board = read_pgm(REPO_ROOT / "images" / "64x64.pgm")
    mid = board
    for _ in range(40):
        mid = vector_step(mid)
    ck = save_checkpoint(tmp_path / "ck.npz", mid, 40)

    p = Params(turns=100, image_width=64, image_height=64)
    events = queue.Queue()
    remote = RemoteBroker(address)
    try:
        result = run(
            p,
            events,
            None,
            broker=remote,
            images_dir=REPO_ROOT / "images",
            out_dir=tmp_path / "out",
            tick_seconds=3600,
            resume_from=ck,
        )
    finally:
        remote.close()
    assert result.turns_completed == 100
    expected = read_alive_cells(REPO_ROOT / "check" / "images" / "64x64x100.pgm")
    assert_equal_board(result.alive, expected, 64, 64)
    # the resumed run wrote the reference-named output from turn 100
    got = (tmp_path / "out" / "64x64x100.pgm").read_bytes()
    want = (REPO_ROOT / "check" / "images" / "64x64x100.pgm").read_bytes()
    assert got == want


def test_remote_resume_honors_checkpoint_rule():
    """A resumed non-Conway checkpoint must evolve under ITS rule on the
    server — the rulestring travels on the wire (in-process TpuBackend)."""
    from oracle import vector_step

    from gol_distributed_final_tpu.rpc.broker import TpuBackend

    rng = np.random.default_rng(17)
    board = np.where(rng.random((64, 64)) < 0.3, 255, 0).astype(np.uint8)
    backend = TpuBackend(use_mesh=False)
    res = backend.run(
        Request(
            world=board,
            turns=30,
            image_height=64,
            image_width=64,
            initial_turn=10,
            rulestring="B36/S23",  # HIGHLIFE
        )
    )
    assert res.turns_completed == 30
    want = board
    for _ in range(20):  # 30 - 10 resumed turns
        want = vector_step(want, birth=(3, 6), survive=(2, 3))
    np.testing.assert_array_equal(res.world, want)


def test_workers_backend_rejects_non_conway_resume():
    from gol_distributed_final_tpu.rpc.broker import WorkersBackend

    backend = WorkersBackend([])
    backend.clients = [object()]  # non-empty: reach the rule check
    with pytest.raises(RpcError, match="Conway only"):
        backend.run(
            Request(
                world=np.zeros((16, 16), np.uint8),
                turns=10,
                image_height=16,
                image_width=16,
                rulestring="B36/S23",
            )
        )


def test_broker_service_validates_resume_bounds(tpu_broker):
    """Server-side validation: initial_turn outside [0, turns] and world
    shape mismatches are rejected at the service boundary."""
    address, _ = tpu_broker
    client = RpcClient(address)
    try:
        with pytest.raises(RpcError, match="initial_turn"):
            client.call(
                Methods.BROKER_RUN,
                Request(
                    world=np.zeros((16, 16), np.uint8),
                    turns=10,
                    image_height=16,
                    image_width=16,
                    initial_turn=50,
                ),
            )
        with pytest.raises(RpcError, match="does not match params"):
            client.call(
                Methods.BROKER_RUN,
                Request(
                    world=np.zeros((16, 16), np.uint8),
                    turns=10,
                    image_height=32,
                    image_width=32,
                ),
            )
    finally:
        client.close()


def test_session_rule_reaches_remote_broker(tpu_broker, tmp_path):
    """controller.run(rule=HIGHLIFE, broker=RemoteBroker) must evolve
    HighLife ON THE SERVER — the rulestring rides the wire for explicit
    session rules, not just resumed checkpoints."""
    from oracle import vector_step

    from gol_distributed_final_tpu.models import HIGHLIFE

    address, _ = tpu_broker
    p = Params(turns=30, image_width=64, image_height=64)
    events = queue.Queue()
    remote = RemoteBroker(address)
    try:
        result = run(
            p,
            events,
            None,
            broker=remote,
            rule=HIGHLIFE,
            images_dir=REPO_ROOT / "images",
            out_dir=tmp_path / "out",
            tick_seconds=3600,
        )
    finally:
        remote.close()
    import gol_distributed_final_tpu.io.pgm as pgm

    want = pgm.read_board(p, REPO_ROOT / "images")
    for _ in range(30):
        want = vector_step(want, birth=(3, 6), survive=(2, 3))
    np.testing.assert_array_equal(result.world, want)


def test_full_board_wire_mode_golden(tmp_path):
    """The reference-EXACT wire behavior (-wire full: whole board to every
    worker, [start_y, end_y) bounds, broker/broker.go:144) against real
    worker subprocesses, landing on the turn-100 golden."""
    from gol_distributed_final_tpu.rpc.broker import WorkersBackend

    workers = [
        _spawn("gol_distributed_final_tpu.rpc.worker", "-port", "0")
        for _ in range(2)
    ]
    try:
        ports = [_wait_listening(w) for w in workers]
        backend = WorkersBackend(
            [f"127.0.0.1:{p}" for p in ports], wire="full"
        )
        import gol_distributed_final_tpu.io.pgm as pgm

        p = Params(turns=100, threads=2, image_width=16, image_height=16)
        board = pgm.read_board(p, REPO_ROOT / "images")
        result = backend.run(
            Request(
                world=board, turns=100, threads=2,
                image_width=16, image_height=16,
            )
        )
        from gol_distributed_final_tpu.ops import alive_cells

        expected = read_alive_cells(
            REPO_ROOT / "check" / "images" / "16x16x100.pgm"
        )
        assert_equal_board(alive_cells(result.world), expected, 16, 16)
    finally:
        for w in workers:
            if w.poll() is None:
                w.kill()
            w.wait()
