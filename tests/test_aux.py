"""Aux subsystems: checkpoint/resume, tracing, sharded IO, multi-host maths."""

import queue

import numpy as np
import pytest

from gol_distributed_final_tpu import Params, run
from gol_distributed_final_tpu.engine.checkpoint import (
    load_checkpoint,
    save_checkpoint,
)
from gol_distributed_final_tpu.engine.engine import Engine
from gol_distributed_final_tpu.io.pgm import read_pgm
from gol_distributed_final_tpu.io.sharded import (
    create_pgm,
    read_shard,
    write_board_sharded,
    write_rows_at,
)
from gol_distributed_final_tpu.models import HIGHLIFE
from gol_distributed_final_tpu.parallel import make_mesh
from gol_distributed_final_tpu.parallel.multihost import host_row_range

from helpers import REPO_ROOT, assert_equal_board, read_alive_cells


def test_checkpoint_roundtrip(tmp_path):
    board = np.where(np.random.default_rng(0).random((32, 48)) < 0.4, 255, 0).astype(np.uint8)
    p = save_checkpoint(tmp_path / "ck.npz", board, 123, HIGHLIFE)
    world, turn, rule = load_checkpoint(tmp_path / "ck.npz")
    np.testing.assert_array_equal(world, board)
    assert turn == 123
    assert rule.rulestring == "B36/S23"


def test_resume_equals_uninterrupted_run(tmp_path):
    """Stop at turn 40, checkpoint, resume to 100: final board and events
    must match an uninterrupted 100-turn run exactly."""
    # leg 1: run 40 turns on the engine directly
    engine = Engine()
    p40 = Params(turns=40, image_width=64, image_height=64)
    world0 = read_pgm(REPO_ROOT / "images" / "64x64.pgm")
    leg1 = engine.run(p40, world0)
    ck = save_checkpoint(tmp_path / "ck.npz", leg1.world, leg1.turns_completed)

    # leg 2: resume through the full controller to turn 100
    p100 = Params(turns=100, image_width=64, image_height=64)
    events = queue.Queue()
    result = run(
        p100,
        events,
        resume_from=tmp_path / "ck.npz",
        images_dir=REPO_ROOT / "images",
        out_dir=tmp_path / "out",
        tick_seconds=3600,
    )
    assert result.turns_completed == 100
    expected = read_alive_cells(REPO_ROOT / "check" / "images" / "64x64x100.pgm")
    assert_equal_board(result.alive, expected, 64, 64)


def test_trace_produces_profile(tmp_path):
    import jax.numpy as jnp

    from gol_distributed_final_tpu.models import CONWAY
    from gol_distributed_final_tpu.utils.trace import trace

    board = jnp.zeros((32, 32), jnp.uint8)
    with trace(tmp_path / "tr") as d:
        CONWAY.step_n(board, 3).block_until_ready()
    produced = list(d.rglob("*"))
    assert any(f.is_file() for f in produced), "no trace artifacts written"


def test_turns_per_second_meter():
    from gol_distributed_final_tpu.utils.trace import TurnsPerSecond

    m = TurnsPerSecond(cells_per_turn=512 * 512)
    m.update(100)
    assert m.turns_per_second > 0
    assert m.cell_updates_per_second == m.turns_per_second * 512 * 512


def test_sharded_pgm_roundtrip(tmp_path):
    rng = np.random.default_rng(1)
    board = np.where(rng.random((64, 48)) < 0.5, 255, 0).astype(np.uint8)
    path = tmp_path / "sharded.pgm"
    # two "hosts" write disjoint halves, out of order
    offset = create_pgm(path, 48, 64)
    write_rows_at(path, offset, 48, 32, board[32:])
    write_rows_at(path, offset, 48, 0, board[:32])
    np.testing.assert_array_equal(read_pgm(path), board)
    np.testing.assert_array_equal(read_shard(path, 16, 48), board[16:48])


def test_write_board_sharded_convenience(tmp_path):
    board = np.arange(32 * 32, dtype=np.uint32).astype(np.uint8).reshape(32, 32)
    path = tmp_path / "conv.pgm"
    write_board_sharded(path, 32, 32, [(16, board[16:]), (0, board[:16])])
    np.testing.assert_array_equal(read_pgm(path), board)


def test_host_row_range_single_process():
    # single process owns all devices => the whole board
    mesh = make_mesh((4, 2))
    assert host_row_range(mesh, 64) == (0, 64)
    mesh1d = make_mesh((8, 1))
    assert host_row_range(mesh1d, 64) == (0, 64)
    with pytest.raises(ValueError, match="does not divide"):
        host_row_range(mesh, 30)


def test_packed_checkpoint_roundtrip_and_resume(tmp_path):
    """The big-board snapshot path: checkpoint the PACKED bitboard (no
    decode — a config-5 board would be 4 GiB as bytes), resume, and the
    continuation is bit-identical to an uninterrupted evolution."""
    from gol_distributed_final_tpu.engine.checkpoint import (
        load_packed_checkpoint,
        save_packed_checkpoint,
    )
    from gol_distributed_final_tpu.ops import bitpack

    import numpy as np

    rng = np.random.default_rng(17)
    board = np.where(rng.random((128, 128)) < 0.3, 255, 0).astype(np.uint8)
    packed = bitpack.pack(board, 0)

    mid = bitpack.bit_step_n(packed, 40, 0)
    p = save_packed_checkpoint(tmp_path / "big.npz", mid, 40)
    loaded, turn, rule, word_axis = load_packed_checkpoint(p)
    assert (turn, rule.rulestring, word_axis) == (40, "B3/S23", 0)
    np.testing.assert_array_equal(loaded, np.asarray(mid))

    resumed = bitpack.bit_step_n(loaded, 60, word_axis)
    straight = bitpack.bit_step_n(packed, 100, 0)
    np.testing.assert_array_equal(np.asarray(resumed), np.asarray(straight))


def test_checkpoint_format_cross_loading_raises(tmp_path):
    """Each loader rejects the other format with an actionable error
    instead of a KeyError (mixing them up at 65536^2 would try to build a
    4 GiB host array)."""
    from gol_distributed_final_tpu.engine.checkpoint import (
        load_packed_checkpoint,
        save_packed_checkpoint,
    )
    from gol_distributed_final_tpu.ops import bitpack

    import numpy as np
    import pytest

    board = np.zeros((32, 32), np.uint8)
    bytep = save_checkpoint(tmp_path / "b.npz", board, 1)
    packp = save_packed_checkpoint(tmp_path / "p.npz", bitpack.pack(board, 0), 1)
    with pytest.raises(ValueError, match="packed-bitboard checkpoint"):
        load_checkpoint(packp)
    with pytest.raises(ValueError, match="byte-board checkpoint"):
        load_packed_checkpoint(bytep)


def test_cli_resume_session(tmp_path, monkeypatch):
    """`python -m gol_distributed_final_tpu -resume ck.npz`: the session
    continues from the checkpoint turn and the final PGM matches the
    uninterrupted golden (the reference always restarts at turn 0 —
    SURVEY.md §5; resume is the added capability, now on the CLI)."""
    import subprocess
    import sys

    from gol_distributed_final_tpu.engine import Engine, save_checkpoint
    from gol_distributed_final_tpu.engine.engine import EngineConfig
    from gol_distributed_final_tpu.io.pgm import read_pgm
    from gol_distributed_final_tpu.params import Params

    board = read_pgm(REPO_ROOT / "images" / "64x64.pgm")
    mid = Engine(EngineConfig()).run(
        Params(turns=40, image_width=64, image_height=64), board
    )
    ck = save_checkpoint(tmp_path / "ck.npz", mid.world, 40)
    import os

    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        PYTHONPATH=str(REPO_ROOT),
    )
    # no images/ in the scratch dir: resume must not need the input PGM
    r = subprocess.run(
        [sys.executable, "-m", "gol_distributed_final_tpu",
         "-w", "64", "-h", "64", "-turns", "100", "-noVis",
         "-resume", str(ck)],
        capture_output=True, text=True, timeout=240, env=env, cwd=tmp_path,
    )
    assert r.returncode == 0, r.stderr[-500:]
    raw = (tmp_path / "out" / "64x64x100.pgm").read_bytes()
    golden = (REPO_ROOT / "check" / "images" / "64x64x100.pgm").read_bytes()
    assert raw[raw.index(b"255\n") + 4:] == golden[golden.index(b"255\n") + 4:]

    # -resume with -server is supported (the checkpoint ships over the
    # wire — tests/test_rpc.py::test_remote_resume_from_checkpoint); an
    # unreachable broker must fail with a connection error, not an
    # argument-parsing rejection
    r2 = subprocess.run(
        [sys.executable, "-m", "gol_distributed_final_tpu",
         "-resume", str(ck), "-server", "127.0.0.1:1", "-noVis"],
        capture_output=True, text=True, timeout=60, env=env, cwd=tmp_path,
    )
    assert r2.returncode != 0 and "in-process" not in r2.stderr
    assert "ConnectionRefused" in r2.stderr or "refused" in r2.stderr


def test_resume_validates_shape_and_turns(tmp_path):
    """Mismatched params would mislabel the output PGM / visualiser
    window; turns at or below the checkpoint turn would run nothing under
    a contradicting filename. Both rejected up front."""
    import queue

    from gol_distributed_final_tpu import run
    from gol_distributed_final_tpu.engine import save_checkpoint
    from gol_distributed_final_tpu.params import Params

    board = np.zeros((64, 64), np.uint8)
    ck = save_checkpoint(tmp_path / "ck.npz", board, 40)
    with pytest.raises(ValueError, match="mislabel"):
        run(Params(turns=100, image_width=128, image_height=128),
            queue.Queue(), None, resume_from=ck)
    with pytest.raises(ValueError, match="not beyond"):
        run(Params(turns=40, image_width=64, image_height=64),
            queue.Queue(), None, resume_from=ck)


def test_periodic_auto_checkpoint_and_recovery(tmp_path):
    """EngineConfig(checkpoint_every=...) writes crash-recovery
    checkpoints between chunks (packed for bitboard planes — no decode);
    resuming from the last one reproduces the uninterrupted run."""
    import numpy as np

    from gol_distributed_final_tpu.engine import (
        Engine,
        load_packed_checkpoint,
    )
    from gol_distributed_final_tpu.engine.engine import EngineConfig
    from gol_distributed_final_tpu.io.pgm import read_pgm
    from gol_distributed_final_tpu.ops import bitpack
    from gol_distributed_final_tpu.ops.plane import BitPlane
    from gol_distributed_final_tpu.params import Params

    board = read_pgm(REPO_ROOT / "images" / "64x64.pgm")
    ck = tmp_path / "auto.npz"
    cfg = EngineConfig(
        final_world=False,
        min_chunk=10,
        max_chunk=10,
        checkpoint_every=30,
        checkpoint_path=str(ck),
    )
    Engine(cfg).run(
        Params(turns=100, image_width=64, image_height=64),
        None,
        plane=BitPlane(),
        initial_state=bitpack.pack(board, 0),
    )
    packed, turn, rule, word_axis = load_packed_checkpoint(ck)
    # chunks pinned to 10: crossings at 30, 60, 90; the file holds the
    # LAST mid-run overwrite — exactly 90, never the run-end turn (a
    # checkpoint-only-at-completion regression must fail here)
    assert turn == 90 and rule.rulestring == "B3/S23"
    resumed = bitpack.bit_step_n(packed, 100 - turn, word_axis)
    straight = bitpack.bit_step_n(bitpack.pack(board, 0), 100, 0)
    np.testing.assert_array_equal(np.asarray(resumed), np.asarray(straight))

    # byte-plane path: decoded checkpoint, loadable by the byte loader
    ck2 = tmp_path / "auto_byte.npz"
    cfg2 = EngineConfig(
        min_chunk=10, max_chunk=10, checkpoint_every=50,
        checkpoint_path=str(ck2), auto_fast=False,
    )
    Engine(cfg2).run(
        Params(turns=100, image_width=64, image_height=64), board
    )
    world, turn2, rule2 = load_checkpoint(ck2)
    assert turn2 == 100 and world.shape == (64, 64)  # crossings at 50, 100


def test_auto_checkpoint_stamps_active_plane_rule(tmp_path):
    """An explicit plane with a non-config rule must be recorded in the
    checkpoint — resuming a HIGHLIFE run as Conway would silently
    diverge."""
    import numpy as np

    from gol_distributed_final_tpu.engine import Engine, load_packed_checkpoint
    from gol_distributed_final_tpu.engine.engine import EngineConfig
    from gol_distributed_final_tpu.models import HIGHLIFE
    from gol_distributed_final_tpu.ops import bitpack
    from gol_distributed_final_tpu.ops.plane import BitPlane
    from gol_distributed_final_tpu.params import Params

    rng = np.random.default_rng(6)
    board = np.where(rng.random((64, 64)) < 0.3, 255, 0).astype(np.uint8)
    ck = tmp_path / "hl.npz"
    cfg = EngineConfig(
        final_world=False, min_chunk=10, max_chunk=10,
        checkpoint_every=20, checkpoint_path=str(ck),
    )
    Engine(cfg).run(
        Params(turns=50, image_width=64, image_height=64),
        None, plane=BitPlane(HIGHLIFE), initial_state=bitpack.pack(board, 0),
    )
    _, turn, rule, _ = load_packed_checkpoint(ck)
    assert rule.rulestring == HIGHLIFE.rulestring and turn == 40


def test_cli_rule_and_trace(tmp_path):
    """`-rule B36/S23` evolves HighLife (PGM matches the numpy oracle),
    `-trace-device DIR` leaves a jax.profiler trace behind — the
    reference's TestTrace role (trace_test.go:12-29) on the CLI — and
    `-trace` leaves the Chrome span trace beside the output PGM."""
    import os
    import subprocess
    import sys

    import numpy as np

    from oracle import vector_step
    from gol_distributed_final_tpu.io.pgm import read_pgm

    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=str(REPO_ROOT))
    (tmp_path / "images").mkdir()
    rng = np.random.default_rng(21)
    board = np.where(rng.random((64, 64)) < 0.3, 255, 0).astype(np.uint8)
    (tmp_path / "images" / "64x64.pgm").write_bytes(
        b"P5\n64 64\n255\n" + board.tobytes()
    )
    r = subprocess.run(
        [sys.executable, "-m", "gol_distributed_final_tpu",
         "-w", "64", "-h", "64", "-turns", "30", "-noVis",
         "-rule", "B36/S23", "-trace",
         "-trace-device", str(tmp_path / "tr")],
        capture_output=True, text=True, timeout=240, env=env, cwd=tmp_path,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    got = read_pgm(tmp_path / "out" / "64x64x30.pgm")
    want = board
    for _ in range(30):
        want = vector_step(want, birth=(3, 6), survive=(2, 3))
    np.testing.assert_array_equal(got, want)
    trace_files = list((tmp_path / "tr").rglob("*"))
    assert any(f.is_file() for f in trace_files), "no device trace written"
    import json as _json

    span_doc = _json.loads((tmp_path / "out" / "trace_64x64x30.json").read_text())
    cats = {e.get("cat") for e in span_doc["traceEvents"] if e["ph"] == "X"}
    assert "controller.session" in cats and "engine.chunk" in cats

    # -rule + -resume is rejected up front (the checkpoint's rule wins)
    r2 = subprocess.run(
        [sys.executable, "-m", "gol_distributed_final_tpu",
         "-rule", "B36/S23", "-resume", "x.npz", "-noVis"],
        capture_output=True, text=True, timeout=60, env=env, cwd=tmp_path,
    )
    assert r2.returncode != 0 and "conflicts" in r2.stderr
