"""TestAlive analogue (count_test.go:17-69): the ticker's AliveCellsCount
events must be exact against the golden per-turn CSV, the first report must
arrive within the liveness bound, and 'q' must detach cleanly mid-run.

Scaled for CI: 64x64 board, fast tick — the contract (exact counts at the
reported turn, cadence, quit semantics) is identical to the reference's
512x512 / 2 s / 100M-turn setup.
"""

import queue
import threading

import numpy as np

from gol_distributed_final_tpu import (
    AliveCellsCount,
    FinalTurnComplete,
    Params,
    StateChange,
    State,
)
from gol_distributed_final_tpu import run
from gol_distributed_final_tpu.engine.controller import CLOSED

from helpers import REPO_ROOT, read_alive_counts


def test_alive_counts_match_golden_csv(tmp_path):
    counts = read_alive_counts(REPO_ROOT / "check" / "alive" / "64x64.csv")
    initial_alive = 2819  # not in the CSV: count of images/64x64.pgm at turn 0
    p = Params(turns=100_000_000, image_width=64, image_height=64)
    events = queue.Queue()
    keypresses = queue.Queue()

    done = threading.Event()
    collected = []
    errors = []

    def consumer():
        ticks = 0
        try:
            while True:
                ev = events.get(timeout=30)
                if ev is CLOSED:
                    break
                collected.append(ev)
                if isinstance(ev, AliveCellsCount):
                    ticks += 1
                    if ticks == 5:  # after 5 correct reports, press 'q'
                        keypresses.put("q")
        except BaseException as e:  # surface thread failures to pytest
            errors.append(e)
            keypresses.put("q")  # unblock the run
        finally:
            done.set()

    t = threading.Thread(target=consumer)
    t.start()
    result = run(
        p,
        events,
        keypresses,
        images_dir=REPO_ROOT / "images",
        out_dir=tmp_path / "out",
        tick_seconds=0.2,
    )
    assert done.wait(timeout=30)
    t.join()
    assert not errors, errors

    alive_events = [e for e in collected if isinstance(e, AliveCellsCount)]
    assert len(alive_events) >= 5, "liveness: ticker must report"
    for ev in alive_events:
        # beyond the 10k-turn CSV the 64^2 board is in its steady state of
        # 101 (check/alive/64x64.csv:10001) — the reference's own test
        # asserts the steady state past the CSV the same way
        # (count_test.go:45-51); ticks land there when compile caches are
        # warm and the engine races past 10k before five ticks elapse
        expected = (
            initial_alive
            if ev.completed_turns == 0
            else counts.get(ev.completed_turns, 101)
        )
        assert ev.cells_count == expected, (
            f"turn {ev.completed_turns}: got {ev.cells_count}, want {expected}"
        )

    # 'q' semantics: StateChange{Quitting} from the ticker, then the normal
    # closing sequence with turns_completed < requested turns
    finals = [e for e in collected if isinstance(e, FinalTurnComplete)]
    assert len(finals) == 1
    assert 0 < finals[0].completed_turns < p.turns
    quits = [
        e
        for e in collected
        if isinstance(e, StateChange) and e.new_state == State.QUITTING
    ]
    assert len(quits) == 2  # one from 'q', one from the closing sequence


def _csv_sweep(size: int):
    """Every per-turn alive count for turns 1..10000 must equal the golden
    CSV line — the reference's strictest fixture, validated in full
    (count_test.go:45-51 checks every reported count against the CSV; here
    we check EVERY turn, not just the ones a ticker lands on). 32-divisible
    boards sweep on the packed plane; the 16^2 fixture (16 % 32 != 0) on
    the byte-stencil sibling — completing the fixture triple (VERDICT r4
    item 3)."""
    import jax.numpy as jnp

    from gol_distributed_final_tpu.io.pgm import read_pgm
    from gol_distributed_final_tpu.ops import bitpack, stencil

    counts = read_alive_counts(
        REPO_ROOT / "check" / "alive" / f"{size}x{size}.csv"
    )
    turns = max(counts)
    assert turns == 10_000
    board = read_pgm(REPO_ROOT / "images" / f"{size}x{size}.pgm")
    if size % 32 == 0:
        got = np.asarray(bitpack.alive_history(bitpack.pack(board), turns))
    else:
        got = np.asarray(stencil.alive_history(jnp.asarray(board), turns))
    want = np.array([counts[t] for t in range(1, turns + 1)], got.dtype)
    mismatch = np.nonzero(got != want)[0]
    assert mismatch.size == 0, (
        f"first mismatch at turn {mismatch[0] + 1}: "
        f"got {got[mismatch[0]]}, want {want[mismatch[0]]}"
    )


def test_full_10k_sweep_16():
    _csv_sweep(16)


def test_full_10k_sweep_64():
    _csv_sweep(64)


def test_full_10k_sweep_512():
    _csv_sweep(512)


def test_first_report_within_liveness_bound(tmp_path):
    """First AliveCellsCount must arrive within 5 s of start
    (count_test.go:30-38) even on a large board: chunking must not let a
    single dispatch starve the ticker."""
    import time

    p = Params(turns=100_000_000, image_width=512, image_height=512)
    events = queue.Queue()
    keypresses = queue.Queue()
    start = time.monotonic()
    errors = []

    def watcher():
        try:
            while True:
                ev = events.get(timeout=30)
                if isinstance(ev, AliveCellsCount):
                    assert time.monotonic() - start < 5.0, "first report too late"
                    return
        except BaseException as e:  # surface thread failures to pytest
            errors.append(e)
        finally:
            keypresses.put("q")  # always unblock the run

    t = threading.Thread(target=watcher)
    t.start()
    run(
        p,
        events,
        keypresses,
        images_dir=REPO_ROOT / "images",
        out_dir=tmp_path / "out",
        tick_seconds=2.0,
    )
    t.join()
    assert not errors, errors
