"""Multi-host end-to-end: a REAL 2-process jax.distributed job.

VERDICT.md round-1 item 5: two subprocesses x 4 virtual CPU devices each
join via ``jax.distributed.initialize``, shard the board over the global
('rows', 'cols') mesh, evolve 100 turns with halo ppermutes crossing the
process boundary, and stream the result to one PGM via per-host disjoint
pwrites (``host_row_range`` + io/sharded.py). The parent asserts golden
parity byte-for-byte. This is the BASELINE config-5 topology at test scale
(the reference's analogue: more worker addresses in the broker list,
broker/broker.go:288-300).
"""

import os
import socket
import subprocess
import sys

import pytest

from helpers import REPO_ROOT


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.parametrize("turns", [100])
def test_two_process_distributed_golden(tmp_path, turns):
    num_procs = 2
    coordinator = f"127.0.0.1:{_free_port()}"
    out_path = tmp_path / f"64x64x{turns}.pgm"
    procs = []
    for rank in range(num_procs):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        env["PYTHONPATH"] = str(REPO_ROOT)
        procs.append(
            subprocess.Popen(
                [
                    sys.executable,
                    str(REPO_ROOT / "tests" / "multihost_child.py"),
                    coordinator,
                    str(num_procs),
                    str(rank),
                    str(REPO_ROOT / "images"),
                    str(out_path),
                    str(turns),
                ],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
        )
    try:
        outputs = []
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outputs.append(out)
        for rank, (p, out) in enumerate(zip(procs, outputs)):
            assert p.returncode == 0, f"rank {rank} failed:\n{out}"
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()

    got = out_path.read_bytes()
    want = (REPO_ROOT / "check" / "images" / f"64x64x{turns}.pgm").read_bytes()
    assert got == want, "distributed output PGM differs from golden"


def test_two_process_pod_checkpoint_resume_streamed(tmp_path):
    """Config 5 at its real topology (VERDICT round-3 item 1): a REAL
    2-process jax.distributed job over a 2048^2 PACKED board drives the
    full pod session — per-rank streamed input, tick collectives, a
    scripted snapshot, per-rank periodic checkpoints at turn 16, a resume
    landing byte-identically, and per-rank streamed output. The parent
    verifies both outputs against an independent numpy oracle, byte for
    byte."""
    import numpy as np

    import sys as _sys

    _sys.path.insert(0, str(REPO_ROOT / "tests"))
    from oracle import vector_step

    size, turns = 2048, 20
    rng = np.random.default_rng(11)
    board = np.where(rng.random((size, size)) < 0.25, 255, 0).astype(np.uint8)
    header = b"P5\n%d %d\n255\n" % (size, size)
    (tmp_path / f"{size}x{size}.pgm").write_bytes(header + board.tobytes())

    num_procs = 2
    coordinator = f"127.0.0.1:{_free_port()}"
    procs = []
    for rank in range(num_procs):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        env["PYTHONPATH"] = str(REPO_ROOT)
        procs.append(
            subprocess.Popen(
                [
                    sys.executable,
                    str(REPO_ROOT / "tests" / "multihost_pod_child.py"),
                    coordinator,
                    str(num_procs),
                    str(rank),
                    str(tmp_path),
                    str(size),
                    str(turns),
                ],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
        )
    try:
        outputs = []
        for p in procs:
            out, _ = p.communicate(timeout=600)
            outputs.append(out)
        for rank, (p, out) in enumerate(zip(procs, outputs)):
            assert p.returncode == 0, f"rank {rank} failed:\n{out}"
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()

    want = board
    for _ in range(turns):
        want = vector_step(want)
    expected_bytes = header + want.tobytes()
    direct = (tmp_path / "out" / f"{size}x{size}x{turns}.pgm").read_bytes()
    resumed = (tmp_path / "out2" / f"{size}x{size}x{turns}.pgm").read_bytes()
    assert direct == expected_bytes, "pod output differs from oracle"
    assert resumed == expected_bytes, "resumed pod output differs"
