"""TestGol + TestPgm analogues: full runs through the public ``run`` API
asserted against the reference's committed golden fixtures
(gol_test.go:15-47, pgm_test.go:10-42)."""

import queue

import numpy as np
import pytest

from gol_distributed_final_tpu import FinalTurnComplete, Params, run
from gol_distributed_final_tpu.engine.controller import CLOSED
from gol_distributed_final_tpu.io.pgm import read_pgm

from helpers import REPO_ROOT, assert_equal_board, read_alive_cells

# the reference matrix: {16, 64, 512}^2 x {0, 1, 100} turns (gol_test.go:16-31)
MATRIX = [(size, turns) for size in (16, 64, 512) for turns in (0, 1, 100)]


def run_case(size, turns, tmp_path):
    p = Params(turns=turns, image_width=size, image_height=size)
    events = queue.Queue()
    result = run(
        p,
        events,
        images_dir=REPO_ROOT / "images",
        out_dir=tmp_path / "out",
        tick_seconds=3600,  # no ticker noise in golden runs
    )
    drained = []
    while True:
        ev = events.get_nowait()
        if ev is CLOSED:
            break
        drained.append(ev)
    return p, result, drained


@pytest.mark.parametrize("size,turns", MATRIX)
def test_gol_final_board(size, turns, tmp_path):
    p, result, events = run_case(size, turns, tmp_path)
    finals = [e for e in events if isinstance(e, FinalTurnComplete)]
    assert len(finals) == 1
    assert finals[0].completed_turns == turns
    expected = read_alive_cells(
        REPO_ROOT / "check" / "images" / f"{size}x{size}x{turns}.pgm"
    )
    assert_equal_board(finals[0].alive, expected, size, size)


@pytest.mark.parametrize("size,turns", MATRIX)
def test_pgm_output_bytes(size, turns, tmp_path):
    p, result, events = run_case(size, turns, tmp_path)
    written = read_pgm(tmp_path / "out" / f"{p.output_filename}.pgm")
    golden = read_pgm(REPO_ROOT / "check" / "images" / f"{size}x{size}x{turns}.pgm")
    np.testing.assert_array_equal(written, golden)


def test_event_sequence_tail(tmp_path):
    """The closing sequence matches gol/distributor.go:161-184:
    FinalTurnComplete -> ImageOutputComplete -> StateChange{Quitting}."""
    from gol_distributed_final_tpu import ImageOutputComplete, StateChange, State

    _, _, events = run_case(16, 1, tmp_path)
    tail = events[-3:]
    assert isinstance(tail[0], FinalTurnComplete)
    assert isinstance(tail[1], ImageOutputComplete)
    assert tail[1].filename == "16x16x1"
    assert isinstance(tail[2], StateChange)
    assert tail[2].new_state == State.QUITTING
    assert str(tail[2]) == "Quitting"


def test_non_square_session_true_hxw(tmp_path):
    """True H x W semantics through the FULL session, W != H. The
    reference conflates width/height in several allocations and in the
    kernel's wrap logic (SURVEY.md §5 quirks — invisible on its square
    inputs); here a 96x64 board must evolve correctly end to end, with
    the reference's <W>x<H> filename conventions."""
    from oracle import vector_step

    from gol_distributed_final_tpu import Params, run

    H, W, TURNS = 64, 96, 20
    rng = np.random.default_rng(41)
    board = np.where(rng.random((H, W)) < 0.3, 255, 0).astype(np.uint8)
    (tmp_path / "images").mkdir()
    (tmp_path / "images" / f"{W}x{H}.pgm").write_bytes(
        b"P5\n%d %d\n255\n" % (W, H) + board.tobytes()
    )
    p = Params(turns=TURNS, image_width=W, image_height=H)
    result = run(
        p,
        queue.Queue(),
        images_dir=tmp_path / "images",
        out_dir=tmp_path / "out",
        tick_seconds=3600,
    )
    want = board
    for _ in range(TURNS):
        want = vector_step(want)
    np.testing.assert_array_equal(result.world, want)
    got = read_pgm(tmp_path / "out" / f"{W}x{H}x{TURNS}.pgm")
    np.testing.assert_array_equal(got, want)
