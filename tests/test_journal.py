"""Durable lifecycle-journal tests (obs/journal.py, obs/history.py):
HLC properties (monotonicity under wall-clock regression, merge-order
causality, deterministic tie-break), crc-framed segment round-trips and
torn-tail detection (a SIGKILL mid-append is skipped LOUDLY, never a
crash or a silent gap), size-capped rotation with metered — never
silent — drops, incremental Status windows (``journal_since``), the
cross-process history merge, the doctor bundle's keep-all-journal
retention, and the README/event-kind registry lints.
"""

import json
import pathlib
import threading

import pytest

from gol_distributed_final_tpu.obs import history as obs_history
from gol_distributed_final_tpu.obs import journal as obs_journal
from gol_distributed_final_tpu.obs.journal import (
    EVENT_KINDS,
    HLC,
    Journal,
    hlc_key,
    read_segment,
    read_segments,
    segment_paths,
)


@pytest.fixture(autouse=True)
def _no_global_journal():
    """Every test leaves the process-global journal disabled (the
    module-level ``record`` surface must stay a cheap no-op for the
    whole tier-1 suite)."""
    yield
    obs_journal.disable()


def _fake_clock(times):
    """An injectable wall clock yielding ``times`` then holding the last
    value — the skew/regression property harness."""
    it = iter(times)
    last = [times[0]]

    def now():
        try:
            last[0] = next(it)
        except StopIteration:
            pass
        return last[0]

    return now


# -- HLC properties -----------------------------------------------------------


def test_hlc_monotonic_under_wall_regression():
    """Stamps never go backwards even when the wall clock does: physical
    holds, logical advances instead."""
    clock = HLC(node="a", now=_fake_clock([100.0, 50.0, 50.0, 99.0, 200.0]))
    stamps = [clock.tick() for _ in range(5)]
    keys = [(s[0], s[1]) for s in stamps]
    assert keys == sorted(keys)
    assert all(keys[i] < keys[i + 1] for i in range(4))
    # the regression interval rode on logical, not physical
    assert stamps[1][0] == stamps[0][0] == 100_000
    assert stamps[1][1] == stamps[0][1] + 1
    # and a real wall advance resets logical
    assert stamps[4] == [200_000, 0, "a"]


def test_hlc_merge_orders_after_remote():
    """Causality: a stamp issued after merging a remote stamp always
    sorts AFTER the remote event — even when the local wall clock is
    behind the remote's (the skewed-broker case)."""
    worker = HLC(node="worker", now=_fake_clock([100.0]))
    broker = HLC(node="broker", now=_fake_clock([40.0]))  # 60 s behind
    w_stamp = worker.tick()
    merged = broker.merge(w_stamp)
    b_stamp = broker.tick()
    assert hlc_key({"hlc": merged}) > hlc_key({"hlc": w_stamp})
    assert hlc_key({"hlc": b_stamp}) > hlc_key({"hlc": w_stamp})


def test_hlc_merge_malformed_is_noop():
    clock = HLC(node="a", now=_fake_clock([10.0]))
    before = clock.read()
    for junk in (None, [], ["x"], "nope", [1], object()):
        assert clock.merge(junk) is None
    assert clock.read() == before


def test_hlc_key_tie_break_deterministic():
    """Same (physical, logical) on two nodes: node id breaks the tie, so
    any merge order renders one timeline."""
    a = {"hlc": [5, 0, "alpha"], "seq": 1}
    b = {"hlc": [5, 0, "beta"], "seq": 1}
    c = {"hlc": [5, 1, "alpha"], "seq": 2}
    for perm in ([a, b, c], [c, b, a], [b, c, a]):
        assert sorted(perm, key=hlc_key) == [a, b, c]


def test_hlc_key_fallback_without_stamp():
    """Foreign records without a usable stamp fall back to wall-clock ms
    — ordered best-effort, never a crash."""
    assert hlc_key({"t_unix": 2.5}) == (2500, 0, "")
    assert hlc_key({}) == (0, 0, "")
    assert hlc_key({"hlc": "garbage"}) == (0, 0, "")


def test_hlc_thread_stamps_unique():
    """Concurrent ticks never mint duplicate stamps (the lock holds the
    physical/logical pair together)."""
    clock = HLC(node="a")
    stamps = []

    def spin():
        for _ in range(200):
            stamps.append(tuple(clock.tick()[:2]))

    threads = [threading.Thread(target=spin) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(set(stamps)) == len(stamps)


# -- the segment writer -------------------------------------------------------


def test_journal_round_trip(tmp_path):
    j = Journal(out_dir=tmp_path, role="engine")
    try:
        j.record("run.start", "engine", turns=100)
        j.record("chunk.commit", "engine", k=8, turn=8)
        j.record("run.end", "engine", turn=100)
        j.flush()
    finally:
        j.close()
    events, problems = read_segment(j.path)
    assert problems == []
    assert [e["kind"] for e in events] == ["run.start", "chunk.commit", "run.end"]
    assert [e["seq"] for e in events] == [1, 2, 3]
    assert events[0]["args"] == {"turns": 100}
    assert events[0]["role"] == "engine"
    # stamped, and in HLC order as written
    keys = [hlc_key(e) for e in events]
    assert keys == sorted(keys)


def test_torn_tail_detected_and_skipped(tmp_path):
    """A SIGKILL mid-append leaves a half-written final record: the crc
    frame catches it, the reader reports it LOUDLY and keeps every
    intact record — never a crash, never a silent gap."""
    j = Journal(out_dir=tmp_path, role="worker")
    try:
        for i in range(5):
            j.record("chunk.commit", "worker", turn=i)
        j.flush()
    finally:
        j.close()
    raw = j.path.read_bytes()
    j.path.write_bytes(raw[: len(raw) - 7])  # tear the final record
    events, problems = read_segment(j.path)
    assert len(events) == 4
    assert len(problems) == 1
    assert "skipped" in problems[0]
    assert str(j.path) in problems[0]


def test_flipped_byte_detected(tmp_path):
    j = Journal(out_dir=tmp_path, role="worker")
    try:
        j.record("run.start", "worker")
        j.record("run.end", "worker")
        j.flush()
    finally:
        j.close()
    raw = bytearray(j.path.read_bytes())
    # flip one byte inside the FIRST record's json payload
    raw[12] ^= 0x40
    j.path.write_bytes(bytes(raw))
    events, problems = read_segment(j.path)
    assert [e["kind"] for e in events] == ["run.end"]
    assert len(problems) == 1
    assert "crc mismatch" in problems[0]


def test_rotation_bounded_and_drops_metered(tmp_path):
    """Size-capped rotation: the generation chain never exceeds ``keep``
    segments, and retired records are METERED on the drop counter plus a
    ``journal.drop`` event — bounded retention, never silent."""
    j = Journal(out_dir=tmp_path, role="engine", rotate_bytes=1024, keep=2)
    try:
        for i in range(300):
            j.record("chunk.commit", "engine", turn=i, pad="x" * 40)
        j.flush()
        summary = j.summary()
        segs = j.segments()
    finally:
        j.close()
    assert summary["rotations"] >= 2
    assert 1 <= len(segs) <= 2
    assert summary["dropped"] > 0
    assert summary["by_kind"].get("journal.drop", 0) >= 1
    # on-disk segment names parse back through the reader surface
    assert sorted(segment_paths(tmp_path)) == sorted(segs)


def test_window_incremental(tmp_path):
    j = Journal(out_dir=tmp_path, role="broker")
    try:
        j.record("run.start", "broker")
        j.record("chunk.commit", "broker", turn=1)
        w0 = j.window(since=0)
        assert w0["seq"] == 2
        assert [e["seq"] for e in w0["events"]] == [1, 2]
        # the poller echoes the last seq it saw: only NEW events return
        j.record("chunk.commit", "broker", turn=2)
        w1 = j.window(since=w0["seq"])
        assert [e["seq"] for e in w1["events"]] == [3]
        assert j.window(since=w1["seq"])["events"] == []
        # windows are plain JSON-able (they cross the Status payload)
        json.dumps(w1)
    finally:
        j.close()


def test_window_queue_overflow_is_metered(tmp_path):
    j = Journal(out_dir=tmp_path, role="engine", queue_capacity=4)
    try:
        # the writer may drain between records; pre-empt it by holding
        # the lock is overkill — instead just assert the invariant that
        # dropped is reported in the window whenever it happens
        for i in range(64):
            j.record("chunk.commit", "engine", turn=i)
        w = j.window()
        assert w["seq"] == 64
        assert w["dropped"] >= 0  # metered, present in the payload
    finally:
        j.close()


def test_read_segments_merge_deterministic(tmp_path):
    """Two processes' segments merge into ONE HLC-ordered timeline, the
    same regardless of read order."""
    a = Journal(out_dir=tmp_path, role="broker", clock=HLC(node="broker"))
    b = Journal(out_dir=tmp_path, role="worker", clock=HLC(node="worker"))
    try:
        for i in range(5):
            a.record("chunk.commit", "broker", turn=i)
            b.record("chunk.commit", "worker", turn=i)
        a.flush()
        b.flush()
        pa, pb = a.path, b.path
    finally:
        a.close()
        b.close()
    ev1, pr1 = read_segments([pa, pb])
    ev2, pr2 = read_segments([pb, pa])
    assert pr1 == pr2 == []
    assert [hlc_key(e) for e in ev1] == [hlc_key(e) for e in ev2]
    assert [e["seq"] for e in ev1] == [e["seq"] for e in ev2]
    # the directory form reads the same set
    ev3, _ = read_segments(tmp_path)
    assert len(ev3) == len(ev1) == 10


# -- the process-global surface -----------------------------------------------


def test_module_record_noop_when_disabled(tmp_path):
    assert not obs_journal.enabled()
    obs_journal.record("run.start", "engine")  # must not raise
    assert obs_journal.window() is None
    assert obs_journal.summary() is None


def test_module_enable_disable(tmp_path):
    j = obs_journal.enable(out_dir=tmp_path, role="engine")
    try:
        assert obs_journal.enabled()
        assert obs_journal.journal() is j
        # the global journal shares the process HLC with the RPC stamps
        assert j.clock is obs_journal.clock()
        obs_journal.record("run.start", "engine", turns=5)
        assert obs_journal.window()["seq"] == 1
        assert obs_journal.summary()["by_kind"] == {"run.start": 1}
    finally:
        obs_journal.disable()
    assert not obs_journal.enabled()
    events, problems = read_segment(j.path)
    assert problems == []
    assert [e["kind"] for e in events] == ["run.start"]


def test_flush_on_crash_records_final_event(tmp_path):
    j = obs_journal.enable(out_dir=tmp_path, role="worker")
    obs_journal.record("run.start", "worker")
    obs_journal.flush_on_crash(RuntimeError("boom"))
    obs_journal.disable()
    events, problems = read_segment(j.path)
    assert problems == []
    assert [e["kind"] for e in events] == ["run.start", "crash"]
    assert events[-1]["name"] == "RuntimeError"
    assert events[-1]["args"]["message"] == "boom"


def test_rpc_stamp_observe_round_trip():
    """The wire surface: stamp() mints, observe() merges — a stamp
    minted after observing a remote one orders after it."""
    remote = [obs_journal.clock().read()[0] + 5000, 3, "remote"]
    obs_journal.observe(remote)
    local = obs_journal.stamp()
    assert hlc_key({"hlc": local}) > hlc_key({"hlc": remote})
    obs_journal.observe(None)  # skewed peer without the field: no-op


# -- history: the cross-process merge -----------------------------------------


def _seed_segments(tmp_path):
    """Three processes' worth of a loss/recovery story, written through
    real journals with a shared causal chain."""
    bclock = HLC(node="broker-1")
    # distinct roles: two journals in ONE test process would otherwise
    # share the journal_<role>_<pid>.jsonl path (real deployments get a
    # pid each)
    w0 = Journal(out_dir=tmp_path, role="worker0", clock=HLC(node="worker-0"))
    w1 = Journal(out_dir=tmp_path, role="worker1", clock=HLC(node="worker-1"))
    br = Journal(out_dir=tmp_path, role="broker", clock=bclock)
    try:
        br.record("run.start", "broker", turns=64)
        br.record("session.admit", "7", tenant="t7", turns=64)
        w0.record("run.start", "worker", index=0)
        w1.record("run.start", "worker", index=1)
        w0.record("chunk.commit", "worker", k=8, turn=8)
        # the broker observes worker-0's reply, then loses worker-1
        bclock.merge(w0.clock.read())
        br.record("chunk.commit", "sessions", k=8)
        br.record("worker.lost", "127.0.0.1:9001", reason="probe timeout")
        br.record("recovery.resplit", "resident", lost=1, remaining=1)
        br.record("worker.readmit", "127.0.0.1:9001", connected=True)
        br.record("session.final", "7", turn=64, tenant="t7")
        for j in (w0, w1, br):
            j.flush()
    finally:
        for j in (w0, w1, br):
            j.close()


def test_history_merge_spans_processes(tmp_path):
    _seed_segments(tmp_path)
    hist = obs_history.build_history("t", out_dir=tmp_path)
    assert hist["problems"] == []
    assert len(hist["nodes"]) == 3
    kinds = [e["kind"] for e in hist["events"]]
    # the causal chain: the broker's commit (which observed worker-0's
    # stamp) and everything after it sort after worker-0's commit
    w0_commit = next(
        i for i, e in enumerate(hist["events"])
        if e["kind"] == "chunk.commit" and "worker-0" in str(e.get("hlc"))
    )
    br_commit = kinds.index("chunk.commit", w0_commit + 1)
    assert br_commit > w0_commit
    assert kinds.index("worker.lost") < kinds.index("recovery.resplit")
    assert kinds.index("recovery.resplit") < kinds.index("worker.readmit")
    assert kinds.index("session.admit") < kinds.index("session.final")
    # render + artifact round-trip
    text = obs_history.render(hist)
    assert "worker.lost" in text and "3 process(es)" in text
    path = obs_history.write_history(hist, tmp_path)
    assert json.loads(path.read_text())["events_total"] == hist["events_total"]


def test_history_filters(tmp_path):
    _seed_segments(tmp_path)
    by_tenant = obs_history.build_history("t", out_dir=tmp_path, tenant="t7")
    assert {e["kind"] for e in by_tenant["events"]} == {
        "session.admit", "session.final"
    }
    by_addr = obs_history.build_history(
        "t", out_dir=tmp_path, address="127.0.0.1:9001"
    )
    assert {e["kind"] for e in by_addr["events"]} == {
        "worker.lost", "worker.readmit"
    }


def test_history_dedups_live_and_segment(tmp_path):
    """The same event seen via a live Status window AND the flushed
    segment appears once in the merge."""
    j = Journal(out_dir=tmp_path, role="broker", clock=HLC(node="b"))
    try:
        j.record("run.start", "broker")
        j.flush()
        live = j.window()["events"]
        seg_events, _ = read_segment(j.path)
    finally:
        j.close()
    merged = obs_history.merge_events(seg_events, live)
    assert len(merged) == 1


def test_history_reports_torn_tail_loudly(tmp_path):
    _seed_segments(tmp_path)
    victim = segment_paths(tmp_path)[0]
    raw = victim.read_bytes()
    victim.write_bytes(raw[: len(raw) - 5])
    hist = obs_history.build_history("t", out_dir=tmp_path)
    assert any("skipped" in p for p in hist["problems"])
    assert "PROBLEMS" in obs_history.render(hist)


def test_history_cli_from_dead_segments(tmp_path, capsys):
    _seed_segments(tmp_path)
    rc = obs_history.main(["postmortem", "-dir", str(tmp_path)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "worker.lost" in out
    assert (tmp_path / "history_postmortem.json").exists()


def test_history_cli_empty_dir_fails(tmp_path, capsys):
    assert obs_history.main(["empty", "-dir", str(tmp_path)]) == 1


# -- doctor bundle retention --------------------------------------------------


def test_bundle_keeps_all_journal_generations(tmp_path):
    """The incident bundle collects EVERY journal generation but caps
    other artifact classes at newest-3, naming what it dropped in the
    manifest — an incomplete bundle never masquerades as complete."""
    from gol_distributed_final_tpu.obs.doctor import write_bundle

    for gen in ("", ".g1", ".g2", ".g3", ".g4"):
        (tmp_path / f"journal_broker_123{gen}.jsonl").write_text("")
    for i in range(5):
        (tmp_path / f"trace_run{i}.json").write_text("{}")
    bdir = write_bundle([], {}, out_dir=tmp_path)
    names = {p.name for p in bdir.iterdir()}
    assert sum(1 for n in names if n.startswith("journal_")) == 5
    assert sum(1 for n in names if n.startswith("trace_")) == 3
    manifest = json.loads((bdir / "manifest.json").read_text())
    dropped = manifest["dropped"]
    assert len(dropped) == 2
    assert all(d["kind"] == "trace" for d in dropped)
    assert all("newest-3" in d["why"] for d in dropped)


# -- registry + doc lints -----------------------------------------------------


def test_every_emitted_kind_is_declared():
    """The registry-drift lint over the real tree: every literal kind at
    a ``journal.record(...)`` site anywhere in the package exists in
    EVENT_KINDS."""
    from gol_distributed_final_tpu.obs.lint import undeclared_journal_kinds

    assert undeclared_journal_kinds() == []


def test_drift_lint_catches_undeclared_kind(tmp_path):
    from gol_distributed_final_tpu.obs.lint import undeclared_journal_kinds

    (tmp_path / "rogue.py").write_text(
        '_journal.record("totally.new.kind", "x")\n'
    )
    missing = undeclared_journal_kinds(package_root=tmp_path)
    assert len(missing) == 1
    assert "totally.new.kind" in missing[0]


def test_journal_docs_lint():
    """The README "Journal & history" section documents the journal
    meters and knobs, and every declared event kind."""
    from gol_distributed_final_tpu.obs.lint import (
        _readme_section,
        undocumented_journal_names,
    )

    assert undocumented_journal_names() == []
    section = _readme_section(None, "## Journal & history")
    missing = [k for k in EVENT_KINDS if k not in section]
    assert missing == [], f"event kinds missing from the README table: {missing}"


def test_journal_metrics_registered():
    from gol_distributed_final_tpu.obs import instruments  # noqa: F401
    from gol_distributed_final_tpu.obs.metrics import registry

    names = {f.name for f in registry().families()}
    for n in (
        "gol_journal_events_total",
        "gol_journal_bytes_total",
        "gol_journal_rotations_total",
        "gol_journal_drops_total",
    ):
        assert n in names
