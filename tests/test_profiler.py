"""Continuous-profiler tests (obs/profiler.py, obs/flame.py): bounded
trie/table folds, adaptive cadence backoff + decay, incremental
``profile_since`` windows (skew-safe, old-pickle posture), collapsed +
speedscope artifact round-trips through the flame loader, diff math,
the doctor hotspot join, GC-pause metering, the regress cross-round
gates, bundle collection, history time-windows, the hygiene gc-callback
checker — and one live drill: a sleep-slowed worker on a resident
cluster is NAMED by the doctor and flagged by the flame diff.
"""

import gc
import json
import os
import pathlib
import subprocess
import sys
import time

import numpy as np
import pytest

from gol_distributed_final_tpu.obs import flame
from gol_distributed_final_tpu.obs import metrics as obs_metrics
from gol_distributed_final_tpu.obs import profiler as obs_profiler
from gol_distributed_final_tpu.obs.profiler import (
    ContinuousProfiler,
    frame_name,
    is_idle_frame,
)
from gol_distributed_final_tpu.obs.status import scalar_value, series_map

from helpers import REPO_ROOT


@pytest.fixture
def live_metrics():
    """Enable the process-global registry for one test, zeroed before and
    disabled+zeroed after (the test_slo.py posture)."""
    reg = obs_metrics.registry()
    reg.reset()
    obs_metrics.enable()
    yield reg
    obs_metrics.enable(False)
    reg.reset()


@pytest.fixture(autouse=True)
def _no_global_profiler():
    """Every test leaves the process-global profiler OFF — a leaked
    sampler thread would poison every later test's timing."""
    yield
    obs_profiler.disable()


def _stack(*frames):
    """[("f", "pkg/f.py", 1), ...] root-first from 'f' names."""
    return [(f, f"pkg/{f}.py", i + 1) for i, f in enumerate(frames)]


def _tick(p, stacks, n=1):
    seq = 0
    for _ in range(n):
        seq = p.sample_once(cost=0.0, stacks=stacks)
    return seq


# -- sampling: the trie + flat table ------------------------------------------


class TestSampling:
    def test_injected_stacks_deterministic(self):
        p = ContinuousProfiler(10.0)
        _tick(p, [("main", _stack("a", "b"))], n=3)
        rows = p.hot_frames()
        assert rows[0]["func"] == "b" and rows[0]["self"] == 3
        assert rows[0]["cum"] == 3
        a = next(r for r in rows if r["func"] == "a")
        assert a["self"] == 0 and a["cum"] == 3
        w = p.window(0)
        assert w["stacks"] == 3 and w["threads"] == ["main"]

    def test_recursion_counts_once_per_stack(self):
        p = ContinuousProfiler(10.0)
        rec = [("f", "pkg/f.py", 1), ("f", "pkg/f.py", 1)]
        _tick(p, [("main", rec)], n=2)
        row = next(r for r in p.hot_frames() if r["func"] == "f")
        assert row["cum"] == 2  # not 4: recursion counts once per stack
        assert row["self"] == 2

    def test_trie_node_cap_folds_to_other(self):
        p = ContinuousProfiler(10.0, max_nodes=8, max_frames=512)
        for i in range(50):
            _tick(p, [("main", [(f"fn{i}", "pkg/m.py", i + 1)])])
        w = p.window(0)
        # the root + at most max_nodes children + the one <other> bucket
        assert w["nodes"] <= p.max_nodes + 1
        assert w["stacks"] == 50  # no sample is dropped, only folded
        assert any("<other>" in line for line in p.collapsed_lines())

    def test_flat_table_cap_folds_to_other(self):
        p = ContinuousProfiler(10.0, max_nodes=4096, max_frames=8)
        for i in range(50):
            _tick(p, [("main", [(f"fn{i}", "pkg/m.py", i + 1)])])
        rows = p.hot_frames(top=1000)
        assert len(rows) <= 9  # 8 real frames + the <other> bucket
        other = next(r for r in rows if r["func"] == "<other>")
        assert other["self"] >= 42  # the folded tail's self hits land there

    def test_adaptive_backoff_doubles_and_meters(self, live_metrics):
        p = ContinuousProfiler(10.0, budget=0.01)
        p.sample_once(cost=1.0, stacks=[])  # ewma 0.2s >> 1% of 10ms
        assert p.period_s == pytest.approx(0.02)
        for _ in range(10):
            p.sample_once(cost=1.0, stacks=[])
        assert p.period_s == pytest.approx(p.max_period_s)  # capped
        w = p.window(0)
        assert w["backoffs"] >= 1
        snap = live_metrics.snapshot()
        assert scalar_value(snap, "gol_profile_backoffs_total") >= 1
        assert scalar_value(snap, "gol_profile_samples_total") >= 11

    def test_adaptive_decay_returns_to_base(self):
        p = ContinuousProfiler(10.0, budget=0.01)
        p.sample_once(cost=1.0, stacks=[])
        assert p.period_s > p.base_period_s
        for _ in range(300):
            p.sample_once(cost=0.0, stacks=[])
        assert p.period_s == pytest.approx(p.base_period_s)
        assert p.window(0)["backoffs"] >= 1  # history is not erased

    def test_window_incremental_since(self):
        p = ContinuousProfiler(10.0)
        _tick(p, [("main", _stack("a"))])
        w1 = p.window(0)
        assert [r["func"] for r in w1["frames"]] == ["a"]
        # nothing moved since: the incremental window ships no frames
        assert p.window(w1["seq"])["frames"] == []
        _tick(p, [("main", _stack("b"))])
        w2 = p.window(w1["seq"])
        assert [r["func"] for r in w2["frames"]] == ["b"]  # only the mover
        assert w2["seq"] == w1["seq"] + 1
        # the head still rides every window, frames or not
        assert w2["stacks"] == 2 and w2["schema"] == "gol-profile/1"

    def test_window_is_json_serializable(self):
        p = ContinuousProfiler(10.0)
        _tick(p, [("main", _stack("a", "b"))], n=2)
        doc = json.loads(json.dumps(p.window(0)))
        assert doc["schema"] == "gol-profile/1"
        assert doc["gc"]["tracked"] is False

    def test_summary_caps_frames_at_ten(self):
        p = ContinuousProfiler(10.0)
        for i in range(20):
            _tick(p, [("main", [(f"fn{i}", "pkg/m.py", 1)])])
        assert len(p.summary()["frames"]) == 10
        assert len(p.window(0)["frames"]) == 20

    def test_hot_stacks_leaf_paths(self):
        p = ContinuousProfiler(10.0)
        _tick(p, [("main", _stack("a", "b"))], n=3)
        _tick(p, [("main", _stack("a", "c"))], n=1)
        rows = p.hot_stacks()
        assert rows[0]["self"] == 3
        assert rows[0]["stack"].endswith("b (pkg/b.py:2)")
        assert "a (pkg/a.py:1)" in rows[0]["stack"]

    def test_constructor_validation(self):
        with pytest.raises(ValueError):
            ContinuousProfiler(0.0)
        with pytest.raises(ValueError):
            ContinuousProfiler(10.0, max_nodes=2)

    def test_real_stack_extraction_names_this_test(self):
        """No injection: a real sample of a live helper thread must name
        the helper's own function."""
        import threading

        stop = threading.Event()

        def profiler_target_spin():
            while not stop.is_set():
                sum(range(50))

        t = threading.Thread(target=profiler_target_spin, daemon=True)
        t.start()
        try:
            p = ContinuousProfiler(10.0)
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                p.sample_once(cost=0.0)
                if any(
                    "profiler_target_spin" in r["func"]
                    for r in p.hot_frames(top=1000)
                ):
                    break
            else:
                pytest.fail("live thread never sampled by name")
        finally:
            stop.set()
            t.join()


# -- artifacts: collapsed + speedscope ----------------------------------------


class TestArtifacts:
    def _profiled(self):
        p = ContinuousProfiler(10.0)
        _tick(p, [("main", _stack("a", "b"))], n=2)
        _tick(p, [("main", _stack("a"))], n=1)
        return p

    def test_collapsed_golden(self):
        p = self._profiled()
        assert p.collapsed_lines() == [
            "main;a (pkg/a.py:1) 1",
            "main;a (pkg/a.py:1);b (pkg/b.py:2) 2",
        ]

    def test_write_artifacts_tmp_then_rename(self, tmp_path):
        p = self._profiled()
        paths = p.write_artifacts(str(tmp_path), "t1")
        assert [x.name for x in paths] == [
            "profile_t1.collapsed", "profile_t1.speedscope.json",
        ]
        assert all(x.exists() for x in paths)
        assert not list(tmp_path.glob("*.tmp"))

    def test_speedscope_schema(self):
        doc = self._profiled().speedscope_dict("x")
        assert doc["$schema"] == (
            "https://www.speedscope.app/file-format-schema.json"
        )
        assert {f["name"] for f in doc["shared"]["frames"]} == {"a", "b"}
        prof = doc["profiles"][0]
        assert prof["type"] == "sampled" and prof["name"] == "main"
        assert len(prof["samples"]) == len(prof["weights"])
        assert prof["endValue"] == sum(prof["weights"]) == 3

    def test_collapsed_roundtrip_through_flame(self, tmp_path):
        paths = self._profiled().write_artifacts(str(tmp_path), "rt")
        prof = flame.load_collapsed(paths[0])
        assert prof["total"] == 3
        assert prof["frames"]["b (pkg/b.py:2)"] == [2, 2]
        assert prof["frames"]["a (pkg/a.py:1)"] == [1, 3]

    def test_speedscope_roundtrip_matches_collapsed(self, tmp_path):
        paths = self._profiled().write_artifacts(str(tmp_path), "rt")
        a = flame.load_collapsed(paths[0])
        b = flame.load_speedscope(paths[1])
        assert a["total"] == b["total"]
        assert a["frames"] == b["frames"]

    def test_parse_frame_inverts_frame_name(self):
        name = frame_name("step", "/x/y/gol_distributed_final_tpu/ops/k.py", 7)
        assert flame.parse_frame(name) == (
            "step", "gol_distributed_final_tpu/ops/k.py", 7
        )
        assert flame.parse_frame("just_a_name") == ("just_a_name", "", 0)

    def test_is_idle_frame_semantics(self):
        assert is_idle_frame("wait", "pkg/anything.py")
        assert is_idle_frame("step", "/usr/lib/python3/threading.py")
        # the rpc frame pump parks in sock.recv/sendall: wire-wait, not work
        assert is_idle_frame(
            "recv_frame_sized", "gol_distributed_final_tpu/rpc/protocol.py"
        )
        assert not is_idle_frame(
            "fault_point", "gol_distributed_final_tpu/rpc/faults.py"
        )


# -- flame: merge / tables / diffs --------------------------------------------


def _prof(source, total, frames):
    return {"source": source, "total": total,
            "frames": {k: list(v) for k, v in frames.items()}}


class TestFlame:
    def test_merge_profiles(self):
        m = flame.merge_profiles([
            _prof("x", 10, {"a": (5, 10), "b": (5, 5)}),
            _prof("y", 10, {"a": (2, 2)}),
        ])
        assert m["total"] == 20
        assert m["frames"]["a"] == [7, 12] and m["frames"]["b"] == [5, 5]

    def test_hot_rows_shares_and_active_filter(self):
        prof = _prof("x", 10, {
            "work (pkg/w.py:1)": (6, 6),
            "wait (threading.py:1)": (4, 4),
        })
        rows = flame.hot_rows(prof)
        assert rows[0]["frame"].startswith("work")
        assert rows[0]["self_share"] == pytest.approx(0.6)
        assert rows[1]["idle"] is True
        active = flame.hot_rows(prof, active_only=True)
        assert [r["frame"] for r in active] == ["work (pkg/w.py:1)"]

    def test_diff_math_and_sort(self):
        old = _prof("old", 100, {"a": (50, 50), "b": (50, 50)})
        new = _prof("new", 100, {"a": (80, 80), "c": (20, 20)})
        movers = flame.diff_profiles(old, new)
        assert [m["frame"] for m in movers] == ["a", "c", "b"]
        assert movers[0]["delta_pp"] == pytest.approx(30.0)
        assert movers[1]["old_share"] == 0.0  # absent side diffs vs zero
        assert movers[2]["delta_pp"] == pytest.approx(-50.0)

    def test_diff_noise_floor(self):
        old = _prof("old", 1000, {"a": (500, 500), "b": (500, 500)})
        new = _prof("new", 1000, {"a": (503, 503), "b": (497, 497)})
        assert flame.diff_profiles(old, new, noise_pp=0.5) == []
        assert len(flame.diff_profiles(old, new, noise_pp=0.1)) == 2

    def test_from_window(self):
        p = ContinuousProfiler(10.0)
        _tick(p, [("main", _stack("a", "b"))], n=2)
        prof = flame.from_window(p.window(0), source="t")
        assert prof["total"] == 2
        assert prof["frames"]["b (pkg/b.py:2)"] == [2, 2]

    def test_load_bench_round(self, tmp_path):
        doc = {"c7_profile": {
            "per_turn_us": 12.0,
            "profile_hot": [
                {"frame": "step (ops/k.py:3)", "self_share": 0.62},
                {"frame": "dumps (rpc/protocol.py:9)", "self_share": 0.2},
            ],
        }}
        path = tmp_path / "BENCH_r01.json"
        path.write_text(json.dumps(doc))
        prof = flame.load_bench_round(path)
        assert prof["total"] == 10000
        assert prof["frames"]["step (ops/k.py:3)"] == [6200, 0]
        # and the generic source dispatcher routes BENCH*.json here
        assert flame.load_source(str(path))["frames"] == prof["frames"]

    def test_render_table_and_diff_render(self):
        prof = _prof("x", 10, {"work (pkg/w.py:1)": (6, 6)})
        out = flame.render_table(prof)
        assert "work (pkg/w.py:1)" in out and "60.0%" in out
        movers = flame.diff_profiles(
            _prof("o", 10, {"a": (1, 1)}), _prof("n", 10, {"a": (9, 9)})
        )
        text = flame.render_diff(movers, _prof("o", 10, {}),
                                 _prof("n", 10, {}))
        assert "+80.00pp" in text and "a" in text


# -- gc-pause metering --------------------------------------------------------


class TestGcMetering:
    def test_gc_pause_metering_and_removal(self, live_metrics):
        p = ContinuousProfiler(10.0)
        p.install_gc()
        try:
            gc.collect()
            w = p.window(0)
            assert w["gc"]["tracked"] is True
            assert w["gc"]["pauses"] >= 1
            assert w["gc"]["max_pause_s"] >= 0.0
            snap = live_metrics.snapshot()
            pause = series_map(snap, "gol_gc_pause_seconds")
            assert pause and pause[()]["count"] >= 1
            gens = series_map(snap, "gol_gc_collections_total")
            assert gens  # labelled by generation
        finally:
            p.remove_gc()
        assert p._gc_callback not in gc.callbacks
        assert p.window(0)["gc"]["tracked"] is False

    def test_gc_callback_is_lock_free_under_registry_lock(
        self, live_metrics
    ):
        """A collection can trigger at any allocation, so the hook can
        preempt a thread already inside ``metrics.snapshot()`` — it must
        finish WITHOUT taking the registry lock (the old direct
        ``observe()`` self-deadlocked a live worker's Status thread),
        deferring the histogram rows to the next tick's flush."""
        p = ContinuousProfiler(10.0)
        p.install_gc()
        try:
            with live_metrics._lock:  # what snapshot() holds
                gc.collect()          # old code: deadlocks right here
            p.sample_once(cost=0.0, stacks=[])  # drains deferred rows
            snap = live_metrics.snapshot()
            pause = series_map(snap, "gol_gc_pause_seconds")
            assert pause and pause[()]["count"] >= 1
        finally:
            p.remove_gc()

    def test_gc_pause_rule_in_default_book(self):
        from gol_distributed_final_tpu.obs.slo import (
            DEFAULT_RULE_NAMES,
            default_rules,
        )

        assert "gc-pause" in DEFAULT_RULE_NAMES
        rule = next(r for r in default_rules() if r.name == "gc-pause")
        assert rule.metric == "gol_gc_pause_seconds"


# -- the module-global lifecycle ----------------------------------------------


class TestModuleLifecycle:
    def test_enable_disable(self, tmp_path):
        before = len(gc.callbacks)
        p = obs_profiler.enable(
            period_ms=50.0, out_dir=str(tmp_path), tag="t",
            start_thread=False,
        )
        try:
            assert obs_profiler.enabled() and obs_profiler.profiler() is p
            assert len(gc.callbacks) == before + 1  # track_gc default on
            assert obs_metrics.registry() is not None
            p.sample_once(cost=0.0, stacks=[("main", _stack("a"))])
            assert obs_profiler.window(0)["stacks"] == 1
            assert len(obs_profiler.summary()["frames"]) == 1
        finally:
            obs_profiler.disable()
        assert not obs_profiler.enabled()
        assert len(gc.callbacks) == before  # the pairing hygiene enforces
        assert obs_profiler.window() is None
        assert obs_profiler.summary() is None
        obs_metrics.enable(False)
        obs_metrics.registry().reset()

    def test_shutdown_writes_run_end_artifacts(self, tmp_path):
        p = obs_profiler.enable(
            period_ms=50.0, out_dir=str(tmp_path), tag="end",
            track_gc=False, start_thread=False,
        )
        p.sample_once(cost=0.0, stacks=[("main", _stack("a"))])
        obs_profiler.shutdown()
        assert (tmp_path / "profile_end.collapsed").exists()
        assert (tmp_path / "profile_end.speedscope.json").exists()
        obs_profiler.shutdown()  # disabled: a no-op, never a raise
        obs_metrics.enable(False)
        obs_metrics.registry().reset()

    def test_flush_on_crash_never_raises(self, tmp_path):
        p = obs_profiler.enable(
            period_ms=50.0, out_dir=str(tmp_path), tag="t",
            track_gc=False, start_thread=False,
        )
        p.sample_once(cost=0.0, stacks=[("main", _stack("a"))])
        obs_profiler.flush_on_crash(ValueError("boom"))
        assert (tmp_path / "profile_crash_t.collapsed").exists()
        obs_profiler.disable()
        obs_profiler.flush_on_crash(ValueError("boom"))  # off: no-op
        obs_metrics.enable(False)
        obs_metrics.registry().reset()

    def test_daemon_thread_samples_on_its_own(self, tmp_path):
        obs_profiler.enable(
            period_ms=2.0, out_dir=str(tmp_path), tag="t",
            track_gc=False,
        )
        try:
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                w = obs_profiler.window(0)
                if w and w["stacks"] >= 5:
                    break
                time.sleep(0.01)
            else:
                pytest.fail("daemon sampler never folded a stack")
        finally:
            obs_profiler.disable()
            obs_metrics.enable(False)
            obs_metrics.registry().reset()


# -- Status integration: the skew-safe profile_since round-trip ---------------


class TestStatusWindow:
    def test_status_payload_embeds_incremental_window(self, live_metrics):
        from gol_distributed_final_tpu.obs.report import status_payload

        p = obs_profiler.enable(period_ms=50.0, track_gc=False,
                                start_thread=False)
        p.sample_once(cost=0.0, stacks=[("main", _stack("a"))])
        payload = status_payload(role="test", profile_since=0)
        assert payload["profile"]["frames"][0]["func"] == "a"
        seq = payload["profile"]["seq"]
        again = status_payload(role="test", profile_since=seq)
        assert again["profile"]["frames"] == []  # nothing moved since
        obs_profiler.disable()
        assert "profile" not in status_payload(role="test", profile_since=0)

    def test_old_pickle_without_profile_since_gets_full_window(self):
        """A Request unpickled from a pre-profiler peer has NO
        profile_since attribute — the handlers' getattr posture must
        read it as 0 (the full window), never raise."""
        from gol_distributed_final_tpu.rpc.protocol import Request

        req = Request()
        assert req.profile_since == 0  # current default asks for all
        old = Request()
        del old.profile_since  # the old-pickle shape: field absent
        psince = getattr(old, "profile_since", 0)
        assert psince == 0

    def test_watch_profile_panel_pure_render(self):
        from gol_distributed_final_tpu.obs.watch import _profile_lines

        payload = {"profile": {
            "seq": 7, "stacks": 100, "period_ms": 10.0, "backoffs": 1,
            "gc": {"pauses": 2, "pause_s": 0.01, "max_pause_s": 0.008},
            "frames": [
                {"func": "wait", "file": "threading.py", "line": 1,
                 "self": 60, "cum": 60},
                {"func": "hot", "file": "pkg/h.py", "line": 3,
                 "self": 30, "cum": 40},
            ],
        }}
        lines = _profile_lines(payload)
        assert "PROFILE" in lines[0] and "backoff" in lines[0]
        assert any("gc: 2 pause(s)" in l for l in lines)
        body = "\n".join(lines)
        assert "hot" in body and "wait" not in body  # busy view only
        assert _profile_lines({"metrics": {}}) == []  # no window: no panel


# -- doctor: the hotspot join -------------------------------------------------


def _hot_status(frames, stacks=100, hot_stacks=(), metrics=None):
    return {"worker 127.0.0.1:9999": {
        "pid": 1, "role": "worker", "metrics_enabled": True,
        "metrics": metrics or {},
        "profile": {
            "schema": "gol-profile/1", "seq": 50, "stacks": stacks,
            "period_ms": 10.0, "frames": frames,
            "hot_stacks": list(hot_stacks),
        },
    }}


class TestDoctorHotspot:
    def test_hotspot_named_from_profile_window(self):
        from gol_distributed_final_tpu.obs.doctor import diagnose

        statuses = _hot_status(
            [
                {"func": "wait", "file": "threading.py", "line": 1,
                 "self": 500, "cum": 500},  # parked: excluded
                {"func": "serialize", "file": "rpc/protocol.py", "line": 9,
                 "self": 60, "cum": 80},
                {"func": "misc", "file": "pkg/m.py", "line": 2,
                 "self": 10, "cum": 10},
            ],
            hot_stacks=[{"stack": "main;run;serialize (rpc/protocol.py:9)",
                         "self": 60}],
        )
        findings = diagnose(statuses)
        hot = next(f for f in findings if f["title"].startswith("hotspot"))
        assert "serialize" in hot["title"] and "86%" in hot["title"]
        assert any("hot path" in e for e in hot["evidence"])
        assert "flame -diff" in hot["detail"]

    def test_hotspot_joins_segment_decomposition(self, monkeypatch):
        from gol_distributed_final_tpu.obs import doctor as obs_doctor
        from gol_distributed_final_tpu.obs import perf as obs_perf

        monkeypatch.setattr(
            obs_perf, "decomposition_summary",
            lambda snap: {"broker": {
                "host_prep": {"share": 0.58, "seconds": 1.0},
                "_total": {"share": 1.0},
            }},
        )
        statuses = _hot_status([
            {"func": "dumps", "file": "rpc/protocol.py", "line": 9,
             "self": 71, "cum": 71},
        ])
        hot = next(
            f for f in obs_doctor.diagnose(statuses)
            if f["title"].startswith("hotspot")
        )
        assert "host_prep" in hot["detail"] and "58%" in hot["detail"]
        assert any("gol_turn_segment_seconds" in e for e in hot["evidence"])

    def test_no_hotspot_below_concentration_or_sample_floor(self):
        from gol_distributed_final_tpu.obs.doctor import diagnose

        spread = _hot_status([
            {"func": f"f{i}", "file": "pkg/m.py", "line": i,
             "self": 20, "cum": 20} for i in range(5)
        ])  # top busy share 0.2 < 0.25
        assert not any(
            f["title"].startswith("hotspot") for f in diagnose(spread)
        )
        few = _hot_status(
            [{"func": "hot", "file": "pkg/h.py", "line": 1,
              "self": 10, "cum": 10}],
            stacks=10,  # below the 20-stack honesty floor
        )
        assert not any(
            f["title"].startswith("hotspot") for f in diagnose(few)
        )

    def test_all_idle_profile_yields_no_hotspot(self):
        from gol_distributed_final_tpu.obs.doctor import diagnose

        parked = _hot_status([
            {"func": "wait", "file": "threading.py", "line": 1,
             "self": 900, "cum": 900},
            {"func": "select", "file": "selectors.py", "line": 1,
             "self": 100, "cum": 100},
        ], stacks=1000)
        assert not any(
            f["title"].startswith("hotspot") for f in diagnose(parked)
        )


# -- bundle: profile artifacts + uniform dropped stamps -----------------------


class TestBundleProfiles:
    def test_bundle_collects_profiles_and_stamps_caps(self, tmp_path):
        from gol_distributed_final_tpu.obs.doctor import write_bundle

        for i in range(8):  # two past the keep=6 cap
            f = tmp_path / f"profile_w{i}.collapsed"
            f.write_text("main;a (pkg/a.py:1) 1\n")
            mtime = time.time() - (8 - i) * 10
            os.utime(f, (mtime, mtime))
        (tmp_path / "profile_w0.speedscope.json").write_text("{}")
        bdir = write_bundle([], {}, out_dir=str(tmp_path))
        manifest = json.loads((bdir / "manifest.json").read_text())
        copied = {e["file"] for e in manifest["entries"]}
        assert "profile_w7.collapsed" in copied  # newest kept
        assert "profile_w0.speedscope.json" in copied
        dropped = [
            d for d in manifest["dropped"] if d["kind"] == "profile"
        ]
        assert {d["file"] for d in dropped} == {
            "profile_w0.collapsed", "profile_w1.collapsed",
        }
        # every dropped entry carries the uniform shape: file/kind/why
        assert all(set(d) == {"file", "kind", "why"}
                   for d in manifest["dropped"])


# -- regress: the cross-round profile gates -----------------------------------


class TestRegressProfileGate:
    def test_overhead_gate(self):
        from gol_distributed_final_tpu.obs.regress import _apply_profile_gate

        out = _apply_profile_gate(
            {"profile_overhead_pct": 1.0}, {"profile_overhead_pct": 9.0},
            {"verdict": "OK"}, 0.05,
        )
        assert out["verdict"] == "REGRESSED"
        assert out["profile_overhead_delta_pts"] == pytest.approx(8.0)
        ok = _apply_profile_gate(
            {"profile_overhead_pct": 1.0}, {"profile_overhead_pct": 2.0},
            {"verdict": "OK"}, 0.05,
        )
        assert ok["verdict"] == "OK"  # 1pt < the 5pt threshold

    def test_hot_frame_mover_gate(self):
        from gol_distributed_final_tpu.obs.regress import _apply_profile_gate

        old = {"profile_hot": [{"frame": "a", "self_share": 0.10}]}
        new = {"profile_hot": [{"frame": "a", "self_share": 0.60}]}
        out = _apply_profile_gate(old, new, {"verdict": "OK"}, 0.05)
        assert out["verdict"] == "REGRESSED"
        assert out["profile_top_mover"] == "a"
        mild = _apply_profile_gate(
            old,
            {"profile_hot": [{"frame": "a", "self_share": 0.30}]},
            {"verdict": "OK"}, 0.05,
        )
        assert mild["verdict"] == "OK"  # reported, not gated
        assert mild["profile_top_mover_delta_share"] == pytest.approx(0.2)

    def test_compare_case_carries_profile_gate(self):
        from gol_distributed_final_tpu.obs.regress import compare_case

        old = {"per_turn_us": 10.0, "spread_s": 0.0, "n_hi": 2, "n_lo": 1,
               "profile_overhead_pct": 1.0}
        new = {"per_turn_us": 10.0, "spread_s": 0.0, "n_hi": 2, "n_lo": 1,
               "profile_overhead_pct": 50.0}
        out = compare_case(old, new, threshold=0.05)
        assert out["verdict"] == "REGRESSED"
        assert "profiler overhead" in out["why"]
        # the incomparable path (broken fit) still runs the profile gate
        broken = compare_case(
            {"profile_overhead_pct": 1.0},
            {"profile_overhead_pct": 50.0},
            threshold=0.05,
        )
        assert broken["verdict"] == "REGRESSED"


# -- history: HLC time-window flags -------------------------------------------


class TestHistoryWindow:
    def test_matches_since_until_inclusive(self):
        from gol_distributed_final_tpu.obs.history import _matches

        ev = {"kind": "x", "hlc": [1000, 0, "n1"]}
        assert _matches(ev, None, None, since_ms=500, until_ms=1500)
        assert _matches(ev, None, None, since_ms=1000, until_ms=1000)
        assert not _matches(ev, None, None, since_ms=1001, until_ms=None)
        assert not _matches(ev, None, None, since_ms=None, until_ms=999)
        # no usable stamp: physical falls back to 0 — survives only an
        # unbounded-below window
        unstamped = {"kind": "x"}
        assert _matches(unstamped, None, None, since_ms=None, until_ms=50)
        assert not _matches(unstamped, None, None, since_ms=1, until_ms=None)

    def test_build_history_records_window_filters(self, tmp_path):
        from gol_distributed_final_tpu.obs.history import build_history

        doc = build_history(
            "t", out_dir=str(tmp_path), brokers=[], workers=[],
            since_ms=5, until_ms=9,
        )
        assert doc["filters"]["since_ms"] == 5
        assert doc["filters"]["until_ms"] == 9
        assert doc["events"] == []

    @staticmethod
    def _write_segment(tmp_path):
        from gol_distributed_final_tpu.obs import journal as obs_journal

        seg = tmp_path / "journal_test_123.jsonl"
        events = [
            {"schema": obs_journal.SCHEMA, "kind": "worker.lost",
             "name": "w1", "seq": i + 1,
             "hlc": [1000 * (i + 1), 0, "test-node"]}
            for i in range(3)  # physical stamps 1000, 2000, 3000
        ]
        seg.write_bytes(b"".join(
            obs_journal._frame(json.dumps(e).encode()) for e in events
        ))
        return seg

    def test_build_history_windows_segment_events(self, tmp_path):
        from gol_distributed_final_tpu.obs.history import build_history

        self._write_segment(tmp_path)
        doc = build_history("t", out_dir=str(tmp_path), brokers=[],
                            workers=[], since_ms=1500, until_ms=2500)
        assert [e["seq"] for e in doc["events"]] == [2]
        unbounded = build_history("t", out_dir=str(tmp_path))
        assert [e["seq"] for e in unbounded["events"]] == [1, 2, 3]

    def test_cli_flags_window_the_artifact(self, tmp_path, capsys):
        from gol_distributed_final_tpu.obs import history

        self._write_segment(tmp_path)
        rc = history.main([
            "t", "-dir", str(tmp_path), "-since", "1500", "-until", "2500",
        ])
        assert rc == 0
        capsys.readouterr()
        doc = json.loads((tmp_path / "history_t.json").read_text())
        assert doc["filters"]["since_ms"] == 1500
        assert doc["filters"]["until_ms"] == 2500
        assert doc["events_total"] == 1


# -- hygiene: the gc-callback registration checker ----------------------------


class TestHygieneGcCallbacks:
    def test_append_without_remove_flagged(self):
        from gol_distributed_final_tpu.analysis.hygiene import HygieneChecker

        from test_analysis import findings_for

        found = findings_for(HygieneChecker(), """
            import gc

            def install(cb):
                gc.callbacks.append(cb)
        """)
        assert len(found) == 1
        assert "gc.callbacks.append" in found[0].message

    def test_append_with_remove_anywhere_in_file_ok(self):
        from gol_distributed_final_tpu.analysis.hygiene import HygieneChecker

        from test_analysis import findings_for

        found = findings_for(HygieneChecker(), """
            import gc

            def install(cb):
                gc.callbacks.append(cb)

            def uninstall(cb):
                gc.callbacks.remove(cb)
        """)
        assert found == []


# -- lint: the README Profiling section ---------------------------------------


def test_profiler_names_documented(repo_root):
    from gol_distributed_final_tpu.obs.lint import (
        _PROFILER_DOC_NAMES,
        undocumented_profiler_names,
    )

    assert "gol_gc_pause_seconds" in _PROFILER_DOC_NAMES
    assert undocumented_profiler_names() == []


# -- live: cross-process profile polls + the slow-worker drill ----------------


def _spawn_worker(extra_args=(), extra_env=None):
    env = dict(os.environ)
    env.pop("GOL_FAULT_POINTS", None)
    env["JAX_PLATFORMS"] = "cpu"
    # conftest pins THIS process to 8 virtual CPU devices via XLA_FLAGS,
    # which the child would inherit — an 8-device jax init in every
    # worker is seconds of import/compile churn that starves the 5ms
    # sampler and can stall Status past its timeout on a loaded runner.
    # A strip worker needs exactly one device.
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    if extra_env:
        env.update(extra_env)
    return subprocess.Popen(
        [sys.executable, "-m", "gol_distributed_final_tpu.rpc.worker",
         "-port", "0", *extra_args],
        cwd=REPO_ROOT, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True,
    )


def _wait_port(proc, timeout=60):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if "listening on :" in line:
            return int(line.rsplit(":", 1)[1].split()[0])
        if proc.poll() is not None:
            raise RuntimeError(f"worker died: {proc.stdout.read()}")
    raise TimeoutError("worker did not report listening")


def _kill(procs):
    for p in procs:
        if p.poll() is None:
            p.kill()
        p.wait()


def test_live_profile_window_over_status(live_metrics):
    """A ``-profile`` worker ships an incremental profile window over the
    real Status surface; an echoed far-future seq ships zero frames."""
    from gol_distributed_final_tpu.obs.status import fetch_status

    w = _spawn_worker(extra_args=("-profile", "5"))
    try:
        port = _wait_port(w)
        addr = f"127.0.0.1:{port}"
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            payload = fetch_status(addr, worker=True, profile_since=0)
            pw = payload.get("profile")
            if pw and pw.get("stacks", 0) >= 5:
                break
            time.sleep(0.1)
        else:
            pytest.fail("worker never shipped a populated profile window")
        assert pw["schema"] == "gol-profile/1"
        assert pw["period_ms"] > 0 and pw["frames"]
        # the incremental contract: nothing can have moved past a seq
        # far beyond the sampler's own
        later = fetch_status(
            addr, worker=True, profile_since=pw["seq"] + 10 ** 9
        )
        assert later["profile"]["frames"] == []
        assert later["profile"]["stacks"] >= pw["stacks"]
    finally:
        _kill([w])


def test_live_drill_doctor_names_slowed_site_and_flame_diffs_it(
    live_metrics,
):
    """THE acceptance drill: a sleep-slowed worker (GOL_FAULT_POINTS on
    its strip_step/update sites) in a live 2-worker resident cluster,
    both workers under ``-profile``. One Status poll later the doctor's
    hotspot finding names the slowed site's function (``fault_point`` —
    the Python frame that owns the injected sleep), and the flame diff
    of slow-vs-clean flags that frame as the top mover."""
    from gol_distributed_final_tpu.obs.doctor import collect, diagnose
    from gol_distributed_final_tpu.rpc.broker import serve
    from gol_distributed_final_tpu.rpc.client import RpcClient
    from gol_distributed_final_tpu.rpc.protocol import Methods, Request

    slow_env = {
        # one StripStep RPC per K-batch: turns=24 / halo_depth=4 -> 6
        # batches -> ~1.5s parked inside fault_point, the sampled leaf
        "GOL_FAULT_POINTS":
            "worker.strip_step:sleep:1:0.25,worker.update:sleep:1:0.25"
    }
    workers = [
        _spawn_worker(extra_args=("-profile", "5"),
                      extra_env=slow_env if i == 0 else None)
        for i in range(2)
    ]
    server = None
    try:
        ports = [_wait_port(w) for w in workers]
        slow_addr, clean_addr = (f"127.0.0.1:{p}" for p in ports)
        server, service = serve(
            port=0, backend="workers",
            worker_addresses=[slow_addr, clean_addr],
            wire="resident", halo_depth=4,
        )
        addr = f"127.0.0.1:{server.port}"
        rng = np.random.default_rng(11)
        board = np.where(rng.random((64, 64)) < 0.3, 255, 0).astype(np.uint8)
        client = RpcClient(addr)
        try:
            client.call(
                Methods.BROKER_RUN,
                Request(world=board, turns=24, threads=4,
                        image_width=64, image_height=64),
                timeout=120.0,
            )
        finally:
            client.close()
        # ONE doctor poll over the real Status surface names the site.
        # The samples backing it are cumulative, so on a loaded runner a
        # poll that lands before the samplers drained the sleep window
        # is simply retried — each iteration is still a single poll.
        deadline = time.monotonic() + 60.0
        while True:
            statuses = collect(addr, [slow_addr, clean_addr], timeout=30.0)
            findings = diagnose(statuses)
            hot = [f for f in findings if f["title"].startswith("hotspot")]
            if any("fault_point" in f["title"] for f in hot):
                break
            assert time.monotonic() < deadline, [
                (f["title"], f["evidence"]) for f in findings
            ]
            time.sleep(0.5)
        named = next(f for f in hot if "fault_point" in f["title"])
        assert any("rpc/faults.py" in e for e in named["evidence"])
        # the flame diff, clean -> slow: the injected frame is the top
        # active mover by self-share
        clean = flame.load_live(clean_addr, worker=True, timeout=30.0)
        slow = flame.load_live(slow_addr, worker=True, timeout=30.0)
        movers = flame.diff_profiles(clean, slow, active_only=True)
        assert movers, "no mover past the noise floor"
        assert "fault_point" in movers[0]["frame"], movers[:5]
    finally:
        if server is not None:
            service.backend.close()
            server.stop()
        _kill(workers)
