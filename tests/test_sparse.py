"""Activity-sparse stepping suite (ISSUE 14, `scripts/check --sparse`).

Covers the four layers of the sparse tentpole:

* ``ops/sparse.SparseBitPlane`` — numpy-oracle BIT-IDENTICAL parity
  across tile-boundary crossings (R-pentomino, Gosper glider gun, a
  torus-wrapping glider, all-dead, a dense soup through the crossover
  path), capacity-bucket overflow/regrowth, and jit-cache boundedness
  under 100 varying-activity steps.
* early exits — still-life / period-2 exactness through the ENGINE
  (turn count, final board, PGM golden) and the metrics contract.
* dirty-tile wire deltas — worker-level delta/full StripFetch protocol,
  the live resident-cluster byte contract (delta sync ≥ 10× below a
  full gather on a <1%-active board), and delta-application failure
  modes.
* delta checkpoints — round-trip through ``load_resume_checkpoint``,
  corrupted-delta refusal, wrong-base refusal.

Plus the satellite gates: obs/regress.py's per-active-cell and
sparse-byte verdicts, auto_plane routing knobs, and the SPARSITY panel.
"""

import numpy as np
import pytest

from gol_distributed_final_tpu.models import CONWAY, LifeRule
from gol_distributed_final_tpu.obs import metrics as obs_metrics
from gol_distributed_final_tpu.ops import sparse as sparse_mod
from gol_distributed_final_tpu.ops.sparse import (
    SparseBitPlane,
    apply_dirty_tiles,
    dirty_tile_grid,
    extract_dirty_tiles,
    sparse_capable,
    wire_tile_grid,
)

from oracle import vector_step


def _oracle_n(board, n):
    for _ in range(n):
        board = vector_step(board)
    return board


def _r_pentomino(h, w):
    board = np.zeros((h, w), np.uint8)
    for dx, dy in ((1, 0), (2, 0), (0, 1), (1, 1), (1, 2)):
        board[h // 2 + dy, w // 2 + dx] = 255
    return board


def _glider(h, w, y=1, x=1):
    board = np.zeros((h, w), np.uint8)
    for dy, dx in ((0, 1), (1, 2), (2, 0), (2, 1), (2, 2)):
        board[(y + dy) % h, (x + dx) % w] = 255
    return board


def _gosper_gun(h, w):
    cells = [
        (5, 1), (5, 2), (6, 1), (6, 2), (5, 11), (6, 11), (7, 11),
        (4, 12), (8, 12), (3, 13), (9, 13), (3, 14), (9, 14), (6, 15),
        (4, 16), (8, 16), (5, 17), (6, 17), (7, 17), (6, 18), (3, 21),
        (4, 21), (5, 21), (3, 22), (4, 22), (5, 22), (2, 23), (6, 23),
        (1, 25), (2, 25), (6, 25), (7, 25), (3, 35), (4, 35), (3, 36),
        (4, 36),
    ]
    board = np.zeros((h, w), np.uint8)
    for y, x in cells:
        board[y, x] = 255
    return board


@pytest.fixture
def live_metrics():
    obs_metrics.enable()
    obs_metrics.registry().reset()
    yield obs_metrics
    obs_metrics.enable(False)
    obs_metrics.registry().reset()


def _metric(name, labels=()):
    for fam in obs_metrics.registry().snapshot()["families"]:
        if fam["name"] == name:
            for s in fam["series"]:
                if tuple(s.get("labels", ())) == tuple(labels):
                    return s.get("value", 0)
    return 0


# -- oracle bit-parity across tile boundaries --------------------------------


def test_r_pentomino_parity_crosses_tile_boundaries():
    """The methuselah outgrows its seed tiles (capacity buckets overflow
    and regrow along the way) and every bit matches the oracle."""
    board = _r_pentomino(256, 256)
    plane = SparseBitPlane(CONWAY, tile=(1, 16))  # 8x16 = 128 tiles
    state = plane.encode(board)
    seed_count = state.count
    state = plane.step_n(state, 300)
    assert np.array_equal(plane.decode(state), _oracle_n(board, 300))
    assert state.count > seed_count  # the frontier genuinely spread


def test_glider_gun_parity():
    board = _gosper_gun(128, 128)
    plane = SparseBitPlane(CONWAY, tile=(1, 16))
    state = plane.step_n(plane.encode(board), 200)
    assert np.array_equal(plane.decode(state), _oracle_n(board, 200))


def test_glider_wraps_torus_across_tiles():
    board = _glider(64, 64, y=60, x=60)  # launched into the wrap corner
    plane = SparseBitPlane(CONWAY, tile=(1, 8))
    state = plane.step_n(plane.encode(board), 250)
    assert np.array_equal(plane.decode(state), _oracle_n(board, 250))


def test_all_dead_board_is_free_and_still():
    plane = SparseBitPlane(CONWAY, tile=(1, 8))
    state = plane.encode(np.zeros((64, 64), np.uint8))
    assert state.count == 0
    state = plane.step_n(state, 1000)
    assert plane.alive_count(state) == 0
    assert state.steady == "still"


def test_dense_soup_takes_crossover_path_bit_identical():
    """A 30% soup is far past the density crossover: step_n must route
    through the dense path and STILL match the oracle bit for bit."""
    rng = np.random.default_rng(3)
    board = np.where(rng.random((64, 64)) < 0.3, 255, 0).astype(np.uint8)
    plane = SparseBitPlane(CONWAY, tile=(1, 8))
    state = plane.encode(board)
    assert state.count > sparse_mod.SPARSE_DENSITY_CROSSOVER * 2 * 8
    state = plane.step_n(state, 40)
    assert np.array_equal(plane.decode(state), _oracle_n(board, 40))


def test_b0_rule_refused():
    with pytest.raises(ValueError, match="births on 0"):
        SparseBitPlane(LifeRule.from_rulestring("B0/S23"))


def test_sparse_capable_routing(monkeypatch):
    monkeypatch.delenv("GOL_SPARSE", raising=False)
    assert not sparse_capable(CONWAY, (64, 64))  # below the size floor
    assert sparse_capable(CONWAY, (4096, 4096))
    assert not sparse_capable(CONWAY, (4097, 4096))  # rows not packable
    monkeypatch.setenv("GOL_SPARSE", "on")
    assert sparse_capable(CONWAY, (64, 64))
    monkeypatch.setenv("GOL_SPARSE", "off")
    assert not sparse_capable(CONWAY, (4096, 4096))


def test_auto_plane_selects_sparse(monkeypatch):
    from gol_distributed_final_tpu.ops import auto

    monkeypatch.setenv("GOL_SPARSE", "on")
    auto._PLANE_CACHE.pop((CONWAY.rulestring, (96, 96)), None)
    plane = auto.auto_plane(CONWAY, (96, 96))
    assert isinstance(plane, SparseBitPlane)
    auto._PLANE_CACHE.pop((CONWAY.rulestring, (96, 96)), None)
    monkeypatch.setenv("GOL_SPARSE", "off")
    plane = auto.auto_plane(CONWAY, (96, 96))
    assert not isinstance(plane, SparseBitPlane)
    auto._PLANE_CACHE.pop((CONWAY.rulestring, (96, 96)), None)


# -- jit-cache boundedness under frontier churn ------------------------------


def test_frontier_churn_keeps_compile_count_bounded():
    """100 steps of a growing/shrinking soup: the compiled-program count
    may only move by the number of power-of-two capacity buckets — never
    one program per frontier size."""
    rng = np.random.default_rng(11)
    board = np.zeros((256, 256), np.uint8)
    board[96:160, 96:160] = np.where(
        rng.random((64, 64)) < 0.35, 255, 0
    ).astype(np.uint8)
    plane = SparseBitPlane(CONWAY, tile=(1, 16))
    state = plane.encode(board)
    before = sparse_mod.compiled_program_count()
    counts = set()
    for _ in range(100):
        state = plane.step_n(state, 1)
        counts.add(state.count)
    grew = sparse_mod.compiled_program_count() - before
    total_tiles = 8 * 16
    max_buckets = total_tiles.bit_length() + 2
    assert len(counts) > 5, "the frontier must actually churn"
    assert grew <= max_buckets, (
        f"{grew} programs compiled for {len(counts)} distinct frontier "
        f"sizes — the pow2 bucket contract is broken"
    )
    assert np.array_equal(plane.decode(state), _oracle_n(board, 100))


# -- early exits through the engine ------------------------------------------


def test_engine_still_life_early_exit_exact(live_metrics, tmp_path):
    """A block run for 5000 turns: exact turn count, exact final board
    (PGM golden), and the still-life early exit metered."""
    from gol_distributed_final_tpu.engine.engine import Engine
    from gol_distributed_final_tpu.io.pgm import read_pgm, write_pgm
    from gol_distributed_final_tpu.params import Params

    board = np.zeros((64, 64), np.uint8)
    board[30:32, 30:32] = 255
    plane = SparseBitPlane(CONWAY, tile=(1, 2))
    result = Engine().run(
        Params(turns=5000, image_width=64, image_height=64),
        board,
        plane=plane,
    )
    assert result.turns_completed == 5000
    assert np.array_equal(result.world, board)  # a block is a block
    # PGM golden: the run's final frame equals the oracle's, byte for byte
    golden = tmp_path / "golden.pgm"
    final = tmp_path / "final.pgm"
    write_pgm(golden, _oracle_n(board, 5000))
    write_pgm(final, result.world)
    assert golden.read_bytes() == final.read_bytes()
    assert _metric("gol_early_exit_total", ("still",)) >= 1


@pytest.mark.parametrize("turns", [400, 401])
def test_engine_period2_early_exit_exact(live_metrics, turns):
    """A blinker run to an even AND an odd horizon: the period-2 jump
    must land on the right phase both ways."""
    from gol_distributed_final_tpu.engine.engine import Engine
    from gol_distributed_final_tpu.params import Params

    board = np.zeros((64, 64), np.uint8)
    board[20, 19:22] = 255  # horizontal blinker
    plane = SparseBitPlane(CONWAY, tile=(1, 2))
    result = Engine().run(
        Params(turns=turns, image_width=64, image_height=64),
        board,
        plane=plane,
    )
    assert result.turns_completed == turns
    assert np.array_equal(result.world, _oracle_n(board, turns))
    assert _metric("gol_early_exit_total", ("period2",)) >= 1


def test_session_dead_universe_early_retire(live_metrics):
    """The satellite: an all-dead universe with a huge budget retires at
    the FIRST advance boundary with full FinalTurnComplete semantics."""
    from gol_distributed_final_tpu.engine.sessions import SessionTable
    from gol_distributed_final_tpu.events import FinalTurnComplete

    events = []
    table = SessionTable(CONWAY, (32, 32), capacity=2, max_chunk=4)
    dead = table.admit(
        np.zeros((32, 32), np.uint8), 100_000, on_event=events.append
    )
    glider = table.admit(_glider(32, 32), 8)
    n = 0
    while table.advance():
        n += 1
        assert n < 10, "the dead universe must not burn its budget"
    assert dead.done.is_set() and dead.turns_done == 100_000
    assert dead.alive_count == 0
    assert np.array_equal(dead.result, np.zeros((32, 32), np.uint8))
    finals = [e for e in events if isinstance(e, FinalTurnComplete)]
    assert len(finals) == 1
    assert finals[0].completed_turns == 100_000 and finals[0].alive == []
    assert glider.done.is_set() and glider.turns_done == 8
    assert _metric("gol_early_exit_total", ("dead",)) == 1


# -- dirty-tile wire deltas --------------------------------------------------


def test_tile_delta_roundtrip_ragged_edges():
    rng = np.random.default_rng(5)
    a = np.where(rng.random((100, 300)) < 0.2, 255, 0).astype(np.uint8)
    b = a.copy()
    b[0:3, 0:3] ^= 255          # top-left tile
    b[97:100, 290:300] ^= 255   # ragged bottom-right tile
    b[80, 120] ^= 255           # a ragged bottom-left tile
    dirty = dirty_tile_grid(a, b)
    assert dirty.shape == wire_tile_grid((100, 300))
    assert int(dirty.sum()) == 3
    flat = extract_dirty_tiles(b, dirty)
    assert np.array_equal(apply_dirty_tiles(a, dirty, flat), b)
    # malformed payloads must refuse loudly, never half-apply
    with pytest.raises(ValueError, match="truncated"):
        apply_dirty_tiles(a, dirty, flat[:-1])
    with pytest.raises(ValueError, match="trailing"):
        apply_dirty_tiles(a, dirty, np.concatenate([flat, flat[:1]]))


def test_worker_strip_fetch_delta_protocol():
    """Worker-level contract: StripStep accumulates dirty tiles; a fetch
    whose base turn matches the anchor gets a delta, anything else a
    full frame; the accumulator re-anchors either way."""
    from gol_distributed_final_tpu.rpc.protocol import Request
    from gol_distributed_final_tpu.rpc.worker import (
        WorkerService,
        compute_strip,
    )

    service = WorkerService(server=None)
    board = _r_pentomino(96, 128)
    service.strip_start(Request(world=board, worker=0, initial_turn=0))
    halos = np.concatenate([board[-1:], board[:1]], axis=0)
    res = service.strip_step(
        Request(world=halos, worker=0, turns=1, initial_turn=0)
    )
    assert isinstance(res.dirty, np.ndarray) and res.dirty.any()
    want = compute_strip(board, 0, 96)

    # mismatched base -> full frame, accumulator re-anchored at turn 1
    full = service.strip_fetch(Request(worker=0, delta_base_turn=999))
    assert getattr(full, "dirty", None) is None
    assert np.array_equal(np.asarray(full.work_slice), want)

    # advance again; now the broker's copy is anchored at turn 1
    halos = np.concatenate([want[-1:], want[:1]], axis=0)
    service.strip_step(
        Request(world=halos, worker=0, turns=1, initial_turn=1)
    )
    delta = service.strip_fetch(Request(worker=0, delta_base_turn=1))
    assert isinstance(delta.dirty, np.ndarray)
    want2 = compute_strip(want, 0, 96)
    rebuilt = apply_dirty_tiles(
        want, delta.dirty, np.asarray(delta.work_slice)
    )
    assert np.array_equal(rebuilt, want2)
    # a delta frame must be smaller than the strip it replaces
    assert np.asarray(delta.work_slice).nbytes < want2.nbytes

    # a skew-shaped fetch (no delta_base_turn at all) stays full
    legacy = service.strip_fetch(Request(worker=0))
    assert getattr(legacy, "dirty", None) is None
    assert np.array_equal(np.asarray(legacy.work_slice), want2)


def test_resident_delta_sync_live_cluster_byte_contract(live_metrics):
    """The live contract on a <1%-active 1024² board (the bench runs the
    16384² version): a delta sync ships ≥ 10× fewer StripFetch bytes
    than a full gather, bit-identical both ways."""
    from gol_distributed_final_tpu.rpc import worker as rpc_worker
    from gol_distributed_final_tpu.rpc.broker import WorkersBackend
    from gol_distributed_final_tpu.rpc.protocol import Methods, Request

    def fetch_received():
        total = 0.0
        for fam in obs_metrics.registry().snapshot()["families"]:
            if fam["name"] == "gol_wire_bytes_total":
                for s in fam["series"]:
                    if s.get("labels") == [Methods.STRIP_FETCH, "received"]:
                        total += s["value"]
        return total

    size, turns = 1024, 3
    board = _r_pentomino(size, size)
    want = _oracle_n(board, turns)
    got, sync_bytes = {}, {}
    for sparse in (True, False):
        servers = [rpc_worker.serve(port=0) for _ in range(2)]
        addrs = [f"127.0.0.1:{s.port}" for s, _ in servers]
        backend = WorkersBackend(
            addrs, wire="resident", halo_depth=1, sync_interval=0,
            sparse_sync=sparse,
        )
        try:
            b0 = fetch_received()
            res = backend.run(Request(
                world=board, turns=turns, threads=2,
                image_width=size, image_height=size,
            ))
            sync_bytes[sparse] = fetch_received() - b0
            got[sparse] = np.asarray(res.world)
        finally:
            backend.close()
            for server, _service in servers:
                server.stop()
    np.testing.assert_array_equal(got[True], want)
    np.testing.assert_array_equal(got[False], want)
    assert sync_bytes[True] * 10 <= sync_bytes[False], (
        f"delta sync {sync_bytes[True]:.0f} B vs full "
        f"{sync_bytes[False]:.0f} B"
    )
    assert _metric("gol_sparse_frame_bytes_total") > 0


# -- delta checkpoints -------------------------------------------------------


def test_delta_checkpoint_roundtrip_and_refusals(tmp_path):
    from gol_distributed_final_tpu.engine.checkpoint import (
        CheckpointError,
        apply_delta_checkpoint,
        checkpoint_digest,
        clear_delta_checkpoints,
        delta_checkpoint_paths,
        load_resume_checkpoint,
        npz_path,
        save_checkpoint,
        save_delta_checkpoint,
    )

    base = _r_pentomino(256, 512)
    later = _oracle_n(base, 10)
    p = tmp_path / "ck.npz"
    save_checkpoint(p, base, 100, CONWAY)
    base_digest = checkpoint_digest(base, 100, CONWAY.rulestring)
    dirty = dirty_tile_grid(base, later)
    dpath = save_delta_checkpoint(
        p, later, dirty, 110, CONWAY, 100, base_digest
    )
    assert delta_checkpoint_paths(p) == [(110, dpath)]
    # the delta's tile payload is a fraction of the full board's bytes
    with np.load(dpath, allow_pickle=False) as data:
        assert data["tiles"].nbytes < later.nbytes

    # round-trip through the -resume loader: full gen + newest delta
    board, turn, rule, gen = load_resume_checkpoint(p)
    assert turn == 110 and gen == 0
    assert np.array_equal(board, later)

    # wrong base refuses with a typed error
    other = np.zeros((128, 128), np.uint8)
    with pytest.raises(CheckpointError) as exc:
        apply_delta_checkpoint(dpath, other, 100, CONWAY)
    assert exc.value.kind == "delta-base"

    # corrupted delta: flip payload bytes inside the npz -> digest
    # refusal, and -resume falls back to the verified FULL generation
    with np.load(dpath, allow_pickle=False) as data:
        fields = {k: data[k] for k in data.files}
    fields["tiles"] = np.asarray(fields["tiles"], np.uint8) ^ 255
    np.savez_compressed(dpath.with_suffix(""), **fields)
    with pytest.raises(CheckpointError) as exc:
        apply_delta_checkpoint(dpath, base, 100, CONWAY)
    assert exc.value.kind == "digest"
    board, turn, rule, gen = load_resume_checkpoint(p)
    assert turn == 100 and np.array_equal(board, base)

    clear_delta_checkpoints(p)
    assert delta_checkpoint_paths(p) == []


def test_broker_auto_checkpoint_writes_deltas(tmp_path, live_metrics):
    """End to end: a resident broker with -auto-checkpoint 0 writes a
    full keyframe first, then dirty-tile deltas the -resume loader
    replays onto it."""
    from gol_distributed_final_tpu.engine.checkpoint import (
        delta_checkpoint_paths,
        load_resume_checkpoint,
    )
    from gol_distributed_final_tpu.rpc import worker as rpc_worker
    from gol_distributed_final_tpu.rpc.broker import WorkersBackend
    from gol_distributed_final_tpu.rpc.protocol import Request

    size, turns = 128, 6
    board = _r_pentomino(size, size)
    ck = tmp_path / "auto.npz"
    servers = [rpc_worker.serve(port=0) for _ in range(2)]
    addrs = [f"127.0.0.1:{s.port}" for s, _ in servers]
    backend = WorkersBackend(
        addrs, wire="resident", halo_depth=1, sync_interval=1,
        auto_checkpoint=(0.0, str(ck)),
    )
    try:
        backend.run(Request(
            world=board, turns=turns, threads=2,
            image_width=size, image_height=size,
        ))
    finally:
        backend.close()
        for server, _service in servers:
            server.stop()
    deltas = delta_checkpoint_paths(ck)
    assert deltas, "deltas must land between full keyframes"
    board_r, turn_r, rule_r, _gen = load_resume_checkpoint(ck)
    assert turn_r == deltas[-1][0]
    assert np.array_equal(board_r, _oracle_n(board, turn_r))


# -- the regress gates + the watch panel -------------------------------------


def test_regress_gates_active_throughput_and_sync_bytes():
    from gol_distributed_final_tpu.obs.regress import compare_case

    base = {
        "per_turn_us": 100.0, "n_lo": 100, "n_hi": 1100, "spread_s": 0.0001,
        "cell_updates_per_s_active": 1e9,
        "sparse_frame_bytes_per_sync": 1000.0,
    }
    # a 30% per-active-cell throughput drop past the noise band gates
    worse = dict(base, cell_updates_per_s_active=0.7e9)
    v = compare_case(base, worse)
    assert v["verdict"] == "REGRESSED" and "active" in v["why"]
    # sparse sync byte growth gates deterministically — even when the
    # wall-clock fit is unusable (the c11 case shape)
    nofit = dict(base, per_turn_us=0.0)
    fat = dict(nofit, sparse_frame_bytes_per_sync=1500.0)
    v = compare_case(nofit, fat)
    assert v["verdict"] == "REGRESSED" and "sparse sync bytes" in v["why"]
    # within threshold: no gate
    ok = dict(
        base,
        cell_updates_per_s_active=0.99e9,
        sparse_frame_bytes_per_sync=1010.0,
    )
    v = compare_case(base, ok)
    assert v["verdict"] != "REGRESSED"


def test_watch_sparsity_panel_renders(live_metrics):
    from gol_distributed_final_tpu.obs.instruments import (
        ACTIVE_TILES,
        EARLY_EXIT_TOTAL,
        SPARSE_FRAME_BYTES_TOTAL,
        TILE_SKIPS_TOTAL,
    )
    from gol_distributed_final_tpu.obs.watch import render_status

    ACTIVE_TILES.set(42)
    TILE_SKIPS_TOTAL.inc(1000)
    SPARSE_FRAME_BYTES_TOTAL.inc(2048)
    EARLY_EXIT_TOTAL.labels("still").inc()
    payload = {
        "role": "broker",
        "pid": 1,
        "metrics_enabled": True,
        "metrics": obs_metrics.registry().snapshot(),
    }
    out = render_status("t", payload)
    assert "SPARSITY" in out
    assert "active tiles 42" in out
    assert "still 1" in out


def test_sparse_lint_both_ways(tmp_path):
    from gol_distributed_final_tpu.obs.lint import undocumented_sparse_names

    assert undocumented_sparse_names() == []
    bad = tmp_path / "README.md"
    bad.write_text("# x\n## Sparse stepping\nonly gol_active_tiles here\n")
    missing = undocumented_sparse_names(bad)
    assert "gol_early_exit_total" in missing
    assert "GOL_SPARSE" in missing
