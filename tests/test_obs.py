"""Observability-layer tests (obs/): registry semantics, the live Status
verb against real broker/worker subprocesses, the RunReport artifact, the
version-skew request handling, and the metric-name lint.
"""

import json
import queue

import numpy as np
import pytest

from gol_distributed_final_tpu import Params, run
from gol_distributed_final_tpu.io.pgm import read_board
from gol_distributed_final_tpu.obs import metrics as obs_metrics
from gol_distributed_final_tpu.obs.metrics import (
    DEFAULT_BUCKETS,
    Registry,
    merge_snapshots,
    parse_prometheus_text,
    snapshot_to_prometheus,
)
from gol_distributed_final_tpu.rpc.client import RemoteBroker, RpcClient
from gol_distributed_final_tpu.rpc.protocol import Methods, Request

from helpers import REPO_ROOT
from test_rpc import _spawn, _wait_listening


@pytest.fixture
def live_metrics():
    """Enable the process-global registry for one test, zeroed before and
    disabled+zeroed after — other tests must keep seeing the no-op
    default."""
    reg = obs_metrics.registry()
    reg.reset()
    obs_metrics.enable()
    yield reg
    obs_metrics.enable(False)
    reg.reset()


def _series(snapshot: dict, name: str) -> dict:
    """{labels_tuple: series_dict} for one family of a snapshot."""
    for fam in snapshot["families"]:
        if fam["name"] == name:
            return {tuple(s["labels"]): s for s in fam["series"]}
    return {}


# -- registry unit tests -----------------------------------------------------


def test_histogram_bucket_math():
    """Observations land in the first bucket whose edge >= value (the
    Prometheus ``le`` contract), values past the last edge overflow, and
    sum/count track exactly."""
    r = Registry()
    h = r.histogram("h", buckets=(0.1, 1.0, 10.0))
    h.observe(0.05)   # <= 0.1        -> bucket 0
    h.observe(0.1)    # == edge, le   -> bucket 0
    h.observe(0.5)    # <= 1.0        -> bucket 1
    h.observe(10.0)   # == last edge  -> bucket 2
    h.observe(99.0)   # past the end  -> overflow
    (series,) = _series(r.snapshot(), "h").values()
    assert series["buckets"] == [2, 1, 1, 1]
    assert series["count"] == 5
    assert series["sum"] == pytest.approx(0.05 + 0.1 + 0.5 + 10.0 + 99.0)


def test_histogram_observe_n_counts_as_n():
    """The engine's chunked form: one call records a whole chunk's turns,
    so histogram count == turn count."""
    r = Registry()
    h = r.histogram("h")
    h.observe_n(0.001, 64)
    (series,) = _series(r.snapshot(), "h").values()
    assert series["count"] == 64
    assert series["sum"] == pytest.approx(0.064)


def test_merge_is_exact_bucketwise_addition():
    """Fixed edges make the cross-host merge exact: merging two snapshots
    equals one registry that saw both observation streams."""
    def fill(reg, values):
        h = reg.histogram("h")
        c = reg.counter("c", labelnames=("k",))
        for v in values:
            h.observe(v)
            c.labels("x").inc()

    a, b, union = Registry(), Registry(), Registry()
    fill(a, [0.001, 0.5])
    fill(b, [0.5, 7.0, 1e6])
    fill(union, [0.001, 0.5, 0.5, 7.0, 1e6])
    merged = merge_snapshots(a.snapshot(), b.snapshot())
    assert merged == union.snapshot()
    # gauges merge by max (a meaningful high-water semantics)
    a2, b2 = Registry(), Registry()
    a2.gauge("g").set(3)
    b2.gauge("g").set(5)
    (g,) = _series(merge_snapshots(a2.snapshot(), b2.snapshot()), "g").values()
    assert g["value"] == 5


def test_merge_refuses_mismatched_edges():
    a, b = Registry(), Registry()
    a.histogram("h", buckets=(1.0, 2.0))
    b.histogram("h", buckets=(1.0, 3.0))
    with pytest.raises(ValueError, match="bucket-edge"):
        merge_snapshots(a.snapshot(), b.snapshot())


def test_prometheus_exposition_round_trip():
    """Every sample the text exposition emits parses back to exactly the
    registry's state — cumulative buckets, +Inf, label escaping."""
    r = Registry()
    h = r.histogram("rt_seconds", "help text", ("method",))
    h.labels("Operations.Run").observe(0.3)
    h.labels("Operations.Run").observe_n(0.02, 5)
    r.counter("rt_total", labelnames=("m",)).labels("a b").inc(7)
    r.gauge("rt_gauge").set(2.5)
    parsed = parse_prometheus_text(snapshot_to_prometheus(r.snapshot()))
    assert parsed['rt_seconds_count{method="Operations.Run"}'] == 6
    assert parsed['rt_seconds_sum{method="Operations.Run"}'] == pytest.approx(0.4)
    assert parsed['rt_seconds_bucket{method="Operations.Run",le="+Inf"}'] == 6
    # cumulative at an intermediate edge: the 5 fast observations
    assert parsed['rt_seconds_bucket{method="Operations.Run",le="0.025"}'] == 5
    assert parsed['rt_total{m="a b"}'] == 7
    assert parsed['rt_gauge'] == 2.5
    # sample count: one line per bucket edge + inf + sum + count + 2 scalars
    assert len(parsed) == len(DEFAULT_BUCKETS) + 1 + 2 + 2


def test_disabled_registry_records_nothing():
    r = Registry(enabled=False)
    c, h = r.counter("c"), r.histogram("h")
    c.inc(10)
    h.observe(1.0)
    snap = r.snapshot()
    (cs,) = _series(snap, "c").values()
    (hs,) = _series(snap, "h").values()
    assert cs["value"] == 0 and hs["count"] == 0


def test_reregistration_is_idempotent_but_signature_checked():
    r = Registry()
    c1 = r.counter("c", "help", ("k",))
    assert r.counter("c", "help", ("k",)) is c1
    with pytest.raises(ValueError, match="different signature"):
        r.histogram("c")


# -- the version-skew fix (ADVICE r5) ----------------------------------------


def _strip_extensions(req: Request) -> Request:
    """Simulate an older client: its pickled Request simply lacks the
    extension fields, so the server-side attribute is MISSING, not 0."""
    for field in (
        "halo_depth", "rulestring", "initial_turn", "include_world",
        "trace_ctx",
    ):
        del req.__dict__[field]
    return req


def test_old_client_request_gets_default_behavior():
    """A version-skewed client whose Request pickle predates the extension
    fields must get the server's default behavior (depth from -halo-depth,
    fresh run, full-world retrieve) — not an opaque AttributeError reply."""
    from gol_distributed_final_tpu.rpc.broker import serve

    server, service = serve(port=0)
    client = RpcClient(f"127.0.0.1:{server.port}")
    try:
        p = Params(turns=4, threads=8, image_width=16, image_height=16)
        board = read_board(p, REPO_ROOT / "images")
        req = _strip_extensions(
            Request(
                world=board, turns=4, image_width=16, image_height=16, threads=8
            )
        )
        res = client.call(Methods.BROKER_RUN, req)
        assert res.turns_completed == 4
        assert res.world.shape == (16, 16)
        # retrieve without include_world = the original full-world form
        snap = client.call(Methods.RETRIEVE, _strip_extensions(Request()))
        assert snap.world is not None and snap.turns_completed == 4
    finally:
        client.close()
        server.stop()


def test_old_client_request_on_workers_backend_paths():
    """The WorkersBackend reads the same extension fields defensively: an
    extension-less Request must clear every admission check (halo_depth,
    rulestring) and the initial-turn read without AttributeError. turns=0
    keeps the scatter loop empty, so the stub client is never called."""
    from gol_distributed_final_tpu.rpc.broker import WorkersBackend

    backend = WorkersBackend([])
    backend.clients = [object()]  # passes the connected check, never used
    req = _strip_extensions(
        Request(
            world=np.zeros((8, 8), np.uint8),
            turns=0,
            image_width=8,
            image_height=8,
        )
    )
    res = backend.run(req)
    assert res.turns_completed == 0
    assert res.world.shape == (8, 8)


# -- Status verb + RunReport integration -------------------------------------


def test_status_verb_live_tpu_broker():
    """A -metrics tpu-backend broker answers Operations.Status mid-life
    with plausible per-verb and engine counters: the acceptance shape —
    step histogram count == turns evolved, Run verb counted server-side."""
    broker = _spawn(
        "gol_distributed_final_tpu.rpc.broker", "-port", "0", "-metrics"
    )
    try:
        port = _wait_listening(broker)
        remote = RemoteBroker(f"127.0.0.1:{port}")
        try:
            p = Params(turns=20, threads=8, image_width=64, image_height=64)
            board = read_board(p, REPO_ROOT / "images")
            result = remote.run(p, board)
            assert result.turns_completed == 20
            status = remote.status()
        finally:
            remote.close()
        assert status["metrics_enabled"] is True
        assert status["role"] == "broker"
        snap = status["metrics"]
        run_series = _series(snap, "gol_rpc_server_requests_total")
        assert run_series[("Operations.Run",)]["value"] >= 1
        assert run_series[("Operations.Status",)]["value"] >= 1
        (step,) = _series(snap, "gol_engine_step_seconds").values()
        assert step["count"] == 20
        (turns,) = _series(snap, "gol_engine_turns_total").values()
        assert turns["value"] == 20
        # Status is read-only: a second snapshot still serves, run intact
        client = RpcClient(f"127.0.0.1:{port}")
        try:
            again = client.call(Methods.STATUS, Request())
            assert again.status["metrics"]["families"]
        finally:
            client.close()
    finally:
        if broker.poll() is None:
            broker.kill()
        broker.wait()


def test_status_verb_counts_update_calls_across_workers_backend():
    """The workers-backend broker's OUTBOUND Update traffic shows in its
    Status reply (client-side per-verb counters), and a -metrics worker's
    own Status shows the INBOUND side — both ends of the wire metered."""
    workers = [
        _spawn("gol_distributed_final_tpu.rpc.worker", "-port", "0", "-metrics")
        for _ in range(2)
    ]
    broker = None
    try:
        ports = [_wait_listening(w) for w in workers]
        addrs = ",".join(f"127.0.0.1:{p}" for p in ports)
        broker = _spawn(
            "gol_distributed_final_tpu.rpc.broker",
            "-port", "0", "-backend", "workers", "-workers", addrs, "-metrics",
        )
        broker_port = _wait_listening(broker)
        remote = RemoteBroker(f"127.0.0.1:{broker_port}")
        try:
            p = Params(turns=10, threads=2, image_width=16, image_height=16)
            board = read_board(p, REPO_ROOT / "images")
            assert remote.run(p, board).turns_completed == 10
            status = remote.status()
        finally:
            remote.close()
        update = ("GameOfLifeOperations.Update",)
        outbound = _series(status["metrics"], "gol_rpc_client_requests_total")
        # 10 turns scattered over 2 workers: 20 Update calls
        assert outbound[update]["value"] == 20
        sent = _series(status["metrics"], "gol_rpc_client_sent_bytes_total")
        assert sent[update]["value"] > 0
        lat = _series(status["metrics"], "gol_rpc_client_request_seconds")
        assert lat[update]["count"] == 20

        from gol_distributed_final_tpu.obs.status import fetch_status

        wstatus = fetch_status(f"127.0.0.1:{ports[0]}", worker=True)
        assert wstatus["role"] == "worker"
        inbound = _series(
            wstatus["metrics"], "gol_rpc_server_requests_total"
        )
        assert inbound[update]["value"] == 10
    finally:
        for proc in (*workers, *( [broker] if broker else [] )):
            if proc.poll() is None:
                proc.kill()
            proc.wait()


def test_run_report_written_and_parseable(live_metrics, tmp_path):
    """A short headless run with -report semantics: the RunReport exists,
    parses, and its per-turn step histogram count equals the turn count
    (the acceptance criterion, scaled down for CI)."""
    p = Params(turns=30, threads=8, image_width=64, image_height=64)
    result = run(
        p,
        queue.Queue(),
        images_dir=REPO_ROOT / "images",
        out_dir=tmp_path / "out",
        tick_seconds=3600.0,
        report=True,
    )
    assert result.turns_completed == 30
    path = tmp_path / "out" / "report_64x64x30.json"
    assert path.exists()
    report = json.loads(path.read_text())
    assert report["schema"] == "gol-run-report/1"
    assert report["params"]["turns"] == 30
    assert report["wall_seconds"] > 0
    assert report["devices"]["local_devices"], "device inventory missing"
    (step,) = _series(report["metrics"], "gol_engine_step_seconds").values()
    assert step["count"] == 30
    assert "gol_engine_step_seconds" in report["stage_timings"]
    assert report["stage_timings"]["gol_engine_turns_total"] == 30
    events = _series(report["metrics"], "gol_controller_events_total")
    assert events[("FinalTurnComplete",)]["value"] == 1


def test_report_flag_off_writes_nothing(tmp_path):
    p = Params(turns=4, threads=8, image_width=16, image_height=16)
    run(
        p,
        queue.Queue(),
        images_dir=REPO_ROOT / "images",
        out_dir=tmp_path / "out",
        tick_seconds=3600.0,
    )
    assert not list((tmp_path / "out").glob("report_*.json"))


# -- tooling -----------------------------------------------------------------


def test_every_registered_metric_is_documented():
    """The check-style lint: obs/instruments.py and the README table are
    one contract — an instrument added without docs fails here."""
    from gol_distributed_final_tpu.obs.lint import undocumented_metrics

    assert undocumented_metrics() == []


def test_status_cli_formats(live_metrics, capsys):
    """The operator one-liner renders both formats against a live server."""
    from gol_distributed_final_tpu.obs.status import main as status_main
    from gol_distributed_final_tpu.rpc.broker import serve

    server, service = serve(port=0)
    try:
        assert status_main([f"127.0.0.1:{server.port}"]) == 0
        payload = json.loads(capsys.readouterr().out)
        assert payload["metrics_enabled"] is True
        assert status_main(["-format", "prom", f":{server.port}"]) == 0
        parsed = parse_prometheus_text(capsys.readouterr().out)
        assert 'gol_rpc_server_requests_total{method="Operations.Status"}' in parsed
    finally:
        server.stop()
