"""Bitboard data-plane tests: packing, the carry-save adder step, the
pallas kernel (interpret mode), and automatic plane selection."""

import numpy as np
import pytest

import jax.numpy as jnp

from gol_distributed_final_tpu.models import CONWAY, HIGHLIFE
from gol_distributed_final_tpu.ops import bitpack
from gol_distributed_final_tpu.ops.auto import auto_step_n_fn
from gol_distributed_final_tpu.ops.pallas_stencil import pallas_bit_step_n_fn

from oracle import vector_step


def random_board(h, w, seed=0, density=0.35):
    rng = np.random.default_rng(seed)
    return np.where(rng.random((h, w)) < density, 255, 0).astype(np.uint8)


@pytest.mark.parametrize("word_axis", [0, 1])
@pytest.mark.parametrize("shape", [(32, 32), (64, 96), (96, 64), (32, 256)])
def test_pack_roundtrip(word_axis, shape):
    board = random_board(*shape, seed=shape[0] + word_axis)
    packed = bitpack.pack(board, word_axis)
    np.testing.assert_array_equal(bitpack.unpack(packed, word_axis), board)


def test_pack_rejects_indivisible():
    with pytest.raises(ValueError, match="not divisible"):
        bitpack.pack(random_board(33, 32), word_axis=0)
    with pytest.raises(ValueError, match="not divisible"):
        bitpack.pack(random_board(32, 33), word_axis=1)


@pytest.mark.parametrize("word_axis", [0, 1])
def test_bit_step_matches_oracle(word_axis):
    board = random_board(64, 96, seed=3)
    packed = bitpack.pack(board, word_axis)
    want = board
    for turn in range(5):
        packed = jnp.asarray(bitpack.bit_step(packed, word_axis))
        want = vector_step(want)
        got = bitpack.unpack(np.asarray(packed), word_axis)
        np.testing.assert_array_equal(got, want, err_msg=f"turn {turn}")


def test_bit_step_n_long_run_golden():
    """1000 turns on the shipped 64x64 board must match the golden CSV."""
    from gol_distributed_final_tpu.io.pgm import read_pgm

    from helpers import REPO_ROOT, read_alive_counts

    counts = read_alive_counts(REPO_ROOT / "check" / "alive" / "64x64.csv")
    board = read_pgm(REPO_ROOT / "images" / "64x64.pgm")
    packed = bitpack.pack(board, 0)
    for n in (1, 100, 1000):
        out = bitpack.bit_step_n(bitpack.pack(board, 0), n, 0)
        alive = int(np.count_nonzero(bitpack.unpack(np.asarray(out), 0)))
        assert alive == counts[n], f"turn {n}: {alive} != {counts[n]}"


def test_packed_step_n_fn_engine_shape():
    fn = bitpack.packed_step_n_fn(0)
    board = random_board(32, 64, seed=9)
    out = np.asarray(fn(board, 7))
    want = board
    for _ in range(7):
        want = vector_step(want)
    np.testing.assert_array_equal(out, want)
    assert out.dtype == np.uint8


@pytest.mark.parametrize("word_axis", [0, 1])
def test_pallas_bit_kernel_interpret(word_axis):
    """The pallas kernel path, run in interpreter mode on CPU."""
    fn = pallas_bit_step_n_fn(word_axis=word_axis, interpret=True)
    board = random_board(32, 32, seed=4)
    got = np.asarray(fn(board, 3))
    want = board
    for _ in range(3):
        want = vector_step(want)
    np.testing.assert_array_equal(got, want)


def test_auto_plane_selection():
    # any life-like rule + divisible axis -> a bit plane (XLA flavour on CPU)
    assert auto_step_n_fn(CONWAY, (64, 64)) is not None
    assert auto_step_n_fn(CONWAY, (64, 50)) is not None  # h % 32 == 0
    assert auto_step_n_fn(CONWAY, (50, 64)) is not None  # w % 32 == 0
    assert auto_step_n_fn(HIGHLIFE, (64, 64)) is not None
    # indivisible -> None (roll stencil handles it)
    assert auto_step_n_fn(CONWAY, (50, 50)) is None


@pytest.mark.parametrize(
    "rulename,birth,survive",
    [
        ("highlife", (3, 6), (2, 3)),
        ("seeds", (2,), ()),
        ("day-and-night", (3, 6, 7, 8), (3, 4, 6, 7, 8)),
    ],
)
def test_bit_step_general_rules(rulename, birth, survive):
    from gol_distributed_final_tpu.models import LifeRule

    rule = LifeRule.from_rulestring(
        "B" + "".join(map(str, birth)) + "/S" + "".join(map(str, survive))
    )
    fn = bitpack.packed_step_n_fn(0, rule=rule)
    board = random_board(64, 64, seed=11)
    got = np.asarray(fn(board, 4))
    want = board
    for _ in range(4):
        want = vector_step(want, birth=birth, survive=survive)
    np.testing.assert_array_equal(got, want, err_msg=rulename)


def test_pallas_bit_kernel_general_rule_interpret():
    fn = pallas_bit_step_n_fn(word_axis=0, interpret=True, rule=HIGHLIFE)
    board = random_board(32, 32, seed=12)
    got = np.asarray(fn(board, 3))
    want = board
    for _ in range(3):
        want = vector_step(want, birth=(3, 6), survive=(2, 3))
    np.testing.assert_array_equal(got, want)


def test_engine_auto_fast_golden(tmp_path):
    """Engine auto-selects the bit plane; run must stay golden-exact, and
    disabling auto_fast must agree."""
    import queue

    from gol_distributed_final_tpu import FinalTurnComplete, Params, run
    from gol_distributed_final_tpu.engine.engine import EngineConfig
    from gol_distributed_final_tpu.engine.controller import CLOSED

    from helpers import REPO_ROOT, assert_equal_board, read_alive_cells

    expected = read_alive_cells(REPO_ROOT / "check" / "images" / "64x64x100.pgm")
    for auto in (True, False):
        p = Params(turns=100, image_width=64, image_height=64)
        events = queue.Queue()
        run(
            p,
            events,
            engine_config=EngineConfig(auto_fast=auto),
            images_dir=REPO_ROOT / "images",
            out_dir=tmp_path / f"out{auto}",
            tick_seconds=3600,
        )
        final = None
        while True:
            ev = events.get_nowait()
            if ev is CLOSED:
                break
            if isinstance(ev, FinalTurnComplete):
                final = ev
        assert_equal_board(final.alive, expected, 64, 64)


def test_vmem_gate_falls_back_on_compile_failure(monkeypatch):
    """If the whole-board VMEM kernel fails at compile/call time (the
    fits_vmem working-set factor is a measured heuristic — a board near the
    boundary can OOM under a new compiler), BitPlane.step_n must fall back
    to a correct path and cache the decision instead of crashing."""
    from gol_distributed_final_tpu.ops import plane as plane_mod
    from gol_distributed_final_tpu.ops.plane import BitPlane

    calls = {"n": 0}

    def exploding_compile(*args, **kwargs):
        calls["n"] += 1

        def run(packed):
            raise RuntimeError("Mosaic: RESOURCE_EXHAUSTED: VMEM allocation")

        return run

    from gol_distributed_final_tpu.ops import pallas_stencil

    monkeypatch.setattr(pallas_stencil, "_bit_compiled", exploding_compile)
    monkeypatch.setattr(plane_mod, "_VMEM_KERNEL_OK", {})

    board = random_board(64, 64, seed=5)
    plane = BitPlane(word_axis=0)
    state = plane.encode(board)
    got = plane.decode(plane.step_n(state, 7))
    want = board
    for _ in range(7):
        want = vector_step(want)
    np.testing.assert_array_equal(got, want)
    assert calls["n"] == 1

    # the failure is cached per shape: the second call skips the attempt
    plane.step_n(state, 3)
    assert calls["n"] == 1


def test_any_rule_bitboard_matches_oracle_property():
    """Property: for ANY B/S rule in the full 2^18 rule space, the
    bit-sliced CSA bitboard agrees with the independent numpy oracle.
    The named-rule tests pin 4 points; this sweeps randomly drawn ones
    (hypothesis) — a masked term lost in the adder tree for some
    neighbour count would be caught here."""
    # gate, don't fail: hypothesis is absent from some CI images
    pytest.importorskip("hypothesis")
    from hypothesis import given, settings
    from hypothesis import strategies as st

    @settings(max_examples=20, deadline=None)
    @given(
        birth=st.sets(st.integers(0, 8)),
        survive=st.sets(st.integers(0, 8)),
        seed=st.integers(0, 2**31),
    )
    def check(birth, survive, seed):
        rng = np.random.default_rng(seed)
        board = np.where(rng.random((64, 64)) < 0.4, 255, 0).astype(np.uint8)
        bmask = sum(1 << c for c in birth)
        smask = sum(1 << c for c in survive)
        got = bitpack.unpack(
            np.asarray(bitpack.bit_step_n(bitpack.pack(board, 0), 3, 0, bmask, smask)),
            0,
        )
        want = board
        for _ in range(3):
            want = vector_step(want, birth=tuple(birth), survive=tuple(survive))
        np.testing.assert_array_equal(got, want)

    check()
