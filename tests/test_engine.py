"""Engine control-plane tests: pause/resume, quit, detach/reattach, snapshot
consistency, and the CellFlipped/TurnComplete protocol (the TestSdl contract,
sdl_test.go:18-116)."""

import queue
import threading
import time

import numpy as np

from gol_distributed_final_tpu import (
    AliveCellsCount,
    CellFlipped,
    FinalTurnComplete,
    Params,
    StateChange,
    State,
    TurnComplete,
)
from gol_distributed_final_tpu.engine import Engine
from gol_distributed_final_tpu.engine.engine import EngineConfig
from gol_distributed_final_tpu.io.pgm import read_pgm
from gol_distributed_final_tpu import run
from gol_distributed_final_tpu.engine.controller import CLOSED

from helpers import REPO_ROOT
from oracle import vector_step


def small_board(seed=0, size=16):
    rng = np.random.default_rng(seed)
    return np.where(rng.random((size, size)) < 0.3, 255, 0).astype(np.uint8)


def run_in_thread(engine, params, world, **kw):
    result = {}

    def target():
        result["run"] = engine.run(params, world, **kw)

    t = threading.Thread(target=target)
    t.start()
    return t, result


def test_retrieve_snapshot_is_consistent():
    engine = Engine(EngineConfig(max_chunk=1))
    world = small_board()
    p = Params(turns=200, image_width=16, image_height=16)
    t, result = run_in_thread(engine, p, world)
    seen = []
    while t.is_alive():
        snap = engine.retrieve()
        seen.append(snap)
        time.sleep(0.001)
    t.join()
    # every snapshot's world must be exactly the oracle's board at that turn
    boards = {0: world}
    b = world
    for i in range(1, 201):
        b = vector_step(b)
        boards[i] = b
    for snap in seen:
        np.testing.assert_array_equal(snap.world, boards[snap.turns_completed])
        assert snap.alive_count == int(np.count_nonzero(boards[snap.turns_completed]))


def test_pause_stops_progress_and_resume_continues():
    engine = Engine(EngineConfig(max_chunk=4))
    p = Params(turns=100_000, image_width=16, image_height=16)
    t, result = run_in_thread(engine, p, small_board(1))
    time.sleep(0.3)
    assert engine.pause() is True
    turn_a = engine.retrieve().turns_completed
    time.sleep(0.3)
    turn_b = engine.retrieve().turns_completed
    assert turn_b == turn_a  # no progress while paused
    assert engine.pause() is False
    time.sleep(0.3)
    assert engine.retrieve().turns_completed > turn_b
    engine.quit()
    t.join(timeout=10)
    assert not t.is_alive()


def test_quit_then_reattach_fresh_run():
    """'q' detaches the controller; the engine survives and a new Run starts
    from scratch (README.md:187, broker/broker.go:64)."""
    engine = Engine(EngineConfig(max_chunk=4))
    p = Params(turns=100_000, image_width=16, image_height=16)
    t, result = run_in_thread(engine, p, small_board(2))
    time.sleep(0.2)
    engine.quit()
    t.join(timeout=10)
    first = result["run"]
    assert 0 < first.turns_completed < 100_000

    # reattach: fresh run resets the turn counter
    p2 = Params(turns=3, image_width=16, image_height=16)
    second = engine.run(p2, small_board(3))
    assert second.turns_completed == 3


def test_zero_turns_board_passthrough():
    engine = Engine()
    world = small_board(4)
    p = Params(turns=0, image_width=16, image_height=16)
    res = engine.run(p, world)
    assert res.turns_completed == 0
    np.testing.assert_array_equal(res.world, world)


def test_flip_protocol_reconstructs_every_turn(tmp_path):
    """TestSdl's contract: applying CellFlipped XORs reproduces the board at
    every TurnComplete, and flips precede their TurnComplete
    (sdl_test.go:56-74, gol/event.go:55-57)."""
    p = Params(turns=8, image_width=16, image_height=16)
    events = queue.Queue()
    run(
        p,
        events,
        emit_flips=True,
        images_dir=REPO_ROOT / "images",
        out_dir=tmp_path / "out",
        tick_seconds=3600,
    )
    shadow = np.zeros((16, 16), np.uint8)
    oracle_board = read_pgm(REPO_ROOT / "images" / "16x16.pgm")
    turn = 0
    saw_final = False
    while True:
        ev = events.get_nowait()
        if ev is CLOSED:
            break
        if isinstance(ev, CellFlipped):
            x, y = ev.cell
            shadow[y, x] ^= 255
        elif isinstance(ev, TurnComplete):
            turn += 1
            assert ev.completed_turns == turn
            oracle_board = vector_step(oracle_board)
            np.testing.assert_array_equal(shadow, oracle_board)
        elif isinstance(ev, FinalTurnComplete):
            saw_final = True
    assert turn == 8 and saw_final


def test_quit_before_run_starts_still_quits():
    """A 'q' that lands between ticker start and run-loop init must not be
    discarded: the run should end immediately."""
    engine = Engine(EngineConfig(max_chunk=4))
    engine.quit()
    p = Params(turns=100_000, image_width=16, image_height=16)
    res = engine.run(p, small_board(7))
    assert res.turns_completed == 0
    # and the quit is consumed: a fresh run proceeds normally
    assert engine.run(Params(turns=2, image_width=16, image_height=16), small_board(7)).turns_completed == 2


def test_pause_before_run_starts_run_starts_parked():
    engine = Engine(EngineConfig(max_chunk=4))
    engine.pause()  # before any run
    p = Params(turns=100_000, image_width=16, image_height=16)
    t, _ = run_in_thread(engine, p, small_board(8))
    time.sleep(0.3)
    assert engine.retrieve(include_world=False).turns_completed == 0
    engine.pause()  # resume
    time.sleep(0.3)
    assert engine.retrieve(include_world=False).turns_completed > 0
    engine.quit()
    t.join(timeout=10)


def test_count_only_snapshot_alive_is_empty():
    engine = Engine()
    engine.run(Params(turns=1, image_width=16, image_height=16), small_board(9))
    snap = engine.retrieve(include_world=False)
    assert snap.world is None and snap.alive == []


def test_super_quit_sets_flag():
    engine = Engine(EngineConfig(max_chunk=2))
    p = Params(turns=100_000, image_width=16, image_height=16)
    t, _ = run_in_thread(engine, p, small_board(5))
    time.sleep(0.1)
    engine.super_quit()
    t.join(timeout=10)
    assert engine.super_quit_requested


def test_pipeline_engages_when_growth_stops_at_max_chunk():
    """Once chunk doubling hits max_chunk the loop must dispatch
    asynchronously (no per-chunk block_until_ready): a step result whose
    block_until_ready is counted should be awaited far fewer times than
    there are chunks."""
    board = small_board(3, 64)
    syncs = {"n": 0}

    class Counting:
        def __init__(self, arr):
            self.arr = arr

        def block_until_ready(self):
            syncs["n"] += 1
            return self

    import jax.numpy as jnp
    from gol_distributed_final_tpu.models import CONWAY

    def step_n(b, n):
        out = CONWAY.step_n(jnp.asarray(getattr(b, "arr", b)), int(n))
        return Counting(out)

    class WrapPlane:
        rule = CONWAY

        def encode(self, b):
            return jnp.asarray(b)

        def step_n(self, state, n):
            return step_n(state, n)

        def decode(self, state):
            return np.asarray(getattr(state, "arr", state))

        def alive_count(self, state):
            return int(np.count_nonzero(self.decode(state)))

    # 64 chunks of 4 turns after instant growth: with the depth-3 window,
    # syncs ~= chunks - depth; the old synchronous loop did one per chunk
    eng = Engine(EngineConfig(min_chunk=4, max_chunk=4))
    res = eng.run(
        Params(turns=256, image_width=64, image_height=64),
        board,
        plane=WrapPlane(),
    )
    assert res.turns_completed == 256
    n_chunks = 256 // 4
    assert syncs["n"] <= n_chunks - 2, syncs["n"]
    # parity: pipelining must not change the result
    want = board
    for _ in range(256):
        want = vector_step(want)
    np.testing.assert_array_equal(res.world, want)


def test_pipeline_engages_when_growth_stops_on_slow_dispatch():
    """Growth can also end via target_dispatch_seconds (large boards never
    reach max_chunk). Later chunks must then go through the async window
    rather than paying a synchronous wait per chunk — the round-3 review
    caught exactly this path staying synchronous forever."""
    board = small_board(4, 64)
    calls = {"sync": 0, "chunks": []}

    import jax.numpy as jnp
    from gol_distributed_final_tpu.models import CONWAY

    class SlowPlane:
        rule = CONWAY

        def encode(self, b):
            return jnp.asarray(b)

        def step_n(self, state, n):
            calls["chunks"].append(int(n))
            out = CONWAY.step_n(getattr(state, "arr", state), int(n))

            class R:
                def __init__(self, arr):
                    self.arr = arr

                def block_until_ready(self):
                    calls["sync"] += 1
                    time.sleep(0.05)  # every dispatch exceeds the target
                    return self

            return R(out)

        def decode(self, state):
            return np.asarray(getattr(state, "arr", state))

        def alive_count(self, state):
            return int(np.count_nonzero(self.decode(state)))

    eng = Engine(
        EngineConfig(min_chunk=1, max_chunk=1 << 20, target_dispatch_seconds=0.01)
    )
    res = eng.run(
        Params(turns=40, image_width=64, image_height=64),
        board,
        plane=SlowPlane(),
    )
    assert res.turns_completed == 40
    # first chunk (size 1) is timed synchronously and ends growth; the
    # remaining 39 single-turn chunks flow through the depth-3 window
    assert calls["chunks"][0] == 1 and len(calls["chunks"]) == 40
    assert calls["sync"] <= len(calls["chunks"]) - 2, calls["sync"]


def test_retrieve_world_raises_on_byte_free_engine():
    """A final_world=False engine must refuse retrieve(include_world=True):
    decoding the full byte raster is exactly what that configuration
    promises never happens (the broker wrappers already enforce this; the
    Engine surface itself must too)."""
    import pytest

    from gol_distributed_final_tpu.ops import bitpack
    from gol_distributed_final_tpu.ops.plane import BitPlane

    engine = Engine(EngineConfig(final_world=False))
    engine.run(
        Params(turns=2, image_width=64, image_height=64),
        None,
        plane=BitPlane(),
        initial_state=bitpack.pack(small_board(11, 64), 0),
    )
    with pytest.raises(ValueError, match="include_world"):
        engine.retrieve()
    # the count-only path stays open
    snap = engine.retrieve(include_world=False)
    assert snap.world is None and snap.turns_completed == 2


def test_checkpoint_io_error_does_not_abort_run(tmp_path):
    """A failing checkpoint write (disk full, bad path) must not kill the
    multi-hour run it exists to protect: the run completes and the failure
    is surfaced on the RunResult."""
    blocker = tmp_path / "not_a_dir"
    blocker.write_text("a file where the checkpoint wants a directory")
    cfg = EngineConfig(
        min_chunk=10,
        max_chunk=10,
        checkpoint_every=30,
        checkpoint_path=str(blocker / "ck.npz"),  # mkdir will fail
    )
    res = Engine(cfg).run(
        Params(turns=100, image_width=64, image_height=64), small_board(12, 64)
    )
    assert res.turns_completed == 100
    assert isinstance(res.checkpoint_error, OSError)


def test_ticker_survives_snapshot_failure_and_still_quits():
    """A failing snapshot ('s' on a broker that cannot ship a world) must
    not kill the control thread, and 'q' must still quit even when its
    final snapshot fails — otherwise the engine runs forever with no way
    to stop it."""
    from gol_distributed_final_tpu.engine.controller import _Ticker
    from gol_distributed_final_tpu.engine.engine import Snapshot

    class ByteFreeBroker:
        def __init__(self):
            self.quit_called = threading.Event()

        def retrieve(self, include_world=True):
            if include_world:
                raise ValueError("no byte raster on this surface")
            return Snapshot(None, 5, 7)

        def quit(self):
            self.quit_called.set()

        def pause(self):
            pass

        def super_quit(self):
            pass

    broker = ByteFreeBroker()
    events, keys = queue.Queue(), queue.Queue()
    ticker = _Ticker(
        Params(turns=10, image_width=16, image_height=16),
        events, keys, broker, "out", 3600.0,
    )
    ticker.start()
    try:
        keys.put("s")  # snapshot raises; thread must survive
        time.sleep(0.2)
        assert ticker._thread.is_alive(), "ticker died on a failed snapshot"
        keys.put("q")  # final snapshot raises too; quit must still land
        assert broker.quit_called.wait(timeout=5), "'q' did not reach quit()"
        quits = [e for e in iter_drain(events) if isinstance(e, StateChange)]
        assert quits and quits[-1].new_state == State.QUITTING
        assert quits[-1].completed_turns == 5  # count-only fallback turn
    finally:
        ticker.stop()


def iter_drain(q):
    out = []
    while True:
        try:
            out.append(q.get_nowait())
        except queue.Empty:
            return out


def test_ticker_quits_even_when_broker_is_dead():
    """'q' on a fully dead broker (every retrieve raises) must still set
    done and deliver quit() — the turn falls back to the last one a
    successful tick saw."""
    from gol_distributed_final_tpu.engine.controller import _Ticker

    class DeadBroker:
        def __init__(self):
            self.quit_called = threading.Event()

        def retrieve(self, include_world=True):
            raise OSError("connection lost")

        def quit(self):
            self.quit_called.set()

        def pause(self):
            pass

        def super_quit(self):
            pass

    broker = DeadBroker()
    events, keys = queue.Queue(), queue.Queue()
    ticker = _Ticker(
        Params(turns=10, image_width=16, image_height=16),
        events, keys, broker, "out", 0.05,  # fast ticks: they fail too
    )
    ticker.start()
    try:
        time.sleep(0.2)  # several failing ticks; thread must survive them
        assert ticker._thread.is_alive(), "ticker died on failing ticks"
        keys.put("q")
        assert broker.quit_called.wait(timeout=5), "'q' did not reach quit()"
        assert ticker.done.is_set()
    finally:
        ticker.stop()


def test_multihost_checkpoint_without_packed_plane_raises():
    """checkpoint_every on a multi-host state whose plane has no packed
    shard format must fail AT RUN ENTRY, not silently skip every write
    (VERDICT round 3 item 4) and not hours into a pod run."""
    import pytest

    from gol_distributed_final_tpu.models import CONWAY

    class FakeGlobalState:
        is_fully_addressable = False

    class NoWordAxisPlane:
        rule = CONWAY

        def step_n(self, state, n):
            raise AssertionError("must not be reached")

    engine = Engine(
        EngineConfig(final_world=False, checkpoint_every=10)
    )
    with pytest.raises(ValueError, match="word_axis"):
        engine.run(
            Params(turns=100, image_width=64, image_height=64),
            None,
            plane=NoWordAxisPlane(),
            initial_state=FakeGlobalState(),
        )
    # the engine is reusable after the rejected run
    assert not engine._running


def test_chunk_hook_exception_leaves_engine_reusable():
    """A failing chunk gate (e.g. a pod broadcast whose peer died) must
    propagate — the caller decides recovery — but the engine must come
    back reusable: _running cleared, a fresh run accepted."""
    import pytest

    calls = {"n": 0}

    def bad_hook(engine, state, turn):
        calls["n"] += 1
        if calls["n"] == 2:
            raise ConnectionError("peer rank vanished")

    cfg = EngineConfig(min_chunk=2, max_chunk=2, chunk_hook=bad_hook)
    engine = Engine(cfg)
    p = Params(turns=100, image_width=16, image_height=16)
    with pytest.raises(ConnectionError):
        engine.run(p, small_board(13))
    assert not engine._running
    # the hook keeps firing on the rerun (fresh call counter from 3 on):
    # turns=4 with chunk 2 gates twice, so the counter must reach 4
    res = engine.run(Params(turns=4, image_width=16, image_height=16), small_board(13))
    assert res.turns_completed == 4
    assert calls["n"] == 4, "chunk_hook was disabled by the earlier failure"


def test_control_plane_soak_random_keys(tmp_path):
    """Monkey-test the session control plane: a random p/s/p/... key
    stream (seeded, ending in 'q') drives a long 64^2 session while the
    2-tick invariants are checked against the ONE-dispatch per-turn
    history oracle (bitpack.alive_history): every AliveCellsCount must be
    exact for its reported turn, whatever interleaving of pauses,
    snapshots, and chunk commits produced it; the final board must equal
    the history's state at turns_completed."""
    import random

    from gol_distributed_final_tpu.ops import bitpack

    board = read_pgm(REPO_ROOT / "images" / "64x64.pgm")
    packed = bitpack.pack(board, 0)
    N = 200_000
    history = np.asarray(bitpack.alive_history(packed, N))  # counts, turn 1..N

    events, keys = queue.Queue(), queue.Queue()
    rng = random.Random(7)

    def feeder():
        pauses = 0
        for _ in range(12):
            key = rng.choice(["p", "s", "p"])
            pauses += key == "p"
            keys.put(key)
            time.sleep(0.08)
        if pauses % 2:  # ensure 'q' lands on a RUNNING session
            keys.put("p")
        keys.put("q")

    t = threading.Thread(target=feeder)
    t.start()
    result = run(
        Params(turns=N, image_width=64, image_height=64),
        events,
        keys,
        images_dir=REPO_ROOT / "images",
        out_dir=tmp_path / "out",
        tick_seconds=0.03,
    )
    t.join()

    initial_alive = int(np.count_nonzero(board))
    collected = []
    while True:
        ev = events.get_nowait()
        if ev is CLOSED:
            break
        collected.append(ev)
    ticks = [e for e in collected if isinstance(e, AliveCellsCount)]
    assert ticks, "soak produced no tick events"
    for e in ticks:
        want = (
            initial_alive
            if e.completed_turns == 0
            else int(history[e.completed_turns - 1])
        )
        assert e.cells_count == want, (
            f"turn {e.completed_turns}: {e.cells_count} != {want}"
        )
    finals = [e for e in collected if isinstance(e, FinalTurnComplete)]
    assert len(finals) == 1
    done = result.turns_completed
    assert 0 < done <= N
    assert len(finals[0].alive) == int(history[done - 1])
    # the final world is exactly the history state at that turn
    want_board = np.asarray(
        bitpack.unpack(np.asarray(bitpack.bit_step_n(packed, done, 0)), 0)
    )
    np.testing.assert_array_equal(result.world, want_board)
