"""The native window ABI, exercised for real (VERDICT round 3 item 2).

libSDL2 is absent from this image, so ``native/window.cc`` is built against
the vendored no-op SDL stub (``native/sdl2_stub/``) — producing a .so with
the SAME eight golwin_* exports the real build has — and loaded through the
REAL ``SdlWindow`` ctypes path. This is the test that fails when window.cc's
exported C ABI and the ctypes declarations in viz/window.py drift apart:
a renamed/removed symbol fails the CDLL attribute lookup at declaration
time, and a signature change shows up as a shadow/native state mismatch
(golwin_count_pixels is compared against the Python-side pixel shadow after
every mutation).

Reference anchor: sdl/window.go:10-104 (the reference's only native-code
component, reached through cgo; here through ctypes).
"""

import ctypes
import pathlib
import shutil
import subprocess

import pytest

NATIVE_DIR = (
    pathlib.Path(__file__).resolve().parent.parent
    / "gol_distributed_final_tpu"
    / "native"
)

pytestmark = pytest.mark.skipif(
    shutil.which("g++") is None or shutil.which("make") is None,
    reason="native toolchain unavailable",
)


@pytest.fixture(scope="module")
def stub_lib():
    subprocess.run(
        ["make", "libgolwindow_stub.so"],
        cwd=NATIVE_DIR,
        check=True,
        capture_output=True,
        text=True,
    )
    return NATIVE_DIR / "libgolwindow_stub.so"


def test_all_declared_symbols_exist(stub_lib):
    """Every golwin_* function viz/window.py declares ctypes signatures
    for must be exported by window.cc — catching a rename/removal on
    either side."""
    lib = ctypes.CDLL(str(stub_lib))
    for sym in (
        "golwin_create",
        "golwin_flip_pixel",
        "golwin_set_pixel",
        "golwin_count_pixels",
        "golwin_clear_pixels",
        "golwin_render_frame",
        "golwin_poll_key",
        "golwin_destroy",
    ):
        getattr(lib, sym)  # raises AttributeError on a missing export


def test_sdlwindow_drives_native_abi(stub_lib):
    """Construct the REAL SdlWindow over the stub-backed library and drive
    flip/set/count/clear/render through it; after every mutation the
    native pixel buffer's count must equal the Python shadow's — a
    truncated handle or misdeclared argument diverges (or crashes) here."""
    from gol_distributed_final_tpu.viz.window import SdlWindow

    win = SdlWindow(16, 8, "abi-test", lib_path=stub_lib)
    try:
        native_count = lambda: int(
            win._lib.golwin_count_pixels(win._handle)
        )
        assert native_count() == 0

        win.flip_pixel(0, 0)
        win.flip_pixel(15, 7)
        win.flip_pixel(3, 4)
        assert win.count_pixels() == 3 == native_count()

        win.flip_pixel(3, 4)  # flip back off
        assert win.count_pixels() == 2 == native_count()

        win.set_pixel(5, 5)
        win.set_pixel(6, 5, 0x00ABCDEF)
        assert win.count_pixels() == 4 == native_count()

        win.render_frame()
        win.render_frame()
        assert int(win._lib.sdl_stub_render_count()) >= 2

        win.clear_pixels()
        assert win.count_pixels() == 0 == native_count()

        # bounds panic still comes from the shared Python check
        with pytest.raises(IndexError):
            win.flip_pixel(16, 0)
    finally:
        win.destroy()
    assert win._handle is None  # destroy() cleared the handle


def test_poll_key_through_native_switch(stub_lib):
    """Inject events through the stub queue and read them back through the
    REAL golwin_poll_key switch: p/s/q/k map to themselves, other keys are
    swallowed, window-close maps to 'q', empty queue is None
    (sdl/loop.go:16-28 semantics)."""
    from gol_distributed_final_tpu.viz.window import SdlWindow

    win = SdlWindow(4, 4, "keys", lib_path=stub_lib)
    try:
        assert win.poll_key() is None
        for ch in "pqsk":
            win._lib.sdl_stub_push_key(ord(ch))
        win._lib.sdl_stub_push_key(ord("x"))  # not in the keymap
        assert win.poll_key() == "p"
        assert win.poll_key() == "q"
        assert win.poll_key() == "s"
        # 'k' then 'x': the switch swallows 'x' inside one poll loop, so
        # 'k' is returned and the queue is empty afterwards
        assert win.poll_key() == "k"
        assert win.poll_key() is None
        win._lib.sdl_stub_push_quit()
        assert win.poll_key() == "q"  # window close quits the controller
    finally:
        win.destroy()


def _stub_hooks(lib):
    lib.sdl_stub_trace.restype = ctypes.c_char_p
    lib.sdl_stub_violations.restype = ctypes.c_char_p
    return lib


def test_sdl_usage_contract_full_session(stub_lib):
    """The stub is BEHAVIORAL (VERDICT r4 item 2): it records the SDL call
    sequence and validates arguments (texture pitch == W*4, ARGB8888 +
    STREAMING texture, live-handle use, update/clear/copy/present frame
    ordering, create/destroy pairing). Driving a real window lifecycle
    must leave zero violations and exactly the reference's call shape
    (sdl/window.go:40-104: NewWindow -> RenderFrame* -> Destroy)."""
    from gol_distributed_final_tpu.viz.window import SdlWindow

    lib = _stub_hooks(ctypes.CDLL(str(stub_lib)))
    lib.sdl_stub_reset()
    win = SdlWindow(16, 8, "contract", lib_path=stub_lib)
    win.flip_pixel(2, 3)
    win.render_frame()
    win.render_frame()
    win.destroy()
    assert lib.sdl_stub_violations() == b"", lib.sdl_stub_violations()
    frame = "Update,Clear,Copy,Present"
    want = f"Init,CreateWindow,CreateRenderer,CreateTexture,{frame},{frame}," \
           "DestroyTexture,DestroyRenderer,DestroyWindow,Quit"
    assert lib.sdl_stub_trace().decode() == want
    lib.sdl_stub_reset()


def test_sdl_contract_validator_is_not_vacuous(stub_lib):
    """The validator actually fires on misuse: a texture created against a
    bogus renderer, and an update with a sheared pitch, are both recorded
    as violations — so the clean-trace assertion above is meaningful."""
    lib = _stub_hooks(ctypes.CDLL(str(stub_lib)))
    lib.sdl_stub_reset()
    lib.SDL_CreateTexture.restype = ctypes.c_void_p
    lib.SDL_CreateTexture.argtypes = [
        ctypes.c_void_p, ctypes.c_uint32, ctypes.c_int,
        ctypes.c_int, ctypes.c_int,
    ]
    lib.SDL_CreateTexture(None, 0x16362004, 1, 8, 8)
    assert b"SDL_CreateTexture" in lib.sdl_stub_violations()
    lib.sdl_stub_reset()

    # a correct session, then a WRONG-pitch update through the raw API
    from gol_distributed_final_tpu.viz.window import SdlWindow

    win = SdlWindow(16, 8, "pitch", lib_path=stub_lib)
    try:
        assert lib.sdl_stub_violations() == b""
        lib.SDL_UpdateTexture.restype = ctypes.c_int
        lib.SDL_UpdateTexture.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int,
        ]
        # reach the live texture the same way window.cc stores it: first
        # field of the GolWindow struct is the SDL_Window*, then renderer,
        # texture — instead of guessing offsets, misuse via a fresh call:
        # pitch in PIXELS (16) instead of bytes (64), classic shear bug
        buf = (ctypes.c_uint8 * (16 * 8 * 4))()
        tex = ctypes.cast(
            ctypes.cast(win._handle, ctypes.POINTER(ctypes.c_void_p))[2],
            ctypes.c_void_p,
        )
        lib.SDL_UpdateTexture(tex, None, buf, 16)
        assert b"pitch 16 != width*4 (64)" in lib.sdl_stub_violations()
    finally:
        win.destroy()
    lib.sdl_stub_reset()


def test_keysym_offsets_roundtrip_real_layout(stub_lib):
    """The vendored SDL_Event now mirrors real SDL2's union layout: sym at
    byte offset 20, event size 56. push_key writes through the struct and
    golwin_poll_key reads it back — if window.cc (or the header) drifted
    from the real field offsets, the key would come back garbled."""
    from gol_distributed_final_tpu.viz.window import SdlWindow

    win = SdlWindow(4, 4, "offsets", lib_path=stub_lib)
    try:
        win._lib.sdl_stub_push_key(ord("s"))
        assert win.poll_key() == "s"
    finally:
        win.destroy()


def test_make_window_uses_native_when_present(stub_lib, monkeypatch):
    """make_window's SDL branch: with a loadable library at _WINDOW_LIB the
    native window is selected (this image never exercises that branch
    otherwise)."""
    import gol_distributed_final_tpu.viz.window as winmod

    monkeypatch.setattr(winmod, "_WINDOW_LIB", stub_lib)
    w = winmod.make_window(8, 8)
    try:
        assert isinstance(w, winmod.SdlWindow)
    finally:
        w.destroy()
