"""End-to-end data-integrity suite (rpc/integrity.py + the three planes).

Covers the silent-corruption contract PR 4/5 left open:

* **Checked frames** (rpc/protocol.py) — in-header crc32 round-trip over
  both frame shapes, refusal-before-parse on any flipped byte (pickle,
  sidecar, or the crc word itself), and version skew in both directions
  (a non-advertising peer never receives a checked frame; a checked
  frame reaching an old receiver fails loudly, never mis-parses).
* **Halo cross-attestation** (rpc/worker.py + rpc/broker.py) — the
  redundant-boundary-band digest math on uneven splits (wraparound
  included), the per-strip digest chain, and the recovery path: an
  in-place strip corruption or a sidecar bit flip is detected within one
  K-turn batch and the run still finishes bit-identical to the oracle —
  while the same faults against ``-integrity off`` are proven SILENT
  (the undefended half of the contract).
* **Verified checkpoints** (engine/checkpoint.py) — digest round-trip,
  typed actionable errors for every way an npz can be wrong,
  ``-ckpt-keep`` generation rotation, and the ``-resume`` fallback that
  never reattaches unverified state.

Fast deterministic tests run in tier-1; the live subprocess-cluster
corruption scenarios are ``slow``-marked (``scripts/check --integrity``
runs everything).
"""

import socket
import threading

import numpy as np
import pytest

from gol_distributed_final_tpu.engine.checkpoint import (
    CheckpointError,
    checkpoint_digest,
    generation_path,
    load_checkpoint,
    load_resume_checkpoint,
    load_verified_checkpoint,
    npz_path,
    rotate_generations,
    save_checkpoint,
    save_packed_checkpoint,
)
from gol_distributed_final_tpu.models import CONWAY
from gol_distributed_final_tpu.obs import metrics as obs_metrics
from gol_distributed_final_tpu.rpc import faults, integrity
from gol_distributed_final_tpu.rpc import worker as rpc_worker
from gol_distributed_final_tpu.rpc.broker import WorkersBackend
from gol_distributed_final_tpu.rpc.client import RpcClient
from gol_distributed_final_tpu.rpc.faults import ChaosProxy
from gol_distributed_final_tpu.rpc.integrity import IntegrityError
from gol_distributed_final_tpu.rpc.protocol import (
    MAX_FRAME,
    Request,
    Response,
    _FLAG_CK,
    _FLAG_OOB,
    _HEADER,
    loads_restricted,
    recv_frame_sized,
    send_frame,
)
from gol_distributed_final_tpu.rpc.server import RpcServer

from oracle import vector_step
from test_chaos import _counter, _kill_all
from test_rpc import _spawn, _wait_listening


@pytest.fixture(autouse=True)
def integrity_on():
    """Every test starts from the default-on posture and restores it —
    the undefended tests flip the global off and must not leak that."""
    integrity.set_enabled(True)
    yield
    integrity.set_enabled(True)


@pytest.fixture
def clean_faults():
    faults.configure(None)
    yield faults
    faults.configure(None)


@pytest.fixture
def live_metrics():
    obs_metrics.enable()
    obs_metrics.registry().reset()
    yield obs_metrics
    obs_metrics.enable(False)


def _labeled(name: str, snap=None) -> dict:
    """{labels_tuple: value} for one counter family. Zero-valued series
    are dropped: registry().reset() keeps registered label series at 0.0,
    so earlier tests in the same process must not make `== {}` assertions
    order-dependent."""
    if snap is None:
        snap = obs_metrics.registry().snapshot()
    for fam in snap.get("families", []):
        if fam.get("name") == name:
            return {
                tuple(s.get("labels", ())): s.get("value", 0.0)
                for s in fam.get("series", [])
                if s.get("value")
            }
    return {}


# -- digests ------------------------------------------------------------------


@pytest.mark.parametrize(
    "digest", [integrity.array_digest, integrity.state_digest],
    ids=["blake2b", "adler32"],
)
def test_digests_deterministic_and_bind_shape_dtype(digest):
    """Both digest tiers — blake2b (checkpoints) and the adler32 state
    chain (the per-batch resident-strip plane) — honour the same
    contract: deterministic, layout-normalising, shape/dtype-binding,
    and sensitive to any single flipped byte."""
    rng = np.random.default_rng(0)
    a = rng.integers(0, 255, (32, 16), dtype=np.uint8)
    assert digest(a) == digest(a.copy())
    # a non-contiguous view with the same logical content digests equal
    # (ascontiguousarray normalises the layout before hashing)
    assert digest(a[::1]) == digest(a)
    # same bytes, different shape or dtype: different digest — a reshaped
    # or recast buffer cannot impersonate the original
    assert digest(a) != digest(a.reshape(16, 32))
    assert digest(a) != digest(a.view(np.int8))
    # one flipped byte flips the digest — everywhere
    for r in range(a.shape[0]):
        b = a.copy()
        b[r, r % a.shape[1]] ^= 0xFF
        assert digest(a) != digest(b)
    # the empty array (the final shrinking attestation band) is defined
    # and stable
    assert digest(np.empty((0, 16), np.uint8)) == (
        digest(np.empty((0, 16), np.uint8))
    )


def test_state_digest_rolls_and_separates_boundaries():
    """The rolling fold the attestation accumulators rely on: folding
    [A, B] equals digesting them in sequence, differs from [B, A], and
    from folding a single concatenated array (each fold binds its own
    shape header, so band boundaries cannot alias)."""
    rng = np.random.default_rng(3)
    a = rng.integers(0, 255, (6, 8), dtype=np.uint8)
    b = rng.integers(0, 255, (4, 8), dtype=np.uint8)
    ab = integrity.state_hex(
        integrity.state_add(integrity.state_add(integrity.state_new(), a), b)
    )
    ab2 = integrity.state_hex(
        integrity.state_add(integrity.state_add(integrity.state_new(), a), b)
    )
    ba = integrity.state_hex(
        integrity.state_add(integrity.state_add(integrity.state_new(), b), a)
    )
    cat = integrity.state_digest(np.concatenate([a, b], axis=0))
    assert ab == ab2
    assert ab != ba
    assert ab != cat


# -- checked frames -----------------------------------------------------------


class _RecordingSock:
    def __init__(self):
        self.chunks = []

    def sendall(self, data):
        self.chunks.append(bytes(data))


def _frame_bytes(obj, oob=False, checksum=False) -> bytes:
    sock = _RecordingSock()
    send_frame(sock, obj, oob=oob, checksum=checksum)
    return b"".join(sock.chunks)


def _recv_raw(raw: bytes):
    a, b = socket.socketpair()
    try:
        a.sendall(raw)
        a.close()
        return recv_frame_sized(b)
    finally:
        b.close()


@pytest.mark.parametrize("oob", [False, True])
def test_checked_frame_roundtrip_both_shapes(oob, live_metrics):
    big = np.arange(64 * 64, dtype=np.uint8).reshape(64, 64)
    raw = _frame_bytes({"id": 3, "x": big}, oob=oob, checksum=True)
    (word,) = _HEADER.unpack(raw[:8])
    assert word & _FLAG_CK
    assert bool(word & _FLAG_OOB) == oob
    c0 = _counter("gol_integrity_checks_total")
    obj, nbytes = _recv_raw(raw)
    assert nbytes == len(raw)
    assert obj["id"] == 3
    np.testing.assert_array_equal(obj["x"], big)
    assert _counter("gol_integrity_checks_total") == c0 + 1
    assert _labeled("gol_integrity_failures_total") == {}


@pytest.mark.parametrize("oob", [False, True])
def test_checked_frame_flip_refused_before_parse(oob, live_metrics):
    """Any flipped byte — pickle, sidecar, or the in-header crc word
    itself — is a loud IntegrityError and the frame is NEVER parsed.
    This is the corruption class `ChaosProxy.corrupt_sidecar` lands and
    TCP's own 16-bit checksum can miss."""
    big = np.arange(64 * 64, dtype=np.uint8).reshape(64, 64)
    raw = bytearray(_frame_bytes({"x": big}, oob=oob, checksum=True))
    for pos in (len(raw) // 2, 9):  # a body byte, a crc-word byte
        flipped = bytearray(raw)
        flipped[pos] ^= 0x01
        f0 = _labeled("gol_integrity_failures_total").get(("frame",), 0)
        with pytest.raises(IntegrityError, match="refusing to parse"):
            _recv_raw(bytes(flipped))
        assert _labeled("gol_integrity_failures_total")[("frame",)] == f0 + 1
    # IntegrityError is a ConnectionError: every transport-failure path
    # treats the stream as dead
    assert issubclass(IntegrityError, ConnectionError)


def test_checked_frame_crc_rides_in_header():
    """The crc word sits right behind the length word and ships in the
    SAME sendall — the latency contract: a receiver that has drained the
    body never waits on a trailing segment (whose delivery would ride on
    the sender thread being rescheduled) to verify."""
    for oob in (False, True):
        sock = _RecordingSock()
        send_frame(
            sock, {"x": np.arange(4096, dtype=np.uint8)},
            oob=oob, checksum=True,
        )
        head = sock.chunks[0]
        assert len(head) == 12  # length word + crc word, one sendall
        body = b"".join(sock.chunks[1:])
        want = integrity.crc_pack(integrity.crc_add(0, body))
        assert head[8:12] == want
    # and a checked frame cut off before its crc word is a loud
    # connection error, never a parse
    a, b = socket.socketpair()
    try:
        a.sendall(_HEADER.pack(_FLAG_CK | 2) + b"xx")
        a.close()
        with pytest.raises(ConnectionError, match="peer closed"):
            recv_frame_sized(b)
    finally:
        b.close()


def test_checked_frame_fails_old_receivers_loudly():
    """Both vintages of old receiver refuse a checked frame at the length
    check — bit 62 rides above MAX_FRAME — never a mis-parse."""
    raw = _frame_bytes({"x": 1}, checksum=True)
    (word,) = _HEADER.unpack(raw[:8])
    # pre-protocol-5 receiver: raw length word
    assert word > MAX_FRAME
    # PR 5-era receiver: masks only bit 63, still sees an absurd length
    assert word & (_FLAG_OOB - 1) > MAX_FRAME


def test_server_sends_checked_frames_only_to_advertising_clients():
    server = RpcServer(port=0)
    server.register("T.Echo", lambda req: Response(turns_completed=1))
    server.serve_background()

    def one_call(envelope_extra):
        sock = socket.create_connection(("127.0.0.1", server.port), timeout=5)
        try:
            send_frame(
                sock,
                {"id": 0, "method": "T.Echo", "request": Request(),
                 **envelope_extra},
            )
            head = b""
            while len(head) < 8:
                head += sock.recv(8 - len(head))
            (word,) = _HEADER.unpack(head)
            return word
        finally:
            sock.close()

    try:
        # an old client never advertised "ck": its reply frame is plain
        assert not one_call({}) & _FLAG_CK
        # an advertising client gets a checked reply on the same server
        assert one_call({"ck": 1}) & _FLAG_CK
    finally:
        server.stop()


def test_client_never_checks_frames_to_old_server():
    """Old-server skew: replies without the "ck" advertisement keep the
    client's frames unchecked forever — and with -integrity off the
    client does not even advertise."""
    listener = socket.socket()
    listener.bind(("127.0.0.1", 0))
    listener.listen(1)
    port = listener.getsockname()[1]
    seen = []

    def old_server():
        conn, _ = listener.accept()
        with conn:
            for _ in range(2):
                head = b""
                while len(head) < 8:
                    head += conn.recv(8 - len(head))
                (word,) = _HEADER.unpack(head)
                seen.append(word)
                body = b""
                length = word & (_FLAG_CK - 1)
                while len(body) < length:
                    body += conn.recv(min(1 << 20, length - len(body)))
                msg = loads_restricted(body)
                seen.append(msg.get("ck"))
                # an OLD server's reply: no "ck" (and no "oob") key
                send_frame(conn, {"id": msg["id"], "result": Response()})

    t = threading.Thread(target=old_server, daemon=True)
    t.start()
    client = RpcClient(f"127.0.0.1:{port}", timeout=5)
    try:
        client.call("T.X", Request(), timeout=5)
        integrity.set_enabled(False)
        client.call("T.X", Request(), timeout=5)
        assert client._peer_ck is False
        words, advertised = seen[0::2], seen[1::2]
        assert all(not w & _FLAG_CK for w in words), (
            "an old server was sent a checked frame"
        )
        # enabled: the client advertises; disabled: it does not
        assert advertised == [1, None]
    finally:
        integrity.set_enabled(True)
        client.close()
        listener.close()
        t.join(timeout=5)


def test_negotiated_connection_upgrades_to_checked_both_ways(live_metrics):
    """Two current peers with -integrity on converge to checked frames in
    both directions after the first exchange; the check counters move."""
    server = RpcServer(port=0)
    server.register("T.Echo", lambda req: Response(world=np.asarray(req.world)))
    server.serve_background()
    client = RpcClient(f"127.0.0.1:{server.port}", timeout=5)
    try:
        big = np.random.default_rng(3).integers(0, 255, (64, 64), np.uint8)
        assert client._peer_ck is False
        client.call("T.Echo", Request(world=big), timeout=5)
        assert client._peer_ck is True  # reply advertised: upgraded
        c0 = _counter("gol_integrity_checks_total")
        r = client.call("T.Echo", Request(world=big), timeout=5)
        np.testing.assert_array_equal(r.world, big)
        # request verified by the server AND reply verified by the client
        assert _counter("gol_integrity_checks_total") >= c0 + 2
        assert _labeled("gol_integrity_failures_total") == {}
    finally:
        client.close()
        server.stop()


# -- halo cross-attestation ---------------------------------------------------


def _split_bounds(h, n):
    """Contiguous row strips, uneven like the broker's _split."""
    base, extra = divmod(h, n)
    bounds, s = [], 0
    for i in range(n):
        e = s + base + (1 if i < extra else 0)
        bounds.append((s, e))
        s = e
    return bounds


def _attest_all(board, bounds, k):
    """Run every strip through strip_step_batch(attest=True) with the
    wrapped neighbour halos the broker would relay."""
    h = board.shape[0]
    out = []
    for s, e in bounds:
        top = board[np.arange(s - k, s) % h]
        bottom = board[np.arange(e, e + k) % h]
        strip, counts, att_top, att_bottom = rpc_worker.strip_step_batch(
            board[s:e].copy(), top, bottom, k, attest=True
        )
        out.append((strip, counts, att_top, att_bottom))
    return out


@pytest.mark.parametrize("h,n,k", [(31, 3, 3), (23, 4, 2), (16, 1, 4)])
def test_attestation_bands_agree_across_uneven_splits(h, n, k):
    """The redundant-boundary-band math: worker i's per-step top-band
    digests equal worker i-1's bottom-band digests (wraparound included,
    single-worker self-agreement included), strip heights uneven."""
    rng = np.random.default_rng(h * 10 + n)
    board = np.where(rng.random((h, 12)) < 0.4, 255, 0).astype(np.uint8)
    bounds = _split_bounds(h, n)
    assert len({e - s for s, e in bounds}) > 1 or n == 1  # genuinely uneven
    results = _attest_all(board, bounds, k)
    for i in range(n):
        up = (i - 1) % n
        assert results[i][2] == results[up][3], (
            f"top bands of strip {i} disagree with bottom bands of {up}"
        )
        assert results[i][2] and isinstance(results[i][2], str)
    # and the strips really advanced k turns (the bands attested REAL rows)
    want = board.copy()
    for _ in range(k):
        want = vector_step(want)
    for (s, e), (strip, _c, _t, _b) in zip(bounds, results):
        np.testing.assert_array_equal(strip, want[s:e])


def test_attestation_catches_wrong_compute():
    """A flipped cell near one strip's boundary breaks band agreement
    with the neighbour that shares that boundary in the SAME batch — the
    ≤K-turn detection bound the broker's cross-check relies on. A cell
    outside the other boundary's dependency cone leaves those bands
    untouched (the cone math is exact, not fuzzy)."""
    rng = np.random.default_rng(7)
    board = np.where(rng.random((24, 12)) < 0.4, 255, 0).astype(np.uint8)
    bounds = _split_bounds(24, 3)
    k = 3
    clean = _attest_all(board, bounds, k)
    # strip 1 steps from a corrupted copy of its rows while its
    # neighbours step from the clean board — the wrong-compute shape
    h = board.shape[0]
    s, e = bounds[1]
    corrupt = board[s:e].copy()
    corrupt[0, 5] ^= 0xFF  # first row: inside the TOP boundary's cone
    top = board[np.arange(s - k, s) % h]
    bottom = board[np.arange(e, e + k) % h]
    _strip, _c, att_top, att_bottom = rpc_worker.strip_step_batch(
        corrupt, top, bottom, k, attest=True
    )
    # the broker's cross-check: worker 1's top bands vs worker 0's bottom
    # bands must now DISAGREE — the corruption is caught this batch
    assert att_top != clean[0][3]
    # the bottom boundary sits 8 rows away: k=3 steps of light cone never
    # reach it, so those bands still agree with worker 2's clean top
    assert att_bottom == clean[1][3]
    assert clean[2][2] == att_bottom


def test_worker_strip_step_reply_carries_verifiable_digests(clean_faults):
    service = rpc_worker.WorkerService(server=None)
    rng = np.random.default_rng(11)
    strip = np.where(rng.random((8, 16)) < 0.4, 255, 0).astype(np.uint8)
    service.strip_start(Request(world=strip.copy(), worker=0, initial_turn=0))
    halos = np.zeros((4, 16), np.uint8)
    res = service.strip_step(
        Request(world=halos, turns=2, worker=0, initial_turn=0)
    )
    d = res.digests
    assert isinstance(d, dict)
    assert d["pre"] == integrity.state_digest(strip)
    assert d["strip"] == integrity.state_digest(service._strip)
    assert d["edges"] == integrity.state_digest(res.edges)
    assert d["attest_top"] and d["attest_bottom"]
    # -integrity off: no digests are computed or shipped (the skew shape
    # an old worker would produce — the broker must tolerate it)
    integrity.set_enabled(False)
    res2 = service.strip_step(
        Request(world=halos, turns=2, worker=0, initial_turn=2)
    )
    assert res2.digests is None


def test_fault_point_corrupt_flips_exactly_one_byte(clean_faults):
    faults.configure("worker.strip_corrupt:corrupt:2:5")
    arr = np.zeros((4, 4), np.uint8)
    faults.fault_point("worker.strip_corrupt", target=arr)  # hit 1: no-op
    assert not arr.any()
    faults.fault_point("worker.strip_corrupt", target=arr)  # hit 2: fires
    assert arr.reshape(-1)[5] == 0xFF
    assert int(np.count_nonzero(arr)) == 1
    faults.fault_point("worker.strip_corrupt", target=arr)  # hit 3: no-op
    assert int(np.count_nonzero(arr)) == 1


# -- resident cluster: corruption detected, recovered, bit-identical ----------


def _rand_board(h, w, seed):
    rng = np.random.default_rng(seed)
    return np.where(rng.random((h, w)) < 0.4, 255, 0).astype(np.uint8)


def _oracle(board, turns):
    want = board.copy()
    for _ in range(turns):
        want = vector_step(want)
    return want


def _run_backend(backend, board, turns, threads):
    try:
        return backend.run(
            Request(
                world=board, turns=turns, threads=threads,
                image_width=board.shape[1], image_height=board.shape[0],
            )
        )
    finally:
        backend.close()


def test_resident_inplace_strip_corruption_detected_bit_identical(
    clean_faults, live_metrics
):
    """Acceptance: a worker's RESIDENT strip is corrupted in place
    mid-run (the `corrupt` fault action at `worker.strip_corrupt` — one
    byte, a valid cell value, invisible without digests). The pre-batch
    digest breaks the broker's committed chain on the very next
    StripStep, the worker is routed through the loss/rebuild path, and
    the finished run is bit-identical to the oracle."""
    servers = [rpc_worker.serve(port=0) for _ in range(3)]
    addrs = [f"127.0.0.1:{s.port}" for s, _ in servers]
    board = _rand_board(48, 48, seed=21)
    turns = 600
    faults.configure("worker.strip_corrupt:corrupt:30:100")
    try:
        backend = WorkersBackend(
            addrs, wire="resident", halo_depth=4, sync_interval=64,
            rpc_deadline=5.0, probe_interval=0.2,
        )
        res = _run_backend(backend, board, turns, threads=3)
        assert res.turns_completed == turns
        np.testing.assert_array_equal(res.world, _oracle(board, turns))
        fails = _labeled("gol_integrity_failures_total")
        assert fails.get(("strip",), 0) >= 1, (
            "the in-place corruption was never detected"
        )
        assert _counter("gol_worker_lost_total") >= 1
    finally:
        for s, _svc in servers:
            s.stop()


def test_resident_inplace_corruption_is_silent_without_integrity(
    clean_faults, live_metrics
):
    """The undefended half of the contract: the SAME fault against
    ``-integrity off`` completes the run with a silently-wrong board —
    no detection, no loss, no error. This is the exposure the issue
    names; the test pins it so the defended test above means something."""
    integrity.set_enabled(False)
    servers = [rpc_worker.serve(port=0) for _ in range(3)]
    addrs = [f"127.0.0.1:{s.port}" for s, _ in servers]
    board = _rand_board(48, 48, seed=22)
    turns = 600
    faults.configure("worker.strip_corrupt:corrupt:30:100")
    try:
        backend = WorkersBackend(
            addrs, wire="resident", halo_depth=4, sync_interval=64,
            rpc_deadline=5.0, probe_interval=0.2,
        )
        res = _run_backend(backend, board, turns, threads=3)
        assert res.turns_completed == turns
        assert not np.array_equal(res.world, _oracle(board, turns)), (
            "the corruption did not survive — the fault harness is not "
            "expressing the silent-corruption class"
        )
        assert _labeled("gol_integrity_failures_total") == {}
        assert _counter("gol_worker_lost_total") == 0
    finally:
        for s, _svc in servers:
            s.stop()


def test_resident_sidecar_bitflip_detected_bit_identical(live_metrics):
    """Acceptance: one bit flipped inside an out-of-band ndarray sidecar
    on the resident wire (ChaosProxy corrupt_sidecar — the fault PR 5's
    proxy refused to land). The checked frame refuses to parse, the
    worker is treated as lost, readmitted through the now-clean proxy,
    and the run finishes bit-identical."""
    servers = [rpc_worker.serve(port=0) for _ in range(3)]
    proxy = ChaosProxy(f"127.0.0.1:{servers[1][0].port}", corrupt_sidecar=20)
    addrs = [
        f"127.0.0.1:{servers[0][0].port}",
        proxy.address,
        f"127.0.0.1:{servers[2][0].port}",
    ]
    # 128 columns: halo/edge frames are 8*128 = 1024 B >= the out-of-band
    # threshold, so steady-state StripStep traffic carries raw sidecars
    board = _rand_board(96, 128, seed=23)
    turns = 800
    try:
        backend = WorkersBackend(
            addrs, wire="resident", halo_depth=4, sync_interval=64,
            rpc_deadline=2.0, probe_interval=0.2,
        )
        res = _run_backend(backend, board, turns, threads=3)
        assert res.turns_completed == turns
        np.testing.assert_array_equal(res.world, _oracle(board, turns))
        fails = _labeled("gol_integrity_failures_total")
        assert fails.get(("frame",), 0) >= 1, (
            "the sidecar flip was never caught by a frame checksum"
        )
        assert _counter("gol_worker_lost_total") >= 1
    finally:
        proxy.close()
        for s, _svc in servers:
            s.stop()


# -- verified checkpoints -----------------------------------------------------


def test_checkpoint_digest_roundtrip_and_metadata_binding(tmp_path):
    board = _rand_board(12, 9, seed=1)
    p = save_checkpoint(tmp_path / "ck", board, 17, CONWAY)
    got, turn, rule = load_verified_checkpoint(p)
    np.testing.assert_array_equal(got, board)
    assert turn == 17 and rule.rulestring == CONWAY.rulestring
    # the lenient loader still reads v2 files (forward-compatible keys)
    got2, turn2, _rule2 = load_checkpoint(p)
    np.testing.assert_array_equal(got2, board)
    assert turn2 == 17
    # the digest binds every metadata field, not just the board bytes
    d = checkpoint_digest(board, 17, CONWAY.rulestring)
    assert checkpoint_digest(board, 18, CONWAY.rulestring) != d
    assert checkpoint_digest(board, 17, "B36/S23") != d
    assert checkpoint_digest(board.reshape(9, 12), 17, CONWAY.rulestring) != d


def test_checkpoint_typed_errors_cover_every_corruption(tmp_path, live_metrics):
    """Every way an npz can be wrong is a CheckpointError with a kind and
    an actionable message — never a raw zipfile/KeyError traceback (the
    satellite: `-resume` with garbage used to surface one)."""
    board = _rand_board(8, 8, seed=2)

    def expect(path, kind, match):
        f0 = _labeled("gol_ckpt_verify_total").get(("fail",), 0)
        with pytest.raises(CheckpointError, match=match) as ei:
            load_verified_checkpoint(path)
        assert ei.value.kind == kind
        assert _labeled("gol_ckpt_verify_total")[("fail",)] == f0 + 1

    garbage = tmp_path / "garbage.npz"
    garbage.write_bytes(b"this is not an npz at all")
    expect(garbage, "unreadable", "not a readable checkpoint")

    good = save_checkpoint(tmp_path / "good", board, 5, CONWAY)
    truncated = tmp_path / "truncated.npz"
    truncated.write_bytes(good.read_bytes()[: good.stat().st_size // 2])
    expect(truncated, "unreadable", "truncated or corrupt")

    fields = tmp_path / "fields.npz"
    np.savez(fields, board=board)
    expect(fields, "truncated", "missing checkpoint field")

    packed = save_packed_checkpoint(
        tmp_path / "packed", np.zeros((1, 8), np.uint32), 5
    )
    expect(packed, "format", "packed-bitboard")

    legacy = tmp_path / "legacy.npz"
    np.savez(
        legacy, board=board, turn=np.int64(5),
        rulestring=np.str_(CONWAY.rulestring),
    )
    expect(legacy, "unverified", "no integrity digest")

    forged = tmp_path / "forged.npz"
    np.savez(
        forged, board=board, turn=np.int64(5),
        rulestring=np.str_(CONWAY.rulestring), format_version=np.int64(2),
        digest=np.str_("0" * 32),
    )
    expect(forged, "digest", "failed digest verification")

    # a verifying load counts on the ok side
    ok0 = _labeled("gol_ckpt_verify_total").get(("ok",), 0)
    load_verified_checkpoint(good)
    assert _labeled("gol_ckpt_verify_total")[("ok",)] == ok0 + 1


def test_ckpt_generation_rotation_and_resume_fallback(tmp_path):
    board = _rand_board(8, 8, seed=3)
    base = tmp_path / "auto"
    # three auto-checkpoint writes with keep=3, the broker's sequence:
    # rotate THEN write-current (tmp+rename)
    for turn in (10, 20, 30):
        tmp = base.with_name("auto.tmp")
        written = save_checkpoint(tmp, board, turn, CONWAY)
        rotate_generations(base, keep=3)
        written.replace(npz_path(base))
    assert generation_path(base, 0) == npz_path(base)
    for gen, turn in ((0, 30), (1, 20), (2, 10)):
        _b, t, _r = load_verified_checkpoint(generation_path(base, gen))
        assert t == turn
    # newest verifies: fallback returns gen 0
    got = load_resume_checkpoint(base, keep=3)
    assert (got[1], got[3]) == (30, 0)
    # corrupt the newest: fallback walks to gen 1
    npz_path(base).write_bytes(b"scribble")
    got = load_resume_checkpoint(base, keep=3)
    assert (got[1], got[3]) == (20, 1)
    # keep=1 refuses instead of silently reading an older generation
    with pytest.raises(CheckpointError) as ei:
        load_resume_checkpoint(base, keep=1)
    assert ei.value.kind == "exhausted"
    # every generation bad: exhausted, listing each attempt
    generation_path(base, 1).write_bytes(b"scribble")
    generation_path(base, 2).unlink()
    with pytest.raises(CheckpointError, match="not found") as ei:
        load_resume_checkpoint(base, keep=3)
    assert ei.value.kind == "exhausted"
    assert str(ei.value).count("[unreadable]") == 2


def test_resume_cli_refuses_unverified_loudly(tmp_path, capsys):
    """The broker and controller `-resume` surfaces turn a bad checkpoint
    into a parser error (typed message, exit 2) BEFORE anything starts —
    not a mid-setup traceback."""
    from gol_distributed_final_tpu.__main__ import main as controller_main
    from gol_distributed_final_tpu.rpc.broker import main as broker_main

    bad = tmp_path / "bad.npz"
    bad.write_bytes(b"zip? no")
    with pytest.raises(SystemExit) as ei:
        broker_main(["-backend", "workers", "-workers", "127.0.0.1:1",
                     "-resume", str(bad)])
    assert ei.value.code == 2
    assert "not a readable checkpoint" in capsys.readouterr().err
    with pytest.raises(SystemExit) as ei:
        controller_main(["-resume", str(bad)])
    assert ei.value.code == 2
    assert "not a readable checkpoint" in capsys.readouterr().err
    # a pre-integrity file is refused just as loudly (unverified kind)
    legacy = tmp_path / "legacy.npz"
    np.savez(
        legacy, board=np.zeros((4, 4), np.uint8), turn=np.int64(1),
        rulestring=np.str_(CONWAY.rulestring),
    )
    with pytest.raises(SystemExit):
        broker_main(["-backend", "workers", "-workers", "127.0.0.1:1",
                     "-resume", str(legacy)])
    assert "no integrity digest" in capsys.readouterr().err


def test_broker_ckpt_keep_flag_validation(capsys):
    from gol_distributed_final_tpu.rpc.broker import main as broker_main

    with pytest.raises(SystemExit):
        broker_main(["-backend", "workers", "-workers", "127.0.0.1:1",
                     "-ckpt-keep", "0"])
    assert "-ckpt-keep must be >= 1" in capsys.readouterr().err
    with pytest.raises(SystemExit):
        broker_main(["-backend", "tpu", "-ckpt-keep", "3"])
    assert "does nothing here" in capsys.readouterr().err


# -- observability surfaces ---------------------------------------------------


def test_watch_renders_integrity_panel(live_metrics):
    from gol_distributed_final_tpu.obs import instruments as ins
    from gol_distributed_final_tpu.obs.watch import render_status

    def payload():
        return {
            "role": "broker", "pid": 1, "metrics_enabled": True,
            "metrics": obs_metrics.registry().snapshot(),
        }

    # all-zero registry: no INTEGRITY panel noise
    assert "INTEGRITY" not in render_status("b", payload())
    ins.INTEGRITY_CHECKS_TOTAL.inc(500)
    ins.CKPT_VERIFY_TOTAL.labels("ok").inc()
    frame = render_status("b", payload())
    assert "INTEGRITY" in frame
    assert "checks 500" in frame
    assert "ckpt verify ok 1" in frame
    assert "CORRUPTION CAUGHT" not in frame
    ins.INTEGRITY_FAILURES_TOTAL.labels("strip").inc()
    frame = render_status("b", payload())
    assert "CORRUPTION CAUGHT" in frame
    assert "strip 1" in frame


def test_integrity_lint_and_readme_section():
    from gol_distributed_final_tpu.obs.lint import (
        missing_readme_sections,
        undocumented_integrity_metrics,
    )

    assert undocumented_integrity_metrics() == []
    assert missing_readme_sections() == []


# -- live subprocess chaos (slow: scripts/check --integrity) ------------------


def _status_counter(address: str, name: str, worker=False) -> dict:
    """{labels: value} for one family out of a live Status payload."""
    from gol_distributed_final_tpu.obs.status import fetch_status

    payload = fetch_status(address, worker=worker, timeout=5.0)
    return _labeled(name, payload.get("metrics") or {})


def _run_live_cluster(faulted_worker_target, other_ports, turns):
    """Drive a spawned resident cluster to completion and return
    (result, broker_address). The caller owns process/proxy cleanup."""
    from gol_distributed_final_tpu import Params
    from gol_distributed_final_tpu.rpc.client import RemoteBroker
    from test_chaos import _read_board_64

    broker = _spawn(
        "gol_distributed_final_tpu.rpc.broker",
        "-port", "0", "-backend", "workers", "-metrics",
        "-wire", "resident", "-halo-depth", "8", "-sync-interval", "64",
        "-workers",
        ",".join(
            [faulted_worker_target]
            + [f"127.0.0.1:{p}" for p in other_ports]
        ),
        "-rpc-deadline", "5", "-probe-interval", "0.2",
    )
    address = f"127.0.0.1:{_wait_listening(broker)}"
    remote = RemoteBroker(address, timeout=30.0)
    result = {}
    t = threading.Thread(
        target=lambda: result.update(r=remote.run(
            Params(turns=turns, threads=3, image_width=64, image_height=64),
            _read_board_64(),
        ))
    )
    t.start()
    try:
        t.join(timeout=300)
        assert not t.is_alive(), "run hung after the corruption"
    finally:
        if t.is_alive():
            remote.quit()
            t.join(timeout=30)
        remote.close()
    return result["r"], address, broker


@pytest.mark.slow
def test_chaos_sidecar_bitflip_live_bit_identical():
    """Acceptance, live: a ChaosProxy flips ONE BIT inside an out-of-band
    sidecar between the broker and a worker mid-run. The checked frame is
    refused before parsing (gol_integrity_failures_total{frame} on
    whichever peer received it), the worker is dropped and readmitted
    through the now-clean path, and the finished board is bit-identical
    to an uninterrupted oracle run."""
    from test_chaos import _oracle_64

    turns = 3000
    workers = [
        _spawn("gol_distributed_final_tpu.rpc.worker", "-port", "0",
               "-metrics")
        for _ in range(3)
    ]
    broker = proxy = None
    try:
        ports = [_wait_listening(w) for w in workers]
        proxy = ChaosProxy(f"127.0.0.1:{ports[0]}", corrupt_sidecar=30)
        result, address, broker = _run_live_cluster(
            proxy.address, ports[1:], turns
        )
        assert result.turns_completed == turns
        np.testing.assert_array_equal(result.world, _oracle_64(turns))
        broker_fails = _status_counter(
            address, "gol_integrity_failures_total"
        ).get(("frame",), 0)
        worker_fails = _status_counter(
            f"127.0.0.1:{ports[0]}", "gol_integrity_failures_total",
            worker=True,
        ).get(("frame",), 0)
        assert broker_fails + worker_fails >= 1, (
            "no frame checksum failure was recorded anywhere"
        )
        lost = _status_counter(address, "gol_worker_lost_total")
        assert sum(lost.values()) >= 1
        readmitted = _status_counter(address, "gol_worker_readmitted_total")
        assert sum(readmitted.values()) >= 1, (
            "the corrupted-path worker was never readmitted"
        )
    finally:
        if proxy is not None:
            proxy.close()
        _kill_all([*workers, broker])


@pytest.mark.slow
def test_chaos_inplace_strip_corruption_live_bit_identical(monkeypatch):
    """Acceptance, live: a worker subprocess corrupts its RESIDENT strip
    in place mid-run (GOL_FAULT_POINTS corrupt action — the fault only
    that process sees). The broker's digest chain catches it within one
    batch (gol_integrity_failures_total{strip}), routes it through
    quarantine/rebuild, and the run finishes bit-identical to the
    oracle."""
    from test_chaos import _oracle_64

    turns = 3000
    monkeypatch.setenv(
        "GOL_FAULT_POINTS", "worker.strip_corrupt:corrupt:25:300"
    )
    faulted = _spawn(
        "gol_distributed_final_tpu.rpc.worker", "-port", "0", "-metrics"
    )
    monkeypatch.delenv("GOL_FAULT_POINTS")
    workers = [faulted] + [
        _spawn("gol_distributed_final_tpu.rpc.worker", "-port", "0",
               "-metrics")
        for _ in range(2)
    ]
    broker = None
    try:
        ports = [_wait_listening(w) for w in workers]
        result, address, broker = _run_live_cluster(
            f"127.0.0.1:{ports[0]}", ports[1:], turns
        )
        assert result.turns_completed == turns
        np.testing.assert_array_equal(result.world, _oracle_64(turns))
        fails = _status_counter(address, "gol_integrity_failures_total")
        assert fails.get(("strip",), 0) >= 1, (
            "the in-place corruption was never detected by the chain"
        )
        lost = _status_counter(address, "gol_worker_lost_total")
        assert sum(lost.values()) >= 1
    finally:
        _kill_all([*workers, broker])
