"""Fused K-turns-per-launch suite (ISSUE 15).

Covers the whole fused tier (ops/fused.py) and its consumers:

* ladder arithmetic + the pow2 K quantiser (chunk churn never recompiles);
* oracle bit-parity of the fused entry points vs the serial kernels across
  K ∈ {1, 2, 4, 8}, odd remainders, the three test_wire geometries, the
  HighLife rule, and both packings;
* the grid-tiled fused kernels (bit rows/grid2d + byte strips) with forced
  block shapes — the shrinking-cone-in-the-halo-strips form;
* the batched grid variant vs per-universe loops, and the fused
  step+count programs on both batched planes;
* the engine's counted chunk driver (host-free alive fold, dispatch-free
  ticker retrieve) and the session table's step_n_counts chunk path;
* the resident worker's three StripStep paths (dense / dead-band skip /
  fused) — strips, counts, AND attestation digests bit-identical;
* ops/auto routing (fused_bitplane label, GOL_FUSED knob), the launch
  meters, the analysis jit-cache checker's fused entries, the
  dispatches_per_turn regress gate, and the README fused lint.

Run standalone via ``scripts/check --fused``.
"""

import numpy as np
import pytest

from gol_distributed_final_tpu.models import CONWAY, LifeRule
from gol_distributed_final_tpu.obs import metrics as obs_metrics
from gol_distributed_final_tpu.ops import bitpack
from gol_distributed_final_tpu.ops.fused import (
    FUSED_MAX_K,
    FusedBitPlane,
    _ladder,
    can_tile_byte,
    fold_counts,
    fused_bit_step_n,
    fused_bit_step_n_batch,
    fused_step_n,
    fused_strip_steps,
    quantise_k,
)

from oracle import vector_step

HIGHLIFE = LifeRule.from_rulestring("B36/S23", name="highlife")

#: the resident-wire parity geometries (tests/test_wire.py): uneven split
#: shapes, none 32-row-divisible — the byte tier's bread and butter
WIRE_GEOMETRIES = [(24, 33), (64, 64), (16, 40)]


def _rand_board(h, w, seed=0, density=0.3):
    rng = np.random.default_rng(seed)
    return np.where(rng.random((h, w)) < density, 255, 0).astype(np.uint8)


def _oracle(board, n, birth=(3,), survive=(2, 3)):
    for _ in range(n):
        board = vector_step(board, birth, survive)
    return board


@pytest.fixture
def live_metrics():
    reg = obs_metrics.registry()
    reg.reset()
    obs_metrics.enable()
    yield reg
    obs_metrics.enable(False)
    reg.reset()


def _metric(name, labels=()):
    for fam in obs_metrics.registry().snapshot()["families"]:
        if fam["name"] == name:
            for s in fam["series"]:
                if tuple(s.get("labels", ())) == tuple(labels):
                    return s["value"]
    return 0.0


# -- quantiser + ladder -------------------------------------------------------


def test_quantise_k_is_pow2_and_clamped():
    assert [quantise_k(v) for v in (1, 2, 3, 5, 7, 8, 9, 1000)] == [
        1, 2, 2, 4, 4, 8, 8, 8,
    ]
    assert quantise_k(0) == 1 and quantise_k(-3) == 1
    assert quantise_k(FUSED_MAX_K) == FUSED_MAX_K


def test_ladder_covers_n_exactly_with_bounded_stages():
    for n in (1, 7, 8, 13, 137, 4096):
        for k in (1, 2, 4, 8):
            full, rems = _ladder(n, k)
            assert full * k + sum(rems) == n
            # remainder stages are distinct pow2 < k: the compile-key set
            # is bounded by log2(k)+1 regardless of n churn
            assert all(r < k and r & (r - 1) == 0 for r in rems)
            assert len(set(rems)) == len(rems)


# -- fused bitboard parity ----------------------------------------------------


@pytest.mark.parametrize("k", [1, 2, 4, 8])
@pytest.mark.parametrize("n", [1, 5, 13])
def test_fused_bit_parity_vs_oracle(k, n):
    """fused-K == serial == numpy oracle, odd remainders included (the
    pow2 remainder ladder is in the path for every n % k != 0)."""
    board = _rand_board(64, 64, seed=k * 100 + n)
    packed = bitpack.pack(board, 0)
    got = fused_bit_step_n(packed, n, k=k, interpret=True)
    want_serial = bitpack.bit_step_n(packed, n, 0)
    assert np.array_equal(np.asarray(got), np.asarray(want_serial))
    assert np.array_equal(
        bitpack.unpack(np.asarray(got), 0), _oracle(board, n)
    )


def test_fused_bit_word_axis1_parity():
    board = _rand_board(40, 64, seed=3)  # h not 32-divisible: packs cols
    packed = bitpack.pack(board, 1)
    got = fused_bit_step_n(packed, 11, k=4, word_axis=1, interpret=True)
    assert np.array_equal(bitpack.unpack(np.asarray(got), 1), _oracle(board, 11))


def test_fused_highlife_parity():
    board = _rand_board(64, 64, seed=5, density=0.4)
    packed = bitpack.pack(board, 0)
    got = fused_bit_step_n(packed, 9, k=4, rule=HIGHLIFE, interpret=True)
    assert np.array_equal(
        bitpack.unpack(np.asarray(got), 0),
        _oracle(board, 9, birth=(3, 6), survive=(2, 3)),
    )


@pytest.mark.parametrize("blocks", [dict(block_rows=8), dict(block_rows=8, block_cols=128)])
@pytest.mark.parametrize("k", [2, 8])
def test_fused_tiled_parity(blocks, k):
    """The grid-tiled fused kernel (rows AND grid2d regimes via forced
    block shapes): K steps per grid program on the 8-row halo strips,
    shrinking cone discarded — bit-identical to the serial kernel."""
    board = _rand_board(512, 256, seed=k)
    packed = bitpack.pack(board, 0)  # (16, 256): multi-block both ways
    got = fused_bit_step_n(packed, 13, k=k, interpret=True, **blocks)
    assert np.array_equal(
        np.asarray(got), np.asarray(bitpack.bit_step_n(packed, 13, 0))
    )


def test_tiled_launch_rejects_k_past_the_cone():
    from gol_distributed_final_tpu.ops.pallas_tiled import tiled_pallas_call

    with pytest.raises(ValueError, match="fused turns"):
        tiled_pallas_call(9, (16, 256), True)


# -- fused byte tier ----------------------------------------------------------


@pytest.mark.parametrize("geometry", WIRE_GEOMETRIES)
def test_fused_byte_parity_wire_geometries(geometry):
    h, w = geometry
    board = _rand_board(h, w, seed=h + w)
    got = fused_step_n(board, 13, k=4, interpret=True)
    assert np.array_equal(np.asarray(got), _oracle(board, 13))


def test_fused_byte_tiled_parity():
    from gol_distributed_final_tpu.ops.fused import _fused_byte_tiled_compiled

    shape = (64, 128)
    assert can_tile_byte(shape)
    board = _rand_board(*shape, seed=11)
    fn = _fused_byte_tiled_compiled(
        13, 4, shape, CONWAY.birth_mask, CONWAY.survive_mask, True
    )
    got = fn(np.asarray(board))
    assert np.array_equal(np.asarray(got), _oracle(board, 13))


# -- batched grid variant + fused counts --------------------------------------


def _mixed_batch(size=64, seed=7):
    dense = _rand_board(size, size, seed=seed)
    glider = np.zeros((size, size), np.uint8)
    for y, x in ((1, 2), (2, 3), (3, 1), (3, 2), (3, 3)):
        glider[y, x] = 255
    return np.stack([dense, np.zeros((size, size), np.uint8), glider])


def test_fused_batch_parity_vs_per_universe():
    boards = _mixed_batch()
    packed = np.stack([np.asarray(bitpack.pack(b, 0)) for b in boards])
    import jax.numpy as jnp

    got = fused_bit_step_n_batch(jnp.asarray(packed), 13, k=8, interpret=True)
    for i, b in enumerate(boards):
        solo = fused_bit_step_n(bitpack.pack(b, 0), 13, k=8, interpret=True)
        assert np.array_equal(np.asarray(got)[i], np.asarray(solo))
        assert np.array_equal(
            bitpack.unpack(np.asarray(got)[i], 0), _oracle(b, 13)
        )


@pytest.mark.parametrize("plane_kind", ["bit", "byte"])
def test_step_n_counts_matches_step_then_count(plane_kind):
    """The fused chunk program == step_n followed by alive_counts, for
    both batched tiers — the sessions hot path's one-dispatch form."""
    from gol_distributed_final_tpu.ops.batched import (
        BatchBitPlane,
        BatchBytePlane,
    )

    boards = _mixed_batch(size=64 if plane_kind == "bit" else 30)
    plane = BatchBitPlane(CONWAY, 0) if plane_kind == "bit" else BatchBytePlane(CONWAY)
    state = plane.encode(boards)
    out, counts = plane.step_n_counts(state, 7)
    want = plane.step_n(state, 7)
    assert np.array_equal(plane.decode(out), plane.decode(want))
    assert counts.dtype == np.int64
    assert np.array_equal(counts, plane.alive_counts(want))


def test_fused_plane_counted_and_fold():
    board = _rand_board(64, 64, seed=13)
    plane = FusedBitPlane(CONWAY, 0)
    state = plane.encode(board)
    out, counts = plane.step_n_counted(state, 9)
    assert np.array_equal(np.asarray(out), np.asarray(plane.step_n(state, 9)))
    assert fold_counts(counts) == plane.alive_count(out)
    assert fold_counts(counts) == int(np.count_nonzero(_oracle(board, 9)))


# -- engine counted driver ----------------------------------------------------


def test_engine_counted_driver_and_dispatch_free_ticker(monkeypatch):
    """The engine's chunk driver consumes step_n_counted (the fused
    step+count dispatch) and the count-only Retrieve is served from the
    committed fold — no reduction dispatch at all."""
    from gol_distributed_final_tpu.engine.engine import Engine, EngineConfig
    from gol_distributed_final_tpu.params import Params

    board = _rand_board(64, 64, seed=17)
    calls = {"counted": 0}
    orig = FusedBitPlane.step_n_counted

    def spy(self, state, n):
        calls["counted"] += 1
        return orig(self, state, n)

    monkeypatch.setattr(FusedBitPlane, "step_n_counted", spy)
    engine = Engine(EngineConfig())
    res = engine.run(Params(turns=37, image_width=64, image_height=64), board)
    assert calls["counted"] >= 1
    want = _oracle(board, 37)
    assert np.array_equal(res.world, want)

    # the ticker path: the plane-side reduction must NOT run — the count
    # comes from the fold committed with the final chunk
    monkeypatch.setattr(
        FusedBitPlane,
        "alive_count",
        lambda self, state: pytest.fail("ticker paid a reduction dispatch"),
    )
    snap = engine.retrieve(include_world=False)
    assert snap.turns_completed == 37
    assert snap.alive_count == int(np.count_nonzero(want))


def test_sessions_advance_uses_fused_counts(monkeypatch):
    from gol_distributed_final_tpu.engine.sessions import SessionTable
    from gol_distributed_final_tpu.ops.batched import BatchBitPlane

    calls = {"counts": 0}
    orig = BatchBitPlane.step_n_counts

    def spy(self, state, n):
        calls["counts"] += 1
        return orig(self, state, n)

    monkeypatch.setattr(BatchBitPlane, "step_n_counts", spy)
    boards = _mixed_batch()
    table = SessionTable(CONWAY, (64, 64), capacity=8)
    sessions = [table.admit(b, 25) for b in boards]
    while table.advance():
        pass
    assert calls["counts"] >= 1
    for sess, b in zip(sessions, boards):
        assert sess.done.is_set()
        assert np.array_equal(sess.result, _oracle(b, 25))
        assert sess.alive_count == int(np.count_nonzero(sess.result))


# -- the resident worker's strip paths ----------------------------------------


def _strip_scenarios(k, w=48, h=40):
    rng = np.random.default_rng(k)
    z = np.zeros((k, w), np.uint8)
    dense = np.where(rng.random((h, w)) < 0.3, 255, 0).astype(np.uint8)
    top = np.where(rng.random((k, w)) < 0.3, 255, 0).astype(np.uint8)
    bot = np.where(rng.random((k, w)) < 0.3, 255, 0).astype(np.uint8)
    glider = np.zeros((h, w), np.uint8)
    for y, x in ((1, 2), (2, 3), (3, 1), (3, 2), (3, 3)):
        glider[18 + y, 20 + x] = 255
    edge = np.zeros((h, w), np.uint8)
    edge[0, 5:8] = 255
    return [
        ("dense", dense, top, bot),
        ("glider-mid", glider, z, z),
        ("halo-live-only", np.zeros((h, w), np.uint8), top, bot),
        ("strip-edge", edge, z, z),
        ("all-dead", np.zeros((h, w), np.uint8), z, z),
    ]


@pytest.mark.parametrize("k", [1, 2, 4, 8])
def test_strip_paths_bit_identical_including_digests(k):
    """dense / skip / fused / auto all yield the same strip, the same
    per-step counts, AND the same attestation digests — the broker's
    cross-attestation can never tell the routing apart."""
    from gol_distributed_final_tpu.rpc import integrity as _integrity
    from gol_distributed_final_tpu.rpc.worker import strip_step_batch

    _integrity.set_enabled(True)
    for label, strip, top, bot in _strip_scenarios(k):
        dense = strip_step_batch(strip, top, bot, k, attest=True, mode="dense")
        for mode in ("skip", "fused", "auto"):
            got = strip_step_batch(strip, top, bot, k, attest=True, mode=mode)
            assert np.array_equal(dense[0], got[0]), (label, mode)
            assert dense[1] == got[1], (label, mode)
            assert dense[2:] == got[2:], (label, mode)


def test_strip_skip_meters_saved_rows(live_metrics):
    from gol_distributed_final_tpu.rpc.worker import strip_step_batch

    k, h, w = 4, 64, 32
    strip = np.zeros((h, w), np.uint8)
    for y, x in ((1, 2), (2, 3), (3, 1), (3, 2), (3, 3)):
        strip[30 + y, 10 + x] = 255
    z = np.zeros((k, w), np.uint8)
    before = _metric("gol_strip_rows_skipped_total")
    out, counts = strip_step_batch(strip, z, z, k)  # auto -> skip
    after = _metric("gol_strip_rows_skipped_total")
    assert after > before
    assert out.shape == strip.shape
    # parity vs the dense path
    want, want_counts = strip_step_batch(strip, z, z, k, mode="dense")
    assert np.array_equal(out, want) and counts == want_counts


def test_worker_fused_env_knob(monkeypatch):
    from gol_distributed_final_tpu.rpc import worker as w

    monkeypatch.setenv("GOL_WORKER_FUSED", "off")
    assert w._worker_fused_mode() == "off"
    monkeypatch.delenv("GOL_WORKER_FUSED")
    assert w._worker_fused_mode() == "auto"
    # unknown mode kwarg refuses loudly
    strip = _rand_board(8, 8, seed=1)
    halo = np.zeros((1, 8), np.uint8)
    with pytest.raises(ValueError, match="mode"):
        w.strip_step_batch(strip, halo, halo, 1, mode="warp")


# -- routing + meters ---------------------------------------------------------


def test_auto_plane_routes_fused_tier(live_metrics, monkeypatch):
    from gol_distributed_final_tpu.ops.auto import auto_plane
    from gol_distributed_final_tpu.ops.plane import BitPlane

    shape = (64, 416)  # unique shape: selection cache is cold
    before = _metric("gol_ops_plane_selected_total", ("fused_bitplane",))
    plane = auto_plane(CONWAY, shape)
    assert isinstance(plane, FusedBitPlane)
    assert _metric(
        "gol_ops_plane_selected_total", ("fused_bitplane",)
    ) == before + 1
    # the knob restores the classic tier (fresh shape: decisions cache)
    monkeypatch.setenv("GOL_FUSED", "off")
    classic = auto_plane(CONWAY, (64, 448))
    assert isinstance(classic, BitPlane) and not isinstance(
        classic, FusedBitPlane
    )


def test_fused_launch_meters(live_metrics):
    packed = bitpack.pack(_rand_board(64, 64, seed=23), 0)
    before = _metric("gol_fused_launches_total")
    fused_bit_step_n(packed, 13, k=8, interpret=True)
    after = _metric("gol_fused_launches_total")
    full, rems = _ladder(13, 8)
    assert after - before == full + len(rems)
    # the K histogram saw every stage
    for fam in obs_metrics.registry().snapshot()["families"]:
        if fam["name"] == "gol_fused_turns_per_launch":
            counts = sum(s.get("count", 0) for s in fam["series"])
            assert counts >= full + len(rems)


# -- analysis: the fused entries ride the jit-cache checker -------------------


def test_jit_cache_checker_covers_fused_entries():
    import textwrap

    from gol_distributed_final_tpu.analysis import core
    from gol_distributed_final_tpu.analysis.jit import JitCacheChecker

    def findings_for(src):
        found, _ = core.analyze_source(
            textwrap.dedent(src), "ops/mod.py", [JitCacheChecker()]
        )
        return found

    flagged = findings_for("""
        def drive(packed, budgets):
            n = min(budgets)
            return fused_bit_step_n(packed, n)
    """)
    assert len(flagged) == 1 and "un-quantised" in flagged[0].message
    # the static K kwarg is the same hazard (fused_strip_steps has no
    # positional turn arg in this call shape)
    flagged_k = findings_for("""
        def drive(padded, budgets, h):
            return fused_strip_steps(padded, k=min(budgets), strip_rows=h)
    """)
    assert len(flagged_k) == 1
    clean = findings_for("""
        def drive(packed, budgets):
            n = min(budgets)
            if n > 2:
                n = 1 << (n.bit_length() - 1)
            return fused_bit_step_n(packed, n)
    """)
    assert clean == []


# -- regress: the deterministic launch-floor gate -----------------------------


def test_regress_gates_dispatches_per_turn():
    from gol_distributed_final_tpu.obs.regress import compare_case

    old = {"per_turn_us": 1.0, "dispatches_per_turn": 0.125}
    grown = {"per_turn_us": 1.0, "dispatches_per_turn": 1.0}
    out = compare_case(old, grown)
    assert out["verdict"] == "REGRESSED"
    assert "dispatches" in out["why"]
    # steady launches stay clean; improvement never gates
    assert compare_case(old, dict(old))["verdict"] != "REGRESSED"
    better = {"per_turn_us": 1.0, "dispatches_per_turn": 0.0625}
    assert compare_case(old, better)["verdict"] != "REGRESSED"
    # deterministic: gates even when a wall-clock side is unusable
    broken = {"per_turn_us": 0.0, "dispatches_per_turn": 1.0}
    assert compare_case(old, broken)["verdict"] == "REGRESSED"


# -- lint: the Fused stepping section is the doc of record --------------------


def test_fused_lint_both_ways(tmp_path):
    from gol_distributed_final_tpu.obs.lint import (
        _FUSED_DOC_NAMES,
        undocumented_fused_names,
    )

    assert undocumented_fused_names() == []  # the shipped README passes
    stripped = tmp_path / "README.md"
    stripped.write_text(
        "# x\n\n## Fused stepping\n\nnothing here\n\n## Next\n"
        + "\n".join(_FUSED_DOC_NAMES)  # named OUTSIDE the section: no credit
    )
    assert undocumented_fused_names(stripped) == sorted(_FUSED_DOC_NAMES)
