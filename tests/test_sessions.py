"""Multi-universe serving suite: batched kernels, session table, scheduler.

Covers the three layers the device-batched serving surface stands on:

* the batched kernel family (``ops/stencil.step_n_batch``,
  ``ops/bitpack.bit_step_n_batch``, ``ops/pallas_stencil._bit_compiled_batch``,
  the batched reductions) — every tier against a per-universe numpy-oracle
  loop over MIXED batches (all-dead and single-glider universes riding
  beside dense random ones in one tensor);
* ``engine/sessions.SessionTable`` — admission control (capacity /
  geometry / turns refusals with metered reasons), mid-batch leave with
  slot compaction, per-session event demux exactness from the one batched
  reduction, per-session snapshots, join at a chunk boundary;
* ``rpc/broker.SessionScheduler`` + the ``Operations.SessionRun`` verb —
  concurrent blocking sessions over a live in-process broker, per-session
  tagged Retrieve, capacity refusal as an error reply.

Run standalone via ``scripts/check --sessions``.
"""

import threading
import time

import numpy as np
import pytest

from gol_distributed_final_tpu.models import CONWAY, LifeRule
from gol_distributed_final_tpu.obs import metrics as obs_metrics

from oracle import vector_step

HIGHLIFE = LifeRule.from_rulestring("B36/S23", name="highlife")


def _seq(board, n, birth=(3,), survive=(2, 3)):
    """Per-universe oracle loop: n turns of the independent numpy stencil."""
    for _ in range(n):
        board = vector_step(board, birth, survive)
    return board


def _mixed_batch(b=6, h=64, w=64, seed=0):
    """A batch with mixed liveness: universe 0 all dead, universe 1 a lone
    glider, the rest dense random — one tensor, very different dynamics."""
    rng = np.random.default_rng(seed)
    boards = np.where(rng.random((b, h, w)) < 0.3, 255, 0).astype(np.uint8)
    if b > 1:
        boards[0] = 0
        boards[1] = 0
        for y, x in ((1, 2), (2, 3), (3, 1), (3, 2), (3, 3)):
            boards[1, y, x] = 255
    return boards


def _oracle_batch(boards, n, birth=(3,), survive=(2, 3)):
    return np.stack([_seq(b, n, birth, survive) for b in boards])


@pytest.fixture
def live_metrics():
    reg = obs_metrics.registry()
    reg.reset()
    obs_metrics.enable()
    yield reg
    obs_metrics.enable(False)
    reg.reset()


def _metric(name, labels=()):
    for fam in obs_metrics.registry().snapshot()["families"]:
        if fam["name"] == name:
            for s in fam["series"]:
                if tuple(s.get("labels", ())) == tuple(labels):
                    return s["value"]
    return 0.0


# -- batched kernel family ---------------------------------------------------


def test_batched_byte_tier_parity_mixed_batch():
    from gol_distributed_final_tpu.ops.stencil import step_n_batch

    boards = _mixed_batch()
    want = _oracle_batch(boards, 8)
    got = np.asarray(step_n_batch(boards, 8))
    assert np.array_equal(got, want)
    # non-Conway rule through the same batched tier
    want_hl = _oracle_batch(boards, 5, birth=(3, 6), survive=(2, 3))
    got_hl = np.asarray(
        step_n_batch(
            boards, 5,
            birth_mask=HIGHLIFE.birth_mask,
            survive_mask=HIGHLIFE.survive_mask,
        )
    )
    assert np.array_equal(got_hl, want_hl)


def test_batched_xla_bit_tier_parity_mixed_batch():
    from gol_distributed_final_tpu.ops import bitpack

    boards = _mixed_batch()
    want = _oracle_batch(boards, 8)
    packed = bitpack.pack_device_batch(boards)
    out = bitpack.bit_step_n_batch(packed, 8)
    assert np.array_equal(
        np.asarray(bitpack.unpack_device_batch(out)), want
    )
    # word_axis=1 packing too
    packed1 = bitpack.pack_device_batch(boards, 1)
    out1 = bitpack.bit_step_n_batch(packed1, 8, 1)
    assert np.array_equal(
        np.asarray(bitpack.unpack_device_batch(out1, 1)), want
    )


def test_batched_pallas_tier_parity_mixed_batch():
    from gol_distributed_final_tpu.ops import bitpack
    from gol_distributed_final_tpu.ops.pallas_stencil import _bit_compiled_batch

    boards = _mixed_batch(b=3, h=32, w=32, seed=2)
    want = _oracle_batch(boards, 6)
    packed = bitpack.pack_device_batch(boards)
    out = _bit_compiled_batch(6, 0, True)(packed)  # interpret: CPU mesh
    assert np.array_equal(np.asarray(bitpack.unpack_device_batch(out)), want)
    # odd turn count exercises the unroll remainder
    out5 = _bit_compiled_batch(5, 0, True)(packed)
    assert np.array_equal(
        np.asarray(bitpack.unpack_device_batch(out5)),
        _oracle_batch(boards, 5),
    )


def test_batched_reductions_demux_per_universe():
    from gol_distributed_final_tpu.ops import bitpack
    from gol_distributed_final_tpu.ops.reduce import alive_count_batch

    boards = _mixed_batch()
    want = (boards != 0).sum(axis=(1, 2))
    assert np.array_equal(np.asarray(alive_count_batch(boards)), want)
    counts = bitpack.alive_count_packed_batch(bitpack.pack_device_batch(boards))
    assert counts.dtype == np.int64
    assert np.array_equal(counts, want)
    assert counts[0] == 0  # the all-dead universe demuxes to exactly zero


def test_batch_planes_decode_take_compaction():
    from gol_distributed_final_tpu.ops.batched import (
        BatchBitPlane,
        BatchBytePlane,
    )

    boards = _mixed_batch()
    want = _oracle_batch(boards, 4)
    for plane in (BatchBitPlane(CONWAY), BatchBytePlane(CONWAY)):
        state = plane.step_n(plane.encode(boards), 4)
        assert np.array_equal(plane.decode(state), want)
        assert np.array_equal(plane.decode_one(state, 1), want[1])
        assert np.array_equal(
            plane.alive_counts(state), (want != 0).sum(axis=(1, 2))
        )
        # slot compaction: keep rows [0, 2, 5] in order, batch stays dense
        kept = plane.take(state, [0, 2, 5])
        assert np.array_equal(plane.decode(kept), want[[0, 2, 5]])
        # join: append a fresh universe to the compacted batch
        joined = plane.append(kept, plane.encode(boards[3:4]))
        assert np.array_equal(
            plane.decode(joined), np.concatenate([want[[0, 2, 5]], boards[3:4]])
        )


def test_auto_batch_plane_selector_and_indivisible_geometry():
    from gol_distributed_final_tpu.ops.auto import auto_batch_plane
    from gol_distributed_final_tpu.ops.batched import (
        BatchBitPlane,
        BatchBytePlane,
    )

    assert isinstance(auto_batch_plane(CONWAY, (64, 64)), BatchBitPlane)
    assert isinstance(auto_batch_plane(CONWAY, (64, 50)), BatchBitPlane)
    plane = auto_batch_plane(CONWAY, (30, 30))
    assert isinstance(plane, BatchBytePlane)  # no packable axis
    # decisions are cached: the same key returns the same plane object
    assert auto_batch_plane(CONWAY, (30, 30)) is plane
    # the byte tier really serves the indivisible geometry
    boards = _mixed_batch(b=4, h=30, w=30, seed=3)
    state = plane.step_n(plane.encode(boards), 7)
    assert np.array_equal(plane.decode(state), _oracle_batch(boards, 7))


def test_auto_plane_selection_hoisted_once_per_decision(live_metrics):
    """The ISSUE 7 small fix: auto_plane used to sample HBM and bump the
    tier counter on EVERY call; per-session admission in a hot serving
    loop must pay a dict hit instead — the counter moves once per NEW
    (rule, shape) decision, never per universe."""
    from gol_distributed_final_tpu.ops.auto import auto_batch_plane, auto_plane

    shape = (96, 544)  # unique: never used elsewhere, so the cache is cold
    # VMEM-fit bitboards now select the fused tier (ISSUE 15: its own
    # label, so the roofline attributes fused sites separately)
    before = _metric("gol_ops_plane_selected_total", ("fused_bitplane",))
    p1 = auto_plane(CONWAY, shape)
    for _ in range(50):  # a hot admission loop
        assert auto_plane(CONWAY, shape) is p1
    after = _metric("gol_ops_plane_selected_total", ("fused_bitplane",))
    assert after - before == 1
    bshape = (96, 576)
    before = _metric("gol_ops_plane_selected_total", ("batch_bitplane",))
    b1 = auto_batch_plane(CONWAY, bshape)
    for _ in range(50):
        assert auto_batch_plane(CONWAY, bshape) is b1
    after = _metric("gol_ops_plane_selected_total", ("batch_bitplane",))
    assert after - before == 1


# -- session table lifecycle -------------------------------------------------


def test_admission_rejects_at_capacity_geometry_turns(live_metrics):
    from gol_distributed_final_tpu.engine.sessions import (
        SessionRejected,
        SessionTable,
    )

    table = SessionTable(CONWAY, (32, 32), capacity=2)
    boards = _mixed_batch(b=3, h=32, w=32, seed=4)
    table.admit(boards[0], 5)
    table.admit(boards[1], 5)
    with pytest.raises(SessionRejected) as exc:
        table.admit(boards[2], 5)
    assert exc.value.reason == "capacity"
    with pytest.raises(SessionRejected) as exc:
        table.admit(np.zeros((16, 16), np.uint8), 5)
    assert exc.value.reason == "geometry"
    with pytest.raises(SessionRejected) as exc:
        table.admit(boards[0][:32, :32], 0)
    assert exc.value.reason == "turns"
    assert _metric("gol_sessions_rejected_total", ("capacity",)) == 1
    assert _metric("gol_sessions_rejected_total", ("geometry",)) == 1
    assert _metric("gol_sessions_rejected_total", ("turns",)) == 1
    assert _metric("gol_sessions_admitted_total") == 2
    assert _metric("gol_sessions_active") == 2


def test_mid_batch_leave_frees_slot_without_stalling(live_metrics):
    """Differing budgets: the 4-turn universe finishes first, its slot
    compacts away (the device batch shrinks), and the survivors keep
    advancing — bit-identical to their sequential runs throughout. The
    all-dead universe 0 additionally EARLY-RETIRES at the same boundary
    (its alive count demuxed to 0, so the rest of its budget is credited
    arithmetically — gol_early_exit_total{kind="dead"})."""
    from gol_distributed_final_tpu.engine.sessions import SessionTable

    boards = _mixed_batch(b=3, h=32, w=32, seed=5)
    table = SessionTable(CONWAY, (32, 32), capacity=4)
    s_a = table.admit(boards[0], 5)  # all-dead: early-retires
    s_b = table.admit(boards[1], 4)
    s_c = table.admit(boards[2], 9)
    remaining = table.advance()  # k = 4: smallest budget AND the dead
    assert s_b.done.is_set() and s_a.done.is_set()
    assert not s_c.done.is_set()
    assert remaining == 1
    assert len(table._active) == 1 and table._state.shape[0] == 1
    assert np.array_equal(s_b.result, _seq(boards[1], 4))
    n = 0
    while table.advance():
        n += 1
        assert n < 10
    assert s_a.turns_done == 5 and s_b.turns_done == 4 and s_c.turns_done == 9
    assert np.array_equal(s_a.result, _seq(boards[0], 5))
    assert np.array_equal(s_c.result, _seq(boards[2], 9))
    assert _metric("gol_sessions_active") == 0
    assert _metric("gol_early_exit_total", ("dead",)) == 1
    # universe-turns COMPUTED (the dead universe's credited fifth turn
    # is arithmetic, never dispatched): 3 x 4, then s_c alone 4 + 1
    assert _metric("gol_session_turns_total") == 3 * 4 + 1 * 4 + 1 * 1


def test_cancel_is_a_mid_batch_leave():
    from gol_distributed_final_tpu.engine.sessions import SessionTable

    boards = _mixed_batch(b=2, h=32, w=32, seed=6)
    table = SessionTable(CONWAY, (32, 32), capacity=2, max_chunk=2)
    s_a = table.admit(boards[0], 8)
    s_b = table.admit(boards[1], 8)
    table.advance()  # both at turn 2
    table.cancel(s_b)
    while table.advance():
        pass
    assert s_b.done.is_set() and s_b.result is None
    assert s_a.done.is_set() and s_a.turns_done == 8
    assert np.array_equal(s_a.result, _seq(boards[0], 8))


def test_per_session_event_demux_exactness():
    """Every event a session observes demuxes from the ONE batched
    reduction — turns and counts must match the per-universe oracle
    exactly at every chunk boundary, and FinalTurnComplete's cell list
    must be the final board's."""
    from gol_distributed_final_tpu.engine.sessions import SessionTable
    from gol_distributed_final_tpu.events import (
        AliveCellsCount,
        FinalTurnComplete,
        TurnComplete,
    )

    boards = _mixed_batch(b=3, h=32, w=32, seed=7)
    events = {0: [], 1: [], 2: []}
    table = SessionTable(CONWAY, (32, 32), capacity=3)
    sessions = [
        table.admit(boards[i], budget, on_event=events[i].append)
        for i, budget in enumerate((5, 3, 9))
    ]
    while table.advance():
        pass
    # chunk boundaries with power-of-two quantisation for heterogeneous
    # budgets: k=2 (all, min 3 -> pow2 2). The all-dead universe 0 then
    # early-retires (count demuxed to 0: its final three turns are
    # credited arithmetically, no further ticks), so the remaining
    # boundaries come from budgets (3, 9): k=1 (min is 1), k=4, k=2.
    expected = {0: [2], 1: [2, 3], 2: [2, 3, 7, 9]}
    for i, budget in enumerate((5, 3, 9)):
        ticks = [e for e in events[i] if isinstance(e, AliveCellsCount)]
        turns = [e for e in events[i] if isinstance(e, TurnComplete)]
        finals = [e for e in events[i] if isinstance(e, FinalTurnComplete)]
        expected_turns = expected[i]
        assert [e.completed_turns for e in ticks] == expected_turns
        assert [e.completed_turns for e in turns] == expected_turns
        for e in ticks:  # count exactness vs the oracle at that turn
            want = int(
                np.count_nonzero(_seq(boards[i], e.completed_turns))
            )
            assert e.cells_count == want, (i, e.completed_turns)
        assert len(finals) == 1
        assert finals[0].completed_turns == budget
        final_board = _seq(boards[i], budget)
        got_cells = {(c.x, c.y) for c in finals[0].alive}
        ys, xs = np.nonzero(final_board)
        assert got_cells == {(int(x), int(y)) for x, y in zip(xs, ys)}
        assert sessions[i].alive_count == int(np.count_nonzero(final_board))


def test_session_snapshot_consistent_mid_drain():
    from gol_distributed_final_tpu.engine.sessions import SessionTable

    boards = _mixed_batch(b=2, h=32, w=32, seed=8)
    table = SessionTable(CONWAY, (32, 32), capacity=2)
    s_a = table.admit(boards[0], 4)
    s_b = table.admit(boards[1], 8)
    # pending snapshot serves the seed board at turn 0
    world, turn, alive = table.snapshot(s_b, include_world=True)
    assert turn == 0 and np.array_equal(world, boards[1])
    assert alive == int(np.count_nonzero(boards[1]))
    table.advance()  # k = 4: s_a retires, s_b at turn 4
    world, turn, alive = table.snapshot(s_b, include_world=True)
    want = _seq(boards[1], 4)
    assert turn == 4 and np.array_equal(world, want)
    assert alive == int(np.count_nonzero(want))
    # finished session snapshot serves its result
    world, turn, alive = table.snapshot(s_a, include_world=True)
    assert turn == 4 and np.array_equal(world, _seq(boards[0], 4))


def test_join_at_chunk_boundary_mid_flight():
    """A universe admitted while the batch is mid-flight joins at the next
    advance boundary and both finish bit-identical to sequential runs."""
    from gol_distributed_final_tpu.engine.sessions import SessionTable

    boards = _mixed_batch(b=2, h=32, w=32, seed=9)
    table = SessionTable(CONWAY, (32, 32), capacity=2, max_chunk=2)
    s_a = table.admit(boards[0], 6)
    table.advance()  # a alone at turn 2
    s_b = table.admit(boards[1], 4)
    while table.advance():
        pass
    assert np.array_equal(s_a.result, _seq(boards[0], 6))
    assert np.array_equal(s_b.result, _seq(boards[1], 4))
    assert s_a.turns_done == 6 and s_b.turns_done == 4


# -- the broker scheduler + RPC surface --------------------------------------


def test_session_run_rpc_concurrent_parity():
    """Concurrent SessionRun verbs over a live in-process broker: every
    universe's reply is bit-identical to its sequential oracle run."""
    from gol_distributed_final_tpu.params import Params
    from gol_distributed_final_tpu.rpc import broker as rpc_broker
    from gol_distributed_final_tpu.rpc.client import RemoteBroker

    server, service = rpc_broker.serve(port=0, session_capacity=8)
    try:
        addr = f"127.0.0.1:{server.port}"
        boards = _mixed_batch(b=5, h=32, w=32, seed=10)
        budgets = [4, 7, 3, 9, 5]
        results: dict = {}

        def one(i):
            rb = RemoteBroker(addr)
            try:
                results[i] = rb.session_run(
                    Params(turns=budgets[i], image_width=32, image_height=32),
                    boards[i],
                )
            finally:
                rb.client.close()

        threads = [
            threading.Thread(target=one, args=(i,)) for i in range(5)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
        for i in range(5):
            assert results[i].turns_completed == budgets[i]
            assert np.array_equal(
                results[i].world, _seq(boards[i], budgets[i])
            ), i
    finally:
        server.stop()


def test_session_run_rpc_rejects_at_capacity(live_metrics):
    """Admission past -session-capacity is an ERROR REPLY, not a queue:
    pre-fill the broker's table to its bound, then a SessionRun refusal
    names capacity and bumps the refusal counter."""
    from gol_distributed_final_tpu.params import Params
    from gol_distributed_final_tpu.rpc import broker as rpc_broker
    from gol_distributed_final_tpu.rpc.client import RemoteBroker, RpcError

    server, service = rpc_broker.serve(port=0, session_capacity=2)
    try:
        addr = f"127.0.0.1:{server.port}"
        boards = _mixed_batch(b=3, h=32, w=32, seed=11)
        # fill the table directly (deterministic: no driver race) — the
        # scheduler's submit then sees a full table
        from gol_distributed_final_tpu.engine.sessions import SessionTable

        sched = service._session_scheduler()
        with sched._work:
            sched._table = SessionTable(CONWAY, (32, 32), 2)
            sched._table.admit(boards[0], 50)
            sched._table.admit(boards[1], 50)
        rb = RemoteBroker(addr)
        try:
            with pytest.raises(RpcError, match="capacity|full"):
                rb.session_run(
                    Params(turns=5, image_width=32, image_height=32),
                    boards[2],
                )
        finally:
            rb.client.close()
        assert _metric("gol_sessions_rejected_total", ("capacity",)) == 1
    finally:
        server.stop()


def test_session_run_rpc_rejects_geometry_and_rule_mismatch():
    from gol_distributed_final_tpu.params import Params
    from gol_distributed_final_tpu.rpc import broker as rpc_broker
    from gol_distributed_final_tpu.rpc.client import RemoteBroker, RpcError
    from gol_distributed_final_tpu.engine.sessions import SessionTable

    server, service = rpc_broker.serve(port=0, session_capacity=4)
    try:
        addr = f"127.0.0.1:{server.port}"
        sched = service._session_scheduler()
        boards = _mixed_batch(b=1, h=32, w=32, seed=12)
        with sched._work:
            sched._table = SessionTable(CONWAY, (32, 32), 4)
            sched._table.admit(boards[0], 50)  # occupied: geometry is pinned
        rb = RemoteBroker(addr)
        try:
            with pytest.raises(RpcError, match="geometry|batch"):
                rb.session_run(
                    Params(turns=5, image_width=16, image_height=16),
                    np.zeros((16, 16), np.uint8),
                )
            with pytest.raises(RpcError, match="rule"):
                rb.session_run(
                    Params(turns=5, image_width=32, image_height=32),
                    boards[0],
                    rule=HIGHLIFE,
                )
        finally:
            rb.client.close()
    finally:
        server.stop()


def test_session_retrieve_by_tag_mid_flight():
    """A nonzero session_id tags the session; Retrieve with the same tag
    serves THAT universe's (turn, alive, board) demuxed from the batch —
    consistent with the oracle at whatever turn the snapshot lands on."""
    from gol_distributed_final_tpu.params import Params
    from gol_distributed_final_tpu.rpc import broker as rpc_broker
    from gol_distributed_final_tpu.rpc.broker import SessionScheduler
    from gol_distributed_final_tpu.rpc.client import RemoteBroker, RpcError

    server, service = rpc_broker.serve(port=0, session_capacity=4)
    try:
        addr = f"127.0.0.1:{server.port}"
        # max_chunk=1: one turn per driver boundary, a wide mid-flight
        # window for the tagged Retrieve to land in
        with service._sessions_lock:
            service._sessions = SessionScheduler(capacity=4, max_chunk=1)
        boards = _mixed_batch(b=1, h=32, w=32, seed=13)
        # wide enough that the watcher reliably lands mid-flight even on
        # a loaded host (the full suite runs alongside): ~240 driver
        # boundaries vs a single already-connected Retrieve round-trip
        turns = 240
        done = threading.Event()
        result: dict = {}

        def run():
            rb = RemoteBroker(addr)
            try:
                result["r"] = rb.session_run(
                    Params(turns=turns, image_width=32, image_height=32),
                    boards[0],
                    session_id=7,
                )
            finally:
                rb.client.close()
                done.set()

        # connect the watcher BEFORE the run starts: its first Retrieve
        # races only the session admission, not TCP connect setup
        rb2 = RemoteBroker(addr)
        t = threading.Thread(target=run)
        t.start()
        snap = None
        try:
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline and not done.is_set():
                try:
                    snap = rb2.retrieve(include_world=True, session_id=7)
                    break
                except RpcError:
                    time.sleep(0.01)  # not yet admitted
            assert snap is not None, "never caught the session in flight"
            want = _seq(boards[0], snap.turns_completed)
            assert np.array_equal(snap.world, want)
            assert snap.alive_count == int(np.count_nonzero(want))
            # unknown tag is a loud error, not a silent global snapshot
            with pytest.raises(RpcError, match="no session"):
                rb2.retrieve(session_id=999)
        finally:
            rb2.client.close()
        t.join(60)
        assert np.array_equal(result["r"].world, _seq(boards[0], turns))
    finally:
        server.stop()


# -- observability surface ---------------------------------------------------


def test_watch_sessions_panel(live_metrics):
    from gol_distributed_final_tpu.obs import instruments as ins
    from gol_distributed_final_tpu.obs.watch import render_status

    ins.SESSIONS_ACTIVE.set(12)
    ins.SESSIONS_ADMITTED_TOTAL.inc(40)
    ins.SESSIONS_REJECTED_TOTAL.labels("capacity").inc(3)
    ins.SESSION_TURNS_TOTAL.inc(12345)
    payload = {
        "role": "broker",
        "pid": 1,
        "metrics_enabled": True,
        "metrics": obs_metrics.registry().snapshot(),
    }
    frame = render_status("broker :8040", payload, None)
    assert "SESSIONS" in frame
    assert "active 12" in frame and "admitted 40" in frame
    assert "capacity 3" in frame
    assert "12,345" in frame
    # an idle broker renders no SESSIONS panel
    obs_metrics.registry().reset()
    payload["metrics"] = obs_metrics.registry().snapshot()
    assert "SESSIONS" not in render_status("broker :8040", payload, None)


def test_lint_session_metrics_sections(tmp_path, repo_root):
    from gol_distributed_final_tpu.obs import lint

    assert lint.undocumented_session_metrics() == []
    assert "Sessions" not in lint.missing_readme_sections()
    bare = tmp_path / "README.md"
    bare.write_text("# nothing\n")
    missing = lint.undocumented_session_metrics(bare)
    assert "gol_sessions_active" in missing
    assert "gol_session_turns_total" in missing
    assert "Sessions" in lint.missing_readme_sections(bare)
