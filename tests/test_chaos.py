"""Fault-tolerance / chaos suite (rpc/faults.py + the recovery paths).

Fast deterministic units run in tier-1; the live-cluster scenarios —
wedged worker behind the chaos proxy, SIGKILLed worker readmission,
kill -9 broker + ``-resume`` — are marked ``slow`` and run via
``scripts/check --chaos`` so the tier-1 gate stays fast.
"""

import threading
import time

import numpy as np
import pytest

from gol_distributed_final_tpu import Params
from gol_distributed_final_tpu.obs import metrics as obs_metrics
from gol_distributed_final_tpu.rpc import faults
from gol_distributed_final_tpu.rpc.broker import BrokerService, WorkersBackend
from gol_distributed_final_tpu.rpc.client import RemoteBroker, RpcClient, RpcError
from gol_distributed_final_tpu.rpc.faults import ChaosProxy, FaultInjected
from gol_distributed_final_tpu.rpc.protocol import Methods, Request, Response
from gol_distributed_final_tpu.rpc.server import RpcServer

from helpers import REPO_ROOT, assert_equal_board, read_alive_cells
from test_rpc import _poll_turn, _spawn, _wait_listening


@pytest.fixture
def clean_faults():
    """Reset the fault-point spec before and after a test."""
    faults.configure(None)
    yield faults
    faults.configure(None)


@pytest.fixture
def live_metrics():
    """Enable the process-global registry for one test (counters no-op
    while disabled), restoring the off default after."""
    obs_metrics.enable()
    yield obs_metrics
    obs_metrics.enable(False)


def _counter(name: str, snap=None) -> float:
    """Summed value of a counter family from a registry/Status snapshot."""
    if snap is None:
        snap = obs_metrics.registry().snapshot()
    for fam in snap.get("families", []):
        if fam.get("name") == name:
            return sum(s.get("value", 0.0) for s in fam.get("series", []))
    return 0.0


def _fetch_broker_counter(address: str, name: str) -> float:
    from gol_distributed_final_tpu.obs.status import fetch_status

    payload = fetch_status(address, timeout=5.0)
    return _counter(name, payload.get("metrics") or {})


# -- fault points (env-triggered in-process faults) ---------------------------


def test_fault_point_spec_parsing_and_raise(clean_faults):
    faults.configure("p:raise:2")
    faults.fault_point("p")  # hit 1: no fire
    with pytest.raises(FaultInjected, match="hit 2"):
        faults.fault_point("p")
    faults.fault_point("p")  # hit 3: a raise fires exactly once
    with pytest.raises(ValueError, match="unknown fault action"):
        faults.configure("p:explode:1")
    with pytest.raises(ValueError, match="sleep needs seconds"):
        faults.configure("p:sleep:1")


def test_fault_point_sleep_and_unconfigured_noop(clean_faults):
    faults.configure("slow:sleep:2:0.05")
    t0 = time.monotonic()
    faults.fault_point("slow")  # hit 1 < k: free
    assert time.monotonic() - t0 < 0.04
    t0 = time.monotonic()
    faults.fault_point("slow")  # hit 2 >= k: sleeps every hit from now on
    assert time.monotonic() - t0 >= 0.05
    faults.fault_point("never.configured")  # unknown site: no-op


def test_fault_point_reads_env_once(clean_faults, monkeypatch):
    monkeypatch.setenv("GOL_FAULT_POINTS", "envp:raise:1")
    faults.configure(None)  # forget: next hit re-reads the env
    with pytest.raises(FaultInjected):
        faults.fault_point("envp")


# -- chaos proxy --------------------------------------------------------------


def _echo_server():
    server = RpcServer(port=0)
    server.register("Echo.Echo", lambda req: req)
    server.serve_background()
    return server


def test_proxy_forwards_frames_and_counts():
    server = _echo_server()
    proxy = ChaosProxy(f"127.0.0.1:{server.port}")
    try:
        client = RpcClient(proxy.address, timeout=5.0)
        res = client.call("Echo.Echo", Request(turns=7), timeout=10.0)
        assert res.turns == 7
        assert proxy.frames_forwarded == 2  # request + reply
        client.close()
    finally:
        proxy.close()
        server.stop()


def test_proxy_corrupt_frame_fails_call_then_reconnect_recovers():
    """A corrupted frame must land as a failed call (unpickling error →
    dropped connection), never a silently-wrong payload; a reconnecting
    client then recovers through the same proxy."""
    server = _echo_server()
    proxy = ChaosProxy(f"127.0.0.1:{server.port}", seed=3)
    try:
        client = RpcClient(proxy.address, timeout=5.0, reconnect=True)
        assert client.call("Echo.Echo", Request(turns=1), timeout=10.0).turns == 1
        proxy.set_fault(corrupt_frame=proxy.frames_forwarded)
        with pytest.raises(RpcError):
            client.call("Echo.Echo", Request(turns=2), timeout=10.0)
        deadline = time.monotonic() + 10
        while True:  # retry across the reconnect backoff window
            try:
                res = client.call("Echo.Echo", Request(turns=3), timeout=10.0)
                break
            except RpcError:
                assert time.monotonic() < deadline, "never recovered"
                time.sleep(0.05)
        assert res.turns == 3
        client.close()
    finally:
        proxy.close()
        server.stop()


def test_client_reconnect_backoff_gates_and_recovers(live_metrics):
    """Transport death → the next call reconnects; while the peer stays
    dead, attempts are gated by capped jittered exponential backoff; when
    a listener returns on the same port, the client heals."""
    server = _echo_server()
    proxy = ChaosProxy(f"127.0.0.1:{server.port}")
    port = proxy.port
    client = RpcClient(proxy.address, timeout=5.0, reconnect=True)
    try:
        assert client.call("Echo.Echo", Request(turns=1), timeout=10.0).turns == 1
        retries0 = _counter("gol_rpc_retries_total")
        proxy.close()  # the peer vanishes, connections die
        with pytest.raises(RpcError):
            client.call("Echo.Echo", Request(turns=2), timeout=5.0)
        # dial attempt against a closed port: refused, starts the backoff
        with pytest.raises(RpcError, match="reconnect|backing off"):
            client.call("Echo.Echo", Request(turns=2), timeout=5.0)
        # immediately again: gated by the backoff window, no dial attempt
        with pytest.raises(RpcError, match="backing off"):
            client.call("Echo.Echo", Request(turns=2), timeout=5.0)
        proxy2 = ChaosProxy(f"127.0.0.1:{server.port}", listen_port=port)
        try:
            deadline = time.monotonic() + 10
            while True:
                try:
                    res = client.call("Echo.Echo", Request(turns=4), timeout=10.0)
                    break
                except RpcError:
                    assert time.monotonic() < deadline, "never reconnected"
                    time.sleep(0.05)
            assert res.turns == 4
            assert _counter("gol_rpc_retries_total") > retries0
        finally:
            proxy2.close()
    finally:
        client.close()
        server.stop()


# -- WorkersBackend recovery units (fake workers, in-process) ----------------


class _FakeWorker:
    """Duck-typed scatter client: evolves nothing, echoes the strip."""

    def __init__(self, delay=0.0):
        self.delay = delay
        self.closed = False
        self.calls = 0

    def call(self, method, req, timeout=None, **kw):
        self.calls += 1
        if self.delay:
            time.sleep(self.delay)
        if req.world is None:  # a control verb (WorkerQuit): no strip
            return Response()
        return Response(work_slice=req.world[1:-1])

    def close(self):
        self.closed = True


class _DeadWorker(_FakeWorker):
    def __init__(self, exc=RpcError("boom")):
        super().__init__()
        self.exc = exc

    def call(self, method, req, timeout=None, **kw):
        self.calls += 1
        raise self.exc


def _board(h=8, w=8, seed=5):
    rng = np.random.default_rng(seed)
    return np.where(rng.random((h, w)) < 0.4, 255, 0).astype(np.uint8)


def test_dead_client_is_closed_and_dropped_mid_run(live_metrics):
    """Satellite: a worker removed mid-run must have its RpcClient CLOSED
    and dropped from WorkersBackend.clients — no corpse for Status polls,
    collect_remote_spans, or super_quit to pay a timeout on."""
    good, dead = _FakeWorker(), _DeadWorker()
    backend = WorkersBackend([])
    backend.clients = [good, dead]
    lost0 = _counter("gol_worker_lost_total")
    retries0 = _counter("gol_turn_retry_total")
    board = _board()
    res = backend.run(
        Request(world=board, turns=5, threads=2, image_width=8, image_height=8)
    )
    assert res.turns_completed == 5
    assert dead.closed and backend.clients == [good]
    assert _counter("gol_worker_lost_total") == lost0 + 1
    assert _counter("gol_turn_retry_total") == retries0 + 1
    # the run recomputed every turn over the survivor: identity fake, so
    # the board is unchanged and each turn cost exactly one good call
    np.testing.assert_array_equal(res.world, board)


def test_super_quit_survives_half_dead_socket():
    """Satellite: the WorkerQuit fan-out must catch OSError too — a
    half-dead socket used to abort the loop and leave the remaining
    workers running."""
    half_dead = _DeadWorker(OSError("broken pipe"))
    survivor = _FakeWorker()
    backend = WorkersBackend([])
    backend.clients = [half_dead, survivor]
    backend.super_quit()
    assert survivor.calls == 1, "the quit fan-out never reached the survivor"
    assert half_dead.closed and survivor.closed


def test_probe_readmits_pre_status_worker(live_metrics):
    """A version-skewed worker WITHOUT the Status verb still proves life:
    its 'unknown method' ERROR REPLY is a completed round-trip, so the
    probe readmits it instead of quarantining it forever."""
    server = RpcServer(port=0)  # registers no verbs at all: every call errors
    server.serve_background()
    addr = f"127.0.0.1:{server.port}"
    backend = WorkersBackend([addr], probe_interval=0.1)
    try:
        assert len(backend.clients) == 1  # connected at init
        readmits0 = _counter("gol_worker_readmitted_total")
        backend._mark_lost(backend.clients[0], "test")
        assert backend.clients == []
        deadline = time.monotonic() + 10
        while not backend.clients:
            assert time.monotonic() < deadline, (
                "pre-Status worker never readmitted"
            )
            time.sleep(0.05)
        with backend._lock:
            assert addr not in backend._lost
        assert _counter("gol_worker_readmitted_total") == readmits0 + 1
    finally:
        backend._probe_stop.set()
        for c in backend.clients:
            c.close()
        server.stop()


def test_super_quit_reaches_lost_but_alive_workers():
    """SuperQuit takes the WHOLE cluster down: a worker that was evicted
    (lost) but is alive and reachable still gets WorkerQuit, best-effort
    via a fresh dial of its roster address."""
    quits = []
    server = RpcServer(port=0)
    server.register(
        Methods.WORKER_QUIT, lambda req: quits.append(1) or Response()
    )
    server.register(Methods.WORKER_STATUS, lambda req: Response(status={"x": 1}))
    server.serve_background()
    try:
        backend = WorkersBackend([])
        with backend._lock:
            backend._lost[f"127.0.0.1:{server.port}"] = time.monotonic() + 999
        backend.super_quit()
        assert quits == [1], "lost-but-alive worker never got WorkerQuit"
    finally:
        server.stop()


def test_adaptive_scatter_deadline_formula():
    backend = WorkersBackend([])
    assert backend._scatter_deadline() == 300.0  # cold: no turn observed yet
    backend._turn_seconds = 0.01
    assert backend._scatter_deadline() == 5.0  # floored
    backend._turn_seconds = 1.0
    assert backend._scatter_deadline() == 21.0  # 20x EWMA + 1
    # deliberately uncapped: a wedge costs ~20x a LEGIT turn, so a slow
    # cluster's honest 70 s turns are never evicted wholesale
    backend._turn_seconds = 70.0
    assert backend._scatter_deadline() == 1401.0
    pinned = WorkersBackend([], rpc_deadline=2.5)
    pinned._turn_seconds = 10.0
    assert pinned._scatter_deadline() == 2.5  # -rpc-deadline wins


def test_auto_checkpoint_writes_loadable_npz(tmp_path, live_metrics):
    from gol_distributed_final_tpu.engine.checkpoint import load_checkpoint
    from gol_distributed_final_tpu.models import CONWAY

    path = tmp_path / "bk.npz"
    backend = WorkersBackend([], auto_checkpoint=(0.0, str(path)))
    backend.clients = [_FakeWorker()]
    ckpts0 = _counter("gol_auto_checkpoint_total")
    board = _board()
    backend.run(
        Request(world=board, turns=4, threads=1, image_width=8, image_height=8)
    )
    world, turn, rule = load_checkpoint(path)
    assert turn == 4 and rule.rulestring == CONWAY.rulestring
    np.testing.assert_array_equal(world, board)  # identity fake
    assert _counter("gol_auto_checkpoint_total") == ckpts0 + 4
    assert not path.with_name("bk.npz.tmp.npz").exists()  # renamed away


def test_broker_service_resume_substitution_and_validation():
    from gol_distributed_final_tpu.engine.engine import RunResult
    from gol_distributed_final_tpu.models import CONWAY

    seen = {}

    class FakeBackend:
        def run(self, req):
            seen["req"] = req
            return RunResult(req.turns, req.world)

    ckpt_world = _board(16, 16, seed=9)
    service = BrokerService(None, FakeBackend(), resume=(ckpt_world, 40, CONWAY))
    fresh = _board(16, 16, seed=1)
    service.run(
        Request(world=fresh, turns=100, image_width=16, image_height=16)
    )
    assert seen["req"].initial_turn == 40
    np.testing.assert_array_equal(seen["req"].world, ckpt_world)
    # consumed: the next fresh Run starts from its own world at turn 0
    service.run(
        Request(world=fresh, turns=100, image_width=16, image_height=16)
    )
    assert seen["req"].initial_turn == 0
    np.testing.assert_array_equal(seen["req"].world, fresh)
    # loud mismatches, not silent from-zero runs
    service2 = BrokerService(None, FakeBackend(), resume=(ckpt_world, 40, CONWAY))
    with pytest.raises(ValueError, match="checkpoint board is"):
        service2.run(
            Request(world=_board(8, 8), turns=100, image_width=8, image_height=8)
        )
    with pytest.raises(ValueError, match="nothing would run"):
        service2.run(
            Request(world=fresh, turns=40, image_width=16, image_height=16)
        )
    # a Run that fails AFTER substitution must not burn the stash: the
    # retried Run still resumes (workers may just have been restarting)
    class FailsOnce(FakeBackend):
        fails = 1

        def run(self, req):
            if self.fails:
                self.fails -= 1
                raise RpcError("no workers connected")
            return super().run(req)

    service3 = BrokerService(None, FailsOnce(), resume=(ckpt_world, 40, CONWAY))
    with pytest.raises(RpcError, match="no workers"):
        service3.run(
            Request(world=fresh, turns=100, image_width=16, image_height=16)
        )
    service3.run(
        Request(world=fresh, turns=100, image_width=16, image_height=16)
    )
    assert seen["req"].initial_turn == 40, "retried Run lost the resume stash"

    # a Run consumed by a buffered pre-run Quit makes NO progress past the
    # checkpoint: the stash must survive for the reattaching Run
    class QuitConsumed(FakeBackend):
        quits = 1

        def run(self, req):
            seen["req"] = req
            done = req.turns if not self.quits else req.initial_turn
            self.quits = 0
            return RunResult(done, req.world)

    service4 = BrokerService(
        None, QuitConsumed(), resume=(ckpt_world, 40, CONWAY)
    )
    service4.run(
        Request(world=fresh, turns=100, image_width=16, image_height=16)
    )
    assert service4._resume is not None, "no-progress Run burned the stash"
    service4.run(
        Request(world=fresh, turns=100, image_width=16, image_height=16)
    )
    assert seen["req"].initial_turn == 40  # re-applied, then consumed
    assert service4._resume is None


def test_pause_and_quit_race_worker_loss_without_deadlock():
    """Satellite: Pause toggled while the turn loop is inside the resplit
    retry parks on the committed turn; quit then ends the run. No
    deadlock in any interleaving."""
    slow = _FakeWorker(delay=0.02)
    dying = _FakeWorker(delay=0.02)
    backend = WorkersBackend([])

    def die_at_5(method, req, timeout=None, **kw):
        dying.calls += 1
        if backend.retrieve(False).turns_completed >= 5:
            raise RpcError("induced death mid-run")
        time.sleep(0.02)
        return Response(work_slice=req.world[1:-1])

    dying.call = die_at_5
    backend.clients = [slow, dying]
    board = _board(16, 16)
    req = Request(
        world=board, turns=10**9, threads=2, image_width=16, image_height=16
    )
    t = threading.Thread(target=lambda: backend.run(req))
    t.start()
    try:
        deadline = time.monotonic() + 20
        while (
            backend.retrieve(False).turns_completed < 6
            and time.monotonic() < deadline
        ):
            time.sleep(0.005)
        backend.pause()  # may land inside the loss/resplit retry
        a = backend.retrieve(False).turns_completed
        time.sleep(0.2)
        b = backend.retrieve(False).turns_completed
        assert a == b, "board advanced while parked"
        assert dying.closed, "lost worker not closed"
        backend.pause()  # resume over the survivor
        deadline = time.monotonic() + 20
        while (
            backend.retrieve(False).turns_completed <= b
            and time.monotonic() < deadline
        ):
            time.sleep(0.005)
        assert backend.retrieve(False).turns_completed > b
    finally:
        backend.quit()
        t.join(timeout=20)
    assert not t.is_alive(), "run loop deadlocked"


def test_stuck_scatter_send_cannot_hang_the_run():
    """The client deadline only bounds the REPLY wait: a scatter stuck in
    the SEND (peer stopped draining its receive buffer) must be cut by
    the gather's own deadline+grace bound, not hang the run forever."""
    release = threading.Event()
    good = _FakeWorker()

    class StuckInSend:
        closed = False

        def call(self, method, req, timeout=None, **kw):
            release.wait()  # ignores the timeout — a blocked sendall
            raise RpcError("released")

        def close(self):
            self.closed = True

    stuck = StuckInSend()
    backend = WorkersBackend([], rpc_deadline=0.5)
    # steady state: a clean-turn estimate exists, so the gather's send
    # allowance is 10x EWMA, not the generous first-turn cold bound
    backend._turn_seconds = 0.01
    backend.clients = [good, stuck]
    board = _board()
    t0 = time.monotonic()
    try:
        res = backend.run(
            Request(
                world=board, turns=3, threads=2, image_width=8, image_height=8
            )
        )
    finally:
        release.set()  # free the parked pool thread
    assert res.turns_completed == 3
    assert time.monotonic() - t0 < 10, "gather did not cut the stuck send"
    assert stuck.closed and backend.clients == [good]
    np.testing.assert_array_equal(res.world, board)


def test_repeat_losses_escalate_probe_quarantine():
    """A flapping worker (readmitted, then lost again) must see its
    per-address probe backoff DOUBLE across losses — the entry survives
    readmission — so a compute-wedged-but-Status-answering worker cannot
    tax every turn a deadline forever."""
    backend = WorkersBackend([], probe_interval=0.5)
    for expected in (1.0, 2.0, 4.0):
        fake = _FakeWorker()
        with backend._lock:
            backend.clients.append(fake)
            backend._client_addr[id(fake)] = "10.0.0.9:8030"
        backend._mark_lost(fake, "test")
        assert backend._probe_backoff["10.0.0.9:8030"] == expected
        # a successful readmission clears _lost but KEEPS the backoff
        with backend._lock:
            backend._lost.pop("10.0.0.9:8030", None)
    assert backend.clients == []
    # WorkersBackend refuses a busy-spin probe cadence outright
    with pytest.raises(ValueError, match="probe_interval"):
        WorkersBackend([], probe_interval=0)


def test_failed_probe_never_collapses_loss_quarantine():
    """A failed readmission probe of a dead address grows toward the short
    probe cap, but must never shrink a loss-escalated quarantine: the live
    probe thread keeps a pre-seeded 16 s quarantine at >= 16 s."""
    addr = "127.0.0.1:9"  # discard port: connects are refused instantly
    backend = WorkersBackend([addr], probe_interval=0.1)
    try:
        with backend._lock:
            assert addr in backend._lost  # dead at connect, kept on roster
            backend._probe_backoff[addr] = 16.0  # an escalated quarantine
            backend._lost[addr] = time.monotonic()  # probe due now
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            with backend._lock:
                if backend._lost[addr] > time.monotonic() + 1.0:
                    break  # a failed probe rescheduled far out: preserved
            time.sleep(0.05)
        with backend._lock:
            assert backend._probe_backoff[addr] >= 16.0, (
                "failed probe collapsed the loss quarantine"
            )
            assert addr in backend._lost
    finally:
        backend._probe_stop.set()


def test_watch_renders_worker_health_column():
    from gol_distributed_final_tpu.obs.watch import render_status

    payload = {
        "role": "broker",
        "pid": 1,
        "metrics_enabled": True,
        "workers": [
            {"address": "10.0.0.3:8030", "state": "connected"},
            {"address": "10.0.0.4:8030", "state": "lost", "retry_in_s": 1.5},
        ],
        "metrics": {
            "families": [
                {
                    "name": "gol_worker_lost_total",
                    "type": "counter",
                    "labelnames": [],
                    "series": [{"labels": [], "value": 3}],
                },
                {
                    "name": "gol_worker_readmitted_total",
                    "type": "counter",
                    "labelnames": [],
                    "series": [{"labels": [], "value": 2}],
                },
            ]
        },
    }
    out = render_status("broker :8040", payload)
    assert "WORKERS (roster health)" in out
    assert "10.0.0.3:8030" in out and "connected" in out
    assert "10.0.0.4:8030" in out and "next probe in 1.5s" in out
    assert "lost 3" in out and "readmitted 2" in out
    # a skewed payload without the field renders no panel, no crash
    assert "WORKERS" not in render_status(
        "b", {"role": "broker", "pid": 1, "metrics_enabled": True}
    )


# -- live chaos scenarios (subprocess clusters; slow-marked) ------------------


def _read_board_64():
    import gol_distributed_final_tpu.io.pgm as pgm

    p = Params(turns=1, image_width=64, image_height=64)
    return pgm.read_board(p, REPO_ROOT / "images")


def _oracle_64(turns):
    from oracle import vector_step

    world = _read_board_64()
    for _ in range(turns):
        world = vector_step(world)
    return world


def _kill_all(procs):
    for p in procs:
        if p is not None:
            if p.poll() is None:
                p.kill()
            p.wait()


@pytest.mark.slow
def test_wedged_worker_costs_at_most_one_deadline_golden(tmp_path):
    """Acceptance (a): a worker wedged at the transport (chaos proxy,
    wedge from frame 0) costs the run AT MOST one -rpc-deadline — the
    broker drops it at the deadline, resplits, and completes with the
    bit-correct final board instead of hanging like the reference. The
    readmission probe must NOT readmit it: a probe through the wedged
    path cannot complete the required Status round-trip."""
    from test_rpc import _run_remote

    workers = [
        _spawn("gol_distributed_final_tpu.rpc.worker", "-port", "0")
        for _ in range(2)
    ]
    broker = proxy = None
    try:
        ports = [_wait_listening(w) for w in workers]
        proxy = ChaosProxy(f"127.0.0.1:{ports[1]}", wedge_after=0)
        broker = _spawn(
            "gol_distributed_final_tpu.rpc.broker",
            "-port", "0", "-backend", "workers", "-metrics",
            "-workers", f"127.0.0.1:{ports[0]},{proxy.address}",
            "-rpc-deadline", "2", "-probe-interval", "0.2",
        )
        address = f"127.0.0.1:{_wait_listening(broker)}"
        t0 = time.monotonic()
        result, _ = _run_remote(address, 64, 100, tmp_path, threads=2)
        elapsed = time.monotonic() - t0
        expected = read_alive_cells(
            REPO_ROOT / "check" / "images" / "64x64x100.pgm"
        )
        assert_equal_board(result.alive, expected, 64, 64)
        # paid the one deadline for the wedged scatter, and only that:
        # nowhere near a second 60 s cold deadline or a hang
        assert 2.0 <= elapsed < 30.0, f"elapsed {elapsed:.1f}s"
        assert _fetch_broker_counter(address, "gol_worker_lost_total") == 1
        assert (
            _fetch_broker_counter(address, "gol_worker_readmitted_total") == 0
        ), "a wedged path must not be readmitted"
    finally:
        if proxy is not None:
            proxy.close()
        _kill_all([*workers, broker])


@pytest.mark.slow
def test_worker_killed_restarted_is_readmitted_and_split_reexpands(tmp_path):
    """Acceptance (b) + the pause/loss race satellite, live: SIGKILL a
    worker mid-run (Pause racing the resplit retry parks cleanly on the
    committed turn), restart it on the same port, and the probe readmits
    it — readmitted counter > 0, the restarted worker serves Update
    traffic again (the split re-expanded), and the final board is
    bit-identical to the oracle."""
    turns = 4000
    workers = [
        _spawn("gol_distributed_final_tpu.rpc.worker", "-port", "0")
        for _ in range(3)
    ]
    broker = restarted = None
    try:
        ports = [_wait_listening(w) for w in workers]
        broker = _spawn(
            "gol_distributed_final_tpu.rpc.broker",
            "-port", "0", "-backend", "workers", "-metrics",
            "-workers", ",".join(f"127.0.0.1:{p}" for p in ports),
            "-rpc-deadline", "5", "-probe-interval", "0.2",
        )
        address = f"127.0.0.1:{_wait_listening(broker)}"
        p = Params(turns=turns, threads=3, image_width=64, image_height=64)
        board = _read_board_64()
        remote = RemoteBroker(address, timeout=30.0)
        result = {}
        t = threading.Thread(target=lambda: result.update(r=remote.run(p, board)))
        t.start()
        try:
            _poll_turn(remote, 300)
            workers[1].kill()  # SIGKILL mid-run
            workers[1].wait()
            remote.pause()  # races the loss/resplit retry; must park
            a = remote.retrieve(include_world=False).turns_completed
            time.sleep(0.3)
            b = remote.retrieve(include_world=False).turns_completed
            assert a == b, "board advanced while parked"
            assert a < turns, "run finished before the kill landed"
            # restart the worker on ITS OLD PORT: the roster address heals
            restarted = _spawn(
                "gol_distributed_final_tpu.rpc.worker",
                "-port", str(ports[1]), "-metrics",
            )
            _wait_listening(restarted)
            deadline = time.monotonic() + 30
            while (
                _fetch_broker_counter(address, "gol_worker_readmitted_total")
                < 1
            ):
                assert time.monotonic() < deadline, "never readmitted"
                time.sleep(0.2)
            remote.pause()  # resume; next turn replans over 3 workers
            t.join(timeout=300)
            assert not t.is_alive(), "run did not complete after readmission"
        finally:
            if t.is_alive():
                remote.quit()
                t.join(timeout=30)
            remote.close()
        r = result["r"]
        assert r.turns_completed == turns
        np.testing.assert_array_equal(r.world, _oracle_64(turns))
        assert _fetch_broker_counter(address, "gol_worker_lost_total") >= 1
        # the readmitted worker carried strips again: split re-expanded
        from gol_distributed_final_tpu.obs.status import fetch_status

        wpayload = fetch_status(
            f"127.0.0.1:{ports[1]}", worker=True, timeout=5.0
        )
        updates = 0.0
        for fam in (wpayload.get("metrics") or {}).get("families", []):
            if fam["name"] == "gol_rpc_server_requests_total":
                updates = sum(
                    s["value"]
                    for s in fam["series"]
                    if Methods.WORKER_UPDATE in tuple(s["labels"])
                )
        assert updates > 0, "restarted worker never served Update again"
    finally:
        _kill_all([*workers, broker, restarted])


@pytest.mark.slow
def test_broker_kill9_resume_is_bit_identical(tmp_path):
    """Acceptance (c): kill -9 the broker mid-run; restart it with
    -resume pointing at its -auto-checkpoint; the reattached run's final
    board is bit-identical to an uninterrupted run (the oracle)."""
    turns = 4000
    ckpt = tmp_path / "bk.npz"
    workers = [
        _spawn("gol_distributed_final_tpu.rpc.worker", "-port", "0")
        for _ in range(2)
    ]
    broker = broker2 = None
    try:
        ports = [_wait_listening(w) for w in workers]
        addrs = ",".join(f"127.0.0.1:{p}" for p in ports)
        broker = _spawn(
            "gol_distributed_final_tpu.rpc.broker",
            "-port", "0", "-backend", "workers", "-workers", addrs,
            "-auto-checkpoint", "0.05", str(ckpt),
        )
        address = f"127.0.0.1:{_wait_listening(broker)}"
        p = Params(turns=turns, threads=2, image_width=64, image_height=64)
        board = _read_board_64()
        remote = RemoteBroker(address, timeout=30.0)
        outcome = {}

        def runner():
            try:
                outcome["r"] = remote.run(p, board)
            except Exception as e:
                outcome["e"] = e

        t = threading.Thread(target=runner)
        t.start()
        _poll_turn(remote, 500)
        deadline = time.monotonic() + 10
        while not ckpt.exists():
            assert time.monotonic() < deadline, "auto-checkpoint never wrote"
            time.sleep(0.02)
        broker.kill()  # SIGKILL: no finallys, no flushes
        broker.wait()
        t.join(timeout=30)
        assert not t.is_alive()
        remote.close()
        assert "e" in outcome, "Run should have failed with the broker"

        broker2 = _spawn(
            "gol_distributed_final_tpu.rpc.broker",
            "-port", "0", "-backend", "workers", "-workers", addrs,
            "-auto-checkpoint", "0.05", str(ckpt),
            "-resume", str(ckpt),
        )
        address2 = f"127.0.0.1:{_wait_listening(broker2)}"
        remote2 = RemoteBroker(address2, timeout=30.0)
        try:
            # the controller re-issues the SAME fresh Run; the broker
            # reattaches it at the checkpoint's turn via initial_turn
            r = remote2.run(p, board)
        finally:
            remote2.close()
        assert r.turns_completed == turns
        np.testing.assert_array_equal(r.world, _oracle_64(turns))
    finally:
        _kill_all([*workers, broker, broker2])
