"""Config-5-shaped streamed sparse big-board runs (VERDICT round-1 item 8).

A 4096^2 board (the reduced-size stand-in for 65536^2) seeded with an
R-pentomino: evolved through the XLA bitboard plane and streamed to/from
PGM in row blocks — the full byte board never exists. Correctness is
pinned against the numpy oracle evolved on the populated window (the
R-pentomino's 100-turn envelope is far inside a 512^2 window, so the
window evolution is exact).
"""

import numpy as np
import pytest

from gol_distributed_final_tpu.bigboard import (
    load_packed_from_pgm,
    r_pentomino,
    run_big_board,
    seed_packed,
    stream_packed_to_pgm,
)
from gol_distributed_final_tpu.io.sharded import read_shard
from gol_distributed_final_tpu.ops.bitpack import alive_count_packed
from gol_distributed_final_tpu.ops.pallas_stencil import fits_vmem

from oracle import vector_step

SIZE = 4096
TURNS = 100
WIN = 512  # window comfortably containing the 100-turn envelope
W0 = SIZE // 2 - WIN // 2


def oracle_window(turns=TURNS):
    """The centre window evolved exactly (the envelope never reaches its
    edge, so no wrap effects). Shared logic in helpers.oracle_window."""
    from helpers import oracle_window as _ow

    return _ow(SIZE, turns, WIN)


def test_big_board_streamed_run_matches_oracle(tmp_path):
    out = tmp_path / "big.pgm"
    alive = run_big_board(
        SIZE, TURNS, out, cells=r_pentomino(SIZE), row_block=512
    )
    window = oracle_window()
    assert alive == int(np.count_nonzero(window))

    # the populated window read back from disk is exactly the oracle's
    got = read_shard(out, W0, W0 + WIN)[:, W0 : W0 + WIN]
    np.testing.assert_array_equal(got, window)

    # far rows are untouched dead space — read a distant block
    far = read_shard(out, 0, 256)
    assert not far.any()


def test_big_board_takes_the_xla_path():
    """4096^2 packed must be past the VMEM-kernel gate: the run above
    exercises the XLA bitboard, not the (test-mode interpreted) kernel."""
    state = seed_packed(SIZE, r_pentomino(SIZE))
    assert not fits_vmem(state.shape, itemsize=4)


def test_streamed_pgm_roundtrip(tmp_path):
    """PGM -> packed -> PGM through row-block streaming is lossless."""
    path = tmp_path / "seed.pgm"
    state = seed_packed(SIZE, r_pentomino(SIZE))
    stream_packed_to_pgm(path, state, row_block=512)
    loaded = load_packed_from_pgm(path, row_block=512)
    np.testing.assert_array_equal(np.asarray(loaded), np.asarray(state))
    assert alive_count_packed(loaded) == 5


def test_resume_from_streamed_pgm(tmp_path):
    """Evolve 60 turns, stream out, load, evolve 40 more: identical to an
    uninterrupted 100-turn run — checkpoint/resume at config-5 scale."""
    mid = tmp_path / "mid.pgm"
    run_big_board(SIZE, 60, mid, cells=r_pentomino(SIZE), row_block=512)
    final = tmp_path / "final.pgm"
    alive = run_big_board(
        SIZE, 40, final, in_path=mid, row_block=512
    )
    window = oracle_window(100)
    assert alive == int(np.count_nonzero(window))
    got = read_shard(final, W0, W0 + WIN)[:, W0 : W0 + WIN]
    np.testing.assert_array_equal(got, window)


def test_seed_packed_rejects_out_of_range():
    with pytest.raises(ValueError, match="outside"):
        seed_packed(64, [(64, 0)])


@pytest.mark.parametrize("word_axis", [0, 1])
def test_seed_packed_row_range(word_axis):
    """Per-rank seeding (ADVICE r4): building only the rows of a range
    yields exactly the matching slice of the full-board seeding; cells
    outside the range are skipped, cells outside the BOARD still raise."""
    cells = [(3, 5), (50, 37), (63, 32), (0, 63)]
    full = np.asarray(seed_packed(64, cells, word_axis))
    local = np.asarray(
        seed_packed(64, cells, word_axis, row_range=(32, 64))
    )
    wlo, whi = (1, 2) if word_axis == 0 else (32, 64)
    np.testing.assert_array_equal(local, full[wlo:whi])
    with pytest.raises(ValueError, match="outside"):
        seed_packed(64, [(0, 64)], word_axis, row_range=(0, 32))
    if word_axis == 0:
        with pytest.raises(ValueError, match="word-aligned"):
            seed_packed(64, cells, 0, row_range=(8, 40))


def test_cli_smoke(tmp_path):
    from gol_distributed_final_tpu import bigboard

    out = tmp_path / "cli.pgm"
    rc = bigboard.main(["-size", "2048", "-turns", "20", "-out", str(out)])
    assert rc == 0
    window = np.zeros((256, 256), np.uint8)
    for x, y in r_pentomino(2048):
        window[y - 896, x - 896] = 255
    for _ in range(20):
        window = vector_step(window)
    got = read_shard(out, 896, 896 + 256)[:, 896 : 896 + 256]
    np.testing.assert_array_equal(got, window)


def test_decode_window_matches_oracle():
    """A window decoded straight from the packed board — no full unpack —
    equals the oracle evolution, including unaligned window origins."""
    from gol_distributed_final_tpu.bigboard import decode_window
    from gol_distributed_final_tpu.ops.plane import BitPlane

    state = seed_packed(SIZE, r_pentomino(SIZE))
    state = BitPlane().step_n(state, TURNS)
    window = oracle_window()
    got = decode_window(state, W0, W0, WIN, WIN)
    np.testing.assert_array_equal(got, window)
    # word-unaligned origin: offset by 5 rows, 3 cols into the window
    got_off = decode_window(state, W0 + 5, W0 + 3, WIN - 5, WIN - 3)
    np.testing.assert_array_equal(got_off, window[5:, 3:])


def test_decode_window_bounds_and_axis1():
    from gol_distributed_final_tpu.bigboard import decode_window
    from gol_distributed_final_tpu.ops import bitpack

    rng = np.random.default_rng(3)
    board = np.where(rng.random((128, 128)) < 0.3, 255, 0).astype(np.uint8)
    for axis in (0, 1):
        packed = bitpack.pack(board, axis)
        got = decode_window(packed, 17, 33, 50, 60, word_axis=axis)
        np.testing.assert_array_equal(got, board[17:67, 33:93])
    with pytest.raises(ValueError, match="outside"):
        decode_window(bitpack.pack(board, 0), 100, 0, 50, 10)
    with pytest.raises(ValueError, match="positive"):
        decode_window(bitpack.pack(board, 0), 100, 0, -50, 10)


def test_alive_cells_packed_sparse_extraction():
    """Sparse O(populated-rows) cell extraction matches the byte-plane
    reduction — same cells, same row-major order — for both packings,
    plus the empty board."""
    from gol_distributed_final_tpu.ops import bitpack
    from gol_distributed_final_tpu.ops.reduce import alive_cells

    rng = np.random.default_rng(9)
    board = np.where(rng.random((128, 160)) < 0.1, 255, 0).astype(np.uint8)
    want = alive_cells(board)
    for axis in (0, 1):
        got = bitpack.alive_cells_packed(bitpack.pack(board, axis), axis)
        assert got == want
    assert bitpack.alive_cells_packed(bitpack.pack(np.zeros((64, 64), np.uint8), 0)) == []


def test_engine_driven_big_board_with_control_plane(tmp_path):
    """The config-5 control story: the engine evolves a packed board it
    never decodes (final_world=False), the count-only Retrieve works
    mid-run, the final cells come from sparse extraction, and the
    streamed PGM matches the oracle window."""
    import threading

    from gol_distributed_final_tpu.engine import Engine
    from gol_distributed_final_tpu.engine.engine import EngineConfig
    from gol_distributed_final_tpu.bigboard import run_big_board
    from gol_distributed_final_tpu.io.sharded import read_shard

    eng = Engine(EngineConfig(final_world=False, min_chunk=4, max_chunk=16))
    counts = []
    stop = threading.Event()

    def ticker():
        while not stop.is_set():
            counts.append(eng.retrieve(include_world=False).alive_count)

    t = threading.Thread(target=ticker)
    t.start()
    out = tmp_path / "eng.pgm"
    alive = run_big_board(
        SIZE, TURNS, out, cells=r_pentomino(SIZE), row_block=512, engine=eng
    )
    stop.set()
    t.join(30)
    window = oracle_window()
    assert alive == int(np.count_nonzero(window))
    got = read_shard(out, W0, W0 + WIN)[:, W0 : W0 + WIN]
    np.testing.assert_array_equal(got, window)
    assert counts, "count-only retrieve must work mid-run"


def test_initial_state_requires_plane_and_no_world():
    from gol_distributed_final_tpu.engine import Engine
    from gol_distributed_final_tpu.engine.engine import EngineConfig
    from gol_distributed_final_tpu.ops import bitpack
    from gol_distributed_final_tpu.ops.plane import BitPlane
    from gol_distributed_final_tpu.params import Params

    state = bitpack.pack(np.zeros((64, 64), np.uint8), 0)
    eng = Engine(EngineConfig())
    p = Params(turns=1, image_width=64, image_height=64)
    with pytest.raises(ValueError, match="explicit plane"):
        eng.run(p, None, initial_state=state)
    with pytest.raises(ValueError, match="world=None"):
        eng.run(p, np.zeros((64, 64), np.uint8), plane=BitPlane(), initial_state=state)


def test_final_alive_from_sparse_extraction_matches_golden():
    """final_world=False must produce the same FinalTurnComplete payload
    as the decoding path, cells included."""
    from gol_distributed_final_tpu.engine import Engine
    from gol_distributed_final_tpu.engine.engine import EngineConfig
    from gol_distributed_final_tpu.io.pgm import read_pgm
    from gol_distributed_final_tpu.ops import bitpack
    from gol_distributed_final_tpu.ops.plane import BitPlane
    from gol_distributed_final_tpu.params import Params
    from helpers import REPO_ROOT

    board = read_pgm(REPO_ROOT / "images" / "64x64.pgm")
    p = Params(turns=100, image_width=64, image_height=64)
    ref = Engine(EngineConfig()).run(p, board)
    res = Engine(EngineConfig(final_world=False)).run(
        p, None, plane=BitPlane(), initial_state=bitpack.pack(board, 0)
    )
    assert res.world is None
    assert res.alive == ref.alive


def test_big_session_full_event_surface(tmp_path):
    """The reference session contract at big-board scale: AliveCellsCount
    ticks, 's' snapshot mid-run, pause/resume StateChanges with the
    turn-minus-one resume quirk, and the exact closing sequence — all on
    a board that never exists as bytes."""
    import queue

    from gol_distributed_final_tpu.bigboard import big_session
    from gol_distributed_final_tpu.engine.controller import CLOSED
    from gol_distributed_final_tpu.events import (
        AliveCellsCount,
        FinalTurnComplete,
        ImageOutputComplete,
        Quitting,
        State,
        StateChange,
    )

    events: "queue.Queue" = queue.Queue()
    keys: "queue.Queue" = queue.Queue()
    keys.put("s")
    keys.put("p")
    keys.put("p")
    res = big_session(
        SIZE,
        TURNS,
        cells=r_pentomino(SIZE),
        row_block=512,
        events=events,
        keypresses=keys,
        tick_seconds=0.1,
        out_dir=tmp_path,
    )
    seq = []
    while True:
        ev = events.get(timeout=60)
        if ev is CLOSED:
            break
        seq.append(ev)
    window = oracle_window()
    final = [e for e in seq if isinstance(e, FinalTurnComplete)]
    assert len(final) == 1 and res.turns_completed == TURNS
    assert len(final[0].alive) == int(np.count_nonzero(window))
    assert any(isinstance(e, AliveCellsCount) for e in seq)
    states = [e.new_state for e in seq if isinstance(e, StateChange)]
    assert states[:2] == [State.PAUSED, State.EXECUTING]
    assert states[-1] is Quitting
    assert isinstance(seq[-2], ImageOutputComplete)
    # the streamed output PGM window matches the oracle
    got = read_shard(
        tmp_path / f"{SIZE}x{SIZE}x{TURNS}.pgm", W0, W0 + WIN
    )[:, W0 : W0 + WIN]
    np.testing.assert_array_equal(got, window)
    # the 's' snapshot wrote the same file mid-run (overwritten at end);
    # the run result's world never materialised
    assert res.world is None


def test_cli_session_smoke(tmp_path):
    """`python -m gol_distributed_final_tpu.bigboard -session`: events
    print in the reference's `Completed Turns <n> <event>` form and the
    streamed PGM lands under the -out directory."""
    import os
    import subprocess
    import sys

    from helpers import REPO_ROOT

    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=str(REPO_ROOT))
    r = subprocess.run(
        [sys.executable, "-m", "gol_distributed_final_tpu.bigboard",
         "-session", "-size", "2048", "-turns", "50",
         "-out", str(tmp_path / "x.pgm"), "-row-block", "512"],
        capture_output=True, text=True, timeout=300, env=env, cwd=tmp_path,
    )
    assert r.returncode == 0, r.stderr[-500:]
    assert "Quitting" in r.stdout and "alive " in r.stdout
    # -session honors the exact -out path, same as batch mode
    assert (tmp_path / "x.pgm").exists()
