"""Fleet-collector tests (obs/fleet.py + friends): the parallel Status
fan-out contract, EXACT cross-host registry merge over live targets with
overlapping labelled counters/histograms, counter reset between sweeps
(target restart), version-skew exclusion (loud, never wrong), staleness
marking + the ``target-down`` page, the fleet doctor finding that names
a dead target with its scrape evidence, watch's zero-flag FLEET panel —
and the live acceptance drill: two subprocess brokers (one resident-wire
over two workers), SIGKILL one broker, and the whole fleet surface must
tell the truth about it within the staleness bound.
"""

import socket
import time

import pytest

from gol_distributed_final_tpu.obs import doctor as obs_doctor
from gol_distributed_final_tpu.obs import fleet as obs_fleet
from gol_distributed_final_tpu.obs import metrics as obs_metrics
from gol_distributed_final_tpu.obs.status import (
    fetch_many,
    fetch_status,
    scalar_value,
    series_map,
)
from gol_distributed_final_tpu.rpc.protocol import Methods, Response
from gol_distributed_final_tpu.rpc.server import RpcServer

from test_rpc import _spawn, _wait_listening


@pytest.fixture
def live_metrics():
    """Enable the process-global registry for one test, zeroed before and
    disabled+zeroed after (the test_obs.py posture)."""
    reg = obs_metrics.registry()
    reg.reset()
    obs_metrics.enable()
    yield reg
    obs_metrics.enable(False)
    reg.reset()


class _StubTarget:
    """A live loopback Status server with a fully scripted payload — the
    per-process registry under test's total control (distinct synthetic
    'hosts', unlike in-process brokers that share one global registry)."""

    def __init__(self, payload):
        self.payload = payload
        self.requests = []
        self.server = RpcServer(port=0)

        def _status(req):
            self.requests.append(req)
            return Response(status=self.payload)

        self.server.register(Methods.STATUS, _status)
        self.server.register(Methods.WORKER_STATUS, _status)
        self.server.serve_background()
        self.address = f"127.0.0.1:{self.server.port}"

    def stop(self):
        """Stop AND verify the port refuses. RpcServer.stop() closes the
        listener fd, but a thread already blocked in accept() holds the
        open file description until its syscall returns — so the port can
        keep accepting. One kick connection releases it; poll until the
        OS actually refuses (these tests need dead to MEAN dead)."""
        self.server.stop()
        deadline = time.time() + 10.0
        while time.time() < deadline:
            try:
                kick = socket.create_connection(
                    ("127.0.0.1", self.server.port), timeout=1.0)
                kick.close()
                time.sleep(0.01)
            except OSError:
                return
        raise RuntimeError("stub port still accepting after stop()")


def _snap(counters=(), hist=None, edges=(0.1, 1.0)):
    """A synthetic per-process registry snapshot: one labelled counter
    family and (optionally) one fixed-edge histogram family."""
    fams = []
    if counters:
        fams.append({
            "name": "t_requests_total", "type": "counter", "help": "t",
            "labelnames": ["code"],
            "series": [
                {"labels": list(labels), "value": value}
                for labels, value in counters
            ],
        })
    if hist is not None:
        fams.append({
            "name": "t_latency_seconds", "type": "histogram", "help": "t",
            "labelnames": [], "le": list(edges),
            "series": [{
                "labels": [], "buckets": list(hist),
                "sum": float(sum(hist)), "count": float(sum(hist)),
            }],
        })
    return {"schema": "gol-metrics/1", "families": fams}


def _dead_address() -> str:
    """A loopback port with NO listener: bound once to claim a fresh
    ephemeral port, then fully closed before anyone connects."""
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return f"127.0.0.1:{port}"


def _payload(snap, pid=4242, **extra):
    p = {"schema": "gol-status/1", "pid": pid, "time_unix": time.time(),
         "role": "worker", "metrics_enabled": True, "metrics": snap}
    p.update(extra)
    return p


# -- fetch_many contract ------------------------------------------------------


def test_fetch_many_exactly_one_of_payload_or_error(live_metrics):
    """Every target gets a (payload, fetched_at, error) triple with
    exactly one of payload/error set — a dead target is DATA."""
    stub = _StubTarget(_payload(_snap(counters=((("a",), 1.0),))))
    dead_addr = _dead_address()
    try:
        results = fetch_many(
            [{"address": f"tcp://{stub.address}", "worker": True},
             {"address": dead_addr, "worker": True}],
            timeout=5.0,
        )
        assert set(results) == {stub.address, dead_addr}
        payload, fetched_at, error = results[stub.address]
        assert error is None and isinstance(payload, dict)
        assert isinstance(fetched_at, float)
        payload, fetched_at, error = results[dead_addr]
        assert payload is None and isinstance(error, str) and error
        assert isinstance(fetched_at, float)
    finally:
        stub.stop()


# -- exact merge over live targets -------------------------------------------


def test_merge_is_exact_over_overlapping_labelled_series(live_metrics):
    """Three live 'hosts' with overlapping labelled counters and a shared
    fixed-edge histogram: every merged counter equals the ARITHMETIC SUM
    of the per-process values, every histogram bucket the per-bucket sum
    — bit-exact, the PR 1 merge contract at fleet scale."""
    stubs = [
        _StubTarget(_payload(_snap(
            counters=((("ok",), 3.0), (("err",), 1.0)),
            hist=[1, 2, 3],
        ), pid=100 + i))
        for i in range(2)
    ]
    stubs.append(_StubTarget(_payload(_snap(
        counters=((("ok",), 10.0), (("timeout",), 7.0)),
        hist=[5, 0, 1],
    ), pid=102)))
    collector = obs_fleet.FleetCollector(
        [], extra_workers=[s.address for s in stubs], interval=0.2,
        timeout=5.0,
    )
    try:
        fleet = collector.sweep()
        assert fleet["merge_excluded"] == {}
        merged = collector.status_payload()["metrics"]
        req = series_map(merged, "t_requests_total")
        assert req[("ok",)]["value"] == 3.0 + 3.0 + 10.0
        assert req[("err",)]["value"] == 1.0 + 1.0
        assert req[("timeout",)]["value"] == 7.0
        lat = series_map(merged, "t_latency_seconds")[()]
        assert lat["buckets"] == [1 + 1 + 5, 2 + 2 + 0, 3 + 3 + 1]
        assert lat["count"] == 6.0 + 6.0 + 6.0
    finally:
        for s in stubs:
            s.stop()


def test_counter_reset_between_sweeps_stays_exact(live_metrics):
    """Only the CURRENT sweep's snapshots are merged: a target restart
    (counters reset, new pid) between polls yields merged totals exactly
    equal to the restarted process's own snapshot — never a stale sum —
    and the restart resets the echoed incremental cursors."""
    stub = _StubTarget(_payload(
        _snap(counters=((("ok",), 100.0),)), pid=1111,
        timeline={"seq": 7, "samples": []},
    ))
    collector = obs_fleet.FleetCollector(
        [], extra_workers=[stub.address], interval=0.2, timeout=5.0)
    try:
        collector.sweep()
        assert stub.requests[-1].timeline_since == 0
        collector.sweep()
        # the cursor echoed back is the last seq the collector received
        assert stub.requests[-1].timeline_since == 7
        # restart: new pid, counters reset, seq numbering begins again
        stub.payload = _payload(
            _snap(counters=((("ok",), 5.0),)), pid=2222,
            timeline={"seq": 2, "samples": []},
        )
        collector.sweep()
        merged = collector.status_payload()["metrics"]
        assert series_map(merged, "t_requests_total")[("ok",)]["value"] == 5.0
        collector.sweep()
        # the pid change dropped the pre-restart cursor (7): the echo now
        # follows the restarted numbering, not the dead process's
        assert stub.requests[-1].timeline_since == 2
        (row,) = collector.status_payload()["fleet"]["targets"]
        assert row["cursors"]["timeline_since"] == 2
    finally:
        stub.stop()


def test_version_skew_is_excluded_loudly_never_wrongly(live_metrics):
    """A target missing the metrics snapshot (old server) and a target
    whose histogram edges mismatch (skewed build) are both EXCLUDED from
    the merge by name with a reason and counted in
    gol_fleet_merge_failures_total — while the merged totals stay exactly
    the sum of the included snapshots."""
    good = _StubTarget(_payload(
        _snap(counters=((("ok",), 3.0),), hist=[1, 2, 3], edges=(0.1, 1.0)),
        pid=1))
    old = _StubTarget({"schema": "gol-status/1", "pid": 2,
                       "role": "worker"})  # no metrics at all
    skewed = _StubTarget(_payload(
        _snap(counters=((("ok",), 50.0),), hist=[9, 9, 9], edges=(0.5, 5.0)),
        pid=3))
    collector = obs_fleet.FleetCollector(
        [], extra_workers=[good.address, old.address, skewed.address],
        interval=0.2, timeout=5.0)
    try:
        fleet = collector.sweep()
        excluded = fleet["merge_excluded"]
        assert old.address in excluded and "skew" in excluded[old.address]
        # the merge folds in sorted-address order: ONE of the two
        # edge-mismatched snapshots lands, the other is refused — which
        # one depends on the ephemeral ports, but exactly one is out
        edge_excluded = set(excluded) - {old.address}
        assert len(edge_excluded) == 1
        (loser,) = edge_excluded
        assert "mismatch" in excluded[loser]
        winner = {good.address: 3.0, skewed.address: 50.0}[
            ({good.address, skewed.address} - {loser}).pop()]
        merged = collector.status_payload()["metrics"]
        assert series_map(merged, "t_requests_total")[("ok",)]["value"] == winner
        failures = scalar_value(merged, "gol_fleet_merge_failures_total")
        assert failures == 2.0
        # the skew degrades LOUDLY in every consumer: watch renders the
        # exclusions, it never crashes on the thin payload
        from gol_distributed_final_tpu.obs.watch import render_status

        text = render_status("fleet", collector.status_payload())
        assert "EXCLUDED" in text
    finally:
        good.stop()
        old.stop()
        skewed.stop()


# -- staleness + the target-down page ----------------------------------------


def test_dead_target_goes_stale_and_target_down_fires(live_metrics):
    """A target that stops answering is marked failing, then STALE once
    its last-success age passes STALE_INTERVALS sweeps; the
    gol_fleet_targets_down gauge counts it and the target-down page
    fires over the merged timeline."""
    stub = _StubTarget(_payload(_snap(counters=((("ok",), 1.0),))))
    collector = obs_fleet.FleetCollector(
        [], extra_workers=[stub.address], interval=1.0, timeout=2.0)
    (row,) = collector.sweep()["targets"]
    assert row["state"] == "ok"
    stub.stop()
    (row,) = collector.sweep(wall=time.time())["targets"]
    assert row["state"] == "failing"
    assert row["consecutive_failures"] == 1 and row["error"]
    # past the bound (3 x 1.0 s interval): STALE, gauge up, page firing
    later = time.time() + 3.0 * collector.interval + 2.0
    (row,) = collector.sweep(now=later, wall=later)["targets"]
    assert row["state"] == "stale"
    payload = collector.status_payload()
    assert scalar_value(payload["metrics"], "gol_fleet_targets_down") == 1.0
    alerts = {a["rule"]: a for a in payload["alerts"]}
    assert alerts["target-down"]["state"] == "firing"
    assert alerts["target-down"]["severity"] == "page"


def test_fleet_doctor_names_dead_target_with_scrape_evidence(live_metrics):
    """The doctor's TOP finding on a fleet payload with a stale broker
    names the dead address and carries the scrape health as evidence —
    a dead broker is a first-class finding, not a timeout traceback."""
    stub = _StubTarget(_payload(_snap(counters=((("ok",), 1.0),)),
                                role="broker"))
    # a short REAL cadence: status_payload() judges staleness against the
    # real clock, so the bound (3 x 0.05 s) must pass in real time
    collector = obs_fleet.FleetCollector(
        [stub.address], interval=0.05, timeout=2.0)
    collector.sweep()
    stub.stop()
    deadline = time.time() + 30.0
    while time.time() < deadline:
        collector.sweep()
        payload = collector.status_payload()
        if payload["fleet"]["targets"][0]["state"] == "stale":
            break
        time.sleep(0.06)
    else:
        pytest.fail("target never went stale")
    findings = obs_doctor.diagnose({"fleet 127.0.0.1:9": payload})
    top = findings[0]
    assert top["severity"] == "page"
    assert stub.address in top["title"] and "DOWN" in top["title"]
    evidence = "\n".join(top.get("evidence", []))
    assert "consecutive failure" in evidence
    assert "last successful scrape" in evidence
    text = obs_doctor.render(findings, {"fleet 127.0.0.1:9": payload})
    assert stub.address in text


# -- watch through the collector ---------------------------------------------


def test_watch_renders_fleet_and_per_broker_panels_zero_flags(live_metrics):
    """Watch pointed at ONE address — the collector's — renders the
    FLEET panel plus a per-broker sub-panel, and the broker's workers
    are scraped by roster auto-discovery: zero manual -worker flags."""
    worker_stub = _StubTarget(_payload(
        _snap(counters=((("ok",), 2.0),)), pid=11))
    broker_stub = _StubTarget(_payload(
        _snap(counters=((("ok",), 1.0),)), pid=12, role="broker",
        workers=[{"address": worker_stub.address, "state": "READY",
                  "retry_in_s": None}],
    ))
    collector = obs_fleet.FleetCollector(
        [broker_stub.address], interval=0.2, timeout=5.0)
    server = None
    try:
        collector.sweep()  # scrapes the broker, learns its roster
        fleet = collector.sweep()  # scrapes the discovered worker too
        rows = {r["address"]: r for r in fleet["targets"]}
        assert rows[worker_stub.address]["worker"] is True
        assert rows[worker_stub.address]["via"] == broker_stub.address
        assert rows[worker_stub.address]["state"] == "ok"
        # merged = broker + the AUTO-DISCOVERED worker, exactly
        merged = collector.status_payload()["metrics"]
        assert series_map(merged, "t_requests_total")[("ok",)]["value"] == 3.0
        server = obs_fleet.serve(collector, port=0)
        from gol_distributed_final_tpu.obs.watch import Watcher

        frame, ok = Watcher(
            f"127.0.0.1:{server.port}", [], timeout=5.0).frame()
        assert ok
        assert "FLEET" in frame
        assert broker_stub.address in frame
        assert "via fleet" in frame
    finally:
        if server is not None:
            server.stop()
        broker_stub.stop()
        worker_stub.stop()


# -- the live acceptance drill (subprocess cluster; slow-marked) --------------


# the exactness family for the live drill: the resident run leaves it
# NONZERO on broker and workers alike (in-header frame crcs + halo
# attestations) and QUIESCENT afterwards — unlike the rpc request/byte
# counters, which every Status scrape itself moves
_DRILL_FAMILY = "gol_integrity_checks_total"


def _family_values(addr: str, worker: bool) -> dict:
    """{labels: value} of the drill family from one independent fetch."""
    p = fetch_status(addr, worker=worker, timeout=10.0)
    return {
        labels: s.get("value") or 0.0
        for labels, s in series_map(p.get("metrics") or {}, _DRILL_FAMILY).items()
    }


def _summed(maps) -> dict:
    out = {}
    for m in maps:
        for labels, v in m.items():
            out[labels] = out.get(labels, 0.0) + v
    return out


@pytest.mark.slow
def test_live_fleet_drill_sigkill_broker(live_metrics):
    """The acceptance drill, live: a collector over TWO subprocess
    brokers (one resident-wire over two subprocess workers), worker
    auto-discovery, exact 4-way merge — then SIGKILL one broker and
    within the staleness bound the fleet Status marks it stale, the
    target-down page fires, the fleet doctor's TOP finding names the
    dead broker with scrape evidence, and the merged counters stay
    exactly equal to the sum of the SURVIVING targets' snapshots."""
    import numpy as np

    from gol_distributed_final_tpu.rpc.client import RpcClient
    from gol_distributed_final_tpu.rpc.protocol import Request

    workers = [
        _spawn("gol_distributed_final_tpu.rpc.worker", "-port", "0",
               "-metrics")
        for _ in range(2)
    ]
    broker_a = broker_b = fleet_server = None
    try:
        wports = [_wait_listening(w) for w in workers]
        waddrs = [f"127.0.0.1:{p}" for p in wports]
        broker_a = _spawn(
            "gol_distributed_final_tpu.rpc.broker",
            "-port", "0", "-backend", "workers", "-metrics",
            "-workers", ",".join(waddrs),
            "-wire", "resident", "-halo-depth", "8",
        )
        broker_b = _spawn(
            "gol_distributed_final_tpu.rpc.broker", "-port", "0", "-metrics")
        addr_a = f"127.0.0.1:{_wait_listening(broker_a)}"
        addr_b = f"127.0.0.1:{_wait_listening(broker_b)}"
        # real work through the resident wire, so engine-turn counters
        # are nonzero and STATIC afterwards (exactness needs quiescence)
        rng = np.random.default_rng(7)
        board = np.where(
            rng.random((64, 64)) < 0.3, 255, 0).astype(np.uint8)
        client = RpcClient(addr_a)
        try:
            client.call(
                Methods.BROKER_RUN,
                Request(world=board, turns=8, image_width=64,
                        image_height=64, threads=2),
                timeout=180.0,
            )
        finally:
            client.close()

        collector = obs_fleet.FleetCollector(
            [addr_a, addr_b], interval=0.2, timeout=10.0)
        collector.sweep()  # brokers + roster discovery
        fleet = collector.sweep()  # + the discovered workers
        rows = {r["address"]: r for r in fleet["targets"]}
        assert set(rows) == {addr_a, addr_b, *waddrs}
        for waddr in waddrs:
            assert rows[waddr]["worker"] is True
            assert rows[waddr]["via"] == addr_a  # auto-discovered
            assert rows[waddr]["state"] == "ok"
        assert fleet["merge_excluded"] == {}
        # exact 4-way merge: every labelled series of the drill family
        # equals the arithmetic sum of the four per-process snapshots,
        # each fetched independently of the collector
        want = _summed([
            _family_values(addr_a, False), _family_values(addr_b, False),
            *(_family_values(w, True) for w in waddrs),
        ])
        assert sum(want.values()) > 0
        merged = collector.status_payload()["metrics"]
        got = {
            labels: s.get("value") or 0.0
            for labels, s in series_map(merged, _DRILL_FAMILY).items()
        }
        assert got == want

        broker_b.kill()  # SIGKILL: no shutdown path, no goodbyes
        broker_b.wait()
        deadline = time.time() + 30.0
        while time.time() < deadline:
            collector.sweep()
            payload = collector.status_payload()
            row_b = {
                r["address"]: r for r in payload["fleet"]["targets"]
            }[addr_b]
            if row_b["state"] == "stale":
                break
            time.sleep(0.2)
        else:
            pytest.fail("killed broker never went stale")
        assert row_b["consecutive_failures"] >= 1
        assert row_b["error"]
        # the dead broker left the merge within one sweep: merged totals
        # are exactly the sum of the SURVIVING targets' own snapshots
        assert addr_b not in payload["fleet"]["broker_status"]
        survivors = _summed([
            _family_values(addr_a, False),
            *(_family_values(w, True) for w in waddrs),
        ])
        got = {
            labels: s.get("value") or 0.0
            for labels, s in series_map(
                payload["metrics"], _DRILL_FAMILY).items()
        }
        assert got == survivors
        assert scalar_value(
            payload["metrics"], "gol_fleet_targets_down") == 1.0
        alerts = {a["rule"]: a for a in payload["alerts"]}
        assert alerts["target-down"]["state"] == "firing"

        # every consumer at ONE address: the fleet doctor's top finding
        # names the dead broker with its scrape evidence; watch renders
        # FLEET + the surviving broker's sub-panel, zero -worker flags
        fleet_server = obs_fleet.serve(collector, port=0)
        fleet_addr = f"127.0.0.1:{fleet_server.port}"
        statuses = obs_doctor.collect(fleet_addr, [], timeout=10.0)
        findings = obs_doctor.diagnose(statuses)
        top = findings[0]
        assert top["severity"] == "page"
        assert addr_b in top["title"] and "DOWN" in top["title"]
        assert any("consecutive failure" in e
                   for e in top.get("evidence", []))
        from gol_distributed_final_tpu.obs.watch import Watcher

        frame, ok = Watcher(fleet_addr, [], timeout=10.0).frame()
        assert ok
        assert "FLEET" in frame
        assert addr_a in frame and addr_b in frame
        assert "via fleet" in frame
    finally:
        if fleet_server is not None:
            fleet_server.stop()
        for p in (*workers, broker_a, broker_b):
            if p is not None and p.poll() is None:
                p.kill()
            if p is not None:
                p.wait()
