"""Grid-tiled pallas bitboard kernel: interpret-mode parity on CPU.

The real-TPU behavior (4.5x over the XLA fallback at 16384^2, exact
parity, the oracle-validated R-pentomino gate) is exercised by bench.py on
hardware; here the same kernel runs in pallas interpret mode at small
sizes, pinned against the independent XLA bitboard step and the numpy
oracle — including blocks that wrap the torus through the modulo index
maps.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from gol_distributed_final_tpu.models import HIGHLIFE
from gol_distributed_final_tpu.ops import bitpack
from gol_distributed_final_tpu.ops.pallas_tiled import (
    _EXT_BYTES_TARGET,
    _ext_shape,
    _pick_blocks,
    can_tile,
    tiled_bit_step_n_fn,
)

from oracle import vector_step


def random_board(h, w, seed=0, density=0.3):
    rng = np.random.default_rng(seed)
    return np.where(rng.random((h, w)) < density, 255, 0).astype(np.uint8)


def test_can_tile_and_block_choice():
    assert can_tile((512, 16384))  # 16384^2 packed
    assert can_tile((16, 512))  # 512^2 packed: two 8-row blocks
    assert not can_tile((8, 256))  # single block: nothing to tile
    assert not can_tile((12, 384))  # not sublane-divisible
    assert not can_tile((16, 192))  # width not lane(128)-divisible
    for rows, width in [(512, 16384), (128, 4096), (2048, 65536), (16, 512)]:
        pb, wb = _pick_blocks(rows, width)
        assert pb % 8 == 0 and rows % pb == 0
        assert wb % 128 == 0 and width % wb == 0
        er, ec = _ext_shape(pb, wb, width)
        assert er * ec * 4 <= _EXT_BYTES_TARGET
    # the ADVICE round-2 failure shape: 65536^2 packed rows are 256 KiB
    # wide, so the block MUST split the lane axis to bound VMEM
    pb, wb = _pick_blocks(2048, 65536)
    assert wb < 65536
    # moderate widths stay full-width: contiguous HBM reads, no column
    # halos (the 2-D split measured 3x slower at 4096^2)
    assert _pick_blocks(128, 4096)[1] == 4096
    assert _pick_blocks(16, 512)[1] == 512


def test_invalid_block_shape_raises():
    packed = bitpack.pack_device(jnp.asarray(random_board(512, 256)), 0)
    with pytest.raises(ValueError, match="block_rows"):
        tiled_bit_step_n_fn(interpret=True, block_rows=12)(packed, 1)
    with pytest.raises(ValueError, match="block_rows"):
        # multiple of 8 but does not divide the 16 packed rows: would
        # silently evolve a truncated board if accepted
        tiled_bit_step_n_fn(interpret=True, block_rows=48)(packed, 1)
    with pytest.raises(ValueError, match="block_cols"):
        tiled_bit_step_n_fn(interpret=True, block_cols=192)(packed, 1)


def test_tiled_2d_grid_matches_xla_bitboard():
    """Blocks split along BOTH axes (grid 2x2, forced small blocks):
    column-halo and corner fetches must reproduce the XLA bitboard."""
    board = random_board(512, 256, seed=11)
    packed = bitpack.pack_device(jnp.asarray(board), 0)  # [16, 256]
    tiled = tiled_bit_step_n_fn(interpret=True, block_rows=8, block_cols=128)
    got = tiled(packed, 5)
    want = bitpack.bit_step_n(packed, 5, 0)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_tiled_glider_wraps_through_column_blocks():
    """A glider moving diagonally crosses every block-column boundary and
    both torus edges; 2-D modulo index maps must bring it home."""
    board = np.zeros((512, 256), np.uint8)
    for x, y in [(1, 0), (2, 1), (0, 2), (1, 2), (2, 2)]:
        board[y, x] = 255
    packed = bitpack.pack_device(jnp.asarray(board), 0)  # [16, 256], grid 2x2
    tiled = tiled_bit_step_n_fn(interpret=True, block_rows=8, block_cols=128)
    out = tiled(packed, 4 * 512)  # H down + H right; H % W == 0 => home
    np.testing.assert_array_equal(
        np.asarray(bitpack.unpack_device(out, 0)), board
    )


@pytest.mark.parametrize("turns", [1, 7])
def test_tiled_matches_xla_bitboard(turns):
    board = random_board(512, 256, seed=3)
    packed = bitpack.pack_device(jnp.asarray(board), 0)  # [16, 256], grid=2
    assert can_tile(packed.shape)
    tiled = tiled_bit_step_n_fn(interpret=True)
    got = tiled(packed, turns)
    want = bitpack.bit_step_n(packed, turns, 0)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_tiled_glider_wraps_through_block_boundaries():
    """A glider crossing every word-row block boundary AND the torus edge
    (the modulo index maps) returns home exactly."""
    board = np.zeros((768, 256), np.uint8)  # packed [24, 256], 3 blocks
    for x, y in [(1, 0), (2, 1), (0, 2), (1, 2), (2, 2)]:
        board[y, x] = 255
    packed = bitpack.pack_device(jnp.asarray(board), 0)
    tiled = tiled_bit_step_n_fn(interpret=True, block_rows=8)
    out = tiled(packed, 4 * 768)  # full vertical wrap
    np.testing.assert_array_equal(
        np.asarray(bitpack.unpack_device(out, 0)), board
    )


def test_tiled_oracle_and_rule():
    board = random_board(512, 128, seed=9)
    packed = bitpack.pack_device(jnp.asarray(board), 0)
    got = np.asarray(
        bitpack.unpack_device(
            tiled_bit_step_n_fn(interpret=True, rule=HIGHLIFE)(packed, 3), 0
        )
    )
    want = board
    for _ in range(3):
        want = vector_step(want, birth=(3, 6), survive=(2, 3))
    np.testing.assert_array_equal(got, want)


def test_bitplane_routes_large_boards_to_tiled_on_tpu():
    """The plane's size routing: VMEM kernel under the gate, tiled beyond
    it on TPU, XLA bitboard in interpret mode (CPU tests)."""
    from gol_distributed_final_tpu.ops.pallas_stencil import fits_vmem
    from gol_distributed_final_tpu.ops.plane import BitPlane

    import unittest.mock

    plane = BitPlane()
    assert plane.interpret  # CPU test env
    big = jnp.zeros((512, 2048), jnp.int32)  # past the gate, tileable
    assert not fits_vmem(big.shape, itemsize=4) and can_tile(big.shape)
    # interpret mode must NOT take the tiled path (it would crawl): the
    # XLA bitboard step must handle gate-exceeding boards here
    with unittest.mock.patch(
        "gol_distributed_final_tpu.ops.pallas_tiled.tiled_bit_step_n_fn",
        side_effect=AssertionError("interpret mode must not tile"),
    ):
        out = plane.step_n(big, 1)
    assert out.shape == big.shape


@pytest.mark.parametrize("mode_blocks", [(None, None), (8, None), (8, 128)])
def test_tiled_word_axis1_matches_xla(mode_blocks):
    """Column packing ([H, W/32]) through BOTH regimes: the halo geometry
    is packing-agnostic (output word (i,j) reads words (i+-1,j+-1)), so
    the same kernels must be bit-exact under word_axis=1 — the layout
    that keeps packed rows narrow on very wide boards. (8, None) forces
    a 16-block rows grid so cross-block row halos are exercised; the
    auto plan degenerates to a single block at this size."""
    br, bc = mode_blocks
    board = random_board(128, 8192, seed=13)
    packed = bitpack.pack_device(jnp.asarray(board), 1)  # [128, 256]
    tiled = tiled_bit_step_n_fn(
        interpret=True, word_axis=1, block_rows=br, block_cols=bc
    )
    got = tiled(packed, 5)
    want = bitpack.bit_step_n(packed, 5, 1)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    oracle = board
    for _ in range(5):
        oracle = vector_step(oracle)
    np.testing.assert_array_equal(
        np.asarray(bitpack.unpack_device(got, 1)), oracle
    )
