"""Test harness configuration.

Forces JAX onto the CPU backend with 8 virtual devices BEFORE jax is imported
anywhere, so the multi-device sharding paths (parallel/halo.py) are exercised
on a virtual mesh exactly as the driver's dryrun does. Real-TPU behavior is
covered by bench.py, not the test suite.
"""

import os
import pathlib
import sys

# force-override: the ambient environment pins JAX_PLATFORMS=axon (the real
# TPU tunnel) and a sitecustomize module imports jax at interpreter start,
# so plain env vars are too late — go through jax.config, which works as
# long as no devices have been queried yet
import re

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = re.sub(
    r"--xla_force_host_platform_device_count=\d+",
    "",
    os.environ.get("XLA_FLAGS", ""),
)
os.environ["XLA_FLAGS"] = (
    _flags + " --xla_force_host_platform_device_count=8"
).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

assert jax.devices()[0].platform == "cpu", (
    "tests must run on the virtual CPU mesh; jax devices were already "
    f"initialised on {jax.devices()[0].platform} before conftest ran"
)
assert len(jax.devices()) == 8, "expected 8 virtual CPU devices"

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def repo_root() -> pathlib.Path:
    return REPO_ROOT


@pytest.fixture(scope="session")
def images_dir(repo_root) -> pathlib.Path:
    return repo_root / "images"


@pytest.fixture(scope="session")
def check_dir(repo_root) -> pathlib.Path:
    return repo_root / "check"


