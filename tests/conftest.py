"""Test harness configuration.

Forces JAX onto the CPU backend with 8 virtual devices BEFORE jax is imported
anywhere, so the multi-device sharding paths (parallel/halo.py) are exercised
on a virtual mesh exactly as the driver's dryrun does. Real-TPU behavior is
covered by bench.py, not the test suite.
"""

import pathlib
import sys

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

# force-override: the ambient environment pins JAX_PLATFORMS=axon (the real
# TPU tunnel) and a sitecustomize module imports jax at interpreter start,
# so plain env vars are too late — utils/cpumesh.py goes through jax.config,
# which works as long as no devices have been queried yet
from gol_distributed_final_tpu.utils.cpumesh import (  # noqa: E402
    force_virtual_cpu_devices,
)

assert force_virtual_cpu_devices(8), (
    "tests must run on the 8-device virtual CPU mesh; jax devices were "
    "already initialised on another platform before conftest ran"
)

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: chaos/fault-injection tests (live subprocess clusters, "
        "deliberate stalls) excluded from the tier-1 'not slow' gate; run "
        "them via scripts/check --chaos",
    )


@pytest.fixture(scope="session")
def repo_root() -> pathlib.Path:
    return REPO_ROOT


@pytest.fixture(scope="session")
def images_dir(repo_root) -> pathlib.Path:
    return repo_root / "images"


@pytest.fixture(scope="session")
def check_dir(repo_root) -> pathlib.Path:
    return repo_root / "check"


