"""Test harness configuration.

Forces JAX onto the CPU backend with 8 virtual devices BEFORE jax is imported
anywhere, so the multi-device sharding paths (parallel/halo.py) are exercised
on a virtual mesh exactly as the driver's dryrun does. Real-TPU behavior is
covered by bench.py, not the test suite.
"""

import os
import pathlib
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def repo_root() -> pathlib.Path:
    return REPO_ROOT


@pytest.fixture(scope="session")
def images_dir(repo_root) -> pathlib.Path:
    return repo_root / "images"


@pytest.fixture(scope="session")
def check_dir(repo_root) -> pathlib.Path:
    return repo_root / "check"


