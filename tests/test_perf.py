"""Performance-attribution suite (ISSUE 12): the roofline classifier
(obs/perf.py), the dispatch-wall decomposition, straggler/critical-path
attribution (obs/critical.py), the doctor's straggler finding + incident
bundle, the regress --latest selection fix, and the achieved-throughput
regression gate."""

import json
import os
import pathlib
import subprocess
import sys
import time

import numpy as np
import pytest

from gol_distributed_final_tpu.obs import critical as obs_critical
from gol_distributed_final_tpu.obs import device as obs_device
from gol_distributed_final_tpu.obs import metrics as obs_metrics
from gol_distributed_final_tpu.obs import perf as obs_perf
from gol_distributed_final_tpu.obs.status import series_map

from helpers import REPO_ROOT


@pytest.fixture
def live_metrics():
    obs_metrics.enable()
    yield obs_metrics
    obs_metrics.enable(False)


@pytest.fixture
def fresh_attribution():
    """Reset the tracker, the calibration cache, and the attribution
    switch around a test."""
    obs_critical.tracker().reset()
    obs_perf.reset_ceilings()
    obs_perf.set_attribution(True)
    yield
    obs_critical.tracker().reset()
    obs_perf.reset_ceilings()
    obs_perf.set_attribution(True)


def _ceilings(flops=1e12, bytes_per_s=1e11):
    return obs_perf.Ceilings(
        device_kind="test", flops_per_s=flops, bytes_per_s=bytes_per_s,
        launch_seconds=5e-6, source="known",
    )


def _segment_count(component, segment):
    snap = obs_metrics.registry().snapshot()
    s = series_map(snap, "gol_turn_segment_seconds").get((component, segment))
    return (s or {}).get("count") or 0


# -- roofline classifier core -------------------------------------------------


def test_classifier_three_classes():
    ceil = _ceilings()
    # dominant, substantial FLOP utilization
    assert obs_perf.classify(8e11, 1e9, ceil)["bound_class"] == "compute-bound"
    # dominant, substantial memory utilization
    assert obs_perf.classify(1e10, 8e10, ceil)["bound_class"] == "memory-bound"
    # far below BOTH ceilings: launch/issue latency is the residual
    row = obs_perf.classify(1e9, 1e8, ceil)
    assert row["bound_class"] == "launch-bound"
    assert row["flops_utilization"] < obs_perf.LAUNCH_UTILIZATION
    assert row["memory_utilization"] < obs_perf.LAUNCH_UTILIZATION


def test_classifier_zero_flops_degenerate():
    """A site whose cost analysis reported nothing must classify without
    dividing by anything: zero/zero is launch-bound, zero flops with
    real byte traffic is memory-bound."""
    ceil = _ceilings()
    assert obs_perf.classify(0.0, 0.0, ceil)["bound_class"] == "launch-bound"
    assert obs_perf.classify(0.0, 9e10, ceil)["bound_class"] == "memory-bound"
    # and a zero ceiling (broken calibration) must not raise either
    broken = obs_perf.Ceilings("z", 0.0, 0.0, 0.0, "fitted")
    assert obs_perf.classify(1e9, 1e9, broken)["bound_class"] == "launch-bound"


def test_ceiling_calibration_cached(fresh_attribution):
    """The microbench runs ON FIRST USE per device kind, then every later
    call is a cache hit returning the same object."""
    first = obs_perf.calibrate("weird-cpu-kind")
    fits = obs_perf._FIT_RUNS
    assert fits == 1 and first.source == "fitted"
    again = obs_perf.calibrate("weird-cpu-kind")
    assert again is first
    assert obs_perf._FIT_RUNS == fits  # no second microbench
    # a KNOWN TPU kind never pays the microbench at all
    v5e = obs_perf.calibrate("TPU v5e")
    assert v5e.source == "known" and obs_perf._FIT_RUNS == fits
    assert v5e.flops_per_s > 1e13 and v5e.bytes_per_s > 1e11


def test_bench_round_classification_pin(fresh_attribution):
    """The acceptance pin on this repo's own bench data: against v5e
    ceilings, the 128² floor case classifies launch-bound and the
    4096²+ dense cases classify NON-launch-bound."""
    ceil = obs_perf.calibrate("v5e")
    rows = obs_perf.rows_from_bench(REPO_ROOT / "BENCH_r04.json", ceil)
    by_case = {r["case"]: r for r in rows}
    assert by_case["c2_128_pallas_bitboard"]["bound_class"] == "launch-bound"
    for case in (
        "c4_4096_tiled_bitboard",
        "c5_16384_sparse_bigboard",
        "c5_65536_sparse_bigboard",
    ):
        assert by_case[case]["bound_class"] != "launch-bound", case
    # embedded roofline fields (bench.py from this PR on) take precedence
    # over the name-parsed model
    fake = {"cases": {"c2_128_x": {
        "per_turn_us": 1.0, "achieved_flops": 5.0,
        "achieved_bytes_per_s": 7.0, "bound_class": "memory-bound",
    }}, "provenance": None, "salvaged": False, "label": "x"}
    import gol_distributed_final_tpu.obs.regress as regress

    orig = regress.load_bench
    regress.load_bench = lambda _p: fake
    try:
        rows = obs_perf.rows_from_bench("whatever.json", ceil)
    finally:
        regress.load_bench = orig
    assert rows[0]["achieved_flops"] == 5.0
    assert rows[0]["bound_class"] == "memory-bound"


def test_dispatch_stats_and_refresh(live_metrics, fresh_attribution):
    """The roofline join end to end in-process: an instrumented jitted
    call records its dispatch wall + program cost exactly once per call,
    and refresh_metrics publishes achieved gauges + ONE bound class."""
    import jax
    import jax.numpy as jnp

    obs_device.reset_dispatch()
    jitted = jax.jit(lambda x: x * 2 + 1)
    wrapped = obs_device.instrument_jit("perf.test_site", jitted)
    x = jnp.ones((33, 17), jnp.float32)  # unique signature for this test
    for _ in range(3):
        np.asarray(wrapped(x))
    stats = obs_device.dispatch_stats()
    assert stats["perf.test_site"]["calls"] == 3
    assert stats["perf.test_site"]["wall_s"] > 0
    rows = obs_perf.refresh_metrics(_ceilings())
    row = next(r for r in rows if r["site"] == "perf.test_site")
    assert row["bound_class"] in obs_perf.BOUND_CLASSES
    snap = obs_metrics.registry().snapshot()
    achieved = series_map(snap, "gol_kernel_achieved_flops")
    assert ("perf.test_site",) in achieved
    bound = series_map(snap, "gol_kernel_bound")
    on = [
        labels for labels, s in bound.items()
        if labels[0] == "perf.test_site" and s.get("value")
    ]
    assert len(on) == 1 and on[0][1] == row["bound_class"]


# -- dispatch-wall decomposition ----------------------------------------------


def test_engine_decomposition_segments(live_metrics, fresh_attribution):
    from gol_distributed_final_tpu.engine.engine import Engine, EngineConfig
    from gol_distributed_final_tpu.params import Params

    before = {
        seg: _segment_count("engine", seg)
        for seg in ("host_prep", "device_compute", "demux")
    }
    rng = np.random.default_rng(5)
    board = np.where(rng.random((32, 32)) < 0.3, 255, 0).astype(np.uint8)
    Engine(EngineConfig(min_chunk=1, max_chunk=4)).run(
        Params(turns=8, image_width=32, image_height=32), board
    )
    for seg, prev in before.items():
        assert _segment_count("engine", seg) > prev, seg
    decomp = obs_perf.decomposition_summary()
    assert "engine" in decomp
    segs = decomp["engine"]
    assert segs["_total_s"] > 0
    assert abs(sum(
        e["share"] for k, e in segs.items() if isinstance(e, dict)
    ) - 1.0) < 0.01


def test_sessions_decomposition_segments(live_metrics, fresh_attribution):
    from gol_distributed_final_tpu.engine.sessions import SessionTable
    from gol_distributed_final_tpu.models import CONWAY

    before = _segment_count("sessions", "device_compute")
    rng = np.random.default_rng(6)
    boards = np.where(rng.random((3, 16, 16)) < 0.3, 255, 0).astype(np.uint8)
    table = SessionTable(CONWAY, (16, 16), capacity=4)
    for i in range(3):
        table.admit(boards[i], 4)
    while table.advance():
        pass
    assert _segment_count("sessions", "device_compute") > before
    assert _segment_count("sessions", "demux") > 0


def test_attribution_switch_disables_segments(live_metrics, fresh_attribution):
    from gol_distributed_final_tpu.engine.engine import Engine, EngineConfig
    from gol_distributed_final_tpu.params import Params

    obs_perf.set_attribution(False)
    before = _segment_count("engine", "device_compute")
    board = np.zeros((16, 16), np.uint8)
    Engine(EngineConfig(min_chunk=1, max_chunk=2)).run(
        Params(turns=4, image_width=16, image_height=16), board
    )
    assert _segment_count("engine", "device_compute") == before


# -- straggler / critical-path attribution ------------------------------------

_MATRIX = [
    {":8030": 0.010, ":8031": 0.012, ":8032": 0.055, ":8033": 0.011}
    for _ in range(4)
]


def test_tracker_attributes_fake_matrix(fresh_attribution):
    cp = obs_critical.attribute_batches(_MATRIX)
    assert cp["batches"] == 4
    s = cp["straggler"]
    assert s and s["addr"] == ":8032"
    assert s["gated_share"] == 1.0
    assert s["skew"] > obs_critical.STRAGGLER_SKEW_RATIO
    rows = {w["addr"]: w for w in cp["workers"]}
    assert rows[":8030"]["gated"] == 0 and rows[":8032"]["gated"] == 4
    assert rows[":8032"]["calls"] == 4


def test_tracker_balanced_roster_names_nobody(fresh_attribution):
    balanced = [
        {":8030": 0.010, ":8031": 0.011, ":8032": 0.012, ":8033": 0.010}
        for _ in range(6)
    ]
    cp = obs_critical.attribute_batches(balanced)
    assert cp["straggler"] is None
    assert cp["skew_ratio"] < obs_critical.STRAGGLER_SKEW_RATIO


def test_tracker_sets_skew_gauge_and_service_preference(
    live_metrics, fresh_attribution
):
    t = obs_critical.tracker()
    # service time preferred over round trip when the reply carried it:
    # a slow WIRE to a fast worker must not skew its service EWMA
    t.record_batch([(":a", 0.050, 0.001), (":b", 0.010, 0.009)])
    cp = t.snapshot()
    rows = {w["addr"]: w for w in cp["workers"]}
    assert rows[":a"]["ewma_s"] == pytest.approx(0.001)
    # ...but the GATING attribution stays on the round trip (the gather
    # completed at :a regardless of where the time went)
    assert rows[":a"]["gated"] == 1
    snap = obs_metrics.registry().snapshot()
    g = series_map(snap, "gol_worker_skew_ratio").get(())
    assert g and g.get("value") > 0


def test_doctor_straggler_finding_canned(fresh_attribution):
    from gol_distributed_final_tpu.obs.doctor import diagnose

    cp = obs_critical.attribute_batches(_MATRIX)
    statuses = {
        "broker 127.0.0.1:1": {
            "pid": 1, "metrics_enabled": True, "metrics": {},
            "critical_path": cp,
        }
    }
    findings = diagnose(statuses)
    top = findings[0]
    assert "straggler" in top["title"]
    assert ":8032" in top["suspects"]
    assert any(":8030" in e for e in top["evidence"])  # per-addr evidence
    # a healthy payload must NOT produce the finding
    healthy = {
        "broker 127.0.0.1:1": {
            "pid": 1, "metrics_enabled": True, "metrics": {},
        }
    }
    assert all("straggler" not in f["title"] for f in diagnose(healthy))


def test_worker_skew_rule_in_default_book():
    from gol_distributed_final_tpu.obs.slo import (
        DEFAULT_RULE_NAMES,
        default_rules,
    )

    assert "worker-skew" in DEFAULT_RULE_NAMES
    rule = next(r for r in default_rules() if r.name == "worker-skew")
    assert rule.metric == "gol_worker_skew_ratio"


# -- live slow worker: the doctor names it ------------------------------------


def _spawn_worker(extra_env=None):
    env = dict(os.environ)
    env.pop("GOL_FAULT_POINTS", None)
    env["JAX_PLATFORMS"] = "cpu"
    if extra_env:
        env.update(extra_env)
    return subprocess.Popen(
        [sys.executable, "-m", "gol_distributed_final_tpu.rpc.worker",
         "-port", "0"],
        cwd=REPO_ROOT, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True,
    )


def _wait_port(proc, timeout=60):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if "listening on :" in line:
            return int(line.rsplit(":", 1)[1].split()[0])
        if proc.poll() is not None:
            raise RuntimeError(f"worker died: {proc.stdout.read()}")
    raise TimeoutError("worker did not report listening")


def test_live_slow_worker_named_by_doctor(live_metrics, fresh_attribution):
    """A sleep-injected slow worker (GOL_FAULT_POINTS on its update /
    strip_step sites) in a live 4-worker resident cluster: the broker's
    critical-path attribution gates on it from the FIRST K-batch, and
    the doctor's top finding names it with per-address service-time
    evidence."""
    from gol_distributed_final_tpu.obs.doctor import collect, diagnose, render
    from gol_distributed_final_tpu.rpc.broker import serve
    from gol_distributed_final_tpu.rpc.client import RpcClient
    from gol_distributed_final_tpu.rpc.protocol import Methods, Request

    obs_metrics.registry().reset()  # other modules' counters must not
    # outrank the straggler in the shared-process registry
    slow_env = {
        "GOL_FAULT_POINTS":
            "worker.strip_step:sleep:1:0.08,worker.update:sleep:1:0.08"
    }
    workers = [_spawn_worker(slow_env if i == 0 else None) for i in range(4)]
    server = None
    try:
        ports = [_wait_port(w) for w in workers]
        slow_addr = f"127.0.0.1:{ports[0]}"
        server, service = serve(
            port=0, backend="workers",
            worker_addresses=[f"127.0.0.1:{p}" for p in ports],
            wire="resident", halo_depth=4,
        )
        addr = f"127.0.0.1:{server.port}"
        rng = np.random.default_rng(9)
        board = np.where(rng.random((64, 64)) < 0.3, 255, 0).astype(np.uint8)
        client = RpcClient(addr)
        try:
            client.call(
                Methods.BROKER_RUN,
                Request(world=board, turns=12, threads=4,
                        image_width=64, image_height=64),
                timeout=120.0,
            )
        finally:
            client.close()
        cp = obs_critical.tracker().snapshot()
        assert cp["batches"] >= 1
        s = cp["straggler"]
        assert s and s["addr"] == slow_addr, cp
        # per-addr StripStep service-time histogram recorded broker-side
        snap = obs_metrics.registry().snapshot()
        strips = series_map(snap, "gol_strip_step_seconds")
        assert (slow_addr,) in strips and strips[(slow_addr,)]["count"] >= 1
        # the doctor, over the real read-only Status surface
        statuses = collect(addr, [])
        findings = diagnose(statuses)
        top = findings[0]
        assert "straggler" in top["title"], [f["title"] for f in findings]
        assert slow_addr in top["suspects"]
        assert render(findings, statuses).strip()
        # the broker also decomposed its batches: wire + compute segments
        assert _segment_count("broker", "device_compute") >= 1
        assert _segment_count("broker", "wire") >= 1
    finally:
        if server is not None:
            service.backend.close()
            server.stop()
        for w in workers:
            if w.poll() is None:
                w.kill()
            w.wait()


# -- regress: --latest selection + achieved-throughput gate -------------------


def test_latest_bench_files_ignores_non_rounds(tmp_path):
    from gol_distributed_final_tpu.obs.regress import latest_bench_files

    for name in (
        "BENCH_r01.json", "BENCH_r02.json", "BENCH_r10.json",
        "MULTICHIP_r03.json", "MULTICHIP_r11.json", "BENCH_rX.json",
        "BENCH_r05.json.tmp",
    ):
        (tmp_path / name).write_text("{}")
    rounds = [p.name for p in latest_bench_files(tmp_path)]
    # strictly BENCH_r<number>.json, numerically ordered (r10 after r02)
    assert rounds == ["BENCH_r01.json", "BENCH_r02.json", "BENCH_r10.json"]


def test_latest_cli_skips_junk_rounds(tmp_path, capsys):
    """--latest over a directory whose only *_r*.json files are junk
    must be a clean no-op, not a load error on MULTICHIP data."""
    from gol_distributed_final_tpu.obs.regress import main

    (tmp_path / "MULTICHIP_r01.json").write_text('{"n_devices": 8}')
    (tmp_path / "MULTICHIP_r02.json").write_text('{"n_devices": 8}')
    assert main(["--latest", "--dir", str(tmp_path)]) == 0
    assert "fewer than two" in capsys.readouterr().err


def _case(us, flops=None, cls=None):
    out = {
        "per_turn_us": us, "spread_s": 0.00001, "n_lo": 100, "n_hi": 1100,
    }
    if flops is not None:
        out["achieved_flops"] = flops
    if cls is not None:
        out["bound_class"] = cls
    return out


def test_regress_gates_achieved_throughput():
    from gol_distributed_final_tpu.obs.regress import compare_case

    # big achieved-FLOP/s drop past threshold + noise: REGRESSED with the
    # roofline why, even though wall-clock alone would already flag it
    v = compare_case(
        _case(1.0, flops=1e12, cls="memory-bound"),
        _case(2.0, flops=5e11, cls="launch-bound"),
        threshold=0.05, noise_k=2.0,
    )
    assert v["verdict"] == "REGRESSED"
    assert v["bound_class_change"] == "memory-bound -> launch-bound"
    assert v["achieved_delta_pct"] == pytest.approx(-50.0)
    # drop inside the noise band: never gated by the roofline fields
    v = compare_case(
        _case(1.0, flops=1.00e12), _case(1.001, flops=0.999e12),
        threshold=0.05, noise_k=2.0,
    )
    assert v["verdict"] == "jitter"
    # an achieved drop must gate even when wall-clock is unusable
    # (salvaged fragment): the incomparable verdict upgrades
    broken_old = {"per_turn_us": 0, "achieved_flops": 1e12}
    broken_new = {"per_turn_us": 0, "achieved_flops": 1e11}
    v = compare_case(broken_old, broken_new, threshold=0.05)
    assert v["verdict"] == "REGRESSED"
    assert "achieved" in v["why"]


# -- watch panels + report embeds ---------------------------------------------


def test_watch_renders_attribution_panels(live_metrics, fresh_attribution):
    from gol_distributed_final_tpu.obs.watch import render_status

    for seg, dt in (
        ("host_prep", 0.01), ("device_compute", 0.2),
        ("wire", 0.05), ("demux", 0.02),
    ):
        import gol_distributed_final_tpu.obs.instruments as ins

        ins.TURN_SEGMENT_SECONDS.labels("broker", seg).observe(dt)
    import gol_distributed_final_tpu.obs.instruments as ins

    ins.KERNEL_ACHIEVED_FLOPS.labels("pallas.vmem_bit").set(2e11)
    ins.KERNEL_ACHIEVED_BYTES.labels("pallas.vmem_bit").set(4e10)
    ins.KERNEL_BOUND.labels("pallas.vmem_bit", "launch-bound").set(1)
    cp = obs_critical.attribute_batches(_MATRIX)
    payload = {
        "role": "broker", "pid": 1, "metrics_enabled": True,
        "metrics": obs_metrics.registry().snapshot(),
        "critical_path": cp,
    }
    frame = render_status("broker :8040", payload)
    assert "WHERE TIME GOES" in frame
    assert "device_compute" in frame and "wire" in frame
    assert "CRITICAL PATH" in frame
    assert "STRAGGLER :8032" in frame
    assert "ROOFLINE" in frame and "launch-bound" in frame


def test_report_embeds_attribution(live_metrics, fresh_attribution, tmp_path):
    from gol_distributed_final_tpu.obs.report import write_run_report
    from gol_distributed_final_tpu.params import Params

    import gol_distributed_final_tpu.obs.instruments as ins

    ins.TURN_SEGMENT_SECONDS.labels("engine", "device_compute").observe(0.5)
    ins.TURN_SEGMENT_SECONDS.labels("engine", "demux").observe(0.1)
    obs_critical.tracker().record_batch([(":a", 0.02, None), (":b", 0.01, None)])
    path = write_run_report(
        Params(turns=4, image_width=16, image_height=16), tmp_path
    )
    report = json.loads(path.read_text())
    assert "where_time_goes" in report
    assert report["where_time_goes"]["engine"]["device_compute"]["count"] >= 1
    assert report["critical_path"]["batches"] >= 1


def test_status_payload_ships_critical_path(live_metrics, fresh_attribution):
    from gol_distributed_final_tpu.obs.report import status_payload

    assert "critical_path" not in status_payload(role="broker")
    obs_critical.tracker().record_batch([(":a", 0.02, None), (":b", 0.01, None)])
    payload = status_payload(role="broker")
    assert payload["critical_path"]["batches"] == 1


# -- doctor bundle ------------------------------------------------------------


def test_doctor_bundle_collects_artifacts(tmp_path):
    from gol_distributed_final_tpu.obs.doctor import write_bundle

    out = tmp_path / "out"
    out.mkdir()
    (out / "trace_64x64x8.json").write_text("[]")
    (out / "flight_host.jsonl").write_text("{}\n")
    (out / "report_16x16x4.json").write_text("{}")
    (out / "analysis.json").write_text("{}")
    statuses = {
        "broker 127.0.0.1:1": {
            "pid": 1, "metrics": {}, "timeline": {"seq": 3},
            "accounting": {"tenants": []}, "flight": [],
        },
        "worker 127.0.0.1:2": {"error": "no status: dead"},
    }
    findings = [{"severity": "warn", "title": "t", "rank": 1}]
    bdir = write_bundle(findings, statuses, out)
    assert bdir.parent == out and bdir.name.startswith("bundle_")
    manifest = json.loads((bdir / "manifest.json").read_text())
    names = {e["file"] for e in manifest["entries"]}
    # diagnosis + one full status per target + the on-disk artifacts
    assert "doctor.json" in names
    assert any(n.startswith("status_broker") for n in names)
    assert any(n.startswith("status_worker") for n in names)
    for artifact in (
        "trace_64x64x8.json", "flight_host.jsonl",
        "report_16x16x4.json", "analysis.json",
    ):
        assert artifact in names and (bdir / artifact).exists()
    # the full status payload (timeline + accounting evidence) is IN the
    # bundle, not a trimmed identity stub
    status_file = next(n for n in names if n.startswith("status_broker"))
    payload = json.loads((bdir / status_file).read_text())
    assert payload["timeline"] == {"seq": 3}
    assert manifest["targets"] == sorted(statuses)


# -- lint + selfchecks --------------------------------------------------------


def test_perf_lint_both_ways(tmp_path):
    from gol_distributed_final_tpu.obs.lint import (
        missing_readme_sections,
        undocumented_perf_names,
    )

    assert undocumented_perf_names() == []  # the shipped README documents all
    assert "## Performance attribution" not in missing_readme_sections()
    bad = tmp_path / "README.md"
    bad.write_text("# nothing\n\n## Performance attribution\n\nonly prose\n")
    missing = undocumented_perf_names(bad)
    assert "gol_kernel_bound" in missing and "launch-bound" in missing


def test_critical_selfcheck_passes(capsys):
    assert obs_critical._selfcheck() == 0
    assert "straggler attribution exact" in capsys.readouterr().out
