"""2-D tile data-plane suite: the checkerboard grid over the resident wire.

Covers the layers ``-grid`` stands on:

* ``rpc/worker.py`` tile kernel — ``tile_step_batch`` oracle parity from
  the four depth-K edge halos plus four K×K corner blocks (the full 2-D
  dependency cone), the bit-packed halo wire format (``pack_tile_blocks``
  round-trip, strict truncation errors), the 2-D dead-band skip route,
  masked-rule (HighLife) parity, and the eight-band attestation payload.
* ``rpc/broker.py`` tile sessions — bit-parity against the wrapping
  oracle across grids × batch depths × uneven splits, the squarest-fit
  ``auto`` resolver and its gauges, the H-cap removal (8 workers on a
  4-row board via 2x4), structured roster refusals, byte-identity of an
  explicit one-column grid with the legacy strip plane, the 2-D
  cross-attestation BOTH-quarantine contract, and one-tile loss recovery.
* ``obs/regress.py`` — the deterministic halo-byte gate beside the wire
  gate, and ``analysis/skew.py`` auto-discovering the tile wire fields.
"""

import threading
import time

import numpy as np
import pytest

from gol_distributed_final_tpu.models.life import CONWAY, HIGHLIFE
from gol_distributed_final_tpu.obs import metrics as obs_metrics
from gol_distributed_final_tpu.rpc import worker as rpc_worker
from gol_distributed_final_tpu.rpc.broker import (
    WorkersBackend,
    _auto_grid,
    parse_grid,
)
from gol_distributed_final_tpu.rpc.client import RpcError
from gol_distributed_final_tpu.rpc.protocol import Methods, Request
from gol_distributed_final_tpu.rpc.worker import (
    pack_tile_blocks,
    tile_edge_shapes,
    tile_halo_shapes,
    tile_step_batch,
    unpack_tile_blocks,
    _packed_len,
)

from oracle import vector_step


def _rand_board(h, w, seed, density=0.4):
    rng = np.random.default_rng(seed)
    return np.where(rng.random((h, w)) < density, 255, 0).astype(np.uint8)


def _lut_step(board, rule):
    """Wrapping one-step oracle for an arbitrary masked rule."""
    b = (board != 0).astype(np.int32)
    n = sum(
        np.roll(np.roll(b, dr, 0), dc, 1)
        for dr in (-1, 0, 1) for dc in (-1, 0, 1)
    ) - b
    nxt = np.where(b == 1, (rule.survive_mask >> n) & 1, (rule.birth_mask >> n) & 1)
    return np.where(nxt.astype(bool), 255, 0).astype(np.uint8)


def _wrap_halos(board, s, e, x0, x1, k):
    """The 8-tuple (top, bottom, left, right, tl, tr, bl, br) a broker
    would relay for the tile ``board[s:e, x0:x1]`` — toroidal indices."""
    h, w = board.shape

    def rs(a, b):
        return np.arange(a, b) % h

    def cs(a, b):
        return np.arange(a, b) % w

    return (
        board[np.ix_(rs(s - k, s), cs(x0, x1))],
        board[np.ix_(rs(e, e + k), cs(x0, x1))],
        board[np.ix_(rs(s, e), cs(x0 - k, x0))],
        board[np.ix_(rs(s, e), cs(x1, x1 + k))],
        board[np.ix_(rs(s - k, s), cs(x0 - k, x0))],
        board[np.ix_(rs(s - k, s), cs(x1, x1 + k))],
        board[np.ix_(rs(e, e + k), cs(x0 - k, x0))],
        board[np.ix_(rs(e, e + k), cs(x1, x1 + k))],
    )


# -- tile kernel --------------------------------------------------------------


@pytest.mark.parametrize("k", [1, 3])
def test_tile_step_batch_matches_oracle_shrinking_form(k):
    board = _rand_board(24, 18, seed=k)
    s, e, x0, x1 = 8, 14, 6, 12
    tile = board[s:e, x0:x1].copy()
    got, counts = tile_step_batch(tile, _wrap_halos(board, s, e, x0, x1, k), k)
    want = board.copy()
    per_step = []
    for _ in range(k):
        want = vector_step(want)
        per_step.append(int(np.count_nonzero(want[s:e, x0:x1])))
    np.testing.assert_array_equal(got, want[s:e, x0:x1])
    assert counts == per_step


def test_tile_step_batch_highlife_parity():
    """The masked-rule path: B36/S23 through the same shrinking cone —
    and the seed genuinely exercises B6 (HighLife diverges from Conway)."""
    board = _rand_board(20, 20, seed=77, density=0.45)
    s, e, x0, x1 = 5, 13, 4, 14
    k = 3
    want_hl, want_cw = board.copy(), board.copy()
    for _ in range(k):
        want_hl = _lut_step(want_hl, HIGHLIFE)
        want_cw = _lut_step(want_cw, CONWAY)
    assert not np.array_equal(want_hl, want_cw), "seed never fired B6"
    np.testing.assert_array_equal(want_cw[:], vector_step(
        vector_step(vector_step(board))
    ))  # the LUT oracle agrees with the Conway oracle on Conway
    got, _counts = tile_step_batch(
        board[s:e, x0:x1].copy(), _wrap_halos(board, s, e, x0, x1, k), k,
        rule=HIGHLIFE,
    )
    np.testing.assert_array_equal(got, want_hl[s:e, x0:x1])


def test_tile_skip_route_matches_dense_and_fused_refuses():
    """A lone glider deep inside an otherwise dead tile: the 2-D dead-band
    skip must reproduce the dense result AND all eight attestation
    digests; ``mode='fused'`` is an explicit refusal (the fused strip
    kernel wraps columns locally, which a tile must not)."""
    board = np.zeros((40, 40), np.uint8)
    board[10:13, 10:13] = np.where(
        np.array([[0, 1, 0], [0, 0, 1], [1, 1, 1]]), 255, 0
    ).astype(np.uint8)
    s, e, x0, x1 = 4, 36, 4, 36
    k = 4
    halos = _wrap_halos(board, s, e, x0, x1, k)
    tile = board[s:e, x0:x1]
    d_tile, d_counts, d_att = tile_step_batch(
        tile.copy(), halos, k, attest=True, mode="dense"
    )
    s_tile, s_counts, s_att = tile_step_batch(
        tile.copy(), halos, k, attest=True, mode="skip"
    )
    np.testing.assert_array_equal(s_tile, d_tile)
    assert s_counts == d_counts
    assert s_att == d_att
    with pytest.raises(ValueError, match="no fused path"):
        tile_step_batch(tile.copy(), halos, k, mode="fused")


def test_pack_unpack_roundtrip_and_strict_errors():
    k, th, tw = 3, 7, 11  # odd cell counts: partial trailing bytes
    shapes = tile_halo_shapes(k, th, tw)
    rng = np.random.default_rng(5)
    blocks = [
        np.where(rng.random(sh) < 0.5, 255, 0).astype(np.uint8)
        for sh in shapes
    ]
    buf = pack_tile_blocks(blocks)
    assert buf.size == sum(_packed_len(sh) for sh in shapes)
    for got, want in zip(unpack_tile_blocks(buf, shapes), blocks):
        np.testing.assert_array_equal(got, want)
    with pytest.raises(ValueError, match="truncated"):
        unpack_tile_blocks(buf[:-1], shapes)
    with pytest.raises(ValueError, match="trailing"):
        unpack_tile_blocks(np.concatenate([buf, buf[:1]]), shapes)
    assert tile_edge_shapes(k, th, tw) == [(k, tw), (k, tw), (th, k), (th, k)]


def test_tile_batch_depth_exceeding_thinnest_dimension_refuses():
    tile = np.zeros((4, 9), np.uint8)
    halos = tuple(np.zeros(sh, np.uint8) for sh in tile_halo_shapes(5, 4, 9))
    with pytest.raises(ValueError, match="exceeds tile minimum dimension"):
        tile_step_batch(tile, halos, 5)


def test_worker_tile_session_validates_packed_halo_buffer():
    """A StripStart carrying grid fields flips the session to the tile
    wire: StripStep then demands the exact packed halo byte count and
    replies with packed edges plus the eight-band attestation digests."""
    service = rpc_worker.WorkerService(server=None)
    tile = _rand_board(8, 10, seed=3)
    service.strip_start(Request(
        world=tile.copy(), worker=0, initial_turn=0,
        grid_rows=2, grid_cols=2, start_x=0, end_x=10,
    ))
    k = 2
    shapes = tile_halo_shapes(k, 8, 10)
    with pytest.raises(ValueError, match="must pack to"):
        service.strip_step(Request(
            world=np.zeros(3, np.uint8), turns=k, worker=0, initial_turn=0,
        ))
    halos = pack_tile_blocks([np.zeros(sh, np.uint8) for sh in shapes])
    res = service.strip_step(Request(
        world=halos, turns=k, worker=0, initial_turn=0,
    ))
    assert res.turns_completed == k
    assert res.edges.size == sum(
        _packed_len(sh) for sh in tile_edge_shapes(k, 8, 10)
    )
    assert {"attest_tl", "attest_tr", "attest_bl", "attest_br"} <= set(
        res.digests
    )


# -- grid resolution ----------------------------------------------------------


def test_parse_grid_and_auto_resolver():
    assert parse_grid("auto") == "auto"
    assert parse_grid("2x2") == (2, 2)
    assert parse_grid("2x4") == (4, 2)  # CxR: 2 columns, 4 rows
    for bad in ("3x", "x3", "0x2", "2x-1", "nope"):
        with pytest.raises(ValueError):
            parse_grid(bad)
    assert _auto_grid(4, 32, 32) == (2, 2)  # square board: squarest split
    assert _auto_grid(4, 4, 400) == (1, 4)  # wide board: column bands
    assert _auto_grid(3, 400, 4) == (3, 1)  # tall board: row bands
    assert _auto_grid(1, 8, 8) == (1, 1)


def test_grid_requires_resident_wire_and_valid_spec():
    with pytest.raises(ValueError, match="resident"):
        WorkersBackend(["127.0.0.1:1"], wire="haloed", grid="2x2")
    with pytest.raises(ValueError):
        WorkersBackend(["127.0.0.1:1"], wire="resident", grid="3x")


# -- in-process cluster -------------------------------------------------------


@pytest.fixture(scope="module")
def tile_cluster():
    """Nine in-process workers — enough for the 3x3 grid."""
    servers = [rpc_worker.serve(port=0) for _ in range(9)]
    yield [f"127.0.0.1:{s.port}" for s, _ in servers]
    for server, _service in servers:
        server.stop()


@pytest.fixture
def live_metrics():
    obs_metrics.enable()
    obs_metrics.registry().reset()
    yield obs_metrics
    obs_metrics.enable(False)


def _counter(name):
    for fam in obs_metrics.registry().snapshot()["families"]:
        if fam["name"] == name:
            return {tuple(s["labels"]): s["value"] for s in fam["series"]}
    return {}


def _gauge(name):
    vals = list(_counter(name).values())
    return vals[0] if vals else None


def _run_grid(addrs, board, turns, k, grid, sync_interval=16, **kw):
    backend = WorkersBackend(
        addrs, wire="resident", halo_depth=k, sync_interval=sync_interval,
        grid=grid, **kw,
    )
    try:
        return backend.run(
            Request(
                world=board, turns=turns, threads=len(addrs),
                image_width=board.shape[1], image_height=board.shape[0],
            )
        )
    finally:
        backend.close()


_ORACLE_CACHE = {}


def _oracle(board, turns):
    key = (board.tobytes(), board.shape, turns)
    if key not in _ORACLE_CACHE:
        want = board.copy()
        for _ in range(turns):
            want = vector_step(want)
        _ORACLE_CACHE[key] = want
    return _ORACLE_CACHE[key]


@pytest.mark.parametrize("grid", ["1x4", "4x1", "2x2", "3x3", "2x4"])
@pytest.mark.parametrize("k", [1, 4, 8])
def test_tile_parity_vs_oracle(tile_cluster, grid, k):
    """Bit-identical to the wrapping oracle across the grid matrix: both
    orientations, squares, the 8-worker 2x4, uneven splits on BOTH axes
    (24 % 3, 33 % 2, 33 % 3 all nonzero), partial final batches
    (41 % 4 != 0), and the per-grid K clamp to the thinnest band."""
    board = _rand_board(24, 33, seed=2433)
    turns = 41
    res = _run_grid(tile_cluster, board, turns, k, grid)
    assert res.turns_completed == turns
    np.testing.assert_array_equal(res.world, _oracle(board, turns))


def test_tile_auto_grid_squarest_fit_and_gauges(tile_cluster, live_metrics):
    """``-grid auto`` on a square board with 4 requested lanes resolves
    2x2 (the squarest factorization), publishes the grid gauges, and
    meters halo bytes on all three axes."""
    board = _rand_board(32, 32, seed=9)
    turns = 16
    backend = WorkersBackend(
        tile_cluster, wire="resident", halo_depth=4, sync_interval=16,
        grid="auto",
    )
    try:
        res = backend.run(
            Request(world=board, turns=turns, threads=4,
                    image_width=32, image_height=32)
        )
    finally:
        backend.close()
    np.testing.assert_array_equal(res.world, _oracle(board, turns))
    assert _gauge("gol_tile_grid_rows") == 2
    assert _gauge("gol_tile_grid_cols") == 2
    assert _gauge("gol_tile_edge_cells") == 2 * 4 * (16 + 16) + 4 * 16
    halo = _counter("gol_halo_bytes_total")
    for axis in ("row", "col", "corner"):
        assert halo.get((axis,), 0) > 0, f"axis={axis} never metered"


def test_tile_grid_eight_workers_on_four_row_board(tile_cluster):
    """The H-cap removal: a 4-row board can ONLY split 4 ways as strips,
    but 2x4 puts 8 workers on it (1x20 tiles; K clamps to 1)."""
    board = _rand_board(4, 40, seed=440)
    turns = 17
    res = _run_grid(tile_cluster, board, turns, 4, "2x4")
    assert res.turns_completed == turns
    np.testing.assert_array_equal(res.world, _oracle(board, turns))


def test_tile_corner_glider_cone_exact(tile_cluster):
    """A glider crossing the 2x2 junction diagonally mid-K-batch: its
    light cone enters the next tile through the K×K CORNER block, so
    parity here is exactness of the corner-halo geometry."""
    board = np.zeros((16, 16), np.uint8)
    board[5:8, 5:8] = np.where(
        np.array([[0, 1, 0], [0, 0, 1], [1, 1, 1]]), 255, 0
    ).astype(np.uint8)
    turns = 24
    res = _run_grid(tile_cluster, board, turns, 8, "2x2")
    np.testing.assert_array_equal(res.world, _oracle(board, turns))


def test_grid_rejections_are_structured(tile_cluster):
    board = _rand_board(24, 33, seed=1)
    with pytest.raises(RpcError) as ei:
        _run_grid(tile_cluster[:4], board, 8, 4, "3x3")
    assert ei.value.reason == "grid_roster"
    with pytest.raises(RpcError) as ei:
        _run_grid(tile_cluster[:4], _rand_board(1, 40, seed=2), 8, 4, "2x2")
    assert ei.value.reason == "grid_unsatisfiable"


def test_one_column_grid_is_wire_byte_identical(tile_cluster, live_metrics):
    """``-grid 1x4`` IS the strip plane: same loop, same frames — the
    run's gol_wire_bytes_total delta matches a plain 4-lane resident run
    EXACTLY, byte for byte."""
    board = _rand_board(64, 64, seed=64)
    turns = 48

    def run(grid):
        backend = WorkersBackend(
            tile_cluster, wire="resident", halo_depth=4, sync_interval=16,
            grid=grid,
        )
        try:
            b0 = sum(_counter("gol_wire_bytes_total").values())
            res = backend.run(
                Request(world=board, turns=turns, threads=4,
                        image_width=64, image_height=64)
            )
            return res, sum(_counter("gol_wire_bytes_total").values()) - b0
        finally:
            backend.close()

    res_plain, bytes_plain = run(None)
    res_grid, bytes_grid = run("1x4")
    np.testing.assert_array_equal(res_plain.world, res_grid.world)
    np.testing.assert_array_equal(res_grid.world, _oracle(board, turns))
    assert bytes_grid == bytes_plain, (
        f"1x4 moved {bytes_grid} B, plain strips {bytes_plain} B"
    )


def test_tile_attestation_mismatch_quarantines_both(live_metrics):
    """The 2-D cross-attestation contract: one worker's tampered
    attest_top digest disagrees with its up-neighbour's attest_bottom —
    the broker cannot name the liar, so BOTH tiles quarantine
    (gol_worker_lost_total >= 2, gol_integrity_failures_total{attest}),
    recovery rebuilds from the last verified sync, and the finished
    board is still bit-identical to the oracle."""
    servers = [rpc_worker.serve(port=0) for _ in range(4)]
    addrs = [f"127.0.0.1:{s.port}" for s, _ in servers]
    state = {"armed": True}
    orig = servers[1][1].strip_step

    def tampered(req):
        res = orig(req)
        d = getattr(res, "digests", None)
        if (
            state["armed"] and isinstance(d, dict) and "attest_top" in d
            and res.turns_completed >= 60
        ):
            d["attest_top"] = "00" * 16
            state["armed"] = False
        return res

    servers[1][0].register(Methods.STRIP_STEP, tampered)
    board = _rand_board(48, 48, seed=13)
    turns = 600
    try:
        res = _run_grid(
            addrs, board, turns, 4, "2x2", sync_interval=16,
            rpc_deadline=2.0, probe_interval=0.2,
        )
        assert res.turns_completed == turns
        np.testing.assert_array_equal(res.world, _oracle(board, turns))
        assert not state["armed"], "the tamper never fired"
        assert _counter("gol_integrity_failures_total").get(("attest",), 0) >= 1
        assert sum(_counter("gol_worker_lost_total").values()) >= 2, (
            "a band disagreement must quarantine BOTH parties"
        )
    finally:
        for server, _service in servers:
            server.stop()


def test_tile_worker_loss_recovers_bit_identical():
    """Kill one tile's server mid-run: the broker rebuilds the lost block
    at the committed turn (survivor fetches + the 2-D modular dependency
    cone recompute), re-splits the grid over the survivors, and the
    final board is bit-identical to the oracle."""
    servers = [rpc_worker.serve(port=0) for _ in range(4)]
    addrs = [f"127.0.0.1:{s.port}" for s, _ in servers]
    board = _rand_board(48, 48, seed=17)
    turns = 1200
    backend = WorkersBackend(
        addrs, wire="resident", halo_depth=4, sync_interval=32,
        grid="2x2", rpc_deadline=2.0, probe_interval=0.2,
    )
    out = {}
    t = threading.Thread(
        target=lambda: out.update(
            r=backend.run(
                Request(world=board, turns=turns, threads=4,
                        image_width=48, image_height=48)
            )
        )
    )
    t.start()
    try:
        deadline = time.monotonic() + 60
        while backend.retrieve(include_world=False).turns_completed < 150:
            assert time.monotonic() < deadline, "run never got going"
            time.sleep(0.002)
        servers[1][0].stop()  # mid-batch tile loss
        t.join(timeout=120)
        assert not t.is_alive(), "run hung after the loss"
        assert out["r"].turns_completed == turns
        np.testing.assert_array_equal(out["r"].world, _oracle(board, turns))
    finally:
        if t.is_alive():
            backend.quit()
            t.join(timeout=30)
        backend.close()
        for server, _service in servers:
            try:
                server.stop()
            except Exception:
                pass


# -- gates and skew safety ----------------------------------------------------


def test_bench_diff_gates_halo_bytes_not_just_wall_clock():
    """``scripts/bench_diff`` (obs/regress.py): a case whose
    ``halo_bytes_per_turn`` grew past the threshold REGRESSES even when
    wall-clock is clean — the same deterministic posture as the wire-byte
    gate, on the tile plane's own meter."""
    from gol_distributed_final_tpu.obs.regress import compare_case

    base = {
        "per_turn_us": 100.0, "spread_s": 0.001, "n_lo": 100, "n_hi": 1100,
        "halo_bytes_per_turn": 520.0,
    }
    same = compare_case(base, dict(base))
    assert same["verdict"] == "jitter"
    assert same["halo_bytes_delta_pct"] == 0.0
    bloated = compare_case(base, dict(base, halo_bytes_per_turn=700.0))
    assert bloated["verdict"] == "REGRESSED"
    assert "halo" in bloated["why"]
    slimmer = compare_case(base, dict(base, halo_bytes_per_turn=100.0))
    assert slimmer["verdict"] == "jitter"  # a comms WIN never gates
    plain = compare_case(
        {k: v for k, v in base.items() if k != "halo_bytes_per_turn"},
        {k: v for k, v in base.items() if k != "halo_bytes_per_turn"},
    )
    assert "halo_bytes_delta_pct" not in plain


def test_skew_checker_auto_discovers_tile_wire_fields():
    """The tile grid fields ride protocol.py as extension fields — the
    skew-safety checker's AST parse must pick them up WITHOUT a manual
    registry edit (the PR 7 contract)."""
    from gol_distributed_final_tpu.analysis.skew import wire_extension_fields

    req_ext, _res_ext = wire_extension_fields()
    assert {"grid_rows", "grid_cols", "start_x", "end_x"} <= set(req_ext)
