"""The bit-packed mesh data plane: parity on the virtual 8-device CPU mesh.

The contract (VERDICT.md round-1 item 2): the fast bitboard kernel running
INSIDE shard_map — packed halos over ppermute — is bit-identical to the
single-device stencil, for 1-D and 2-D meshes, gliders crossing shard
boundaries, goldens, and device-side popcounts. Also covers the on-device
pack/unpack (ops/bitpack.pack_device) and the plane-based engine.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from gol_distributed_final_tpu.models import CONWAY, HIGHLIFE
from gol_distributed_final_tpu.ops import bitpack, step_n
from gol_distributed_final_tpu.ops.plane import BitPlane, BytePlane
from gol_distributed_final_tpu.parallel import (
    ShardedBitPlane,
    choose_bit_layout,
    make_bit_plane,
    make_mesh,
    sharded_bit_step_n_fn,
)

from helpers import REPO_ROOT, assert_equal_board, read_alive_cells
from oracle import vector_step

requires_8 = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices"
)


def random_board(h, w, seed=0, density=0.3):
    rng = np.random.default_rng(seed)
    return np.where(rng.random((h, w)) < density, 255, 0).astype(np.uint8)


# -- on-device pack/unpack --------------------------------------------------


@pytest.mark.parametrize("word_axis", [0, 1])
def test_pack_device_matches_numpy_pack(word_axis):
    board = random_board(64, 96, seed=3)
    dev = np.asarray(bitpack.pack_device(jnp.asarray(board), word_axis))
    host = np.asarray(bitpack.pack(board, word_axis))
    np.testing.assert_array_equal(dev, host)


@pytest.mark.parametrize("word_axis", [0, 1])
def test_unpack_device_roundtrip(word_axis):
    board = random_board(96, 64, seed=4)
    packed = bitpack.pack_device(jnp.asarray(board), word_axis)
    np.testing.assert_array_equal(
        np.asarray(bitpack.unpack_device(packed, word_axis)), board
    )


def test_alive_count_packed_popcount():
    board = random_board(64, 64, seed=5)
    packed = bitpack.pack_device(jnp.asarray(board), 0)
    assert bitpack.alive_count_packed(packed) == int(np.count_nonzero(board))


# -- layout choice ----------------------------------------------------------


def test_choose_bit_layout():
    assert choose_bit_layout((256, 256), (8, 1)) == 0  # 256 % (32*8) == 0
    assert choose_bit_layout((64, 64), (8, 1)) == 1  # rows pack fails, cols ok
    assert choose_bit_layout((64, 64), (2, 2)) == 0
    assert choose_bit_layout((50, 50), (2, 4)) is None


# -- sharded bit step parity ------------------------------------------------

MESH_SHAPES = [(8, 1), (1, 8), (4, 2), (2, 4)]


@requires_8
@pytest.mark.parametrize("shape", MESH_SHAPES)
def test_sharded_bit_step_matches_single_device(shape):
    mesh = make_mesh(shape)
    board = random_board(256, 256, seed=11)
    word_axis = choose_bit_layout(board.shape, shape)
    assert word_axis is not None
    stepn = sharded_bit_step_n_fn(mesh, word_axis=word_axis)
    packed = bitpack.pack_device(jnp.asarray(board), word_axis)
    got = np.asarray(
        bitpack.unpack_device(stepn(packed, 3), word_axis)
    )
    want = board
    for _ in range(3):
        want = vector_step(want)
    np.testing.assert_array_equal(got, want)


@requires_8
@pytest.mark.parametrize("shape", [(8, 1), (2, 4)])
def test_bit_glider_crosses_shard_boundaries(shape):
    """A glider translating across every internal boundary (and the torus
    edge) returns home exactly — carry bits crossing word boundaries and
    halo words crossing device boundaries must agree everywhere."""
    mesh = make_mesh(shape)
    board = np.zeros((64, 64), np.uint8)
    for x, y in [(1, 0), (2, 1), (0, 2), (1, 2), (2, 2)]:
        board[y, x] = 255
    word_axis = choose_bit_layout(board.shape, shape)
    stepn = sharded_bit_step_n_fn(mesh, word_axis=word_axis)
    packed = bitpack.pack_device(jnp.asarray(board), word_axis)
    out = stepn(packed, 4 * 64)  # full wrap in one dispatch
    np.testing.assert_array_equal(
        np.asarray(bitpack.unpack_device(out, word_axis)), board
    )


@requires_8
def test_sharded_bit_highlife():
    mesh = make_mesh((2, 4))
    board = random_board(64, 128, seed=8)
    word_axis = choose_bit_layout(board.shape, (2, 4))
    stepn = sharded_bit_step_n_fn(mesh, HIGHLIFE, word_axis)
    packed = bitpack.pack_device(jnp.asarray(board), word_axis)
    got = np.asarray(bitpack.unpack_device(stepn(packed, 2), word_axis))
    want = board
    for _ in range(2):
        want = vector_step(want, birth=(3, 6), survive=(2, 3))
    np.testing.assert_array_equal(got, want)


# -- the plane interface ----------------------------------------------------


@requires_8
@pytest.mark.parametrize("shape", [(8, 1), (4, 2)])
def test_sharded_bit_plane_golden_64(shape):
    """ShardedBitPlane vs the 64x64x100 golden: encode once, 100 turns on
    the mesh, decode once."""
    from gol_distributed_final_tpu.io.pgm import read_pgm
    from gol_distributed_final_tpu.ops import alive_cells

    mesh = make_mesh(shape)
    board = read_pgm(REPO_ROOT / "images" / "64x64.pgm")
    plane = make_bit_plane(mesh, board.shape)
    assert plane is not None
    state = plane.encode(board)
    state = plane.step_n(state, 100)
    got = plane.decode(state)
    expected = read_alive_cells(REPO_ROOT / "check" / "images" / "64x64x100.pgm")
    assert_equal_board(alive_cells(got), expected, 64, 64)
    # device-side popcount agrees with the decoded board
    assert plane.alive_count(state) == int(np.count_nonzero(got))


def test_single_device_bit_plane_golden():
    """BitPlane (single device): packed state across chunks, golden parity."""
    from gol_distributed_final_tpu.io.pgm import read_pgm
    from gol_distributed_final_tpu.ops import alive_cells

    board = read_pgm(REPO_ROOT / "images" / "64x64.pgm")
    plane = BitPlane()
    state = plane.encode(board)
    for _ in range(4):  # several chunks, state stays packed
        state = plane.step_n(state, 25)
    got = plane.decode(state)
    expected = read_alive_cells(REPO_ROOT / "check" / "images" / "64x64x100.pgm")
    assert_equal_board(alive_cells(got), expected, 64, 64)
    assert plane.alive_count(state) == int(np.count_nonzero(got))


@requires_8
def test_engine_runs_on_sharded_bit_plane(tmp_path):
    """Full engine run with the bit mesh plane: golden parity end-to-end,
    count-only retrieve served by the sharded popcount."""
    import queue

    from gol_distributed_final_tpu import FinalTurnComplete, Params, run
    from gol_distributed_final_tpu.engine.controller import CLOSED
    from gol_distributed_final_tpu.engine.engine import EngineConfig

    mesh = make_mesh((4, 2))
    plane = make_bit_plane(mesh, (64, 64))
    assert isinstance(plane, ShardedBitPlane)
    cfg = EngineConfig(plane=plane)
    p = Params(turns=100, image_width=64, image_height=64)
    events = queue.Queue()
    run(
        p,
        events,
        engine_config=cfg,
        images_dir=REPO_ROOT / "images",
        out_dir=tmp_path / "out",
        tick_seconds=3600,
    )
    final = None
    while True:
        ev = events.get_nowait()
        if ev is CLOSED:
            break
        if isinstance(ev, FinalTurnComplete):
            final = ev
    expected = read_alive_cells(REPO_ROOT / "check" / "images" / "64x64x100.pgm")
    assert_equal_board(final.alive, expected, 64, 64)


def test_engine_auto_uses_bit_plane():
    """auto_fast picks the BitPlane for a 32-divisible board and the engine
    serves count-only retrieves from the packed state."""
    from gol_distributed_final_tpu.engine.engine import Engine
    from gol_distributed_final_tpu.params import Params

    engine = Engine()
    board = random_board(64, 64, seed=9)
    result = engine.run(Params(turns=10, image_width=64, image_height=64), board)
    assert engine._plane is not None and isinstance(engine._plane, BitPlane)
    want = board
    for _ in range(10):
        want = vector_step(want)
    np.testing.assert_array_equal(result.world, want)
    snap = engine.retrieve(include_world=False)
    assert snap.alive_count == int(np.count_nonzero(want))


@requires_8
def test_pallas_local_step_parity_on_mesh():
    """The pallas-routed local step (tile-thick halos + grid-tiled kernel
    per device) must agree with the XLA local step across block and torus
    boundaries — the multi-chip large-board path, exercised in interpret
    mode on the CPU mesh."""
    from gol_distributed_final_tpu.parallel.bit_halo import (
        packed_sharding,
        sharded_bit_step_n_fn,
    )
    from gol_distributed_final_tpu.parallel.mesh import make_mesh
    from gol_distributed_final_tpu.ops import bitpack

    mesh = make_mesh((2, 4))
    rng = np.random.default_rng(21)
    board = np.where(rng.random((1024, 1024)) < 0.3, 255, 0).astype(np.uint8)
    packed = jax.device_put(
        bitpack.pack(board, 0), packed_sharding(mesh)
    )  # [32, 1024] -> local blocks (16, 256): ext (32, 512) tiles cleanly
    fast = sharded_bit_step_n_fn(mesh, pallas_local=True, interpret=True)
    ref = sharded_bit_step_n_fn(mesh, pallas_local=False)
    got, want = fast(packed, 6), ref(packed, 6)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_pallas_local_routing_gate():
    """Auto-routing: every tile-ALIGNED row-packed block routes to pallas
    (the r5 real-chip sweep measured it faster at every size); misaligned
    shapes and column packing stay on the XLA step."""
    from gol_distributed_final_tpu.parallel.bit_halo import _auto_use_pallas

    ok = lambda shape, axis: _auto_use_pallas(1, shape, axis, interpret=False)
    assert ok((128, 8192), 0)  # 16384^2 over 4 chips
    assert ok((16, 256), 0)  # small aligned block: pallas still wins (r5)
    assert not ok((12, 8192), 0)  # sublane-misaligned
    assert not ok((128, 8200), 0)  # lane-misaligned
    assert not ok((8192, 128), 1)  # column packing unsupported


class TestWideHalos:
    """Temporal blocking: halo_depth=k exchanges k-deep halos and runs k
    turns per exchange — k-fold fewer collective latencies, identical
    evolution. Parity against the depth-1 path at awkward turn counts
    (remainder path included), both packings, byte AND packed planes."""

    @pytest.mark.parametrize("depth", [2, 3, 4])
    @pytest.mark.parametrize("word_axis", [0, 1])
    def test_packed_wide_matches_depth1(self, depth, word_axis):
        import jax

        from gol_distributed_final_tpu.parallel.bit_halo import (
            packed_sharding,
            sharded_bit_step_n_fn,
        )

        mesh = make_mesh((2, 4))
        size = 512  # local blocks (8, 128) / (256, 2): depth <= 4 fits
        shape = (size // 32, size) if word_axis == 0 else (size, size // 32)
        rng = np.random.default_rng(31)
        packed = jax.device_put(
            rng.integers(0, 1 << 32, shape, dtype=np.uint64)
            .astype(np.uint32)
            .view(np.int32),
            packed_sharding(mesh),
        )
        base = sharded_bit_step_n_fn(mesh, word_axis=word_axis)
        wide = sharded_bit_step_n_fn(
            mesh, word_axis=word_axis, halo_depth=depth
        )
        for n in (depth, depth * 3 + 1, 1):  # exact, remainder, sub-depth
            np.testing.assert_array_equal(
                np.asarray(wide(packed, n)),
                np.asarray(base(packed, n)),
                err_msg=f"depth={depth} n={n} word_axis={word_axis}",
            )

    def test_packed_wide_beyond_pallas_bound(self):
        """Depths past the pallas sublane bound (8) stay on the XLA local
        step and remain exact: depth 9 at 1024^2 (blocks (16, 256) words,
        so the halo is over half the block) vs the depth-1 path."""
        import jax

        from gol_distributed_final_tpu.parallel.bit_halo import (
            packed_sharding,
            sharded_bit_step_n_fn,
        )

        mesh = make_mesh((2, 4))
        rng = np.random.default_rng(35)
        packed = jax.device_put(
            rng.integers(0, 1 << 32, (32, 1024), dtype=np.uint64)
            .astype(np.uint32)
            .view(np.int32),
            packed_sharding(mesh),
        )
        base = sharded_bit_step_n_fn(mesh)
        deep = sharded_bit_step_n_fn(mesh, halo_depth=9)
        for n in (9, 10):
            np.testing.assert_array_equal(
                np.asarray(deep(packed, n)), np.asarray(base(packed, n))
            )

    @pytest.mark.parametrize("depth", [2, 5])
    def test_byte_wide_matches_depth1(self, depth):
        from gol_distributed_final_tpu.parallel.halo import sharded_step_n_fn

        mesh = make_mesh((2, 4))
        rng = np.random.default_rng(32)
        board = np.where(rng.random((64, 64)) < 0.3, 255, 0).astype(np.uint8)
        base = sharded_step_n_fn(mesh)
        wide = sharded_step_n_fn(mesh, halo_depth=depth)
        for n in (depth * 2, depth * 2 + 1):
            np.testing.assert_array_equal(
                np.asarray(wide(board, n)), np.asarray(base(board, n))
            )

    def test_wide_rejects_bad_depth(self):
        from gol_distributed_final_tpu.parallel.bit_halo import (
            sharded_bit_step_n_fn,
        )

        mesh = make_mesh((2, 4))
        with pytest.raises(ValueError, match="halo_depth"):
            sharded_bit_step_n_fn(mesh, halo_depth=0)
        # the pallas aligned-ext form is bounded by the sublane tile (8):
        # deeper halos must drop to the XLA local step
        with pytest.raises(ValueError, match="pallas"):
            sharded_bit_step_n_fn(mesh, halo_depth=9, pallas_local=True)
        # depth larger than the local block
        import jax

        from gol_distributed_final_tpu.parallel.bit_halo import packed_sharding

        packed = jax.device_put(
            np.zeros((4, 128), np.int32), packed_sharding(mesh)
        )
        step = sharded_bit_step_n_fn(mesh, halo_depth=3)  # local (2, 32)
        with pytest.raises(ValueError, match="exceeds the local block"):
            step(packed, 3)

    @pytest.mark.parametrize("depth", [2, 3, 8])
    def test_pallas_wide_matches_xla_wide(self, depth):
        """Wide halos THROUGH the pallas tiled local step (VERDICT r4
        item 1): the k-word halo rides the same fixed tile-aligned ext
        and the kernel runs k launches on it. Must match both the XLA
        wide path at the same depth and the depth-1 base path, across
        block and torus boundaries, including the remainder path —
        depth 8 is the exact ring-creep boundary (rows pad = 0)."""
        from gol_distributed_final_tpu.parallel.bit_halo import (
            packed_sharding,
            sharded_bit_step_n_fn,
        )

        mesh = make_mesh((2, 4))
        rng = np.random.default_rng(34)
        board = np.where(rng.random((1024, 1024)) < 0.3, 255, 0).astype(np.uint8)
        packed = jax.device_put(
            bitpack.pack(board, 0), packed_sharding(mesh)
        )  # [32, 1024] -> local blocks (16, 256): ext (32, 512) tiles cleanly
        fast_wide = sharded_bit_step_n_fn(
            mesh, pallas_local=True, interpret=True, halo_depth=depth
        )
        xla_wide = sharded_bit_step_n_fn(mesh, halo_depth=depth)
        base = sharded_bit_step_n_fn(mesh)
        for n in (depth, depth * 2 + 1):  # exact and remainder chunking
            got = np.asarray(fast_wide(packed, n))
            np.testing.assert_array_equal(
                got, np.asarray(xla_wide(packed, n)),
                err_msg=f"pallas-wide vs xla-wide, depth={depth} n={n}",
            )
            np.testing.assert_array_equal(
                got, np.asarray(base(packed, n)),
                err_msg=f"pallas-wide vs depth-1, depth={depth} n={n}",
            )

    def test_pallas_wide_auto_routing(self):
        """Auto routing composes the knobs: a past-the-gate block with
        halo_depth <= 8 still routes to pallas; depth > 8 falls back to
        XLA instead of raising."""
        from gol_distributed_final_tpu.parallel.bit_halo import (
            _auto_use_pallas,
            sharded_bit_step_n_fn,
        )

        past_gate = (128, 8192)  # 16384^2 over 4 chips: past the VMEM gate
        assert _auto_use_pallas(1, past_gate, 0, interpret=False)
        assert _auto_use_pallas(8, past_gate, 0, interpret=False)
        # the sublane bound: depth 9 silently stays on XLA...
        assert not _auto_use_pallas(9, past_gate, 0, interpret=False)
        # ...and constructing with auto routing + deep halo must not raise
        sharded_bit_step_n_fn(make_mesh((2, 4)), halo_depth=9)

    @requires_8
    def test_rule_depth_route_composition_property(self):
        """Property: for ANY B/S rule, any halo depth 1..4, and either
        local-step route (XLA / interpreted pallas), the mesh evolution
        equals the single-device bitboard under the same rule — the three
        knobs must compose for the whole rule space, not just Conway
        (extends test_bitpack's rule-space property onto the mesh)."""
        # gate, don't fail: hypothesis is absent from some CI images
        pytest.importorskip("hypothesis")
        from hypothesis import given, settings
        from hypothesis import strategies as st

        from gol_distributed_final_tpu.models import LifeRule
        from gol_distributed_final_tpu.parallel.bit_halo import (
            packed_sharding,
            sharded_bit_step_n_fn,
        )

        mesh = make_mesh((2, 4))
        rng = np.random.default_rng(40)
        board = np.where(rng.random((512, 512)) < 0.35, 255, 0).astype(np.uint8)
        host_packed = bitpack.pack(board, 0)
        packed = jax.device_put(host_packed, packed_sharding(mesh))

        @settings(max_examples=10, deadline=None)
        @given(
            birth=st.sets(st.integers(0, 8)),
            survive=st.sets(st.integers(0, 8)),
            depth=st.integers(1, 4),
            use_pallas=st.booleans(),
        )
        def check(birth, survive, depth, use_pallas):
            bmask = sum(1 << c for c in birth)
            smask = sum(1 << c for c in survive)
            rule = LifeRule(
                f"B{''.join(map(str, sorted(birth)))}"
                f"/S{''.join(map(str, sorted(survive)))}",
                bmask, smask,
            )
            stepn = sharded_bit_step_n_fn(
                mesh, rule,
                pallas_local=use_pallas,
                interpret=True if use_pallas else None,
                halo_depth=depth,
            )
            n = depth + 1  # always exercises the remainder path
            got = np.asarray(stepn(packed, n))
            want = np.asarray(
                bitpack.bit_step_n(host_packed, n, 0, bmask, smask)
            )
            np.testing.assert_array_equal(
                got, want,
                err_msg=f"B{sorted(birth)}/S{sorted(survive)} "
                        f"depth={depth} pallas={use_pallas}",
            )

        check()

    @pytest.mark.parametrize("depth", [2, 3])
    def test_wide_pod_session_golden(self, depth, tmp_path):
        """The knob through the full pod surface: a wide-halo session's
        streamed output is byte-identical to the depth-1 session's."""
        import queue

        from gol_distributed_final_tpu.pod import pod_session

        rng = np.random.default_rng(33)
        board = np.where(rng.random((256, 256)) < 0.3, 255, 0).astype(np.uint8)
        (tmp_path / "256x256.pgm").write_bytes(
            b"P5\n256 256\n255\n" + board.tobytes()
        )
        mesh = make_mesh((2, 4))
        outs = {}
        for d in (1, depth):
            pod_session(
                256, 20, mesh,
                in_path=tmp_path / "256x256.pgm",
                events=queue.Queue(),
                tick_seconds=3600,
                out_dir=tmp_path / f"out{d}",
                min_chunk=4, max_chunk=4,
                halo_depth=d,
            )
            outs[d] = (tmp_path / f"out{d}" / "256x256x20.pgm").read_bytes()
        assert outs[1] == outs[depth]
