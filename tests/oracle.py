"""Independent pure-NumPy oracle for life-like automata, used only by tests.

Deliberately implemented differently from both the framework's XLA stencil
and the reference's Go kernel: modular index arithmetic over an explicit
neighbour loop, no rolls, no masks.
"""

import numpy as np


def naive_step(board: np.ndarray, birth=(3,), survive=(2, 3)) -> np.ndarray:
    h, w = board.shape
    out = np.zeros_like(board)
    for y in range(h):
        for x in range(w):
            n = 0
            for dy in (-1, 0, 1):
                for dx in (-1, 0, 1):
                    if dy == 0 and dx == 0:
                        continue
                    if board[(y + dy) % h, (x + dx) % w] != 0:
                        n += 1
            if board[y, x] != 0:
                out[y, x] = 255 if n in survive else 0
            else:
                out[y, x] = 255 if n in birth else 0
    return out


def vector_step(board: np.ndarray, birth=(3,), survive=(2, 3)) -> np.ndarray:
    """Faster vectorised oracle (np.roll) for multi-turn parity runs."""
    ones = (board != 0).astype(np.int32)
    n = np.zeros_like(ones)
    for dy in (-1, 0, 1):
        for dx in (-1, 0, 1):
            if (dy, dx) == (0, 0):
                continue
            n += np.roll(ones, (dy, dx), axis=(0, 1))
    alive = board != 0
    nxt = np.where(alive, np.isin(n, survive), np.isin(n, birth))
    return np.where(nxt, 255, 0).astype(np.uint8)
