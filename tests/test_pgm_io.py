"""PGM codec tests (reference behavior: gol/io.go:42-126)."""

import numpy as np
import pytest

from gol_distributed_final_tpu import Params
from gol_distributed_final_tpu.io.pgm import (
    PgmError,
    PgmReader,
    PgmWriter,
    read_board,
    read_pgm,
    write_board,
    write_pgm,
)


def test_roundtrip(tmp_path):
    board = np.where(np.random.default_rng(0).random((17, 23)) < 0.5, 255, 0).astype(np.uint8)
    p = tmp_path / "b.pgm"
    write_pgm(p, board)
    np.testing.assert_array_equal(read_pgm(p), board)


def test_header_format(tmp_path):
    board = np.zeros((4, 6), np.uint8)
    p = tmp_path / "b.pgm"
    write_pgm(p, board)
    raw = p.read_bytes()
    assert raw.startswith(b"P5\n6 4\n255\n")
    assert len(raw) == len(b"P5\n6 4\n255\n") + 24


@pytest.mark.parametrize(
    "content,msg",
    [
        (b"P2\n2 2\n255\n...", "Not a pgm file"),
        (b"P5\n2 2\n255\n" + bytes(4), None),  # valid
        (b"P5\n2 2\n254\n" + bytes(4), "Incorrect maxval/bit depth"),
        (b"junk", "Not a pgm file"),
        (b"", "Not a pgm file"),
    ],
)
def test_validation_messages(tmp_path, content, msg):
    p = tmp_path / "x.pgm"
    p.write_bytes(content)
    if msg is None:
        assert read_pgm(p).shape == (2, 2)
    else:
        with pytest.raises(PgmError, match=msg):
            read_pgm(p)


def test_dimension_validation(tmp_path):
    p = tmp_path / "x.pgm"
    p.write_bytes(b"P5\n3 2\n255\n" + bytes(6))
    with pytest.raises(PgmError, match="Incorrect width"):
        read_pgm(p, expect_width=4)
    with pytest.raises(PgmError, match="Incorrect height"):
        read_pgm(p, expect_height=4, expect_width=3)


def test_comments_in_header(tmp_path):
    p = tmp_path / "c.pgm"
    p.write_bytes(b"P5\n# a comment\n2 2\n255\n" + bytes([1, 2, 3, 4]))
    np.testing.assert_array_equal(read_pgm(p), [[1, 2], [3, 4]])


def test_streamed_rows(tmp_path):
    board = np.arange(64, dtype=np.uint8).reshape(8, 8)
    p = tmp_path / "s.pgm"
    write_pgm(p, board)
    with PgmReader(p) as r:
        np.testing.assert_array_equal(r.read_rows(2, 5), board[2:5])
        np.testing.assert_array_equal(r.read_rows(0, 0), board[0:0])
        with pytest.raises(PgmError):
            r.read_rows(5, 9)


def test_streamed_writer_enforces_shape(tmp_path):
    p = tmp_path / "w.pgm"
    with pytest.raises(PgmError, match="wrote 2 rows"):
        with PgmWriter(p, width=4, height=3) as w:
            w.write_rows(np.zeros((2, 4), np.uint8))
    with pytest.raises(PgmError, match="does not match width"):
        with PgmWriter(tmp_path / "w2.pgm", width=4, height=3) as w:
            w.write_rows(np.zeros((3, 5), np.uint8))


def test_board_conventions(tmp_path, images_dir):
    # images/<W>x<H>.pgm in, out/<W>x<H>x<T>.pgm out (gol/distributor.go:144,165)
    p = Params(turns=7, image_width=16, image_height=16)
    board = read_board(p, images_dir)
    assert board.shape == (16, 16)
    out = write_board(board, p.output_filename, tmp_path / "out")
    assert out.name == "16x16x7.pgm"
    np.testing.assert_array_equal(read_pgm(out), board)


def test_truncated_raster(tmp_path):
    p = tmp_path / "t.pgm"
    p.write_bytes(b"P5\n4 4\n255\n" + bytes(10))
    with pytest.raises(PgmError):
        read_pgm(p)
