"""The static-analysis suite (gol_distributed_final_tpu/analysis/).

Fixture-snippet corpus: every checker proves it FIRES on its positives
and stays QUIET on its negatives; suppression semantics (inline +
standalone, mandatory justification, unknown ids); finding file:line
exactness; the walker's skip/parse-failure contract; the obs/lint
re-seat; and the self-host gate — the shipped tree must analyze clean.

No jax import anywhere: the analyzer is dependency-free by contract.
"""

import ast
import json
import textwrap

import pytest

from gol_distributed_final_tpu.analysis import (
    all_checkers,
    ast_checkers,
    core,
)
from gol_distributed_final_tpu.analysis.__main__ import PACKAGE_ROOT, main
from gol_distributed_final_tpu.analysis.hygiene import HygieneChecker
from gol_distributed_final_tpu.analysis.jit import JitCacheChecker
from gol_distributed_final_tpu.analysis.locks import LockDisciplineChecker
from gol_distributed_final_tpu.analysis.skew import SkewSafetyChecker


def findings_for(checker, src, relpath="rpc/mod.py"):
    """Unsuppressed findings from one checker over one snippet."""
    found, _sup = core.analyze_source(
        textwrap.dedent(src), relpath, [checker]
    )
    return [f for f in found if f.check == checker.id]


def analyze(src, checkers=None, relpath="rpc/mod.py"):
    return core.analyze_source(
        textwrap.dedent(src),
        relpath,
        ast_checkers() if checkers is None else checkers,
    )


# -- skew-safety -------------------------------------------------------------


class TestSkewSafety:
    def test_positive_raw_extension_read(self):
        found = findings_for(SkewSafetyChecker(), """
            def handler(req):
                return req.halo_depth
        """)
        assert len(found) == 1
        assert "halo_depth" in found[0].message

    def test_positive_getattr_without_default(self):
        found = findings_for(SkewSafetyChecker(), """
            def handler(res):
                return getattr(res, "digests")
        """)
        assert len(found) == 1
        assert "no default" in found[0].message

    def test_positive_unguarded_dict_read(self):
        found = findings_for(SkewSafetyChecker(), """
            def poll(reply):
                return reply["oob"]
        """)
        assert len(found) == 1
        assert ".get" in found[0].message

    def test_negative_defaulted_getattr_and_base_fields(self):
        found = findings_for(SkewSafetyChecker(), """
            def handler(req):
                depth = getattr(req, "halo_depth", 0)
                return req.turns + req.worker + depth
        """)
        assert found == []

    def test_negative_store_is_send_path(self):
        found = findings_for(SkewSafetyChecker(), """
            def send(req):
                req.initial_turn = 7
                req.rulestring = "B3/S23"
        """)
        assert found == []

    def test_negative_guarded_dict_read(self):
        found = findings_for(SkewSafetyChecker(), """
            def poll(reply):
                if "error" in reply:
                    raise RuntimeError(reply["error"])
                return reply.get("status")
        """)
        assert found == []

    def test_dict_rule_scoped_to_rpc_obs(self):
        src = """
            def poll(reply):
                return reply["result"]
        """
        assert findings_for(SkewSafetyChecker(), src, "rpc/x.py")
        assert findings_for(SkewSafetyChecker(), src, "obs/x.py")
        assert not findings_for(SkewSafetyChecker(), src, "engine/x.py")

    def test_guard_inherited_by_closure(self):
        found = findings_for(SkewSafetyChecker(), """
            def poll(reply):
                if "error" in reply:
                    def fail():
                        return reply["error"]
                    return fail
        """)
        assert found == []

    def test_extension_fields_parsed_from_protocol(self):
        # the checker's field sets self-update from rpc/protocol.py's
        # own AST: every declared dataclass field beyond the Go-mirror
        # base set is an extension field
        import dataclasses

        from gol_distributed_final_tpu.analysis import skew
        from gol_distributed_final_tpu.rpc import protocol

        checker = SkewSafetyChecker()
        req_fields = {f.name for f in dataclasses.fields(protocol.Request)}
        res_fields = {f.name for f in dataclasses.fields(protocol.Response)}
        assert checker.request_ext == req_fields - skew.REQUEST_BASE
        assert checker.response_ext == res_fields - skew.RESPONSE_BASE
        assert "session_id" in checker.request_ext
        assert "digests" in checker.response_ext


# -- lock-discipline ---------------------------------------------------------


class TestLockDiscipline:
    def test_positive_unlocked_read(self):
        found = findings_for(LockDisciplineChecker(), """
            class Ring:
                _GUARDED_BY = {"_ring": "_lock"}

                def peek(self):
                    return self._ring[0]
        """)
        assert len(found) == 1
        assert "_ring" in found[0].message and "peek" in found[0].message

    def test_positive_comment_declared_guard(self):
        found = findings_for(LockDisciplineChecker(), """
            class Ring:
                def __init__(self):
                    self._items = []  # guarded-by: _lock

                def drop(self):
                    self._items.clear()
        """)
        assert len(found) == 1
        assert "_items" in found[0].message

    def test_positive_nested_function_releases_lock(self):
        # a thread target defined under 'with' runs AFTER release
        found = findings_for(LockDisciplineChecker(), """
            class Ring:
                _GUARDED_BY = {"_ring": "_lock"}

                def kick(self):
                    with self._lock:
                        def later():
                            return list(self._ring)
                    return later
        """)
        assert len(found) == 1

    def test_negative_access_under_lock_and_init(self):
        found = findings_for(LockDisciplineChecker(), """
            class Ring:
                _GUARDED_BY = {"_ring": "_lock"}

                def __init__(self):
                    self._ring = []

                def push(self, x):
                    with self._lock:
                        self._ring.append(x)
        """)
        assert found == []

    def test_negative_condition_alias(self):
        found = findings_for(LockDisciplineChecker(), """
            class Sched:
                _GUARDED_BY = {"_table": ("_lock", "_work")}

                def submit(self):
                    with self._work:
                        return self._table
        """)
        assert found == []

    def test_annotated_declaration_still_enforced(self):
        # `_GUARDED_BY: ClassVar[dict] = {...}` must not silently
        # disable the contract
        found = findings_for(LockDisciplineChecker(), """
            class Ring:
                _GUARDED_BY: dict = {"_ring": "_lock"}

                def peek(self):
                    return self._ring[0]
        """)
        assert len(found) == 1

    def test_unparsable_declaration_is_loud(self):
        # a _GUARDED_BY the checker cannot read is a finding, never a
        # silently-ignored contract
        found = findings_for(LockDisciplineChecker(), """
            class Ring:
                _GUARDED_BY = build_guard_map()

                def peek(self):
                    return self._ring[0]
        """)
        assert len(found) == 1
        assert "cannot read" in found[0].message

    def test_negative_holds_marker(self):
        found = findings_for(LockDisciplineChecker(), """
            class Ring:
                _GUARDED_BY = {"_ring": "_lock"}

                def _rings(self):  # gol: holds(_lock)
                    return list(self._ring)
        """)
        assert found == []


# -- jit-cache ---------------------------------------------------------------


class TestJitCache:
    def test_positive_min_derived_turn_arg(self):
        found = findings_for(JitCacheChecker(), """
            def drive(plane, state, budgets):
                k = min(budgets)
                return plane.step_n(state, k)
        """)
        assert len(found) == 1
        assert "un-quantised" in found[0].message

    def test_positive_arithmetic_inline(self):
        found = findings_for(JitCacheChecker(), """
            def drive(plane, state, total, done):
                return plane.step_n(state, total - done)
        """)
        assert len(found) == 1

    def test_positive_time_in_jitted_body(self):
        found = findings_for(JitCacheChecker(), """
            import time

            @jax.jit
            def run(board):
                t = time.monotonic()
                return board, t
        """)
        assert len(found) == 1
        assert "trace time" in found[0].message

    def test_positive_item_in_kernel_body(self):
        found = findings_for(JitCacheChecker(), """
            def _bit_kernel(ref, out):
                n = ref[0].item()
                out[:] = n
        """)
        assert len(found) == 1
        assert ".item()" in found[0].message

    def test_positive_wrapper_call_does_not_launder(self):
        # int()/abs()/round() around a min() is the same unbounded-key
        # hazard as the bare min()
        found = findings_for(JitCacheChecker(), """
            def drive(plane, state, budgets, cap):
                n = int(min(budgets, cap))
                return plane.step_n(state, n)
        """)
        assert len(found) == 1

    def test_negative_quantised_and_constant(self):
        # the session-batcher idiom: derive raw, quantise in place
        found = findings_for(JitCacheChecker(), """
            def drive(plane, state, budgets, cap):
                k = min(min(budgets), cap)
                if k > 2:
                    k = 1 << (k.bit_length() - 1)
                plane.step_n(state, k)
                return plane.step_n(state, 64)
        """)
        assert found == []

    def test_negative_parameter_passthrough(self):
        found = findings_for(JitCacheChecker(), """
            def step_many(plane, state, n):
                return plane.step_n(state, n)
        """)
        assert found == []

    def test_negative_host_calls_outside_kernels(self):
        found = findings_for(JitCacheChecker(), """
            import time

            def bench(board):
                t0 = time.monotonic()
                return board.item(), time.monotonic() - t0
        """)
        assert found == []


# -- hygiene -----------------------------------------------------------------


class TestHygiene:
    def test_positive_undaemonised_thread(self):
        found = findings_for(HygieneChecker(), """
            import threading

            def serve():
                threading.Thread(target=loop).start()
        """)
        assert len(found) == 1
        assert "daemon=True" in found[0].message

    def test_positive_silent_broad_except(self):
        found = findings_for(HygieneChecker(), """
            def close(sock):
                try:
                    sock.close()
                except Exception:
                    pass
        """)
        assert len(found) == 1
        assert "swallows" in found[0].message

    def test_positive_bare_except_assignment_only(self):
        found = findings_for(HygieneChecker(), """
            def probe():
                try:
                    return 1
                except:
                    ok = False
        """)
        assert len(found) == 1

    def test_positive_join_in_another_class_is_no_proof(self):
        # the join must live in the binding's OWNING scope: class A
        # joining its own self._thread must not exempt class B's
        # never-joined thread of the same conventional name
        found = findings_for(HygieneChecker(), """
            import threading

            class A:
                def start(self):
                    self._thread = threading.Thread(target=run)

                def stop(self):
                    self._thread.join()

            class B:
                def start(self):
                    self._thread = threading.Thread(target=run)
                    self._thread.start()
        """)
        assert len(found) == 1
        assert "threading.Thread" in found[0].message

    def test_negative_self_thread_joined_in_sibling_method(self):
        found = findings_for(HygieneChecker(), """
            import threading

            class A:
                def start(self):
                    self._thread = threading.Thread(target=run)

                def stop(self):
                    self._thread.join()
        """)
        assert found == []

    def test_negative_daemon_or_joined(self):
        found = findings_for(HygieneChecker(), """
            import threading

            def serve():
                threading.Thread(target=loop, daemon=True).start()
                consumer = threading.Thread(target=drain)
                consumer.start()
                consumer.join()
        """)
        assert found == []

    def test_negative_handled_excepts(self):
        found = findings_for(HygieneChecker(), """
            def close(sock):
                try:
                    sock.close()
                except OSError:
                    pass  # narrow type: fine
                try:
                    sock.close()
                except Exception:
                    logger.warning("close failed")
                try:
                    sock.close()
                except Exception as exc:
                    err = exc  # captured for the agreement vote
        """)
        assert found == []


# -- suppressions ------------------------------------------------------------


class TestSuppressions:
    SRC = """
        def handler(req):
            return req.halo_depth  # gol: allow(skew-safety): fixture reason
    """

    def test_inline_suppression_hides_and_records(self):
        found, suppressed = analyze(self.SRC)
        assert found == []
        assert [f.check for f in suppressed] == ["skew-safety"]

    def test_standalone_comment_applies_to_next_code_line(self):
        found, suppressed = analyze("""
            def handler(req):
                # gol: allow(skew-safety): fixture reason
                return req.halo_depth
        """)
        assert found == []
        assert len(suppressed) == 1

    def test_missing_justification_is_a_finding(self):
        found, _sup = analyze("""
            def handler(req):
                return req.halo_depth  # gol: allow(skew-safety)
        """)
        assert [f.check for f in found] == [core.CHECK_SUPPRESSION]
        assert "justification" in found[0].message

    def test_unknown_check_id_is_a_finding(self):
        found, _sup = analyze("""
            def handler(req):
                return req.turns  # gol: allow(not-a-check): why
        """)
        assert [f.check for f in found] == [core.CHECK_SUPPRESSION]
        assert "not-a-check" in found[0].message

    def test_wrong_id_does_not_hide(self):
        found, _sup = analyze("""
            def handler(req):
                return req.halo_depth  # gol: allow(hygiene): wrong checker
        """)
        assert "skew-safety" in [f.check for f in found]

    def test_trailing_allow_on_multiline_statement_covers_its_start(self):
        # findings anchor at the statement's first line; the allow on
        # its closing line must still hide them
        found, suppressed = analyze("""
            def handler(res):
                edges = getattr(
                    res,
                    "edges",
                )  # gol: allow(skew-safety): validated upstream
                return edges
        """)
        assert found == []
        assert [f.check for f in suppressed] == ["skew-safety"]

    def test_allow_on_compound_header_does_not_mute_body(self):
        found, _sup = analyze("""
            def handler(req):
                if req.turns:  # gol: allow(skew-safety): header only
                    return req.halo_depth
        """)
        assert [f.check for f in found] == ["skew-safety"]
        assert found[0].line == 4

    def test_allow_syntax_in_docstring_is_inert(self):
        found, suppressed = analyze('''
            def handler(req):
                """Suppress with '# gol: allow(skew-safety): why'."""
                return req.turns
        ''')
        assert found == [] and suppressed == []


# -- framework contracts -----------------------------------------------------


class TestFramework:
    def test_finding_line_exactness(self):
        src = textwrap.dedent("""
            class Ring:
                _GUARDED_BY = {"_ring": "_lock"}

                def peek(self):
                    x = 1
                    return self._ring[0]
        """)
        found, _ = core.analyze_source(src, "obs/x.py", ast_checkers())
        assert len(found) == 1
        # dedented source: line 1 is blank, class on 2 ... return on 7
        assert (found[0].path, found[0].line) == ("obs/x.py", 7)
        assert found[0].location == "obs/x.py:7"
        line = src.splitlines()[found[0].line - 1]
        assert "self._ring[0]" in line

    def test_parse_failure_is_loud(self, tmp_path):
        (tmp_path / "bad.py").write_text("def broken(:\n")
        (tmp_path / "good.py").write_text("x = 1\n")
        report = core.run(tmp_path, checkers=ast_checkers(), with_repo=False)
        assert not report.clean
        assert [f.check for f in report.findings] == [core.CHECK_PARSE]
        assert report.findings[0].path == "bad.py"

    def test_non_utf8_source_is_a_loud_finding_not_a_crash(self, tmp_path):
        # PEP 263 latin-1 source decodes fine; a file that lies about
        # its encoding becomes a parse-failure finding, never a traceback
        (tmp_path / "latin.py").write_bytes(
            b"# -*- coding: latin-1 -*-\nname = '\xe9'\n"
        )
        (tmp_path / "liar.py").write_bytes(
            b"# -*- coding: utf-8 -*-\nname = '\xe9'\n"
        )
        report = core.run(tmp_path, checkers=ast_checkers(), with_repo=False)
        assert [f.check for f in report.findings] == [core.CHECK_PARSE]
        assert report.findings[0].path == "liar.py"
        assert report.files == 1  # latin.py analyzed fine

    def test_repo_checkers_survive_missing_readme(self, tmp_path):
        # a fixture tree without README.md: every repo checker reports a
        # finding instead of crashing the run with FileNotFoundError
        (tmp_path / "mod.py").write_text("x = 1\n")
        report = core.run(tmp_path, with_repo=True)
        assert all(
            f.check.startswith("lint-") or f.check == core.CHECK_PARSE
            for f in report.findings
        )
        assert not report.clean  # missing docs are findings, loudly

    def test_walker_skips_native_and_generated(self, tmp_path):
        (tmp_path / "native").mkdir()
        (tmp_path / "native" / "broken.py").write_text("def (:\n")
        (tmp_path / "gen.py").write_text(
            "# @generated by tool\ndef broken(:\n"
        )
        (tmp_path / "ok.py").write_text("x = 1\n")
        report = core.run(tmp_path, checkers=ast_checkers(), with_repo=False)
        assert report.clean
        assert report.files == 1  # only ok.py analyzed

    def test_duplicate_findings_dedupe(self):
        found, _ = analyze("""
            def relay(res):
                return res.edges[:4], res.edges[4:]
        """)
        assert len([f for f in found if f.check == "skew-safety"]) == 1

    def test_report_json_round_trip(self, tmp_path):
        (tmp_path / "mod.py").write_text("import threading\n")
        report = core.run(tmp_path, checkers=ast_checkers(), with_repo=False)
        blob = json.loads(json.dumps(report.to_json()))
        assert blob["clean"] is True
        assert set(blob["checks"]) == {c.id for c in ast_checkers()}

    def test_checker_registry_ids_unique_and_documented(self):
        checkers = all_checkers()
        ids = [c.id for c in checkers]
        assert len(ids) == len(set(ids))
        for c in checkers:
            assert c.id and c.description and c.bug_class


# -- obs/lint re-seat --------------------------------------------------------


class TestLintReseat:
    def test_every_lint_check_is_a_checker(self):
        from gol_distributed_final_tpu.obs.lint import CHECKS

        lint_ids = {c.id for c in all_checkers() if c.id.startswith("lint-")}
        assert {check_id for check_id, *_ in CHECKS} <= lint_ids
        assert "lint-analysis-docs" in lint_ids

    def test_reseated_checker_reports_what_lint_reports(self, tmp_path):
        # a README missing a documented metric name: the wrapped checker
        # must surface exactly the names the obs.lint function returns
        from gol_distributed_final_tpu.analysis.lints import readme_checkers
        from gol_distributed_final_tpu.obs import lint as obs_lint

        readme = tmp_path / "README.md"
        readme.write_text("# empty\n")
        missing = obs_lint.undocumented_wire_metrics(readme_path=readme)
        assert missing  # the fixture README documents nothing
        checker = next(
            c for c in readme_checkers() if c.id == "lint-wire-metrics"
        )
        got = list(checker.check_tree(tmp_path))
        assert {f.message.rsplit(" ", 1)[-1] for f in got} == set(missing)
        assert all(f.path == "README.md" for f in got)


# -- CLI ---------------------------------------------------------------------


class TestCli:
    def test_exit_zero_and_json_artifact_on_clean_tree(
        self, tmp_path, capsys, monkeypatch
    ):
        (tmp_path / "mod.py").write_text("x = 1\n")
        monkeypatch.chdir(tmp_path)
        rc = main([str(tmp_path), "--no-lint", "-json", "-out", "artifacts"])
        assert rc == 0
        blob = json.loads(capsys.readouterr().out)
        assert blob["clean"] is True
        on_disk = json.loads(
            (tmp_path / "artifacts" / "analysis.json").read_text()
        )
        assert on_disk == blob

    def test_exit_nonzero_on_finding(self, tmp_path, capsys):
        (tmp_path / "rpc").mkdir()
        (tmp_path / "rpc" / "mod.py").write_text(
            "def f(req):\n    return req.halo_depth\n"
        )
        rc = main([str(tmp_path), "--no-lint"])
        assert rc == 1
        assert "skew-safety" in capsys.readouterr().out

    def test_checks_filter_and_list(self, capsys):
        rc = main(["--list", "--checks", "hygiene"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "hygiene" in out and "skew-safety" not in out

    def test_unknown_check_id_is_usage_error(self):
        with pytest.raises(SystemExit) as exc:
            main(["--checks", "nope"])
        assert exc.value.code == 2

    def test_checks_filter_keeps_other_suppression_ids_known(self, capsys):
        # a --checks-filtered run must not turn the tree's justified
        # suppressions naming OTHER checkers into format findings
        rc = main(["--checks", "jit-cache", "--no-lint"])
        out = capsys.readouterr().out
        assert rc == 0, out
        assert "unknown check id" not in out

    def test_single_file_target_keeps_path_scope(self, tmp_path, capsys):
        # a single-file target inside a package must keep its rpc/
        # path segment, so the path-scoped dict rule still applies and
        # the finding location stays clickable
        pkg = tmp_path / "pkg"
        (pkg / "rpc").mkdir(parents=True)
        (pkg / "__init__.py").write_text("")
        (pkg / "rpc" / "__init__.py").write_text("")
        target = pkg / "rpc" / "mod.py"
        target.write_text("def f(reply):\n    return reply['oob']\n")
        rc = main([str(target), "--no-lint"])
        out = capsys.readouterr().out
        assert rc == 1
        assert "pkg/rpc/mod.py:2" in out and "skew-safety" in out


# -- self-host ---------------------------------------------------------------


class TestSelfHost:
    def test_shipped_tree_analyzes_clean(self):
        """The acceptance gate: the whole package — AST checkers AND the
        re-seated README lints — exits clean, with every suppression
        carrying a justification (a justification-less allow is itself a
        finding, so a clean report proves the allow-list is auditable)."""
        report = core.run(PACKAGE_ROOT)
        assert report.clean, "\n" + report.render()
        # the tree genuinely exercises the suppression machinery
        assert report.suppressed, "expected justified suppressions in-tree"
        assert report.files > 50

    def test_self_host_covers_every_ast_checker(self):
        # the fixture corpus proves each checker can fire; the shipped
        # tree proves each stays quiet — both directions of the contract
        report = core.run(PACKAGE_ROOT, checkers=ast_checkers(),
                          with_repo=False)
        assert report.clean
