"""Tenant-scoped serving observability (obs/accounting.py, obs/canary.py,
obs/loadgen.py): ledger math + bounded cardinality, chunk-boundary
attribution reconciling exactly with the session meters, structured
reject reasons on the client exception, incremental + version-skew-safe
Status accounting windows under the documented size budget, the canary's
bit-exact probe (and its detection of an injected wrong-board fault),
the canary-failure SLO rule, the open-loop load generator, the watch
TENANTS panel, and the doctor's tenant-skew finding.
"""

import pickle
import threading
import time

import numpy as np
import pytest

from gol_distributed_final_tpu.obs import accounting as obs_accounting
from gol_distributed_final_tpu.obs import metrics as obs_metrics
from gol_distributed_final_tpu.obs import timeline as obs_timeline
from gol_distributed_final_tpu.obs.accounting import (
    TenantLedger,
    make_tag,
    tenant_of,
)


@pytest.fixture
def live_metrics():
    """Enable the registry + zero the global ledger for one test (the
    test_slo.py posture, extended to the accounting global)."""
    reg = obs_metrics.registry()
    reg.reset()
    obs_accounting.ledger().reset()
    obs_metrics.enable()
    yield reg
    obs_metrics.enable(False)
    reg.reset()
    obs_accounting.ledger().reset()


# -- the tag convention ------------------------------------------------------


def test_tenant_of_convention():
    # high 32 bits = tenant; low bits = nonce
    assert tenant_of(make_tag(7, 123)) == "7"
    assert tenant_of(make_tag(7, 999)) == "7"
    # a zero nonce is forced nonzero so the tag never collapses to 0
    assert make_tag(7, 0) != 7 << 32
    assert tenant_of(make_tag(7, 0)) == "7"
    # a pre-convention small tag is its own tenant
    assert tenant_of(42) == "42"
    # untagged / invalid degrade to the "-" tenant, never raise
    assert tenant_of(0) == "-"
    assert tenant_of(None) == "-"
    assert tenant_of(-3) == "-"


# -- ledger math -------------------------------------------------------------


def test_ledger_records_and_totals(live_metrics):
    led = TenantLedger(top_k=4)
    led.record_admit("a", 0.5, 100)
    led.record_chunk(["a", "a", "b"], 4, 0.9)  # 0.3 s + 4 turns each
    led.record_reject("b", "capacity")
    led.record_reject("b", "capacity")
    led.record_error("a")
    led.record_reply_bytes("a", 50)
    win = led.window()
    by = {e["tenant"]: e for e in win["tenants"]}
    assert by["a"]["sessions"] == 1
    assert by["a"]["wire_bytes"] == 150
    assert by["a"]["turns"] == 8
    assert by["a"]["device_seconds"] == pytest.approx(0.6)
    assert by["a"]["errors"] == 1
    assert by["b"]["rejects"] == {"capacity": 2}
    assert by["b"]["rejects_total"] == 2
    totals = win["totals"]
    assert totals["turns"] == 12
    assert totals["device_seconds"] == pytest.approx(0.9)
    assert totals["rejects"] == 2 and totals["errors"] == 1
    # sorted by device-seconds descending
    assert win["tenants"][0]["tenant"] == "a"


def test_ledger_disabled_registry_is_noop():
    obs_metrics.enable(False)
    led = TenantLedger()
    led.record_admit("a", 0.1, 10)
    led.record_chunk(["a"], 1, 0.1)
    assert not led.has_data
    assert led.window()["tenants"] == []


def test_ledger_bounded_cardinality(live_metrics):
    """A tag flood must not grow memory: top_k tracked, the rest fold
    into ONE 'other' bucket whose aggregates keep the totals exact."""
    led = TenantLedger(top_k=8)
    for i in range(50):
        led.record_admit(f"t{i}", 0.0, 1)
    win = led.window()
    assert win["tracked"] == 8 and len(win["tenants"]) == 8
    other = win["other"]
    assert other["sessions"] == 42
    assert other["distinct_tenants"] == 42
    assert win["totals"]["sessions"] == 50
    assert win["totals"]["wire_bytes"] == 50
    # distinct counts TENANTS, not records: one noisy overflow tenant
    # hammering the ledger must still read as ONE tenant
    for _ in range(30):
        led.record_admit("t49", 0.0, 1)
        led.record_chunk(["t49"], 1, 0.001)
    assert led.window()["other"]["distinct_tenants"] == 42
    # ...and the distinct set is itself bounded (8 x top_k): a tag flood
    # saturates the reading instead of growing memory
    for i in range(5000):
        led.record_admit(f"flood{i}", 0.0, 1)
    assert led.window()["other"]["distinct_tenants"] == 8 * 8
    assert led.window()["totals"]["sessions"] == 50 + 30 + 5000


def test_ledger_incremental_window(live_metrics):
    led = TenantLedger()
    led.record_admit("a", 0.0, 1)
    seq1 = led.seq
    led.record_admit("b", 0.0, 1)
    win = led.window(since=seq1)
    names = [e["tenant"] for e in win["tenants"]]
    assert names == ["b"]  # only the tenant that changed since seq1
    assert win["totals"]["sessions"] == 2  # totals always ride
    assert led.window(since=led.seq)["tenants"] == []


# -- chunk-boundary attribution (engine/sessions.py) -------------------------


def test_session_table_attributes_chunks(live_metrics):
    """The ledger's device-seconds/turns must reconcile EXACTLY with
    gol_session_turn_seconds' sum and gol_session_turns_total — same
    chunk walls, split per tenant."""
    from gol_distributed_final_tpu.engine.sessions import SessionTable
    from gol_distributed_final_tpu.obs.status import scalar_value, series_map

    rng = np.random.default_rng(0)
    table = SessionTable(shape=(16, 16), capacity=8)
    boards = [
        np.where(rng.random((16, 16)) < 0.3, 255, 0).astype(np.uint8)
        for _ in range(4)
    ]
    for i, b in enumerate(boards):
        table.admit(b, 12, tenant=f"t{i % 2}")
    while table.advance():
        pass
    snap = obs_metrics.registry().snapshot()
    win = obs_accounting.ledger().window()
    totals = win["totals"]
    assert totals["turns"] == int(
        scalar_value(snap, "gol_session_turns_total")
    ) == 4 * 12
    hist = series_map(snap, "gol_session_turn_seconds").get(())
    # abs tolerance = the window's round(…, 6) quantum
    assert totals["device_seconds"] == pytest.approx(
        hist["sum"], rel=1e-6, abs=1e-6
    )
    by = {e["tenant"]: e for e in win["tenants"]}
    assert by["t0"]["turns"] == by["t1"]["turns"] == 24


# -- the serving surface (scheduler + structured rejects) --------------------


def _serve_loopback(**kw):
    from gol_distributed_final_tpu.rpc.broker import serve

    server, service = serve(port=0, **kw)
    return server, service, f"127.0.0.1:{server.port}"


def test_scheduler_attribution_and_reject_reason(live_metrics):
    """Live loopback: tenant-packed SessionRuns attribute per tenant;
    a capacity refusal reaches the client as RpcError with
    kind == 'SessionRejected' AND the STRUCTURED reason (no string
    matching) — and the ledger books the reject to the tenant."""
    from gol_distributed_final_tpu.params import Params
    from gol_distributed_final_tpu.rpc.client import RemoteBroker, RpcError

    server, service, addr = _serve_loopback(session_capacity=2)
    rng = np.random.default_rng(1)
    board = np.where(rng.random((16, 16)) < 0.3, 255, 0).astype(np.uint8)
    params = Params(turns=400, image_width=16, image_height=16, threads=1)
    try:
        brokers = [RemoteBroker(addr, timeout=30.0) for _ in range(3)]
        results, errors = [], []

        def run(i):
            try:
                results.append(
                    brokers[i].session_run(
                        params, board, session_id=make_tag(10 + i, i + 1)
                    )
                )
            except RpcError as exc:
                errors.append(exc)

        # fill the two capacity slots first so the third is refused
        threads = []
        for i in range(2):
            t = threading.Thread(target=run, args=(i,), daemon=True)
            t.start()
            threads.append(t)
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            active = obs_metrics.registry().gauge("gol_sessions_active").value
            if active >= 2:
                break
            time.sleep(0.01)
        run(2)  # over capacity: refused synchronously
        for t in threads:
            t.join(timeout=60.0)
        assert len(results) == 2 and len(errors) == 1
        err = errors[0]
        assert err.kind == "SessionRejected"
        assert err.reason == "capacity"  # the structured reject reason
        # a COMPLETED tag keeps serving its final snapshot (the bounded
        # finished cache) — a trailing poller never eats an error reply
        snap = brokers[0].retrieve(session_id=make_tag(10, 1))
        assert snap.turns_completed == 400
        assert snap.world is not None and snap.world.shape == (16, 16)
        # ...but a tag never admitted is still a loud error
        with pytest.raises(RpcError, match="no session"):
            brokers[0].retrieve(session_id=999999)
        win = obs_accounting.ledger().window()
        by = {e["tenant"]: e for e in win["tenants"]}
        assert by["12"]["rejects"] == {"capacity": 1}
        assert by["10"]["sessions"] == 1 and by["11"]["sessions"] == 1
        assert by["10"]["turns"] == 400
        # board bytes both ways: 256 in + 256 out
        assert by["10"]["wire_bytes"] == 512
        for b in brokers:
            b.close()
    finally:
        service._shutdown()


# -- Status payload: incremental, skew-safe, size-budgeted -------------------


def test_status_accounting_window_and_skew(live_metrics):
    from gol_distributed_final_tpu.rpc.client import RpcClient
    from gol_distributed_final_tpu.rpc.protocol import Methods, Request

    led = obs_accounting.ledger()
    led.record_admit("7", 0.01, 64)
    seq = led.seq
    led.record_admit("8", 0.01, 64)
    server, service, addr = _serve_loopback()
    client = RpcClient(addr)
    try:
        res = client.call(
            Methods.STATUS, Request(accounting_since=seq)
        )
        acct = res.status["accounting"]
        assert [e["tenant"] for e in acct["tenants"]] == ["8"]
        assert acct["totals"]["sessions"] == 2
        # a version-skewed client whose pickle predates accounting_since
        # gets the FULL ledger, never an AttributeError reply
        old = Request()
        del old.__dict__["accounting_since"]
        res = client.call(Methods.STATUS, old)
        assert len(res.status["accounting"]["tenants"]) == 2
        # hostile non-int degrades to the full window, not a crash
        bad = Request()
        bad.accounting_since = "not-a-seq"
        res = client.call(Methods.STATUS, bad)
        assert len(res.status["accounting"]["tenants"]) == 2
    finally:
        client.close()
        service._shutdown()


def test_status_payload_size_budget(live_metrics):
    """The documented budget (README "Accounting & capacity"): an
    INCREMENTAL Status reply — timeline echo + alerts + accounting at
    top-K=16 tenants — stays under 64 KiB."""
    from gol_distributed_final_tpu.obs.report import status_payload
    from gol_distributed_final_tpu.rpc.protocol import Response

    led = obs_accounting.ledger()
    assert led.top_k == 16
    tl = obs_timeline.enable(period=60.0, start_thread=False)
    try:
        for i in range(40):  # 16 tracked + a busy 'other' bucket
            t = str(1000 + i)
            led.record_admit(t, 0.001, 4096)
            led.record_chunk([t] * 3, 32, 0.05)
            led.record_reject(t, "capacity")
        for _ in range(5):
            obs_metrics.registry().counter("gol_engine_turns_total").inc(7)
            tl.sample_once()
        seq = tl.seq
        obs_metrics.registry().counter("gol_engine_turns_total").inc()
        tl.sample_once()
        payload = status_payload(
            role="broker", timeline_since=seq, accounting_since=0
        )
        assert payload["accounting"]["tenants"] and payload["alerts"]
        # the gol_fleet_* families are collector-process-only (registered
        # on obs.fleet import, which a broker entry point never does);
        # pytest shares one process with the fleet suite, so strip them
        # from the broker-role budget measurement
        metrics = payload.get("metrics") or {}
        metrics["families"] = [
            f for f in metrics.get("families", ())
            if not f["name"].startswith("gol_fleet_")
        ]
        nbytes = len(pickle.dumps(Response(status=payload), protocol=5))
        assert nbytes < 65536, f"incremental Status reply is {nbytes} B"
    finally:
        obs_timeline.disable()


# -- canary ------------------------------------------------------------------


def test_canary_probe_bit_exact(live_metrics):
    from gol_distributed_final_tpu.obs.canary import CanaryProber
    from gol_distributed_final_tpu.obs.status import series_map

    server, service, addr = _serve_loopback()
    prober = CanaryProber(addr, size=16, turns=16, verb="session")
    try:
        out = prober.probe_once()
        assert out["result"] == "ok", out
        snap = obs_metrics.registry().snapshot()
        probes = series_map(snap, "gol_canary_probes_total")
        assert (probes.get(("ok",)) or {}).get("value") == 1
        lat = series_map(snap, "gol_canary_latency_seconds").get(())
        assert lat and lat["count"] == 1
        # the canary's usage is ledger-attributed under its tenant
        by = {
            e["tenant"]: e
            for e in obs_accounting.ledger().window()["tenants"]
        }
        assert str(0xCA) in by and by[str(0xCA)]["turns"] == 16
    finally:
        prober.stop()
        service._shutdown()


def test_canary_detects_injected_wrong_board(live_metrics):
    """The acceptance scenario: a resident-strip worker corrupted in
    place (GOL_FAULT_POINTS strip corrupt) with -integrity off — the
    white-box defenses are disabled by design, so the serving path
    returns a silently-wrong board, and the BLACKBOX canary is what
    catches it, within one probe."""
    from gol_distributed_final_tpu.obs.canary import (
        CanaryProber,
        _oracle_evolve,
        canary_board,
    )
    from gol_distributed_final_tpu.obs.status import series_map
    from gol_distributed_final_tpu.rpc import faults as rpc_faults
    from gol_distributed_final_tpu.rpc import integrity as rpc_integrity
    from gol_distributed_final_tpu.rpc import worker as rpc_worker
    from gol_distributed_final_tpu.rpc.broker import serve

    # pick a flip index whose corruption provably survives to the final
    # board (a flip in a dead neighborhood just dies out — that WOULD be
    # served correctly, and correctly is not what this test injects)
    board = canary_board(16, 0, 1)
    want, _ = _oracle_evolve(board, 16)

    def flip_matters(i: int) -> bool:
        flipped = board.copy()
        flipped.reshape(-1)[i] ^= 0xFF
        return not np.array_equal(_oracle_evolve(flipped, 16)[0], want)

    idx = next(i for i in range(board.size) if flip_matters(i))

    wserver, _wservice = rpc_worker.serve(port=0)
    server, service = serve(
        port=0, backend="workers",
        worker_addresses=[f"127.0.0.1:{wserver.port}"], wire="resident",
    )
    rpc_integrity.set_enabled(False)  # undefended by design
    rpc_faults.configure(f"worker.strip_corrupt:corrupt:1:{idx}")
    prober = CanaryProber(
        f"127.0.0.1:{server.port}", size=16, turns=16, verb="run"
    )
    # rules=[]: the rule is evaluated EXPLICITLY below — a metering
    # rulebook here would leave a canary-failure label child behind for
    # test_slo's exact-series assertions (registry reset keeps children)
    tl = obs_timeline.enable(period=60.0, start_thread=False, rules=[])
    try:
        tl.sample_once()  # the pre-probe baseline tick
        out = prober.probe_once()
        assert out["result"] == "corrupt", out
        assert "diverges from the oracle" in out["detail"] or "alive" in out["detail"]
        snap = obs_metrics.registry().snapshot()
        probes = series_map(snap, "gol_canary_probes_total")
        assert (probes.get(("corrupt",)) or {}).get("value") == 1
        # ...and the canary-failure SLO rule FIRES on the very next tick
        # — within one probe period, the acceptance contract
        tl.sample_once()
        from gol_distributed_final_tpu.obs import slo

        rule = next(
            r for r in slo.default_rules() if r.name == "canary-failure"
        )
        firing, value, detail = rule.evaluate(tl)
        assert firing and value == 1, detail
    finally:
        obs_timeline.disable()
        rpc_faults.configure(None)
        rpc_integrity.set_enabled(True)
        prober.stop()
        service._shutdown()
        wserver.stop()


def test_canary_failure_rule_fires_on_failures_only(live_metrics):
    """The canary-failure SLO rule watches ONLY the corrupt/error result
    streams: a healthy probing stream must never arm it."""
    from gol_distributed_final_tpu.obs import slo

    # rules=[] so the rule only evaluates where this test calls it (a
    # metering rulebook would leak a label child into later exact-series
    # assertions — see the corrupt test above)
    tl = obs_timeline.enable(period=60.0, start_thread=False, rules=[])
    try:
        rule = next(
            r for r in slo.default_rules() if r.name == "canary-failure"
        )
        probes = obs_metrics.registry().counter(
            "gol_canary_probes_total", labelnames=("result",)
        )
        tl.sample_once(now=0.0, wall=0.0)
        probes.labels("ok").inc(10)
        tl.sample_once(now=10.0, wall=10.0)
        firing, _, detail = rule.evaluate(tl)
        assert not firing, detail
        probes.labels("corrupt").inc()
        tl.sample_once(now=20.0, wall=20.0)
        firing, value, detail = rule.evaluate(tl)
        assert firing and value == 1
        assert "corrupt" in detail
        # and it is in the default rulebook's stable name contract
        assert "canary-failure" in slo.DEFAULT_RULE_NAMES
    finally:
        obs_timeline.disable()


# -- loadgen -----------------------------------------------------------------


def test_loadgen_open_loop_and_reject_classification(live_metrics):
    """A burst past -session-capacity: completions + classified rejects
    sum to the schedule, rejects classify by the STRUCTURED reason, and
    the client-side latency histograms record every completion."""
    from gol_distributed_final_tpu.obs.loadgen import LoadConfig, LoadGenerator
    from gol_distributed_final_tpu.obs.status import series_map

    server, service, addr = _serve_loopback(session_capacity=2)
    try:
        summary = LoadGenerator(addr, LoadConfig(
            rate=1e6, sessions=10, arrival="burst", burst=10,
            tenants=3, size=16, turns=500, seed=5, timeout=120.0,
        )).run()
        assert summary["issued"] == 10
        assert (
            summary["completed"] + summary["rejected_total"]
            + summary["errors"] == 10
        )
        assert summary["errors"] == 0
        assert summary["rejected_total"] >= 1
        assert set(summary["rejected"]) == {"capacity"}
        assert summary["admit_to_first_turn"]["n"] == summary["completed"]
        snap = obs_metrics.registry().snapshot()
        outcomes = series_map(snap, "gol_loadgen_sessions_total")
        assert (outcomes.get(("ok",)) or {}).get("value") == summary["completed"]
        assert (outcomes.get(("rejected",)) or {}).get("value") == summary[
            "rejected_total"
        ]
        e2e = series_map(snap, "gol_loadgen_session_seconds").get(())
        assert e2e and e2e["count"] == summary["completed"]
        # ledger reconciliation (the --loadgen gate's assert, in-proc)
        totals = obs_accounting.ledger().totals()
        assert totals["turns"] == summary["completed"] * 500
        assert totals["rejects"] == summary["rejected_total"]
    finally:
        service._shutdown()


def test_loadgen_schedule_determinism():
    from gol_distributed_final_tpu.obs.loadgen import LoadConfig, LoadGenerator

    cfg = LoadConfig(rate=100.0, sessions=20, arrival="poisson", seed=9)
    a = LoadGenerator("127.0.0.1:1", cfg)._schedule()
    b = LoadGenerator("127.0.0.1:1", cfg)._schedule()
    assert a == b and len(a) == 20 and a == sorted(a)
    burst = LoadConfig(rate=100.0, sessions=20, arrival="burst", burst=5)
    times = LoadGenerator("127.0.0.1:1", burst)._schedule()
    assert times[0] == times[4] and times[5] == pytest.approx(0.05)
    with pytest.raises(ValueError):
        LoadConfig(arrival="nope").validate()
    with pytest.raises(ValueError):
        LoadConfig(rate=0).validate()


# -- watch TENANTS panel + doctor tenant skew --------------------------------


def _acct_payload(hot_share=0.8, rejects=0):
    tenants = [
        {"tenant": "7", "device_seconds": hot_share * 10, "turns": 800,
         "wire_bytes": 4096, "sessions": 8, "rejects": {"capacity": rejects},
         "rejects_total": rejects, "errors": 0, "seq": 5},
        {"tenant": "8", "device_seconds": (1 - hot_share) * 10, "turns": 200,
         "wire_bytes": 1024, "sessions": 2, "rejects": {},
         "rejects_total": 0, "errors": 0, "seq": 6},
    ]
    return {
        "schema": "gol-accounting/1", "seq": 6, "top_k": 16, "tracked": 2,
        "tenants": tenants, "other": None,
        "totals": {"device_seconds": 10.0, "turns": 1000,
                   "wire_bytes": 5120, "sessions": 10,
                   "rejects": rejects, "errors": 0},
    }


def test_watch_tenants_panel_pure_render():
    from gol_distributed_final_tpu.obs.watch import render_status

    payload = {
        "role": "broker", "pid": 1, "metrics_enabled": True,
        "metrics": {"families": []},
        "accounting": _acct_payload(),
    }
    out = render_status("broker :1", payload)
    assert "TENANTS (usage, top-16)" in out
    assert "TOTAL" in out and "  7 " in out
    # no accounting → no panel
    del payload["accounting"]
    assert "TENANTS" not in render_status("broker :1", payload)


def test_doctor_names_hot_tenant():
    from gol_distributed_final_tpu.obs.doctor import diagnose, render

    statuses = {
        "broker 127.0.0.1:8040": {
            "role": "broker", "pid": 1, "metrics_enabled": True,
            "metrics": {"families": []},
            "accounting": _acct_payload(hot_share=0.8, rejects=12),
        }
    }
    findings = diagnose(statuses)
    skew = [f for f in findings if "device-seconds" in f["title"]]
    assert skew and "tenant 7" in skew[0]["title"]
    assert skew[0]["suspects"] == ["tenant 7"]
    assert any("800 turns" in e for e in skew[0]["evidence"])
    burn = [f for f in findings if "burn" in f["title"]]
    assert burn and "tenant 7" in burn[0]["title"]
    assert render(findings, statuses)  # renderable end to end
    # balanced usage + no burn → no skew finding
    ok = {
        "broker b": {
            "role": "broker", "pid": 1, "metrics_enabled": True,
            "metrics": {"families": []},
            "accounting": _acct_payload(hot_share=0.5, rejects=0),
        }
    }
    names = [f["title"] for f in diagnose(ok)]
    assert not any("device-seconds" in t or "burn" in t for t in names)


# -- lint --------------------------------------------------------------------


def test_accounting_and_canary_lints(tmp_path):
    from gol_distributed_final_tpu.obs import lint

    assert lint.undocumented_canary_metrics() == []
    assert lint.undocumented_accounting_names() == []
    assert lint.missing_readme_sections() == []
    bare = tmp_path / "README.md"
    bare.write_text("# nothing\n")
    assert "gol_canary_probes_total" in lint.undocumented_canary_metrics(bare)
    assert "accounting_since" in lint.undocumented_accounting_names(bare)
    missing = lint.missing_readme_sections(bare)
    assert "## Accounting & capacity" in missing
    assert "## Canary & load harness" in missing
