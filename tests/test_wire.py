"""Wire data-plane suite: protocol-5 out-of-band frames + resident strips.

Covers the two layers the `-wire resident` mode stands on:

* ``rpc/protocol.py`` out-of-band framing — zero-copy send (the socket is
  handed the array's own memory) and zero-copy receive (the unpickled
  array wraps the receive buffer), plus old↔new frame-flag skew in both
  directions (an un-negotiated peer only ever sees plain frames; a
  flagged frame reaching an OLD receiver fails loudly, never mis-parses).
* ``rpc/broker.py`` + ``rpc/worker.py`` resident sessions — oracle parity
  against the tpu backend across geometries and batch depths, lockstep
  enforcement, snapshot/pause sync boundaries, the per-step alive-count
  feed, the wire-byte contract (resident ≥ 10× fewer bytes per turn than
  haloed), and loss recovery.

Fast in-process tests run in tier-1; the live multi-process chaos
scenario is ``slow``-marked (``scripts/check --wire`` runs everything).
"""

import pickle
import socket
import struct
import threading
import time

import numpy as np
import pytest

from gol_distributed_final_tpu.obs import metrics as obs_metrics
from gol_distributed_final_tpu.rpc import protocol
from gol_distributed_final_tpu.rpc import worker as rpc_worker
from gol_distributed_final_tpu.rpc.broker import TpuBackend, WorkersBackend
from gol_distributed_final_tpu.rpc.client import RemoteBroker, RpcClient
from gol_distributed_final_tpu.rpc.protocol import (
    MAX_FRAME,
    Methods,
    Request,
    Response,
    _FLAG_OOB,
    _HEADER,
    loads_restricted,
    recv_frame_sized,
    send_frame,
)
from gol_distributed_final_tpu.rpc.server import RpcServer

from oracle import vector_step


# -- protocol-5 out-of-band frames -------------------------------------------


class _RecordingSock:
    """Captures every sendall buffer — the zero-copy send assertion needs
    the exact objects handed to the socket."""

    def __init__(self):
        self.chunks = []

    def sendall(self, data):
        self.chunks.append(data)


def test_oob_send_is_zero_copy_and_small_arrays_stay_inband():
    big = np.arange(64 * 64, dtype=np.uint8).reshape(64, 64)
    small = np.arange(8, dtype=np.uint8)  # < _OOB_THRESHOLD: in-band
    sock = _RecordingSock()
    nbytes = send_frame(sock, {"big": big, "small": small}, oob=True)
    assert nbytes == sum(
        len(bytes(c)) if not isinstance(c, memoryview) else c.nbytes
        for c in sock.chunks
    ) + 0  # send_frame returns header + body, and we captured everything
    # header word carries the flag
    (word,) = _HEADER.unpack(bytes(sock.chunks[0]))
    assert word & _FLAG_OOB
    # exactly one sidecar (the big array): the subheader says so
    nbufs, _pickle_len = protocol._OOB_SUB.unpack_from(bytes(sock.chunks[1]), 0)
    assert nbufs == 1
    # and the sidecar chunk IS the array's own memory — no serialize copy
    sidecar = sock.chunks[-1]
    assert isinstance(sidecar, memoryview)
    assert np.shares_memory(np.frombuffer(sidecar, np.uint8), big)


def test_oob_receive_reconstructs_views_of_the_sidecar_buffers():
    arr = np.random.default_rng(0).integers(0, 255, (50, 60), dtype=np.uint8)
    raws = []
    payload = pickle.dumps(
        {"x": arr}, protocol=5,
        buffer_callback=lambda pb: raws.append(bytes(pb.raw())) and False,
    )
    buffers = [bytearray(r) for r in raws]
    got = loads_restricted(payload, buffers)["x"]
    assert np.array_equal(got, arr)
    # zero parse-time copy: the array wraps the receive buffer
    assert np.shares_memory(got, np.frombuffer(buffers[0], np.uint8))


def test_oob_socket_roundtrip_request_response():
    a, b = socket.socketpair()
    try:
        big = np.random.default_rng(1).integers(0, 255, (100, 100), np.uint8)
        req = Request(world=big, turns=7, initial_turn=3)
        sent = send_frame(a, {"id": 1, "request": req}, oob=True)
        obj, nbytes = recv_frame_sized(b)
        assert nbytes == sent
        assert obj["id"] == 1
        assert obj["request"].turns == 7
        assert np.array_equal(obj["request"].world, big)
        # the received array is a VIEW (its memory is the frame buffer),
        # never an owning copy
        assert obj["request"].world.base is not None
    finally:
        a.close()
        b.close()


def test_oob_frame_length_mismatch_is_a_loud_connection_error():
    a, b = socket.socketpair()
    try:
        # subheader claims a pickle + sidecar that don't add up to the
        # framed length: the receiver must refuse before allocating
        sub = protocol._OOB_SUB.pack(1, 10) + protocol._OOB_LEN.pack(10)
        body = sub + b"x" * 10  # 10 sidecar bytes missing
        a.sendall(_HEADER.pack(_FLAG_OOB | len(body)))
        a.sendall(body)
        with pytest.raises(ConnectionError, match="length mismatch"):
            recv_frame_sized(b)
    finally:
        a.close()
        b.close()


def _old_recv_frame(sock):
    """The PRE-out-of-band receiver, verbatim: 8-byte length header, one
    plain pickle. The skew test sends it a flagged frame and the length
    check must fail loudly (bit 63 rides above MAX_FRAME)."""
    head = b""
    while len(head) < 8:
        chunk = sock.recv(8 - len(head))
        if not chunk:
            raise ConnectionError("peer closed the connection")
        head += chunk
    (length,) = struct.Struct(">Q").unpack(head)
    if length > MAX_FRAME:
        raise ConnectionError(f"frame of {length} bytes exceeds limit")
    raise AssertionError("an old receiver must never parse a flagged frame")


def test_flagged_frame_fails_an_old_receiver_loudly():
    a, b = socket.socketpair()
    try:
        send_frame(a, {"x": np.zeros((64, 64), np.uint8)}, oob=True)
        with pytest.raises(ConnectionError, match="exceeds limit"):
            _old_recv_frame(b)
    finally:
        a.close()
        b.close()


def test_old_client_keeps_getting_plain_reply_frames():
    """New-server-old-client skew: an envelope WITHOUT the "oob" key (an
    old client's) must be answered with a PLAIN frame — the server only
    upgrades a connection its peer advertised on."""
    server = RpcServer(port=0)
    server.register("T.Echo", lambda req: Response(world=np.asarray(req.world)))
    server.serve_background()
    sock = socket.create_connection(("127.0.0.1", server.port), timeout=5)
    try:
        big = np.arange(64 * 64, dtype=np.uint8).reshape(64, 64)
        # old-client envelope: no "oob" key, plain frame
        send_frame(sock, {"id": 0, "method": "T.Echo",
                          "request": Request(world=big)})
        head = b""
        while len(head) < 8:
            head += sock.recv(8 - len(head))
        (word,) = _HEADER.unpack(head)
        assert not word & _FLAG_OOB, "old client was sent a flagged frame"
        body = b""
        while len(body) < word:
            body += sock.recv(min(1 << 20, word - len(body)))
        reply = loads_restricted(body)
        assert np.array_equal(reply["result"].world, big)
        # the server DOES advertise, so a current client would upgrade
        assert reply.get("oob") == 1
    finally:
        sock.close()
        server.stop()


def test_new_client_against_old_server_stays_plain():
    """Old-server-new-client skew: a server whose replies lack the "oob"
    key never receives a flagged frame, however many calls are made."""
    listener = socket.socket()
    listener.bind(("127.0.0.1", 0))
    listener.listen(1)
    port = listener.getsockname()[1]
    flagged = []

    def old_server():
        conn, _ = listener.accept()
        with conn:
            for _ in range(2):
                head = b""
                while len(head) < 8:
                    head += conn.recv(8 - len(head))
                (word,) = _HEADER.unpack(head)
                flagged.append(bool(word & _FLAG_OOB))
                length = word & (protocol._LEN_MASK if not word & _FLAG_OOB else (1 << 64) - 1)
                body = b""
                while len(body) < length:
                    body += conn.recv(min(1 << 20, length - len(body)))
                msg = loads_restricted(body)
                # an OLD server's reply: no "oob" advertisement
                send_frame(conn, {"id": msg["id"], "result": Response()})

    t = threading.Thread(target=old_server, daemon=True)
    t.start()
    client = RpcClient(f"127.0.0.1:{port}", timeout=5)
    try:
        big = np.zeros((64, 64), np.uint8)
        client.call("T.X", Request(world=big), timeout=5)
        client.call("T.X", Request(world=big), timeout=5)
        assert client._peer_oob is False
        assert flagged == [False, False], "an old server saw a flagged frame"
    finally:
        client.close()
        listener.close()
        t.join(timeout=5)


def test_rpc_negotiation_upgrades_and_roundtrips_big_arrays():
    server = RpcServer(port=0)
    server.register("T.Echo", lambda req: Response(world=np.asarray(req.world)))
    server.serve_background()
    client = RpcClient(f"127.0.0.1:{server.port}", timeout=5)
    try:
        big = np.random.default_rng(2).integers(0, 255, (128, 128), np.uint8)
        assert client._peer_oob is False
        r1 = client.call("T.Echo", Request(world=big), timeout=5)
        assert np.array_equal(r1.world, big)
        # the first reply advertised: this transport is upgraded now
        assert client._peer_oob is True
        r2 = client.call("T.Echo", Request(world=big), timeout=5)  # rides OOB
        assert np.array_equal(r2.world, big)
        assert r2.world.base is not None  # a view of the receive buffer
    finally:
        client.close()
        server.stop()


# -- resident strips: kernel + lockstep units --------------------------------


def test_strip_step_batch_matches_oracle_shrinking_form():
    rng = np.random.default_rng(5)
    board = np.where(rng.random((20, 16)) < 0.4, 255, 0).astype(np.uint8)
    k = 4
    # strip = rows [8, 14) of the board; halos are the k rows around it
    s, e = 8, 14
    strip = board[s:e]
    top = board[s - k:s]
    bottom = board[e:e + k]
    got, counts = rpc_worker.strip_step_batch(strip, top, bottom, k)
    want = board.copy()
    per_step = []
    for _ in range(k):
        want = vector_step(want)
        per_step.append(int(np.count_nonzero(want[s:e])))
    assert np.array_equal(got, want[s:e])
    assert counts == per_step


def test_worker_service_enforces_lockstep_and_session():
    service = rpc_worker.WorkerService(server=None)
    with pytest.raises(ValueError, match="StripStart must precede"):
        service.strip_step(
            Request(world=np.zeros((2, 8), np.uint8), turns=1, worker=0)
        )
    strip = np.zeros((4, 8), np.uint8)
    service.strip_start(Request(world=strip, worker=1, initial_turn=10))
    halos = np.zeros((2, 8), np.uint8)
    with pytest.raises(ValueError, match="lockstep violation"):
        service.strip_step(
            Request(world=halos, turns=1, worker=1, initial_turn=9)
        )
    with pytest.raises(ValueError, match="index mismatch"):
        service.strip_step(
            Request(world=halos, turns=1, worker=2, initial_turn=10)
        )
    with pytest.raises(ValueError, match="exceeds strip height"):
        service.strip_step(
            Request(
                world=np.zeros((10, 8), np.uint8), turns=5, worker=1,
                initial_turn=10,
            )
        )
    res = service.strip_step(
        Request(world=halos, turns=1, worker=1, initial_turn=10)
    )
    assert res.turns_completed == 11
    assert res.edges.shape == (2, 8)
    fetched = service.strip_fetch(Request())
    assert fetched.turns_completed == 11
    # a re-seed REPLACES the session wholesale
    service.strip_start(Request(world=strip, worker=1, initial_turn=0))
    assert service.strip_fetch(Request()).turns_completed == 0


# -- resident strips: in-process cluster -------------------------------------


@pytest.fixture(scope="module")
def wire_cluster():
    """Four in-process workers (real RpcServers on loopback sockets)."""
    servers = [rpc_worker.serve(port=0) for _ in range(4)]
    yield [f"127.0.0.1:{s.port}" for s, _ in servers]
    for server, _service in servers:
        server.stop()


def _rand_board(h, w, seed):
    rng = np.random.default_rng(seed)
    return np.where(rng.random((h, w)) < 0.4, 255, 0).astype(np.uint8)


def _run_resident(addrs, board, turns, k, sync_interval=16, **kw):
    backend = WorkersBackend(
        addrs, wire="resident", halo_depth=k, sync_interval=sync_interval,
        **kw,
    )
    try:
        return backend.run(
            Request(
                world=board, turns=turns, threads=4,
                image_width=board.shape[1], image_height=board.shape[0],
            )
        )
    finally:
        backend.close()


_TPU_ORACLE_CACHE = {}


def _tpu_backend_world(board, turns):
    """The tpu backend's answer for the same Run — the parity oracle."""
    key = (board.tobytes(), turns)
    if key not in _TPU_ORACLE_CACHE:
        res = TpuBackend().run(
            Request(
                world=board, turns=turns, threads=4,
                image_width=board.shape[1], image_height=board.shape[0],
            )
        )
        _TPU_ORACLE_CACHE[key] = np.asarray(res.world)
    return _TPU_ORACLE_CACHE[key]


@pytest.mark.parametrize("geometry", [(24, 33), (64, 64), (16, 40)])
@pytest.mark.parametrize("k", [1, 4])
def test_resident_parity_vs_tpu_backend(wire_cluster, geometry, k):
    """Bit-identical to the tpu backend across geometries and batch
    depths — uneven splits, partial final batches (41 % 4 != 0), and
    periodic re-syncs included."""
    h, w = geometry
    board = _rand_board(h, w, seed=h * 100 + w)
    turns = 41
    res = _run_resident(wire_cluster, board, turns, k)
    assert res.turns_completed == turns
    np.testing.assert_array_equal(
        res.world, _tpu_backend_world(board, turns)
    )


def test_resident_snapshot_pause_and_alive_ticker(wire_cluster):
    """The snapshot path syncs on demand; pause parks on a synced board;
    the count-only retrieve (the 2 s AliveCellsCount ticker) is served
    from the per-step StripStep counts and is oracle-exact."""
    board = _rand_board(48, 48, seed=9)
    turns = 4000
    backend = WorkersBackend(
        wire_cluster, wire="resident", halo_depth=4, sync_interval=64
    )
    out = {}
    t = threading.Thread(
        target=lambda: out.update(
            r=backend.run(
                Request(
                    world=board, turns=turns, threads=4,
                    image_width=48, image_height=48,
                )
            )
        )
    )
    t.start()
    try:
        deadline = time.monotonic() + 60
        while backend.retrieve(include_world=False).turns_completed < 100:
            assert time.monotonic() < deadline, "run never got going"
            time.sleep(0.002)
        # mid-run full snapshot: triggers one sync round, and the pair
        # (world, turn) must be oracle-consistent
        snap = backend.retrieve(include_world=True)
        want = board.copy()
        for _ in range(snap.turns_completed):
            want = vector_step(want)
        np.testing.assert_array_equal(snap.world, want)
        # count-only: the shared _record_alive feed, no gather
        tick = backend.retrieve(include_world=False)
        want_t = want
        for _ in range(tick.turns_completed - snap.turns_completed):
            want_t = vector_step(want_t)
        assert tick.alive_count == int(np.count_nonzero(want_t))
        backend.pause()
        a = backend.retrieve(include_world=True)
        time.sleep(0.2)
        b = backend.retrieve(include_world=False)
        assert a.turns_completed == b.turns_completed, "advanced while parked"
        # parked on a synced board: the snapshot is immediate and exact
        want_p = board.copy()
        for _ in range(a.turns_completed):
            want_p = vector_step(want_p)
        np.testing.assert_array_equal(a.world, want_p)
        backend.pause()  # resume
        t.join(timeout=120)
        assert not t.is_alive()
        want_final = board.copy()
        for _ in range(turns):
            want_final = vector_step(want_final)
        np.testing.assert_array_equal(out["r"].world, want_final)
    finally:
        if t.is_alive():
            backend.quit()
            t.join(timeout=30)
        backend.close()


@pytest.fixture
def live_metrics():
    obs_metrics.enable()
    obs_metrics.registry().reset()
    yield obs_metrics
    obs_metrics.enable(False)


def _wire_totals():
    out = {}
    for fam in obs_metrics.registry().snapshot()["families"]:
        if fam["name"] == "gol_wire_bytes_total":
            out["bytes"] = sum(s["value"] for s in fam["series"])
        if fam["name"] == "gol_turn_batch_size":
            s = fam["series"][0] if fam["series"] else {}
            out["batches"] = s.get("count", 0)
            out["batched_turns"] = s.get("sum", 0.0)
        if fam["name"] == "gol_strip_resync_total":
            out["resyncs"] = sum(s["value"] for s in fam["series"])
    return out


def test_resident_wire_bytes_10x_below_haloed(wire_cluster, live_metrics):
    """The acceptance contract, byte-accounted on loopback: resident K=8
    moves >= 10x fewer frame bytes per turn than haloed, batches are
    metered (gol_turn_batch_size), and sync_interval=0 costs exactly one
    run-end resync."""
    board = _rand_board(128, 128, seed=4)
    turns = 80

    b0 = _wire_totals().get("bytes", 0.0)
    backend = WorkersBackend(wire_cluster, wire="haloed")
    try:
        r_hal = backend.run(
            Request(world=board, turns=turns, threads=4,
                    image_width=128, image_height=128)
        )
    finally:
        backend.close()
    s1 = _wire_totals()
    haloed_per_turn = (s1["bytes"] - b0) / turns

    res = _run_resident(wire_cluster, board, turns, k=8, sync_interval=0)
    s2 = _wire_totals()
    resident_per_turn = (s2["bytes"] - s1["bytes"]) / turns

    np.testing.assert_array_equal(res.world, r_hal.world)  # same bits
    assert resident_per_turn * 10 <= haloed_per_turn, (
        f"resident {resident_per_turn:.0f} B/turn vs haloed "
        f"{haloed_per_turn:.0f} B/turn"
    )
    assert s2["batches"] - s1["batches"] == turns / 8
    assert s2["batched_turns"] - s1["batched_turns"] == turns
    assert s2["resyncs"] - s1.get("resyncs", 0) == 1, (
        "sync_interval=0 must sync only at run end"
    )


def test_resident_worker_loss_recovers_bit_identical():
    """Kill one worker's server mid-run: the broker marks it lost,
    rebuilds the board at the committed turn (survivor fetches + local
    worker-kernel recompute from the last sync), reseeds over the
    survivors, and the final board is bit-identical to the oracle."""
    servers = [rpc_worker.serve(port=0) for _ in range(3)]
    addrs = [f"127.0.0.1:{s.port}" for s, _ in servers]
    board = _rand_board(48, 48, seed=11)
    turns = 1500
    backend = WorkersBackend(
        addrs, wire="resident", halo_depth=4, sync_interval=64,
        rpc_deadline=2.0, probe_interval=0.2,
    )
    out = {}
    t = threading.Thread(
        target=lambda: out.update(
            r=backend.run(
                Request(world=board, turns=turns, threads=3,
                        image_width=48, image_height=48)
            )
        )
    )
    t.start()
    try:
        deadline = time.monotonic() + 60
        while backend.retrieve(include_world=False).turns_completed < 150:
            assert time.monotonic() < deadline, "run never got going"
            time.sleep(0.002)
        servers[1][0].stop()  # mid-batch loss
        t.join(timeout=120)
        assert not t.is_alive(), "run hung after the loss"
        want = board.copy()
        for _ in range(turns):
            want = vector_step(want)
        assert out["r"].turns_completed == turns
        np.testing.assert_array_equal(out["r"].world, want)
    finally:
        if t.is_alive():
            backend.quit()
            t.join(timeout=30)
        backend.close()
        for server, _service in servers:
            try:
                server.stop()
            except Exception:
                pass


def test_bench_diff_gates_wire_bytes_not_just_wall_clock():
    """``scripts/bench_diff`` (obs/regress.py): a case whose
    ``wire_bytes_per_turn`` grew past the threshold REGRESSES even when
    its wall-clock is clean — byte accounting is deterministic, so no
    noise band applies."""
    from gol_distributed_final_tpu.obs.regress import compare_case

    base = {
        "per_turn_us": 100.0, "spread_s": 0.001, "n_lo": 100, "n_hi": 1100,
        "wire_bytes_per_turn": 5000.0,
    }
    same = compare_case(base, dict(base))
    assert same["verdict"] == "jitter"
    assert same["bytes_delta_pct"] == 0.0
    bloated = compare_case(base, dict(base, wire_bytes_per_turn=6000.0))
    assert bloated["verdict"] == "REGRESSED"
    assert "bytes" in bloated["why"]
    slimmer = compare_case(base, dict(base, wire_bytes_per_turn=500.0))
    assert slimmer["verdict"] == "jitter"  # a comms WIN never gates
    # the byte gate survives a broken wall-clock fit (a salvaged round's
    # zero/missing per_turn_us): deterministic comms growth still gates
    broken = compare_case(
        dict(base, per_turn_us=0.0), dict(base, wire_bytes_per_turn=6000.0)
    )
    assert broken["verdict"] == "REGRESSED"
    assert "bytes" in broken["why"]
    # cases without the meter (every non-wire config) are untouched
    plain = compare_case(
        {k: v for k, v in base.items() if k != "wire_bytes_per_turn"},
        {k: v for k, v in base.items() if k != "wire_bytes_per_turn"},
    )
    assert "bytes_delta_pct" not in plain


# -- live multi-process chaos (slow: scripts/check --wire) --------------------


@pytest.mark.slow
def test_resident_chaos_kill_worker_mid_batch_bit_identical(tmp_path):
    """The live scenario: a subprocess cluster running ``-wire resident
    -halo-depth 4``, one worker SIGKILLed mid-batch, restarted on its old
    port, readmitted by the probe (the split re-expands) — and the
    finished run is bit-identical to an uninterrupted oracle."""
    from test_chaos import _kill_all, _oracle_64, _read_board_64
    from test_rpc import _poll_turn, _spawn, _wait_listening

    turns = 4000
    workers = [
        _spawn("gol_distributed_final_tpu.rpc.worker", "-port", "0")
        for _ in range(3)
    ]
    broker = restarted = None
    try:
        ports = [_wait_listening(w) for w in workers]
        broker = _spawn(
            "gol_distributed_final_tpu.rpc.broker",
            "-port", "0", "-backend", "workers", "-metrics",
            "-wire", "resident", "-halo-depth", "4", "-sync-interval", "32",
            "-workers", ",".join(f"127.0.0.1:{p}" for p in ports),
            "-rpc-deadline", "5", "-probe-interval", "0.2",
        )
        address = f"127.0.0.1:{_wait_listening(broker)}"
        from gol_distributed_final_tpu import Params

        p = Params(turns=turns, threads=3, image_width=64, image_height=64)
        board = _read_board_64()
        remote = RemoteBroker(address, timeout=30.0)
        result = {}
        t = threading.Thread(
            target=lambda: result.update(r=remote.run(p, board))
        )
        t.start()
        try:
            _poll_turn(remote, 300)
            workers[1].kill()  # SIGKILL mid-batch
            workers[1].wait()
            # restart on the old port: the roster address heals and the
            # probe readmits it; the resident split must RE-EXPAND
            restarted = _spawn(
                "gol_distributed_final_tpu.rpc.worker",
                "-port", str(ports[1]), "-metrics",
            )
            _wait_listening(restarted)
            from test_chaos import _fetch_broker_counter

            deadline = time.monotonic() + 30
            while (
                _fetch_broker_counter(address, "gol_worker_readmitted_total")
                < 1
            ):
                assert time.monotonic() < deadline, "never readmitted"
                time.sleep(0.2)
            t.join(timeout=300)
            assert not t.is_alive(), "run did not complete after readmission"
        finally:
            if t.is_alive():
                remote.quit()
                t.join(timeout=30)
            remote.close()
        r = result["r"]
        assert r.turns_completed == turns
        np.testing.assert_array_equal(r.world, _oracle_64(turns))
        # the readmitted worker held a strip again: it served StripStep
        from gol_distributed_final_tpu.obs.status import fetch_status

        wpayload = fetch_status(
            f"127.0.0.1:{ports[1]}", worker=True, timeout=5.0
        )
        steps = 0.0
        for fam in (wpayload.get("metrics") or {}).get("families", []):
            if fam["name"] == "gol_rpc_server_requests_total":
                steps = sum(
                    s["value"]
                    for s in fam["series"]
                    if Methods.STRIP_STEP in tuple(s["labels"])
                )
        assert steps > 0, "restarted worker never held a resident strip"
    finally:
        _kill_all([*workers, broker, restarted])
