"""Concurrency soundness: the lock-composition checkers + the sanitizer.

Static half (gol_distributed_final_tpu/analysis/lockorder.py): fixture
trees prove each finding kind FIRES on its positives and stays QUIET on
its negatives — ``lock-order`` acquisition-graph cycles (direct,
via call edges, cross-class, non-reentrant re-entry), ``atomicity``
read-release-write TOCTOU, ``blocking-under-lock`` blocking calls under
hot-path locks — plus the satellite contracts: stale-suppression
detection, multi-lock / loud ``holds(..)`` markers, executor hygiene.

Dynamic half (gol_distributed_final_tpu/utils/locksan.py): the runtime
sanitizer aborts on an observed order inversion (both stacks in the
message, evidence artifact written), the watchdog dumps all-thread
tracebacks when a lock is held past the deadline with waiters queued,
and the DISABLED path hands out plain ``threading`` objects.

No jax import anywhere: the analyzer and the sanitizer are
dependency-free by contract.
"""

import os
import textwrap
import threading
import time

from gol_distributed_final_tpu.analysis import all_checkers, core
from gol_distributed_final_tpu.analysis.__main__ import PACKAGE_ROOT
from gol_distributed_final_tpu.analysis.hygiene import HygieneChecker
from gol_distributed_final_tpu.analysis.lockorder import (
    AtomicityChecker,
    BlockingUnderLockChecker,
    LockOrderChecker,
)
from gol_distributed_final_tpu.analysis.locks import LockDisciplineChecker
from gol_distributed_final_tpu.utils import locksan

import pytest


def write_tree(tmp_path, files: dict):
    for name, src in files.items():
        path = tmp_path / name
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(src))
    return tmp_path


def tree_findings(checker, tmp_path, files: dict):
    write_tree(tmp_path, files)
    return [
        f for f in checker.check_tree(tmp_path) if f.check == checker.id
    ]


def file_findings(checker, src, relpath="rpc/mod.py"):
    found, _sup = core.analyze_source(
        textwrap.dedent(src), relpath, [checker]
    )
    return [f for f in found if f.check == checker.id]


@pytest.fixture
def sanitizer(tmp_path):
    locksan.install(deadline=0.2, out_dir=tmp_path)
    try:
        yield tmp_path
    finally:
        locksan.uninstall()


# -- lock-order ---------------------------------------------------------------


class TestLockOrder:
    def test_positive_direct_cycle(self, tmp_path):
        found = tree_findings(LockOrderChecker(), tmp_path, {"mod.py": """
            import threading

            class C:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def fwd(self):
                    with self._a:
                        with self._b:
                            pass

                def rev(self):
                    with self._b:
                        with self._a:
                            pass
        """})
        assert len(found) == 1
        msg = found[0].message
        assert "cycle" in msg
        # the witness carries both edges with file:line
        assert "C._a -> C._b" in msg and "C._b -> C._a" in msg
        assert "mod.py:" in msg

    def test_positive_cycle_through_helper_call(self, tmp_path):
        # a helper called under lock A that takes lock B contributes the
        # A->B edge — the cycle closes through the call graph
        found = tree_findings(LockOrderChecker(), tmp_path, {"mod.py": """
            import threading

            class C:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def fwd(self):
                    with self._a:
                        self._take_b()

                def _take_b(self):
                    with self._b:
                        pass

                def rev(self):
                    with self._b:
                        with self._a:
                            pass
        """})
        assert len(found) == 1
        assert "cycle" in found[0].message

    def test_positive_cross_class_cycle_via_typed_attr(self, tmp_path):
        # the SessionScheduler/SessionTable shape, inverted on purpose:
        # Sched holds its lock calling into Table, Table holds its lock
        # calling back — resolved through `self._table = Table()`
        found = tree_findings(LockOrderChecker(), tmp_path, {"mod.py": """
            import threading

            class Table:
                def __init__(self, sched):
                    self._lock = threading.Lock()
                    self._sched: "Sched" = sched

                def admit(self):
                    with self._lock:
                        pass

                def kick(self):
                    with self._lock:
                        self._sched.wake()

            class Sched:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._table = Table(self)

                def submit(self):
                    with self._lock:
                        self._table.admit()

                def wake(self):
                    with self._lock:
                        pass
        """})
        assert len(found) == 1
        msg = found[0].message
        assert "Sched._lock" in msg and "Table._lock" in msg

    def test_positive_nonreentrant_reentry_via_helper(self, tmp_path):
        found = tree_findings(LockOrderChecker(), tmp_path, {"mod.py": """
            import threading

            class C:
                def __init__(self):
                    self._a = threading.Lock()

                def outer(self):
                    with self._a:
                        self._inner()

                def _inner(self):
                    with self._a:
                        pass
        """})
        assert len(found) == 1
        assert "re-acquires non-reentrant" in found[0].message

    def test_negative_consistent_order(self, tmp_path):
        found = tree_findings(LockOrderChecker(), tmp_path, {"mod.py": """
            import threading

            class C:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def one(self):
                    with self._a:
                        with self._b:
                            pass

                def two(self):
                    with self._a:
                        self._take_b()

                def _take_b(self):
                    with self._b:
                        pass
        """})
        assert found == []

    def test_negative_rlock_reentry_and_condition_alias(self, tmp_path):
        # an RLock re-entered through a helper is the timeline sampler's
        # legitimate nesting; a Condition wrapping a lock is the SAME
        # node, so `with self._work` then a helper's `with self._lock`
        # is reentry of one lock, not an edge (and RLock-backed: quiet)
        found = tree_findings(LockOrderChecker(), tmp_path, {"mod.py": """
            import threading

            class Sampler:
                def __init__(self):
                    self._lock = threading.RLock()

                def window(self):
                    with self._lock:
                        self.summary()

                def summary(self):
                    with self._lock:
                        pass

            class Sched:
                def __init__(self):
                    self._lock = threading.RLock()
                    self._work = threading.Condition(self._lock)

                def submit(self):
                    with self._work:
                        self._commit()

                def _commit(self):
                    with self._lock:
                        pass
        """})
        assert found == []

    def test_holds_contract_contributes_edges(self, tmp_path):
        # a holds(_a) helper taking _b is an A->B edge even though no
        # with-block nests them syntactically
        found = tree_findings(LockOrderChecker(), tmp_path, {"mod.py": """
            import threading

            class C:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()
                    self._x = 0

                _GUARDED_BY = {"_x": "_a"}

                def helper(self):  # gol: holds(_a)
                    with self._b:
                        pass

                def rev(self):
                    with self._b:
                        with self._a:
                            pass
        """})
        assert len(found) == 1
        assert "cycle" in found[0].message

    def test_cycle_finding_is_suppressible_at_its_anchor(self, tmp_path):
        write_tree(tmp_path, {"mod.py": """
            import threading

            class C:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def fwd(self):
                    with self._a:
                        # gol: allow(lock-order): fixture — proves
                        # repo-level findings route through per-file
                        # suppressions at the first edge's site
                        with self._b:
                            pass

                def rev(self):
                    with self._b:
                        with self._a:
                            pass
        """})
        report = core.run(
            tmp_path, checkers=[LockOrderChecker()], with_repo=True
        )
        # the finding anchors at the normalized cycle's first edge (the
        # inner acquisition in fwd); the allow there hides it
        order = [f for f in report.findings if f.check == "lock-order"]
        hidden = [f for f in report.suppressed if f.check == "lock-order"]
        assert len(order) + len(hidden) == 1
        assert hidden, "expected the allow at the anchor to hide the cycle"


# -- atomicity ----------------------------------------------------------------


class TestAtomicity:
    def test_positive_counter_reload(self):
        found = file_findings(AtomicityChecker(), """
            class C:
                _GUARDED_BY = {"_count": "_lock"}

                def bump(self):
                    with self._lock:
                        c = self._count
                    with self._lock:
                        self._count = c + 1
        """)
        assert len(found) == 1
        assert "stale local 'c'" in found[0].message

    def test_positive_deletion_sized_by_stale_read(self):
        # the sessions.advance shape: grab a prefix, release, delete by
        # the grabbed length under a later acquisition
        found = file_findings(AtomicityChecker(), """
            class C:
                _GUARDED_BY = {"_pending": "_lock"}

                def drain(self):
                    with self._lock:
                        grabbed = list(self._pending)
                    encoded = encode(grabbed)
                    with self._lock:
                        del self._pending[: len(grabbed)]
                    return encoded
        """)
        assert len(found) == 1
        assert "_pending" in found[0].message

    def test_negative_single_critical_section(self):
        found = file_findings(AtomicityChecker(), """
            class C:
                _GUARDED_BY = {"_count": "_lock"}

                def bump(self):
                    with self._lock:
                        c = self._count
                        self._count = c + 1
        """)
        assert found == []

    def test_negative_write_not_derived_from_stale_read(self):
        # a later locked write whose value owes nothing to the earlier
        # read is the single-writer commit pattern (the broker's turn
        # loop), not a TOCTOU
        found = file_findings(AtomicityChecker(), """
            class C:
                _GUARDED_BY = {"_world": "_lock"}

                def turn(self):
                    with self._lock:
                        world = self._world
                    new_world = step(world)
                    with self._lock:
                        self._world = new_world
        """)
        assert found == []

    def test_negative_rebind_kills_staleness(self):
        # the local is re-derived between the regions; the write no
        # longer carries the stale read
        found = file_findings(AtomicityChecker(), """
            class C:
                _GUARDED_BY = {"_state": "_lock"}

                def advance(self):
                    with self._lock:
                        state = self._state
                    state = step(state)
                    with self._lock:
                        self._state = state
        """)
        assert found == []

    def test_suppressible_with_driver_contract(self):
        found, suppressed = core.analyze_source(textwrap.dedent("""
            class C:
                _GUARDED_BY = {"_pending": "_lock"}

                def drain(self):
                    with self._lock:
                        grabbed = list(self._pending)
                    with self._lock:
                        # gol: allow(atomicity): fixture driver contract
                        del self._pending[: len(grabbed)]
        """), "rpc/mod.py", [AtomicityChecker()])
        assert found == []
        assert [f.check for f in suppressed] == ["atomicity"]


# -- blocking-under-lock ------------------------------------------------------


class TestBlockingUnderLock:
    def test_positive_socket_send_under_hot_lock(self, tmp_path):
        found = tree_findings(BlockingUnderLockChecker(), tmp_path,
                              {"mod.py": """
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()

                def advance(self):
                    with self._lock:
                        pass

                def push(self, sock, payload):
                    with self._lock:
                        sock.sendall(payload)
        """})
        assert len(found) == 1
        assert "sock.sendall()" in found[0].message
        assert "C.advance" in found[0].message

    def test_positive_sleep_under_hot_lock_via_helper(self, tmp_path):
        # the blocking call hides one call-edge away from the with-block
        found = tree_findings(BlockingUnderLockChecker(), tmp_path,
                              {"mod.py": """
            import threading
            import time

            class C:
                def __init__(self):
                    self._lock = threading.Lock()

                def update(self):
                    with self._lock:
                        self._backoff()

                def _backoff(self):
                    time.sleep(0.5)
        """})
        assert len(found) == 1
        assert "time.sleep()" in found[0].message

    def test_negative_cold_lock(self, tmp_path):
        # no hot path takes this lock: the write-serialisation pattern
        # is allowed to block under it without a finding
        found = tree_findings(BlockingUnderLockChecker(), tmp_path,
                              {"mod.py": """
            import threading

            class C:
                def __init__(self):
                    self._write_lock = threading.Lock()

                def send(self, sock, payload):
                    with self._write_lock:
                        sock.sendall(payload)
        """})
        assert found == []

    def test_negative_condition_wait_releases_the_held_lock(self, tmp_path):
        found = tree_findings(BlockingUnderLockChecker(), tmp_path,
                              {"mod.py": """
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._work = threading.Condition(self._lock)

                def advance(self):
                    with self._lock:
                        pass

                def drive(self):
                    with self._work:
                        while self.idle():
                            self._work.wait()

                def idle(self):
                    return False
        """})
        assert found == []

    def test_negative_lambda_body_runs_lock_free(self, tmp_path):
        # a lambda defined under the lock (thread target, callback)
        # runs LATER with nothing held — same rule as nested defs
        found = tree_findings(BlockingUnderLockChecker(), tmp_path,
                              {"mod.py": """
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()

                def advance(self):
                    with self._lock:
                        pass

                def kick(self, sock):
                    with self._lock:
                        t = threading.Thread(
                            target=lambda: sock.recv(1), daemon=True
                        )
                        t.start()
        """})
        assert found == []

    def test_negative_blocking_outside_the_lock(self, tmp_path):
        found = tree_findings(BlockingUnderLockChecker(), tmp_path,
                              {"mod.py": """
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()

                def advance(self):
                    with self._lock:
                        done = self.snapshot()
                    done.wait()

                def snapshot(self):
                    with self._lock:
                        return self.event
        """})
        assert found == []


# -- holds(..) markers (locks.py satellite) -----------------------------------


class TestHoldsMarkers:
    def test_multi_lock_contract_holds_both(self):
        found = file_findings(LockDisciplineChecker(), """
            class C:
                _GUARDED_BY = {"_t": ("_lock", "_work"), "_u": "_cond"}

                def helper(self):  # gol: holds(_lock, _cond)
                    return (self._t, self._u)
        """)
        assert found == []

    def test_unparsable_marker_is_loud(self):
        found = file_findings(LockDisciplineChecker(), """
            class C:
                _GUARDED_BY = {"_t": "_lock"}

                def helper(self):  # gol: holds _lock
                    return self._t
        """)
        assert any("unparsable holds marker" in f.message for f in found)

    def test_empty_marker_is_loud(self):
        found = file_findings(LockDisciplineChecker(), """
            class C:
                _GUARDED_BY = {"_t": "_lock"}

                def helper(self):  # gol: holds()
                    return self._t
        """)
        assert any("holds() names no lock" in f.message for f in found)

    def test_unknown_lock_name_is_loud(self):
        # a typo'd contract would otherwise silently hold nothing
        found = file_findings(LockDisciplineChecker(), """
            class C:
                _GUARDED_BY = {"_t": "_lock"}

                def helper(self):  # gol: holds(_locck)
                    return self._t
        """)
        assert any("guards nothing with '_locck'" in f.message for f in found)
        # and the access itself is NOT double-reported: the marker is
        # honored (held) so the contract problem is the only finding
        assert len(found) == 1

    def test_wellformed_marker_still_quiet(self):
        found = file_findings(LockDisciplineChecker(), """
            class C:
                _GUARDED_BY = {"_t": "_lock"}

                def helper(self):  # gol: holds(_lock)
                    return self._t
        """)
        assert found == []


# -- executor hygiene (hygiene.py satellite) ----------------------------------


class TestExecutorHygiene:
    def test_positive_unmanaged_pool(self):
        found = file_findings(HygieneChecker(), """
            import concurrent.futures

            def scatter(items):
                pool = concurrent.futures.ThreadPoolExecutor(4)
                return [pool.submit(f, i) for i in items]
        """)
        assert len(found) == 1
        assert "ThreadPoolExecutor" in found[0].message
        assert "shut down" in found[0].message

    def test_positive_unbound_pool(self):
        found = file_findings(HygieneChecker(), """
            from concurrent.futures import ThreadPoolExecutor

            def scatter(f, items):
                return ThreadPoolExecutor(4).map(f, items)
        """)
        assert len(found) == 1

    def test_negative_context_managed(self):
        found = file_findings(HygieneChecker(), """
            import concurrent.futures

            def scatter(f, items):
                with concurrent.futures.ThreadPoolExecutor(4) as pool:
                    return list(pool.map(f, items))
        """)
        assert found == []

    def test_negative_shutdown_in_owning_scope(self):
        # the broker turn-loop pattern: one pool per run, shutdown in
        # the finally
        found = file_findings(HygieneChecker(), """
            import concurrent.futures

            def run(f, items):
                pool = concurrent.futures.ThreadPoolExecutor(4)
                try:
                    return [x.result() for x in
                            [pool.submit(f, i) for i in items]]
                finally:
                    pool.shutdown(wait=False)
        """)
        assert found == []

    def test_negative_self_pool_shut_down_in_sibling_method(self):
        found = file_findings(HygieneChecker(), """
            import concurrent.futures

            class C:
                def start(self):
                    self._pool = concurrent.futures.ThreadPoolExecutor(2)

                def close(self):
                    self._pool.shutdown()
        """)
        assert found == []


# -- stale suppressions (core.py satellite) -----------------------------------


class TestStaleSuppressions:
    def test_unmatched_allow_is_stale_in_full_run(self, tmp_path):
        write_tree(tmp_path, {"rpc/mod.py": """
            def handler(req):
                return req.turns  # gol: allow(skew-safety): long fixed
        """})
        report = core.run(tmp_path)  # default = the full registry
        stale = [f for f in report.findings
                 if f.check == core.CHECK_STALE]
        assert len(stale) == 1
        assert "allow(skew-safety)" in stale[0].message
        assert stale[0].path == "rpc/mod.py"

    def test_matched_allow_is_not_stale(self, tmp_path):
        write_tree(tmp_path, {"rpc/mod.py": """
            def handler(req):
                return req.halo_depth  # gol: allow(skew-safety): fixture
        """})
        report = core.run(tmp_path)
        assert [f for f in report.findings
                if f.check == core.CHECK_STALE] == []
        assert [f.check for f in report.suppressed] == ["skew-safety"]

    def test_filtered_run_skips_the_stale_pass(self, tmp_path):
        # a --checks-subset run proves nothing about other checkers'
        # suppressions and must not flag them
        from gol_distributed_final_tpu.analysis.skew import SkewSafetyChecker

        write_tree(tmp_path, {"rpc/mod.py": """
            def handler(req):
                return req.turns  # gol: allow(hygiene): other checker
        """})
        report = core.run(
            tmp_path, checkers=[SkewSafetyChecker()], with_repo=True
        )
        assert [f for f in report.findings
                if f.check == core.CHECK_STALE] == []

    def test_malformed_allow_is_format_not_stale(self, tmp_path):
        # the format finding already fails the run; stale on top would
        # bury it
        write_tree(tmp_path, {"rpc/mod.py": """
            def handler(req):
                return req.turns  # gol: allow(skew-safety)
        """})
        report = core.run(tmp_path)
        checks = [f.check for f in report.findings]
        assert core.CHECK_SUPPRESSION in checks
        assert core.CHECK_STALE not in checks

    def test_multi_id_allow_reports_only_the_dead_id(self, tmp_path):
        write_tree(tmp_path, {"rpc/mod.py": """
            def handler(req):
                return req.halo_depth  # gol: allow(skew-safety, hygiene): both named
        """})
        report = core.run(tmp_path)
        stale = [f for f in report.findings
                 if f.check == core.CHECK_STALE]
        assert len(stale) == 1
        assert "allow(hygiene)" in stale[0].message
        assert "skew-safety" not in stale[0].message


# -- the runtime sanitizer ----------------------------------------------------


_ENV_ARMED = os.environ.get("GOL_LOCKSAN", "") not in ("", "0")


class TestLockSanitizer:
    @pytest.mark.skipif(_ENV_ARMED, reason="GOL_LOCKSAN armed by the env")
    def test_disabled_path_hands_out_plain_threading_objects(self):
        # GOL_LOCKSAN unset in the test environment: no wrapper type,
        # no per-acquire bookkeeping on the hot path
        assert not locksan.enabled()
        lk = locksan.lock("X")
        assert type(lk) is type(threading.Lock())
        rl = locksan.rlock("X")
        assert type(rl) is type(threading.RLock())
        cv = locksan.condition("X")
        assert type(cv) is threading.Condition

    @pytest.mark.skipif(_ENV_ARMED, reason="GOL_LOCKSAN armed by the env")
    def test_wired_classes_stay_plain_when_disabled(self):
        from gol_distributed_final_tpu.obs.flight import FlightRecorder

        fr = FlightRecorder(enabled=True)
        assert type(fr._lock) is type(threading.Lock())

    def test_order_violation_aborts_with_both_stacks(self, sanitizer):
        a, b = locksan.lock("A"), locksan.lock("B")
        with a:
            with b:
                pass
        with pytest.raises(locksan.LockOrderViolation) as exc:
            with b:
                with a:
                    pass
        msg = str(exc.value)
        assert "inverts the recorded order" in msg
        assert "acquiring thread" in msg
        assert "first-recorded conflicting edge" in msg
        assert locksan.violations()
        # evidence on disk even if a broad handler had swallowed the
        # raise — the scripts/check --locksan glob gate
        assert list(sanitizer.glob("locksan_*.txt"))

    def test_transitive_inversion_detected(self, sanitizer):
        a, b, c = locksan.lock("A"), locksan.lock("B"), locksan.lock("C")
        with a:
            with b:
                pass
        with b:
            with c:
                pass
        with pytest.raises(locksan.LockOrderViolation):
            with c:
                with a:
                    pass

    def test_consistent_order_is_quiet(self, sanitizer):
        a, b = locksan.lock("A"), locksan.lock("B")
        for _ in range(3):
            with a:
                with b:
                    pass
        assert locksan.violations() == []

    def test_nonreentrant_self_reacquire_aborts(self, sanitizer):
        a = locksan.lock("A")
        with pytest.raises(locksan.LockOrderViolation) as exc:
            with a:
                with a:
                    pass
        assert "self-deadlock" in str(exc.value)

    def test_rlock_reentry_and_condition_semantics(self, sanitizer):
        rl = locksan.rlock("R")
        with rl:
            with rl:  # legitimate reentry: no violation, no edge
                pass
        lk = locksan.lock("L")
        cv = locksan.condition("L._cv", lk)
        hits = []

        def waiter():
            with cv:
                cv.wait(timeout=2)
                hits.append(1)

        t = threading.Thread(target=waiter, daemon=True)
        t.start()
        time.sleep(0.1)
        with cv:
            cv.notify_all()
        t.join(2)
        assert hits == [1]
        assert locksan.violations() == []

    def test_try_acquire_is_not_an_ordering_commitment(self, sanitizer):
        # hold-A/try-B backoff cannot deadlock (the try never blocks):
        # it must not poison the graph with an A->B edge that a later
        # blocking B->A path then trips
        a, b = locksan.lock("A"), locksan.lock("B")
        with a:
            assert b.acquire(blocking=False)
            b.release()
        with b:
            with a:  # blocking B->A is the only committed order
                pass
        assert locksan.violations() == []

    def test_dead_locks_fall_out_of_the_registry(self, sanitizer):
        import gc

        lk = locksan.lock("Ephemeral")
        with lk:
            pass
        before = len(locksan._STATE.locks)
        del lk
        gc.collect()
        assert sum(
            1 for ref in locksan._STATE.locks if ref() is not None
        ) < before

    def test_watchdog_dumps_all_threads_on_long_hold(self, sanitizer):
        w = locksan.lock("W")

        def holder():
            with w:
                time.sleep(0.8)  # > the 0.2 s install() deadline

        def blocked():
            with w:
                pass

        h = threading.Thread(target=holder, daemon=True)
        h.start()
        time.sleep(0.05)
        b = threading.Thread(target=blocked, daemon=True)
        b.start()
        h.join(3)
        b.join(3)
        assert locksan.watchdog_fires() >= 1
        arts = list(sanitizer.glob("locksan_*.txt"))
        assert arts
        text = "\n".join(p.read_text() for p in arts)
        assert "watchdog" in text and "W" in text
        assert "--- thread" in text  # all-thread tracebacks present

    def test_short_holds_never_fire_the_watchdog(self, sanitizer):
        w = locksan.lock("W")
        for _ in range(5):
            with w:
                time.sleep(0.01)
        time.sleep(0.3)  # a full watchdog period
        assert locksan.watchdog_fires() == 0

    def test_wired_class_under_sanitizer(self, sanitizer):
        # construct-after-install: the wired factory hands back an
        # instrumented lock and the class works normally through it
        from gol_distributed_final_tpu.obs.flight import FlightRecorder

        fr = FlightRecorder(enabled=True)
        assert isinstance(fr._lock, locksan._SanLock)
        fr.record("span.open", "fixture")
        assert len(fr.snapshot()) == 1


# -- self-host ----------------------------------------------------------------


class TestSelfHost:
    def test_shipped_tree_composition_clean(self):
        """The acceptance gate: lock-order + atomicity +
        blocking-under-lock run clean over the whole package, and the
        suppression machinery is genuinely exercised — the known
        single-driver TOCTOU shapes in sessions/scheduler are allowed
        WITH justifications, not invisible."""
        report = core.run(PACKAGE_ROOT)
        assert report.clean, "\n" + report.render()
        hidden = {f.check for f in report.suppressed}
        assert "atomicity" in hidden
        assert "blocking-under-lock" in hidden

    def test_no_stale_suppressions_in_tree(self):
        report = core.run(PACKAGE_ROOT)
        assert [f for f in report.findings
                if f.check == core.CHECK_STALE] == []

    def test_new_checkers_registered_and_documented(self):
        ids = {c.id for c in all_checkers()}
        assert {"lock-order", "atomicity", "blocking-under-lock"} <= ids
