"""pod.py in-process: the multi-host packed session surface exercised on
the single-process 8-device CPU mesh (process boundaries are covered by
tests/test_multihost.py::test_two_process_pod_* via real jax.distributed
children; here the same code paths run fully addressable, which keeps the
control-plane semantics — gates, ticks, pause barrier, quit, snapshot,
checkpoint/resume — fast to iterate and deterministic)."""

import queue

import numpy as np
import pytest

from gol_distributed_final_tpu.engine.controller import CLOSED
from gol_distributed_final_tpu.events import (
    AliveCellsCount,
    FinalTurnComplete,
    ImageOutputComplete,
    Quitting,
    State,
    StateChange,
)
from gol_distributed_final_tpu.parallel import make_mesh
from gol_distributed_final_tpu.pod import (
    load_packed_from_pgm_sharded,
    pod_session,
    stream_packed_to_pgm_sharded,
)

from helpers import REPO_ROOT
from oracle import vector_step

SIZE, TURNS = 256, 20


def _random_board(seed=5, size=SIZE):
    rng = np.random.default_rng(seed)
    return np.where(rng.random((size, size)) < 0.3, 255, 0).astype(np.uint8)


def _write_pgm(path, board):
    path.parent.mkdir(parents=True, exist_ok=True)
    h, w = board.shape
    path.write_bytes(b"P5\n%d %d\n255\n" % (w, h) + board.tobytes())


def _oracle(board, turns):
    for _ in range(turns):
        board = vector_step(board)
    return board


def _drain(events):
    seq = []
    while True:
        ev = events.get(timeout=60)
        if ev is CLOSED:
            return seq
        seq.append(ev)


def test_pod_session_end_to_end(tmp_path):
    """Seed from a streamed PGM, run the session, and get the reference
    closing sequence plus a byte-exact streamed output."""
    board = _random_board()
    in_path = tmp_path / f"{SIZE}x{SIZE}.pgm"
    _write_pgm(in_path, board)
    mesh = make_mesh((2, 4))
    events = queue.Queue()

    res = pod_session(
        SIZE,
        TURNS,
        mesh,
        in_path=in_path,
        events=events,
        tick_seconds=0.001,  # every gate ticks
        out_dir=tmp_path / "out",
        min_chunk=4,
        max_chunk=4,
    )
    seq = _drain(events)
    want = _oracle(board, TURNS)

    assert res.turns_completed == TURNS
    ticks = [e for e in seq if isinstance(e, AliveCellsCount)]
    assert ticks, "no AliveCellsCount gates fired"
    # every tick's count is exact for its turn (gates land on chunk
    # boundaries: turns 4, 8, 12, 16, 20)
    by_turn = {}
    b = board
    for t in range(1, TURNS + 1):
        b = vector_step(b)
        by_turn[t] = int(np.count_nonzero(b))
    for e in ticks:
        assert e.cells_count == by_turn[e.completed_turns]
    final = [e for e in seq if isinstance(e, FinalTurnComplete)]
    assert len(final) == 1
    assert len(final[0].alive) == int(np.count_nonzero(want))
    with pytest.raises(NotImplementedError):
        list(final[0].alive)  # pod runs never materialise the cell list
    assert isinstance(seq[-2], ImageOutputComplete)
    assert (
        isinstance(seq[-1], StateChange) and seq[-1].new_state is Quitting
    )

    got = (tmp_path / "out" / f"{SIZE}x{SIZE}x{TURNS}.pgm").read_bytes()
    assert got == b"P5\n%d %d\n255\n" % (SIZE, SIZE) + want.tobytes()


def test_pod_session_pause_snapshot_quit(tmp_path):
    """The keyboard surface through the chunk gate: 's' streams a
    snapshot, 'p'/'p' pause and resume (with the turn-1 resume quirk and
    tick suppression while paused), 'k' shuts the whole session down
    early (broker/broker.go:241-249 — 'q' no longer stops the run, see
    test_pod_q_detaches_controller)."""
    import threading
    import time

    board = _random_board(6)
    in_path = tmp_path / f"{SIZE}x{SIZE}.pgm"
    _write_pgm(in_path, board)
    mesh = make_mesh((2, 4))
    events = queue.Queue()
    keys = queue.Queue()

    # feed keys with pacing from a thread: snapshot early, then a pause
    # long enough to prove frozen ticks, resume, quit
    def feed():
        keys.put("s")
        time.sleep(0.4)
        keys.put("p")
        time.sleep(0.5)
        keys.put("p")
        time.sleep(0.2)
        keys.put("k")

    feeder = threading.Thread(target=feed)
    feeder.start()
    res = pod_session(
        SIZE,
        1_000_000,  # 'k' must end it
        mesh,
        in_path=in_path,
        events=events,
        keypresses=keys,
        tick_seconds=0.05,
        out_dir=tmp_path / "out",
        min_chunk=2,
        max_chunk=2,
    )
    feeder.join()
    seq = _drain(events)
    assert 0 < res.turns_completed < 1_000_000

    changes = [e for e in seq if isinstance(e, StateChange)]
    paused = [e for e in changes if e.new_state == State.PAUSED]
    executing = [e for e in changes if e.new_state == State.EXECUTING]
    assert len(paused) == 1 and len(executing) == 1
    # the gate is the pause barrier: the turn cannot move between the
    # pause and resume events, so the quirk arithmetic is exact here
    assert executing[0].completed_turns == paused[0].completed_turns - 1
    # ticks are suppressed while paused: no AliveCellsCount strictly
    # between the two StateChanges
    i0, i1 = seq.index(paused[0]), seq.index(executing[0])
    assert not any(
        isinstance(e, AliveCellsCount) for e in seq[i0 + 1 : i1]
    ), "tick emitted while paused"
    quits = [e for e in changes if e.new_state is Quitting]
    assert len(quits) == 2  # one from 'k', one from the closing sequence
    # the snapshot (and later the final write) landed at the session path
    assert (tmp_path / "out" / f"{SIZE}x{SIZE}x1000000.pgm").exists()


def test_pod_q_detaches_controller(tmp_path):
    """Reference q semantics on the pod (VERDICT r4 item 4,
    gol/distributor.go:64-77 + README.md:187): 'q' closes the CONTROLLER
    — rank 0's event stream ends with StateChange{Quitting} then CLOSED —
    while the run itself continues headless to completion and still
    streams its output PGM. 'k' (the other test) is the coordinated full
    shutdown."""
    board = _random_board(7)
    in_path = tmp_path / f"{SIZE}x{SIZE}.pgm"
    _write_pgm(in_path, board)
    events = queue.Queue()
    keys = queue.Queue()
    keys.put("q")  # drained at the FIRST gate: detach almost immediately
    res = pod_session(
        SIZE,
        TURNS,
        mesh := make_mesh((2, 4)),
        in_path=in_path,
        events=events,
        keypresses=keys,
        tick_seconds=3600,
        out_dir=tmp_path / "out",
        min_chunk=2,
        max_chunk=2,
    )
    # the run completed EVERY turn despite the early 'q'
    assert res.turns_completed == TURNS
    seq = _drain(events)
    # the controller saw exactly the detach pair and nothing after: no
    # FinalTurnComplete / ImageOutputComplete ride a closed surface
    assert isinstance(seq[-1], StateChange) and seq[-1].new_state is Quitting
    assert seq[-1].completed_turns == 2  # the first gate
    assert not any(isinstance(e, FinalTurnComplete) for e in seq)
    assert not any(isinstance(e, ImageOutputComplete) for e in seq)
    # the output obligation stands: final PGM is golden vs the oracle
    got = (tmp_path / "out" / f"{SIZE}x{SIZE}x{TURNS}.pgm").read_bytes()
    want = _oracle(board, TURNS)
    assert got == b"P5\n%d %d\n255\n" % (SIZE, SIZE) + want.tobytes()


def test_pod_cancelled_pause_pair_still_emits_events(tmp_path):
    """Two 'p' presses drained at ONE gate cancel (the board never
    pauses) but the event stream still shows the Paused/Executing pair,
    like the reference handling each press as it arrives
    (gol/distributor.go:108-121; ADVICE r4)."""
    board = _random_board(8)
    in_path = tmp_path / f"{SIZE}x{SIZE}.pgm"
    _write_pgm(in_path, board)
    events = queue.Queue()
    keys = queue.Queue()
    keys.put("p")
    keys.put("p")  # both drain at the first gate: XOR-cancelled
    res = pod_session(
        SIZE,
        TURNS,
        make_mesh((2, 4)),
        in_path=in_path,
        events=events,
        keypresses=keys,
        tick_seconds=3600,
        out_dir=tmp_path / "out",
        min_chunk=2,
        max_chunk=2,
    )
    assert res.turns_completed == TURNS  # never actually paused
    seq = _drain(events)
    changes = [e for e in seq if isinstance(e, StateChange)]
    paused = [e for e in changes if e.new_state == State.PAUSED]
    executing = [e for e in changes if e.new_state == State.EXECUTING]
    assert len(paused) == 1 and len(executing) == 1
    # adjacent in the stream, with the same turn arithmetic a real
    # pause/resume across one gate would have shown
    i0, i1 = seq.index(paused[0]), seq.index(executing[0])
    assert i1 == i0 + 1
    assert executing[0].completed_turns == paused[0].completed_turns - 1


def test_pod_keys_behind_q_are_not_consulted(tmp_path):
    """A 'k' (or any key) queued BEHIND the 'q' in the same gate drain
    belongs to the closed controller surface: the run must still complete
    headless, not be killed by the stale 'k'."""
    board = _random_board(10)
    in_path = tmp_path / f"{SIZE}x{SIZE}.pgm"
    _write_pgm(in_path, board)
    keys = queue.Queue()
    keys.put("q")
    keys.put("k")  # behind the detach: dead surface, never consulted
    res = pod_session(
        SIZE, TURNS, make_mesh((2, 4)), in_path=in_path,
        events=queue.Queue(), keypresses=keys, tick_seconds=3600,
        out_dir=tmp_path / "out", min_chunk=2, max_chunk=2,
    )
    assert res.turns_completed == TURNS


def test_pod_k_output_holds_killed_state(tmp_path):
    """After 'k', the session PGM holds the board AS OF the kill turn —
    the reference's write-before-SuperQuit contract
    (gol/distributor.go:92-106), delivered here by the closing sequence's
    stream of the final (killed-at) state."""
    board = _random_board(11)
    in_path = tmp_path / f"{SIZE}x{SIZE}.pgm"
    _write_pgm(in_path, board)
    keys = queue.Queue()
    keys.put("k")
    res = pod_session(
        SIZE, 1_000_000, make_mesh((2, 4)), in_path=in_path,
        events=queue.Queue(), keypresses=keys, tick_seconds=3600,
        out_dir=tmp_path / "out", min_chunk=2, max_chunk=2,
    )
    assert res.turns_completed == 2
    got = (tmp_path / "out" / f"{SIZE}x{SIZE}x1000000.pgm").read_bytes()
    want = _oracle(board, 2)
    assert got == b"P5\n%d %d\n255\n" % (SIZE, SIZE) + want.tobytes()


def test_pod_q_streams_snapshot_at_detach_gate(tmp_path, monkeypatch):
    """'q' streams the CURRENT state at the detach gate (the reference's
    write-before-quit, gol/distributor.go:63-77) — for a detached run
    this is the only on-disk copy until completion overwrites it. Pinned
    by recording the stream calls: one at the gate with the turn-2 board,
    one from the closing sequence with the final board."""
    import gol_distributed_final_tpu.pod as pod_mod

    board = _random_board(12)
    in_path = tmp_path / f"{SIZE}x{SIZE}.pgm"
    _write_pgm(in_path, board)
    streams = []
    real = pod_mod.stream_packed_to_pgm_sharded

    def spy(path, state, word_axis, row_block):
        from gol_distributed_final_tpu.ops.bitpack import unpack
        streams.append(unpack(np.asarray(state), word_axis))
        return real(path, state, word_axis, row_block)

    monkeypatch.setattr(pod_mod, "stream_packed_to_pgm_sharded", spy)
    keys = queue.Queue()
    keys.put("q")
    res = pod_session(
        SIZE, TURNS, make_mesh((2, 4)), in_path=in_path,
        events=queue.Queue(), keypresses=keys, tick_seconds=3600,
        out_dir=tmp_path / "out", min_chunk=2, max_chunk=2,
    )
    assert res.turns_completed == TURNS
    assert len(streams) == 2, f"{len(streams)} stream calls"
    np.testing.assert_array_equal(streams[0], _oracle(board, 2))
    np.testing.assert_array_equal(streams[1], _oracle(board, TURNS))


def test_pod_rejects_depth_too_deep_for_blocks(tmp_path):
    """A board whose packed layout cannot carry the requested halo depth
    fails at session entry with an error naming the knob — not hours in
    with a shard_map error."""
    board = _random_board(9, size=64)
    in_path = tmp_path / "64x64.pgm"
    _write_pgm(in_path, board)
    with pytest.raises(ValueError, match="halo_depth=2"):
        pod_session(
            64, 10, make_mesh((2, 4)), in_path=in_path,
            events=queue.Queue(), tick_seconds=3600,
            out_dir=tmp_path / "out", halo_depth=2,
        )


def test_pod_pause_pair_order_matches_state(tmp_path):
    """The cancelled-pair events mirror what press-at-a-time handling
    would emit: Paused/Executing from a running board, but
    Executing/Paused (resume, re-pause) when drained INSIDE the pause
    barrier — the stream must never end on a state opposite to
    reality."""
    from gol_distributed_final_tpu.events import State, StateChange
    from gol_distributed_final_tpu.pod import _PodControl
    from gol_distributed_final_tpu.params import Params

    def pair_events(paused):
        events = queue.Queue()
        control = _PodControl(
            Params(turns=4, image_width=64, image_height=64),
            events, queue.Queue(), tmp_path / "x.pgm", 0, 64, 3600, True,
        )
        control.paused = paused
        control._pause_pairs = 1
        control._apply(None, None, 3, 0)
        out = []
        while not events.empty():
            out.append(events.get_nowait())
        return [(e.completed_turns, e.new_state) for e in out
                if isinstance(e, StateChange)]

    assert pair_events(False) == [(3, State.PAUSED), (2, State.EXECUTING)]
    assert pair_events(True) == [(2, State.EXECUTING), (3, State.PAUSED)]


def test_pod_checkpoint_and_resume(tmp_path):
    """Periodic per-rank checkpoints + resume: interrupt nothing, just
    verify the turn-16 checkpoint a 20-turn run leaves behind resumes to
    a byte-identical final board."""
    board = _random_board(7)
    in_path = tmp_path / f"{SIZE}x{SIZE}.pgm"
    _write_pgm(in_path, board)
    mesh = make_mesh((2, 4))
    ck = tmp_path / "podck.npz"

    res = pod_session(
        SIZE,
        TURNS,
        mesh,
        in_path=in_path,
        events=queue.Queue(),
        tick_seconds=3600,
        out_dir=tmp_path / "out",
        checkpoint_every=8,
        checkpoint_path=ck,
        min_chunk=4,
        max_chunk=4,
    )
    assert res.turns_completed == TURNS

    from gol_distributed_final_tpu.engine.checkpoint import (
        load_packed_checkpoint_sharded,
    )
    from gol_distributed_final_tpu.parallel.bit_halo import packed_sharding

    # a single-process run's state is fully addressable, so the engine
    # wrote the PLAIN packed format; the sharded loader accepts it (the
    # one-host <-> pod interop path), holding the LAST mid-run crossing
    assert ck.exists()
    state, turn, rule, word_axis = load_packed_checkpoint_sharded(
        ck, packed_sharding(mesh)
    )
    assert turn == 16 and rule.rulestring == "B3/S23" and word_axis == 0

    res2 = pod_session(
        SIZE,
        TURNS,
        mesh,
        resume_from=ck,
        events=queue.Queue(),
        tick_seconds=3600,
        out_dir=tmp_path / "out2",
        min_chunk=4,
        max_chunk=4,
    )
    assert res2.turns_completed == TURNS
    direct = (tmp_path / "out" / f"{SIZE}x{SIZE}x{TURNS}.pgm").read_bytes()
    resumed = (tmp_path / "out2" / f"{SIZE}x{SIZE}x{TURNS}.pgm").read_bytes()
    assert resumed == direct
    want = _oracle(board, TURNS)
    assert direct == b"P5\n%d %d\n255\n" % (SIZE, SIZE) + want.tobytes()


def test_pod_sharded_pgm_roundtrip(tmp_path):
    """load_packed_from_pgm_sharded -> stream_packed_to_pgm_sharded is an
    identity on the bytes, and the loaded state is the mesh-sharded
    packing of the on-disk board."""
    board = _random_board(8)
    in_path = tmp_path / f"{SIZE}x{SIZE}.pgm"
    _write_pgm(in_path, board)
    mesh = make_mesh((2, 4))
    state = load_packed_from_pgm_sharded(in_path, mesh)
    from gol_distributed_final_tpu.ops.bitpack import (
        alive_count_packed,
        pack,
    )

    np.testing.assert_array_equal(np.asarray(state), pack(board, 0))
    assert alive_count_packed(state) == int(np.count_nonzero(board))
    out = tmp_path / "round.pgm"
    stream_packed_to_pgm_sharded(out, state, row_block=64)
    assert out.read_bytes() == in_path.read_bytes()


def test_pod_session_rejects_stale_resume(tmp_path):
    """A resume whose turns target is not beyond the checkpoint, or whose
    rule disagrees, is rejected before anything runs."""
    from gol_distributed_final_tpu.bigboard import seed_packed
    from gol_distributed_final_tpu.engine.checkpoint import (
        save_packed_checkpoint_sharded,
    )
    from gol_distributed_final_tpu.models import HIGHLIFE

    mesh = make_mesh((2, 4))
    ck = tmp_path / "ck.npz"
    state = seed_packed(SIZE, [(10, 10), (11, 10), (12, 10)])
    save_packed_checkpoint_sharded(ck, state, 30)
    with pytest.raises(ValueError, match="not beyond"):
        pod_session(SIZE, 30, mesh, resume_from=ck, events=queue.Queue())
    with pytest.raises(ValueError, match="rule"):
        pod_session(
            SIZE, 60, mesh, resume_from=ck, rule=HIGHLIFE,
            events=queue.Queue(),
        )


def test_decode_window_sharded_single_host_fallback(tmp_path):
    """On a fully-addressable state the pod window decode equals the
    local one (the gather branch is exercised by the 2-process child,
    tests/multihost_pod_child.py)."""
    from gol_distributed_final_tpu.bigboard import decode_window
    from gol_distributed_final_tpu.pod import decode_window_sharded

    board = _random_board(9)
    in_path = tmp_path / f"{SIZE}x{SIZE}.pgm"
    _write_pgm(in_path, board)
    mesh = make_mesh((2, 4))
    state = load_packed_from_pgm_sharded(in_path, mesh)
    got = decode_window_sharded(state, 32, 48, 64, 96)
    np.testing.assert_array_equal(got, decode_window(state, 32, 48, 64, 96))
    np.testing.assert_array_equal(got, board[32:96, 48:144])


def test_pod_session_column_packed_layout(tmp_path):
    """A geometry where only COLUMN packing divides (96^2 over an (8,1)
    mesh: 96 % (32*8) != 0 but 96 % 8 == 0 and 96 % 32 == 0) must route
    the whole session — seeding, evolution, streaming — through the
    word_axis=1 layout and still land oracle-exact."""
    from gol_distributed_final_tpu.parallel.bit_halo import choose_bit_layout

    size, turns = 96, 12
    board = _random_board(10, size)
    in_path = tmp_path / f"{size}x{size}.pgm"
    _write_pgm(in_path, board)
    mesh = make_mesh((8, 1))
    assert choose_bit_layout((size, size), (8, 1)) == 1  # the premise

    res = pod_session(
        size,
        turns,
        mesh,
        in_path=in_path,
        events=queue.Queue(),
        tick_seconds=0.001,
        out_dir=tmp_path / "out",
        min_chunk=4,
        max_chunk=4,
    )
    assert res.turns_completed == turns
    want = _oracle(board, turns)
    assert len(res.alive) == int(np.count_nonzero(want))
    got = (tmp_path / "out" / f"{size}x{size}x{turns}.pgm").read_bytes()
    assert got == b"P5\n%d %d\n255\n" % (size, size) + want.tobytes()


def test_load_packed_from_pgm_sharded_rejects_indivisible(tmp_path):
    board = np.zeros((48, 48), np.uint8)  # 48 % 32 != 0
    in_path = tmp_path / "48x48.pgm"
    _write_pgm(in_path, board)
    mesh = make_mesh((2, 4))
    with pytest.raises(ValueError, match="not divisible"):
        load_packed_from_pgm_sharded(in_path, mesh)
