"""Child process for the 2-process POD end-to-end test (VERDICT round-3
item 1: config 5 at its real topology — multi-host x packed board).

One rank of a real ``jax.distributed`` job at 2048^2 packed. Phase 1 runs
``pod_session`` from a streamed PGM with periodic per-rank checkpoints and
a scripted 's' snapshot; phase 2 resumes from the mid-run checkpoint in a
fresh engine and must land on the identical final board. Every host-side
byte that moves — input read, snapshot, checkpoint shard, final output —
touches only this rank's rows.

Usage: multihost_pod_child.py <coordinator> <num_procs> <proc_id> <tmpdir>
       <size> <turns>
"""

import pathlib
import queue
import sys


def main() -> int:
    coordinator, num_procs, proc_id, tmpdir, size, turns = sys.argv[1:7]
    num_procs, proc_id = int(num_procs), int(proc_id)
    size, turns = int(size), int(turns)
    tmpdir = pathlib.Path(tmpdir)

    import jax

    from gol_distributed_final_tpu.engine.checkpoint import (
        checkpoint_shard_path,
    )
    from gol_distributed_final_tpu.engine.controller import CLOSED
    from gol_distributed_final_tpu.events import (
        AliveCellsCount,
        FinalTurnComplete,
        ImageOutputComplete,
        Quitting,
        StateChange,
    )
    from gol_distributed_final_tpu.parallel import make_mesh, multihost
    from gol_distributed_final_tpu.pod import pod_session

    assert multihost.initialize(coordinator, num_procs, proc_id)
    devices = jax.devices()
    assert len(devices) == 4 * num_procs
    mesh = make_mesh((num_procs, 4), devices=devices)
    ck = tmpdir / "podck.npz"

    # phase 1: session from the parent-written PGM, checkpoints every 8
    # turns (the last mid-run crossing for turns=20 is 16), one scripted
    # snapshot pressed before the run starts (lands at the first gate)
    events: "queue.Queue" = queue.Queue()
    keys: "queue.Queue" = queue.Queue()
    if proc_id == 0:
        keys.put("s")
    res = pod_session(
        size,
        turns,
        mesh,
        in_path=tmpdir / f"{size}x{size}.pgm",
        events=events,
        keypresses=keys,
        tick_seconds=0.001,  # every gate ticks
        out_dir=tmpdir / "out",
        checkpoint_every=8,
        checkpoint_path=ck,
        min_chunk=4,
        max_chunk=4,
    )
    assert res.turns_completed == turns

    seq = []
    while True:
        ev = events.get(timeout=10)
        if ev is CLOSED:
            break
        seq.append(ev)
    if proc_id == 0:
        ticks = [e for e in seq if isinstance(e, AliveCellsCount)]
        assert ticks, "no tick events on the controller rank"
        final = [e for e in seq if isinstance(e, FinalTurnComplete)]
        assert len(final) == 1 and len(final[0].alive) >= 0
        assert any(isinstance(e, ImageOutputComplete) for e in seq)
        assert isinstance(seq[-1], StateChange) and seq[-1].new_state is Quitting
        # ticks report the count every rank agreed on via the collective
        print(f"rank 0 saw {len(ticks)} ticks, final alive {len(final[0].alive)}")
    else:
        assert not seq, "non-root ranks must not emit events"

    # this rank's checkpoint shard exists and stamps the mid-run turn
    import numpy as np

    shard = checkpoint_shard_path(ck, proc_id, num_procs)
    assert shard.exists(), f"missing checkpoint shard {shard}"
    with np.load(shard, allow_pickle=False) as data:
        assert int(data["turn"]) == 16, int(data["turn"])
        assert int(data["num_processes"]) == num_procs

    # phase 2: resume from turn 16 in a fresh engine — WITH wide halos
    # (halo_depth=4: with 4 turns remaining and chunk=4, each dispatch is
    # EXACTLY one wide iteration — n // depth = 1, no single-step
    # remainder — so a genuine 4-deep halo ppermute crosses the process
    # boundary; a deeper setting would silently fall into the remainder
    # path and exercise nothing wide). Resume x temporal blocking proven
    # cross-host; the end must still be byte-identical.
    res2 = pod_session(
        size,
        turns,
        mesh,
        resume_from=ck,
        events=queue.Queue(),
        tick_seconds=3600,
        out_dir=tmpdir / "out2",
        min_chunk=4,
        max_chunk=4,
        halo_depth=4,
    )
    assert res2.turns_completed == turns

    # pod-scale inspection: the collective window decode returns the same
    # board region on EVERY rank, matching the streamed PGM on disk
    from gol_distributed_final_tpu.io.sharded import read_shard
    from gol_distributed_final_tpu.pod import decode_window_sharded

    c = size // 2
    state2 = res2._state  # the final mesh-sharded packed board
    assert not state2.is_fully_addressable
    win = decode_window_sharded(state2, c - 64, c - 64, 128, 128)
    rows = read_shard(tmpdir / "out2" / f"{size}x{size}x{turns}.pgm", c - 64, c + 64)
    np.testing.assert_array_equal(win, rows[:, c - 64 : c + 64])

    # phase 3 (ADVICE r4): a bad shard must fail a resume CLEANLY on every
    # rank — per-rank validation errors are agreed collectively before any
    # raise, so the GOOD rank gets a ValueError naming the failed peer
    # instead of stranding forever inside the turn allgather
    from gol_distributed_final_tpu.engine.checkpoint import (
        load_packed_checkpoint_sharded,
    )
    from gol_distributed_final_tpu.parallel.bit_halo import packed_sharding

    if proc_id == 1:
        # corrupt THIS rank's own shard: stamp an impossible process count
        with np.load(shard, allow_pickle=False) as data:
            fields = {k: data[k] for k in data.files}
        fields["num_processes"] = np.int64(3)
        np.savez(shard, **fields)
    try:
        load_packed_checkpoint_sharded(ck, packed_sharding(mesh))
        raise AssertionError("load of a corrupt shard set must fail")
    except ValueError as exc:
        msg = str(exc)
        if proc_id == 1:
            assert "was written by 3" in msg, msg  # the local validation
        else:
            assert "failed on 1 other rank" in msg, msg  # the agreement

    # phase 4 (VERDICT r4 item 6): a DIRECT Engine run with default
    # chunking on a multi-host state must honor the dispatch-time target —
    # the ranks agree on the slowest rank's elapsed, so growth stops
    # identically everywhere (no SPMD desync) and gates stay dense. With a
    # sub-microsecond target, growth must stop at chunk=1: one gate per
    # turn. Under the old pure-doubling behavior the gates would land at
    # 1,3,7,... and this assertion fails.
    from gol_distributed_final_tpu.engine.engine import Engine, EngineConfig
    from gol_distributed_final_tpu.params import Params
    from gol_distributed_final_tpu.parallel.bit_halo import make_bit_plane

    plane = make_bit_plane(mesh, (size, size))
    gates = []
    eng = Engine(
        EngineConfig(
            final_world=False,
            target_dispatch_seconds=1e-9,
            chunk_hook=lambda e, s, t: gates.append(t),
        )
    )
    res3 = eng.run(
        Params(turns=6, image_width=size, image_height=size),
        None,
        plane=plane,
        initial_state=res2._state,
    )
    assert res3.turns_completed == 6
    assert gates == [1, 2, 3, 4, 5, 6], gates

    print(f"rank {proc_id} done", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
