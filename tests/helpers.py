"""Shared test helpers — ports of the reference harness support code
(gol_test.go:58-129, count_test.go:71-89), implemented over this framework's
own PGM codec."""

import csv
import pathlib

from gol_distributed_final_tpu.io.pgm import read_pgm
from gol_distributed_final_tpu.ops import alive_cells
from gol_distributed_final_tpu.utils import Cell, alive_cells_to_string

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent


def read_alive_cells(pgm_path) -> set[Cell]:
    """Alive-cell set parsed from a golden PGM (gol_test.go:88-129)."""
    return set(alive_cells(read_pgm(pgm_path)))


def read_alive_counts(csv_path) -> dict[int, int]:
    """completed_turns -> alive_cells from a golden CSV (count_test.go:71-89)."""
    with open(csv_path) as f:
        rows = csv.DictReader(f)
        return {int(r["completed_turns"]): int(r["alive_cells"]) for r in rows}


def assert_equal_board(given, expected, width, height):
    """Multiset equality of alive cells, pretty-printed on small-board
    failure like gol_test.go:42-56."""
    given, expected = set(given), set(expected)
    if given != expected:
        msg = f"{len(given)} alive cells given, {len(expected)} expected"
        if width <= 16 and height <= 16:
            msg += "\n" + alive_cells_to_string(given, expected, width, height)
        raise AssertionError(msg)


def oracle_window(size: int, turns: int, win: int, cells=None):
    """Exact evolution of the populated centre window of a big sparse
    board (default seed: the centred R-pentomino). Valid while the
    pattern's envelope stays inside the window — the caller picks `win`
    with margin (an R-pentomino's 100-turn envelope fits 512^2 easily)."""
    import numpy as np

    from oracle import vector_step

    if cells is None:
        from gol_distributed_final_tpu.bigboard import r_pentomino

        cells = r_pentomino(size)
    w0 = size // 2 - win // 2
    window = np.zeros((win, win), np.uint8)
    for x, y in cells:
        window[y - w0, x - w0] = 255
    for _ in range(turns):
        window = vector_step(window)
    return window
