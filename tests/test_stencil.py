"""Unit tests for the core stencil ops against an independent NumPy oracle."""

import numpy as np
import pytest

import jax.numpy as jnp

from gol_distributed_final_tpu.models import CONWAY, DAY_AND_NIGHT, HIGHLIFE, SEEDS
from gol_distributed_final_tpu.ops import (
    alive_cells,
    alive_count,
    neighbour_counts,
    step,
    step_n,
)
from gol_distributed_final_tpu.utils import Cell

from oracle import naive_step, vector_step


def random_board(h, w, seed=0, density=0.3):
    rng = np.random.default_rng(seed)
    return np.where(rng.random((h, w)) < density, 255, 0).astype(np.uint8)


def test_neighbour_counts_blinker():
    board = np.zeros((5, 5), np.uint8)
    board[2, 1:4] = 255  # horizontal blinker
    n = np.asarray(neighbour_counts(jnp.asarray(board)))
    assert n[2, 2] == 2  # centre sees its two arms
    assert n[1, 2] == 3 and n[3, 2] == 3  # birth sites above/below centre
    assert n[2, 1] == 1 and n[2, 3] == 1


def test_blinker_oscillates():
    board = np.zeros((5, 5), np.uint8)
    board[2, 1:4] = 255
    one = np.asarray(step(jnp.asarray(board)))
    expected = np.zeros((5, 5), np.uint8)
    expected[1:4, 2] = 255  # vertical phase
    np.testing.assert_array_equal(one, expected)
    two = np.asarray(step(jnp.asarray(one)))
    np.testing.assert_array_equal(two, board)


def test_toroidal_wrap_glider_crosses_edge():
    # glider at the corner must wrap, like worker/worker.go:48-63's edge cases
    board = np.zeros((8, 8), np.uint8)
    for x, y in [(1, 0), (2, 1), (0, 2), (1, 2), (2, 2)]:
        board[y, x] = 255
    b = board
    for _ in range(4 * 8):  # a glider translates by (1,1) every 4 turns
        b = np.asarray(step(jnp.asarray(b)))
    np.testing.assert_array_equal(b, board)


@pytest.mark.parametrize("shape", [(1, 1), (2, 3), (5, 5), (16, 16), (17, 13), (64, 64)])
def test_step_matches_naive_oracle(shape):
    board = random_board(*shape, seed=shape[0] * 100 + shape[1])
    got = np.asarray(step(jnp.asarray(board)))
    want = naive_step(board)
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize(
    "rule,birth,survive",
    [
        (CONWAY, (3,), (2, 3)),
        (HIGHLIFE, (3, 6), (2, 3)),
        (SEEDS, (2,), ()),
        (DAY_AND_NIGHT, (3, 6, 7, 8), (3, 4, 6, 7, 8)),
    ],
)
def test_rule_family_matches_oracle(rule, birth, survive):
    board = random_board(32, 32, seed=7)
    got = np.asarray(rule.step(jnp.asarray(board)))
    want = naive_step(board, birth=birth, survive=survive)
    np.testing.assert_array_equal(got, want)


def test_rulestring_roundtrip():
    assert CONWAY.rulestring == "B3/S23"
    assert HIGHLIFE.rulestring == "B36/S23"
    assert SEEDS.rulestring == "B2/S"


def test_step_n_equals_repeated_step():
    board = random_board(32, 48, seed=3)
    chunk = np.asarray(step_n(jnp.asarray(board), 17))
    b = board
    for _ in range(17):
        b = vector_step(b)
    np.testing.assert_array_equal(chunk, b)


def test_step_n_zero_is_identity():
    board = random_board(8, 8, seed=1)
    np.testing.assert_array_equal(np.asarray(step_n(jnp.asarray(board), 0)), board)


def test_alive_reductions():
    board = np.zeros((4, 6), np.uint8)
    board[0, 1] = 255
    board[3, 5] = 255
    board[2, 0] = 255
    assert int(alive_count(jnp.asarray(board))) == 3
    cells = alive_cells(board)
    assert set(cells) == {Cell(1, 0), Cell(0, 2), Cell(5, 3)}
    # row-major like broker/broker.go:47-58
    assert cells == [Cell(1, 0), Cell(0, 2), Cell(5, 3)]


def test_values_stay_0_or_255():
    board = random_board(16, 16, seed=9)
    out = np.asarray(step_n(jnp.asarray(board), 5))
    assert set(np.unique(out)).issubset({0, 255})
