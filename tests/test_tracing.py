"""Distributed-tracing + flight-recorder tests (obs/tracing.py, obs/flight.py):
the cross-process trace over a live broker+worker subprocess pair, the
Chrome trace-event export schema, ring wraparound, dump-on-exception, the
structured RPC error replies, version-skew pickles without ``trace_ctx``,
the no-op path, and the span-name lint.
"""

import json
import queue

import numpy as np
import pytest

from gol_distributed_final_tpu import Params, run
from gol_distributed_final_tpu.io.pgm import read_board
from gol_distributed_final_tpu.obs import flight as obs_flight
from gol_distributed_final_tpu.obs import tracing as obs_tracing
from gol_distributed_final_tpu.obs.flight import FlightRecorder
from gol_distributed_final_tpu.obs.tracing import (
    Tracer,
    to_chrome_trace,
    write_chrome_trace,
)
from gol_distributed_final_tpu.rpc.client import RemoteBroker, RpcClient, RpcError
from gol_distributed_final_tpu.rpc.protocol import Methods, Request

from helpers import REPO_ROOT
from test_rpc import _spawn, _wait_listening

# the keys Perfetto's trace-event importer requires on a complete event
PERFETTO_KEYS = ("ph", "ts", "pid", "tid", "name")


@pytest.fixture
def live_tracing():
    """Enable the process-global tracer + flight recorder for one test,
    zeroed before and disabled+zeroed after — every other test must keep
    seeing the one-flag-check no-op default."""
    tr, fr = obs_tracing.tracer(), obs_flight.recorder()
    tr.reset()
    fr.reset()
    obs_tracing.enable()
    obs_tracing.set_process_name("controller")
    obs_flight.enable()
    yield tr
    obs_tracing.enable(False)
    obs_flight.enable(False)
    obs_tracing.set_process_name("")
    tr.reset()
    fr.reset()


# -- unit: tracer semantics ---------------------------------------------------


def test_span_parenting_and_ring():
    t = Tracer(enabled=True, capacity=4)
    with t.span("outer") as outer:
        assert t.current_ctx()["span_id"] == outer.span_id
        with t.span("inner") as inner:
            assert inner.trace_id == outer.trace_id
            assert inner.parent_id == outer.span_id
    spans = t.snapshot()
    assert [s["name"] for s in spans] == ["inner", "outer"]  # close order
    # ring wraparound: capacity 4 keeps only the newest 4
    for i in range(10):
        t.end_span(t.start_span(f"s{i}"))
    assert [s["name"] for s in t.snapshot()] == ["s6", "s7", "s8", "s9"]


def test_explicit_parent_ctx_crosses_threads():
    """The wire/pool form: an explicit parent context joins the trace even
    where the thread-local stack is empty (RPC server, scatter pool)."""
    import threading

    t = Tracer(enabled=True)
    root = t.start_span("root")
    ctx = root.ctx()
    done = threading.Event()

    def worker():
        t.end_span(t.start_span("child", parent_ctx=ctx))
        done.set()

    threading.Thread(target=worker).start()
    assert done.wait(5)
    t.end_span(root)
    child, root_rec = t.snapshot()
    assert child["trace_id"] == root_rec["trace_id"]
    assert child["parent_id"] == root_rec["span_id"]


def test_unsampled_trace_records_nothing_but_propagates():
    t = Tracer(enabled=True)
    t.sample_rate = 0.0
    root = t.start_span("root")
    assert root is not None and not root.sampled
    # the decision propagates: a child under an unsampled context is
    # unsampled too (remote processes won't record either)
    child = t.start_span("child", parent_ctx=root.ctx())
    t.end_span(child)
    t.end_span(root)
    assert t.snapshot() == []


def test_chrome_export_schema_and_tracks():
    """Every exported event carries the Perfetto-required keys; span
    records from several processes become distinct named tracks."""
    spans = [
        {
            "name": "rpc.client.call", "trace_id": "t1", "span_id": f"s{i}",
            "parent_id": "", "pid": 100 + i, "tid": 1, "role": role,
            "ts_us": 1000 * i, "dur_us": 500,
            "args": {"method": "Operations.Run"},
        }
        for i, role in enumerate(["controller", "broker", "worker:1"])
    ]
    doc = to_chrome_trace(spans)
    assert set(doc) == {"traceEvents", "displayTimeUnit"}
    for ev in doc["traceEvents"]:
        for key in PERFETTO_KEYS:
            assert key in ev, f"{key} missing from {ev}"
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    metas = {
        e["args"]["name"]
        for e in doc["traceEvents"]
        if e["ph"] == "M" and e["name"] == "process_name"
    }
    assert len(xs) == 3 and all(e["dur"] >= 1 for e in xs)
    assert metas == {"controller", "broker", "worker:1"}
    # verb rides the display name; ids ride args for trace reassembly
    assert xs[0]["name"] == "rpc.client.call Operations.Run"
    assert xs[0]["args"]["trace_id"] == "t1"


def test_disabled_tracer_is_noop_without_allocations():
    """The acceptance bound: with -trace off an instrumented site costs a
    flag check — start_span returns None before ANY allocation (no Span,
    no ids, no clock reads), measured via tracemalloc against the module."""
    import tracemalloc

    assert not obs_tracing.enabled()
    assert obs_tracing.start_span(obs_tracing.SPAN_ENGINE_CHUNK) is None
    assert obs_tracing.current_ctx() is None
    obs_tracing.end_span(None)  # None-safe
    tracemalloc.start()
    try:
        obs_tracing.start_span(obs_tracing.SPAN_ENGINE_CHUNK)  # warm
        before = tracemalloc.take_snapshot()
        for _ in range(200):
            obs_tracing.end_span(
                obs_tracing.start_span(obs_tracing.SPAN_ENGINE_CHUNK)
            )
            obs_flight.record("rpc.send", "Operations.Run")
        after = tracemalloc.take_snapshot()
    finally:
        tracemalloc.stop()
    grown = [
        stat
        for stat in after.compare_to(before, "filename")
        if stat.size_diff > 0
        and stat.traceback[0].filename
        in (obs_tracing.__file__, obs_flight.__file__)
    ]
    assert not grown, f"disabled-path allocations: {grown}"
    assert obs_tracing.tracer().snapshot() == []
    assert obs_flight.recorder().snapshot() == []


# -- unit: flight recorder ----------------------------------------------------


def test_flight_ring_wraparound_and_dump(tmp_path):
    fr = FlightRecorder(capacity=8, enabled=True)
    for i in range(20):
        fr.record("rpc.send", f"verb{i}", i=i)
    events = fr.snapshot()
    assert len(events) == 8  # bounded
    assert [e["args"]["i"] for e in events] == list(range(12, 20))  # newest
    assert [e["seq"] for e in events] == list(range(13, 21))  # seq never resets
    path = fr.dump(tmp_path / "flight_test.jsonl")
    lines = [json.loads(line) for line in path.read_text().splitlines()]
    assert len(lines) == 8 and lines[-1]["name"] == "verb19"


def test_engine_crash_dumps_flight_ring(tmp_path, live_tracing):
    """An unhandled engine exception leaves out/flight_<host>.jsonl behind,
    ending with the crash event — the post-mortem the hang/crash class of
    bug otherwise destroys."""
    from gol_distributed_final_tpu.engine.engine import Engine

    obs_flight.set_dump_dir(tmp_path)
    try:
        def boom(board, n):
            raise RuntimeError("kernel exploded")

        p = Params(turns=4, threads=8, image_width=16, image_height=16)
        board = read_board(p, REPO_ROOT / "images")
        with pytest.raises(RuntimeError, match="kernel exploded"):
            Engine().run(p, board, step_n_fn=boom)
    finally:
        obs_flight.set_dump_dir("out")
    path = obs_flight.crash_dump_path(tmp_path)
    assert path.exists()
    lines = [json.loads(line) for line in path.read_text().splitlines()]
    assert lines[-1]["kind"] == "crash"
    assert lines[-1]["name"] == "RuntimeError"
    assert "kernel exploded" in lines[-1]["args"]["message"]


def test_flight_disabled_records_and_dumps_nothing(tmp_path):
    assert not obs_flight.enabled()
    obs_flight.record("rpc.send", "x")
    assert obs_flight.recorder().snapshot() == []
    assert obs_flight.dump_on_crash(RuntimeError("x"), tmp_path) is None
    assert not list(tmp_path.iterdir())


# -- the utils/trace.py guard fix ---------------------------------------------


def test_profiler_trace_stops_when_body_raises(tmp_path, monkeypatch):
    import types

    import jax

    from gol_distributed_final_tpu.utils.trace import trace

    calls = []
    monkeypatch.setattr(
        jax, "profiler", types.SimpleNamespace(
            start_trace=lambda d: calls.append("start"),
            stop_trace=lambda: calls.append("stop"),
        ),
    )
    with pytest.raises(RuntimeError, match="body"):
        with trace(tmp_path / "tr"):
            raise RuntimeError("body")
    assert calls == ["start", "stop"], "a raising body must still stop"


def test_profiler_trace_start_failure_skips_stop(tmp_path, monkeypatch):
    import types

    import jax

    from gol_distributed_final_tpu.utils.trace import trace

    calls = []

    def bad_start(d):
        calls.append("start")
        raise OSError("profiler unavailable")

    monkeypatch.setattr(
        jax, "profiler", types.SimpleNamespace(
            start_trace=bad_start,
            stop_trace=lambda: calls.append("stop"),
        ),
    )
    with pytest.raises(OSError, match="profiler unavailable"):
        with trace(tmp_path / "tr"):
            pass  # pragma: no cover - never reached
    assert calls == ["start"], "stop on a never-started profiler masks the error"


# -- structured RPC error replies ---------------------------------------------


def test_rpc_error_carries_kind_and_remote_traceback():
    """A handler-side failure names the exception class and raise site in
    the reply (RpcError.kind / .remote_traceback), instead of only an
    opaque message string."""
    from gol_distributed_final_tpu.rpc.broker import serve

    server, service = serve(port=0)
    client = RpcClient(f"127.0.0.1:{server.port}")
    try:
        bad = Request(
            world=np.zeros((8, 8), np.uint8), turns=4,
            image_width=16, image_height=16,  # shape mismatch -> ValueError
        )
        with pytest.raises(RpcError) as err:
            client.call(Methods.BROKER_RUN, bad)
        assert err.value.kind == "ValueError"
        assert "does not match params" in str(err.value)
        # the traceback tail names the raise site, truncated server-side
        assert "broker.py" in err.value.remote_traceback
        assert len(err.value.remote_traceback) <= 2000
    finally:
        client.close()
        server.stop()


def test_rpc_error_without_structured_fields_degrades(monkeypatch):
    """An OLD server's error reply has no error_kind/error_traceback keys:
    the client must surface a plain RpcError with kind None."""
    err = RpcError("boom")
    assert err.kind is None and err.remote_traceback is None


def test_flight_records_rpc_error(live_tracing):
    from gol_distributed_final_tpu.rpc.broker import serve

    server, service = serve(port=0)
    client = RpcClient(f"127.0.0.1:{server.port}")
    try:
        with pytest.raises(RpcError):
            client.call(Methods.BROKER_RUN, Request(turns=-1))
    finally:
        client.close()
        server.stop()
    kinds = {(e["kind"], e["name"]) for e in obs_flight.recorder().snapshot()}
    # both ends run in this process: the server-side structured error
    # record and the client-side failed-receive record
    assert ("rpc.error", Methods.BROKER_RUN) in kinds
    assert ("rpc.recv", Methods.BROKER_RUN) in kinds


# -- version skew -------------------------------------------------------------


def test_request_pickle_without_trace_ctx_is_served():
    """A version-skewed client's Request pickle predates trace_ctx: a
    TRACING server must read it via getattr and serve the default (no
    trace), never an AttributeError reply."""
    broker = _spawn(
        "gol_distributed_final_tpu.rpc.broker", "-port", "0", "-trace"
    )
    try:
        port = _wait_listening(broker)
        client = RpcClient(f"127.0.0.1:{port}")
        try:
            p = Params(turns=4, threads=8, image_width=16, image_height=16)
            board = read_board(p, REPO_ROOT / "images")
            req = Request(
                world=board, turns=4, image_width=16, image_height=16
            )
            del req.__dict__["trace_ctx"]  # the old client's pickle shape
            res = client.call(Methods.BROKER_RUN, req)
            assert res.turns_completed == 4
            # the reply from a tracing server still carries ITS span ctx
            # (harmless to an old client, linkable for a new one)
            assert getattr(res, "trace_ctx", None) is not None
        finally:
            client.close()
    finally:
        if broker.poll() is None:
            broker.kill()
        broker.wait()


# -- the acceptance path: live three-process trace ----------------------------


def test_cross_process_trace_spans_share_one_trace_id(tmp_path, live_tracing):
    """A -trace session over a live broker + 2-worker subprocess pair
    exports a Chrome trace whose events carry the Perfetto-required keys,
    with >= 3 distinct process tracks (controller, broker, worker) and
    every RPC span sharing ONE trace_id — the cross-process propagation
    contract, end to end."""
    workers = [
        _spawn("gol_distributed_final_tpu.rpc.worker", "-port", "0", "-trace")
        for _ in range(2)
    ]
    broker = None
    try:
        ports = [_wait_listening(w) for w in workers]
        addrs = ",".join(f"127.0.0.1:{p}" for p in ports)
        broker = _spawn(
            "gol_distributed_final_tpu.rpc.broker",
            "-port", "0", "-backend", "workers", "-workers", addrs, "-trace",
        )
        broker_port = _wait_listening(broker)
        remote = RemoteBroker(f"127.0.0.1:{broker_port}")
        try:
            p = Params(turns=10, threads=2, image_width=16, image_height=16)
            result = run(
                p,
                queue.Queue(),
                broker=remote,
                images_dir=REPO_ROOT / "images",
                out_dir=tmp_path / "out",
                tick_seconds=3600.0,
            )
            assert result.turns_completed == 10

            # the broker's Status also snapshots its flight ring — the
            # live post-mortem surface for a wedged run
            from gol_distributed_final_tpu.obs.status import fetch_status

            status = fetch_status(f"127.0.0.1:{broker_port}")
            assert status["flight"], "broker flight ring missing from Status"
            kinds = {e["kind"] for e in status["flight"]}
            assert "rpc.dispatch" in kinds
        finally:
            remote.close()
    finally:
        for proc in (*workers, *([broker] if broker else [])):
            if proc.poll() is None:
                proc.kill()
            proc.wait()

    doc = json.loads((tmp_path / "out" / "trace_16x16x10.json").read_text())
    events = doc["traceEvents"]
    for ev in events:
        for key in PERFETTO_KEYS:
            assert key in ev, f"{key} missing from {ev}"
    spans = [e for e in events if e["ph"] == "X"]
    tracks = {
        e["args"]["name"]
        for e in events
        if e["ph"] == "M" and e["name"] == "process_name"
    }
    assert "controller" in tracks and "broker" in tracks
    assert sum(1 for t in tracks if t.startswith("worker")) == 2
    assert len({e["pid"] for e in spans}) >= 3
    # the acceptance criterion: RPC spans (client AND server side, all
    # three processes) share one trace_id — and here the whole session does
    rpc_ids = {
        e["args"]["trace_id"] for e in spans if e["cat"].startswith("rpc.")
    }
    assert len(rpc_ids) == 1
    assert {e["args"]["trace_id"] for e in spans} == rpc_ids
    # every layer made it onto the timeline: session root, broker verbs,
    # per-worker Update strips, per-turn scatter/gather
    cats = {e["cat"] for e in spans}
    assert {
        "controller.session", "rpc.client.call", "rpc.server.dispatch",
        "broker.turn",
    } <= cats


# -- tooling ------------------------------------------------------------------


def test_every_declared_span_name_is_documented():
    from gol_distributed_final_tpu.obs.lint import undocumented_spans

    assert undocumented_spans() == []


def test_in_process_session_exports_trace(tmp_path, live_tracing):
    """-trace without a remote broker: the in-process engine's chunk spans
    land in the same export under the controller's own pid."""
    p = Params(turns=8, threads=8, image_width=16, image_height=16)
    run(
        p,
        queue.Queue(),
        images_dir=REPO_ROOT / "images",
        out_dir=tmp_path / "out",
        tick_seconds=3600.0,
    )
    doc = json.loads((tmp_path / "out" / "trace_16x16x8.json").read_text())
    cats = {e["cat"] for e in doc["traceEvents"] if e["ph"] == "X"}
    assert {"controller.session", "engine.chunk"} <= cats
