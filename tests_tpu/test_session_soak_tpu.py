"""A bounded on-hardware session soak: sustained big_session operation.

The CPU suite proves the control-plane logic; bench.py proves kernel
throughput. What neither covers is SUSTAINED operation on the real chip —
a big-board session evolving for a minute of wall clock while the live
ticker, pause barrier, streamed snapshot, and periodic checkpoints all
fire against it. An 8-minute exploratory soak (r5: 303k turns at 16384^2,
72 monotone ticks, clean pause/resume, correct R-pentomino population)
motivated pinning a repeatable ~1-minute form here — at 4096^2, where a
streamed snapshot is 16 MB instead of the 268 MB that made the 16384^2
form exceed CI budgets under the remote tunnel.

Reference anchor: the ticker + keypress surface the reference runs for
the whole game (gol/distributor.go:25-129), held under real load.
"""

import queue
import threading
import time

import pytest

import jax

pytestmark = [
    pytest.mark.tpu,
    pytest.mark.skipif(
        jax.devices()[0].platform != "tpu",
        reason="needs a real TPU (sustained-session soak)",
    ),
]

SIZE = 4096


def test_session_soak_one_minute(tmp_path):
    from gol_distributed_final_tpu.bigboard import big_session, r_pentomino
    from gol_distributed_final_tpu.engine.controller import CLOSED
    from gol_distributed_final_tpu.engine.engine import Engine, EngineConfig
    from gol_distributed_final_tpu.events import (
        AliveCellsCount,
        FinalTurnComplete,
        Quitting,
        State,
        StateChange,
    )

    events: "queue.Queue" = queue.Queue()
    keys: "queue.Queue" = queue.Queue()
    out_pgm = tmp_path / "out" / f"{SIZE}x{SIZE}x1000000000.pgm"
    observed = {}

    def feeder():
        time.sleep(15)
        keys.put("s")  # snapshot mid-run
        # pin the 's' path specifically: the file appearing BEFORE 'q' is
        # pressed can only be the mid-run snapshot (the closing sequence
        # overwrites the same path later, so post-run existence alone
        # would be vacuous)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and not out_pgm.exists():
            time.sleep(0.5)
        observed["snapshot_mid_run"] = out_pgm.exists()
        time.sleep(10)
        keys.put("p")  # pause ~5 s
        time.sleep(5)
        keys.put("p")
        time.sleep(25)
        keys.put("q")  # end the soak

    threading.Thread(target=feeder, daemon=True).start()
    ck = tmp_path / "soak_ck.npz"
    eng = Engine(
        EngineConfig(
            final_world=False,
            # pinned chunk size: ONE compiled chunk shape (plus the final
            # remainder) instead of the doubling schedule's five — each
            # Mosaic compile is a 20-40 s stall under the remote tunnel,
            # which is compile behavior, not the sustained operation this
            # soak exists to exercise
            min_chunk=4096,
            max_chunk=4096,
            # low enough that even an order-of-magnitude throughput dip
            # (device contention when the whole subset runs together —
            # observed in r5: in-subset wall stretched ~3x and 1M was
            # never crossed) still crosses it several times within the
            # soak window; each crossing is an ~8 MB shard write, which
            # is soak stress, not overhead
            checkpoint_every=50_000,
            checkpoint_path=str(ck),
        )
    )
    t0 = time.monotonic()
    res = big_session(
        SIZE,
        10**9,  # 'q' ends it
        cells=r_pentomino(SIZE),
        engine=eng,
        events=events,
        keypresses=keys,
        tick_seconds=2.0,
        out_dir=tmp_path / "out",
    )
    wall = time.monotonic() - t0
    assert 0 < res.turns_completed < 10**9

    seq = []
    while True:
        ev = events.get(timeout=30)
        if ev is CLOSED:
            break
        seq.append(ev)

    ticks = [e for e in seq if isinstance(e, AliveCellsCount)]
    turns = [e.completed_turns for e in ticks]
    # the ticker stayed ALIVE for the whole soak (compile and snapshot
    # stalls legitimately coalesce ticks, so cadence is not asserted —
    # liveness, monotonicity, and positivity are)
    assert len(ticks) >= 5, (len(ticks), wall)
    assert turns == sorted(turns), "tick turns not monotone"
    assert all(e.cells_count > 0 for e in ticks)
    pauses = [
        e for e in seq
        if isinstance(e, StateChange) and e.new_state == State.PAUSED
    ]
    assert len(pauses) == 1
    finals = [e for e in seq if isinstance(e, FinalTurnComplete)]
    assert len(finals) == 1
    assert isinstance(seq[-1], StateChange) and seq[-1].new_state is Quitting
    # the periodic checkpoint fired at least once during the soak
    assert ck.exists()
    # the mid-run 's' snapshot specifically landed (see feeder), and the
    # closing sequence left the final PGM in place
    assert observed.get("snapshot_mid_run"), observed
    assert out_pgm.exists()
