"""Mosaic-compiled kernel parity on the real chip.

The CPU suite validates every kernel in interpret mode; bench.py gates its
numbers on Conway parity at bench sizes. What neither covers — and what
this file does — is the COMPILED kernels under a non-Conway rule, both
tiled packings, and BitPlane's on-TPU routing (a ``pltpu.roll`` or layout
regression in Mosaic would surface only here).

The ground truth chain: the numpy oracle (tests/oracle.py) anchors the XLA
bitboard at a small size, then the XLA bitboard — same device, no pallas —
anchors each pallas kernel at full size.
"""

import importlib.util
import pathlib

import numpy as np
import pytest

import jax

from gol_distributed_final_tpu.models import CONWAY, HIGHLIFE
from gol_distributed_final_tpu.ops import bitpack, pallas_stencil
from gol_distributed_final_tpu.ops.plane import BitPlane

pytestmark = [
    pytest.mark.tpu,
    pytest.mark.skipif(
        jax.devices()[0].platform != "tpu",
        reason="needs a real TPU (Mosaic-compiled kernels)",
    ),
]

# the numpy oracle, loaded by explicit path: `from oracle import ...` would
# depend on tests/ being on sys.path, which collides with this directory's
# conftest under pytest's importlib mode
_ORACLE_SPEC = importlib.util.spec_from_file_location(
    "gol_tpu_oracle",
    pathlib.Path(__file__).resolve().parent.parent / "tests" / "oracle.py",
)
oracle = importlib.util.module_from_spec(_ORACLE_SPEC)
_ORACLE_SPEC.loader.exec_module(oracle)


def _random_board(seed, size):
    rng = np.random.default_rng(seed)
    return np.where(rng.random((size, size)) < 0.33, 255, 0).astype(np.uint8)


def _random_packed(seed, shape):
    # any random words are a valid packed board
    rng = np.random.default_rng(seed)
    return rng.integers(-(2**31), 2**31, size=shape, dtype=np.int64).astype(
        np.int32
    )


def test_xla_bitboard_matches_numpy_oracle_highlife():
    """The anchor: the on-TPU XLA bitboard vs the pure-numpy oracle under
    HIGHLIFE at 256^2 x 20 turns."""
    vector_step = oracle.vector_step

    board = _random_board(1, 256)
    packed = bitpack.pack(board, 0)
    got = bitpack.bit_step_n(
        packed, 20, 0, HIGHLIFE.birth_mask, HIGHLIFE.survive_mask
    )
    want = board
    for _ in range(20):
        want = vector_step(want, birth=(3, 6), survive=(2, 3))
    np.testing.assert_array_equal(
        np.asarray(bitpack.unpack_device(got, 0)), want
    )


@pytest.mark.parametrize("rule", [CONWAY, HIGHLIFE], ids=lambda r: r.rulestring)
def test_vmem_kernel_matches_xla_bitboard(rule):
    """The whole-board VMEM kernel (compiled, interpret=False) vs the XLA
    bitboard at 512^2 x 100 turns — including a non-Conway rule the bench
    never runs."""
    packed = bitpack.pack(_random_board(2, 512), 0)
    vmem = pallas_stencil._bit_compiled(
        100, 0, False, rule.birth_mask, rule.survive_mask
    )(packed)
    xla = bitpack.bit_step_n(packed, 100, 0, rule.birth_mask, rule.survive_mask)
    np.testing.assert_array_equal(np.asarray(vmem), np.asarray(xla))


@pytest.mark.parametrize("rule", [CONWAY, HIGHLIFE], ids=lambda r: r.rulestring)
@pytest.mark.parametrize("word_axis", [0, 1])
def test_tiled_kernel_both_packings_grid2d(word_axis, rule):
    """The grid-tiled kernel at a 2-D-grid-regime shape (16384^2), both
    packings x {Conway, HighLife}, 10 turns, vs the XLA bitboard on the
    same packing — a Mosaic rule-mask regression in the tiled kernel has
    nowhere to hide."""
    from gol_distributed_final_tpu.ops.pallas_tiled import tiled_bit_step_n_fn

    shape = (512, 16384) if word_axis == 0 else (16384, 512)
    packed = _random_packed(3, shape)
    step = tiled_bit_step_n_fn(interpret=False, word_axis=word_axis, rule=rule)
    got = step(packed, 10)
    want = bitpack.bit_step_n(
        packed, 10, word_axis, rule.birth_mask, rule.survive_mask
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_bitplane_routes_vmem_then_tiled():
    """BitPlane's size routing ON TPU: a 512^2 state goes through the VMEM
    kernel, a 16384^2 state through the tiled kernel — verified by
    instrumenting the route targets, with parity on both."""
    import gol_distributed_final_tpu.ops.pallas_tiled as tiled_mod

    plane = BitPlane(CONWAY)
    assert plane.interpret is False  # on-TPU default: compiled kernels

    calls = []
    orig_tiled = tiled_mod.tiled_bit_step_n_fn
    orig_vmem = pallas_stencil._bit_compiled

    def spy_tiled(*a, **kw):
        calls.append("tiled")
        return orig_tiled(*a, **kw)

    def spy_vmem(*a, **kw):
        calls.append("vmem")
        return orig_vmem(*a, **kw)

    tiled_mod.tiled_bit_step_n_fn = spy_tiled
    pallas_stencil._bit_compiled = spy_vmem
    try:
        small = bitpack.pack(_random_board(4, 512), 0)
        out_small = plane.step_n(small, 5)
        assert calls and calls[-1] == "vmem", calls

        big = _random_packed(5, (512, 16384))
        out_big = plane.step_n(big, 5)
        assert calls[-1] == "tiled", calls
    finally:
        tiled_mod.tiled_bit_step_n_fn = orig_tiled
        pallas_stencil._bit_compiled = orig_vmem

    np.testing.assert_array_equal(
        np.asarray(out_small),
        np.asarray(
            bitpack.bit_step_n(
                small, 5, 0, CONWAY.birth_mask, CONWAY.survive_mask
            )
        ),
    )
    np.testing.assert_array_equal(
        np.asarray(out_big),
        np.asarray(
            bitpack.bit_step_n(big, 5, 0, CONWAY.birth_mask, CONWAY.survive_mask)
        ),
    )


@pytest.mark.parametrize("depth", [2, 8])
def test_pallas_wide_halo_compiled(depth):
    """Wide halos through the COMPILED tiled kernel (the r5 composition —
    the CPU suite only runs it in interpret mode): a (1, 1) mesh on the
    real chip builds the k-word-halo tile-aligned ext and runs k Mosaic
    kernel launches per exchange; parity vs the XLA bitboard, including
    the depth-8 ring-creep boundary and a remainder turn count."""
    from gol_distributed_final_tpu.parallel import make_mesh
    from gol_distributed_final_tpu.parallel.bit_halo import (
        packed_sharding,
        sharded_bit_step_n_fn,
    )

    mesh = make_mesh((1, 1), devices=[jax.devices()[0]])
    packed = jax.device_put(
        _random_packed(7, (64, 2048)), packed_sharding(mesh)
    )  # 2048^2: ext (80, 2304) tiles; min block dim 64 >= depth 8
    wide = sharded_bit_step_n_fn(
        mesh, pallas_local=True, interpret=False, halo_depth=depth
    )
    for n in (depth, depth + 1):  # exact and remainder chunking
        got = np.asarray(wide(packed, n))
        want = np.asarray(
            bitpack.bit_step_n(
                packed, n, 0, CONWAY.birth_mask, CONWAY.survive_mask
            )
        )
        np.testing.assert_array_equal(got, want, err_msg=f"depth={depth} n={n}")


def test_byte_vmem_kernel_matches_roll_stencil():
    """The byte-board VMEM kernel (pallas_step_n_fn, compiled) vs the XLA
    roll stencil at 512^2 x 50 turns under HIGHLIFE."""
    board = _random_board(6, 512)
    step = pallas_stencil.pallas_step_n_fn(HIGHLIFE, interpret=False)
    got = np.asarray(step(board, 50))
    want = np.asarray(HIGHLIFE.step_n(board, 50))
    np.testing.assert_array_equal(got, want)
