"""Real-TPU kernel correctness subset (VERDICT round 3 item 7).

Run ON HARDWARE with one command:

    python -m pytest tests_tpu -q

Unlike ``tests/`` (whose conftest forces the 8-device virtual CPU mesh and
pallas interpret mode), this suite runs the Mosaic-COMPILED kernels on the
real chip — kernel correctness independent of bench.py's parity gates, and
under rules/packings the bench never exercises. Off-TPU every test skips
itself (the platform check lives in the test module), so the same command
is safe anywhere.

Deliberately defines no shared symbols: importing names from a module
called ``conftest`` is ambiguous under pytest's importlib mode (tests/
has a conftest too), so the test modules are self-contained.
"""

import pathlib
import sys

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "tpu: runs Mosaic-compiled kernels on real TPU hardware"
    )
