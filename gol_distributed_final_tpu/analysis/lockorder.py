"""Whole-program lock composition: order cycles, TOCTOU, blocking holds.

``locks.py`` proves each individual access is guarded; nothing there
proves the locks COMPOSE. A dozen cooperating threads per process
(probe/readmission, the SessionScheduler driver, the timeline sampler,
canary/loadgen daemons, RPC reader threads) interact through nested
acquisitions, and the two worst historical bug classes here were
concurrency bugs (PR 8's deque-mutated-during-iteration Status race,
PR 9's unlocked ``_strip_turn`` read). This module machine-checks the
composition, three ways:

* ``lock-order`` — the repo-wide lock-acquisition graph: every
  ``with self.<lock>`` block, ``Condition`` aliases folded onto their
  underlying lock, ``# gol: holds(..)`` caller contracts seeding the
  held-set, and intra-repo call edges traversed (a helper called under
  lock A that takes lock B contributes the A→B edge, including through
  a typed attribute like ``self._table.admit(...)``). A cycle in that
  graph is a deadlock waiting for its interleaving; the finding carries
  the full witness path, file:line per edge. Re-entering a
  NON-reentrant lock (directly or through a call chain) is the
  one-node cycle and is reported the same way.
* ``atomicity`` — the TOCTOU shape behind the PR 9 bug: a guarded field
  read under its lock, the lock released, then the SAME field written
  under a later acquisition of that lock in the same method, with the
  write depending on a local carrying the stale read. Check-then-act
  must happen in ONE critical section (or be justified: the
  single-driver-thread contract is the legitimate exception, and it is
  a suppression with a reason, not silence). Per-file, intraprocedural.
* ``blocking-under-lock`` — a blocking call (``sendall``/``recv``,
  ``Event.wait``, RPC ``call``, ``sleep``, ``join``, future
  ``result``...) made while holding a lock that a HOT PATH also takes
  (the engine turn loop, ``SessionTable.advance``, the worker's
  ``strip_step``/``update`` handlers — ``HOT_METHODS``). One stalled
  socket then wedges the serving loop for every tenant. Waiting on a
  ``Condition`` that wraps the held lock is exempt — that wait
  RELEASES it.

Resolution is deliberately bounded: ``self.method()``, typed attributes
(``self._x = ClassName(...)`` / ``self._x: ClassName``), locals assigned
from either, and repo-unique class names. Module-attribute objects
(``_ins.FOO.inc()``) and untyped parameters don't resolve — the checkers
under-approximate rather than guess, and the runtime sanitizer
(``utils/locksan.py``) covers the dynamically-dispatched remainder.

``lock-order`` and ``blocking-under-lock`` are repo-level checkers (the
graph spans modules); ``atomicity`` is a per-file checker. All three
respect the standard ``# gol: allow(<check>): <why>`` suppressions at
the finding's anchor line.
"""

from __future__ import annotations

import ast
import pathlib
from typing import Dict, FrozenSet, Iterable, List, Optional, Tuple

from .core import (
    Checker, Finding, iter_python_files, is_generated, rel_base,
)
from .locks import guard_map, parse_holds

#: methods whose transitive lock set defines the HOT locks: the engine
#: turn loop (`Engine.run`, the broker backends' `run`/turn loops reached
#: from it), the session batch driver, the worker compute handlers, the
#: sampler tick, and the flight recorder's per-event append
HOT_METHODS = frozenset({
    "advance", "update", "strip_step", "run", "sample_once", "record",
})

#: attribute calls that park the calling thread (socket/IPC, thread
#: joins, future results, sleeps, RPC round-trips)
BLOCKING_ATTRS = frozenset({
    "sendall", "sendto", "recv", "recv_into", "recvfrom", "accept",
    "connect", "wait", "join", "sleep", "result", "call",
})

#: bare-name calls that block (the rpc/protocol.py frame helpers)
BLOCKING_NAMES = frozenset({
    "send_frame", "recv_frame", "recv_frame_sized", "sleep",
})

#: traversal bound: deeper call chains than this stop contributing edges
#: (the repo's real chains are <= 4 deep; the cap guards fixture cycles)
MAX_DEPTH = 10


# -- the per-tree model -------------------------------------------------------


class _ClassModel:
    """One class's lock surface: which attributes ARE locks (with
    ``Condition`` wrappers folded onto the lock they wrap), which are
    reentrant, the ``_GUARDED_BY`` field map, attribute types, and the
    method table with ``holds(..)`` seeds."""

    def __init__(self, name: str, relpath: str):
        self.name = name
        self.relpath = relpath
        self.canon: Dict[str, str] = {}        # lock attr -> canonical attr
        self.reentrant: set = set()            # canonical attrs that re-enter
        self.guards: Dict[str, FrozenSet[str]] = {}
        self.attr_types: Dict[str, str] = {}   # self.<attr> -> class name
        self.methods: Dict[str, Tuple[ast.AST, FrozenSet[str]]] = {}

    def lock_key(self, attr: str) -> Optional[str]:
        base = self.canon.get(attr)
        if base is None:
            return None
        return f"{self.relpath}:{self.name}.{base}"

    def display(self, attr: str) -> str:
        return f"{self.name}.{self.canon.get(attr, attr)}"


def _call_name(call: ast.Call) -> Tuple[str, str]:
    """``(receiver name, callee name)`` — receiver '' for bare names."""
    f = call.func
    if isinstance(f, ast.Attribute):
        if isinstance(f.value, ast.Name):
            return f.value.id, f.attr
        return "?", f.attr
    if isinstance(f, ast.Name):
        return "", f.id
    return "?", ""


def _self_attr_arg(call: ast.Call, index: int) -> Optional[str]:
    """The attr name when positional arg ``index`` is ``self.<attr>``."""
    if len(call.args) > index:
        a = call.args[index]
        if (
            isinstance(a, ast.Attribute)
            and isinstance(a.value, ast.Name)
            and a.value.id == "self"
        ):
            return a.attr
    return None


def _lock_creation(call: ast.Call) -> Optional[Tuple[str, Optional[str]]]:
    """Classify a lock-constructing call: ``("lock"|"rlock", None)`` or
    ``("cond", wrapped self-attr | None)``. Recognizes both the raw
    ``threading`` constructors and the ``utils/locksan`` factories that
    replace them under ``GOL_LOCKSAN=1`` — the static model must not go
    blind the moment the dynamic sanitizer is wired in."""
    base, name = _call_name(call)
    if base in ("threading", ""):
        if name == "Lock":
            return ("lock", None)
        if name == "RLock":
            return ("rlock", None)
        if name == "Condition":
            return ("cond", _self_attr_arg(call, 0))
    if base.lstrip("_") == "locksan":
        if name == "lock":
            return ("lock", None)
        if name == "rlock":
            return ("rlock", None)
        if name == "condition":
            return ("cond", _self_attr_arg(call, 1))
    return None


def _build_class(cls: ast.ClassDef, lines: List[str],
                 relpath: str) -> _ClassModel:
    model = _ClassModel(cls.name, relpath)
    own: Dict[str, Tuple[str, Optional[str]]] = {}
    for node in ast.walk(cls):
        target = None
        value = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target, value = node.targets[0], node.value
        elif isinstance(node, ast.AnnAssign):
            target, value = node.target, node.value
        if not (
            isinstance(target, ast.Attribute)
            and isinstance(target.value, ast.Name)
            and target.value.id == "self"
        ):
            continue
        if isinstance(value, ast.Call):
            kind = _lock_creation(value)
            if kind is not None:
                own[target.attr] = kind
                continue
            base, name = _call_name(value)
            if name and name[0].isupper():
                model.attr_types[target.attr] = name
        # `self._x: ClassName` / `self._x: "ClassName"` (the quoted form
        # is how a back-reference to a later class annotates)
        if isinstance(node, ast.AnnAssign):
            ann = None
            if isinstance(node.annotation, ast.Name):
                ann = node.annotation.id
            elif isinstance(node.annotation, ast.Constant) and isinstance(
                node.annotation.value, str
            ):
                ann = node.annotation.value
            if ann and ann[:1].isupper() and ann.isidentifier():
                model.attr_types.setdefault(target.attr, ann)
    # canonicalize: a Condition aliases the lock it wraps; a Condition
    # over its own implicit lock is its own (reentrant) node
    for attr, (kind, wrapped) in own.items():
        if kind == "cond" and wrapped is not None and wrapped in own:
            model.canon[attr] = wrapped
        else:
            model.canon[attr] = attr
            if kind in ("rlock", "cond"):
                model.reentrant.add(attr)
    model.guards, _problems = guard_map(cls, lines, relpath, "lock-order")
    # guard declarations may name locks constructed in ways the scan
    # above cannot see (injected, inherited): register them as plain
    # non-reentrant locks so guarded-field regions still resolve
    for names in model.guards.values():
        for n in names:
            model.canon.setdefault(n, n)
    # fold guard aliases: a field guarded by ('_lock', '_cond') where
    # _cond wraps _lock collapses to the canonical lock
    model.guards = {
        f: frozenset(model.canon.get(n, n) for n in names)
        for f, names in model.guards.items()
    }
    for stmt in cls.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            held: FrozenSet[str] = frozenset()
            if stmt.lineno <= len(lines):
                names, _problem = parse_holds(lines[stmt.lineno - 1])
                if names:
                    held = frozenset(
                        model.canon.get(n, n) for n in names
                    )
            model.methods[stmt.name] = (stmt, held)
    return model


class _TreeModel:
    """Every class in the tree, plus a by-name index for resolving
    constructor calls (``SessionTable(...)``) and typed attributes
    across modules. Ambiguous names (two classes, one name) resolve to
    nothing — under-approximate, never guess."""

    def __init__(self):
        self.classes: Dict[Tuple[str, str], _ClassModel] = {}
        self.by_name: Dict[str, Optional[_ClassModel]] = {}

    def add(self, model: _ClassModel) -> None:
        self.classes[(model.relpath, model.name)] = model
        if model.name in self.by_name:
            self.by_name[model.name] = None  # ambiguous
        else:
            self.by_name[model.name] = model

    def resolve(self, name: str) -> Optional[_ClassModel]:
        return self.by_name.get(name)


_MODEL_CACHE: Dict[Tuple, _TreeModel] = {}


def build_model(root) -> _TreeModel:
    """Parse the tree once per (content) state; both repo checkers and
    repeated runs share the result."""
    root = pathlib.Path(root).resolve()
    base = rel_base(root)
    files = []
    for path in iter_python_files(root):
        try:
            stat = path.stat()
        except OSError:
            continue
        files.append((path, stat.st_mtime_ns, stat.st_size))
    key = (str(root), tuple(
        (str(p), m, s) for p, m, s in files
    ))
    cached = _MODEL_CACHE.get(key)
    if cached is not None:
        return cached
    model = _TreeModel()
    for path, _m, _s in files:
        try:
            source = path.read_text(encoding="utf-8", errors="replace")
            tree = ast.parse(source)
        except (OSError, SyntaxError, ValueError):
            continue  # the walker already reports parse failures loudly
        if is_generated(source):
            continue
        relpath = path.relative_to(base).as_posix()
        lines = source.splitlines()
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                model.add(_build_class(node, lines, relpath))
    _MODEL_CACHE.clear()  # one live tree at a time: tests churn tmp dirs
    _MODEL_CACHE[key] = model
    return model


# -- the traversal ------------------------------------------------------------


class _Edge:
    __slots__ = ("src", "dst", "relpath", "line", "context")

    def __init__(self, src, dst, relpath, line, context):
        self.src, self.dst = src, dst
        self.relpath, self.line, self.context = relpath, line, context

    @property
    def site(self) -> str:
        return f"{self.relpath}:{self.line}"


class _Block:
    """One blocking call observed with locks held."""

    __slots__ = ("desc", "relpath", "line", "context", "held")

    def __init__(self, desc, relpath, line, context, held):
        self.desc, self.relpath, self.line = desc, relpath, line
        self.context, self.held = context, held


class _Walker:
    """Simulates every method with a held-lock list, emitting acquisition
    edges, reentry findings, and blocking events. Call edges resolve via
    the tree model; a (class, method, held-set) state is visited once."""

    def __init__(self, model: _TreeModel, follow_unheld: bool = False):
        self.model = model
        self.follow_unheld = follow_unheld
        self.edges: Dict[Tuple[str, str], _Edge] = {}
        self.reentries: List[Finding] = []
        self.blocks: List[_Block] = []
        self.acquired: set = set()
        self._seen: set = set()

    # held: ordered tuple of (lock key, display, reentrant)
    def run_method(self, cls: _ClassModel, meth: str, held=()):
        node, holds = cls.methods.get(meth, (None, frozenset()))
        if node is None:
            return
        for attr in sorted(holds):
            key = cls.lock_key(attr)
            if key is not None and key not in {h[0] for h in held}:
                held = held + ((key, cls.display(attr),
                                attr in cls.reentrant),)
        state = (cls.relpath, cls.name, meth,
                 frozenset(h[0] for h in held))
        if state in self._seen or len(held) > MAX_DEPTH:
            return
        self._seen.add(state)
        env: Dict[str, str] = {}  # local name -> class name
        self._walk(node.body, cls, f"{cls.name}.{meth}", held, env)

    def _walk(self, stmts, cls, context, held, env):
        for stmt in stmts:
            self._stmt(stmt, cls, context, held, env)

    def _stmt(self, node, cls, context, held, env):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # nested defs run later, with nothing held
        if isinstance(node, (ast.With, ast.AsyncWith)):
            new_held = held
            for item in node.items:
                ce = item.context_expr
                self._expr(ce, cls, context, new_held, env)
                attr = self._self_lock(ce, cls)
                if attr is None:
                    continue
                key = cls.lock_key(attr)
                held_keys = {h[0] for h in new_held}
                if key in held_keys:
                    if cls.canon.get(attr, attr) not in cls.reentrant:
                        self.reentries.append(Finding(
                            "lock-order", cls.relpath, ce.lineno,
                            f"'{context}' re-acquires non-reentrant lock "
                            f"'{cls.display(attr)}' while already holding "
                            f"it — with threading.Lock this deadlocks the "
                            f"thread against itself (use RLock or "
                            f"restructure)",
                        ))
                    continue
                for h_key, h_disp, _re in new_held:
                    self.edges.setdefault(
                        (h_key, key),
                        _Edge(h_key, key, cls.relpath, ce.lineno, context),
                    )
                self.acquired.add(key)
                new_held = new_held + (
                    (key, cls.display(attr),
                     cls.canon.get(attr, attr) in cls.reentrant),
                )
            self._walk(node.body, cls, context, new_held, env)
            return
        # track trivially-typed locals: v = self.attr / v = ClassName(...)
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and (
            isinstance(node.targets[0], ast.Name)
        ):
            t = self._expr_type(node.value, cls, env)
            if t is not None:
                env[node.targets[0].id] = t
            else:
                env.pop(node.targets[0].id, None)
            self._expr(node.value, cls, context, held, env)
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._expr(child, cls, context, held, env)
            elif isinstance(child, ast.stmt):
                self._stmt(child, cls, context, held, env)
            else:
                # handlers/withitems/comprehension innards: recurse for
                # nested statements and expressions
                for sub in ast.iter_child_nodes(child):
                    if isinstance(sub, ast.expr):
                        self._expr(sub, cls, context, held, env)
                    elif isinstance(sub, ast.stmt):
                        self._stmt(sub, cls, context, held, env)

    def _expr(self, node, cls, context, held, env):
        # hand-rolled walk: a lambda body (thread target, callback) runs
        # LATER with nothing held — ast.walk would descend into it and
        # charge its calls against the definition site's held set
        todo = [node]
        while todo:
            n = todo.pop()
            if isinstance(n, ast.Lambda):
                continue
            if isinstance(n, ast.Call):
                self._call(n, cls, context, held, env)
            todo.extend(ast.iter_child_nodes(n))

    def _call(self, call, cls, context, held, env):
        target = self._resolve(call, cls, env)
        if target is not None:
            callee_cls, callee_meth = target
            if held or self.follow_unheld:
                self.run_method(callee_cls, callee_meth, held)
            return
        if not held:
            return
        base, name = _call_name(call)
        blocking = (
            (base == "" and name in BLOCKING_NAMES)
            or (base != "" and name in BLOCKING_ATTRS)
        )
        if not blocking:
            return
        # str.join / " ".join(...) noise: only flag attribute calls on
        # names/attributes, never on literals or call results
        if isinstance(call.func, ast.Attribute) and not isinstance(
            call.func.value, (ast.Name, ast.Attribute)
        ):
            return
        if name == "wait" and self._waits_on_held(call, cls, held, env):
            return  # Condition.wait releases the lock it wraps
        recv = f"{base}." if base and base != "?" else ""
        self.blocks.append(_Block(
            f"{recv}{name}()", cls.relpath, call.lineno, context, held,
        ))

    def _waits_on_held(self, call, cls, held, env) -> bool:
        f = call.func
        if not (isinstance(f, ast.Attribute) and f.attr == "wait"):
            return False
        v = f.value
        attr = None
        if (
            isinstance(v, ast.Attribute)
            and isinstance(v.value, ast.Name)
            and v.value.id == "self"
        ):
            attr = v.attr
        elif isinstance(v, ast.Name):
            # a local alias of a self lock: `cond = self._work`
            t = env.get(v.id)
            if t and t.startswith("lockattr:"):
                attr = t[len("lockattr:"):]
        if attr is None:
            return False
        key = cls.lock_key(attr)
        return key is not None and key in {h[0] for h in held}

    def _self_lock(self, expr, cls) -> Optional[str]:
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
            and expr.attr in cls.canon
        ):
            return expr.attr
        return None

    def _expr_type(self, value, cls, env) -> Optional[str]:
        if (
            isinstance(value, ast.Attribute)
            and isinstance(value.value, ast.Name)
            and value.value.id == "self"
        ):
            if value.attr in cls.canon:
                return f"lockattr:{value.attr}"
            return cls.attr_types.get(value.attr)
        if isinstance(value, ast.Call):
            base, name = _call_name(value)
            if name and name[0].isupper() and self.model.resolve(name):
                return name
        if isinstance(value, ast.Name):
            return env.get(value.id)
        return None

    def _resolve(self, call, cls, env):
        """``(class model, method name)`` for calls the model can type."""
        f = call.func
        if not isinstance(f, ast.Attribute):
            return None
        v = f.value
        if isinstance(v, ast.Name):
            if v.id == "self":
                if f.attr in cls.methods:
                    return cls, f.attr
                return None
            t = env.get(v.id)
            if t and not t.startswith("lockattr:"):
                m = self.model.resolve(t)
                if m is not None and f.attr in m.methods:
                    return m, f.attr
            return None
        if (
            isinstance(v, ast.Attribute)
            and isinstance(v.value, ast.Name)
            and v.value.id == "self"
        ):
            t = cls.attr_types.get(v.attr)
            if t:
                m = self.model.resolve(t)
                if m is not None and f.attr in m.methods:
                    return m, f.attr
        return None


def _walk_tree(model: _TreeModel, follow_unheld: bool = False,
               entries=None) -> _Walker:
    walker = _Walker(model, follow_unheld=follow_unheld)
    for (relpath, name) in sorted(model.classes):
        cls = model.classes[(relpath, name)]
        for meth in cls.methods:
            if entries is not None and meth not in entries:
                continue
            walker.run_method(cls, meth)
    return walker


# -- checkers -----------------------------------------------------------------


class LockOrderChecker(Checker):
    id = "lock-order"
    description = (
        "the repo-wide lock-acquisition graph (with-blocks, Condition "
        "aliases, holds(..) contracts, intra-repo call edges) is acyclic "
        "and no non-reentrant lock is re-entered"
    )
    bug_class = (
        "ABBA deadlocks between cooperating threads; self-deadlock on "
        "a re-entered threading.Lock"
    )

    def check_tree(self, root) -> Iterable[Finding]:
        model = build_model(root)
        walker = _walk_tree(model)
        findings: List[Finding] = list(walker.reentries)
        findings.extend(self._cycles(walker.edges))
        return findings

    def _cycles(self, edges: Dict[Tuple[str, str], _Edge]):
        adj: Dict[str, List[str]] = {}
        for (a, b) in edges:
            adj.setdefault(a, []).append(b)
            adj.setdefault(b, [])
        for dsts in adj.values():
            dsts.sort()
        reported: set = set()
        for start in sorted(adj):
            if start in reported:
                continue
            cycle = self._shortest_cycle(start, adj)
            if cycle is None:
                continue
            if any(n in reported for n in cycle):
                continue
            reported.update(cycle)
            witness = []
            for a, b in zip(cycle, cycle[1:] + cycle[:1]):
                e = edges[(a, b)]
                witness.append(
                    f"{_disp(a)} -> {_disp(b)} at {e.site} "
                    f"(in {e.context})"
                )
            first = edges[(cycle[0], cycle[1] if len(cycle) > 1
                           else cycle[0])]
            yield Finding(
                self.id, first.relpath, first.line,
                "lock-order cycle (deadlock under the wrong "
                "interleaving): " + "; ".join(witness),
            )

    @staticmethod
    def _shortest_cycle(start: str, adj) -> Optional[List[str]]:
        # path-carrying BFS from start back to start (the graph is a
        # handful of lock nodes; clarity beats parent-pointer surgery)
        from collections import deque

        q = deque([(n, [start, n]) for n in adj.get(start, ())])
        seen = set()
        while q:
            node, path = q.popleft()
            if node == start:
                return path[:-1]  # [start, ..., predecessor-of-start]
            if node in seen:
                continue
            seen.add(node)
            for nxt in adj.get(node, ()):
                q.append((nxt, path + [nxt]))
        return None


def _disp(key: str) -> str:
    # 'rpc/broker.py:SessionScheduler._lock' -> 'SessionScheduler._lock'
    return key.split(":", 1)[1] if ":" in key else key


class BlockingUnderLockChecker(Checker):
    id = "blocking-under-lock"
    description = (
        "no blocking call (socket send/recv, Event.wait, RPC call, "
        "sleep, join, future result) runs while holding a lock a hot "
        "path (engine turn loop, SessionTable.advance, worker "
        "update/strip_step) also takes"
    )
    bug_class = (
        "one stalled peer wedging the serving hot loop for every "
        "session behind a shared lock"
    )

    def check_tree(self, root) -> Iterable[Finding]:
        model = build_model(root)
        # pass 1: the hot-lock set — every lock reachable from a hot
        # entry method (call edges followed even with nothing held),
        # remembering WHICH hot entry reaches it for the message
        hot: Dict[str, str] = {}
        for (relpath, name) in sorted(model.classes):
            cls = model.classes[(relpath, name)]
            for meth in sorted(set(cls.methods) & HOT_METHODS):
                w = _Walker(model, follow_unheld=True)
                w.run_method(cls, meth)
                for key in w.acquired:
                    hot.setdefault(key, f"{name}.{meth}")
        # pass 2: blocking events anywhere in the tree
        walker = _walk_tree(model)
        for b in walker.blocks:
            held_hot = [
                (key, disp) for key, disp, _re in b.held if key in hot
            ]
            if not held_hot:
                continue
            key, disp = held_hot[-1]
            yield Finding(
                self.id, b.relpath, b.line,
                f"'{b.context}' calls blocking '{b.desc}' while holding "
                f"'{disp}', which the hot path '{hot[key]}' also takes — "
                f"one stalled call wedges that loop",
            )


class AtomicityChecker(Checker):
    """Per-file: the read-release-write TOCTOU on ``_GUARDED_BY`` fields
    (module docstring). Intra-method, dataflow-gated: the later locked
    write must LOAD a local assigned from a guarded read in an earlier
    region of the same lock, and the written field must have been read
    in an earlier region — three conditions, so single-region code and
    independent writes stay quiet."""

    id = "atomicity"
    description = (
        "a _GUARDED_BY field read under its lock is not re-written "
        "under a LATER acquisition in the same method from a local "
        "carrying the stale read (check-then-act spans a lock release)"
    )
    bug_class = (
        "TOCTOU on guarded state: the PR 9 unlocked _strip_turn shape — "
        "decide under the lock, act after it, another thread moved first"
    )

    def check_file(self, tree, source, relpath) -> Iterable[Finding]:
        lines = source.splitlines()
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                yield from self._check_class(node, lines, relpath)

    def _check_class(self, cls, lines, relpath):
        model = _build_class(cls, lines, relpath)
        if not model.guards:
            return
        for stmt in cls.body:
            if not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if stmt.name in ("__init__", "__new__"):
                continue
            yield from self._check_method(stmt, model, relpath)

    def _check_method(self, fn, model, relpath):
        # linear walk; state threads through nested/compound statements
        st = {
            "closed_reads": {},   # canonical lock -> set of fields read
            "stale": {},          # local -> (field, read_line)
            "findings": [],
        }
        self._walk(fn.body, model, fn.name, (), st, relpath)
        return st["findings"]

    def _walk(self, stmts, model, meth, held, st, relpath):
        for s in stmts:
            self._stmt(s, model, meth, held, st, relpath)

    def _stmt(self, node, model, meth, held, st, relpath):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquired = []
            for item in node.items:
                ce = item.context_expr
                if (
                    isinstance(ce, ast.Attribute)
                    and isinstance(ce.value, ast.Name)
                    and ce.value.id == "self"
                    and ce.attr in model.canon
                ):
                    canon = model.canon[ce.attr]
                    if canon not in held:
                        acquired.append(canon)
            region = {"reads": set(), "stale": {}}
            regions = st.setdefault("open", [])
            if acquired:
                regions.append((frozenset(acquired), region))
            self._walk(node.body, model, meth,
                       held + tuple(acquired), st, relpath)
            if acquired:
                regions.pop()
                for lock in acquired:
                    st["closed_reads"].setdefault(lock, set()).update(
                        region["reads"]
                    )
                st["stale"].update(region["stale"])
            return
        if isinstance(node, ast.stmt) and not isinstance(
            node, (ast.If, ast.For, ast.AsyncFor, ast.While, ast.Try,
                   ast.With, ast.AsyncWith)
        ):
            self._simple(node, model, meth, held, st, relpath)
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.stmt):
                self._stmt(child, model, meth, held, st, relpath)
            elif isinstance(child, (ast.ExceptHandler,)):
                self._walk(child.body, model, meth, held, st, relpath)

    # -- one simple statement ------------------------------------------------

    _MUTATORS = frozenset({
        "append", "appendleft", "extend", "insert", "add", "discard",
        "remove", "pop", "popleft", "popitem", "clear", "update",
        "setdefault",
    })

    def _simple(self, node, model, meth, held, st, relpath):
        guards = model.guards
        open_regions = st.get("open", [])
        # loads of stale locals in this statement
        loaded = {
            n.id for n in ast.walk(node)
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)
        }
        stale_used = sorted(set(st["stale"]) & loaded)
        # guarded-field reads and writes in this statement
        reads, writes = self._field_touches(node, guards)
        held_set = frozenset(held)
        for field, line in writes:
            locks = guards[field] & held_set
            if not locks:
                continue  # unlocked writes are locks.py's finding
            prior = [
                lock for lock in guards[field]
                if field in st["closed_reads"].get(lock, ())
            ]
            if prior and stale_used:
                local, (rfield, rline) = (
                    stale_used[0], st["stale"][stale_used[0]]
                )
                st["findings"].append(Finding(
                    "atomicity", relpath, line,
                    f"'{meth}' reads guarded 'self.{field}' under its "
                    f"lock, releases it, then writes 'self.{field}' "
                    f"under a LATER acquisition using stale local "
                    f"'{local}' (read from 'self.{rfield}' at line "
                    f"{rline}) — another thread can interleave between "
                    f"the regions; do the check and the act in one "
                    f"critical section or justify the driver contract",
                ))
        # record reads + stale-local candidates into the open regions
        for _locks, region in open_regions:
            for field, _line in reads:
                if guards[field] & _locks:
                    region["reads"].add(field)
        # assignment targets: rebinding kills staleness; a guarded-read
        # assign inside a region creates new stale candidates at close
        targets = self._name_targets(node)
        for t in targets:
            st["stale"].pop(t, None)
        if targets and reads and open_regions:
            _locks, region = open_regions[-1]
            field, line = reads[0]
            if guards[field] & _locks:
                for t in targets:
                    region["stale"][t] = (field, line)

    @staticmethod
    def _name_targets(node) -> List[str]:
        targets = []
        if isinstance(node, ast.Assign):
            for t in node.targets:
                for n in ast.walk(t):
                    if isinstance(n, ast.Name) and isinstance(
                        n.ctx, ast.Store
                    ):
                        targets.append(n.id)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            if isinstance(node.target, ast.Name):
                targets.append(node.target.id)
        return targets

    def _field_touches(self, node, guards):
        """``(reads, writes)`` of guarded ``self.<field>`` in one simple
        statement: plain loads are reads; Store/Del contexts, augmented
        assigns, subscript stores, and mutator-method calls are writes
        (pop/popitem also read — they return guarded state)."""
        reads: List[Tuple[str, int]] = []
        writes: List[Tuple[str, int]] = []

        def field_of(expr):
            if (
                isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"
                and expr.attr in guards
            ):
                return expr.attr
            return None

        for n in ast.walk(node):
            f = field_of(n)
            if f is None:
                continue
            ctx = getattr(n, "ctx", None)
            if isinstance(ctx, (ast.Store, ast.Del)):
                writes.append((f, n.lineno))
            else:
                reads.append((f, n.lineno))
        for n in ast.walk(node):
            # self.F[...] = x / del self.F[...]
            if isinstance(n, ast.Subscript) and isinstance(
                n.ctx, (ast.Store, ast.Del)
            ):
                f = field_of(n.value)
                if f is not None:
                    writes.append((f, n.lineno))
            # self.F.append(x) etc.
            if isinstance(n, ast.Call) and isinstance(
                n.func, ast.Attribute
            ) and n.func.attr in self._MUTATORS:
                f = field_of(n.func.value)
                if f is not None:
                    writes.append((f, n.lineno))
        if isinstance(node, ast.AugAssign):
            f = field_of(node.target)
            if f is not None:
                reads.append((f, node.target.lineno))
                writes.append((f, node.target.lineno))
        return reads, writes


def concurrency_repo_checkers() -> List[Checker]:
    """The repo-level composition checkers (the per-file
    :class:`AtomicityChecker` registers with the AST checkers)."""
    return [LockOrderChecker(), BlockingUnderLockChecker()]
