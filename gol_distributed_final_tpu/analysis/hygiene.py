"""``hygiene`` — daemonised/joined threads, no silently-swallowed excepts.

Two resource-lifecycle contracts:

1. **Threads.** A ``threading.Thread``/``Timer`` must either be created
   ``daemon=True`` (it may never outlive the process) or be provably
   joined: bound to a name on which ``.join(`` is called somewhere in
   the same file. An un-daemonised, un-joined thread wedges interpreter
   shutdown — the broker/worker processes are long-lived servers where
   one leaked thread turns SIGTERM into SIGKILL.

2. **Excepts.** A broad handler (bare ``except:``, ``except Exception``,
   ``except BaseException``) whose body performs NO call, NO raise and
   NO return swallows the failure without leaving evidence — no log
   line, no flight-recorder event, no propagation. The chaos/integrity
   layers exist precisely because silent failure is the worst failure
   mode; a handler that narrows the type, logs, flight-records,
   re-raises, or returns a sentinel all pass.

3. **Executors.** A ``ThreadPoolExecutor``/``ProcessPoolExecutor`` must
   be context-managed (``with ...Executor(...) as pool``) or have a
   provable in-file ``shutdown`` call on its bound name, same
   owning-scope rule as the thread join proof. A leaked pool is the
   thread leak multiplied by its worker count — the RpcClient-pool
   class of bug: the broker's scatter pool outliving its run wedges
   shutdown exactly like one un-joined thread, times ``pool_size``.

4. **GC callbacks.** A file that registers a collector hook
   (``gc.callbacks.append(...)``) must also unregister one
   (``gc.callbacks.remove(...)``) somewhere in the same file. The
   process-global ``gc.callbacks`` list outlives every object: an
   append with no paired remove keeps the callback — and everything
   its closure holds — alive for the life of the interpreter, and
   fires it on collections long after the owner was "closed" (the
   profiler's GC pause meter is exactly this shape; obs/profiler.py
   pairs install_gc with remove_gc).
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Set

from .core import Checker, Finding

_THREAD_FACTORIES = frozenset({"Thread", "Timer"})
_EXECUTOR_FACTORIES = frozenset({"ThreadPoolExecutor", "ProcessPoolExecutor"})
_BROAD = frozenset({"Exception", "BaseException"})


def _is_thread_call(node: ast.Call) -> bool:
    func = node.func
    if isinstance(func, ast.Attribute):
        return (
            func.attr in _THREAD_FACTORIES
            and isinstance(func.value, ast.Name)
            and func.value.id == "threading"
        )
    return isinstance(func, ast.Name) and func.id in _THREAD_FACTORIES


def _is_executor_call(node: ast.Call) -> bool:
    # bare name, or any dotted form ending in the factory
    # (concurrent.futures.ThreadPoolExecutor, futures.ThreadPoolExecutor)
    func = node.func
    if isinstance(func, ast.Attribute):
        return func.attr in _EXECUTOR_FACTORIES
    return isinstance(func, ast.Name) and func.id in _EXECUTOR_FACTORIES


def _target_name(target) -> str:
    if isinstance(target, ast.Name):
        return target.id
    if (
        isinstance(target, ast.Attribute)
        and isinstance(target.value, ast.Name)
        and target.value.id == "self"
    ):
        return f"self.{target.attr}"
    return ""


def _broad_type(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    if isinstance(t, ast.Name):
        return t.id in _BROAD
    if isinstance(t, ast.Tuple):
        return any(
            isinstance(e, ast.Name) and e.id in _BROAD for e in t.elts
        )
    return False


class HygieneChecker(Checker):
    id = "hygiene"
    description = (
        "threads are daemon=True or joined in-file; executors are "
        "context-managed or shut down in-file; gc.callbacks.append is "
        "paired with a remove in-file; broad except handlers "
        "log/flight-record/raise/return instead of silently swallowing"
    )
    bug_class = (
        "leaked threads/pools wedging process shutdown; gc callbacks "
        "registered forever; failures vanishing with no log, flight "
        "event, or propagation"
    )

    def check_file(self, tree, source, relpath) -> Iterable[Finding]:
        findings: List[Finding] = []
        self._check_threads(tree, relpath, findings)
        self._check_executors(tree, relpath, findings)
        self._check_gc_callbacks(tree, relpath, findings)
        self._check_excepts(tree, relpath, findings)
        return findings

    # -- threads -------------------------------------------------------------

    def _check_threads(self, tree, relpath, findings) -> None:
        parents = {
            child: parent
            for parent in ast.walk(tree)
            for child in ast.iter_child_nodes(parent)
        }
        bound: dict = {}  # id(call node) -> bound name
        for node in ast.walk(tree):
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                value = getattr(node, "value", None)
                if isinstance(value, ast.Call) and _is_thread_call(value):
                    targets = (
                        node.targets
                        if isinstance(node, ast.Assign)
                        else [node.target]
                    )
                    for t in targets:
                        name = _target_name(t)
                        if name:
                            bound[id(value)] = name

        def enclosing(node, kinds):
            cur = parents.get(node)
            while cur is not None and not isinstance(cur, kinds):
                cur = parents.get(cur)
            return cur if cur is not None else tree

        joins_cache: dict = {}

        def joins_in(scope) -> Set[str]:
            cached = joins_cache.get(id(scope))
            if cached is None:
                cached = joins_cache[id(scope)] = {
                    name
                    for sub in ast.walk(scope)
                    if isinstance(sub, ast.Attribute) and sub.attr == "join"
                    for name in (_target_name(sub.value),)
                    if name
                }
            return cached

        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call) and _is_thread_call(node)):
                continue
            daemon = next(
                (kw for kw in node.keywords if kw.arg == "daemon"), None
            )
            if daemon is not None:
                if (
                    isinstance(daemon.value, ast.Constant)
                    and daemon.value.value is False
                ):
                    pass  # explicit daemon=False: fall through to join proof
                else:
                    continue
            name = bound.get(id(node))
            if name:
                # the join must live in the scope that OWNS the binding:
                # the enclosing class for self.X (created in one method,
                # joined in another), the enclosing function for locals —
                # a same-named '_thread' joined in a DIFFERENT class is
                # no proof for this one
                scope = enclosing(
                    node,
                    ast.ClassDef
                    if name.startswith("self.")
                    else (ast.FunctionDef, ast.AsyncFunctionDef),
                )
                if name in joins_in(scope):
                    continue
            factory = _func_name(node)
            findings.append(Finding(
                self.id, relpath, node.lineno,
                f"{factory} created without daemon=True and never joined "
                f"in its owning scope — a leaked non-daemon thread wedges "
                f"process shutdown",
            ))

    # -- executors -----------------------------------------------------------

    def _check_executors(self, tree, relpath, findings) -> None:
        """Executor discipline mirrors the thread rule: context-managed
        (``with`` owns the shutdown) or a ``shutdown`` call on the bound
        name in its owning scope — class scope for ``self.X``, function
        scope for locals."""
        parents = {
            child: parent
            for parent in ast.walk(tree)
            for child in ast.iter_child_nodes(parent)
        }

        def enclosing(node, kinds):
            cur = parents.get(node)
            while cur is not None and not isinstance(cur, kinds):
                cur = parents.get(cur)
            return cur if cur is not None else tree

        bound: dict = {}  # id(call node) -> bound name
        managed: set = set()  # id(call node) of with-managed executors
        for node in ast.walk(tree):
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                value = getattr(node, "value", None)
                if isinstance(value, ast.Call) and _is_executor_call(value):
                    targets = (
                        node.targets
                        if isinstance(node, ast.Assign)
                        else [node.target]
                    )
                    for t in targets:
                        name = _target_name(t)
                        if name:
                            bound[id(value)] = name
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    if isinstance(item.context_expr, ast.Call) and (
                        _is_executor_call(item.context_expr)
                    ):
                        managed.add(id(item.context_expr))

        def shutdowns_in(scope):
            return {
                name
                for sub in ast.walk(scope)
                if isinstance(sub, ast.Attribute) and sub.attr == "shutdown"
                for name in (_target_name(sub.value),)
                if name
            }

        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call) and _is_executor_call(node)):
                continue
            if id(node) in managed:
                continue
            name = bound.get(id(node))
            if name:
                scope = enclosing(
                    node,
                    ast.ClassDef
                    if name.startswith("self.")
                    else (ast.FunctionDef, ast.AsyncFunctionDef),
                )
                if name in shutdowns_in(scope):
                    continue
            factory = _func_name(node)
            findings.append(Finding(
                self.id, relpath, node.lineno,
                f"{factory} is neither context-managed nor shut down in "
                f"its owning scope — a leaked pool is pool_size un-joined "
                f"threads wedging process shutdown",
            ))

    # -- gc callbacks --------------------------------------------------------

    def _check_gc_callbacks(self, tree, relpath, findings) -> None:
        """Registration pairing on the process-global collector-hook
        list: every ``gc.callbacks.append(...)`` needs SOME
        ``gc.callbacks.remove(...)`` in the same file. File-level (not
        owning-scope) on purpose: install/uninstall conventionally live
        in different functions of one module (install_gc/remove_gc), and
        the global list means a remove anywhere genuinely discharges the
        leak — unlike a thread join, which must name its thread."""
        appends: List[ast.Call] = []
        has_remove = False
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            kind = _gc_callbacks_op(node)
            if kind == "append":
                appends.append(node)
            elif kind == "remove":
                has_remove = True
        if has_remove:
            return
        for node in appends:
            findings.append(Finding(
                self.id, relpath, node.lineno,
                "gc.callbacks.append without any gc.callbacks.remove in "
                "this file — the process-global hook list keeps the "
                "callback (and its closure) alive and firing on every "
                "collection after the owner is closed",
            ))

    # -- excepts -------------------------------------------------------------

    def _check_excepts(self, tree, relpath, findings) -> None:
        for node in ast.walk(tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _broad_type(node):
                continue
            # handled = the failure leaves evidence or control flow:
            # a call (log/flight-record/metric), a raise, a return — or
            # the bound exception VALUE is read (captured into state the
            # caller inspects: the checkpoint agreement-vote pattern)
            handled = any(
                isinstance(sub, (ast.Call, ast.Raise, ast.Return))
                or (
                    node.name is not None
                    and isinstance(sub, ast.Name)
                    and sub.id == node.name
                    and isinstance(sub.ctx, ast.Load)
                )
                for stmt in node.body
                for sub in ast.walk(stmt)
            )
            if not handled:
                what = (
                    "bare except:" if node.type is None
                    else "broad except"
                )
                findings.append(Finding(
                    self.id, relpath, node.lineno,
                    f"{what} swallows the failure silently (no call, "
                    f"raise, or return in the handler) — log it, "
                    f"flight-record it, narrow the type, or justify the "
                    f"suppression",
                ))


def _gc_callbacks_op(node: ast.Call) -> str:
    """'append' / 'remove' when the call is ``gc.callbacks.append(...)``
    or ``gc.callbacks.remove(...)``; '' otherwise."""
    func = node.func
    if (
        isinstance(func, ast.Attribute)
        and func.attr in ("append", "remove")
        and isinstance(func.value, ast.Attribute)
        and func.value.attr == "callbacks"
        and isinstance(func.value.value, ast.Name)
        and func.value.value.id == "gc"
    ):
        return func.attr
    return ""


def _func_name(node: ast.Call) -> str:
    func = node.func
    if isinstance(func, ast.Attribute):
        if func.attr in _THREAD_FACTORIES:
            return f"threading.{func.attr}"
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return "Thread"
