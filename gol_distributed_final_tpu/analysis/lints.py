"""The README name-drift lints, re-seated on the analysis framework.

``obs/lint.py`` has owned the name contracts since PR 1: every metric /
span / SLO-rule name registered in code must appear in its README
section of record. Those checks keep their home (tests and the
``scripts/check --lint`` alias still call ``obs.lint`` directly — the
functions and their behavior are unchanged); this module wraps each
entry of ``obs.lint.CHECKS`` as a repo-level :class:`~.core.Checker`,
so the default ``scripts/check`` run reports doc drift and AST
violations through ONE runner, one finding format, one exit contract.

It also owns the analyzer's own doc contract: ``lint-analysis-docs``
requires the README "Static analysis" section to name every AST checker
id and the suppression syntax — the same add-a-name-document-it loop the
metric tables enforce.
"""

from __future__ import annotations

import pathlib
from typing import Iterable, List

from .core import Checker, Finding, rel_base


def _readme_line(readme_path: pathlib.Path, needle: str) -> int:
    """Best-effort anchor line for a README finding (1 when absent)."""
    try:
        lines = readme_path.read_text().splitlines()
    except OSError:
        return 1
    for i, line in enumerate(lines, 1):
        if needle in line:
            return i
    return 1


#: check id -> the README heading its findings anchor to, so a missing
#: name reports a clickable line at the section it belongs in
_SECTION_ANCHORS = {
    "lint-metrics": "## Observability",
    "lint-spans": "## Tracing",
    "lint-device-metrics": "Device telemetry",
    "lint-wire-metrics": "## Wire modes",
    "lint-integrity-metrics": "## Integrity",
    "lint-session-metrics": "## Sessions",
    "lint-slo-metrics": "## SLOs & alerting",
    "lint-slo-rules": "## SLOs & alerting",
    "lint-canary-metrics": "## Canary & load harness",
    "lint-accounting-docs": "## Accounting & capacity",
    "lint-perf-metrics": "## Performance attribution",
    "lint-sparse-metrics": "## Sparse stepping",
    "lint-fused-metrics": "## Fused stepping",
    "lint-journal-metrics": "## Journal & history",
    # lint-journal-kinds anchors on the journal section too: a drifted
    # kind means the README's event-kind table is stale alongside the
    # EVENT_KINDS dict
    "lint-journal-kinds": "## Journal & history",
}


class ReadmeLintChecker(Checker):
    """One ``obs.lint.CHECKS`` entry under the analysis runner."""

    bug_class = (
        "doc drift: an operator-facing name registered in code but "
        "absent from its README section of record"
    )

    def __init__(self, check_id: str, func, fail_msg: str):
        self.id = check_id
        self._func = func
        self.description = fail_msg.rstrip(":")
        self._anchor = _SECTION_ANCHORS.get(check_id)

    def check_tree(self, root) -> Iterable[Finding]:
        readme = rel_base(pathlib.Path(root)) / "README.md"
        try:
            missing = self._func(readme_path=readme)
        except OSError as e:
            return [Finding(self.id, "README.md", 1, f"cannot lint: {e}")]
        line = _readme_line(readme, self._anchor) if self._anchor else 1
        return [
            Finding(
                self.id, "README.md", line,
                f"{self.description}: {name}",
            )
            for name in missing
        ]


class AnalysisDocsChecker(Checker):
    """The analyzer's own README contract: the "Static analysis" section
    documents every invariant checker id (AST and lock-composition), the
    suppression syntax, and the lock sanitizer's ``GOL_LOCKSAN`` knob."""

    id = "lint-analysis-docs"
    description = (
        "README 'Static analysis' section names every invariant checker "
        "id, the '# gol: allow' suppression syntax, and the GOL_LOCKSAN "
        "sanitizer knob"
    )
    bug_class = (
        "doc drift: an undocumented checker id, allow syntax, or "
        "sanitizer knob"
    )

    def check_tree(self, root) -> Iterable[Finding]:
        from ..obs.lint import _readme_section
        from . import ast_checkers, concurrency_checkers

        readme = rel_base(pathlib.Path(root)) / "README.md"
        try:
            section = _readme_section(readme, "## Static analysis")
        except OSError as e:
            return [Finding(self.id, "README.md", 1, f"cannot lint: {e}")]
        findings: List[Finding] = []
        line = _readme_line(readme, "## Static analysis")
        for checker in ast_checkers() + concurrency_checkers():
            if checker.id not in section:
                findings.append(Finding(
                    self.id, "README.md", line,
                    f"checker id '{checker.id}' missing from the "
                    f"'Static analysis' section's checker table",
                ))
        if "gol: allow" not in section:
            findings.append(Finding(
                self.id, "README.md", line,
                "suppression syntax ('# gol: allow(<check>): <why>') "
                "missing from the 'Static analysis' section",
            ))
        if "GOL_LOCKSAN" not in section:
            findings.append(Finding(
                self.id, "README.md", line,
                "the lock sanitizer's 'GOL_LOCKSAN' knob (utils/"
                "locksan.py: env switch, watchdog deadline, artifact "
                "path) is missing from the 'Static analysis' section",
            ))
        return findings


def readme_checkers() -> List[Checker]:
    from ..obs.lint import CHECKS

    checkers: List[Checker] = [
        ReadmeLintChecker(check_id, func, fail_msg)
        for check_id, func, fail_msg, _ok_msg in CHECKS
    ]
    checkers.append(AnalysisDocsChecker())
    return checkers
