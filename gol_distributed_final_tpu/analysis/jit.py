"""``jit-cache`` — bounded compile caches and host-free kernel bodies.

Two hazards from the compiled-kernel layer (``ops/``):

1. **Un-quantised static args.** The kernel family's entry points
   (``step_n`` / ``bit_step_n`` and their ``_batch`` forms) take the
   turn count as a STATIC argument — every distinct Python value
   compiles a fresh program. Feeding them a raw runtime-derived value
   (``min(remaining)``, a subtraction of counters) builds an unbounded
   jit cache in a long-lived process, each entry a driver-thread compile
   stall — the exact hazard the session batcher fixed by power-of-two
   quantisation (``k = 1 << (k.bit_length() - 1)``). The checker traces
   the turn argument through the enclosing function's assignments: a
   value is accepted if it is a constant, an unassigned parameter, or
   passes through a recognised quantiser (``.bit_length()``-based
   power-of-two math, or a function named ``*quant*``/``*pow2*``);
   it is flagged when its derivation contains ``min``/``max`` or
   arithmetic over runtime values with no quantiser in the chain.

2. **Host calls inside compiled bodies.** ``time.*``, ``random.*``,
   ``.item()``, ``.block_until_ready()`` and ``device_get`` inside a
   jitted function or a pallas kernel body (a ``@jit``-decorated def, or
   a def whose name contains ``kernel``) either trace once and freeze a
   stale value, or force a host sync in the middle of the device
   program. Both are silent performance/correctness bugs.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Tuple

from .core import Checker, Finding

#: compiled-kernel entry points -> positional index of the static turn
#: argument (counted over the call's OWN argument list; ``plane.step_n(
#: state, n)`` and ``stencil.step_n(board, n, ...)`` both put it at 1)
ENTRY_POINTS: Dict[str, int] = {
    "step_n": 1,
    "bit_step_n": 1,
    "step_n_batch": 1,
    "bit_step_n_batch": 1,
    # the fused K-turns-per-launch family (ops/fused.py): the turn count
    # AND the K argument are both static compile keys — K is quantised
    # inside the entry (quantise_k), so a caller-side raw K passes
    # through the same quantiser-chain rule as a chunk size
    "fused_bit_step_n": 1,
    "fused_step_n": 1,
    "fused_bit_step_n_batch": 1,
    "fused_strip_steps": 1,
    "step_n_counted": 1,
    "step_n_counts": 1,
    # the 2-D tile plane's K-batch entry (rpc/worker.py): numpy today,
    # but the K argument is the same static batch-depth key the fused
    # family compiles on — kept under the rule so a jitted tile kernel
    # cannot regress the cache contract silently
    "tile_step_batch": 2,
}
#: keyword spellings of the same argument (``k`` is the fused family's
#: static turns-per-launch — same unbounded-cache hazard as ``n``)
TURN_KWARGS = ("n", "turns", "k")

#: substrings that mark a call/attribute as a quantiser: a derivation
#: that passes through one lands on a bounded key set
QUANTISER_HINTS = ("bit_length", "quant", "pow2")

_ARITH_OPS = (ast.Add, ast.Sub, ast.Mult, ast.Div, ast.FloorDiv, ast.Mod)
_HOST_ATTRS = frozenset({"item", "block_until_ready", "device_get"})
_HOST_MODULES = frozenset({"time", "random"})


def _func_name(func) -> str:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return ""


def _contains_quantiser(expr) -> bool:
    for node in ast.walk(expr):
        if isinstance(node, ast.Attribute) and any(
            h in node.attr for h in QUANTISER_HINTS
        ):
            return True
        if isinstance(node, ast.Call) and any(
            h in _func_name(node.func) for h in QUANTISER_HINTS
        ):
            return True
    return False


class JitCacheChecker(Checker):
    id = "jit-cache"
    description = (
        "static turn/shape args to ops/ kernel entry points are "
        "quantised (constants, parameters, or power-of-two math) — and "
        "no time/random/.item()/host-sync calls inside jitted or pallas "
        "kernel bodies"
    )
    bug_class = (
        "unbounded jit compile caches (one program per distinct runtime "
        "value) and traced-once/host-sync bugs in kernel bodies"
    )

    def check_file(self, tree, source, relpath) -> Iterable[Finding]:
        findings: List[Finding] = []
        # module scope counts as an enclosing "function" for assignments
        self._check_scope(tree, relpath, findings)
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._check_scope(node, relpath, findings)
                if self._is_compiled(node):
                    self._check_kernel_body(node, relpath, findings)
        return findings

    # -- static-arg quantisation --------------------------------------------

    def _check_scope(self, scope, relpath, findings) -> None:
        """Audit every kernel-entry call whose enclosing scope is exactly
        ``scope`` (nested defs get their own pass)."""
        assigns = self._assignments(scope)
        for node in self._own_nodes(scope):
            if not isinstance(node, ast.Call):
                continue
            name = _func_name(node.func)
            if name not in ENTRY_POINTS:
                continue
            idx = ENTRY_POINTS[name]
            turn_arg = None
            if len(node.args) > idx:
                turn_arg = node.args[idx]
            else:
                for kw in node.keywords:
                    if kw.arg in TURN_KWARGS:
                        turn_arg = kw.value
                        break
            if turn_arg is None:
                continue
            if self._suspicious(turn_arg, assigns, set(), 0):
                findings.append(Finding(
                    self.id, relpath, node.lineno,
                    f"static turn argument to {name}() derives from an "
                    f"un-quantised runtime value (min/max/arithmetic): "
                    f"every distinct value compiles a fresh program — "
                    f"quantise (e.g. 1 << (k.bit_length() - 1)) to bound "
                    f"the jit cache",
                ))

    @staticmethod
    def _own_nodes(scope):
        """Descendants of ``scope`` that are not inside a nested def."""
        stack = list(ast.iter_child_nodes(scope))
        while stack:
            node = stack.pop()
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))

    def _assignments(self, scope) -> Dict[str, List[Tuple[int, ast.AST]]]:
        out: Dict[str, List[Tuple[int, ast.AST]]] = {}
        for node in self._own_nodes(scope):
            if isinstance(node, ast.Assign):
                for target in node.targets:
                    if isinstance(target, ast.Name):
                        out.setdefault(target.id, []).append(
                            (node.lineno, node.value)
                        )
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                if isinstance(node.target, ast.Name):
                    out.setdefault(node.target.id, []).append(
                        (node.lineno, node.value)
                    )
            elif isinstance(node, ast.AugAssign):
                if isinstance(node.target, ast.Name):
                    out.setdefault(node.target.id, []).append(
                        (node.lineno, node)
                    )
        return out

    def _suspicious(self, expr, assigns, seen, depth) -> bool:
        """True when the expression's derivation contains min/max or
        runtime arithmetic with NO quantiser anywhere in the chain.
        Unknown shapes (parameters, attributes, globals) are trusted —
        the checker flags positively-identified hazards, not everything
        it cannot prove."""
        if depth > 5 or _contains_quantiser(expr):
            return False
        if isinstance(expr, ast.Constant):
            return False
        if isinstance(expr, ast.Name):
            if expr.id in seen:
                return False
            seen = seen | {expr.id}
            entries = assigns.get(expr.id)
            if not entries:
                return False  # parameter / global: caller's contract
            # quantised ANYWHERE in the function wins: the idiom is
            # "derive raw, then quantise in place" (engine chunk loop,
            # session batcher)
            if any(_contains_quantiser(rhs) for _, rhs in entries):
                return False
            return any(
                self._suspicious(rhs, assigns, seen, depth + 1)
                for _, rhs in entries
            )
        if isinstance(expr, ast.AugAssign):
            return self._suspicious(expr.value, assigns, seen, depth + 1)
        if isinstance(expr, ast.Call):
            if _func_name(expr.func) in ("min", "max"):
                return True
            # a wrapper call (int(), abs(), round(), anything unknown)
            # doesn't launder its arguments: int(min(a, b)) is the same
            # unbounded-key hazard as min(a, b)
            return any(
                self._suspicious(a, assigns, seen, depth + 1)
                for a in expr.args
            )
        if isinstance(expr, ast.BinOp) and isinstance(expr.op, _ARITH_OPS):
            operands = (expr.left, expr.right)
            if all(isinstance(o, ast.Constant) for o in operands):
                return False
            return True
        if isinstance(expr, ast.IfExp):
            return self._suspicious(
                expr.body, assigns, seen, depth + 1
            ) or self._suspicious(expr.orelse, assigns, seen, depth + 1)
        return False

    # -- kernel-body purity --------------------------------------------------

    @staticmethod
    def _is_compiled(node) -> bool:
        if "kernel" in node.name:
            return True
        for dec in node.decorator_list:
            for sub in ast.walk(dec):
                if isinstance(sub, ast.Attribute) and sub.attr == "jit":
                    return True
                if isinstance(sub, ast.Name) and sub.id == "jit":
                    return True
        return False

    def _check_kernel_body(self, func, relpath, findings) -> None:
        for node in ast.walk(func):
            if not isinstance(node, ast.Call):
                continue
            callee = node.func
            if isinstance(callee, ast.Attribute):
                if callee.attr in _HOST_ATTRS:
                    findings.append(Finding(
                        self.id, relpath, node.lineno,
                        f".{callee.attr}() inside compiled body "
                        f"'{func.name}': a host sync/get in a traced "
                        f"function freezes at trace time or stalls the "
                        f"device program",
                    ))
                    continue
                root = callee
                while isinstance(root, ast.Attribute):
                    root = root.value
                if (
                    isinstance(root, ast.Name)
                    and root.id in _HOST_MODULES
                ):
                    findings.append(Finding(
                        self.id, relpath, node.lineno,
                        f"{root.id}.{callee.attr}() inside compiled body "
                        f"'{func.name}': evaluated ONCE at trace time, "
                        f"then frozen into every later call",
                    ))
