"""AST-based invariant checkers — the contracts reviews kept re-enforcing.

Every review-hardening pass in CHANGES.md fixed the same mechanical bug
classes by hand: a ``Request``/``Response`` extension field read without
``getattr`` (version-skew AttributeError on an old peer's pickle), shared
state touched outside its lock (``deque mutated during iteration``,
double-metered SLO transitions), Python-varying values feeding a kernel's
STATIC turn argument (the unbounded-jit-cache hazard the session batcher
quantises away), and broad ``except: pass`` blocks that swallow evidence.
This package turns those informal contracts into machine-checked
invariants: a dependency-free ``ast`` framework (``core.py``) plus one
checker module per bug class, self-hosted over the whole package by
``scripts/check`` (the analyzer must exit clean on every commit).

The README "Static analysis" section is the operator contract: checker
ids, the invariant each enforces, and the suppression syntax
(``# gol: allow(<check>): <justification>`` — the justification is
mandatory and machine-enforced, so the allow-list stays auditable).

Layout:

* ``core.py``    — Finding/Checker framework, file walker, suppressions,
  the runner and its exit-code contract
* ``skew.py``    — ``skew-safety``: getattr/.get discipline on wire objects
* ``locks.py``   — ``lock-discipline``: ``_GUARDED_BY`` field/lock contracts
* ``jit.py``     — ``jit-cache``: quantised static kernel args, pure kernels
* ``hygiene.py`` — ``hygiene``: daemonised/joined threads, no silent excepts
* ``lints.py``   — the obs/lint.py README name-drift lints, re-seated as
  repo-level checkers (one runner, one finding format, one suppression
  syntax)
* ``__main__.py``— the CLI: ``python -m gol_distributed_final_tpu.analysis``
"""

from __future__ import annotations

from .core import Checker, Finding, Report, run  # noqa: F401


def ast_checkers():
    """The per-file AST checkers, stable order."""
    from .hygiene import HygieneChecker
    from .jit import JitCacheChecker
    from .locks import LockDisciplineChecker
    from .skew import SkewSafetyChecker

    return [
        SkewSafetyChecker(),
        LockDisciplineChecker(),
        JitCacheChecker(),
        HygieneChecker(),
    ]


def repo_checkers():
    """The repo-level checkers (README name-drift lints)."""
    from .lints import readme_checkers

    return readme_checkers()


def all_checkers():
    return ast_checkers() + repo_checkers()
