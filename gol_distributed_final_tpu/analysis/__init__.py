"""AST-based invariant checkers — the contracts reviews kept re-enforcing.

Every review-hardening pass in CHANGES.md fixed the same mechanical bug
classes by hand: a ``Request``/``Response`` extension field read without
``getattr`` (version-skew AttributeError on an old peer's pickle), shared
state touched outside its lock (``deque mutated during iteration``,
double-metered SLO transitions), Python-varying values feeding a kernel's
STATIC turn argument (the unbounded-jit-cache hazard the session batcher
quantises away), and broad ``except: pass`` blocks that swallow evidence.
This package turns those informal contracts into machine-checked
invariants: a dependency-free ``ast`` framework (``core.py``) plus one
checker module per bug class, self-hosted over the whole package by
``scripts/check`` (the analyzer must exit clean on every commit).

The README "Static analysis" section is the operator contract: checker
ids, the invariant each enforces, and the suppression syntax
(``# gol: allow(<check>): <justification>`` — the justification is
mandatory and machine-enforced, so the allow-list stays auditable).

Layout:

* ``core.py``    — Finding/Checker framework, file walker, suppressions
  (with staleness tracking), the runner and its exit-code contract
* ``skew.py``    — ``skew-safety``: getattr/.get discipline on wire objects
* ``locks.py``   — ``lock-discipline``: ``_GUARDED_BY`` field/lock contracts
* ``lockorder.py``— whole-program lock composition: ``lock-order``
  acquisition-graph cycles, ``atomicity`` read-release-write TOCTOU,
  ``blocking-under-lock`` blocking calls under hot-path locks
* ``jit.py``     — ``jit-cache``: quantised static kernel args, pure kernels
* ``hygiene.py`` — ``hygiene``: daemonised/joined threads, context-managed
  executors, no silent excepts
* ``lints.py``   — the obs/lint.py README name-drift lints, re-seated as
  repo-level checkers (one runner, one finding format, one suppression
  syntax)
* ``__main__.py``— the CLI: ``python -m gol_distributed_final_tpu.analysis``

The static layer's runtime twin is ``utils/locksan.py``: ``GOL_LOCKSAN=1``
swaps the instrumented classes' locks for order-recording wrappers that
abort on an observed inversion and watchdog long holds — what the AST
cannot resolve (dynamic dispatch, module-attribute objects), the
sanitizer observes live under ``scripts/check --locksan``.
"""

from __future__ import annotations

from .core import Checker, Finding, Report, run  # noqa: F401


def ast_checkers():
    """The per-file AST checkers, stable order."""
    from .hygiene import HygieneChecker
    from .jit import JitCacheChecker
    from .lockorder import AtomicityChecker
    from .locks import LockDisciplineChecker
    from .skew import SkewSafetyChecker

    return [
        SkewSafetyChecker(),
        LockDisciplineChecker(),
        AtomicityChecker(),
        JitCacheChecker(),
        HygieneChecker(),
    ]


def concurrency_checkers():
    """The repo-level lock-composition checkers (lockorder.py): these
    are INVARIANT checkers like the AST set — ``--no-lint`` keeps them —
    but they need the whole tree (the acquisition graph spans modules),
    so they run through ``check_tree``."""
    from .lockorder import concurrency_repo_checkers

    return concurrency_repo_checkers()


def repo_checkers():
    """The repo-level checkers (README name-drift lints)."""
    from .lints import readme_checkers

    return readme_checkers()


def all_checkers():
    return ast_checkers() + concurrency_checkers() + repo_checkers()
