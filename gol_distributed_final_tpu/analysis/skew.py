"""``skew-safety`` — getattr/.get discipline on wire-crossing objects.

The wire contract (rpc/protocol.py, informal since PR 1): the
``Request``/``Response`` dataclasses grow EXTENSION fields over time, and
a version-skewed peer's pickle simply lacks the new ones — so any read of
an extension field must be a **defaulted** ``getattr`` (absent must mean
"default", never ``AttributeError``), and reads of the negotiated
envelope / Status payload dicts in ``rpc/`` and ``obs/`` must use
``.get`` (an old peer's envelope simply lacks the key). Writes are
exempt: mutating a locally constructed dataclass before sending it is
the send path, and our own class always has the field.

Detection is name-keyed, matching the codebase convention: objects named
``req``/``request`` are Requests, ``res``/``resp``/``response`` are
Responses, and ``envelope``/``reply``/``status``/``payload`` are wire
dicts. The extension-field sets are parsed out of ``rpc/protocol.py``'s
own AST (fields beyond the frozen Go-mirror base set), so adding a wire
field automatically extends the checker — no second registry to drift.

A ``["key"]`` read is accepted when the enclosing function guards the key
with ``"key" in <dict>`` — the membership test is the loud, deliberate
form of the same skew awareness.
"""

from __future__ import annotations

import ast
import pathlib
from typing import Iterable, List

from .core import Checker, Finding

#: the frozen base fields — the stubs.go mirror (rpc/protocol.py): these
#: predate every peer version, so raw attribute reads are safe
REQUEST_BASE = frozenset({
    "world", "turns", "image_height", "image_width", "threads",
    "start_y", "end_y", "worker",
})
RESPONSE_BASE = frozenset({
    "alive", "alive_count", "turns_completed", "world", "work_slice",
    "worker",
})

#: fallback extension sets, used only when rpc/protocol.py is not
#: readable next to this package (fixture trees); the live set is parsed
#: from the dataclasses themselves
_FALLBACK_REQUEST_EXT = frozenset({
    "include_world", "initial_turn", "rulestring", "halo_depth",
    "trace_ctx", "session_id", "timeline_since",
})
_FALLBACK_RESPONSE_EXT = frozenset({
    "status", "trace_ctx", "edges", "counts", "digests",
})

REQUEST_NAMES = frozenset({"req", "request"})
RESPONSE_NAMES = frozenset({"res", "resp", "response"})
#: conventional names of dicts that crossed (or will cross) the wire
DICT_NAMES = frozenset({"envelope", "reply", "status", "payload"})
#: the dict rule applies where wire dicts live (the ISSUE contract:
#: envelope/Status dict reads in rpc/obs must use .get)
DICT_PATH_PARTS = frozenset({"rpc", "obs"})


def _dataclass_fields(tree: ast.Module, class_name: str) -> List[str]:
    for node in tree.body:
        if isinstance(node, ast.ClassDef) and node.name == class_name:
            return [
                stmt.target.id
                for stmt in node.body
                if isinstance(stmt, ast.AnnAssign)
                and isinstance(stmt.target, ast.Name)
            ]
    return []


def wire_extension_fields():
    """``(request_ext, response_ext)`` parsed from rpc/protocol.py's own
    AST — every declared field beyond the frozen base sets."""
    proto = (
        pathlib.Path(__file__).resolve().parent.parent / "rpc" / "protocol.py"
    )
    try:
        tree = ast.parse(proto.read_text())
    except (OSError, SyntaxError):
        return _FALLBACK_REQUEST_EXT, _FALLBACK_RESPONSE_EXT
    req = frozenset(_dataclass_fields(tree, "Request")) - REQUEST_BASE
    res = frozenset(_dataclass_fields(tree, "Response")) - RESPONSE_BASE
    return (req or _FALLBACK_REQUEST_EXT), (res or _FALLBACK_RESPONSE_EXT)


class SkewSafetyChecker(Checker):
    id = "skew-safety"
    description = (
        "extension fields on Request/Response read via defaulted getattr; "
        "wire-dict keys in rpc/obs read via .get (or an explicit 'in' "
        "guard)"
    )
    bug_class = (
        "version-skew AttributeError/KeyError when an older peer's pickle "
        "lacks a field the reader assumes"
    )

    def __init__(self, request_ext=None, response_ext=None):
        if request_ext is None or response_ext is None:
            parsed_req, parsed_res = wire_extension_fields()
            request_ext = parsed_req if request_ext is None else request_ext
            response_ext = (
                parsed_res if response_ext is None else response_ext
            )
        self.request_ext = frozenset(request_ext)
        self.response_ext = frozenset(response_ext)

    # -- helpers ------------------------------------------------------------

    def _ext_fields_for(self, name: str):
        if name in REQUEST_NAMES:
            return self.request_ext
        if name in RESPONSE_NAMES:
            return self.response_ext
        return None

    @staticmethod
    def _in_guards(func_node) -> set:
        """Every ``("key", "name")`` membership test in the function —
        a read of a guarded key is deliberate, not skew-blind."""
        guards = set()
        for node in ast.walk(func_node):
            if not isinstance(node, ast.Compare):
                continue
            for op, comparator in zip(node.ops, node.comparators):
                if (
                    isinstance(op, (ast.In, ast.NotIn))
                    and isinstance(node.left, ast.Constant)
                    and isinstance(node.left.value, str)
                    and isinstance(comparator, ast.Name)
                ):
                    guards.add((node.left.value, comparator.id))
        return guards

    # -- the checker --------------------------------------------------------

    def check_file(self, tree, source, relpath) -> Iterable[Finding]:
        findings: List[Finding] = []
        dict_rule = bool(
            DICT_PATH_PARTS & set(pathlib.PurePosixPath(relpath).parts)
        )

        def check_node(node, guards):
            if (
                isinstance(node, ast.Attribute)
                and isinstance(node.ctx, ast.Load)
                and isinstance(node.value, ast.Name)
            ):
                ext = self._ext_fields_for(node.value.id)
                if ext is not None and node.attr in ext:
                    findings.append(Finding(
                        self.id, relpath, node.lineno,
                        f"raw read of extension field "
                        f"'{node.value.id}.{node.attr}' — use "
                        f"getattr({node.value.id}, {node.attr!r}, "
                        f"<default>): a version-skewed peer's pickle "
                        f"lacks the field",
                    ))
            elif isinstance(node, ast.Call):
                func = node.func
                if (
                    isinstance(func, ast.Name)
                    and func.id == "getattr"
                    and len(node.args) == 2
                    and isinstance(node.args[0], ast.Name)
                    and isinstance(node.args[1], ast.Constant)
                ):
                    ext = self._ext_fields_for(node.args[0].id)
                    if ext is not None and node.args[1].value in ext:
                        findings.append(Finding(
                            self.id, relpath, node.lineno,
                            f"getattr({node.args[0].id}, "
                            f"{node.args[1].value!r}) has no default — "
                            f"it still raises on a version-skewed peer",
                        ))
            elif (
                dict_rule
                and isinstance(node, ast.Subscript)
                and isinstance(node.ctx, ast.Load)
                and isinstance(node.value, ast.Name)
                and node.value.id in DICT_NAMES
                and isinstance(node.slice, ast.Constant)
                and isinstance(node.slice.value, str)
            ):
                key, name = node.slice.value, node.value.id
                if (key, name) not in guards:
                    findings.append(Finding(
                        self.id, relpath, node.lineno,
                        f"unguarded {name}[{key!r}] read — use "
                        f"{name}.get({key!r}) or guard with "
                        f"'{key!r} in {name}' (skew-safe envelope "
                        f"contract)",
                    ))

        def visit(node, guards):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # a function's membership guards cover its whole body
                # (closures inherit the enclosing function's guards)
                guards = guards | self._in_guards(node)
            check_node(node, guards)
            for child in ast.iter_child_nodes(node):
                visit(child, guards)

        visit(tree, frozenset())
        return findings
