"""CLI: ``python -m gol_distributed_final_tpu.analysis [-json] [PATH]``.

Default target is the package itself (the self-hosting contract:
``scripts/check`` runs this and the tree must analyze clean). Exit 0 on
clean, 1 on any unsuppressed finding, 2 on usage errors (argparse).

``-json`` prints the machine form — findings, suppressed findings, and
the checker registry — to stdout and writes ``out/analysis.json``
(temp-name + atomic rename, the obs/doctor.py artifact posture) for
toolchain use.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

from . import all_checkers, ast_checkers, concurrency_checkers
from .core import run

PACKAGE_ROOT = pathlib.Path(__file__).resolve().parent.parent


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m gol_distributed_final_tpu.analysis",
        description="AST invariant checkers + README name lints",
    )
    parser.add_argument(
        "path", nargs="?", default=None,
        help="tree to analyze (default: the installed package)",
    )
    parser.add_argument(
        "-json", dest="as_json", action="store_true",
        help="print machine-readable findings and write out/analysis.json",
    )
    parser.add_argument(
        "-out", default="out",
        help="artifact directory for -json (default out)",
    )
    parser.add_argument(
        "--checks", default=None, metavar="ID[,ID...]",
        help="run only these check ids",
    )
    parser.add_argument(
        "--no-lint", action="store_true",
        help="invariant checkers only (AST + lock-composition; skip the "
        "repo-level README lints)",
    )
    parser.add_argument(
        "--list", action="store_true", dest="list_checks",
        help="list checker ids and exit",
    )
    args = parser.parse_args(argv)

    checkers = (
        ast_checkers() + concurrency_checkers()
        if args.no_lint else all_checkers()
    )
    if args.checks:
        wanted = {s.strip() for s in args.checks.split(",") if s.strip()}
        unknown = wanted - {c.id for c in checkers}
        if unknown:
            parser.error(f"unknown check id(s): {', '.join(sorted(unknown))}")
        checkers = [c for c in checkers if c.id in wanted]
    if args.list_checks:
        for c in checkers:
            print(f"{c.id}: {c.description}")
        return 0

    root = pathlib.Path(args.path) if args.path else PACKAGE_ROOT
    if not root.exists():
        parser.error(f"no such path: {root}")
    # with_repo stays True under --no-lint: the lock-composition
    # checkers are repo-LEVEL (the graph spans modules) but they are
    # invariant checkers, not doc lints — the flag excluded the README
    # lints from `checkers` above, which is all it promises
    report = run(root, checkers=checkers, with_repo=True)
    if args.as_json:
        blob = json.dumps(report.to_json(), indent=1)
        print(blob)
        out_dir = pathlib.Path(args.out)
        out_dir.mkdir(parents=True, exist_ok=True)
        artifact = out_dir / "analysis.json"
        tmp = artifact.with_name(artifact.name + ".tmp")
        tmp.write_text(blob + "\n")
        tmp.replace(artifact)
    else:
        print(report.render())
    return 0 if report.clean else 1


if __name__ == "__main__":
    sys.exit(main())
