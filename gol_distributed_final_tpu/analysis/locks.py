"""``lock-discipline`` — declared guarded fields touched only under lock.

The PR 8 review class this mechanises: a timeline ring iterated while a
sampler tick appended (``deque mutated during iteration``), an SLO
transition metered twice because two sites raced the rulebook. The
contract is declared IN the class, two equivalent ways:

* a class-level ``_GUARDED_BY = {"_ring": "_lock"}`` mapping (values may
  be a tuple when several context managers share the underlying lock —
  e.g. a ``threading.Condition`` wrapping it:
  ``{"_table": ("_lock", "_work")}``);
* a ``# guarded-by: _lock`` trailing comment on the field's assignment.

Any method that reads OR writes a guarded ``self.<field>`` outside a
``with self.<lock>`` block is flagged. ``__init__``/``__new__`` are
exempt (the object is not yet shared); a method whose ``def`` line
carries ``# gol: holds(_lock)`` — or a multi-lock contract like
``# gol: holds(_lock, _cond)`` — declares a caller-holds-the-lock(s)
contract and is treated as locked throughout (the Clang
``REQUIRES()`` idiom). A holds marker the checker cannot parse, or one
naming a lock the class never declares, is itself a LOUD finding: a
typo'd contract silently disabling enforcement is exactly the rot the
suppression-format rule exists to prevent. Nested functions and
lambdas — thread targets, callbacks — run later, so they start with NO
locks held even when defined inside a ``with`` block.

``guard_map`` and ``parse_holds`` are shared with ``lockorder.py`` (the
whole-program lock-composition checkers): one parser, one contract
syntax, no drift between the per-access and the cross-lock layers.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, FrozenSet, Iterable, List, Optional, Tuple

from .core import Checker, Finding

_COMMENT_GUARD_RE = re.compile(
    r"self\.(\w+)\s*[:=][^=].*#\s*guarded-by:\s*(\w+)"
)
#: loose probe: the marker is PRESENT (possibly malformed) on this line
_HOLDS_PROBE_RE = re.compile(r"#\s*gol:\s*holds\b")
#: strict form: '# gol: holds(_lock[, _cond...])'
_HOLDS_RE = re.compile(r"#\s*gol:\s*holds\(\s*([^)]*?)\s*\)")


def parse_holds(line: str) -> Tuple[Optional[FrozenSet[str]], Optional[str]]:
    """``(held lock names | None, parse problem | None)`` for one source
    line. ``(None, None)``: no marker. A marker that is present but
    unreadable — missing parens, empty list — returns a problem string
    so callers can report it loudly instead of silently holding nothing."""
    if not _HOLDS_PROBE_RE.search(line):
        return None, None
    m = _HOLDS_RE.search(line)
    if m is None:
        return None, (
            "unparsable holds marker — write "
            "'# gol: holds(<lock>[, <lock>...])'"
        )
    names = frozenset(s.strip() for s in m.group(1).split(",") if s.strip())
    if not names:
        return None, "holds() names no lock"
    bad = sorted(n for n in names if not n.isidentifier())
    if bad:
        return None, (
            f"holds() names {bad[0]!r}, which is not a plain lock "
            f"attribute name (write the attribute, e.g. holds(_lock))"
        )
    return names, None


def _literal_names(node) -> List[str]:
    """String / tuple-of-strings literal -> lock names."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [
            e.value for e in node.elts
            if isinstance(e, ast.Constant) and isinstance(e.value, str)
        ]
    return []


def guard_map(
    cls: ast.ClassDef, lines: List[str], relpath: str, check_id: str
) -> Tuple[Dict[str, FrozenSet[str]], List[Finding]]:
    """``(field -> lock names, declaration problems)`` for one class: the
    ``_GUARDED_BY`` mapping plus ``# guarded-by:`` trailing comments. A
    binding the parser cannot read is a loud finding, never a
    silently-disabled contract. Shared by the per-access checker below
    and the whole-program composition checkers (lockorder.py)."""
    guards: Dict[str, FrozenSet[str]] = {}
    problems: List[Finding] = []
    for stmt in cls.body:
        # plain or annotated (`_GUARDED_BY: ClassVar[dict] = {...}`)
        # declaration — an annotation must not silently disable the
        # whole contract
        if isinstance(stmt, ast.Assign):
            targets = stmt.targets
        elif isinstance(stmt, ast.AnnAssign):
            targets = [stmt.target]
        else:
            continue
        if not (
            len(targets) == 1
            and isinstance(targets[0], ast.Name)
            and targets[0].id == "_GUARDED_BY"
        ):
            continue
        if not isinstance(stmt.value, ast.Dict):
            problems.append(Finding(
                check_id, relpath, stmt.lineno,
                f"_GUARDED_BY on class '{cls.name}' is not a literal "
                f"{{'field': 'lock'}} dict — the checker cannot read "
                f"it, so the whole lock contract would be silently "
                f"ignored",
            ))
            continue
        for key, value in zip(stmt.value.keys, stmt.value.values):
            names = _literal_names(value)
            if (
                isinstance(key, ast.Constant)
                and isinstance(key.value, str)
                and names
            ):
                guards[key.value] = frozenset(names)
            else:
                problems.append(Finding(
                    check_id, relpath, stmt.lineno,
                    f"_GUARDED_BY entry on class '{cls.name}' is not "
                    f"a string field mapped to a string (or tuple of "
                    f"strings) lock name — entry ignored",
                ))
    end = cls.end_lineno or cls.lineno
    for lineno in range(cls.lineno, min(end, len(lines)) + 1):
        m = _COMMENT_GUARD_RE.search(lines[lineno - 1])
        if m:
            guards[m.group(1)] = guards.get(
                m.group(1), frozenset()
            ) | {m.group(2)}
    return guards, problems


class LockDisciplineChecker(Checker):
    id = "lock-discipline"
    description = (
        "fields declared in _GUARDED_BY (or '# guarded-by: <lock>') are "
        "touched only inside 'with self.<lock>'"
    )
    bug_class = (
        "shared-state races: collections mutated during iteration, "
        "double-counted transitions, torn read/write pairs"
    )

    def check_file(self, tree, source, relpath) -> Iterable[Finding]:
        findings: List[Finding] = []
        lines = source.splitlines()
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                findings.extend(self._check_class(node, lines, relpath))
        return findings

    # -- per-class ----------------------------------------------------------

    def _check_class(
        self, cls: ast.ClassDef, lines: List[str], relpath: str
    ) -> Iterable[Finding]:
        guards, problems = guard_map(cls, lines, relpath, self.id)
        yield from problems
        if not guards:
            return
        lock_names = frozenset().union(*guards.values())
        for stmt in cls.body:
            if not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if stmt.name in ("__init__", "__new__"):
                continue
            if not stmt.args.args or stmt.args.args[0].arg != "self":
                continue
            held: FrozenSet[str] = frozenset()
            if stmt.lineno <= len(lines):
                names, problem = parse_holds(lines[stmt.lineno - 1])
                if problem is not None:
                    # a holds contract the checker cannot read would
                    # otherwise silently hold NOTHING — every guarded
                    # access below it then flags, burying the real
                    # mistake; report the marker itself and exempt the
                    # body (the loud finding already fails the run)
                    yield Finding(
                        self.id, relpath, stmt.lineno,
                        f"'{stmt.name}' carries a {problem}",
                    )
                    held = lock_names
                elif names is not None:
                    unknown = sorted(names - lock_names)
                    if unknown:
                        yield Finding(
                            self.id, relpath, stmt.lineno,
                            f"'{stmt.name}' declares holds({unknown[0]}) "
                            f"but class '{cls.name}' guards nothing with "
                            f"'{unknown[0]}' (known locks: "
                            f"{', '.join(sorted(lock_names))}) — a typo'd "
                            f"contract would silently hold nothing",
                        )
                        held = names | lock_names
                    else:
                        held = names
            for body_stmt in stmt.body:
                yield from self._scan(
                    body_stmt, held, guards, lock_names, relpath, stmt.name
                )

    def _scan(
        self, node, held, guards, lock_names, relpath, method
    ) -> Iterable[Finding]:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquired = set(held)
            for item in node.items:
                ce = item.context_expr
                # the lock expression itself is evaluated un-held
                yield from self._scan(
                    ce, held, guards, lock_names, relpath, method
                )
                if (
                    isinstance(ce, ast.Attribute)
                    and isinstance(ce.value, ast.Name)
                    and ce.value.id == "self"
                    and ce.attr in lock_names
                ):
                    acquired.add(ce.attr)
            for child in node.body:
                yield from self._scan(
                    child, frozenset(acquired), guards, lock_names,
                    relpath, method,
                )
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a nested function runs LATER (thread target, callback):
            # whatever lock the definition site holds is long released
            for child in node.body:
                yield from self._scan(
                    child, frozenset(), guards, lock_names, relpath,
                    f"{method}.{node.name}",
                )
            return
        if isinstance(node, ast.Lambda):
            yield from self._scan(
                node.body, frozenset(), guards, lock_names, relpath,
                f"{method}.<lambda>",
            )
            return
        if isinstance(node, ast.ClassDef):
            return
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
            and node.attr in guards
            and not (guards[node.attr] & held)
        ):
            locks = " / ".join(sorted(guards[node.attr]))
            yield Finding(
                self.id, relpath, node.lineno,
                f"'{method}' touches guarded field 'self.{node.attr}' "
                f"outside 'with self.{locks}' (declared guarded-by "
                f"{locks})",
            )
        for child in ast.iter_child_nodes(node):
            yield from self._scan(
                child, held, guards, lock_names, relpath, method
            )
