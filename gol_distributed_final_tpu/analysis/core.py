"""The static-analysis framework: findings, checkers, suppressions, runner.

Dependency-free (``ast`` + stdlib only): the analyzer must run before any
jax import, in CI and as a pre-test gate (``scripts/check``), on a machine
with nothing but the repo checked out.

Contracts this module owns:

* **Finding** — one violation: ``(check id, repo-relative path, 1-based
  line, message)``. The text renderer prints ``path:line: [check] msg``;
  ``-json`` ships the same tuple as an artifact (the obs/doctor.py
  posture: machine output mirrors the terminal report).
* **Suppression** — ``# gol: allow(<check>[, <check>...]): <justification>``
  as a trailing comment on the flagged line, or on its own comment line
  immediately above it. The justification is MANDATORY: an allow comment
  without one (or naming an unknown check id) is itself a
  ``suppression-format`` finding, so the allow-list can never silently
  rot into an unexplained mute button. And it must stay LIVE: an allow
  that hid nothing across a full-registry run is a
  ``suppression-stale`` finding — when the code it excused (or the
  checker it named) changes, the audit trail shrinks instead of
  fossilising.
* **Walker** — every ``*.py`` under the root, skipping ``native/`` and
  other non-source trees (``SKIP_DIR_NAMES``) and files that declare
  themselves generated. A file that cannot be PARSED is a loud
  ``parse-failure`` finding, never a silent skip: an analyzer that skips
  what it cannot read reports "clean" on exactly the files most likely
  to be broken.
* **Exit code** — 0: clean (suppressed findings don't count, format
  problems do). 1: any unsuppressed finding. 2: usage/internal error
  (the CLI's argparse contract).
"""

from __future__ import annotations

import ast
import dataclasses
import io
import os
import pathlib
import re
import tokenize
from typing import Iterable, List, Optional, Sequence, Tuple

#: framework-owned check ids (not suppressible via themselves)
CHECK_PARSE = "parse-failure"
CHECK_SUPPRESSION = "suppression-format"
CHECK_STALE = "suppression-stale"

#: directory names the walker never descends into: native build trees,
#: caches, artifact dirs — nothing in them is first-party Python source
SKIP_DIR_NAMES = frozenset({
    "__pycache__", "native", "sdl2_stub", "build", "dist", "out",
    ".git", ".venv", "node_modules",
})

#: a file whose first lines carry one of these is a generated artifact —
#: not reviewed source, not held to source contracts
GENERATED_MARKERS = ("@generated", "do not edit")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One invariant violation at ``path:line``."""

    check: str
    path: str
    line: int
    message: str

    @property
    def location(self) -> str:
        return f"{self.path}:{self.line}"

    def to_json(self) -> dict:
        return {
            "check": self.check,
            "path": self.path,
            "line": self.line,
            "message": self.message,
        }


class Checker:
    """Base checker. File checkers override ``check_file`` (called once
    per parsed source file); repo checkers override ``check_tree``
    (called once per run, for whole-tree contracts like the README name
    lints). ``id`` is the stable suppression/README handle,
    ``description`` the one-line invariant, ``bug_class`` the failure it
    guards against (both feed the README checker table)."""

    id: str = ""
    description: str = ""
    bug_class: str = ""

    def check_file(
        self, tree: ast.AST, source: str, relpath: str
    ) -> Iterable[Finding]:
        return ()

    def check_tree(self, root: pathlib.Path) -> Iterable[Finding]:
        return ()


_ALLOW_RE = re.compile(
    r"#\s*gol:\s*allow\(\s*([^)]*?)\s*\)\s*(?::\s*(.*\S))?\s*$"
)


class Suppressions:
    """The per-file ``# gol: allow(...)`` map.

    A trailing allow comment suppresses its whole STATEMENT — every
    physical line of the (simple) statement it ends, so a multi-line
    call's findings (anchored at the statement's first line) are covered
    by an allow on its closing line; a standalone comment line
    suppresses the next statement that holds code (so a long flagged
    line can carry its justification above itself). Format problems —
    no justification, no/unknown check id — surface as
    ``suppression-format`` findings in ``problems``.

    Every well-formed allow additionally tracks whether it HID anything:
    one that matched no finding across a full-registry run is reported
    as ``suppression-stale`` (:meth:`stale_findings`), so the allow-list
    cannot rot as checkers and code evolve — a suppression for a bug
    long since fixed (or a checker long since changed) is itself a
    finding, not a silent permanent mute."""

    def __init__(self, source: str, relpath: str, known_ids, tree=None):
        self.relpath = relpath
        self.by_line: dict = {}
        self.problems: List[Finding] = []
        #: each well-formed allow: {"line", "ids", "used", "malformed"}
        self.allows: List[dict] = []
        known = frozenset(known_ids)
        lines = source.splitlines()
        spans = self._statement_spans(tree)
        for i, raw in self._allow_comments(source):
            m = _ALLOW_RE.search(raw)
            if m is None:
                continue
            ids = [s.strip() for s in m.group(1).split(",") if s.strip()]
            justification = (m.group(2) or "").strip()
            target = i
            if i <= len(lines) and lines[i - 1].lstrip().startswith("#"):
                # standalone comment: applies to the next code line
                j = i + 1
                while j <= len(lines) and (
                    not lines[j - 1].strip()
                    or lines[j - 1].lstrip().startswith("#")
                ):
                    j += 1
                target = j
            malformed = False
            if not ids:
                malformed = True
                self.problems.append(Finding(
                    CHECK_SUPPRESSION, relpath, i,
                    "allow() names no check id",
                ))
            for unknown in (x for x in ids if x not in known):
                malformed = True
                self.problems.append(Finding(
                    CHECK_SUPPRESSION, relpath, i,
                    f"allow() names unknown check id {unknown!r}",
                ))
            if not justification:
                malformed = True
                self.problems.append(Finding(
                    CHECK_SUPPRESSION, relpath, i,
                    "suppression carries no justification — write "
                    "'# gol: allow(<check>): <why this is safe>'",
                ))
            allow = {
                "line": i, "ids": tuple(ids), "used": set(),
                "malformed": malformed,
            }
            self.allows.append(allow)
            index = len(self.allows) - 1
            # record the suppression even when malformed: the format
            # finding above already fails the run, and double-reporting
            # the underlying finding would bury it — and expand it over
            # the containing simple statement's whole span, so findings
            # anchored at a multi-line statement's FIRST line are hidden
            # by an allow on its LAST
            for line in spans.get(target, (target,)):
                slot = self.by_line.setdefault(line, {})
                for check_id in ids:
                    slot.setdefault(check_id, index)

    @staticmethod
    def _statement_spans(tree) -> dict:
        """line -> every line of the innermost SIMPLE statement covering
        it. Compound statements (if/with/for/def) are excluded: an allow
        on their header must not mute their whole body."""
        spans: dict = {}
        if tree is None:
            return spans
        # walk outermost-first so inner statements overwrite (a lambda
        # body's expression statement inside an assign, etc.)
        for node in ast.walk(tree):
            if not isinstance(node, ast.stmt) or isinstance(
                node,
                (
                    ast.If, ast.For, ast.AsyncFor, ast.While, ast.With,
                    ast.AsyncWith, ast.Try, ast.FunctionDef,
                    ast.AsyncFunctionDef, ast.ClassDef,
                ),
            ):
                continue
            end = node.end_lineno or node.lineno
            if end == node.lineno:
                continue
            covered = tuple(range(node.lineno, end + 1))
            for line in covered:
                spans[line] = covered
        return spans

    @staticmethod
    def _allow_comments(source: str):
        """``(line, comment text)`` for every real COMMENT token — the
        tokenizer keeps allow syntax quoted in docstrings/messages (this
        framework's own documentation!) from registering as live
        suppressions."""
        try:
            tokens = tokenize.generate_tokens(io.StringIO(source).readline)
            return [
                (tok.start[0], tok.string)
                for tok in tokens
                if tok.type == tokenize.COMMENT and "gol:" in tok.string
            ]
        except (tokenize.TokenError, IndentationError, SyntaxError):
            # unparsable source never reaches here (ast.parse gates it),
            # but stay defensive: no comments beats a crash
            return []

    def hides(self, finding: Finding) -> bool:
        slot = self.by_line.get(finding.line, {})
        index = slot.get(finding.check)
        if index is None:
            return False
        self.allows[index]["used"].add(finding.check)
        return True

    def stale_findings(self) -> List[Finding]:
        """One ``suppression-stale`` finding per well-formed allow whose
        named check(s) hid NOTHING. Malformed allows are exempt — their
        format finding already fails the run; double-reporting would
        bury it. Callers run this only after EVERY checker in the full
        registry has reported (a filtered ``--checks`` run proves
        nothing about the other checkers' suppressions)."""
        stale = []
        for allow in self.allows:
            if allow["malformed"]:
                continue
            unmatched = [c for c in allow["ids"] if c not in allow["used"]]
            if unmatched:
                stale.append(Finding(
                    CHECK_STALE, self.relpath, allow["line"],
                    f"allow({', '.join(unmatched)}) matched no finding in "
                    f"a full-registry run — the code it excused has "
                    f"changed (or the checker has); delete the "
                    f"suppression or re-justify what it covers",
                ))
        return stale


@dataclasses.dataclass
class Report:
    """One analyzer run: what fired, what was suppressed, what was seen."""

    findings: List[Finding]
    suppressed: List[Finding]
    files: int
    checkers: List[Checker]

    @property
    def clean(self) -> bool:
        return not self.findings

    def to_json(self) -> dict:
        return {
            "clean": self.clean,
            "files": self.files,
            "checks": {
                c.id: {
                    "description": c.description,
                    "bug_class": c.bug_class,
                }
                for c in self.checkers
            },
            "findings": [f.to_json() for f in self.findings],
            "suppressed": [f.to_json() for f in self.suppressed],
        }

    def render(self) -> str:
        lines = []
        for f in sorted(
            self.findings, key=lambda f: (f.path, f.line, f.check)
        ):
            lines.append(f"{f.location}: [{f.check}] {f.message}")
        checks = ", ".join(c.id for c in self.checkers)
        if self.clean:
            lines.append(
                f"analysis ok: {self.files} file(s) clean under "
                f"[{checks}] ({len(self.suppressed)} justified "
                f"suppression(s))"
            )
        else:
            lines.append(
                f"analysis: {len(self.findings)} finding(s) across "
                f"{self.files} file(s) "
                f"({len(self.suppressed)} suppressed)"
            )
        return "\n".join(lines)


def is_generated(source: str) -> bool:
    head = "\n".join(source.splitlines()[:3]).lower()
    return any(marker in head for marker in GENERATED_MARKERS)


def iter_python_files(root) -> Iterable[pathlib.Path]:
    """Every analyzable ``*.py`` under ``root``, deterministic order,
    never descending into ``SKIP_DIR_NAMES`` (native build trees,
    artifact dirs — see module docstring)."""
    root = pathlib.Path(root)
    if root.is_file():
        yield root
        return
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d not in SKIP_DIR_NAMES)
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield pathlib.Path(dirpath) / fn


def rel_base(root: pathlib.Path) -> pathlib.Path:
    """Findings are reported relative to this directory: the first
    non-package ancestor (so paths read
    ``gol_distributed_final_tpu/rpc/broker.py``, clickable from the repo
    root, whether the target is the package, a subpackage, or a single
    file inside one — the path-scoped rules key on the ``rpc``/``obs``
    segments, which this keeps intact). A plain fixture tree with no
    ``__init__.py`` is its own base."""
    root = pathlib.Path(root)
    base = root.parent if root.is_file() else root
    while (base / "__init__.py").exists() and base.parent != base:
        base = base.parent
    return base


def analyze_source(
    source: str,
    relpath: str,
    checkers: Sequence[Checker],
    known_ids: Optional[Iterable[str]] = None,
) -> Tuple[List[Finding], List[Finding]]:
    """Run the file checkers over one source blob —
    ``(findings, suppressed)``. The test fixture corpus drives each
    checker through exactly this entry point."""
    findings, suppressed, _sup = _analyze_file(
        source, relpath, checkers, known_ids
    )
    return findings, suppressed


def _analyze_file(
    source: str,
    relpath: str,
    checkers: Sequence[Checker],
    known_ids: Optional[Iterable[str]] = None,
) -> Tuple[List[Finding], List[Finding], Optional[Suppressions]]:
    """:func:`analyze_source` plus the file's :class:`Suppressions` (for
    the runner: repo-checker findings route through it, and the stale
    pass interrogates it after every checker has reported)."""
    if known_ids is None:
        known_ids = [c.id for c in checkers]
    try:
        tree = ast.parse(source)
    except (SyntaxError, ValueError) as e:
        line = getattr(e, "lineno", None) or 1
        return [Finding(
            CHECK_PARSE, relpath, line,
            f"cannot parse: {getattr(e, 'msg', e)} — the analyzer refuses "
            "to silently skip unreadable source",
        )], [], None
    sup = Suppressions(source, relpath, known_ids, tree=tree)
    findings: List[Finding] = list(sup.problems)
    suppressed: List[Finding] = []
    seen = set(findings)
    for checker in checkers:
        for f in checker.check_file(tree, source, relpath):
            if f in seen:
                continue  # e.g. two reads of one field on one line
            seen.add(f)
            (suppressed if sup.hides(f) else findings).append(f)
    return findings, suppressed, sup


def run(
    root,
    checkers: Optional[Sequence[Checker]] = None,
    with_repo: bool = True,
) -> Report:
    """Analyze every source file under ``root`` (a package directory or
    any tree), then the repo-level checkers. See module docstring for
    the walker, suppression, and exit-code contracts."""
    from . import all_checkers

    root = pathlib.Path(root).resolve()
    if checkers is None:
        checkers = all_checkers()
    file_checkers = [
        c for c in checkers
        if type(c).check_file is not Checker.check_file
    ]
    repo = [
        c for c in checkers
        if type(c).check_tree is not Checker.check_tree
    ]
    # suppressions validate against the FULL registry, not just this
    # run's (possibly --checks-filtered) subset: an in-tree
    # 'gol: allow(hygiene): ...' comment must stay a known id during a
    # --checks jit-cache run, not become a spurious format finding
    known_ids = {c.id for c in checkers} | {c.id for c in all_checkers()}
    base = rel_base(root)
    findings: List[Finding] = []
    suppressed: List[Finding] = []
    sups: dict = {}  # relpath -> Suppressions, for the passes below
    files = 0
    for path in iter_python_files(root):
        try:
            # tokenize.open honors PEP 263 coding declarations, so a
            # legal latin-1 source file decodes instead of crashing the
            # whole run; anything unreadable is still a LOUD finding
            with tokenize.open(path) as f:
                source = f.read()
        except (OSError, SyntaxError, UnicodeDecodeError) as e:
            findings.append(Finding(
                CHECK_PARSE, path.relative_to(base).as_posix(), 1,
                f"cannot read: {e}",
            ))
            continue
        if is_generated(source):
            continue
        files += 1
        relpath = path.relative_to(base).as_posix()
        got, hidden, sup = _analyze_file(
            source, relpath, file_checkers, known_ids
        )
        findings.extend(got)
        suppressed.extend(hidden)
        if sup is not None:
            sups[relpath] = sup
    if with_repo:
        for checker in repo:
            for f in checker.check_tree(root):
                # repo-level findings anchored in a source file (the
                # lock-composition checkers) honor that file's inline
                # allows like any per-file finding; README-anchored doc
                # lints have no suppression surface, as before
                sup = sups.get(f.path)
                if sup is not None and sup.hides(f):
                    suppressed.append(f)
                else:
                    findings.append(f)
    # the stale pass LAST, and only when this run exercised the full
    # registry (plus the repo checkers): a filtered run proves nothing
    # about the other checkers' suppressions and must not flag them
    full = {c.id for c in all_checkers()} <= {c.id for c in checkers}
    if full and with_repo:
        for relpath in sorted(sups):
            findings.extend(sups[relpath].stale_findings())
    return Report(findings, suppressed, files, list(checkers))
