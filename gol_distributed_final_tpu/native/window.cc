// SDL2 window backend — the one native-UI component of the framework.
//
// Mirrors the reference's SDL window (reference: sdl/window.go:10-104,
// reached there through the go-sdl2 cgo binding): an ARGB8888 streaming
// texture over a byte pixel buffer, with FlipPixel/SetPixel/CountPixels/
// ClearPixels/RenderFrame, plus key polling for the p/s/q/k controls
// (reference: sdl/loop.go:16-28).
//
// Build (requires libSDL2 development headers):
//   make -C gol_distributed_final_tpu/native window
// The Python side (viz/window.py) falls back to a headless buffer-only
// window when libgolwindow.so is absent — this image has no libSDL2, so
// the source ships buildable-but-unbuilt and the fallback serves.

#include <cstdint>
#include <cstdlib>
#include <cstring>

#ifdef GOL_HAVE_SDL2
#include <SDL2/SDL.h>

struct GolWindow {
  SDL_Window* window;
  SDL_Renderer* renderer;
  SDL_Texture* texture;
  uint32_t* pixels;
  int width;
  int height;
};

extern "C" {

GolWindow* golwin_create(int width, int height, const char* title) {
  if (SDL_Init(SDL_INIT_VIDEO) != 0) return nullptr;
  GolWindow* w = new GolWindow();
  w->width = width;
  w->height = height;
  w->window =
      SDL_CreateWindow(title, SDL_WINDOWPOS_CENTERED, SDL_WINDOWPOS_CENTERED,
                       width, height, SDL_WINDOW_SHOWN);
  w->renderer = SDL_CreateRenderer(w->window, -1, SDL_RENDERER_ACCELERATED);
  w->texture = SDL_CreateTexture(w->renderer, SDL_PIXELFORMAT_ARGB8888,
                                 SDL_TEXTUREACCESS_STREAMING, width, height);
  w->pixels = (uint32_t*)calloc((size_t)width * height, sizeof(uint32_t));
  return w;
}

void golwin_destroy(GolWindow* w) {
  if (!w) return;
  free(w->pixels);
  SDL_DestroyTexture(w->texture);
  SDL_DestroyRenderer(w->renderer);
  SDL_DestroyWindow(w->window);
  SDL_Quit();
  delete w;
}

void golwin_flip_pixel(GolWindow* w, int x, int y) {
  // XOR all channel bytes, like the reference (sdl/window.go FlipPixel)
  w->pixels[(size_t)y * w->width + x] ^= 0x00FFFFFFu;
}

void golwin_set_pixel(GolWindow* w, int x, int y, uint32_t argb) {
  w->pixels[(size_t)y * w->width + x] = argb;
}

long golwin_count_pixels(GolWindow* w) {
  long count = 0;
  for (long i = 0; i < (long)w->width * w->height; i++)
    if (w->pixels[i] & 0x00FFFFFFu) count++;
  return count;
}

void golwin_clear_pixels(GolWindow* w) {
  memset(w->pixels, 0, (size_t)w->width * w->height * sizeof(uint32_t));
}

void golwin_render_frame(GolWindow* w) {
  SDL_UpdateTexture(w->texture, nullptr, w->pixels,
                    w->width * (int)sizeof(uint32_t));
  SDL_RenderClear(w->renderer);
  SDL_RenderCopy(w->renderer, w->texture, nullptr, nullptr);
  SDL_RenderPresent(w->renderer);
}

// Poll one key event; returns the key char ('p','s','q','k'), 0 for none,
// or -1 for window close.
int golwin_poll_key(GolWindow* w) {
  (void)w;
  SDL_Event e;
  while (SDL_PollEvent(&e)) {
    if (e.type == SDL_QUIT) return -1;
    if (e.type == SDL_KEYDOWN) {
      switch (e.key.keysym.sym) {
        case SDLK_p: return 'p';
        case SDLK_s: return 's';
        case SDLK_q: return 'q';
        case SDLK_k: return 'k';
        default: break;
      }
    }
  }
  return 0;
}

}  // extern "C"

#endif  // GOL_HAVE_SDL2
