// Fast PGM (P5) codec — the native IO path for large boards.
//
// The reference's IO is a Go goroutine streaming one byte at a time over a
// channel (reference: gol/io.go:42-126). This framework's default codec is
// vectorised Python (io/pgm.py); this C++ codec is the accelerated path for
// boards where even that matters (multi-GiB streamed shard IO, SURVEY.md §7
// step 6): raw pread/pwrite with no interpreter in the loop.
//
// Exposed as a plain C ABI for ctypes (no pybind11 in the image).
// Build: make -C gol_distributed_final_tpu/native  (produces libgolio.so)

#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstring>

#include <fcntl.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <unistd.h>

namespace {

// Parse the P5 header: magic, width, height, maxval, raster offset.
// Handles '#' comments and arbitrary whitespace. Returns 0 on success.
int parse_header(const unsigned char* buf, long len, long* width, long* height,
                 long* maxval, long* offset) {
  long pos = 0;
  long fields[3];
  int nfields = 0;
  if (len < 2 || buf[0] != 'P' || buf[1] != '5') return -1;
  pos = 2;
  while (nfields < 3) {
    if (pos >= len) return -1;
    unsigned char c = buf[pos];
    if (c == '#') {
      while (pos < len && buf[pos] != '\n') pos++;
    } else if (c == ' ' || c == '\t' || c == '\n' || c == '\r' || c == '\v' ||
               c == '\f') {
      pos++;
    } else if (c >= '0' && c <= '9') {
      long v = 0;
      while (pos < len && buf[pos] >= '0' && buf[pos] <= '9') {
        v = v * 10 + (buf[pos] - '0');
        pos++;
      }
      fields[nfields++] = v;
    } else {
      return -1;
    }
  }
  // exactly one whitespace byte before the raster
  if (pos >= len) return -1;
  unsigned char c = buf[pos];
  if (!(c == ' ' || c == '\t' || c == '\n' || c == '\r')) return -1;
  pos++;
  *width = fields[0];
  *height = fields[1];
  *maxval = fields[2];
  *offset = pos;
  return 0;
}

}  // namespace

extern "C" {

// Returns 0 on success; fills width/height/maxval/offset.
int golio_read_header(const char* path, long* width, long* height,
                      long* maxval, long* offset) {
  int fd = open(path, O_RDONLY);
  if (fd < 0) return -errno;
  unsigned char buf[4096];
  ssize_t n = read(fd, buf, sizeof(buf));
  close(fd);
  if (n <= 0) return -1;
  return parse_header(buf, (long)n, width, height, maxval, offset);
}

// Read rows [start, stop) of the raster into out (caller-allocated,
// (stop-start)*width bytes). Returns 0 on success.
int golio_read_rows(const char* path, long offset, long width, long start,
                    long stop, unsigned char* out) {
  int fd = open(path, O_RDONLY);
  if (fd < 0) return -errno;
  long total = (stop - start) * width;
  off_t at = offset + (off_t)start * width;
  long done = 0;
  while (done < total) {
    ssize_t n = pread(fd, out + done, total - done, at + done);
    if (n <= 0) {
      close(fd);
      return n == 0 ? -1 : -errno;
    }
    done += n;
  }
  close(fd);
  return 0;
}

// Write a whole board as P5 (header + raster), fsync'd like the reference
// (gol/io.go:84-85). Returns 0 on success.
int golio_write(const char* path, long width, long height,
                const unsigned char* data) {
  int fd = open(path, O_WRONLY | O_CREAT | O_TRUNC, 0644);
  if (fd < 0) return -errno;
  char header[64];
  int hlen = snprintf(header, sizeof(header), "P5\n%ld %ld\n255\n", width,
                      height);
  ssize_t hw = write(fd, header, hlen);
  if (hw != hlen) {
    // a short write may not set errno; never report success for it
    int e = hw < 0 ? errno : EIO;
    close(fd);
    return -e;
  }
  long total = width * height;
  long done = 0;
  while (done < total) {
    ssize_t n = write(fd, data + done, total - done);
    if (n <= 0) {
      int e = n < 0 ? errno : EIO;
      close(fd);
      return -e;
    }
    done += n;
  }
  if (fsync(fd) != 0) {
    int e = errno;
    close(fd);
    return -e;
  }
  return close(fd) == 0 ? 0 : -errno;
}

// Append rows to an already-open file descriptor (streamed shard writes).
int golio_write_rows_fd(int fd, long width, long nrows,
                        const unsigned char* data) {
  long total = width * nrows;
  long done = 0;
  while (done < total) {
    ssize_t n = write(fd, data + done, total - done);
    if (n <= 0) return n < 0 ? -errno : -EIO;
    done += n;
  }
  return 0;
}

}  // extern "C"
