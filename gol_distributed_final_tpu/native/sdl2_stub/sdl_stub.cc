// No-op SDL2 implementation backing sdl2_stub/SDL2/SDL.h — see the header
// for why this exists. Window/renderer/texture handles are distinct dummy
// non-null pointers; SDL_PollEvent drains a small injectable queue so
// window.cc's golwin_poll_key switch runs for real.

#include <SDL2/SDL.h>

namespace {
SDL_Event g_queue[64];
int g_head = 0;
int g_tail = 0;
long g_renders = 0;

void push(const SDL_Event& e) {
  if ((g_tail + 1) % 64 == g_head) return;  // full: drop (test-only queue)
  g_queue[g_tail] = e;
  g_tail = (g_tail + 1) % 64;
}
}  // namespace

extern "C" {

int SDL_Init(uint32_t) { return 0; }
void SDL_Quit(void) {}

SDL_Window* SDL_CreateWindow(const char*, int, int, int, int, uint32_t) {
  static int dummy;
  return reinterpret_cast<SDL_Window*>(&dummy);
}
void SDL_DestroyWindow(SDL_Window*) {}

SDL_Renderer* SDL_CreateRenderer(SDL_Window*, int, uint32_t) {
  static int dummy;
  return reinterpret_cast<SDL_Renderer*>(&dummy);
}
void SDL_DestroyRenderer(SDL_Renderer*) {}

SDL_Texture* SDL_CreateTexture(SDL_Renderer*, uint32_t, int, int, int) {
  static int dummy;
  return reinterpret_cast<SDL_Texture*>(&dummy);
}
void SDL_DestroyTexture(SDL_Texture*) {}

int SDL_UpdateTexture(SDL_Texture*, const SDL_Rect*, const void*, int) {
  return 0;
}
int SDL_RenderClear(SDL_Renderer*) { return 0; }
int SDL_RenderCopy(SDL_Renderer*, SDL_Texture*, const SDL_Rect*,
                   const SDL_Rect*) {
  return 0;
}
void SDL_RenderPresent(SDL_Renderer*) { g_renders++; }

int SDL_PollEvent(SDL_Event* event) {
  if (g_head == g_tail) return 0;
  *event = g_queue[g_head];
  g_head = (g_head + 1) % 64;
  return 1;
}

void sdl_stub_push_key(int sym) {
  SDL_Event e;
  e.type = SDL_KEYDOWN;
  e.key.keysym.sym = sym;
  push(e);
}

void sdl_stub_push_quit(void) {
  SDL_Event e;
  e.type = SDL_QUIT;
  e.key.keysym.sym = 0;
  push(e);
}

long sdl_stub_render_count(void) { return g_renders; }

}  // extern "C"
