// BEHAVIORAL SDL2 stub backing sdl2_stub/SDL2/SDL.h — see the header for
// why this exists. Beyond distinct non-null handles and an injectable
// event queue, the stub now RECORDS the call sequence and VALIDATES each
// call against the real SDL API's contract (VERDICT r4 item 2): init
// ordering, live-handle use, texture pitch, per-frame update/clear/copy/
// present ordering, create/destroy pairing. An SDL-API misuse inside
// window.cc — the kind that would pass a no-op stub and only surface on a
// user's machine with real libSDL2 — lands in sdl_stub_violations(),
// which tests/test_native_window.py asserts is empty after driving a real
// session.
//
// Single-slot by design: one live window/renderer/texture at a time (all
// framework surfaces open at most one window); a concurrent second create
// is itself recorded as a violation.

#include <SDL2/SDL.h>

#include <cstdarg>
#include <cstdio>
#include <cstring>

namespace {
SDL_Event g_queue[64];
int g_head = 0;
int g_tail = 0;
long g_renders = 0;

// ---- behavioral state machine ---------------------------------------------
bool g_inited = false;
int g_win_live = 0, g_ren_live = 0, g_tex_live = 0;  // 0 none, 1 live, -1 dead
int g_win_w = 0, g_win_h = 0;
int g_tex_w = 0, g_tex_h = 0;
bool g_copied_since_present = false;
bool g_cleared_since_present = false;

char g_trace[8192];
size_t g_trace_len = 0;
char g_viol[4096];
size_t g_viol_len = 0;

// handles are addresses of these markers; dead handles stay recognisable
// so use-after-destroy is reported as such, not as "unknown handle"
int g_win_obj, g_ren_obj, g_tex_obj;

void append(char* buf, size_t cap, size_t* len, const char* sep,
            const char* msg) {
  size_t need = strlen(msg) + (*len ? strlen(sep) : 0);
  if (*len + need + 4 >= cap) return;  // full: drop (tests reset first)
  if (*len) {
    memcpy(buf + *len, sep, strlen(sep));
    *len += strlen(sep);
  }
  memcpy(buf + *len, msg, strlen(msg));
  *len += strlen(msg);
  buf[*len] = '\0';
}

void trace(const char* name) { append(g_trace, sizeof g_trace, &g_trace_len, ",", name); }

void violate(const char* fmt, ...) {
  char msg[256];
  va_list ap;
  va_start(ap, fmt);
  vsnprintf(msg, sizeof msg, fmt, ap);
  va_end(ap);
  append(g_viol, sizeof g_viol, &g_viol_len, ";", msg);
}

bool need_init(const char* who) {
  if (!g_inited) {
    violate("%s before SDL_Init", who);
    return false;
  }
  return true;
}

bool check_renderer(const char* who, SDL_Renderer* r) {
  if (r != reinterpret_cast<SDL_Renderer*>(&g_ren_obj) || g_ren_live != 1) {
    violate("%s: %s renderer", who, g_ren_live == -1 ? "destroyed" : "unknown");
    return false;
  }
  return true;
}

bool check_texture(const char* who, SDL_Texture* t) {
  if (t != reinterpret_cast<SDL_Texture*>(&g_tex_obj) || g_tex_live != 1) {
    violate("%s: %s texture", who, g_tex_live == -1 ? "destroyed" : "unknown");
    return false;
  }
  return true;
}

void push(const SDL_Event& e) {
  if ((g_tail + 1) % 64 == g_head) return;  // full: drop (test-only queue)
  g_queue[g_tail] = e;
  g_tail = (g_tail + 1) % 64;
}
}  // namespace

extern "C" {

int SDL_Init(uint32_t flags) {
  trace("Init");
  if (!(flags & SDL_INIT_VIDEO)) violate("SDL_Init without SDL_INIT_VIDEO");
  g_inited = true;
  return 0;
}

void SDL_Quit(void) {
  trace("Quit");
  if (!g_inited) violate("SDL_Quit before SDL_Init");
  if (g_win_live == 1 || g_ren_live == 1 || g_tex_live == 1)
    violate("SDL_Quit with live handles (missing Destroy calls)");
  g_inited = false;
  // real SDL_Quit invalidates everything; a fresh Init may create anew
  g_win_live = g_ren_live = g_tex_live = 0;
}

SDL_Window* SDL_CreateWindow(const char* title, int, int, int w, int h,
                             uint32_t) {
  trace("CreateWindow");
  if (!need_init("SDL_CreateWindow")) return nullptr;
  if (!title) violate("SDL_CreateWindow: null title");
  if (w <= 0 || h <= 0) violate("SDL_CreateWindow: bad size %dx%d", w, h);
  if (g_win_live == 1) violate("SDL_CreateWindow: window already live");
  g_win_live = 1;
  g_win_w = w;
  g_win_h = h;
  return reinterpret_cast<SDL_Window*>(&g_win_obj);
}

void SDL_DestroyWindow(SDL_Window* win) {
  trace("DestroyWindow");
  if (win != reinterpret_cast<SDL_Window*>(&g_win_obj) || g_win_live != 1) {
    violate("SDL_DestroyWindow: %s window",
            g_win_live == -1 ? "double-destroyed" : "unknown");
    return;
  }
  if (g_ren_live == 1)
    violate("SDL_DestroyWindow before SDL_DestroyRenderer");
  g_win_live = -1;
}

SDL_Renderer* SDL_CreateRenderer(SDL_Window* win, int, uint32_t) {
  trace("CreateRenderer");
  if (!need_init("SDL_CreateRenderer")) return nullptr;
  if (win != reinterpret_cast<SDL_Window*>(&g_win_obj) || g_win_live != 1)
    violate("SDL_CreateRenderer: %s window",
            g_win_live == -1 ? "destroyed" : "unknown");
  if (g_ren_live == 1) violate("SDL_CreateRenderer: renderer already live");
  g_ren_live = 1;
  g_copied_since_present = g_cleared_since_present = false;
  return reinterpret_cast<SDL_Renderer*>(&g_ren_obj);
}

void SDL_DestroyRenderer(SDL_Renderer* r) {
  trace("DestroyRenderer");
  if (r != reinterpret_cast<SDL_Renderer*>(&g_ren_obj) || g_ren_live != 1) {
    violate("SDL_DestroyRenderer: %s renderer",
            g_ren_live == -1 ? "double-destroyed" : "unknown");
    return;
  }
  if (g_tex_live == 1)
    violate("SDL_DestroyRenderer before SDL_DestroyTexture");
  g_ren_live = -1;
}

SDL_Texture* SDL_CreateTexture(SDL_Renderer* r, uint32_t format, int access,
                               int w, int h) {
  trace("CreateTexture");
  if (!need_init("SDL_CreateTexture")) return nullptr;
  if (!check_renderer("SDL_CreateTexture", r)) return nullptr;
  if (format != SDL_PIXELFORMAT_ARGB8888)
    violate("SDL_CreateTexture: format 0x%x != ARGB8888", format);
  if (access != SDL_TEXTUREACCESS_STREAMING)
    violate("SDL_CreateTexture: access %d != STREAMING", access);
  if (w <= 0 || h <= 0) violate("SDL_CreateTexture: bad size %dx%d", w, h);
  if (g_tex_live == 1) violate("SDL_CreateTexture: texture already live");
  g_tex_live = 1;
  g_tex_w = w;
  g_tex_h = h;
  return reinterpret_cast<SDL_Texture*>(&g_tex_obj);
}

void SDL_DestroyTexture(SDL_Texture* t) {
  trace("DestroyTexture");
  if (t != reinterpret_cast<SDL_Texture*>(&g_tex_obj) || g_tex_live != 1) {
    violate("SDL_DestroyTexture: %s texture",
            g_tex_live == -1 ? "double-destroyed" : "unknown");
    return;
  }
  g_tex_live = -1;
}

int SDL_UpdateTexture(SDL_Texture* t, const SDL_Rect* rect,
                      const void* pixels, int pitch) {
  trace("Update");
  if (!check_texture("SDL_UpdateTexture", t)) return -1;
  if (!pixels) violate("SDL_UpdateTexture: null pixels");
  // the classic misuse this stub exists to catch: for a full-texture
  // update of a 4-byte format, pitch must be width*4 BYTES (not width,
  // not height*4) — wrong pitch shears every row on a real machine
  if (!rect && pitch != g_tex_w * 4)
    violate("SDL_UpdateTexture: pitch %d != width*4 (%d)", pitch,
            g_tex_w * 4);
  return 0;
}

int SDL_RenderClear(SDL_Renderer* r) {
  trace("Clear");
  if (!check_renderer("SDL_RenderClear", r)) return -1;
  g_cleared_since_present = true;
  return 0;
}

int SDL_RenderCopy(SDL_Renderer* r, SDL_Texture* t, const SDL_Rect*,
                   const SDL_Rect*) {
  trace("Copy");
  if (!check_renderer("SDL_RenderCopy", r)) return -1;
  if (!check_texture("SDL_RenderCopy", t)) return -1;
  g_copied_since_present = true;
  return 0;
}

void SDL_RenderPresent(SDL_Renderer* r) {
  trace("Present");
  if (!check_renderer("SDL_RenderPresent", r)) return;
  if (!g_copied_since_present)
    violate("SDL_RenderPresent without a RenderCopy this frame");
  if (!g_cleared_since_present)
    violate("SDL_RenderPresent without a RenderClear this frame");
  g_copied_since_present = g_cleared_since_present = false;
  g_renders++;
}

int SDL_PollEvent(SDL_Event* event) {
  // not traced: polled every frame, would drown the call log
  if (!g_inited) violate("SDL_PollEvent before SDL_Init");
  if (g_head == g_tail) return 0;
  *event = g_queue[g_head];
  g_head = (g_head + 1) % 64;
  return 1;
}

void sdl_stub_push_key(int sym) {
  SDL_Event e;
  memset(&e, 0, sizeof e);
  // written through the REAL field layout (type at 0, sym at offset 20):
  // golwin_poll_key reading them back round-trips the struct offsets
  e.key.type = SDL_KEYDOWN;
  e.key.state = 1;  // SDL_PRESSED
  e.key.keysym.sym = sym;
  push(e);
}

void sdl_stub_push_quit(void) {
  SDL_Event e;
  memset(&e, 0, sizeof e);
  e.type = SDL_QUIT;
  push(e);
}

long sdl_stub_render_count(void) { return g_renders; }

const char* sdl_stub_trace(void) { return g_trace; }

const char* sdl_stub_violations(void) { return g_viol; }

void sdl_stub_reset(void) {
  g_head = g_tail = 0;
  g_renders = 0;
  g_inited = false;
  g_win_live = g_ren_live = g_tex_live = 0;
  g_win_w = g_win_h = g_tex_w = g_tex_h = 0;
  g_copied_since_present = g_cleared_since_present = false;
  g_trace[0] = g_viol[0] = '\0';
  g_trace_len = g_viol_len = 0;
}

}  // extern "C"
