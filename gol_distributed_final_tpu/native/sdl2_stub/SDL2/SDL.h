// Minimal vendored SDL2 API surface — EXACTLY what native/window.cc uses.
//
// Purpose (VERDICT round 3 item 2): let window.cc compile and run in-tree
// with no system libSDL2, so the exported golwin_* C ABI and the ctypes
// declarations in viz/window.py:72-93 are exercised together in CI. The
// no-op implementations live in ../sdl_stub.cc; SDL_PollEvent is backed by
// a small injectable event queue (sdl_stub_push_key / sdl_stub_push_quit)
// so the real golwin_poll_key switch logic is testable.
//
// This is NOT SDL: declarations mirror the real API's shapes (names,
// arities, the struct fields window.cc touches) but constants are local.
// A build against real SDL2 uses the system header (native/Makefile picks
// the include path).

#ifndef GOL_SDL2_STUB_H
#define GOL_SDL2_STUB_H

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef struct SDL_Window SDL_Window;
typedef struct SDL_Renderer SDL_Renderer;
typedef struct SDL_Texture SDL_Texture;
typedef struct SDL_Rect SDL_Rect;

#define SDL_INIT_VIDEO 0x00000020u
#define SDL_WINDOWPOS_CENTERED 0x2FFF0000
#define SDL_WINDOW_SHOWN 0x00000004
#define SDL_RENDERER_ACCELERATED 0x00000002
#define SDL_PIXELFORMAT_ARGB8888 0x16362004
#define SDL_TEXTUREACCESS_STREAMING 1

#define SDL_QUIT 0x100
#define SDL_KEYDOWN 0x300

// SDLK_* are ASCII in real SDL2 too
#define SDLK_p 'p'
#define SDLK_s 's'
#define SDLK_q 'q'
#define SDLK_k 'k'

// Event structs mirror REAL SDL2's field layout (SDL_events.h /
// SDL_keyboard.h), not a minimal shape: sym lands at byte offset 20 of
// the union and the union is padded to SDL2's 56 bytes. window.cc reads
// fields through this header, so compiling against it exercises the same
// offsets a real-SDL build uses — a hardcoded-offset or struct-shape
// mistake in window.cc that would only break on a user's machine breaks
// here instead (VERDICT r4 item 2).
typedef struct {
  int32_t scancode;
  int32_t sym;
  uint16_t mod;
  uint32_t unused;
} SDL_Keysym;

typedef struct {
  uint32_t type;
  uint32_t timestamp;
  uint32_t windowID;
  uint8_t state;
  uint8_t repeat;
  uint8_t padding2;
  uint8_t padding3;
  SDL_Keysym keysym;
} SDL_KeyboardEvent;

typedef union {
  uint32_t type;
  SDL_KeyboardEvent key;
  uint8_t padding[56];
} SDL_Event;

int SDL_Init(uint32_t flags);
void SDL_Quit(void);
SDL_Window* SDL_CreateWindow(const char* title, int x, int y, int w, int h,
                             uint32_t flags);
void SDL_DestroyWindow(SDL_Window* window);
SDL_Renderer* SDL_CreateRenderer(SDL_Window* window, int index,
                                 uint32_t flags);
void SDL_DestroyRenderer(SDL_Renderer* renderer);
SDL_Texture* SDL_CreateTexture(SDL_Renderer* renderer, uint32_t format,
                               int access, int w, int h);
void SDL_DestroyTexture(SDL_Texture* texture);
int SDL_UpdateTexture(SDL_Texture* texture, const SDL_Rect* rect,
                      const void* pixels, int pitch);
int SDL_RenderClear(SDL_Renderer* renderer);
int SDL_RenderCopy(SDL_Renderer* renderer, SDL_Texture* texture,
                   const SDL_Rect* srcrect, const SDL_Rect* dstrect);
void SDL_RenderPresent(SDL_Renderer* renderer);
int SDL_PollEvent(SDL_Event* event);

// -- stub-only test hooks (absent from real SDL2) ---------------------------
void sdl_stub_push_key(int sym);
void sdl_stub_push_quit(void);
// render-call counter so a test can assert golwin_render_frame reached SDL
long sdl_stub_render_count(void);
// BEHAVIORAL hooks (VERDICT r4 item 2): the stub records the SDL call
// sequence and validates arguments/ordering against the real API's
// contract. trace() is the comma-separated call log; violations() is a
// ';'-joined list of contract breaches ("" when clean); reset() clears
// both plus the state machine, for test isolation.
const char* sdl_stub_trace(void);
const char* sdl_stub_violations(void);
void sdl_stub_reset(void);

#ifdef __cplusplus
}
#endif

#endif  // GOL_SDL2_STUB_H
