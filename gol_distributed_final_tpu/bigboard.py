"""Config-5-shaped runs: sparse boards too large to ever exist as bytes.

BASELINE config 5 is a 65536^2 sparse board (R-pentomino seeded) — as a
byte raster that is 4 GiB; the reference materialises the full board in
the controller, the broker AND every worker (SURVEY.md §5), capping board
size at one machine's RAM. Here the board only ever exists as the int32
bitboard on device (32x smaller), is seeded directly from sparse cell
coordinates, evolves through ops/plane.BitPlane (boards this size are far
past the VMEM-kernel gate, so on TPU the plane routes to the grid-tiled
pallas kernel — 65536^2 runs at ~3.6 ms/turn; the XLA bitboard step is
the interpret/CPU fallback), and reaches disk as a stream of unpacked
ROW BLOCKS through io/sharded.py pwrites. The full byte board never
exists on host or device.

    state  = seed_packed(16384, r_pentomino(16384))   # 32 MiB, device
    state  = plane.step_n(state, turns)               # XLA bitboard
    stream_packed_to_pgm(path, state, row_block=1024) # 16 MiB blocks

Reading streams the same way (``load_packed_from_pgm``): row blocks are
packed on device block-by-block.
"""

from __future__ import annotations

import argparse
import sys
from typing import Iterable, Sequence, Tuple

import numpy as np

from .models import CONWAY, LifeRule
from .ops.bitpack import (
    WORD,
    alive_count_packed,
    pack_device,
    packed_shape,
    unpack_device,
)
from .ops.plane import BitPlane

Cells = Iterable[Tuple[int, int]]  # (x, y) pairs


def r_pentomino(size: int) -> list[tuple[int, int]]:
    """The classic methuselah, centred — the BASELINE config-5 seed."""
    cx = cy = size // 2
    offsets = [(1, 0), (2, 0), (0, 1), (1, 1), (1, 2)]
    return [(cx + dx, cy + dy) for dx, dy in offsets]


def seed_packed(
    size: int,
    cells: Cells,
    word_axis: int = 0,
    row_range: tuple[int, int] | None = None,
):
    """A packed device bitboard with only ``cells`` alive.

    Sparse construction: the dense byte board is never built — word
    indices and bit masks are computed host-side from the coordinate list
    (O(len(cells))), then scattered into a device array of zeros.

    ``row_range=(lo, hi)`` builds only the packed rows covering cell rows
    [lo, hi) — the multi-host path, where each rank seeds only the rows
    its devices own instead of a transient full-board allocation
    (~size^2/8 bytes per rank at 65536^2; ADVICE r4). Cells outside the
    range are skipped (after global-bounds validation). For
    ``word_axis=0``, lo and hi must be word-aligned — pod layouts
    guarantee this (choose_bit_layout's divisibility)."""
    import jax.numpy as jnp

    if size % WORD:
        raise ValueError(f"size {size} not divisible by {WORD}")
    lo, hi = (0, size) if row_range is None else row_range
    if not (0 <= lo < hi <= size):
        raise ValueError(f"row_range {row_range} outside [0, {size})")
    if word_axis == 0 and (lo % WORD or hi % WORD):
        raise ValueError(
            f"row_range {row_range} must be word-aligned for word_axis=0"
        )
    nrows = hi - lo
    shape = packed_shape(nrows, size, word_axis)
    rows, cols, bits = [], [], []
    for x, y in cells:
        if not (0 <= x < size and 0 <= y < size):
            raise ValueError(f"cell ({x}, {y}) outside {size}x{size}")
        if not (lo <= y < hi):
            continue
        if word_axis == 0:
            rows.append((y - lo) // WORD)
            cols.append(x)
            bits.append(y % WORD)
        else:
            rows.append(y - lo)
            cols.append(x // WORD)
            bits.append(x % WORD)
    packed = np.zeros(shape, np.uint32)
    np.bitwise_or.at(
        packed, (np.asarray(rows, np.int64), np.asarray(cols, np.int64)),
        np.uint32(1) << np.asarray(bits, np.uint32),
    )
    return jnp.asarray(packed.view(np.int32))


def check_window(packed_shape, y0, x0, h, w, word_axis: int = 0) -> None:
    """Validate a decode window against a packed board's geometry —
    shared by the single-host and pod (pod.decode_window_sharded)
    decoders so both raise identically on out-of-range requests."""
    rows, cols = packed_shape
    height = rows * WORD if word_axis == 0 else rows
    width = cols if word_axis == 0 else cols * WORD
    if h <= 0 or w <= 0:
        raise ValueError(f"window extent {h}x{w} must be positive")
    if not (0 <= y0 and y0 + h <= height and 0 <= x0 and x0 + w <= width):
        raise ValueError(
            f"window [{y0}:{y0 + h}, {x0}:{x0 + w}] outside {height}x{width}"
        )


def window_word_bounds(
    y0: int, x0: int, h: int, w: int, word_axis: int
) -> tuple[int, int, int]:
    """The covering word range along the PACKED axis for a cell window:
    ``(a0, a1, off)`` — packed indices ``[a0:a1]`` cover the window, and
    the window starts ``off`` cells into the unpacked block. Shared by the
    single-host and pod decoders so their slice arithmetic cannot drift."""
    if word_axis == 0:
        a0, a1 = y0 // WORD, -(-(y0 + h) // WORD)
        return a0, a1, y0 - a0 * WORD
    a0, a1 = x0 // WORD, -(-(x0 + w) // WORD)
    return a0, a1, x0 - a0 * WORD


def decode_window(
    state, y0: int, x0: int, h: int, w: int, word_axis: int = 0
) -> np.ndarray:
    """The uint8 window ``[y0:y0+h, x0:x0+w]`` of a packed board, decoded
    without unpacking anything else — the inspection/visualisation surface
    for boards whose full byte raster would be GiB-scale (the reference's
    SDL window shows the whole board, sdl/window.go:22-104; at config-5
    sizes only a window can ever be shown). Only the word rows covering
    the window cross the packed->byte boundary."""
    check_window(state.shape, y0, x0, h, w, word_axis)
    a0, a1, off = window_word_bounds(y0, x0, h, w, word_axis)
    if word_axis == 0:
        block = state[a0:a1, x0 : x0 + w]
        rows_out = np.asarray(unpack_device(block, 0))
        return rows_out[off : off + h]
    block = state[y0 : y0 + h, a0:a1]
    cols_out = np.asarray(unpack_device(block, 1))
    return cols_out[:, off : off + w]


def stream_packed_to_pgm(path, state, word_axis: int = 0, row_block: int = 1024):
    """Write the bitboard to a P5 PGM in row blocks: at most
    ``row_block x W`` bytes exist at once (io/sharded.py pwrites)."""
    from .io.sharded import create_pgm, write_rows_at

    if word_axis == 0:
        height, width = state.shape[0] * WORD, state.shape[1]
    else:
        height, width = state.shape[0], state.shape[1] * WORD
    row_block = max(WORD, row_block - row_block % WORD)
    offset = create_pgm(path, width, height)
    for start in range(0, height, row_block):
        stop = min(start + row_block, height)
        if word_axis == 0:
            block = state[start // WORD : stop // WORD]
        else:
            block = state[start:stop]
        rows = np.asarray(unpack_device(block, word_axis))
        write_rows_at(path, offset, width, start, rows)


def load_packed_from_pgm(path, word_axis: int = 0, row_block: int = 1024):
    """Stream a P5 PGM into a packed device bitboard, block by block."""
    import jax.numpy as jnp

    from .io.pgm import PgmReader
    from .io.sharded import read_shard

    with PgmReader(path) as r:
        width, height = r.width, r.height
    if height % WORD or width % WORD:
        raise ValueError(f"{width}x{height} not divisible by {WORD}")
    row_block = max(WORD, row_block - row_block % WORD)
    blocks = []
    for start in range(0, height, row_block):
        stop = min(start + row_block, height)
        rows = read_shard(path, start, stop)
        blocks.append(pack_device(jnp.asarray(rows), word_axis))
    return jnp.concatenate(blocks, axis=0)


def run_big_board(
    size: int,
    turns: int,
    out_path,
    *,
    cells: Sequence[tuple[int, int]] | None = None,
    in_path=None,
    rule: LifeRule = CONWAY,
    word_axis: int = 0,
    row_block: int = 1024,
    engine=None,
) -> int:
    """Seed (sparse cells or a streamed PGM), evolve, stream out.

    Returns the final alive count (device-side popcount). The full byte
    board never exists anywhere; peak host memory is one row block.

    With ``engine`` (an ``engine.Engine`` configured with
    ``final_world=False`` — enforced), the evolution runs through the engine's
    chunked control loop instead of one bare dispatch — pause / quit /
    RetrieveCurrentData(count-only) / the 2-second ticker all work
    mid-run on a board whose byte raster will never exist, closing the
    gap between the reference's control surface (broker/broker.go:236-277)
    and config-5 scale."""
    state = _seed_state(size, cells, in_path, word_axis, row_block)
    plane = BitPlane(rule, word_axis)
    if engine is not None:
        _check_byte_free_engine(engine)
        from .params import Params

        engine.run(
            Params(turns=turns, image_width=size, image_height=size),
            None,
            plane=plane,
            initial_state=state,
        )
        state = engine.final_state()
    elif turns:
        state = plane.step_n(state, turns)
    if out_path is not None:
        stream_packed_to_pgm(out_path, state, word_axis, row_block)
    return alive_count_packed(state)


def _seed_state(size, cells, in_path, word_axis, row_block):
    if (cells is None) == (in_path is None):
        raise ValueError("exactly one of cells / in_path must be given")
    if cells is not None:
        return seed_packed(size, cells, word_axis)
    return load_packed_from_pgm(in_path, word_axis, row_block)


def _check_byte_free_engine(engine) -> None:
    if engine.config.final_world:
        raise ValueError(
            "big-board runs need an Engine(EngineConfig(final_world="
            "False)): the default run exit decodes the full byte raster "
            "this surface promises never exists"
        )


class _LazyAliveCells:
    """A sequence-shaped ``FinalTurnComplete.alive`` payload that never
    materialises the O(alive) Python Cell list unless actually iterated:
    ``len()`` is a device-side popcount. A dense 65536^2 board would
    otherwise build billions of Cell objects on the one surface that
    promises GiB-scale state never exists on host (ADVICE.md round 3).
    The byte-scale parity surface (engine/controller.py) keeps the eager
    list the reference ships (gol/event.go:65-68)."""

    def __init__(self, plane, state):
        self._plane = plane
        self._state = state

    def __len__(self) -> int:
        return int(self._plane.alive_count(self._state))

    def __iter__(self):
        return iter(self._plane.alive_cells(self._state))

    def __eq__(self, other):
        try:
            other_list = list(other)
        except TypeError:
            # a List[Cell] payload compared False against non-iterables;
            # this stand-in must not raise where the list did not
            return NotImplemented
        return list(self) == other_list


class _PackedBroker:
    """The slice of the stubs verb surface the ticker needs, served by an
    engine holding a packed state. ``retrieve`` is always count-only —
    the PGM snapshot path streams from the packed state instead of ever
    decoding a world."""

    def __init__(self, engine):
        self.engine = engine

    def retrieve(self, include_world: bool = True):
        return self.engine.retrieve(include_world=False)

    def pause(self):
        return self.engine.pause()

    def quit(self):
        return self.engine.quit()

    def super_quit(self):
        return self.engine.super_quit()


def big_session(
    size: int,
    turns: int,
    *,
    cells: Sequence[tuple[int, int]] | None = None,
    in_path=None,
    rule: LifeRule = CONWAY,
    word_axis: int = 0,
    row_block: int = 1024,
    engine=None,
    events=None,
    keypresses=None,
    tick_seconds: float = 2.0,
    out_dir="out",
):
    """The FULL reference session surface over a packed big board: the
    2-second ``AliveCellsCount`` ticker, the ``s``/``q``/``k``/``p``
    keyboard semantics (gol/distributor.go:61-122), and the closing
    ``FinalTurnComplete`` -> PGM -> ``ImageOutputComplete`` ->
    ``StateChange{Quitting}`` -> CLOSED sequence — on a board whose byte
    raster never exists (snapshots stream row blocks; cells come from
    sparse extraction). Returns the engine's RunResult.

    The byte-session equivalent is ``engine.controller.run``; this is its
    config-5 sibling, sharing the same ticker implementation."""
    import pathlib
    import queue as queue_mod

    from .engine.controller import CLOSED, _Ticker
    from .engine.engine import Engine, EngineConfig
    from .events import (
        FinalTurnComplete,
        ImageOutputComplete,
        Quitting,
        StateChange,
    )
    from .params import Params

    if events is None:
        events = queue_mod.Queue()
    ticker = None
    try:
        # EVERYTHING (validation and seeding included) sits inside the
        # CLOSED guard: an error anywhere must not leave a consumer
        # blocked on the queue (controller.py gives the same guarantee)
        if engine is None:
            engine = Engine(EngineConfig(final_world=False))
        else:
            _check_byte_free_engine(engine)
        params = Params(turns=turns, image_width=size, image_height=size)
        state = _seed_state(size, cells, in_path, word_axis, row_block)
        plane = BitPlane(rule, word_axis)
        out_file = pathlib.Path(out_dir) / f"{params.output_filename}.pgm"

        class _BigTicker(_Ticker):
            def _snapshot_to_pgm(self):
                from .engine.engine import Snapshot

                # state and turn under ONE lock: a retrieve + final_state
                # pair could straddle a chunk commit and disagree by up to
                # max_chunk turns between the reported turn and the PGM
                current, turn = self.broker.engine.state_snapshot()
                if current is not None:
                    stream_packed_to_pgm(
                        out_file, current, word_axis, row_block
                    )
                count = alive_count_packed(current) if current is not None else 0
                return Snapshot(None, turn, count)

        ticker = _BigTicker(
            params, events, keypresses, _PackedBroker(engine), out_dir,
            tick_seconds,
        )
        ticker.start()
        try:
            result = engine.run(
                params, None, plane=plane, initial_state=state
            )
        finally:
            ticker.stop()
        final = engine.final_state()
        events.put(
            FinalTurnComplete(
                result.turns_completed, _LazyAliveCells(plane, final)
            )
        )
        if final is not None:
            stream_packed_to_pgm(out_file, final, word_axis, row_block)
        events.put(
            ImageOutputComplete(result.turns_completed, params.output_filename)
        )
        events.put(StateChange(result.turns_completed, Quitting))
        return result
    finally:
        events.put(CLOSED)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="sparse big-board run (BASELINE config 5 shape)"
    )
    parser.add_argument("-size", type=int, default=16384)
    parser.add_argument("-turns", type=int, default=1000)
    parser.add_argument("-out", default="out/bigboard.pgm")
    parser.add_argument("-in", dest="in_path", default=None,
                        help="seed from a PGM instead of the R-pentomino")
    parser.add_argument("-row-block", type=int, default=1024)
    parser.add_argument(
        "-rule", default=None, metavar="B.../S...",
        help="life-like rulestring (default Conway B3/S23)",
    )
    parser.add_argument(
        "-session", action="store_true", default=False,
        help="run through big_session: 2 s alive-count ticker, s/q/k/p "
             "keys on stdin (tty), events printed like the headless drain",
    )
    args = parser.parse_args(argv)
    rule = LifeRule.from_rulestring(args.rule) if args.rule else CONWAY
    cells = None if args.in_path else r_pentomino(args.size)
    if args.session:
        import pathlib
        import queue as queue_mod
        import threading

        from .__main__ import drain_events, start_tty_keys

        events: "queue_mod.Queue" = queue_mod.Queue()
        keypresses: "queue_mod.Queue" = queue_mod.Queue()
        restore_tty = start_tty_keys(keypresses)
        consumer = threading.Thread(target=drain_events, args=(events,))
        consumer.start()
        try:
            # sessions name the file by the reference convention inside
            # -out's directory; honor the exact -out basename with a
            # final rename so both modes mean the same thing by -out
            out_path = pathlib.Path(args.out)
            result = big_session(
                args.size, args.turns, cells=cells, in_path=args.in_path,
                rule=rule, row_block=args.row_block, events=events,
                keypresses=keypresses, out_dir=out_path.parent,
            )
            conventional = (
                out_path.parent
                / f"{args.size}x{args.size}x{args.turns}.pgm"
            )
            if conventional.exists() and conventional != out_path:
                conventional.replace(out_path)
        finally:
            consumer.join()
            restore_tty()
        # device-side popcount, not len(list-of-Cells): the count must not
        # be the one thing that materialises O(alive) host objects
        print(f"alive {result.alive_count}")
        return 0
    alive = run_big_board(
        args.size, args.turns, args.out,
        cells=cells, in_path=args.in_path, rule=rule,
        row_block=args.row_block,
    )
    print(f"alive {alive}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
