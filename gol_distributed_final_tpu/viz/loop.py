"""The visualiser event loop — ``sdl.Run`` (reference: sdl/loop.go:9-54).

Consumes the controller's event stream and drives a Window:
``CellFlipped`` XORs a pixel, ``TurnComplete`` renders a frame,
``FinalTurnComplete`` (or stream close) destroys the window. Any event
with a non-empty string form is printed as ``Completed Turns <n> <event>``
(sdl/loop.go:44-47). Window keypresses p/s/q/k are forwarded to the
controller's keypress queue (sdl/loop.go:16-28).
"""

from __future__ import annotations

import queue

from ..events import CellFlipped, FinalTurnComplete, TurnComplete
from .window import make_window


def run(params, events: "queue.Queue", keypresses: "queue.Queue | None" = None, *,
        window=None, on_turn=None):
    """Blocking visualiser loop; returns when the stream ends.

    ``window`` may inject a backend (tests use the headless Window);
    ``on_turn(window, completed_turns)`` is called after each rendered frame.
    """
    from ..engine.controller import CLOSED

    if window is None:
        window = make_window(params.image_width, params.image_height)
    alive = True
    try:
        while True:
            if keypresses is not None and alive:
                key = window.poll_key()
                if key is not None:
                    keypresses.put(key)
            try:
                ev = events.get(timeout=0.02)
            except queue.Empty:
                continue
            if ev is CLOSED:
                return
            if isinstance(ev, CellFlipped) and alive:
                window.flip_pixel(ev.cell.x, ev.cell.y)
            elif isinstance(ev, TurnComplete) and alive:
                window.render_frame()
                if on_turn is not None:
                    on_turn(window, ev.completed_turns)
            elif isinstance(ev, FinalTurnComplete):
                # window goes down now (sdl/loop.go:40); keep draining the
                # stream but never touch the destroyed window again
                window.destroy()
                alive = False
            text = str(ev)
            if text:
                print(f"Completed Turns {ev.get_completed_turns()} {text}")
    finally:
        window.destroy()
