from .window import Window, make_window
from .loop import run

__all__ = ["Window", "make_window", "run"]
