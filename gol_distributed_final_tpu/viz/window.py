"""The visualiser window — the reference SDL window's API
(reference: sdl/window.go:10-104) over two backends:

* ``Window``: headless, buffer-only — always available; what the tests and
  -noVis runs use. Keeps the ARGB8888 pixel buffer and the exact
  FlipPixel/SetPixel/CountPixels/ClearPixels semantics (including the
  bounds panic, sdl/window.go FlipPixel).
* ``SdlWindow``: delegates to the native SDL2 binding
  (native/window.cc -> libgolwindow.so) when it has been built on a host
  with libSDL2; ``make_window`` falls back to headless otherwise.
"""

from __future__ import annotations

import ctypes
import pathlib

import numpy as np

_NATIVE_DIR = pathlib.Path(__file__).resolve().parent.parent / "native"
_WINDOW_LIB = _NATIVE_DIR / "libgolwindow.so"

_WHITE = 0x00FFFFFF


class Window:
    """Headless ARGB8888 pixel buffer with the reference window API."""

    def __init__(self, width: int, height: int, title: str = "GoL"):
        self.width = width
        self.height = height
        self.title = title
        self._pixels = np.zeros((height, width), np.uint32)
        self.frames_rendered = 0

    def _check(self, x: int, y: int):
        if not (0 <= x < self.width and 0 <= y < self.height):
            # the reference panics on out-of-bounds flips (sdl/window.go)
            raise IndexError(f"pixel ({x}, {y}) outside {self.width}x{self.height}")

    def flip_pixel(self, x: int, y: int):
        self._check(x, y)
        self._pixels[y, x] ^= _WHITE

    def set_pixel(self, x: int, y: int, argb: int = _WHITE):
        self._check(x, y)
        self._pixels[y, x] = argb

    def count_pixels(self) -> int:
        return int(np.count_nonzero(self._pixels & _WHITE))

    def clear_pixels(self):
        self._pixels[:] = 0

    def render_frame(self):
        self.frames_rendered += 1

    def poll_key(self) -> str | None:
        return None

    def destroy(self):
        pass


class SdlWindow(Window):
    """Native SDL2-backed window (requires libgolwindow.so).

    ``lib_path`` overrides the library location — used by the ABI test to
    load the stub-backed build (libgolwindow_stub.so: the same golwin_*
    exports over the vendored no-op SDL, native/sdl2_stub/)."""

    def __init__(
        self, width: int, height: int, title: str = "GoL", lib_path=None
    ):
        super().__init__(width, height, title)
        lib = ctypes.CDLL(str(lib_path or _WINDOW_LIB))
        # declare EVERY signature: on LP64 an undeclared handle argument
        # would be truncated to a 32-bit c_int (ADVICE/VERDICT round 1)
        lib.golwin_create.restype = ctypes.c_void_p
        lib.golwin_create.argtypes = [ctypes.c_int, ctypes.c_int, ctypes.c_char_p]
        lib.golwin_flip_pixel.restype = None
        lib.golwin_flip_pixel.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_int]
        lib.golwin_set_pixel.restype = None
        lib.golwin_set_pixel.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int,
            ctypes.c_int,
            ctypes.c_uint32,
        ]
        lib.golwin_count_pixels.restype = ctypes.c_long
        lib.golwin_count_pixels.argtypes = [ctypes.c_void_p]
        lib.golwin_clear_pixels.restype = None
        lib.golwin_clear_pixels.argtypes = [ctypes.c_void_p]
        lib.golwin_render_frame.restype = None
        lib.golwin_render_frame.argtypes = [ctypes.c_void_p]
        lib.golwin_poll_key.restype = ctypes.c_int
        lib.golwin_poll_key.argtypes = [ctypes.c_void_p]
        lib.golwin_destroy.restype = None
        lib.golwin_destroy.argtypes = [ctypes.c_void_p]
        self._lib = lib
        self._handle = ctypes.c_void_p(
            lib.golwin_create(width, height, title.encode())
        )
        if not self._handle:
            raise RuntimeError("SDL window creation failed")

    def flip_pixel(self, x, y):
        super().flip_pixel(x, y)
        self._lib.golwin_flip_pixel(self._handle, x, y)

    def set_pixel(self, x, y, argb=_WHITE):
        super().set_pixel(x, y, argb)
        self._lib.golwin_set_pixel(self._handle, x, y, ctypes.c_uint32(argb))

    def clear_pixels(self):
        super().clear_pixels()
        self._lib.golwin_clear_pixels(self._handle)

    def render_frame(self):
        super().render_frame()
        self._lib.golwin_render_frame(self._handle)

    def poll_key(self) -> str | None:
        code = self._lib.golwin_poll_key(self._handle)
        if code == -1:
            return "q"  # window close quits the controller
        if code <= 0:
            return None
        return chr(code)

    def destroy(self):
        if self._handle:
            self._lib.golwin_destroy(self._handle)
            self._handle = None


def make_window(width: int, height: int, title: str = "GoL") -> Window:
    """SDL if the native backend was built on this host, else headless."""
    if _WINDOW_LIB.exists():
        try:
            return SdlWindow(width, height, title)
        except (OSError, RuntimeError):
            pass
    return Window(width, height, title)
