"""Live window view of an engine-driven big-board session.

The reference's SDL window renders the whole board every turn from
``CellFlipped`` events (sdl/loop.go:30-51) — impossible at config-5
scale, where the full raster is GiB and flip events would number
billions. Instead, this view periodically takes the engine's atomic
``(state, turn)`` snapshot and decodes ONLY the watched window
(bigboard.decode_window — KiB, not GiB, cross the device boundary),
refreshing the window pixels wholesale. Works with either window
backend (headless or native SDL) via the same SetPixel/RenderFrame
surface the reference defines (sdl/window.go:10-104).
"""

from __future__ import annotations

import threading

import numpy as np

_WHITE = 0x00FFFFFF


class BigView:
    """Render a fixed window of a (possibly running) engine's packed board.

    ``watch`` spawns a refresh thread; ``stop`` joins it. ``refresh`` is
    also callable directly (no thread) — one frame per call."""

    def __init__(
        self,
        engine,
        y0: int,
        x0: int,
        height: int,
        width: int,
        *,
        word_axis: int = 0,
        window=None,
        interval: float = 0.5,
    ):
        from .window import make_window

        self.engine = engine
        self.y0, self.x0 = y0, x0
        self.window = window or make_window(width, height, "GoL bigview")
        self.word_axis = word_axis
        self.interval = interval
        self.last_turn: int | None = None
        self._shown: np.ndarray | None = None  # what the window displays
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def refresh(self) -> bool:
        """Draw one frame from the current engine state. Returns False if
        the engine holds no state yet."""
        from ..bigboard import decode_window

        state, turn = self.engine.state_snapshot()
        if state is None:
            return False
        win = (
            decode_window(
                state,
                self.y0,
                self.x0,
                self.window.height,
                self.window.width,
                self.word_axis,
            )
            != 0
        )
        # draw through the public SetPixel protocol (the native SDL
        # backend renders via its own texture, so direct buffer writes
        # would bypass it), but only for CHANGED pixels — between
        # refreshes of a settling board that is a small diff
        if self._shown is None:
            self.window.clear_pixels()
            self._shown = np.zeros_like(win)
        for y, x in zip(*np.nonzero(win != self._shown)):
            self.window.set_pixel(int(x), int(y), _WHITE if win[y, x] else 0)
        self._shown = win
        self.window.render_frame()
        self.last_turn = turn
        return True

    def watch(self):
        if self._thread is not None:
            # a second watch() would orphan the first refresh thread and
            # silently drop any pending _error (ADVICE.md round 3)
            raise RuntimeError("BigView is already watching; stop() first")
        self._stop.clear()  # a stop() leaves the event set; re-arm for restart

        def loop():
            try:
                while not self._stop.is_set():
                    if self.refresh():
                        self.live_frames += 1
                    self._stop.wait(self.interval)
            except BaseException as exc:  # surfaced by stop()
                self._error = exc

        self.live_frames = 0
        self._error: BaseException | None = None
        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()
        return self

    def stop(self):
        """Join the watch thread; re-raises any exception it died on (a
        silently dead daemon would otherwise leave a frozen window and a
        green test suite)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._error is not None:
            raise self._error
