"""Device-mesh construction.

The reference's "cluster topology" is a hardcoded list of 8 worker addresses
(broker/broker.go:288-300). Here topology is a ``jax.sharding.Mesh``: rows
(and, for 2-D, columns) of the board are sharded over mesh axes, and all
data-plane communication is XLA collectives over ICI — no address list, no
dial loop, no per-turn TCP.
"""

from __future__ import annotations


import jax
from jax.sharding import Mesh

ROWS, COLS = "rows", "cols"


def best_mesh_shape(n_devices: int, height: int, width: int) -> tuple[int, int]:
    """Pick a (rows, cols) mesh factorisation of ``n_devices``.

    Prefers the most square factorisation that divides the board evenly —
    a 2-D decomposition halves the per-device halo perimeter vs 1-D at the
    same device count (SURVEY.md §2 'TPU-native equivalent').
    Falls back toward 1-D if the board doesn't divide.
    """
    best = (n_devices, 1)
    best_score = None
    for r in range(1, n_devices + 1):
        if n_devices % r:
            continue
        c = n_devices // r
        if height % r or width % c:
            continue
        # minimise halo perimeter per device: w/c + h/r (two row edges of
        # length w/c, two col edges of length h/r)
        score = width // c + height // r
        if best_score is None or score < best_score:
            best, best_score = (r, c), score
    if best_score is None:
        raise ValueError(
            f"no (rows, cols) factorisation of {n_devices} devices divides "
            f"a {height}x{width} board evenly"
        )
    return best


def make_mesh(
    shape: tuple[int, int] | None = None,
    devices=None,
    *,
    height: int | None = None,
    width: int | None = None,
) -> Mesh:
    """Build a ('rows', 'cols') mesh over ``devices`` (default: all).

    If ``shape`` is omitted, chooses via ``best_mesh_shape`` (requires
    height/width).
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if shape is None:
        if height is None or width is None:
            raise ValueError("either shape or (height, width) is required")
        shape = best_mesh_shape(n, height, width)
    r, c = shape
    if r * c != n:
        raise ValueError(f"mesh shape {shape} does not use all {n} devices")
    import numpy as np

    return Mesh(np.asarray(devices).reshape(r, c), (ROWS, COLS))


def shard_map_compat(f, *, mesh: Mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` across jax versions — the ONE spelling every mesh
    plane uses (halo.py, bit_halo.py).

    Newer jax exposes ``jax.shard_map`` with the ``check_vma``
    varying-mesh-axes checker; 0.4.x has only
    ``jax.experimental.shard_map.shard_map`` whose ``check_rep`` plays the
    same role (the replication checker the pallas local route must relax —
    ADVICE.md round 3). Without this shim every mesh dispatch dies with
    ``AttributeError: module 'jax' has no attribute 'shard_map'`` on 0.4.x
    — 52 of the seed's 54 CPU-suite failures."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma,
    )
