from .mesh import best_mesh_shape, make_mesh
from .halo import board_sharding, make_engine_step, sharded_step_fn, sharded_step_n_fn
from .bit_halo import (
    ShardedBitPlane,
    choose_bit_layout,
    make_bit_plane,
    sharded_bit_step_n_fn,
)

__all__ = [
    "make_mesh",
    "best_mesh_shape",
    "board_sharding",
    "sharded_step_fn",
    "sharded_step_n_fn",
    "make_engine_step",
    "ShardedBitPlane",
    "choose_bit_layout",
    "make_bit_plane",
    "sharded_bit_step_n_fn",
]
