"""Halo-exchange stencil steps over a device mesh — the data plane.

The reference ships the FULL board to every worker every turn and gathers
strips back over TCP: O(Threads x H x W) bytes per turn through the broker
(broker/broker.go:135-224, the central scalability limit README.md:204 points
at). Here each device owns one block of the board permanently; per turn it
exchanges only its 1-cell-deep halo with mesh neighbours via
``lax.ppermute`` over ICI — O(perimeter) bytes, no host involvement.

Corner cells are handled by the classic two-phase exchange: rows first
(blocks grow to (h+2, w)), then columns of the *extended* block (to
(h+2, w+2)) — the column messages carry the row halos' end cells, so corner
neighbours arrive without dedicated diagonal sends.

All functions close over a Mesh with axes ('rows', 'cols'); either axis may
have size 1 (a 1-D decomposition is just a degenerate 2-D one).
"""

from __future__ import annotations

import functools
import time
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models import CONWAY, LifeRule
from ..obs import device as _device
from ..obs import instruments as _ins
from ..obs import metrics as _metrics
from ..obs import tracing as _tracing
from ..ops.stencil import apply_rule, counts_from_extended
from .mesh import COLS, ROWS, shard_map_compat


def exchanges_per_dispatch(n: int, depth: int) -> int:
    """Halo exchanges (rows+cols ppermute pairs) a ``wide_loop`` of ``n``
    turns at ``halo_depth=depth`` issues — one per wide iteration plus one
    per single-turn remainder. The obs counter's arithmetic, kept beside
    ``wide_loop`` so the two cannot drift."""
    if depth > 1:
        return n // depth + n % depth
    return n


def board_sharding(mesh: Mesh) -> NamedSharding:
    """The canonical board sharding: rows over 'rows', cols over 'cols'."""
    return NamedSharding(mesh, P(ROWS, COLS))


def _ring_perm(n: int, direction: int) -> list[tuple[int, int]]:
    """Ring permutation: each device i sends to (i + direction) mod n."""
    return [(i, (i + direction) % n) for i in range(n)]


def _exchange(block, axis_name: str, n: int, dim: int, pad: int = 0, k: int = 1):
    """Prepend/append wrap-around halo slices of thickness ``k`` along
    ``dim``, exchanged with ring neighbours on ``axis_name``.

    With a single device on the axis the halo is local wrap — the same
    concat, no communication.

    ``k > 1`` is the WIDE-halo form (temporal blocking): a k-deep halo
    lets the caller run k turns locally before the next exchange, cutting
    the number of collective latencies per turn by k at identical traffic
    volume (k slices every k turns) — the lever that matters when the
    mesh axis crosses DCN, where per-collective latency, not bandwidth,
    bounds scaling. It is ALSO the ext-amortisation lever where latency
    is free (single host, ICI): the extended block is materialised once
    per k turns instead of every turn — on chip, depth 8 at 512^2
    measured 2x over depth 1 on the pallas route (r5).

    ``pad`` adds that many ZERO slices outside each halo, fused into the
    same concatenate: the pallas local step (parallel/bit_halo.py) needs a
    tile-aligned extended block whose outer ring is never read, and a
    separate jnp.pad would cost a full extra array materialisation
    (~50 us/turn measured at 16384^2).
    """
    if k < 1 or k > block.shape[dim]:
        raise ValueError(
            f"halo thickness {k} outside [1, local dim {block.shape[dim]}]"
        )
    if dim == 0:
        first, last = block[:k], block[-k:]
    else:
        first, last = block[:, :k], block[:, -k:]
    if n == 1:
        before, after = last, first
    else:
        # my 'before' halo is the previous device's last slice: everyone
        # sends their last slice one step forward along the ring
        before = lax.ppermute(last, axis_name, _ring_perm(n, 1))
        after = lax.ppermute(first, axis_name, _ring_perm(n, -1))
    parts = [before, block, after]
    if pad:
        zshape = list(block.shape)
        zshape[dim] = pad
        zeros = jnp.zeros(zshape, block.dtype)
        parts = [zeros, *parts, zeros]
    return jnp.concatenate(parts, axis=dim)


def _local_step(block, *, rule: LifeRule, mesh_shape: tuple[int, int]):
    """One turn on a local block, halos included. Runs inside shard_map."""
    nrows, ncols = mesh_shape
    ext = _exchange(block, ROWS, nrows, dim=0)          # (h+2, w)
    ext = _exchange(ext, COLS, ncols, dim=1)            # (h+2, w+2), corners ok
    h, w = block.shape
    counts = counts_from_extended(ext, h, w)
    return apply_rule(
        block, counts, birth_mask=rule.birth_mask, survive_mask=rule.survive_mask
    )


def wide_loop(block, n: int, depth: int, step, wide):
    """``n`` turns as ``n // depth`` wide iterations (``depth`` turns per
    halo exchange) plus a STATIC single-turn remainder — the one chunking
    arithmetic both data planes share, so the byte and packed evolutions
    cannot drift."""
    if depth > 1:
        block = lax.fori_loop(0, n // depth, lambda _, b: wide(b), block)
        for _ in range(n % depth):
            block = step(block)
        return block
    return lax.fori_loop(0, n, lambda _, b: step(b), block)


def halo_depth_fits(depth: int, block_shape) -> bool:
    """A halo can only come from the adjacent device: depth is bounded by
    the local block's smaller dimension. The ONE copy of the bound —
    step-time checks (``check_halo_depth``) and admission guards (the
    broker's plane routing, ``make_bit_plane``) all call it, so they
    cannot drift apart."""
    return depth <= min(block_shape)


def check_halo_depth(depth: int, block_shape) -> None:
    """Raise-form of ``halo_depth_fits``, shared by both planes so the
    error names the knob the user actually set."""
    if not halo_depth_fits(depth, block_shape):
        raise ValueError(
            f"halo_depth {depth} exceeds the local block "
            f"{tuple(block_shape)}: a halo can only come from the "
            "adjacent device"
        )


def _local_step_wide(block, *, rule: LifeRule, mesh_shape, depth: int):
    """``depth`` turns per halo exchange (temporal blocking): exchange a
    depth-deep halo once, then step the extended block ``depth`` times
    locally — each step invalidates one more outer ring, and exactly the
    ``depth`` garbage rings are sliced away at the end. Collective count
    per turn drops ``depth``-fold at identical traffic volume; the price
    is redundant compute on the shrinking halo rings (O(depth * perimeter)
    cells per exchange)."""
    nrows, ncols = mesh_shape
    ext = _exchange(block, ROWS, nrows, dim=0, k=depth)  # (h+2d, w)
    ext = _exchange(ext, COLS, ncols, dim=1, k=depth)  # (h+2d, w+2d)
    for _ in range(depth):  # static: unrolled at trace time
        # shrinking form: each step consumes one halo ring — the ext IS
        # the (interior+2)-window counts_from_extended expects, so no
        # self-wrap concat and no final slice, and later steps run on
        # strictly smaller arrays; after `depth` steps ext is back to the
        # original block shape
        h, w = ext.shape[0] - 2, ext.shape[1] - 2
        counts = counts_from_extended(ext, h, w)
        ext = apply_rule(
            ext[1:-1, 1:-1], counts,
            birth_mask=rule.birth_mask, survive_mask=rule.survive_mask,
        )
    return ext


def sharded_step_fn(mesh: Mesh, rule: LifeRule = CONWAY) -> Callable:
    """A jitted ``board -> board`` over a globally-sharded ``uint8[H, W]``.

    The input is (re)placed to the canonical sharding by jit; the output
    keeps it, so a turn loop never reshards.
    """
    mesh_shape = (mesh.shape[ROWS], mesh.shape[COLS])
    local = functools.partial(_local_step, rule=rule, mesh_shape=mesh_shape)
    sharded = shard_map_compat(
        local, mesh=mesh, in_specs=P(ROWS, COLS), out_specs=P(ROWS, COLS)
    )
    sharding = board_sharding(mesh)
    return jax.jit(sharded, in_shardings=sharding, out_shardings=sharding)


def sharded_step_n_fn(
    mesh: Mesh, rule: LifeRule = CONWAY, *, halo_depth: int = 1
) -> Callable:
    """A jitted ``(board, n) -> board`` running ``n`` turns in ONE dispatch.

    The ``lax.fori_loop`` lives *inside* shard_map, so the whole multi-turn
    evolution — halo ppermutes included — compiles to a single XLA program
    per device: the per-turn synchronisation the reference implements as a
    host-side gather barrier (broker/broker.go:154-156) is just the
    dataflow dependency between collective and stencil.

    ``halo_depth=k`` exchanges k-deep halos and runs k turns per exchange
    (see ``_local_step_wide``) — the DCN-latency lever for multi-host
    meshes. Turn counts not divisible by k finish with single-turn steps.
    """
    if halo_depth < 1:
        raise ValueError(f"halo_depth must be >= 1, got {halo_depth}")
    mesh_shape = (mesh.shape[ROWS], mesh.shape[COLS])
    local = functools.partial(_local_step, rule=rule, mesh_shape=mesh_shape)
    wide = functools.partial(
        _local_step_wide, rule=rule, mesh_shape=mesh_shape, depth=halo_depth
    )
    sharding = board_sharding(mesh)

    @functools.lru_cache(maxsize=None)
    def _compiled(n: int):
        # body runs only on a cache MISS: hits = requests - misses (obs/)
        _ins.COMPILE_CACHE_MISSES_TOTAL.labels("halo.byte").inc()

        def local_n(block):
            return wide_loop(block, n, halo_depth, local, wide)

        sharded = shard_map_compat(
            local_n, mesh=mesh, in_specs=P(ROWS, COLS), out_specs=P(ROWS, COLS)
        )
        jitted = jax.jit(sharded, in_shardings=sharding, out_shardings=sharding)
        # first call per shape goes through a timed explicit lower/compile
        # (+ cost analysis) so compile wall and kernel cost are attributed
        # to this site instead of hiding inside the first dispatch (obs/)
        return _device.instrument_jit("halo.byte", jitted)

    def step_n(board, n):
        check_halo_depth(
            halo_depth,
            (board.shape[0] // mesh_shape[0], board.shape[1] // mesh_shape[1]),
        )
        if not (_metrics.enabled() or _tracing.enabled()
                or _tracing.device_trace_active()):
            return _compiled(int(n))(board)
        # host-side dispatch wall (compile on first call, enqueue after)
        # + the exchange count this dispatch puts on the wire; the
        # device-side exchange time itself lives in the profiler trace,
        # where the TraceAnnotation below carries the same span name
        span = _tracing.start_span(
            _tracing.SPAN_HALO_DISPATCH, plane="byte", turns=int(n)
        )
        if _metrics.enabled():
            _ins.COMPILE_CACHE_REQUESTS_TOTAL.labels("halo.byte").inc()
            _ins.HALO_EXCHANGES_TOTAL.labels("byte").inc(
                exchanges_per_dispatch(int(n), halo_depth)
            )
        t0 = time.monotonic()
        with _tracing.annotate("halo.dispatch"):
            out = _compiled(int(n))(board)
        if _metrics.enabled():
            _ins.HALO_DISPATCH_SECONDS.labels("byte").observe(
                time.monotonic() - t0
            )
        _tracing.end_span(span)
        return out

    return step_n


def make_engine_step(
    mesh: Mesh, rule: LifeRule = CONWAY, *, halo_depth: int = 1
) -> Callable:
    """An ``EngineConfig.step_n_fn``-compatible callable: the engine's turn
    loop runs the whole mesh as one SPMD program. ``halo_depth`` rides
    through to the wide-halo form (see ``sharded_step_n_fn``)."""
    return sharded_step_n_fn(mesh, rule, halo_depth=halo_depth)
