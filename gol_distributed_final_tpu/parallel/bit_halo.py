"""The fast data plane x the mesh: bit-packed halo-exchange steps.

Round 1's mesh path ran the byte-per-cell roll stencil inside shard_map
(parallel/halo.py) — ~12x slower per device than the bitboard kernels the
single-chip bench used. Here ``bit_step`` (ops/bitpack.py: 32 cells/int32
word, carry-save adder trees) runs INSIDE shard_map, so per-device mesh
throughput matches the single-chip bitboard path.

Halo mechanics: the packed array is 2-D (one spatial axis packed into bits,
the other left as elements), sharded P('rows', 'cols'). ``bit_step``'s
output word (i, j) depends only on input words (i±1, j±1), regardless of
which axis is packed — bit carries cross word boundaries through the
ADJACENT ELEMENT along the packed axis, and the 3x3 element neighbourhood
covers the rest. So the classic two-phase thickness-1 halo exchange of the
byte plane (rows first, then columns of the extended block — corners ride
the second phase) works verbatim on packed words: per turn each device
ppermutes one word-row and one word-column — O(perimeter/32) traffic on the
packed axis — then computes ``bit_step`` on the extended block and keeps
the interior. ``bit_step``'s cyclic rotates only contaminate the extended
block's outer ring, which is exactly what gets sliced away; with a
single-device axis the "halo" is the local wrap slice and the same slicing
yields torus semantics.

Reference anchor: the one kernel running on every worker
(worker/worker.go:15-70), re-founded so the strip a worker owns never
leaves its device (vs broker/broker.go:135-224's full-board reships).
"""

from __future__ import annotations

import functools
import time
from typing import Callable, Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models import CONWAY, LifeRule
from ..obs import device as _device
from ..obs import instruments as _ins
from ..obs import metrics as _metrics
from ..obs import tracing as _tracing
from ..ops.bitpack import WORD, bit_step, pack_device, unpack_device
from .halo import (
    _exchange,
    check_halo_depth,
    exchanges_per_dispatch,
    halo_depth_fits,
    wide_loop,
)
from .mesh import COLS, ROWS, shard_map_compat


def choose_bit_layout(
    board_shape: tuple[int, int], mesh_shape: tuple[int, int]
) -> Optional[int]:
    """Pick a ``word_axis`` whose packed array divides over the mesh.

    Prefers packing rows (word_axis=0, packed [H/32, W]) — the lane
    dimension stays W wide, ~6x faster on TPU — falling back to packing
    columns, then None (caller uses the byte plane)."""
    h, w = board_shape
    nrows, ncols = mesh_shape
    if h % (WORD * nrows) == 0 and w % ncols == 0:
        return 0
    if h % nrows == 0 and w % (WORD * ncols) == 0:
        return 1
    return None


def _local_bit_step(block, *, rule: LifeRule, mesh_shape, word_axis: int):
    nrows, ncols = mesh_shape
    ext = _exchange(block, ROWS, nrows, dim=0)  # (h+2, w)
    ext = _exchange(ext, COLS, ncols, dim=1)  # (h+2, w+2), corners ride phase 2
    out = bit_step(
        ext,
        word_axis,
        birth_mask=rule.birth_mask,
        survive_mask=rule.survive_mask,
    )
    return out[1:-1, 1:-1]


def _local_bit_step_wide(
    block, *, rule: LifeRule, mesh_shape, word_axis: int, depth: int
):
    """``depth`` turns per halo exchange on the packed block (temporal
    blocking — see halo._local_step_wide for the ring-invalidation
    argument; here rings are WORDS on the packed axis, elements on the
    other). On the packed axis a k-word halo every k turns ships the same
    volume as one word every turn — the win is k-fold fewer collective
    LATENCIES, the bound when a mesh axis crosses DCN. ``bit_step``'s own
    cyclic rotates only contaminate the outermost ring each step, which
    is exactly the ring invalidated anyway."""
    nrows, ncols = mesh_shape
    ext = _exchange(block, ROWS, nrows, dim=0, k=depth)
    ext = _exchange(ext, COLS, ncols, dim=1, k=depth)
    for _ in range(depth):  # static: unrolled at trace time
        # slice the just-invalidated outer ring off immediately (instead
        # of depth rings at the end): later steps run on strictly smaller
        # arrays, and the final ext is already the block shape
        ext = bit_step(
            ext,
            word_axis,
            birth_mask=rule.birth_mask,
            survive_mask=rule.survive_mask,
        )[1:-1, 1:-1]
    return ext


def _local_bit_step_pallas(
    block, *, rule: LifeRule, mesh_shape, interpret, depth: int = 1
):
    """``depth`` turns on a local block through the grid-tiled pallas
    kernel (word_axis=0 only).

    Beyond the whole-board VMEM gate, the XLA ``bit_step`` spills its
    ~10 bit-plane temporaries to HBM — ~5x slower per device at 16384^2
    (the single-chip finding, ops/pallas_tiled.py). Inside shard_map the
    kernel wins at EVERY aligned size, not just past the gate (r5 chip
    sweep: 1.6-2.8x, see ``_auto_use_pallas``): the XLA local step
    materialises the haloed ext and its temporaries through HBM each
    turn even when a raw single-chip ``bit_step`` of the same size would
    stay fused.

    The kernel needs a sublane/lane-ALIGNED extended block, but only the
    innermost ``depth`` halo words ever feed the kept interior (turn t
    reads words +-t away), so the exchange ships the same thickness-k
    halos as the XLA wide path and zero-pads locally — fused into the
    halo concats — out to the (h+16, w+256) tile-aligned shape: alignment
    costs no extra ICI traffic and no extra materialisation.

    The WIDE form (``depth > 1``, temporal blocking — VERDICT r4 item 1)
    needs no shrinking ext and no new kernel: the ext shape is the SAME
    fixed aligned shape for every depth (pad = tile − depth halo words),
    and the kernel simply runs ``depth`` single-turn launches on it
    (``_tiled_compiled(depth, …)``'s existing fori_loop). Validity is a
    ring-creep argument: the zero padding and the kernel's own torus wrap
    of the ext are wrong data at word-distance ≥ depth from the body, and
    each turn advances the contamination exactly one word-ring inward —
    after ``depth`` turns it has consumed the ``depth``-word halo and
    stops AT the body boundary. Hence the hard bound
    ``depth <= _SUBLANE`` (8): at depth 8 the rows pad is zero and the
    ring-creep exactly meets the interior slice.

    Cost account (r5 chip measurements): at depth 8 the ext build
    amortises 8-fold and the residual overhead vs the raw kernel is just
    the PAD-AREA compute of the fixed aligned ext —
    (h+2·8)/h × (w+2·128)/w — which is 1.20 at a (128, 4096) local block
    (measured 1.21) and shrinks with block size to ~1.05 at the
    (512, 16384) blocks of a real pod, where this path is effectively
    free."""
    from ..ops.pallas_tiled import _LANE, _SUBLANE, _tiled_compiled

    nrows, ncols = mesh_shape
    # pad = tile - (depth halo words): body lands at offset (_SUBLANE, _LANE)
    ext = _exchange(block, ROWS, nrows, dim=0, k=depth, pad=_SUBLANE - depth)
    ext = _exchange(ext, COLS, ncols, dim=1, k=depth, pad=_LANE - depth)
    out = _tiled_compiled(
        depth, tuple(ext.shape), interpret, rule.birth_mask, rule.survive_mask
    )(ext)
    return out[_SUBLANE:-_SUBLANE, _LANE:-_LANE]


def _auto_use_pallas(
    halo_depth: int, block_shape, word_axis: int, interpret: bool
) -> bool:
    """The ``pallas_local=None`` routing decision: the tiled kernel runs
    whenever the local block is tile-ALIGNED (word_axis=0) and the halo
    depth fits the aligned-ext form's sublane bound (8) — deeper halos
    silently stay on the XLA local step, which has no depth ceiling.

    Until r5 this also required the block to be past the VMEM working-set
    gate, on the theory that XLA handles VMEM-resident blocks fine. A
    real-chip sweep (r5, (1,1) mesh) measured the pallas route faster at
    EVERY size — 2.8x at 256^2, 2.1x at 512^2, 1.8x at 1024^2, 1.6x at
    2048^2 — because inside shard_map the XLA local step materialises the
    haloed ext and its bit-plane temporaries through HBM every turn,
    while the kernel keeps them in VMEM. So alignment is the only gate."""
    from ..ops.pallas_tiled import _SUBLANE

    return (
        halo_depth <= _SUBLANE
        and word_axis == 0
        and _pallas_local_aligned(block_shape)
        and not interpret
    )


def _pallas_local_aligned(block_shape) -> bool:
    """The tile-alignment half of the gate: the local block and its
    (h + 2*_SUBLANE, w + 2*_LANE) ext must satisfy the kernel's
    sublane/lane contract (constants shared with ops/pallas_tiled)."""
    from ..ops.pallas_tiled import _LANE, _SUBLANE, can_tile

    h, w = block_shape
    return (
        h % _SUBLANE == 0
        and w % _LANE == 0
        and can_tile((h + 2 * _SUBLANE, w + 2 * _LANE))
    )


def packed_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P(ROWS, COLS))


def sharded_bit_step_n_fn(
    mesh: Mesh,
    rule: LifeRule = CONWAY,
    word_axis: int = 0,
    *,
    pallas_local: bool | None = None,
    interpret: bool | None = None,
    halo_depth: int = 1,
) -> Callable:
    """A jitted ``(packed, n) -> packed`` over a P('rows','cols')-sharded
    int32 bitboard: n turns in ONE dispatch, the fori_loop (halo ppermutes
    included) inside shard_map.

    ``pallas_local`` routes each device's local compute through the
    grid-tiled pallas kernel (None = auto: on real TPU whenever the local
    block is tile-aligned — measured faster at every size, see
    ``_auto_use_pallas``). ``interpret`` forces pallas interpret mode —
    the CPU-mesh test hook.

    ``halo_depth=k`` exchanges k-deep halos and runs k turns locally per
    exchange (``_local_bit_step_wide`` / the wide form of
    ``_local_bit_step_pallas``) — k-fold fewer collective latencies per
    turn, the DCN-scaling lever. The two knobs COMPOSE: on the pallas
    route the k-word halo rides the same fixed tile-aligned ext (pad
    shrinks as the halo grows) and the kernel runs k launches on it, so
    the config-5 topology gets the ~5x local kernel AND the k-fold
    latency cut together. The pallas route bounds ``halo_depth`` at the
    sublane tile (8) — past that the zero-ring contamination would creep
    into the body — so ``pallas_local=True`` with ``halo_depth > 8``
    raises (auto routing simply stays on XLA)."""
    from ..ops.pallas_tiled import _SUBLANE as _PALLAS_MAX_DEPTH

    if halo_depth < 1:
        raise ValueError(f"halo_depth must be >= 1, got {halo_depth}")
    if halo_depth > _PALLAS_MAX_DEPTH and pallas_local:
        raise ValueError(
            f"halo_depth > {_PALLAS_MAX_DEPTH} exceeds the pallas aligned-"
            "ext form (zero-ring contamination would reach the body); "
            "drop pallas_local=True for deeper halos"
        )
    mesh_shape = (mesh.shape[ROWS], mesh.shape[COLS])
    if interpret is None:
        from ..ops.pallas_stencil import default_interpret

        interpret = default_interpret()
    local = functools.partial(
        _local_bit_step, rule=rule, mesh_shape=mesh_shape, word_axis=word_axis
    )
    wide = functools.partial(
        _local_bit_step_wide,
        rule=rule,
        mesh_shape=mesh_shape,
        word_axis=word_axis,
        depth=halo_depth,
    )
    local_pallas = functools.partial(
        _local_bit_step_pallas,
        rule=rule,
        mesh_shape=mesh_shape,
        interpret=interpret,
    )
    wide_pallas = functools.partial(
        _local_bit_step_pallas,
        rule=rule,
        mesh_shape=mesh_shape,
        interpret=interpret,
        depth=halo_depth,
    )
    sharding = packed_sharding(mesh)

    @functools.lru_cache(maxsize=None)
    def _compiled(n: int, use_pallas: bool):
        # body runs only on a cache MISS: hits = requests - misses (obs/)
        _ins.COMPILE_CACHE_MISSES_TOTAL.labels("halo.bit").inc()
        step = local_pallas if use_pallas else local
        wide_fn = wide_pallas if use_pallas else wide

        def local_n(block):
            return wide_loop(block, n, halo_depth, step, wide_fn)

        sharded = shard_map_compat(
            local_n,
            mesh=mesh,
            in_specs=P(ROWS, COLS),
            out_specs=P(ROWS, COLS),
            # pallas_call emits vma-less ShapeDtypeStructs, which the
            # varying-mesh-axes checker rejects inside shard_map — so the
            # checker is relaxed ONLY when the pallas kernel is routed;
            # the plain XLA local step keeps it on (ADVICE.md round 3)
            check_vma=not use_pallas,
        )
        jitted = jax.jit(sharded, in_shardings=sharding, out_shardings=sharding)
        # timed explicit lower/compile + cost analysis on first call per
        # shape (obs/device.py) — compile wall stops hiding in dispatch
        return _device.instrument_jit("halo.bit", jitted)

    def step_n(packed, n):
        # routing on the static LOCAL block shape, decided before the
        # shard_map is built so check_vma can follow the decision
        block_shape = (
            packed.shape[0] // mesh_shape[0],
            packed.shape[1] // mesh_shape[1],
        )
        check_halo_depth(halo_depth, block_shape)
        if pallas_local is None:
            use_pallas = _auto_use_pallas(
                halo_depth, block_shape, word_axis, interpret
            )
        else:
            use_pallas = bool(pallas_local)
            if use_pallas and word_axis != 0:
                # the pallas kernels hardcode row packing; silently
                # running them on a column-packed board would return a
                # wrong evolution
                raise ValueError("pallas_local=True requires word_axis=0")
            if use_pallas and not _pallas_local_aligned(block_shape):
                raise ValueError(
                    f"pallas_local=True requires a sublane/lane-aligned "
                    f"local block; got {tuple(block_shape)}"
                )
        if not (_metrics.enabled() or _tracing.enabled()
                or _tracing.device_trace_active()):
            return _compiled(int(n), use_pallas)(packed)
        # host-side dispatch wall + exchange count, labelled by the local
        # route actually taken (obs/); device-side exchange time lives in
        # the profiler trace, where the TraceAnnotation carries the same
        # span name so the two timelines line up
        plane_label = "bit_pallas" if use_pallas else "bit_xla"
        span = _tracing.start_span(
            _tracing.SPAN_HALO_DISPATCH, plane=plane_label, turns=int(n)
        )
        if _metrics.enabled():
            _ins.COMPILE_CACHE_REQUESTS_TOTAL.labels("halo.bit").inc()
            _ins.HALO_EXCHANGES_TOTAL.labels(plane_label).inc(
                exchanges_per_dispatch(int(n), halo_depth)
            )
        t0 = time.monotonic()
        with _tracing.annotate("halo.dispatch"):
            out = _compiled(int(n), use_pallas)(packed)
        if _metrics.enabled():
            _ins.HALO_DISPATCH_SECONDS.labels(plane_label).observe(
                time.monotonic() - t0
            )
        _tracing.end_span(span)
        return out

    return step_n


class ShardedBitPlane:
    """Engine data plane (ops/plane.py interface): a mesh-sharded bitboard.

    State is the packed int32 array sharded over the mesh; it stays packed
    and sharded across every chunk dispatch. encode/decode are jitted
    device-side pack/unpack placed on the mesh; alive_count is a sharded
    popcount reduction."""

    def __init__(
        self,
        mesh: Mesh,
        rule: LifeRule = CONWAY,
        word_axis: int = 0,
        halo_depth: int = 1,
    ):
        self.mesh = mesh
        self.rule = rule
        self.word_axis = word_axis
        self.halo_depth = halo_depth
        self._step_n = sharded_bit_step_n_fn(
            mesh, rule, word_axis, halo_depth=halo_depth
        )
        packed_shd = packed_sharding(mesh)
        board_shd = NamedSharding(mesh, P(ROWS, COLS))
        self._encode = jax.jit(
            functools.partial(pack_device, word_axis=word_axis),
            in_shardings=board_shd,
            out_shardings=packed_shd,
        )
        self._decode = jax.jit(
            functools.partial(unpack_device, word_axis=word_axis),
            in_shardings=packed_shd,
            out_shardings=board_shd,
        )

    def encode(self, board):
        return self._encode(jnp.asarray(board))

    def step_n(self, state, n: int):
        return self._step_n(state, n)

    def decode(self, state) -> np.ndarray:
        """Full host board — single-host (fully addressable) states only;
        a multihost rank cannot materialise rows it does not own. Use
        ``decode_global`` + per-shard reads in ``jax.distributed`` jobs."""
        return np.asarray(self.decode_global(state))

    def decode_global(self, state):
        """The unpacked uint8 board as a GLOBAL mesh-sharded device array.
        Multihost-safe: each rank reads its own rows via
        ``.addressable_shards`` (tests/multihost_child.py) instead of
        pulling the whole board to one host."""
        return self._decode(state)

    def alive_count(self, state) -> int:
        # multihost-safe: all-gathers row popcounts when shards are not
        # fully addressable (ops/bitpack.alive_count_packed)
        from ..ops.bitpack import alive_count_packed

        return alive_count_packed(state)

    def alive_cells(self, state):
        """Sparse O(populated-rows) cell extraction — single-host states
        only (the Cell list is inherently host-side); multihost ranks use
        decode_global + per-shard reads instead."""
        from ..ops.bitpack import alive_cells_packed

        return alive_cells_packed(state, self.word_axis)


def make_bit_plane(
    mesh: Mesh,
    board_shape: tuple[int, int],
    rule: LifeRule = CONWAY,
    halo_depth: int = 1,
) -> Optional[ShardedBitPlane]:
    """A ShardedBitPlane for this board/mesh if a packed layout divides
    AND the requested halo depth fits its local word blocks, else None
    (caller falls back to the byte halo plane, whose cell-granular blocks
    are 8-32x deeper — a small board can support a wide halo there while
    the packed layout cannot; found by an r5 session drive at 64^2 over
    a (2, 4) mesh, where the packed blocks are (1, 16) words)."""
    from ..ops.bitpack import packed_shape

    mesh_shape = (mesh.shape[ROWS], mesh.shape[COLS])
    word_axis = choose_bit_layout(board_shape, mesh_shape)
    if word_axis is None:
        return None
    rows, cols = packed_shape(*board_shape, word_axis)
    if not halo_depth_fits(
        halo_depth, (rows // mesh_shape[0], cols // mesh_shape[1])
    ):
        return None  # a halo can only come from the adjacent device
    return ShardedBitPlane(mesh, rule, word_axis, halo_depth=halo_depth)
