"""The fast data plane x the mesh: bit-packed halo-exchange steps.

Round 1's mesh path ran the byte-per-cell roll stencil inside shard_map
(parallel/halo.py) — ~12x slower per device than the bitboard kernels the
single-chip bench used. Here ``bit_step`` (ops/bitpack.py: 32 cells/int32
word, carry-save adder trees) runs INSIDE shard_map, so per-device mesh
throughput matches the single-chip bitboard path.

Halo mechanics: the packed array is 2-D (one spatial axis packed into bits,
the other left as elements), sharded P('rows', 'cols'). ``bit_step``'s
output word (i, j) depends only on input words (i±1, j±1), regardless of
which axis is packed — bit carries cross word boundaries through the
ADJACENT ELEMENT along the packed axis, and the 3x3 element neighbourhood
covers the rest. So the classic two-phase thickness-1 halo exchange of the
byte plane (rows first, then columns of the extended block — corners ride
the second phase) works verbatim on packed words: per turn each device
ppermutes one word-row and one word-column — O(perimeter/32) traffic on the
packed axis — then computes ``bit_step`` on the extended block and keeps
the interior. ``bit_step``'s cyclic rotates only contaminate the extended
block's outer ring, which is exactly what gets sliced away; with a
single-device axis the "halo" is the local wrap slice and the same slicing
yields torus semantics.

Reference anchor: the one kernel running on every worker
(worker/worker.go:15-70), re-founded so the strip a worker owns never
leaves its device (vs broker/broker.go:135-224's full-board reships).
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import numpy as np

import jax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models import CONWAY, LifeRule
from ..ops.bitpack import WORD, bit_step, pack_device, unpack_device
from .halo import _exchange
from .mesh import COLS, ROWS


def choose_bit_layout(
    board_shape: tuple[int, int], mesh_shape: tuple[int, int]
) -> Optional[int]:
    """Pick a ``word_axis`` whose packed array divides over the mesh.

    Prefers packing rows (word_axis=0, packed [H/32, W]) — the lane
    dimension stays W wide, ~6x faster on TPU — falling back to packing
    columns, then None (caller uses the byte plane)."""
    h, w = board_shape
    nrows, ncols = mesh_shape
    if h % (WORD * nrows) == 0 and w % ncols == 0:
        return 0
    if h % nrows == 0 and w % (WORD * ncols) == 0:
        return 1
    return None


def _local_bit_step(block, *, rule: LifeRule, mesh_shape, word_axis: int):
    nrows, ncols = mesh_shape
    ext = _exchange(block, ROWS, nrows, dim=0)  # (h+2, w)
    ext = _exchange(ext, COLS, ncols, dim=1)  # (h+2, w+2), corners ride phase 2
    out = bit_step(
        ext,
        word_axis,
        birth_mask=rule.birth_mask,
        survive_mask=rule.survive_mask,
    )
    return out[1:-1, 1:-1]


def packed_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P(ROWS, COLS))


def sharded_bit_step_n_fn(
    mesh: Mesh, rule: LifeRule = CONWAY, word_axis: int = 0
) -> Callable:
    """A jitted ``(packed, n) -> packed`` over a P('rows','cols')-sharded
    int32 bitboard: n turns in ONE dispatch, the fori_loop (halo ppermutes
    included) inside shard_map."""
    mesh_shape = (mesh.shape[ROWS], mesh.shape[COLS])
    local = functools.partial(
        _local_bit_step, rule=rule, mesh_shape=mesh_shape, word_axis=word_axis
    )
    sharding = packed_sharding(mesh)

    @functools.lru_cache(maxsize=None)
    def _compiled(n: int):
        def local_n(block):
            return lax.fori_loop(0, n, lambda _, b: local(b), block)

        sharded = jax.shard_map(
            local_n, mesh=mesh, in_specs=P(ROWS, COLS), out_specs=P(ROWS, COLS)
        )
        return jax.jit(sharded, in_shardings=sharding, out_shardings=sharding)

    def step_n(packed, n):
        return _compiled(int(n))(packed)

    return step_n


class ShardedBitPlane:
    """Engine data plane (ops/plane.py interface): a mesh-sharded bitboard.

    State is the packed int32 array sharded over the mesh; it stays packed
    and sharded across every chunk dispatch. encode/decode are jitted
    device-side pack/unpack placed on the mesh; alive_count is a sharded
    popcount reduction."""

    def __init__(self, mesh: Mesh, rule: LifeRule = CONWAY, word_axis: int = 0):
        self.mesh = mesh
        self.rule = rule
        self.word_axis = word_axis
        self._step_n = sharded_bit_step_n_fn(mesh, rule, word_axis)
        packed_shd = packed_sharding(mesh)
        board_shd = NamedSharding(mesh, P(ROWS, COLS))
        self._encode = jax.jit(
            functools.partial(pack_device, word_axis=word_axis),
            in_shardings=board_shd,
            out_shardings=packed_shd,
        )
        self._decode = jax.jit(
            functools.partial(unpack_device, word_axis=word_axis),
            in_shardings=packed_shd,
            out_shardings=board_shd,
        )

    def encode(self, board):
        import jax.numpy as jnp

        return self._encode(jnp.asarray(board))

    def step_n(self, state, n: int):
        return self._step_n(state, n)

    def decode(self, state) -> np.ndarray:
        """Full host board — single-host (fully addressable) states only;
        a multihost rank cannot materialise rows it does not own. Use
        ``decode_global`` + per-shard reads in ``jax.distributed`` jobs."""
        return np.asarray(self.decode_global(state))

    def decode_global(self, state):
        """The unpacked uint8 board as a GLOBAL mesh-sharded device array.
        Multihost-safe: each rank reads its own rows via
        ``.addressable_shards`` (tests/multihost_child.py) instead of
        pulling the whole board to one host."""
        return self._decode(state)

    def alive_count(self, state) -> int:
        # multihost-safe: all-gathers row popcounts when shards are not
        # fully addressable (ops/bitpack.alive_count_packed)
        from ..ops.bitpack import alive_count_packed

        return alive_count_packed(state)


def make_bit_plane(
    mesh: Mesh, board_shape: tuple[int, int], rule: LifeRule = CONWAY
) -> Optional[ShardedBitPlane]:
    """A ShardedBitPlane for this board/mesh if a packed layout divides,
    else None (caller falls back to the byte halo plane)."""
    mesh_shape = (mesh.shape[ROWS], mesh.shape[COLS])
    word_axis = choose_bit_layout(board_shape, mesh_shape)
    if word_axis is None:
        return None
    return ShardedBitPlane(mesh, rule, word_axis)
