"""Multi-host scaffolding: process initialisation and per-host shard maths.

The reference scales to more machines by adding worker addresses to a
hardcoded list (broker/broker.go:288-300) and paying O(H x W) wire bytes
per worker per turn. Here multi-host is a bigger mesh: processes join via
``jax.distributed``, the board is sharded over a global ('rows', 'cols')
mesh spanning all hosts, and per-turn communication stays O(perimeter)
halo ppermutes — over ICI within a slice, DCN across hosts, inserted by
XLA from the same shard_map program (SURVEY.md §2 backend table).

For boards too large for any single host (BASELINE.json config 5:
65536^2), each host touches only its own row range of the PGM through
``host_row_range`` + io/sharded.py streamed IO.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh

from .mesh import ROWS


def initialize(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> bool:
    """Join the multi-host job (``jax.distributed.initialize``); no-op and
    False for single-process runs so the same code path serves both."""
    if num_processes is None or num_processes <= 1:
        return False
    # XLA:CPU's default collectives cannot execute multiprocess
    # computations at all ("Multiprocess computations aren't implemented
    # on the CPU backend") — the gloo TCP implementation can, and jaxlib
    # ships it. Selecting it here, before the first backend is created,
    # makes the CPU test topology (and any real CPU deployment) execute
    # the same cross-process ppermutes as a TPU pod. Guarded: the option
    # name is version-dependent and only affects CPU client creation, so
    # a jax without it simply keeps its default.
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    # gol: allow(hygiene): version-dependent option probe — a jax
    # without it keeps its default, which is the documented contract
    except Exception:
        pass
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return True


def host_row_range(mesh: Mesh, height: int) -> tuple[int, int]:
    """The [start, stop) board rows this process's devices own under the
    canonical board sharding — its slice of a streamed PGM read/write."""
    n_rows = mesh.shape[ROWS]
    if height % n_rows:
        raise ValueError(f"height {height} does not divide over {n_rows} row shards")
    block = height // n_rows
    local = set(d.id for d in jax.local_devices())
    mesh_rows = [
        r
        for r in range(n_rows)
        if any(d.id in local for d in np.asarray(mesh.devices)[r].flatten())
    ]
    if not mesh_rows:
        raise ValueError("this process owns no devices in the mesh")
    lo, hi = min(mesh_rows), max(mesh_rows)
    if set(range(lo, hi + 1)) != set(mesh_rows):
        raise ValueError(
            "this process's mesh rows are not contiguous; use a process-major "
            "device order when building the mesh"
        )
    return lo * block, (hi + 1) * block


def process_count() -> int:
    return jax.process_count()


def process_index() -> int:
    return jax.process_index()
