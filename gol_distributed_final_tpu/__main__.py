"""Controller process entry point — the main.go equivalent.

Flags mirror the reference (main.go:17-46): -t threads, -w width, -h height,
-turns, -noVis, plus -server (gol/distributor.go:12) to drive a remote broker
instead of the in-process engine. ``-h`` is board height as in the
reference, so argparse's auto-help is disabled; use --help.

Headless mode drains the event stream and prints every event with a
non-empty string as ``Completed Turns <n> <event>`` (sdl/loop.go:44-47;
main.go:59-67's -noVis drain). With a TTY, keypresses s/q/k/p are read raw
from stdin and forwarded like the SDL keymap (sdl/loop.go:16-28).
"""

from __future__ import annotations

import argparse
import queue
import sys
import threading


def _stdin_keys(keypresses: "queue.Queue", done: threading.Event) -> None:
    """Forward raw single-key presses (s/q/k/p) from a TTY.

    The terminal mode is saved/restored by the caller, not here: this
    daemon thread dies blocked in read(1) at process exit, so its finally
    would never run."""
    while not done.is_set():
        ch = sys.stdin.read(1)
        if ch in ("s", "q", "k", "p"):
            keypresses.put(ch)


def start_tty_keys(keypresses: "queue.Queue"):
    """Put the terminal in cbreak mode and forward s/q/k/p keys; returns
    a restore() callable (a no-op off-tty). Shared by the controller CLI
    and the bigboard session CLI."""
    if not sys.stdin.isatty():
        return lambda: None
    import termios
    import tty

    fd = sys.stdin.fileno()
    old = termios.tcgetattr(fd)
    tty.setcbreak(fd)
    done = threading.Event()
    threading.Thread(
        target=_stdin_keys, args=(keypresses, done), daemon=True
    ).start()

    def restore():
        done.set()
        termios.tcsetattr(fd, termios.TCSADRAIN, old)

    return restore


def drain_events(events: "queue.Queue") -> None:
    """Headless consumer (main.go:59-67's -noVis drain): print every event
    with a non-empty string as ``Completed Turns <n> <event>`` until the
    CLOSED sentinel."""
    from .engine.controller import iter_events

    for ev in iter_events(events):
        text = str(ev)
        if text:
            print(f"Completed Turns {ev.get_completed_turns()} {text}")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="gol_distributed_final_tpu", add_help=False
    )
    parser.add_argument("--help", action="help")
    parser.add_argument("-t", type=int, default=8, help="threads / worker shards")
    parser.add_argument("-w", type=int, default=512, help="board width")
    parser.add_argument("-h", type=int, default=512, help="board height")
    parser.add_argument("-turns", type=int, default=10000000000)
    parser.add_argument("-noVis", action="store_true", default=False)
    parser.add_argument(
        "-server", default="", help="broker address (empty: in-process engine)"
    )
    parser.add_argument(
        "-resume", default=None, metavar="CKPT",
        help="continue from an engine/checkpoint.py .npz instead of "
             "images/<W>x<H>.pgm at turn 0; with -server the checkpoint's "
             "board, turn, and rule are shipped to the remote broker",
    )
    parser.add_argument(
        "-rule", default=None, metavar="B.../S...",
        help="life-like rulestring (default Conway B3/S23); shipped to the "
             "broker with -server (the workers backend computes Conway only)",
    )
    parser.add_argument(
        "-trace", action="store_true", default=False,
        help="enable the span tracer + flight recorder (obs/tracing.py): "
             "the session becomes one cross-process trace (controller, "
             "broker, workers share a trace_id via Request.trace_ctx) and "
             "a Perfetto-loadable Chrome trace lands in "
             "out/trace_<W>x<H>x<Turns>.json at session end",
    )
    parser.add_argument(
        "-trace-device", dest="trace_device", nargs="?", const="out/trace_device",
        default=None, metavar="DIR",
        help="wrap the session in a jax.profiler DEVICE trace written to "
             "DIR (default out/trace_device — the reference's TestTrace "
             "role, trace_test.go:12-29); span names ride along as "
             "TraceAnnotations so host and device timelines line up",
    )
    parser.add_argument(
        "-halo-depth", dest="halo_depth", type=int, default=0,
        help="with -server: turns per halo exchange on the broker — the "
             "tpu backend's mesh planes, or a resident-wire workers "
             "backend's batch depth K (0 = the broker's default)",
    )
    parser.add_argument(
        "-metrics", action="store_true", default=False,
        help="enable the metrics registry (obs/): engine, controller, and "
             "RPC-client timings accumulate in-process at near-zero cost",
    )
    parser.add_argument(
        "-report", action="store_true", default=False,
        help="write out/report_<W>x<H>x<Turns>.json (metrics + device "
             "inventory) at FinalTurnComplete; implies -metrics",
    )
    parser.add_argument(
        "-timeline", nargs="?", const=1.0, default=None, type=float,
        metavar="SECS",
        help="enable the in-process metric timeline + SLO rulebook "
             "(obs/timeline.py, obs/slo.py) at this sampling cadence "
             "(default 1 s): server-side rates/p99s and alert states land "
             "in the run report, and counter tracks join the -trace "
             "Chrome export; implies -metrics",
    )
    parser.add_argument(
        "-profile", nargs="?", const=10.0, default=None, type=float,
        metavar="MS",
        help="enable the continuous sampling profiler (obs/profiler.py) "
             "at this cadence (default 10 ms, adaptive backoff): the "
             "run report embeds the hot-frame summary and collapsed-"
             "stack + speedscope artifacts land in out/ at session end "
             "(render/diff with python -m ...obs.flame); implies "
             "-metrics",
    )
    args = parser.parse_args(argv)
    if args.metrics or args.report:
        # before any instrumented path runs, so the report sees the whole
        # session (a -report without metrics would be an empty breakdown)
        from .obs import metrics

        metrics.enable()
    if args.timeline is not None:
        if args.timeline <= 0:
            parser.error(f"-timeline SECS must be > 0, got {args.timeline}")
        from .obs import timeline

        timeline.enable(period=args.timeline)  # implies metrics.enable()
    if args.trace:
        # likewise before any span site runs; the controller role labels
        # this process's track in the exported Chrome trace
        from .obs import flight, tracing

        tracing.enable()
        tracing.set_process_name("controller")
        flight.enable()
    if args.profile is not None:
        if args.profile <= 0:
            parser.error(f"-profile MS must be > 0, got {args.profile}")
        from .obs import profiler as _profiler

        _profiler.enable(
            period_ms=args.profile, tag="controller"
        )  # implies metrics.enable()
    if args.halo_depth < 0:
        parser.error(
            f"-halo-depth must be >= 1 (or 0 for the broker's default), "
            f"got {args.halo_depth}"
        )
    if args.halo_depth and not args.server:
        parser.error("-halo-depth needs -server (a mesh-plane broker knob)")
    if args.rule and args.resume:
        parser.error("-rule conflicts with -resume (the checkpoint's rule wins)")
    rule = None
    if args.rule:
        # validate BEFORE any thread starts: a bogus rulestring raising
        # mid-setup would leave the event consumer joined-on-forever
        from .models import LifeRule

        try:
            rule = LifeRule.from_rulestring(args.rule)
        except ValueError as e:
            parser.error(str(e))
    resume = None
    if args.resume:
        # same posture for the checkpoint: verify it NOW (typed, actionable
        # refusal — engine/checkpoint.py) instead of a mid-setup traceback
        # with the event consumer already running. The verified result is
        # passed through to run() so the file is read and hashed exactly
        # once — a second load could even see a different file after an
        # auto-checkpoint rotation.
        from .engine.checkpoint import CheckpointError, load_verified_checkpoint

        try:
            resume = load_verified_checkpoint(args.resume)
        except CheckpointError as e:
            parser.error(f"-resume {args.resume}: {e}")

    from . import Params, run

    params = Params(
        turns=args.turns, threads=args.t, image_width=args.w, image_height=args.h
    )

    broker = None
    if args.server:
        from .rpc.client import RemoteBroker

        print("Server: ", args.server)
        broker = RemoteBroker(args.server)

    events: "queue.Queue" = queue.Queue()
    keypresses: "queue.Queue" = queue.Queue()

    restore_tty = (
        start_tty_keys(keypresses) if not args.noVis else (lambda: None)
    )

    if args.noVis:
        # headless drain (main.go:59-67)
        consumer = threading.Thread(target=drain_events, args=(events,))
    else:
        # visualiser loop (main.go:57, sdl.Run); headless window fallback
        # when the native SDL backend isn't built
        from .viz import run as viz_run

        consumer = threading.Thread(
            target=viz_run, args=(params, events, keypresses)
        )
    consumer.start()
    try:
        # the in-process engine can feed the visualiser per-cell flips; the
        # remote path (like the reference's distributed mode) cannot
        emit_flips = not args.noVis and broker is None
        import contextlib

        trace_ctx = contextlib.nullcontext()
        if args.trace_device:
            # the profiler trace + host-span alignment (TraceAnnotations)
            from .obs.tracing import device_trace

            trace_ctx = device_trace(args.trace_device)
        with trace_ctx:
            run(params, events, keypresses, broker=broker, rule=rule,
                emit_flips=emit_flips, resume_from=resume,
                halo_depth=args.halo_depth, report=args.report)
    except BaseException as exc:
        if args.profile is not None:
            from .obs import profiler as _profiler

            # crash-path artifacts (the broker/worker hook's controller
            # twin): the profile of the session that died, on disk
            _profiler.flush_on_crash(exc)
        raise
    finally:
        if args.profile is not None:
            from .obs import profiler as _profiler

            _profiler.shutdown()  # run-end artifacts + gc unhook
        consumer.join()
        restore_tty()
    return 0


if __name__ == "__main__":
    sys.exit(main())
