"""Controller process entry point — the main.go equivalent.

Flags mirror the reference (main.go:17-46): -t threads, -w width, -h height,
-turns, -noVis, plus -server (gol/distributor.go:12) to drive a remote broker
instead of the in-process engine. ``-h`` is board height as in the
reference, so argparse's auto-help is disabled; use --help.

Headless mode drains the event stream and prints every event with a
non-empty string as ``Completed Turns <n> <event>`` (sdl/loop.go:44-47;
main.go:59-67's -noVis drain). With a TTY, keypresses s/q/k/p are read raw
from stdin and forwarded like the SDL keymap (sdl/loop.go:16-28).
"""

from __future__ import annotations

import argparse
import queue
import sys
import threading


def _stdin_keys(keypresses: "queue.Queue", done: threading.Event) -> None:
    """Forward raw single-key presses (s/q/k/p) from a TTY.

    The terminal mode is saved/restored by main(), not here: this daemon
    thread dies blocked in read(1) at process exit, so its finally would
    never run."""
    while not done.is_set():
        ch = sys.stdin.read(1)
        if ch in ("s", "q", "k", "p"):
            keypresses.put(ch)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="gol_distributed_final_tpu", add_help=False
    )
    parser.add_argument("--help", action="help")
    parser.add_argument("-t", type=int, default=8, help="threads / worker shards")
    parser.add_argument("-w", type=int, default=512, help="board width")
    parser.add_argument("-h", type=int, default=512, help="board height")
    parser.add_argument("-turns", type=int, default=10000000000)
    parser.add_argument("-noVis", action="store_true", default=False)
    parser.add_argument(
        "-server", default="", help="broker address (empty: in-process engine)"
    )
    parser.add_argument(
        "-resume", default=None, metavar="CKPT",
        help="continue from an engine/checkpoint.py .npz instead of "
             "images/<W>x<H>.pgm at turn 0 (in-process engine only)",
    )
    args = parser.parse_args(argv)
    if args.resume and args.server:
        parser.error("-resume needs the in-process engine (no -server)")

    from . import Params, run
    from .engine.controller import iter_events

    params = Params(
        turns=args.turns, threads=args.t, image_width=args.w, image_height=args.h
    )

    broker = None
    if args.server:
        from .rpc.client import RemoteBroker

        print("Server: ", args.server)
        broker = RemoteBroker(args.server)

    events: "queue.Queue" = queue.Queue()
    keypresses: "queue.Queue" = queue.Queue()
    done = threading.Event()

    old_termios = None
    if sys.stdin.isatty() and not args.noVis:
        import termios
        import tty

        fd = sys.stdin.fileno()
        old_termios = termios.tcgetattr(fd)
        tty.setcbreak(fd)
        threading.Thread(
            target=_stdin_keys, args=(keypresses, done), daemon=True
        ).start()

    if args.noVis:
        # headless drain (main.go:59-67)
        def consume():
            for ev in iter_events(events):
                text = str(ev)
                if text:
                    print(f"Completed Turns {ev.get_completed_turns()} {text}")

        consumer = threading.Thread(target=consume)
    else:
        # visualiser loop (main.go:57, sdl.Run); headless window fallback
        # when the native SDL backend isn't built
        from .viz import run as viz_run

        consumer = threading.Thread(
            target=viz_run, args=(params, events, keypresses)
        )
    consumer.start()
    try:
        # the in-process engine can feed the visualiser per-cell flips; the
        # remote path (like the reference's distributed mode) cannot
        emit_flips = not args.noVis and broker is None
        run(params, events, keypresses, broker=broker,
            emit_flips=emit_flips, resume_from=args.resume)
    finally:
        done.set()
        consumer.join()
        if old_termios is not None:
            import termios

            termios.tcsetattr(
                sys.stdin.fileno(), termios.TCSADRAIN, old_termios
            )
    return 0


if __name__ == "__main__":
    sys.exit(main())
