"""Run parameters — the ``gol.Params`` equivalent (reference: gol/gol.go:4-9).

The reference conflates width/height in several allocations but is only ever
exercised on square boards (SURVEY.md §5 quirks). We implement true H x W
semantics: the board array is ``[height, width]``, a ``Cell`` is ``(x, y)`` =
(column, row), matching reference util/cell.go.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class Params:
    turns: int
    threads: int = 8
    image_width: int = 512
    image_height: int = 512

    @property
    def input_filename(self) -> str:
        # "<W>x<H>" — load-bearing naming convention (gol/distributor.go:144)
        return f"{self.image_width}x{self.image_height}"

    @property
    def output_filename(self) -> str:
        # "<W>x<H>x<Turns>" (gol/distributor.go:165)
        return f"{self.image_width}x{self.image_height}x{self.turns}"
