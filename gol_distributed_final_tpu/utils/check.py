"""Error helper (reference: util/check.go:3-7)."""


def check(err):
    """Raise if ``err`` is an exception instance; mirror of util.Check."""
    if isinstance(err, BaseException):
        raise err
