"""Board pretty-printers for test-failure output (reference: util/visualise.go:8-108).

Renders a board (or a given-vs-expected pair, side by side) in box-drawing
characters so small-board golden-test failures are diagnosable at a glance.
"""

from __future__ import annotations

from typing import Iterable, Sequence

from .cell import Cell

_ALIVE_CH = "█"
_DEAD_CH = " "


def _cells_to_grid(cells: Iterable[Cell], width: int, height: int):
    grid = [[False] * width for _ in range(height)]
    for c in cells:
        x, y = c
        if 0 <= y < height and 0 <= x < width:
            grid[y][x] = True
    return grid


def _render(grid: Sequence[Sequence[bool]], width: int) -> list[str]:
    top = "┌" + "─" * width + "┐"
    bottom = "└" + "─" * width + "┘"
    rows = ["│" + "".join(_ALIVE_CH if v else _DEAD_CH for v in row) + "│" for row in grid]
    return [top, *rows, bottom]


def visualise_matrix(matrix, width: int, height: int) -> str:
    """Render a 2-D 0/255 (or truthy) matrix as a framed board string."""
    grid = [[bool(matrix[y][x]) for x in range(width)] for y in range(height)]
    return "\n".join(_render(grid, width))


def alive_cells_to_string(
    given: Iterable[Cell],
    expected: Iterable[Cell],
    width: int,
    height: int,
) -> str:
    """Draw given-vs-expected boards side by side (util/visualise.go:8)."""
    g = _render(_cells_to_grid(given, width, height), width)
    e = _render(_cells_to_grid(expected, width, height), width)
    gap = "   "
    header = (
        "GIVEN".center(width + 2) + gap + "EXPECTED".center(width + 2)
    )
    return "\n".join([header] + [a + gap + b for a, b in zip(g, e)])
