"""The virtual-CPU-mesh recipe, shared by tests/conftest.py and the driver
dry-run entry (__graft_entry__.dryrun_multichip).

Multi-chip sharding is validated without multi-chip hardware by pointing JAX
at an ``n``-device virtual CPU platform. The ambient environment pins JAX to
the single real TPU chip (JAX_PLATFORMS=axon) and a sitecustomize module
imports jax at interpreter start, so plain env vars are too late — the
takeover must also go through ``jax.config``, which still applies as long as
no devices have been queried yet. This module is import-safe before jax
(nothing here imports jax at module level).
"""

from __future__ import annotations

import os
import re


def virtual_cpu_env(n_devices: int, base: dict | None = None) -> dict:
    """Env overrides forcing an ``n_devices``-wide virtual CPU platform.

    Scrubs any pre-existing ``--xla_force_host_platform_device_count`` from
    XLA_FLAGS (taken from ``base`` or the current environment) first."""
    env = os.environ if base is None else base
    flags = re.sub(
        r"--xla_force_host_platform_device_count=\d+",
        "",
        env.get("XLA_FLAGS", ""),
    )
    return {
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": (
            flags + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip(),
    }


def force_virtual_cpu_devices(n_devices: int) -> bool:
    """Repin THIS process's jax to ``n_devices`` virtual CPU devices.

    Returns True on success. Fails (False) when jax's backends were already
    initialised on another platform — callers needing isolation should spawn
    a subprocess with ``virtual_cpu_env`` instead. Mutates os.environ only
    on success (restored on failure)."""
    saved = {k: os.environ.get(k) for k in ("JAX_PLATFORMS", "XLA_FLAGS")}
    os.environ.update(virtual_cpu_env(n_devices))
    import jax

    saved_platforms = jax.config.jax_platforms
    try:
        jax.config.update("jax_platforms", "cpu")
        devices = jax.devices()
        ok = len(devices) >= n_devices and devices[0].platform == "cpu"
    # gol: allow(hygiene): capability probe — 'no' is a normal answer
    except Exception:
        ok = False
    if not ok:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        try:
            jax.config.update("jax_platforms", saved_platforms)
        # gol: allow(hygiene): backends already initialised makes the
        # config restore inert — nothing to report
        except Exception:
            pass
    return ok
