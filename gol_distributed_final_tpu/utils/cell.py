"""The canonical alive-cell coordinate (reference: util/cell.go:4-6).

``x`` is the column index, ``y`` the row index — the payload type of
``FinalTurnComplete.alive`` and what the golden-image tests assert on.
"""

from typing import NamedTuple


class Cell(NamedTuple):
    x: int
    y: int
