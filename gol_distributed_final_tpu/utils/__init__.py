from .cell import Cell
from .check import check
from .visualise import alive_cells_to_string, visualise_matrix

__all__ = ["Cell", "check", "alive_cells_to_string", "visualise_matrix"]
