"""Profiling / tracing hooks — the TestTrace analogue (reference:
trace_test.go:12-29 wraps a run in runtime/trace for goroutine inspection).

Here the equivalent is a ``jax.profiler`` trace around any region: the
resulting TensorBoard-format trace shows per-dispatch device timelines,
compilations, and transfers.
"""

from __future__ import annotations

import contextlib
import pathlib
import time


@contextlib.contextmanager
def trace(log_dir="trace_out"):
    """Context manager: profile everything inside into ``log_dir``.

    ``start_trace`` lives INSIDE the try and ``stop_trace`` only runs once
    it succeeded: a body that raises must still stop the profiler (or the
    next ``trace()`` would find one already running), while a
    ``start_trace`` failure must not be followed by a ``stop_trace`` on a
    never-started profiler (which raises its own error and masks the
    original one)."""
    import jax

    pathlib.Path(log_dir).mkdir(parents=True, exist_ok=True)
    started = False
    try:
        jax.profiler.start_trace(str(log_dir))
        started = True
        yield pathlib.Path(log_dir)
    finally:
        if started:
            jax.profiler.stop_trace()


class TurnsPerSecond:
    """Tiny throughput meter: feed completed-turn counts, read turns/sec
    and cell-updates/sec (the driver metric, BASELINE.json).

    The clock is sampled once per ``update``, so the rate properties are
    mutually consistent between updates (cell_updates_per_second ==
    turns_per_second * cells_per_turn exactly, tests/test_aux.py)."""

    def __init__(self, cells_per_turn: int):
        self.cells_per_turn = cells_per_turn
        self._t0 = time.monotonic()
        self._turns = 0
        self._elapsed = 0.0

    def update(self, turns_completed: int):
        self._turns = turns_completed
        self._elapsed = time.monotonic() - self._t0

    @property
    def elapsed(self) -> float:
        return self._elapsed

    @property
    def turns_per_second(self) -> float:
        return self._turns / self._elapsed if self._elapsed else 0.0

    @property
    def cell_updates_per_second(self) -> float:
        return self.turns_per_second * self.cells_per_turn
